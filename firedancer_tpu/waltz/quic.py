"""Minimal QUIC (RFC 9000/9001 subset): the TPU transaction ingest
transport.

The reference's production txn ingest is QUIC (ref: src/waltz/quic/
fd_quic.h:11-60, fd_quic.c; tile src/disco/quic/fd_quic_tile.c:234,303
`fd_tpu_reasm_publish_fast` — one transaction per unidirectional
stream). This module implements the wire subset that carries that
traffic between this framework's endpoints:

RFC-TRUE layers (interoperable as specified):
  * varint encoding (RFC 9000 §16)
  * long/short packet headers, packet-number encode/decode (§17, A.2/A.3)
  * Initial packet protection: initial_salt -> HKDF-SHA256
    extract/expand-label -> AES-128-GCM payload AEAD + AES-ECB header
    protection, exactly RFC 9001 §5
  * frames: PADDING PING ACK CRYPTO STREAM(all forms) MAX_* (ignored)
    HANDSHAKE_DONE CONNECTION_CLOSE

The handshake is REAL TLS 1.3 (waltz/tls.py — RFC 8446 subset:
x25519 + ed25519 CertificateVerify + AES-128-GCM, the same profile the
reference's fd_tls implements): ClientHello rides the Initial level,
the server flight (SH / EE / Certificate / CertificateVerify /
Finished) spans Initial + Handshake packets, the client Finished
returns at the Handshake level, and the 1-RTT packet keys are the TLS
application traffic secrets run through the RFC 9001 §5.1 labels.
Handshake packets use their own packet-number space per RFC 9000
§12.3. (r3 shipped a documented stub here; r4 removed it.)

Stream discipline (matches the reference's TPU contract): each
client-initiated UNIDIRECTIONAL stream carries exactly one transaction;
FIN completes it; the server reassembles out-of-order STREAM frames and
hands the payload to the tile (fd_tpu_reasm semantics).
"""
from __future__ import annotations

import os
import struct

from cryptography.hazmat.primitives.ciphers.aead import AESGCM
from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

from . import tls as fdtls

# RFC 9001 §5.2 (QUIC v1)
INITIAL_SALT = bytes.fromhex("38762cf7f55934b34d179ae6a4c80cadccbb7f0a")
VERSION = 1

# packet types (long header, v1)
PT_INITIAL = 0
PT_HANDSHAKE = 2

FRAME_PADDING = 0x00
FRAME_PING = 0x01
FRAME_ACK = 0x02
FRAME_CRYPTO = 0x06
FRAME_STREAM = 0x08           # ..0x0f: OFF/LEN/FIN bits
FRAME_MAX_DATA = 0x10
FRAME_MAX_STREAM_DATA = 0x11
FRAME_MAX_STREAMS_UNI = 0x13
FRAME_CONNECTION_CLOSE = 0x1C
FRAME_HANDSHAKE_DONE = 0x1E

MAX_DATAGRAM = 1350


class QuicError(ValueError):
    pass


class _CallbackError(Exception):
    """Carrier lifting an application on_txn exception OVER the
    hostile-datagram catch in on_datagram — a bug in the consumer must
    surface, not be miscounted as a bad packet."""


# ---------------------------------------------------------------------------
# varints (RFC 9000 §16)
# ---------------------------------------------------------------------------

def enc_varint(v: int) -> bytes:
    if v < 1 << 6:
        return bytes([v])
    if v < 1 << 14:
        return struct.pack(">H", v | 0x4000)
    if v < 1 << 30:
        return struct.pack(">I", v | 0x8000_0000)
    if v < 1 << 62:
        return struct.pack(">Q", v | 0xC000_0000_0000_0000)
    raise QuicError("varint too large")


def dec_varint(b: bytes, off: int) -> tuple[int, int]:
    if off >= len(b):
        raise QuicError("truncated varint")
    pfx = b[off] >> 6
    ln = 1 << pfx
    if off + ln > len(b):
        raise QuicError("truncated varint")
    v = b[off] & 0x3F
    for i in range(1, ln):
        v = (v << 8) | b[off + i]
    return v, off + ln


# ---------------------------------------------------------------------------
# HKDF + TLS 1.3 expand-label — one implementation, in waltz/tls.py
# (RFC 9001 uses the RFC 8446 KDF with an empty context)
# ---------------------------------------------------------------------------

hkdf_extract = fdtls.hkdf_extract


def hkdf_expand_label(secret: bytes, label: bytes, length: int) -> bytes:
    return fdtls.hkdf_expand_label(secret, label, b"", length)


class Keys:
    """One direction's packet protection keys (RFC 9001 §5.1)."""

    def __init__(self, secret: bytes):
        self.key = hkdf_expand_label(secret, b"quic key", 16)
        self.iv = hkdf_expand_label(secret, b"quic iv", 12)
        self.hp = hkdf_expand_label(secret, b"quic hp", 16)
        self.aead = AESGCM(self.key)

    def nonce(self, pn: int) -> bytes:
        return (int.from_bytes(self.iv, "big") ^ pn).to_bytes(12, "big")

    def hp_mask(self, sample: bytes) -> bytes:
        enc = Cipher(algorithms.AES(self.hp), modes.ECB()).encryptor()
        return enc.update(sample[:16])[:5]


def initial_keys(dcid: bytes) -> tuple[Keys, Keys, bytes]:
    """(client_keys, server_keys, initial_secret) per RFC 9001 §5.2."""
    initial = hkdf_extract(INITIAL_SALT, dcid)
    c = hkdf_expand_label(initial, b"client in", 32)
    s = hkdf_expand_label(initial, b"server in", 32)
    return Keys(c), Keys(s), initial


class CryptoBuf:
    """Per-encryption-level in-order reassembly of the CRYPTO stream
    (RFC 9000 §19.6: offsets, arbitrary re-fragmentation, overlapping
    duplication — retransmits may re-slice already-consumed ranges)."""

    MAX = 1 << 16

    def __init__(self):
        self.chunks: dict[int, bytes] = {}
        self.next = 0

    def add(self, offset: int, data: bytes):
        if offset + len(data) > self.MAX:
            raise QuicError("crypto stream exceeds cap")
        if offset < self.next:                 # trim consumed prefix
            data = data[self.next - offset:]
            offset = self.next
        if not data:
            return
        have = self.chunks.get(offset)
        if have is None or len(data) > len(have):
            self.chunks[offset] = data

    def drain(self) -> bytes:
        out = b""
        while True:
            c = self.chunks.pop(self.next, None)
            if c is None:
                # an overlapping chunk may start before `next` yet
                # extend past it
                for off in sorted(self.chunks):
                    if off > self.next:
                        break
                    c2 = self.chunks.pop(off)
                    if off + len(c2) > self.next:
                        c = c2[self.next - off:]
                        break
                if c is None:
                    break
            out += c
            self.next += len(c)
        return out


# ---------------------------------------------------------------------------
# packet protection (RFC 9001 §5.3/5.4)
# ---------------------------------------------------------------------------

def _encode_pn(pn: int) -> bytes:
    return struct.pack(">I", pn & 0xFFFFFFFF)[2:]     # 2-byte pn


def decode_pn(truncated: int, pn_len: int, largest: int) -> int:
    """Reconstruct the full packet number from its truncated wire form
    (RFC 9000 Appendix A.3) given the largest pn received so far."""
    pn_nbits = 8 * pn_len
    expected = largest + 1
    pn_win = 1 << pn_nbits
    pn_hwin = pn_win >> 1
    pn_mask = pn_win - 1
    candidate = (expected & ~pn_mask) | truncated
    if candidate <= expected - pn_hwin and candidate < (1 << 62) - pn_win:
        return candidate + pn_win
    if candidate > expected + pn_hwin and candidate >= pn_win:
        return candidate - pn_win
    return candidate


def seal_long(keys: Keys, ptype: int, dcid: bytes, scid: bytes,
              pn: int, payload: bytes) -> bytes:
    if len(payload) < 4:                      # see seal_short
        payload = payload + bytes(4 - len(payload))
    pn_bytes = _encode_pn(pn)
    first = 0xC0 | (ptype << 4) | (len(pn_bytes) - 1)
    hdr = bytes([first]) + struct.pack(">I", VERSION)
    hdr += bytes([len(dcid)]) + dcid + bytes([len(scid)]) + scid
    if ptype == PT_INITIAL:
        hdr += enc_varint(0)                          # token length
    length = len(pn_bytes) + len(payload) + 16
    hdr += enc_varint(length)
    pn_off = len(hdr)
    hdr += pn_bytes
    ct = keys.aead.encrypt(keys.nonce(pn), payload, hdr)
    pkt = bytearray(hdr + ct)
    sample = bytes(pkt[pn_off + 4:pn_off + 20])
    mask = keys.hp_mask(sample)
    pkt[0] ^= mask[0] & 0x0F
    for i in range(len(pn_bytes)):
        pkt[pn_off + i] ^= mask[1 + i]
    return bytes(pkt)


def seal_short(keys: Keys, dcid: bytes, pn: int, payload: bytes) -> bytes:
    # header protection samples 16 bytes starting 4 past the pn offset
    # (RFC 9001 §5.4.2): pad tiny payloads (PADDING frames) so the
    # sample always exists
    if len(payload) < 4:
        payload = payload + bytes(4 - len(payload))
    pn_bytes = _encode_pn(pn)
    first = 0x40 | (len(pn_bytes) - 1)
    hdr = bytes([first]) + dcid
    pn_off = len(hdr)
    hdr += pn_bytes
    ct = keys.aead.encrypt(keys.nonce(pn), payload, hdr)
    pkt = bytearray(hdr + ct)
    sample = bytes(pkt[pn_off + 4:pn_off + 20])
    mask = keys.hp_mask(sample)
    pkt[0] ^= mask[0] & 0x1F
    for i in range(len(pn_bytes)):
        pkt[pn_off + i] ^= mask[1 + i]
    return bytes(pkt)


def long_header_len(pkt: bytes) -> int:
    """Length of the first coalesced long-header packet WITHOUT
    decrypting (the long header through the length field is cleartext)
    — used to skip packets whose keys have been discarded (RFC 9001
    §4.9.1)."""
    off = 5
    dlen = pkt[off]
    off += 1 + dlen
    slen = pkt[off]
    off += 1 + slen
    if (pkt[0] >> 4) & 0x03 == PT_INITIAL:
        tok_len, off = dec_varint(pkt, off)
        off += tok_len
    length, off = dec_varint(pkt, off)
    end = off + length
    if end > len(pkt):
        raise QuicError("truncated packet")
    return end


def open_long(keys: Keys, pkt: bytes) -> tuple[int, bytes, bytes, bytes,
                                               int]:
    """-> (ptype, dcid, scid, payload, consumed). Raises QuicError."""
    if len(pkt) < 7 or not pkt[0] & 0x80:
        raise QuicError("not a long-header packet")
    off = 1
    ver, = struct.unpack_from(">I", pkt, off)
    off += 4
    if ver != VERSION:
        raise QuicError(f"version {ver:#x}")
    dlen = pkt[off]
    dcid = pkt[off + 1:off + 1 + dlen]
    off += 1 + dlen
    slen = pkt[off]
    scid = pkt[off + 1:off + 1 + slen]
    off += 1 + slen
    ptype = (pkt[0] >> 4) & 0x03
    if ptype == PT_INITIAL:
        tok_len, off = dec_varint(pkt, off)
        off += tok_len
    length, off = dec_varint(pkt, off)
    pn_off = off
    end = pn_off + length
    if end > len(pkt):
        raise QuicError("truncated packet")
    sample = pkt[pn_off + 4:pn_off + 20]
    mask = keys.hp_mask(sample)
    first = pkt[0] ^ (mask[0] & 0x0F)
    pn_len = (first & 0x03) + 1
    pn_bytes = bytes(pkt[pn_off + i] ^ mask[1 + i]
                     for i in range(pn_len))
    pn = int.from_bytes(pn_bytes, "big")
    hdr = bytes([first]) + pkt[1:pn_off] + pn_bytes
    ct = pkt[pn_off + pn_len:end]
    try:
        payload = keys.aead.decrypt(keys.nonce(pn), ct, hdr)
    except Exception:
        raise QuicError("AEAD open failed")
    return ptype, dcid, scid, payload, end


def open_short(keys: Keys, pkt: bytes, dcid_len: int,
               largest: int = -1) -> tuple[int, bytes]:
    if len(pkt) < 1 + dcid_len + 20 or pkt[0] & 0x80:
        raise QuicError("not a short-header packet")
    pn_off = 1 + dcid_len
    sample = pkt[pn_off + 4:pn_off + 20]
    mask = keys.hp_mask(sample)
    first = pkt[0] ^ (mask[0] & 0x1F)
    pn_len = (first & 0x03) + 1
    pn_bytes = bytes(pkt[pn_off + i] ^ mask[1 + i]
                     for i in range(pn_len))
    pn = decode_pn(int.from_bytes(pn_bytes, "big"), pn_len, largest)
    hdr = bytes([first]) + pkt[1:pn_off] + pn_bytes
    ct = pkt[pn_off + pn_len:]
    try:
        payload = keys.aead.decrypt(keys.nonce(pn), ct, hdr)
    except Exception:
        raise QuicError("AEAD open failed")
    return pn, payload


# ---------------------------------------------------------------------------
# frames
# ---------------------------------------------------------------------------

def enc_stream_frame(stream_id: int, offset: int, data: bytes,
                     fin: bool) -> bytes:
    t = FRAME_STREAM | 0x02                   # LEN always present
    if offset:
        t |= 0x04
    if fin:
        t |= 0x01
    out = bytes([t]) + enc_varint(stream_id)
    if offset:
        out += enc_varint(offset)
    out += enc_varint(len(data)) + data
    return out


def enc_crypto_frame(offset: int, data: bytes) -> bytes:
    return (bytes([FRAME_CRYPTO]) + enc_varint(offset)
            + enc_varint(len(data)) + data)


def enc_ack_frame(largest: int) -> bytes:
    return (bytes([FRAME_ACK]) + enc_varint(largest) + enc_varint(0)
            + enc_varint(0) + enc_varint(0))


def parse_frames(payload: bytes):
    """Yield (type, dict) for every frame; unknown frames raise."""
    off = 0
    n = len(payload)
    while off < n:
        t = payload[off]
        if t == FRAME_PADDING:
            off += 1
            continue
        if t == FRAME_PING:
            off += 1
            yield FRAME_PING, {}
            continue
        if t in (FRAME_ACK, FRAME_ACK + 1):
            largest, off2 = dec_varint(payload, off + 1)
            delay, off2 = dec_varint(payload, off2)
            cnt, off2 = dec_varint(payload, off2)
            first, off2 = dec_varint(payload, off2)
            for _ in range(cnt):
                gap, off2 = dec_varint(payload, off2)
                rl, off2 = dec_varint(payload, off2)
            if t == FRAME_ACK + 1:            # ECN counts
                for _ in range(3):
                    _, off2 = dec_varint(payload, off2)
            off = off2
            yield FRAME_ACK, {"largest": largest}
            continue
        if t == FRAME_CRYPTO:
            o, off2 = dec_varint(payload, off + 1)
            ln, off2 = dec_varint(payload, off2)
            yield FRAME_CRYPTO, {"offset": o,
                                 "data": payload[off2:off2 + ln]}
            off = off2 + ln
            continue
        if FRAME_STREAM <= t <= FRAME_STREAM | 0x07:
            sid, off2 = dec_varint(payload, off + 1)
            o = 0
            if t & 0x04:
                o, off2 = dec_varint(payload, off2)
            if t & 0x02:
                ln, off2 = dec_varint(payload, off2)
            else:
                ln = n - off2
            yield FRAME_STREAM, {"stream": sid, "offset": o,
                                 "data": payload[off2:off2 + ln],
                                 "fin": bool(t & 0x01)}
            off = off2 + ln
            continue
        if t in (FRAME_MAX_DATA, FRAME_MAX_STREAM_DATA,
                 FRAME_MAX_STREAMS_UNI):
            _, off = dec_varint(payload, off + 1)
            if t == FRAME_MAX_STREAM_DATA:
                _, off = dec_varint(payload, off)
            continue
        if t == FRAME_HANDSHAKE_DONE:
            off += 1
            yield FRAME_HANDSHAKE_DONE, {}
            continue
        if t in (FRAME_CONNECTION_CLOSE, FRAME_CONNECTION_CLOSE + 1):
            code, off2 = dec_varint(payload, off + 1)
            if t == FRAME_CONNECTION_CLOSE:
                ft, off2 = dec_varint(payload, off2)
            rlen, off2 = dec_varint(payload, off2)
            yield FRAME_CONNECTION_CLOSE, {"code": code}
            off = off2 + rlen
            continue
        raise QuicError(f"unknown frame type {t:#x}")


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

MAX_STREAM_BYTES = 64 * 1024          # per-stream reassembly cap


class _Stream:
    __slots__ = ("chunks", "fin_at", "delivered", "buffered")

    def __init__(self):
        self.chunks: dict[int, bytes] = {}
        self.fin_at: int | None = None
        self.delivered = False
        self.buffered = 0

    def add(self, offset: int, data: bytes, fin: bool):
        """Raises QuicError when the stream exceeds the reassembly cap
        (hostile never-FIN streams must not grow memory unboundedly)."""
        if offset + len(data) > MAX_STREAM_BYTES:
            raise QuicError("stream exceeds reassembly cap")
        if data and offset not in self.chunks:
            self.buffered += len(data)
            if self.buffered > MAX_STREAM_BYTES:
                raise QuicError("stream exceeds reassembly cap")
            self.chunks[offset] = data
        if fin:
            end = offset + len(data)
            self.fin_at = end if self.fin_at is None \
                else min(self.fin_at, end)

    def complete(self) -> bytes | None:
        if self.fin_at is None or self.delivered:
            return None
        out = bytearray()
        off = 0
        while off < self.fin_at:
            c = self.chunks.get(off)
            if c is None:
                return None                   # gap
            out += c
            off += len(c)
        self.delivered = True
        return bytes(out[:self.fin_at])


class _Conn:
    def __init__(self, scid: bytes, ckeys: Keys, skeys: Keys,
                 initial_secret: bytes, peer: tuple,
                 tls: "fdtls.TlsServer"):
        self.scid = scid                      # our CID (client's dcid)
        self.ckeys = ckeys                    # client Initial keys
        self.skeys = skeys                    # server Initial keys
        self.initial_secret = initial_secret
        self.peer = peer
        self.tls = tls
        self.cbuf = {fdtls.EL_INITIAL: CryptoBuf(),
                     fdtls.EL_HANDSHAKE: CryptoBuf()}
        self.chs: Keys | None = None          # client Handshake keys
        self.shs: Keys | None = None          # server Handshake keys
        self.c1rtt: Keys | None = None
        self.s1rtt: Keys | None = None
        self.client_cid = b""
        self.cid_latched = False    # RFC 9000 allows zero-length SCIDs
        self.streams: dict[int, _Stream] = {}
        self.tx_pn = 0                        # 1-RTT pn space
        self.tx_pn_i = 0                      # Initial pn space
        self.tx_pn_h = 0                      # Handshake pn space
        self.rx_largest = -1
        self.rx_window = 0               # bitmap of the last 64 pns
        self.done_streams = 0
        self.hs_response: bytes | None = None    # for Initial retransmit
        self.done_sent = False
        # RFC 9001 §4.9.1: Initial keys are dead once a packet protected
        # with Handshake keys is processed; forged Initials (their keys
        # derive from the public dcid) must not reach the TLS machine
        self.initial_done = False

    def pn_fresh(self, pn: int) -> bool:
        """Anti-replay window (the RFC 9001 §9.2 duty): accept each
        1-RTT pn at most once within a 64-packet sliding window; pns
        older than the window are rejected outright."""
        if pn > self.rx_largest:
            shift = pn - self.rx_largest
            self.rx_window = ((self.rx_window << shift) | 1) \
                & ((1 << 64) - 1)
            self.rx_largest = pn
            return True
        back = self.rx_largest - pn
        if back >= 64:
            return False
        bit = 1 << back
        if self.rx_window & bit:
            return False
        self.rx_window |= bit
        return True


class QuicServer:
    """Single-socket TPU-ingest server: datagram in -> txn payloads out
    (the fd_quic_tile ingest contract). `identity_seed` is the ed25519
    key behind the TLS certificate (ephemeral when omitted)."""

    def __init__(self, sock, on_txn, cid_len: int = 8,
                 max_streams: int = 4096,
                 identity_seed: bytes | None = None):
        self.sock = sock
        self.on_txn = on_txn
        self.cid_len = cid_len
        self.max_streams = max_streams
        self.identity_seed = identity_seed or os.urandom(32)
        self._cert_cache: bytes | None = None
        self.conns: dict[bytes, _Conn] = {}
        self.metrics = {"pkts": 0, "bad_pkts": 0, "conns": 0,
                        "txns": 0, "streams": 0, "closed": 0,
                        "replayed": 0}

    def _cert(self) -> bytes:
        """The identity certificate, built once (a DER build + host
        ed25519 sign per connection would be handshake-flood bait)."""
        if self._cert_cache is None:
            self._cert_cache = fdtls.make_cert(self.identity_seed)
        return self._cert_cache

    # -- datagram ingest ----------------------------------------------------

    def on_datagram(self, data: bytes, addr) -> int:
        self.metrics["pkts"] += 1
        try:
            if data[0] & 0x80:
                return self._on_long(data, addr)
            return self._on_short(data, addr)
        except _CallbackError as e:
            raise e.__cause__ from None        # consumer bug: surface
        except (ValueError, IndexError, struct.error):
            # ValueError covers QuicError + anything a hostile
            # handshake can raise out of the TLS layer: one bad
            # datagram must never kill the ingest tile
            self.metrics["bad_pkts"] += 1
            return 0

    def _on_long(self, data: bytes, addr) -> int:
        """Handle a datagram of one or more coalesced long-header
        packets (RFC 9000 §12.2 — standard clients coalesce
        Initial(ACK) + Handshake(Finished) in one datagram)."""
        # peek dcid for key derivation (header is cleartext up to pn)
        dlen = data[5]
        dcid = data[6:6 + dlen]
        ptype_peek = (data[0] >> 4) & 0x03
        conn = self.conns.get(dcid)
        created = conn is None
        if created:
            if ptype_peek != PT_INITIAL:
                raise QuicError("first packet must be Initial")
            ck, sk, isec = initial_keys(dcid)
            if len(self.conns) >= self.max_streams:
                self.conns.pop(next(iter(self.conns)))
            conn = _Conn(dcid, ck, sk, isec, addr,
                         fdtls.TlsServer(self.identity_seed,
                                         cert=self._cert()))
            self.conns[dcid] = conn
            self.metrics["conns"] += 1
        handled = 0
        off = 0
        opened = 0
        initial_seen = False
        while off < len(data) and data[off] & 0x80:
            chunk = data[off:]
            ptype_peek = (chunk[0] >> 4) & 0x03
            if ptype_peek == PT_INITIAL:
                if conn.initial_done:          # discarded keys: skip
                    off += long_header_len(chunk)
                    continue
                keys, level = conn.ckeys, fdtls.EL_INITIAL
            elif conn.chs is not None:
                keys, level = conn.chs, fdtls.EL_HANDSHAKE
            else:
                raise QuicError("no handshake keys yet")
            try:
                ptype, _, scid, payload, consumed = open_long(keys,
                                                              chunk)
            except QuicError:
                if opened:
                    break          # trailing garbage after good pkts
                if created:        # never-authenticated conn: drop it
                    self.conns.pop(dcid, None)
                raise
            opened += 1
            off += consumed
            if ptype == PT_INITIAL:
                # Latch the return-CID on the FIRST authenticated
                # Initial only: Initial keys derive from the public
                # DCID, so an off-path forger could otherwise redirect
                # our flights with a bogus SCID pre-handshake.
                if not conn.cid_latched:
                    conn.client_cid = scid
                    conn.cid_latched = True
                initial_seen = True
            else:
                conn.initial_done = True
            fed = b""
            for ft, f in parse_frames(payload):
                if ft != FRAME_CRYPTO:
                    continue
                conn.cbuf[level].add(f["offset"], f["data"])
                fed += conn.cbuf[level].drain()
            if fed:
                try:
                    conn.tls.on_crypto(level, fed)
                except fdtls.TlsError:
                    self.conns.pop(dcid, None)
                    raise QuicError("tls failure") from None
                handled += self._pump_tls(conn, addr)
                initial_seen = False
        if not handled and initial_seen \
                and conn.hs_response is not None:
            # retransmitted Initial with no fresh CRYPTO: the client
            # lost our flight — resend it (loss tolerance, RFC 9002)
            self.sock.sendto(conn.hs_response, addr)
            handled += 1
        return handled

    def _pump_tls(self, conn: _Conn, addr) -> int:
        """Flush TLS emissions as sealed packets; install keys as the
        schedule advances. Server flight is coalesced into one
        datagram (RFC 9001 §4.1 pattern)."""
        out = b""
        while conn.tls.emit:
            lvl, hs_data = conn.tls.emit.pop(0)
            if lvl == fdtls.EL_INITIAL:
                payload = enc_ack_frame(0) + enc_crypto_frame(0, hs_data)
                out += seal_long(conn.skeys, PT_INITIAL,
                                 conn.client_cid, conn.scid,
                                 conn.tx_pn_i, payload)
                conn.tx_pn_i += 1
                # SH emitted -> handshake secrets exist
                conn.chs = Keys(conn.tls.sched.c_hs)
                conn.shs = Keys(conn.tls.sched.s_hs)
            else:
                off = 0
                while off < len(hs_data):
                    chunk = hs_data[off:off + 1100]
                    payload = enc_crypto_frame(off, chunk)
                    out += seal_long(conn.shs, PT_HANDSHAKE,
                                     conn.client_cid, conn.scid,
                                     conn.tx_pn_h, payload)
                    conn.tx_pn_h += 1
                    off += len(chunk)
                # server Finished emitted -> application secrets exist
                conn.c1rtt = Keys(conn.tls.sched.c_ap)
                conn.s1rtt = Keys(conn.tls.sched.s_ap)
        sent = 0
        if out:
            conn.hs_response = out
            self.sock.sendto(out, addr)
            sent = 1
        if conn.tls.complete and not conn.done_sent:
            done = seal_short(conn.s1rtt, conn.client_cid, conn.tx_pn,
                              bytes([FRAME_HANDSHAKE_DONE]))
            conn.tx_pn += 1
            self.sock.sendto(done, addr)
            conn.done_sent = True
            sent += 1
        return sent

    def _on_short(self, data: bytes, addr) -> int:
        dcid = data[1:1 + self.cid_len]
        conn = self.conns.get(dcid)
        if conn is None or conn.c1rtt is None:
            raise QuicError("no 1-RTT keys for connection")
        if not conn.tls.complete:
            # RFC 9001 §5.7: the server must not process 1-RTT data
            # before the client Finished authenticates the handshake
            raise QuicError("1-RTT before handshake completion")
        pn, payload = open_short(conn.c1rtt, data, self.cid_len,
                                 conn.rx_largest)
        if not conn.pn_fresh(pn):
            self.metrics["replayed"] += 1
            return 0                      # duplicate/replayed datagram
        handled = 0
        acked = False
        for ft, f in parse_frames(payload):
            if ft == FRAME_STREAM:
                st = conn.streams.get(f["stream"])
                if st is None:
                    if len(conn.streams) >= self.max_streams:
                        conn.streams.pop(next(iter(conn.streams)))
                    st = conn.streams[f["stream"]] = _Stream()
                    self.metrics["streams"] += 1
                st.add(f["offset"], f["data"], f["fin"])
                txn = st.complete()
                if txn is not None:
                    self.metrics["txns"] += 1
                    try:
                        self.on_txn(txn)
                    except Exception as e:
                        raise _CallbackError() from e
                    handled += 1
                    del conn.streams[f["stream"]]
                    conn.done_streams += 1
                if not acked:
                    ack = seal_short(conn.s1rtt, conn.client_cid,
                                     conn.tx_pn, enc_ack_frame(pn))
                    conn.tx_pn += 1
                    self.sock.sendto(ack, addr)
                    acked = True
            elif ft == FRAME_CONNECTION_CLOSE:
                self.conns.pop(dcid, None)
                self.metrics["closed"] += 1
                break
        return handled


# ---------------------------------------------------------------------------
# client (tests / bench load generation)
# ---------------------------------------------------------------------------

class QuicClient:
    def __init__(self, sock, server_addr, cid_len: int = 8,
                 expect_pub: bytes | None = None):
        self.sock = sock
        self.addr = server_addr
        self.scid = os.urandom(cid_len)       # our CID
        self.dcid = os.urandom(cid_len)       # server's CID for us
        self.ckeys, self.skeys, self.initial_secret = \
            initial_keys(self.dcid)
        self.tls = fdtls.TlsClient(expect_pub=expect_pub)
        self.cbuf = {fdtls.EL_INITIAL: CryptoBuf(),
                     fdtls.EL_HANDSHAKE: CryptoBuf()}
        self.chs: Keys | None = None
        self.shs: Keys | None = None
        self.c1rtt: Keys | None = None
        self.s1rtt: Keys | None = None
        self.tx_pn = 0
        self.tx_pn_i = 0
        self.tx_pn_h = 0
        self.rx_largest = -1
        self.next_stream = 2                  # client-initiated uni: 2,6,..
        self.server_pub: bytes | None = None

    def handshake(self, timeout: float = 5.0, retries: int = 3):
        self.tls.start()
        _, ch = self.tls.emit.pop(0)
        hello = enc_crypto_frame(0, ch)
        hello += bytes(max(0, 1162 - len(hello)))     # Initial padding
        pkt = seal_long(self.ckeys, PT_INITIAL, self.dcid, self.scid,
                        self.tx_pn_i, hello)
        self.tx_pn_i += 1
        self.sock.settimeout(timeout)
        for _ in range(retries):
            self.sock.sendto(pkt, self.addr)
            try:
                while not self.tls.complete:
                    data, _ = self.sock.recvfrom(4096)
                    try:
                        self._on_hs_datagram(data)
                    except fdtls.TlsError:
                        raise              # authentication failure
                    except (ValueError, IndexError, struct.error):
                        continue           # stray/garbage datagram
                break
            except TimeoutError:
                continue
        if not self.tls.complete:
            raise QuicError("handshake failed")
        self.server_pub = self.tls.server_pub

    def _on_hs_datagram(self, data: bytes):
        """Parse coalesced long-header packets, feed TLS, flush the
        client Finished when it appears."""
        off = 0
        while off < len(data) and off + 1 < len(data) \
                and data[off] & 0x80:
            chunk = data[off:]
            ptype_peek = (chunk[0] >> 4) & 0x03
            if ptype_peek == PT_INITIAL:
                if self.shs is not None:
                    # Initial keys discarded (RFC 9001 §4.9.1): the
                    # keys are public-derivable, so late/forged
                    # Initials must not reach the TLS machine
                    off += long_header_len(chunk)
                    continue
                keys, level = self.skeys, fdtls.EL_INITIAL
            else:
                if self.shs is None:
                    break
                keys, level = self.shs, fdtls.EL_HANDSHAKE
            ptype, _, _, payload, consumed = open_long(keys, chunk)
            off += consumed
            fed = b""
            for ft, f in parse_frames(payload):
                if ft == FRAME_CRYPTO:
                    self.cbuf[level].add(f["offset"], f["data"])
                    fed += self.cbuf[level].drain()
            if fed:
                self.tls.on_crypto(level, fed)
            if self.tls.sched.s_hs is not None and self.shs is None:
                self.chs = Keys(self.tls.sched.c_hs)
                self.shs = Keys(self.tls.sched.s_hs)
        while self.tls.emit:
            lvl, hs_data = self.tls.emit.pop(0)
            pkt = seal_long(self.chs, PT_HANDSHAKE, self.dcid,
                            self.scid, self.tx_pn_h,
                            enc_crypto_frame(0, hs_data))
            self.tx_pn_h += 1
            self.sock.sendto(pkt, self.addr)
        if self.tls.complete and self.c1rtt is None:
            self.c1rtt = Keys(self.tls.sched.c_ap)
            self.s1rtt = Keys(self.tls.sched.s_ap)

    def send_txn(self, payload: bytes):
        """One txn = one unidirectional stream with FIN (the TPU
        contract)."""
        sid = self.next_stream
        self.next_stream += 4
        off = 0
        mss = MAX_DATAGRAM - 64
        while off < len(payload) or off == 0:
            chunk = payload[off:off + mss]
            fin = off + len(chunk) >= len(payload)
            frame = enc_stream_frame(sid, off, chunk, fin)
            pkt = seal_short(self.c1rtt, self.dcid, self.tx_pn, frame)
            self.tx_pn += 1
            self.sock.sendto(pkt, self.addr)
            off += len(chunk)
            if fin:
                break

    def recv_acks(self, max_pkts: int = 16):
        self.sock.setblocking(False)
        n = 0
        for _ in range(max_pkts):
            try:
                data, _ = self.sock.recvfrom(2048)
            except OSError:
                break
            try:
                pn, payload = open_short(self.s1rtt, data,
                                         len(self.scid),
                                         self.rx_largest)
                self.rx_largest = max(self.rx_largest, pn)
                n += sum(1 for ft, _ in parse_frames(payload)
                         if ft == FRAME_ACK)
            except QuicError:
                pass
        return n
