"""HPACK (RFC 7541) — header compression for HTTP/2.

Scope matches the reference's h2 layer needs (ref: src/waltz/h2/
fd_hpack.c — the gRPC client path): full STATIC table, integer and
(decode-only) Huffman string forms, and a zero-dynamic-table
discipline: we advertise SETTINGS_HEADER_TABLE_SIZE=0, so a compliant
peer never references dynamic entries, and our encoder emits only
static-table references and literals-without-indexing. That keeps both
directions stateless — the property that makes the codec safe to
restart mid-connection (and ~200 lines instead of 2000).
"""
from __future__ import annotations

STATIC = [
    (b":authority", b""), (b":method", b"GET"), (b":method", b"POST"),
    (b":path", b"/"), (b":path", b"/index.html"), (b":scheme", b"http"),
    (b":scheme", b"https"), (b":status", b"200"), (b":status", b"204"),
    (b":status", b"206"), (b":status", b"304"), (b":status", b"400"),
    (b":status", b"404"), (b":status", b"500"), (b"accept-charset", b""),
    (b"accept-encoding", b"gzip, deflate"), (b"accept-language", b""),
    (b"accept-ranges", b""), (b"accept", b""), (b"access-control-allow-origin", b""),
    (b"age", b""), (b"allow", b""), (b"authorization", b""),
    (b"cache-control", b""), (b"content-disposition", b""),
    (b"content-encoding", b""), (b"content-language", b""),
    (b"content-length", b""), (b"content-location", b""),
    (b"content-range", b""), (b"content-type", b""), (b"cookie", b""),
    (b"date", b""), (b"etag", b""), (b"expect", b""), (b"expires", b""),
    (b"from", b""), (b"host", b""), (b"if-match", b""),
    (b"if-modified-since", b""), (b"if-none-match", b""),
    (b"if-range", b""), (b"if-unmodified-since", b""),
    (b"last-modified", b""), (b"link", b""), (b"location", b""),
    (b"max-forwards", b""), (b"proxy-authenticate", b""),
    (b"proxy-authorization", b""), (b"range", b""), (b"referer", b""),
    (b"refresh", b""), (b"retry-after", b""), (b"server", b""),
    (b"set-cookie", b""), (b"strict-transport-security", b""),
    (b"transfer-encoding", b""), (b"user-agent", b""), (b"vary", b""),
    (b"via", b""), (b"www-authenticate", b""),
]

_BY_PAIR = {pair: i + 1 for i, pair in enumerate(STATIC)}
_BY_NAME = {}
for _i, (_n, _v) in enumerate(STATIC):
    _BY_NAME.setdefault(_n, _i + 1)


class HpackError(ValueError):
    pass


def enc_int(value: int, prefix_bits: int, flags: int = 0) -> bytes:
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes([flags | value])
    out = bytearray([flags | limit])
    value -= limit
    while value >= 0x80:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)
    return bytes(out)


def dec_int(data: bytes, off: int, prefix_bits: int) -> tuple[int, int]:
    limit = (1 << prefix_bits) - 1
    if off >= len(data):
        raise HpackError("truncated integer")
    v = data[off] & limit
    off += 1
    if v < limit:
        return v, off
    shift = 0
    while True:
        if off >= len(data):
            raise HpackError("truncated integer continuation")
        b = data[off]
        off += 1
        v += (b & 0x7F) << shift
        shift += 7
        if shift > 35:
            raise HpackError("integer too large")
        if not b & 0x80:
            return v, off


# -- Huffman decode (RFC 7541 appendix B) — decode-only ---------------------
# table as (code, bits, sym); built into a nested dict walker lazily

_HUFF = None


def _huff_table():
    global _HUFF
    if _HUFF is not None:
        return _HUFF
    # (bits, code) per symbol 0..255 + EOS, RFC 7541 Appendix B
    codes = _HUFF_CODES
    root: dict = {}
    for sym, (code, bits) in enumerate(codes):
        node = root
        for i in range(bits - 1, -1, -1):
            bit = (code >> i) & 1
            if i == 0:
                node[bit] = sym
            else:
                node = node.setdefault(bit, {})
                if not isinstance(node, dict):
                    raise AssertionError("huffman table corrupt")
    _HUFF = root
    return root


def huff_decode(data: bytes) -> bytes:
    root = _huff_table()
    out = bytearray()
    node = root
    pad = 0
    pad_ones = True
    for byte in data:
        for i in range(7, -1, -1):
            bit = (byte >> i) & 1
            nxt = node[bit] if bit in node else None
            if nxt is None:
                raise HpackError("bad huffman code")
            if isinstance(nxt, int):
                if nxt == 256:
                    raise HpackError("EOS in huffman data")
                out.append(nxt)
                node = root
                pad = 0
                pad_ones = True
            else:
                node = nxt
                pad += 1
                pad_ones = pad_ones and bit == 1
    if pad > 7:
        raise HpackError("huffman padding too long")
    if pad and not pad_ones:
        # RFC 7541 §5.2: padding MUST be the EOS prefix (all ones)
        raise HpackError("huffman padding not EOS prefix")
    return bytes(out)


def enc_str(s: bytes) -> bytes:
    return enc_int(len(s), 7) + s          # always raw (never huffman)


def dec_str(data: bytes, off: int) -> tuple[bytes, int]:
    if off >= len(data):
        raise HpackError("truncated string")
    huff = bool(data[off] & 0x80)
    n, off = dec_int(data, off, 7)
    if off + n > len(data):
        raise HpackError("truncated string body")
    raw = data[off:off + n]
    return (huff_decode(raw) if huff else raw), off + n


def encode(headers: list[tuple[bytes, bytes]]) -> bytes:
    """Static refs + literals WITHOUT indexing (stateless)."""
    out = bytearray()
    for name, value in headers:
        idx = _BY_PAIR.get((name, value))
        if idx is not None:
            out += enc_int(idx, 7, 0x80)          # indexed field
            continue
        nidx = _BY_NAME.get(name)
        if nidx is not None:
            out += enc_int(nidx, 4, 0x00)         # literal, indexed name
        else:
            out += bytes([0x00]) + enc_str(name)
        out += enc_str(value)
    return bytes(out)


def decode(data: bytes) -> list[tuple[bytes, bytes]]:
    """Decode a header block. Dynamic-table references are a protocol
    error under our SETTINGS_HEADER_TABLE_SIZE=0 announcement."""
    out = []
    off = 0
    while off < len(data):
        b = data[off]
        if b & 0x80:                               # indexed
            idx, off = dec_int(data, off, 7)
            if not 1 <= idx <= len(STATIC):
                raise HpackError(f"dynamic/invalid index {idx}")
            out.append(STATIC[idx - 1])
        elif (b & 0xE0) == 0x20:                   # table size update
            size, off = dec_int(data, off, 5)
            if size != 0:
                raise HpackError("dynamic table not permitted")
        else:
            if b & 0x40:
                prefix = 6
            elif b & 0x10:
                prefix = 4                          # never-indexed
            else:
                prefix = 4                          # without indexing
            idx, off = dec_int(data, off, prefix)
            if idx:
                if idx > len(STATIC):
                    raise HpackError(f"dynamic name index {idx}")
                name = STATIC[idx - 1][0]
            else:
                name, off = dec_str(data, off)
            value, off = dec_str(data, off)
            if b & 0x40:
                # peer asked to index: legal on the wire, but with our
                # 0-size table it must not be referenced later; accept
                # the literal itself
                pass
            out.append((name, value))
    return out


# RFC 7541 Appendix B code table (code, nbits) for symbols 0..256
_HUFF_CODES = [
    (0x1ff8, 13), (0x7fffd8, 23), (0xfffffe2, 28), (0xfffffe3, 28),
    (0xfffffe4, 28), (0xfffffe5, 28), (0xfffffe6, 28), (0xfffffe7, 28),
    (0xfffffe8, 28), (0xffffea, 24), (0x3ffffffc, 30), (0xfffffe9, 28),
    (0xfffffea, 28), (0x3ffffffd, 30), (0xfffffeb, 28), (0xfffffec, 28),
    (0xfffffed, 28), (0xfffffee, 28), (0xfffffef, 28), (0xffffff0, 28),
    (0xffffff1, 28), (0xffffff2, 28), (0x3ffffffe, 30), (0xffffff3, 28),
    (0xffffff4, 28), (0xffffff5, 28), (0xffffff6, 28), (0xffffff7, 28),
    (0xffffff8, 28), (0xffffff9, 28), (0xffffffa, 28), (0xffffffb, 28),
    (0x14, 6), (0x3f8, 10), (0x3f9, 10), (0xffa, 12),
    (0x1ff9, 13), (0x15, 6), (0xf8, 8), (0x7fa, 11),
    (0x3fa, 10), (0x3fb, 10), (0xf9, 8), (0x7fb, 11),
    (0xfa, 8), (0x16, 6), (0x17, 6), (0x18, 6),
    (0x0, 5), (0x1, 5), (0x2, 5), (0x19, 6),
    (0x1a, 6), (0x1b, 6), (0x1c, 6), (0x1d, 6),
    (0x1e, 6), (0x1f, 6), (0x5c, 7), (0xfb, 8),
    (0x7ffc, 15), (0x20, 6), (0xffb, 12), (0x3fc, 10),
    (0x1ffa, 13), (0x21, 6), (0x5d, 7), (0x5e, 7),
    (0x5f, 7), (0x60, 7), (0x61, 7), (0x62, 7),
    (0x63, 7), (0x64, 7), (0x65, 7), (0x66, 7),
    (0x67, 7), (0x68, 7), (0x69, 7), (0x6a, 7),
    (0x6b, 7), (0x6c, 7), (0x6d, 7), (0x6e, 7),
    (0x6f, 7), (0x70, 7), (0x71, 7), (0x72, 7),
    (0xfc, 8), (0x73, 7), (0xfd, 8), (0x1ffb, 13),
    (0x7fff0, 19), (0x1ffc, 13), (0x3ffc, 14), (0x22, 6),
    (0x7ffd, 15), (0x3, 5), (0x23, 6), (0x4, 5),
    (0x24, 6), (0x5, 5), (0x25, 6), (0x26, 6),
    (0x27, 6), (0x6, 5), (0x74, 7), (0x75, 7),
    (0x28, 6), (0x29, 6), (0x2a, 6), (0x7, 5),
    (0x2b, 6), (0x76, 7), (0x2c, 6), (0x8, 5),
    (0x9, 5), (0x2d, 6), (0x77, 7), (0x78, 7),
    (0x79, 7), (0x7a, 7), (0x7b, 7), (0x7ffe, 15),
    (0x7fc, 11), (0x3ffd, 14), (0x1ffd, 13), (0xffffffc, 28),
    (0xfffe6, 20), (0x3fffd2, 22), (0xfffe7, 20), (0xfffe8, 20),
    (0x3fffd3, 22), (0x3fffd4, 22), (0x3fffd5, 22), (0x7fffd9, 23),
    (0x3fffd6, 22), (0x7fffda, 23), (0x7fffdb, 23), (0x7fffdc, 23),
    (0x7fffdd, 23), (0x7fffde, 23), (0xffffeb, 24), (0x7fffdf, 23),
    (0xffffec, 24), (0xffffed, 24), (0x3fffd7, 22), (0x7fffe0, 23),
    (0xffffee, 24), (0x7fffe1, 23), (0x7fffe2, 23), (0x7fffe3, 23),
    (0x7fffe4, 23), (0x1fffdc, 21), (0x3fffd8, 22), (0x7fffe5, 23),
    (0x3fffd9, 22), (0x7fffe6, 23), (0x7fffe7, 23), (0xffffef, 24),
    (0x3fffda, 22), (0x1fffdd, 21), (0xfffe9, 20), (0x3fffdb, 22),
    (0x3fffdc, 22), (0x7fffe8, 23), (0x7fffe9, 23), (0x1fffde, 21),
    (0x7fffea, 23), (0x3fffdd, 22), (0x3fffde, 22), (0xfffff0, 24),
    (0x1fffdf, 21), (0x3fffdf, 22), (0x7fffeb, 23), (0x7fffec, 23),
    (0x1fffe0, 21), (0x1fffe1, 21), (0x3fffe0, 22), (0x1fffe2, 21),
    (0x7fffed, 23), (0x3fffe1, 22), (0x7fffee, 23), (0x7fffef, 23),
    (0xfffea, 20), (0x3fffe2, 22), (0x3fffe3, 22), (0x3fffe4, 22),
    (0x7ffff0, 23), (0x3fffe5, 22), (0x3fffe6, 22), (0x7ffff1, 23),
    (0x3ffffe0, 26), (0x3ffffe1, 26), (0xfffeb, 20), (0x7fff1, 19),
    (0x3fffe7, 22), (0x7ffff2, 23), (0x3fffe8, 22), (0x1ffffec, 25),
    (0x3ffffe2, 26), (0x3ffffe3, 26), (0x3ffffe4, 26), (0x7ffffde, 27),
    (0x7ffffdf, 27), (0x3ffffe5, 26), (0xfffff1, 24), (0x1ffffed, 25),
    (0x7fff2, 19), (0x1fffe3, 21), (0x3ffffe6, 26), (0x7ffffe0, 27),
    (0x7ffffe1, 27), (0x3ffffe7, 26), (0x7ffffe2, 27), (0xfffff2, 24),
    (0x1fffe4, 21), (0x1fffe5, 21), (0x3ffffe8, 26), (0x3ffffe9, 26),
    (0xffffffd, 28), (0x7ffffe3, 27), (0x7ffffe4, 27), (0x7ffffe5, 27),
    (0xfffec, 20), (0xfffff3, 24), (0xfffed, 20), (0x1fffe6, 21),
    (0x3fffe9, 22), (0x1fffe7, 21), (0x1fffe8, 21), (0x7ffff3, 23),
    (0x3fffea, 22), (0x3fffeb, 22), (0x1ffffee, 25), (0x1ffffef, 25),
    (0xfffff4, 24), (0xfffff5, 24), (0x3ffffea, 26), (0x7ffff4, 23),
    (0x3ffffeb, 26), (0x7ffffe6, 27), (0x3ffffec, 26), (0x3ffffed, 26),
    (0x7ffffe7, 27), (0x7ffffe8, 27), (0x7ffffe9, 27), (0x7ffffea, 27),
    (0x7ffffeb, 27), (0xffffffe, 28), (0x7ffffec, 27), (0x7ffffed, 27),
    (0x7ffffee, 27), (0x7ffffef, 27), (0x7fffff0, 27), (0x3ffffee, 26),
    (0x3fffffff, 30),
]
