"""Minimal TLS 1.3 (RFC 8446) for the QUIC handshake.

The reference implements its own TLS 1.3 subset for exactly this job
(src/waltz/tls/fd_tls.c — "server-only, QUIC-only" in spirit: X25519
key share, Ed25519 CertificateVerify, AES-128-GCM), with a mock/minimal
X.509 generator in ballet (SURVEY §2.3 "x509 mock"). This module is the
same scope, TPU-framework-shaped:

  * single cipher suite TLS_AES_128_GCM_SHA256 (0x1301)
  * single group x25519 (0x001d), single sig alg ed25519 (0x0807)
  * server auth only (no client certs), no session resumption/0-RTT,
    no HelloRetryRequest (a client offering the wrong group is closed)
  * self-signed Ed25519 X.509 built by a real DER encoder (not a
    spliced template like the reference's mock — ours parses)

The key schedule (§7.1), transcript hashing, Finished MACs, and
CertificateVerify context are implemented exactly per RFC; external
grounding comes from an independent stack (tests/test_tls.py): the
x25519 exchange is pinned to RFC 7748 vectors and differentially
checked against OpenSSL, and the generated certificate must parse
under `cryptography.x509` with its self-signature verifying under
OpenSSL's Ed25519 — so the DER encoder, signing input, and transcript
discipline are witnessed beyond self-consistency.

Flow (QUIC encryption levels, RFC 9001 §4.1):
  client               server
  Initial:  ClientHello --->
            <--- Initial: ServerHello
            <--- Handshake: EncryptedExtensions, Certificate,
                            CertificateVerify, Finished
  Handshake: Finished --->
  (both sides now hold the 1-RTT application secrets)

State machines expose `emit` as a list of (level, handshake_bytes) and
publish traffic secrets the moment they become available so the QUIC
layer can install packet-protection keys per level.
"""
from __future__ import annotations

import hashlib
import hmac as hmac_mod
import os
import struct

from ..utils import ed25519_ref, x25519

# encryption levels (shared with waltz/quic.py)
EL_INITIAL = 0
EL_HANDSHAKE = 1
EL_APP = 2

HASH_LEN = 32  # SHA-256

CIPHER_AES128GCM_SHA256 = 0x1301
GROUP_X25519 = 0x001D
SIG_ED25519 = 0x0807

# handshake message types
HT_CLIENT_HELLO = 1
HT_SERVER_HELLO = 2
HT_ENCRYPTED_EXTENSIONS = 8
HT_CERTIFICATE = 11
HT_CERTIFICATE_VERIFY = 15
HT_FINISHED = 20
HT_NEW_SESSION_TICKET = 4

# extensions
EXT_SERVER_NAME = 0
EXT_SUPPORTED_GROUPS = 10
EXT_SIGNATURE_ALGORITHMS = 13
EXT_ALPN = 16
EXT_SUPPORTED_VERSIONS = 43
EXT_KEY_SHARE = 51
EXT_QUIC_TRANSPORT_PARAMS = 0x39

TLS13 = 0x0304
LEGACY_VERSION = 0x0303

ALPN_TPU = b"solana-tpu"


class TlsError(ValueError):
    pass


# ---------------------------------------------------------------------------
# HKDF / key schedule (RFC 8446 §7.1)
# ---------------------------------------------------------------------------

def hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac_mod.new(salt, ikm, hashlib.sha256).digest()


def hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    out, t, i = b"", b"", 1
    while len(out) < length:
        t = hmac_mod.new(prk, t + info + bytes([i]),
                         hashlib.sha256).digest()
        out += t
        i += 1
    return out[:length]


def hkdf_expand_label(secret: bytes, label: bytes, context: bytes,
                      length: int) -> bytes:
    full = b"tls13 " + label
    info = (struct.pack(">H", length) + bytes([len(full)]) + full
            + bytes([len(context)]) + context)
    return hkdf_expand(secret, info, length)


def derive_secret(secret: bytes, label: bytes,
                  transcript: bytes) -> bytes:
    return hkdf_expand_label(secret, label,
                             hashlib.sha256(transcript).digest(),
                             HASH_LEN)


class Schedule:
    """The TLS 1.3 key schedule, advanced as transcript milestones
    arrive. Secrets are exposed as attributes; `None` until derived."""

    def __init__(self):
        zeros = bytes(HASH_LEN)
        self.early = hkdf_extract(bytes(HASH_LEN), zeros)
        self.hs: bytes | None = None
        self.master: bytes | None = None
        self.c_hs: bytes | None = None
        self.s_hs: bytes | None = None
        self.c_ap: bytes | None = None
        self.s_ap: bytes | None = None

    def on_shared(self, shared: bytes, transcript_ch_sh: bytes):
        derived = derive_secret(self.early, b"derived", b"")
        self.hs = hkdf_extract(derived, shared)
        self.c_hs = derive_secret(self.hs, b"c hs traffic",
                                  transcript_ch_sh)
        self.s_hs = derive_secret(self.hs, b"s hs traffic",
                                  transcript_ch_sh)

    def on_server_finished(self, transcript_ch_sfin: bytes):
        derived = derive_secret(self.hs, b"derived", b"")
        self.master = hkdf_extract(derived, bytes(HASH_LEN))
        self.c_ap = derive_secret(self.master, b"c ap traffic",
                                  transcript_ch_sfin)
        self.s_ap = derive_secret(self.master, b"s ap traffic",
                                  transcript_ch_sfin)


def finished_mac(base_secret: bytes, transcript: bytes) -> bytes:
    key = hkdf_expand_label(base_secret, b"finished", b"", HASH_LEN)
    return hmac_mod.new(key, hashlib.sha256(transcript).digest(),
                        hashlib.sha256).digest()


# ---------------------------------------------------------------------------
# minimal DER + self-signed Ed25519 X.509
# ---------------------------------------------------------------------------

OID_ED25519 = bytes.fromhex("06032b6570")          # 1.3.101.112
OID_CN = bytes.fromhex("0603550403")               # 2.5.4.3


def _der(tag: int, content: bytes) -> bytes:
    n = len(content)
    if n < 0x80:
        return bytes([tag, n]) + content
    ln = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([tag, 0x80 | len(ln)]) + ln + content


def _der_seq(*parts: bytes) -> bytes:
    return _der(0x30, b"".join(parts))


def make_cert(seed: bytes) -> bytes:
    """Self-signed Ed25519 X.509v3, CN=fdtpu. Real DER (parses under
    standard tooling), fixed validity — the reference's 'x509 mock'
    role with honest encoding."""
    _, _, pub = ed25519_ref.keypair(seed)
    name = _der_seq(_der(0x31, _der_seq(
        OID_CN, _der(0x0C, b"fdtpu"))))
    validity = _der_seq(_der(0x17, b"260101000000Z"),
                        _der(0x17, b"360101000000Z"))
    spki = _der_seq(_der_seq(OID_ED25519),
                    _der(0x03, b"\x00" + pub))
    alg = _der_seq(OID_ED25519)
    tbs = _der_seq(
        _der(0xA0, _der(0x02, b"\x02")),       # [0] version v3
        _der(0x02, b"\x01"),                   # serial
        alg, name, validity, name, spki)
    sig = ed25519_ref.sign(seed, tbs)
    return _der_seq(tbs, alg, _der(0x03, b"\x00" + sig))


def cert_pubkey(cert: bytes) -> bytes:
    """Extract the Ed25519 SPKI public key: the AlgorithmIdentifier
    SEQUENCE (containing only the ed25519 OID) followed by a 33-byte
    BIT STRING (unused-bits byte + 32-byte key)."""
    pat = b"\x30\x05" + OID_ED25519 + b"\x03\x21\x00"
    i = cert.find(pat)
    if i < 0:
        raise TlsError("no ed25519 SPKI in certificate")
    return cert[i + len(pat):i + len(pat) + 32]


CV_CONTEXT_SERVER = b" " * 64 + b"TLS 1.3, server CertificateVerify" \
    + b"\x00"


def cert_verify_payload(transcript: bytes) -> bytes:
    return CV_CONTEXT_SERVER + hashlib.sha256(transcript).digest()


# ---------------------------------------------------------------------------
# handshake message codec
# ---------------------------------------------------------------------------

def _msg(ht: int, body: bytes) -> bytes:
    return bytes([ht]) + len(body).to_bytes(3, "big") + body


def _ext(et: int, body: bytes) -> bytes:
    return struct.pack(">HH", et, len(body)) + body


def _parse_exts(b: bytes) -> dict[int, bytes]:
    out = {}
    off = 0
    while off < len(b):
        et, ln = struct.unpack_from(">HH", b, off)
        out[et] = b[off + 4:off + 4 + ln]
        off += 4 + ln
    return out


def build_client_hello(random32: bytes, x_pub: bytes,
                       quic_tp: bytes) -> bytes:
    exts = b"".join([
        _ext(EXT_SUPPORTED_VERSIONS, bytes([2]) +
             struct.pack(">H", TLS13)),
        _ext(EXT_SUPPORTED_GROUPS,
             struct.pack(">HH", 2, GROUP_X25519)),
        _ext(EXT_SIGNATURE_ALGORITHMS,
             struct.pack(">HH", 2, SIG_ED25519)),
        _ext(EXT_KEY_SHARE, struct.pack(
            ">HHH", 4 + len(x_pub), GROUP_X25519, len(x_pub)) + x_pub),
        _ext(EXT_ALPN, struct.pack(">HB", len(ALPN_TPU) + 1,
                                   len(ALPN_TPU)) + ALPN_TPU),
        _ext(EXT_QUIC_TRANSPORT_PARAMS, quic_tp),
    ])
    body = (struct.pack(">H", LEGACY_VERSION) + random32
            + bytes([0])                                  # session id
            + struct.pack(">HH", 2, CIPHER_AES128GCM_SHA256)
            + bytes([1, 0])                               # compression
            + struct.pack(">H", len(exts)) + exts)
    return _msg(HT_CLIENT_HELLO, body)


def parse_client_hello(body: bytes) -> dict:
    off = 2
    random32 = body[off:off + 32]
    off += 32
    sid_len = body[off]
    off += 1 + sid_len
    cs_len, = struct.unpack_from(">H", body, off)
    suites = [struct.unpack_from(">H", body, off + 2 + i)[0]
              for i in range(0, cs_len, 2)]
    off += 2 + cs_len
    comp_len = body[off]
    off += 1 + comp_len
    ext_len, = struct.unpack_from(">H", body, off)
    exts = _parse_exts(body[off + 2:off + 2 + ext_len])
    ks = exts.get(EXT_KEY_SHARE, b"")
    x_pub = None
    if len(ks) >= 2:
        koff = 2
        while koff + 4 <= len(ks):
            grp, kl = struct.unpack_from(">HH", ks, koff)
            if grp == GROUP_X25519:
                x_pub = ks[koff + 4:koff + 4 + kl]
            koff += 4 + kl
    vers = exts.get(EXT_SUPPORTED_VERSIONS, b"")
    offers13 = TLS13 in [struct.unpack_from(">H", vers, 1 + i)[0]
                         for i in range(0, vers[0] if vers else 0, 2)]
    alpns = []
    ab = exts.get(EXT_ALPN)
    if ab and len(ab) >= 2:
        aoff = 2
        while aoff < len(ab):
            n = ab[aoff]
            alpns.append(ab[aoff + 1:aoff + 1 + n])
            aoff += 1 + n
    return {"random": random32, "suites": suites, "x_pub": x_pub,
            "tls13": offers13, "alpns": alpns,
            "quic_tp": exts.get(EXT_QUIC_TRANSPORT_PARAMS)}


def build_server_hello(random32: bytes, x_pub: bytes) -> bytes:
    exts = b"".join([
        _ext(EXT_SUPPORTED_VERSIONS, struct.pack(">H", TLS13)),
        _ext(EXT_KEY_SHARE, struct.pack(
            ">HH", GROUP_X25519, len(x_pub)) + x_pub),
    ])
    body = (struct.pack(">H", LEGACY_VERSION) + random32
            + bytes([0])
            + struct.pack(">H", CIPHER_AES128GCM_SHA256)
            + bytes([0])
            + struct.pack(">H", len(exts)) + exts)
    return _msg(HT_SERVER_HELLO, body)


def parse_server_hello(body: bytes) -> dict:
    off = 2
    random32 = body[off:off + 32]
    off += 32
    sid_len = body[off]
    off += 1 + sid_len
    suite, = struct.unpack_from(">H", body, off)
    off += 3                                   # suite + compression
    ext_len, = struct.unpack_from(">H", body, off)
    exts = _parse_exts(body[off + 2:off + 2 + ext_len])
    ks = exts.get(EXT_KEY_SHARE, b"")
    x_pub = None
    if len(ks) >= 4:
        grp, kl = struct.unpack_from(">HH", ks, 0)
        if grp == GROUP_X25519:
            x_pub = ks[4:4 + kl]
    return {"random": random32, "suite": suite, "x_pub": x_pub}


def build_certificate(cert: bytes) -> bytes:
    entry = len(cert).to_bytes(3, "big") + cert + struct.pack(">H", 0)
    body = bytes([0]) + len(entry).to_bytes(3, "big") + entry
    return _msg(HT_CERTIFICATE, body)


def parse_certificate(body: bytes) -> bytes:
    ctx_len = body[0]
    off = 1 + ctx_len + 3                      # skip list length
    cert_len = int.from_bytes(body[off:off + 3], "big")
    return body[off + 3:off + 3 + cert_len]


def iter_messages(buf: bytes):
    """Yield (type, body, raw) for complete messages; returns leftover
    offset."""
    off = 0
    while off + 4 <= len(buf):
        ht = buf[off]
        ln = int.from_bytes(buf[off + 1:off + 4], "big")
        if off + 4 + ln > len(buf):
            break
        yield ht, buf[off + 4:off + 4 + ln], buf[off:off + 4 + ln]
        off += 4 + ln
    return


def _complete_len(buf: bytes) -> int:
    """Bytes of `buf` forming complete handshake messages."""
    off = 0
    while off + 4 <= len(buf):
        ln = int.from_bytes(buf[off + 1:off + 4], "big")
        if off + 4 + ln > len(buf):
            break
        off += 4 + ln
    return off


# ---------------------------------------------------------------------------
# state machines
# ---------------------------------------------------------------------------

class _Endpoint:
    def __init__(self):
        self.sched = Schedule()
        self.transcript = b""
        self.emit: list[tuple[int, bytes]] = []   # (level, bytes)
        self.buf = {EL_INITIAL: b"", EL_HANDSHAKE: b"", EL_APP: b""}
        self.complete = False
        self.alert: str | None = None

    def _feed(self, level: int, data: bytes):
        self.buf[level] += data
        n = _complete_len(self.buf[level])
        ready = self.buf[level][:n]
        self.buf[level] = self.buf[level][n:]
        for ht, body, raw in iter_messages(ready):
            self._on_msg(level, ht, body, raw)

    def on_crypto(self, level: int, data: bytes):
        try:
            self._feed(level, data)
        except TlsError:
            raise
        except (IndexError, struct.error, ValueError) as e:
            # ValueError covers hostile key shares (x25519 length /
            # small-order rejection) — anything non-protocol becomes a
            # typed TlsError so transports can fail the conn, not crash
            raise TlsError(f"malformed handshake: {e}") from None


class TlsServer(_Endpoint):
    """Server half. Feed CRYPTO data via on_crypto; read `emit` for
    outbound CRYPTO data per level; traffic secrets appear on `sched`
    as the handshake advances; `complete` after client Finished."""

    def __init__(self, identity_seed: bytes, quic_tp: bytes = b"",
                 cert: bytes | None = None):
        super().__init__()
        self.seed = identity_seed
        self.quic_tp = quic_tp
        self.xpriv = os.urandom(32)
        self.cert = cert if cert is not None else make_cert(identity_seed)
        self.peer_quic_tp: bytes | None = None
        self.alpn_ok = False

    def _on_msg(self, level: int, ht: int, body: bytes, raw: bytes):
        if ht == HT_CLIENT_HELLO and level == EL_INITIAL \
                and self.sched.hs is None:
            ch = parse_client_hello(body)
            if not ch["tls13"] \
                    or CIPHER_AES128GCM_SHA256 not in ch["suites"] \
                    or ch["x_pub"] is None:
                self.alert = "no common cipher/group/version"
                raise TlsError(self.alert)
            if ALPN_TPU not in ch["alpns"]:
                self.alert = "no_application_protocol"
                raise TlsError(self.alert)
            self.alpn_ok = True
            self.peer_quic_tp = ch["quic_tp"]
            self.transcript = raw
            sh = build_server_hello(os.urandom(32),
                                    x25519.pubkey(self.xpriv))
            self.transcript += sh
            shared = x25519.shared(self.xpriv, ch["x_pub"])
            self.sched.on_shared(shared, self.transcript)
            self.emit.append((EL_INITIAL, sh))
            # server flight at the handshake level
            flight = _msg(HT_ENCRYPTED_EXTENSIONS, struct.pack(
                ">H", len(self.quic_tp) + 4)
                + _ext(EXT_QUIC_TRANSPORT_PARAMS, self.quic_tp))
            flight += build_certificate(self.cert)
            self.transcript += flight
            sig = ed25519_ref.sign(
                self.seed, cert_verify_payload(self.transcript))
            cv = _msg(HT_CERTIFICATE_VERIFY,
                      struct.pack(">HH", SIG_ED25519, len(sig)) + sig)
            self.transcript += cv
            fin = _msg(HT_FINISHED,
                       finished_mac(self.sched.s_hs, self.transcript))
            self.transcript += fin
            self.sched.on_server_finished(self.transcript)
            self.emit.append((EL_HANDSHAKE, flight + cv + fin))
        elif ht == HT_FINISHED and level == EL_HANDSHAKE \
                and not self.complete:
            # client Finished covers transcript through server Finished
            expect = finished_mac(self.sched.c_hs, self.transcript)
            if not hmac_mod.compare_digest(body, expect):
                self.alert = "bad client Finished"
                raise TlsError(self.alert)
            self.transcript += raw
            self.complete = True
        else:
            raise TlsError(f"unexpected message {ht} at level {level}")


class TlsClient(_Endpoint):
    """Client half. `start()` emits the ClientHello; server identity
    (SPKI pubkey) lands in `server_pub` after CertificateVerify."""

    def __init__(self, quic_tp: bytes = b"",
                 expect_pub: bytes | None = None):
        super().__init__()
        self.quic_tp = quic_tp
        self.expect_pub = expect_pub
        self.xpriv = os.urandom(32)
        self.server_pub: bytes | None = None
        self.peer_quic_tp: bytes | None = None
        self._cv_transcript: bytes | None = None

    def start(self):
        ch = build_client_hello(os.urandom(32),
                                x25519.pubkey(self.xpriv),
                                self.quic_tp)
        self.transcript = ch
        self.emit.append((EL_INITIAL, ch))

    def _on_msg(self, level: int, ht: int, body: bytes, raw: bytes):
        if ht == HT_SERVER_HELLO and level == EL_INITIAL \
                and self.sched.hs is None:
            sh = parse_server_hello(body)
            if sh["suite"] != CIPHER_AES128GCM_SHA256 \
                    or sh["x_pub"] is None:
                self.alert = "bad ServerHello"
                raise TlsError(self.alert)
            self.transcript += raw
            shared = x25519.shared(self.xpriv, sh["x_pub"])
            self.sched.on_shared(shared, self.transcript)
        elif ht == HT_ENCRYPTED_EXTENSIONS and level == EL_HANDSHAKE:
            exts = _parse_exts(body[2:])
            self.peer_quic_tp = exts.get(EXT_QUIC_TRANSPORT_PARAMS)
            self.transcript += raw
        elif ht == HT_CERTIFICATE and level == EL_HANDSHAKE:
            cert = parse_certificate(body)
            self.server_pub = cert_pubkey(cert)
            if self.expect_pub is not None \
                    and self.server_pub != self.expect_pub:
                self.alert = "server identity mismatch"
                raise TlsError(self.alert)
            self.transcript += raw
        elif ht == HT_CERTIFICATE_VERIFY and level == EL_HANDSHAKE:
            alg, slen = struct.unpack_from(">HH", body, 0)
            sig = body[4:4 + slen]
            if alg != SIG_ED25519 or self.server_pub is None:
                self.alert = "bad CertificateVerify"
                raise TlsError(self.alert)
            if not ed25519_ref.verify(
                    sig, self.server_pub,
                    cert_verify_payload(self.transcript)):
                self.alert = "CertificateVerify signature invalid"
                raise TlsError(self.alert)
            self.transcript += raw
        elif ht == HT_FINISHED and level == EL_HANDSHAKE \
                and not self.complete:
            expect = finished_mac(self.sched.s_hs, self.transcript)
            if not hmac_mod.compare_digest(body, expect):
                self.alert = "bad server Finished"
                raise TlsError(self.alert)
            self.transcript += raw
            self.sched.on_server_finished(self.transcript)
            fin = _msg(HT_FINISHED,
                       finished_mac(self.sched.c_hs, self.transcript))
            self.emit.append((EL_HANDSHAKE, fin))
            self.complete = True
        elif ht == HT_NEW_SESSION_TICKET:
            pass                               # ignored (no resumption)
        else:
            raise TlsError(f"unexpected message {ht} at level {level}")
