"""Shm-resident funk: the fork tree re-expressed over the wksp ABI.

The in-process `Funk` (funk.py) is a dict tree — correct, but it chains
the whole execution path to ONE Python process. The reference backs the
same prepare/cancel/publish semantics with relocatable shared-memory
maps precisely so many tiles can read and write accounts concurrently
(ref: src/funk/fd_funk.h:28-90, src/flamenco/accdb/). `ShmFunk` is that
shape: the record tree lives in a carved store region (native/fdtpu.cc
— txn slot table + record map + size-class heap, serialized on a
dead-owner-stealing spinlock), and this class is a byte-compatible
`Funk` API facade over it, so `svm/accdb.py`, the executor, and the
conformance/bank-hash suites run unchanged on either backend.

Two layers of identity:

  * Python callers use any hashable xid (slots, strings, tuples) — the
    facade interns them to u64 wire xids (assigned from 1; 0 is the
    published root). The intern table is per-process; CROSS-process
    users (exec tiles) exchange the raw u64 over rings and talk to the
    store through `raw` (the runtime.Store view) directly.
  * Values are tag-framed bytes: 0 = bare int lamports (u64 LE, the
    legacy genesis path), 1 = accdb Account (fixed header + var data),
    2 = pickle (sysvars, stake state, anything else) — decode always
    reconstructs the typed record, so AccDb's peek/open_rw contracts
    hold verbatim.

Config rides the topology as a `[funk]` section (normalize_funk is the
one validator — config load, topo.build, and fdlint's bad-funk rule all
call it):

    [funk]
    backend = "shm"       # "process" (dict tree) | "shm" (carved store)
    rec_max = 4096        # record slots
    txn_max = 256         # in-preparation txn slots
    heap_mb = 16          # value heap
"""
from __future__ import annotations

import os
import pickle
import struct

from .funk import MAX_FORK_DEPTH, FunkTxnError

FUNK_DEFAULTS = {
    "backend": "process",
    "rec_max": 4096,
    "txn_max": 256,
    "heap_mb": 16,
}
FUNK_BACKENDS = ("process", "shm")

_TAG_INT, _TAG_ACCT, _TAG_PICKLE = 0, 1, 2
_ACCT_HDR = struct.Struct("<Q32sBQ")      # lamports, owner, exec, rent_epoch


def _suggest(key, candidates):
    from ..lint.registry import suggest
    return suggest(str(key), candidates)


def normalize_funk(spec) -> dict:
    """Validate + default-fill a [funk] table. Same fail-before-launch
    stance as [shed]/[trace]: raises ValueError with a did-you-mean."""
    out = dict(FUNK_DEFAULTS)
    if spec is None:
        return out
    if not isinstance(spec, dict):
        raise ValueError(f"funk spec must be a table, got {spec!r}")
    unknown = set(spec) - set(FUNK_DEFAULTS)
    if unknown:
        key = sorted(unknown)[0]
        raise ValueError(f"unknown funk key(s) {sorted(unknown)}"
                         + _suggest(key, FUNK_DEFAULTS))
    out.update(spec)
    if out["backend"] not in FUNK_BACKENDS:
        raise ValueError(
            f"funk.backend must be one of {FUNK_BACKENDS}, got "
            f"{out['backend']!r}" + _suggest(out["backend"],
                                             FUNK_BACKENDS))
    for key in ("rec_max", "txn_max"):
        out[key] = int(out[key])
        if out[key] < 16:
            raise ValueError(f"funk.{key} must be >= 16, got {out[key]}")
    out["heap_mb"] = int(out["heap_mb"])
    if out["heap_mb"] < 1:
        raise ValueError(
            f"funk.heap_mb must be >= 1, got {out['heap_mb']}")
    return out


def encode_value(val) -> bytes:
    """Typed funk value -> tag-framed bytes (the store's wire form)."""
    from ..svm.accdb import Account
    if isinstance(val, bool):             # bool is an int; don't alias
        return bytes([_TAG_PICKLE]) + pickle.dumps(val)
    if isinstance(val, int) and 0 <= val < (1 << 64):
        return bytes([_TAG_INT]) + struct.pack("<Q", val)
    if isinstance(val, Account):
        data = bytes(val.data)
        return (bytes([_TAG_ACCT])
                + _ACCT_HDR.pack(val.lamports, bytes(val.owner),
                                 1 if val.executable else 0,
                                 val.rent_epoch)
                + data)
    return bytes([_TAG_PICKLE]) + pickle.dumps(val)


def decode_value(buf: bytes):
    from ..svm.accdb import Account
    tag = buf[0]
    if tag == _TAG_INT:
        return struct.unpack_from("<Q", buf, 1)[0]
    if tag == _TAG_ACCT:
        lam, owner, ex, rent = _ACCT_HDR.unpack_from(buf, 1)
        return Account(lamports=lam, data=buf[1 + _ACCT_HDR.size:],
                       owner=owner, executable=bool(ex), rent_epoch=rent)
    return pickle.loads(buf[1:])


class ShmFunk:
    """Funk-API facade over a shm store region.

    Standalone mode (no wksp): creates a private workspace sized to the
    store footprint — the conformance/bank-hash suites and any single
    process wanting crash-consistent account state. Attach mode (wksp +
    off): joins a region carved by topo.build (plan["funk"]), sharing
    the tree with the resolv/exec tile family.
    """

    def __init__(self, wksp=None, off: int | None = None,
                 rec_max: int = 4096, txn_max: int = 256,
                 heap_sz: int = 1 << 24, name: str | None = None):
        from ..runtime import Store, Workspace
        self._own_wksp = None
        if wksp is None:
            fp = Store.footprint(rec_max, txn_max, heap_sz)
            name = name or f"/fdtpu_funk_{os.getpid()}_{id(self):x}"
            wksp = Workspace(name, fp + 4096)
            self._own_wksp = wksp
        self.wksp = wksp
        self.raw = Store(wksp, off=off, rec_max=rec_max,
                         txn_max=txn_max, heap_sz=heap_sz)
        self.off = self.raw.off
        # hashable xid <-> u64 wire xid interning (per-process; the
        # store itself only ever sees the u64s)
        self._xid_to_u64: dict = {}
        self._u64_to_xid: dict = {}
        self._next_xid = 1
        self.last_publish = None

    # -- lifecycle ----------------------------------------------------------

    def close(self, unlink: bool = False):
        if self._own_wksp is not None:
            name = self._own_wksp.name
            self._own_wksp.close()
            if unlink:
                self._own_wksp.unlink()
            self._own_wksp = None

    def __del__(self):                    # best-effort shm hygiene
        try:
            self.close(unlink=True)
        except Exception:                 # noqa: BLE001 — teardown race
            pass

    # -- xid interning -------------------------------------------------------

    def intern_xid(self, xid) -> int:
        """Hashable xid -> wire u64 (0 for None/root). The u64 is what
        crosses rings to the exec tiles."""
        if xid is None:
            return 0
        u = self._xid_to_u64.get(xid)
        if u is None:
            u = self._next_xid
            self._next_xid += 1
            self._xid_to_u64[xid] = u
            self._u64_to_xid[u] = xid
        return u

    def _lookup(self, xid) -> int:
        """Like intern_xid but for paths that must NOT create: unknown
        xids raise the funk error contract."""
        if xid is None:
            return 0
        u = self._xid_to_u64.get(xid)
        if u is None or not self.raw.txn_exists(u):
            raise FunkTxnError(f"unknown txn {xid!r}")
        return u

    def _forget(self, u64: int):
        xid = self._u64_to_xid.pop(u64, None)
        if xid is not None:
            self._xid_to_u64.pop(xid, None)

    def _gc_interned(self):
        """Drop intern entries whose store txn is gone (publish/cancel
        retire whole subtrees store-side)."""
        for u in [u for u in self._u64_to_xid
                  if not self.raw.txn_exists(u)]:
            self._forget(u)

    # -- transaction tree ----------------------------------------------------

    def txn_prepare(self, parent_xid, xid):
        if xid is None:
            raise FunkTxnError(f"xid {xid!r} already in preparation")
        pu = self._lookup(parent_xid) if parent_xid is not None else 0
        if xid in self._xid_to_u64 \
                and self.raw.txn_exists(self._xid_to_u64[xid]):
            raise FunkTxnError(f"xid {xid!r} already in preparation")
        u = self.intern_xid(xid)
        rc = self.raw.txn_prepare(pu, u)
        if rc == -1:
            raise FunkTxnError(f"xid {xid!r} already in preparation")
        if rc == -2:
            raise FunkTxnError(f"unknown parent {parent_xid!r}")
        if rc == -3:
            raise FunkTxnError("fork depth limit")
        if rc != 0:
            raise FunkTxnError(f"store txn table full (rc {rc})")
        return xid

    def txn_cancel(self, xid):
        u = self._lookup(xid)
        self.raw.txn_cancel(u)
        self._gc_interned()

    def txn_publish(self, xid):
        u = self._lookup(xid)
        rc = self.raw.txn_publish(u)
        if rc != 0:
            raise FunkTxnError(f"publish failed (rc {rc})")
        self._gc_interned()
        self.last_publish = xid

    def txn_is_prepared(self, xid) -> bool:
        u = self._xid_to_u64.get(xid)
        return u is not None and self.raw.txn_exists(u)

    def txn_children(self, xid) -> list:
        u = 0 if xid is None else self._lookup(xid)
        kids = self.raw.txn_children(u)
        # children prepared by OTHER processes have no local intern
        # entry; surface the raw u64 (the wire identity) for them
        return [self._u64_to_xid.get(k, k) for k in kids]

    # -- records -------------------------------------------------------------

    def rec_write(self, xid, key: bytes, val):
        u = 0 if xid is None else self._lookup(xid)
        rc = self.raw.put(u, bytes(key), encode_value(val))
        if rc != 0:
            raise MemoryError(f"shm funk store full (rc {rc}): raise "
                              f"[funk] rec_max/heap_mb")

    def rec_remove(self, xid, key: bytes):
        u = 0 if xid is None else self._lookup(xid)
        rc = self.raw.put(u, bytes(key), None)
        if rc != 0:
            raise MemoryError(f"shm funk store full (rc {rc})")

    def rec_query(self, xid, key: bytes):
        u = 0 if xid is None else self._lookup(xid)
        buf = self.raw.get(u, bytes(key))
        return None if buf is None else decode_value(buf)

    def root_items(self) -> dict:
        return {k: decode_value(v)
                for k, v in self.raw.iter_layer(0) if v is not None}

    def txn_recs(self, xid) -> dict:
        u = self._lookup(xid)
        return {k: (None if v is None else decode_value(v))
                for k, v in self.raw.iter_layer(u)}

    def items_at(self, xid) -> dict:
        out = {k: decode_value(v)
               for k, v in self.raw.iter_layer(0) if v is not None}
        if xid is None:
            return out
        chain = []
        u = self._lookup(xid)
        depth = 0
        while u:
            chain.append(u)
            u = max(self.raw.txn_parent(u), 0)
            depth += 1
            if depth > MAX_FORK_DEPTH:
                break
        for layer in reversed(chain):        # oldest ancestor first
            for k, v in self.raw.iter_layer(layer):
                if v is None:
                    out.pop(k, None)
                else:
                    out[k] = decode_value(v)
        return out


class WireFunk:
    """Funk-API facade over a JOINED store region where xids ARE the
    wire u64s (no per-process interning) — the resolv/exec tile view.

    The bank owns the fork lifecycle: it prepares the wave fork,
    broadcasts the u64 xid in the dispatch frames, and publishes after
    every exec tile reported completion. Exec tiles therefore see an
    ALREADY-prepared fork: txn_prepare here is idempotent (an existing
    xid is a no-op), so the WaveExecutor's stage->dispatch->finalize
    seam runs unchanged on either side of the ring. Conflict groups
    are account-disjoint across tiles, so concurrent rec_writes into
    the same fork layer never touch the same key; the store's one
    dead-owner-stealing lock serializes the map surgery itself."""

    def __init__(self, wksp, off: int, rec_max: int = 4096,
                 txn_max: int = 256, heap_sz: int = 1 << 24):
        from ..runtime import Store
        self.wksp = wksp
        self.raw = Store(wksp, off=off, rec_max=rec_max,
                         txn_max=txn_max, heap_sz=heap_sz)
        self.off = off
        self.last_publish = None

    @classmethod
    def from_plan(cls, wksp, plan_funk: dict) -> "WireFunk":
        """Join the store topo.build carved (plan["funk"])."""
        return cls(wksp, off=plan_funk["off"],
                   rec_max=plan_funk["rec_max"],
                   txn_max=plan_funk["txn_max"],
                   heap_sz=plan_funk["heap_sz"])

    @staticmethod
    def _u(xid) -> int:
        if xid is None:
            return 0
        return int(xid)

    def txn_prepare(self, parent_xid, xid):
        u = self._u(xid)
        if u == 0:
            raise FunkTxnError(f"xid {xid!r} already in preparation")
        if self.raw.txn_exists(u):
            return xid                 # bank prepared it: idempotent
        rc = self.raw.txn_prepare(self._u(parent_xid), u)
        if rc == -1:
            raise FunkTxnError(f"xid {xid!r} already in preparation")
        if rc == -2:
            raise FunkTxnError(f"unknown parent {parent_xid!r}")
        if rc == -3:
            raise FunkTxnError("fork depth limit")
        if rc != 0:
            raise FunkTxnError(f"store txn table full (rc {rc})")
        return xid

    def txn_cancel(self, xid):
        u = self._u(xid)
        if u == 0 or not self.raw.txn_exists(u):
            raise FunkTxnError(f"unknown txn {xid!r}")
        self.raw.txn_cancel(u)

    def txn_publish(self, xid):
        u = self._u(xid)
        if u == 0 or not self.raw.txn_exists(u):
            raise FunkTxnError(f"unknown txn {xid!r}")
        rc = self.raw.txn_publish(u)
        if rc != 0:
            raise FunkTxnError(f"publish failed (rc {rc})")
        self.last_publish = xid

    def txn_is_prepared(self, xid) -> bool:
        u = self._u(xid)
        return u != 0 and self.raw.txn_exists(u)

    def rec_write(self, xid, key: bytes, val):
        rc = self.raw.put(self._u(xid), bytes(key), encode_value(val))
        if rc != 0:
            raise MemoryError(f"shm funk store full (rc {rc}): raise "
                              f"[funk] rec_max/heap_mb")

    def rec_remove(self, xid, key: bytes):
        rc = self.raw.put(self._u(xid), bytes(key), None)
        if rc != 0:
            raise MemoryError(f"shm funk store full (rc {rc})")

    def rec_query(self, xid, key: bytes):
        buf = self.raw.get(self._u(xid), bytes(key))
        return None if buf is None else decode_value(buf)

    def root_items(self) -> dict:
        return {k: decode_value(v)
                for k, v in self.raw.iter_layer(0) if v is not None}

    def txn_recs(self, xid) -> dict:
        """The fork layer's own records (deletes as None) — what the
        bank-hash delta scan (flamenco/bank_hash.apply_txn_delta) walks
        before publish; the replay scheduler hashes every slot through
        this exact seam."""
        u = self._u(xid)
        if u == 0 or not self.raw.txn_exists(u):
            raise FunkTxnError(f"unknown txn {xid!r}")
        return {k: (None if v is None else decode_value(v))
                for k, v in self.raw.iter_layer(u)}


def make_funk(cfg: dict | None = None, wksp=None, off: int | None = None):
    """[funk] config -> a funk instance of the configured backend. The
    topology path passes (wksp, off) from plan["funk"]; standalone
    callers get a private segment."""
    cfg = normalize_funk(cfg)
    if cfg["backend"] == "process":
        from .funk import Funk
        return Funk()
    return ShmFunk(wksp=wksp, off=off, rec_max=cfg["rec_max"],
                   txn_max=cfg["txn_max"],
                   heap_sz=cfg["heap_mb"] << 20)
