"""Fork-aware KV store with an in-preparation transaction tree.

Semantics contract (ref: src/funk/fd_funk.h:28-90):

* The "root" holds the last-published state. In-preparation
  transactions form a tree whose root children fork off the published
  state; a transaction's uncommitted updates shadow its ancestors'
  (ref: fd_funk.h "queries ... will observe the transaction's updates,
  its ancestors' updates and the last published state").
* prepare(parent, xid): add a leaf-or-branch child. Forks: a parent may
  have many children (competing forks of that parent).
* cancel(xid): discard the transaction AND all its descendants
  (ref: fd_funk_txn_cancel — cancels the whole subtree).
* publish(xid): make the transaction permanent. All its ancestors are
  published first (their updates fold into root in order), and every
  competing transaction (anything not descended from the published one)
  is cancelled (ref: fd_funk_txn_publish — "first publishes its
  ancestors and cancels any competing transaction histories").
* Record deletion is a tombstone so a child's remove shadows an
  ancestor's value (ref: fd_funk_rec REMOVE semantics).

The reference backs this with relocatable shared-memory maps for O(1)
query regardless of fork depth; here queries walk the ancestor chain
(depth capped like the reference's FD_FUNK_TXN_DEPTH_MAX-style limits,
accdb fork depth <= 128 — src/flamenco/accdb/) — the exec-path accdb
cache goes in front of this when the runtime lands.
"""
from __future__ import annotations

MAX_FORK_DEPTH = 128

KEY_WIDTH = 32

_TOMBSTONE = object()


def key32(key: bytes) -> bytes:
    """Width-normalizing gate for record keys headed into a write api.

    The native shm store ABI (and the reference's funk record map)
    reads EXACTLY 32 key bytes; a shorter python buffer gets hashed
    with per-process trailing garbage, so the record lands under a key
    no other tile can derive and the write is silently lost to the
    rest of the topology (the r17 follower-gate wedge). Route every
    key whose width is not structurally obvious through this helper —
    the short-key lint rule accepts it as proof."""
    if len(key) != KEY_WIDTH:
        raise ValueError(
            f"funk record keys are exactly {KEY_WIDTH} bytes, got "
            f"{len(key)}")
    return key


class FunkTxnError(RuntimeError):
    pass


class _Txn:
    __slots__ = ("xid", "parent", "children", "recs")

    def __init__(self, xid, parent):
        self.xid = xid
        self.parent = parent          # _Txn or None (child of root)
        self.children: list[_Txn] = []
        self.recs: dict[bytes, object] = {}


class Funk:
    """Single-writer fork tree. xids are any hashable (the reference
    uses 32-byte ids; slots work naturally)."""

    def __init__(self):
        self._root: dict[bytes, object] = {}
        self._txns: dict[object, _Txn] = {}
        self.last_publish = None       # xid of last published txn

    # -- transaction tree --------------------------------------------------

    def txn_prepare(self, parent_xid, xid):
        if xid in self._txns or xid is None:
            raise FunkTxnError(f"xid {xid!r} already in preparation")
        if parent_xid is None:
            parent = None
        else:
            parent = self._txns.get(parent_xid)
            if parent is None:
                raise FunkTxnError(f"unknown parent {parent_xid!r}")
        depth = 1
        p = parent
        while p is not None:
            depth += 1
            p = p.parent
        if depth > MAX_FORK_DEPTH:
            raise FunkTxnError("fork depth limit")
        t = _Txn(xid, parent)
        if parent is not None:
            parent.children.append(t)
        self._txns[xid] = t
        return xid

    def _drop_subtree(self, t: _Txn):
        for c in t.children:
            self._drop_subtree(c)
        del self._txns[t.xid]

    def txn_cancel(self, xid):
        """Cancel xid and all descendants (ref: fd_funk_txn_cancel)."""
        t = self._txns.get(xid)
        if t is None:
            raise FunkTxnError(f"unknown txn {xid!r}")
        if t.parent is not None:
            t.parent.children.remove(t)
        self._drop_subtree(t)

    def txn_publish(self, xid):
        """Publish xid (and its ancestors); cancel competing forks
        (ref: fd_funk_txn_publish)."""
        t = self._txns.get(xid)
        if t is None:
            raise FunkTxnError(f"unknown txn {xid!r}")
        # ancestor chain, oldest first
        chain = []
        p = t
        while p is not None:
            chain.append(p)
            p = p.parent
        chain.reverse()
        # fold into a COPY, then swap the reference: concurrent readers
        # (e.g. the bank tile's RPC thread) see either the old or the
        # new published state, never a half-applied fold — publish is
        # atomic for same-process readers (the reference gets this from
        # funk's lockfree record map)
        new_root = dict(self._root)
        for txn in chain:
            for k, v in txn.recs.items():
                if v is _TOMBSTONE:
                    new_root.pop(k, None)
                else:
                    new_root[k] = v
        self._root = new_root
        # survivors: the subtree rooted at t; everything else dies
        survivors = {}

        def keep(node: _Txn):
            survivors[node.xid] = node
            for c in node.children:
                keep(c)

        for c in t.children:
            keep(c)
        for c in t.children:
            c.parent = None
        self._txns = survivors
        self.last_publish = xid

    def txn_is_prepared(self, xid) -> bool:
        return xid in self._txns

    def txn_children(self, xid) -> list:
        if xid is None:
            return [t.xid for t in self._txns.values()
                    if t.parent is None]
        return [c.xid for c in self._txns[xid].children]

    # -- records -----------------------------------------------------------

    def rec_write(self, xid, key: bytes, val):
        """Write in the given in-preparation txn (xid=None writes the
        published root directly — the genesis/snapshot-load path)."""
        if xid is None:
            self._root[key] = val
            return
        t = self._txns.get(xid)
        if t is None:
            raise FunkTxnError(f"unknown txn {xid!r}")
        t.recs[key] = val

    def rec_remove(self, xid, key: bytes):
        if xid is None:
            self._root.pop(key, None)
            return
        t = self._txns.get(xid)
        if t is None:
            raise FunkTxnError(f"unknown txn {xid!r}")
        t.recs[key] = _TOMBSTONE

    def rec_query(self, xid, key: bytes):
        """Value visible at xid: own update, else nearest ancestor's,
        else published state; None if absent/removed
        (ref: fd_funk.h fork query semantics)."""
        if xid is not None:
            t = self._txns.get(xid)
            if t is None:
                raise FunkTxnError(f"unknown txn {xid!r}")
            while t is not None:
                if key in t.recs:
                    v = t.recs[key]
                    return None if v is _TOMBSTONE else v
                t = t.parent
        return self._root.get(key)

    def root_items(self):
        return dict(self._root)

    def txn_recs(self, xid) -> dict:
        """The in-preparation txn's OWN pending writes (no ancestor
        fold; tombstones surface as None) — the bank-hash delta scan."""
        t = self._txns.get(xid)
        if t is None:
            raise FunkTxnError(f"unknown txn {xid!r}")
        return {k: (None if v is _TOMBSTONE else v)
                for k, v in t.recs.items()}

    def items_at(self, xid) -> dict:
        """All records visible at xid: the same fork-overlay visibility
        rule as rec_query, folded over the whole keyspace (nearest
        ancestor wins, tombstones hide). The stake-aggregation /
        snapshot scan entrypoint."""
        out = dict(self._root)
        chain = []
        t = self._txns.get(xid) if xid is not None else None
        while t is not None:
            chain.append(t)
            t = t.parent
        for t in reversed(chain):        # oldest ancestor first
            for k, v in t.recs.items():
                if v is _TOMBSTONE:
                    out.pop(k, None)
                else:
                    out[k] = v
        return out
