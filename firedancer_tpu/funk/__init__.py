"""funk: fork-aware key-value store (prepare / publish / cancel).

Re-expression of the reference's funk database
(ref: src/funk/fd_funk.h:4-90 — record table + in-preparation
transaction tree; src/funk/fd_funk_txn.h — fork management APIs).
"""
from .funk import Funk, FunkTxnError, key32  # noqa: F401
from .shmfunk import (  # noqa: F401
    FUNK_DEFAULTS, ShmFunk, WireFunk, make_funk, normalize_funk,
)
