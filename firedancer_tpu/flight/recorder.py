"""fdflight recorder engine: drain the shm observability plane into
the archive, seal incident bundles around SLO breaches.

Reader-side only — the fdmetrics contract the metric tile pioneered:
every sample is a read of shm regions other tiles already maintain
(metric slots, link telemetry blocks, stem histograms, trace rings,
prof sample rings, the SLO engine's breach dumps), so the writer tiles
pay NOTHING for the archive's existence. The FlightAdapter
(disco/tiles.py) calls `maybe_drain()` from its housekeeping hook; the
`hz` cadence is enforced here, not by the stem.

Counters vs gauges: counter slots archive as DELTAS against the
previous sample (sum over a window == the /metrics counter delta over
the same window, exactly — the fdflight query-equivalence contract),
gauges archive as levels (aux bit 0 set). The first sample after boot
deltas against zero, so a whole-history sum equals the live counter.

Incidents: the recorder watches the metric tile's `slo_breaches`
counter and the per-target breach dumps (disco/slo.py slo_dump_path —
the same doorbell surface fdprof's breach_capture rides). A breach
opens a pending incident; after `incident_window_s` more seconds of
frames the bundle is sealed ATOMICALLY (tmp+rename) next to the
segments: the +/-window frame slice, the breached target's dump, the
saturating-hop attribution, any supervisor black boxes, and a
chrome-trace export of the trace rings — self-contained, so
`tools/fdflight --incident` can replay it after every tile (recorder
included) is SIGKILLed and the workspace is gone.
"""
from __future__ import annotations

import glob
import json
import os
from collections import deque

from ..utils.tempo import monotonic_ns
from . import effective_sources, normalize_flight
from .archive import (ArchiveWriter, saturating_hop, write_atomic_json)
from .codec import (FRAME_SZ, KIND_HIST, KIND_LINK, KIND_MARK,
                    KIND_METRIC, KIND_PROF, KIND_SLO, KIND_TRACE,
                    decode_frames)

# trace events worth archiving at full rate even when sampled: the
# lifecycle/fault vocabulary a post-mortem actually greps for (bulk
# wait/work/consume spans stay shm-only — the archive is history, not
# a second trace ring)
_TRACE_KEEP = ("boot", "halt", "fail", "chaos", "watchdog", "restart",
               "down", "slo", "cpu_fallback", "compile", "tune")


class FlightRecorder:
    def __init__(self, plan: dict, wksp, cfg: dict | None = None,
                 clock=monotonic_ns):
        self.plan, self.wksp = plan, wksp
        self.cfg = normalize_flight(cfg if cfg is not None
                                    else plan.get("flight"))
        self.clock = clock
        self.sources = effective_sources(self.cfg)
        self.node_id = self.cfg["node_id"]
        self.topology = plan.get("topology", "?")
        self.writer = ArchiveWriter(
            self.cfg["dir"], segment_mb=self.cfg["segment_mb"],
            retain_mb=self.cfg["retain_mb"], node_id=self.node_id)
        self._interval_ns = int(1e9 / self.cfg["hz"])
        self._window_ns = int(self.cfg["incident_window_s"] * 1e9)
        self._next_ns = 0
        self._last_metrics: dict[str, list[int]] = {}
        self._last_hists: dict[str, dict[str, int]] = {}
        self._last_links: dict[str, dict[str, int]] = {}
        self._trace_cursor: dict[str, int] = {}
        self._last_prof: dict[str, dict[str, int]] = {}
        self._slo_seen: dict[str, int] = {}     # target -> dumped_at_ns
        self._pending: list[dict] = []
        # in-memory tail for the incident pre-window: raw frame bytes,
        # pruned by timestamp (bounded by 2x window at the drain rate)
        self._tail: deque[tuple[int, bytes]] = deque()
        self.metrics = {"frames": 0, "drains": 0, "incidents": 0,
                        "segments": 0, "bytes": 0}
        ts = self.clock()
        self._emit(KIND_MARK, ts, self.topology, "boot", os.getpid())
        self.writer.flush()

    # -- frame plumbing -----------------------------------------------------

    def _emit(self, kind: int, ts: int, source: str, name: str,
              value: int, aux: int = 0):
        frame = self.writer.append(kind, ts, source, name, value, aux)
        if self._window_ns:
            self._tail.append((ts, frame))

    def _prune_tail(self, now: int):
        horizon = now - 2 * self._window_ns
        while self._tail and self._tail[0][0] < horizon:
            self._tail.popleft()

    # -- sample passes ------------------------------------------------------

    def _drain_metrics(self, ts: int):
        from ..disco.topo import read_metrics
        for tn, spec in self.plan["tiles"].items():
            names = spec.get("metrics_names") or []
            if not names:
                continue
            vals = read_metrics(self.wksp, self.plan, tn)
            gauges = set(spec.get("metrics_gauges")
                         or spec.get("gauges") or [])
            prev = self._last_metrics.get(tn)
            for i, nm in enumerate(names):
                v = int(vals[i])
                if nm in gauges:
                    if prev is None or int(prev[i]) != v:
                        self._emit(KIND_METRIC, ts, tn, nm, v, 1)
                else:
                    d = v - (int(prev[i]) if prev is not None else 0)
                    if d:
                        self._emit(KIND_METRIC, ts, tn, nm, d)
            self._last_metrics[tn] = vals
        self._drain_hists(ts)

    def _drain_hists(self, ts: int):
        from ..disco.metrics import quantile_ns, read_hists
        for tn in self.plan["tiles"]:
            hists = read_hists(self.wksp, self.plan, tn)
            if not hists:
                continue
            prev = self._last_hists.setdefault(tn, {})
            for hk, h in hists.items():
                d = int(h["sum_ns"]) - prev.get(hk, 0)
                if d:
                    self._emit(KIND_HIST, ts, tn, f"{hk}_sum_ns", d)
                prev[hk] = int(h["sum_ns"])
            work = hists.get("work")
            if work and work.get("count"):
                self._emit(KIND_HIST, ts, tn, "work_p99_ns",
                           int(quantile_ns(work, 0.99)), 1)

    def _drain_links(self, ts: int):
        from ..disco.metrics import (merge_hists, quantile_ns,
                                     read_link_metrics)
        for ln, rec in read_link_metrics(self.wksp, self.plan).items():
            prev = self._last_links.setdefault(ln, {})
            cons = rec.get("consumers") or {}
            cur = {
                "pub": int(rec.get("pub", 0)),
                "pub_bytes": int(rec.get("pub_bytes", 0)),
                "backpressure": int(rec.get("backpressure", 0)),
                "consumed": sum(int(c.get("consumed", 0))
                                for c in cons.values()),
                "overruns": sum(int(c.get("overruns", 0))
                                for c in cons.values()),
            }
            for nm, v in cur.items():
                d = v - prev.get(nm, 0)
                if d:
                    self._emit(KIND_LINK, ts, ln, nm, d)
                prev[nm] = v
            h = merge_hists(c["hist"] for c in cons.values()
                            if c.get("hist"))
            if h and h.get("count"):
                self._emit(KIND_LINK, ts, ln, "consume_p99_ns",
                           int(quantile_ns(h, 0.99)), 1)

    def _drain_trace(self, ts: int):
        from ..runtime.tango import TraceRing
        from ..trace.events import decode
        from ..trace.recorder import link_names
        lnames = link_names(self.plan)
        for tn, spec in self.plan["tiles"].items():
            off = spec.get("trace_off")
            if off is None:
                continue
            ring = TraceRing(self.wksp, off, int(spec["trace_depth"]))
            cur, recs, lost = ring.snapshot_since(
                self._trace_cursor.get(tn, 0))
            self._trace_cursor[tn] = cur
            for rec in recs:
                d = decode(rec, lnames)
                if d["ev"] not in _TRACE_KEEP:
                    continue
                aux = (d["etype"] & 0xFFFF) \
                    | (min(d["count"], 0xFFFF) << 16)
                self._emit(KIND_TRACE, d["ts"], tn, d["ev"],
                           d["arg"], aux)

    def _drain_prof(self, ts: int):
        from ..prof.export import read_folded
        try:
            folded = read_folded(self.plan, self.wksp)
        except Exception:
            return
        for tn, stacks in folded.items():
            prev = self._last_prof.setdefault(tn, {})
            for stack, states in stacks.items():
                total = sum(states.values())
                d = total - prev.get(stack, 0)
                if d:
                    leaf = stack.rsplit(";", 1)[-1]
                    self._emit(KIND_PROF, ts, tn, leaf, d)
                prev[stack] = total

    def _drain_slo(self, ts: int):
        from ..disco.slo import slo_dump_path
        targets = (self.plan.get("slo") or {}).get("target") or []
        for t in targets:
            name = t["name"]
            path = slo_dump_path(self.topology, name)
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            stamp = int(doc.get("dumped_at_ns", 0))
            if stamp <= self._slo_seen.get(name, 0):
                continue
            self._slo_seen[name] = stamp
            kind = doc.get("kind", "breach")
            value = max(0, int(doc.get("value") or 0))
            self._emit(KIND_SLO, ts, name, kind, value,
                       int(doc.get("breaches", 0)))
            if kind == "breach":
                self._open_incident(ts, name, value, doc)

    # -- incidents ----------------------------------------------------------

    def _open_incident(self, ts: int, target: str, value: int,
                       dump: dict):
        self._pending.append({"ts": ts, "target": target,
                              "value": value, "dump": dump})
        self.metrics["incidents"] += 1

    def _seal_ready(self, now: int, force: bool = False):
        still = []
        for inc in self._pending:
            if force or now >= inc["ts"] + self._window_ns:
                self._seal(inc, now)
            else:
                still.append(inc)
        self._pending = still

    def _seal(self, inc: dict, now: int):
        t0 = inc["ts"] - self._window_ns
        t1 = inc["ts"] + self._window_ns
        raw = b"".join(fr for ts, fr in self._tail if t0 <= ts <= t1)
        frames, _ = decode_frames(raw)
        doc = {
            "topology": self.topology,
            "node_id": self.node_id,
            "target": inc["target"],
            "value": inc["value"],
            "breach_ts_ns": inc["ts"],
            "sealed_at_ns": now,
            "window_ns": [t0, t1],
            "slo_dump": inc["dump"],
            "saturating_hop": saturating_hop(frames),
            "frames": frames,
            "blackboxes": self._blackboxes(),
            "chrome": self._chrome(),
        }
        path = os.path.join(self.writer.dir,
                            f"incident-{inc['ts']}.json")
        try:
            write_atomic_json(path, doc)
        except OSError:
            return
        from ..utils import log
        log.warning(f"flight: sealed incident bundle {path} "
                    f"(target {inc['target']!r})")

    def _blackboxes(self) -> list[dict]:
        out = []
        for path in sorted(glob.glob(
                f"/dev/shm/fdtpu_{self.topology}.blackbox.*.json")):
            try:
                with open(path) as f:
                    out.append(json.load(f))
            except (OSError, json.JSONDecodeError):
                continue
        return out

    def _chrome(self) -> dict | None:
        """Chrome-trace export of the live trace rings at seal time —
        embedded so the bundle exports to Perfetto with the shm long
        gone. None when the topology is untraced."""
        try:
            from ..trace import export as trace_export
            evs = trace_export.read_rings(self.plan, self.wksp)
            if not any(evs.values()):
                return None
            return trace_export.to_chrome(evs, self.topology)
        except Exception:
            return None

    # -- the housekeeping entry point --------------------------------------

    def maybe_drain(self) -> bool:
        """One rate-limited drain pass (the FlightAdapter housekeeping
        hook). Returns True when a pass ran."""
        now = self.clock()
        if now < self._next_ns:
            return False
        self._next_ns = now + self._interval_ns
        self.drain(now)
        return True

    def drain(self, now: int | None = None):
        now = self.clock() if now is None else now
        if "metrics" in self.sources:
            self._drain_metrics(now)
        if "links" in self.sources:
            self._drain_links(now)
        if "slo" in self.sources:
            self._drain_slo(now)
        if "trace" in self.sources:
            self._drain_trace(now)
        if "prof" in self.sources:
            self._drain_prof(now)
        self._seal_ready(now)
        self._prune_tail(now)
        self.writer.flush()
        self.metrics["drains"] += 1
        self.metrics["frames"] = self.writer.frames
        self.metrics["segments"] = self.writer.rotations + 1
        self.metrics["bytes"] = self.writer.bytes_written

    def close(self):
        """Final drain + halt mark + seal anything pending with the
        frames on hand (a truncated window beats a lost bundle)."""
        now = self.clock()
        self.drain(now)
        self._seal_ready(now, force=True)
        self._emit(KIND_MARK, now, self.topology, "halt", os.getpid())
        self.writer.close()
