"""fdflight CLI: query the durable flight-data archive post-mortem.

    python -m firedancer_tpu.flight DIR                 # archive summary
        [--since NS] [--until NS]      time-range slice (monotonic_ns)
        [--kind metric|hist|link|slo|trace|prof|mark]   (repeatable)
        [--ndjson | --csv]             dump the sliced frames
        [--series SOURCE.NAME]         one (tile|link, metric) series
        [--cumulative]                 re-integrate counter deltas
        [--incident [PATH|TS]]         list bundles / pick one
        [--out FILE]                   with --incident: export the
                                       bundle's embedded chrome trace
        diff A_T0:A_T1 B_T0:B_T1       window-summary diff (the fdbench
                                       shape over runtime history)

Unlike fdtrace/fdprof this never attaches shm: the archive directory
IS the data source, so every query works after every tile (recorder
included) is SIGKILLed and the workspace is unlinked — the whole point
of the archive.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from .archive import (incident_paths, read_frames, series, cumulative,
                      sources_index, window_summary)
from .codec import KIND_NAMES


def _kind_ids(names) -> set | None:
    if not names:
        return None
    by_name = {v: k for k, v in KIND_NAMES.items()}
    out = set()
    for n in names:
        if n not in by_name:
            raise SystemExit(f"fdflight: unknown kind {n!r} "
                             f"(one of {sorted(by_name)})")
        out.add(by_name[n])
    return out


def _summary(dirname: str, frames, dropped: int) -> str:
    idx = sources_index(frames)
    lines = [f"archive {dirname}"]
    if frames:
        t0, t1 = frames[0]["ts"], frames[-1]["ts"]
        lines.append(f"  {len(frames)} frames over "
                     f"{(t1 - t0) / 1e9:.1f}s "
                     f"[{t0} .. {t1}], {dropped} torn/dropped")
    else:
        lines.append(f"  0 frames, {dropped} torn/dropped")
    nodes = sorted({fr["node"] for fr in frames})
    if nodes:
        lines.append(f"  nodes: {nodes}")
    for kind in sorted(idx):
        pairs = idx[kind]
        sample = ", ".join(f"{s}.{n}" for s, n in sorted(pairs)[:4])
        more = f" (+{len(pairs) - 4} more)" if len(pairs) > 4 else ""
        lines.append(f"  {kind:<7} {len(pairs)} series: {sample}{more}")
    incs = incident_paths(dirname)
    lines.append(f"  incidents: {len(incs)}")
    return "\n".join(lines) + "\n"


def _dump_ndjson(frames, out):
    for fr in frames:
        out.write(json.dumps(fr) + "\n")


def _dump_csv(frames, out):
    out.write("ts_ns,node,kind,source,name,value,aux\n")
    for fr in frames:
        out.write(f"{fr['ts']},{fr['node']},{fr['kind_name']},"
                  f"{fr['source']},{fr['name']},{fr['value']},"
                  f"{fr['aux']}\n")


def _pick_incident(dirname: str, sel: str | None) -> str | None:
    incs = incident_paths(dirname)
    if sel is None or sel == "list":
        return None
    if os.path.exists(sel):
        return sel
    hits = [p for p in incs if sel in os.path.basename(p)]
    if len(hits) != 1:
        raise SystemExit(f"fdflight: incident {sel!r} matches "
                         f"{len(hits)} bundles (have "
                         f"{[os.path.basename(p) for p in incs]})")
    return hits[0]


def _incident_line(path: str) -> str:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return f"  {os.path.basename(path)}  UNREADABLE ({e})"
    return (f"  {os.path.basename(path)}  target={doc.get('target')!r} "
            f"value={doc.get('value')} "
            f"hop={doc.get('saturating_hop')!r} "
            f"frames={len(doc.get('frames') or [])} "
            f"chrome={'yes' if doc.get('chrome') else 'no'}")


def _diff(dirname: str, wa: str, wb: str, kinds) -> str:
    """window_summary(A) vs window_summary(B), fdbench-diff style:
    per-series rate deltas, worst regressions first."""
    def parse(w):
        try:
            lo, hi = w.split(":", 1)
            return int(lo), int(hi)
        except ValueError:
            raise SystemExit(f"fdflight: bad window {w!r} "
                             "(want T0_NS:T1_NS)")
    (a0, a1), (b0, b1) = parse(wa), parse(wb)
    fa, _ = read_frames(dirname, a0, a1, kinds)
    fb, _ = read_frames(dirname, b0, b1, kinds)
    sa, sb = window_summary(fa), window_summary(fb)
    keys = sorted(set(sa["metrics"]) | set(sb["metrics"]))
    rows = []
    for k in keys:
        ra = (sa["metrics"].get(k) or {}).get("rate", 0.0)
        rb = (sb["metrics"].get(k) or {}).get("rate", 0.0)
        if not ra and not rb:
            continue
        pct = 100.0 * (rb - ra) / ra if ra else float("inf")
        rows.append((pct, k, ra, rb))
    rows.sort(key=lambda r: r[0])
    lines = [f"A [{a0}:{a1}] {sa['wall_s']}s vs "
             f"B [{b0}:{b1}] {sb['wall_s']}s  (rates /s)"]
    for pct, k, ra, rb in rows:
        tag = "+inf%" if pct == float("inf") else f"{pct:+8.1f}%"
        lines.append(f"  {k:<40} {ra:>12.1f} -> {rb:>12.1f}  {tag}")
    if not rows:
        lines.append("  (no overlapping series)")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fdflight",
        description="query a durable flight-data archive (post-mortem "
                    "safe: reads only the [flight] directory)")
    ap.add_argument("dir", help="archive directory ([flight].dir)")
    ap.add_argument("cmd", nargs="*", default=[],
                    help="optional: diff A_T0:A_T1 B_T0:B_T1")
    ap.add_argument("--since", type=int, default=None,
                    help="slice start (monotonic ns)")
    ap.add_argument("--until", type=int, default=None,
                    help="slice end (monotonic ns)")
    ap.add_argument("--kind", action="append", default=None,
                    help=f"frame kind filter, one of "
                         f"{sorted(KIND_NAMES.values())} (repeatable)")
    ap.add_argument("--ndjson", action="store_true",
                    help="dump sliced frames as NDJSON")
    ap.add_argument("--csv", action="store_true",
                    help="dump sliced frames as CSV")
    ap.add_argument("--series", default=None, metavar="SOURCE.NAME",
                    help="extract one series as '<ts> <value>' lines")
    ap.add_argument("--cumulative", action="store_true",
                    help="with --series: re-integrate counter deltas")
    ap.add_argument("--incident", nargs="?", const="list", default=None,
                    metavar="PATH|SUBSTR",
                    help="list incident bundles, or select one by "
                         "path / name substring")
    ap.add_argument("--out", default=None,
                    help="with --incident: write the bundle's chrome "
                         "trace JSON here (ui.perfetto.dev)")
    args = ap.parse_args(argv)

    kinds = _kind_ids(args.kind)

    if args.cmd:
        if args.cmd[0] != "diff" or len(args.cmd) != 3:
            raise SystemExit("fdflight: trailing command must be "
                             "'diff A_T0:A_T1 B_T0:B_T1'")
        sys.stdout.write(_diff(args.dir, args.cmd[1], args.cmd[2],
                               kinds))
        return 0

    if args.incident is not None:
        picked = _pick_incident(args.dir, args.incident)
        if picked is None:
            incs = incident_paths(args.dir)
            print(f"{len(incs)} incident bundle(s) in {args.dir}")
            for p in incs:
                print(_incident_line(p))
            return 0
        with open(picked) as f:
            doc = json.load(f)
        print(_incident_line(picked))
        if args.out:
            chrome = doc.get("chrome")
            if not chrome:
                print("fdflight: bundle has no embedded chrome trace "
                      "(topology untraced at seal time)",
                      file=sys.stderr)
                return 1
            with open(args.out, "w") as f:
                json.dump(chrome, f)
            print(f"wrote {args.out} "
                  f"({len(chrome.get('traceEvents', []))} events) — "
                  f"open at ui.perfetto.dev")
        return 0

    frames, dropped = read_frames(args.dir, args.since, args.until,
                                  kinds)
    if args.series:
        if "." not in args.series:
            raise SystemExit("fdflight: --series wants SOURCE.NAME")
        src, name = args.series.split(".", 1)
        pts = series(frames, src, name)
        if args.cumulative:
            pts = cumulative(pts)
        for ts, v in pts:
            print(f"{ts} {v}")
        return 0
    if args.ndjson:
        _dump_ndjson(frames, sys.stdout)
        return 0
    if args.csv:
        _dump_csv(frames, sys.stdout)
        return 0
    sys.stdout.write(_summary(args.dir, frames, dropped))
    return 0
