"""fdflight archive: segmented append-only frame storage + queries.

Write side (the recorder tile): `ArchiveWriter` appends fixed-width
frames (flight/codec.py) to the active `seg-<ts>.fdf` under the
[flight] dir, rotates at `segment_mb`, and ages out the oldest
segments once the directory exceeds `retain_mb` — the retention
budget, so the archive is bounded by construction and accumulates
ACROSS runs (each boot opens a fresh segment; KIND_MARK frames record
the seams). No fsync on the hot path: the torn-tail codec makes a
crash lose at most the tail page, never the archive.

Read side (fdflight / monitor --archive / fdgui history): plain
functions over the directory — every read re-validates per-frame
magic+CRC, so a segment a SIGKILL truncated mid-frame loads minus its
torn tail with an explicit dropped count.
"""
from __future__ import annotations

import json
import os

from .codec import FRAME_SZ, decode_frames, encode_frame

SEG_PREFIX = "seg-"
SEG_SUFFIX = ".fdf"
INCIDENT_PREFIX = "incident-"


def _segments(dirname: str) -> list[str]:
    """Segment paths, oldest first (names embed the open timestamp)."""
    try:
        names = os.listdir(dirname)
    except OSError:
        return []
    return [os.path.join(dirname, n) for n in sorted(names)
            if n.startswith(SEG_PREFIX) and n.endswith(SEG_SUFFIX)]


def incident_paths(dirname: str) -> list[str]:
    try:
        names = os.listdir(dirname)
    except OSError:
        return []
    return [os.path.join(dirname, n) for n in sorted(names)
            if n.startswith(INCIDENT_PREFIX) and n.endswith(".json")]


class ArchiveWriter:
    """Single-writer segment appender (the recorder tile owns the
    directory the way a tile owns its trace ring — one writer, any
    number of readers)."""

    def __init__(self, dirname: str, segment_mb: float = 8.0,
                 retain_mb: float = 64.0, node_id: int = 0):
        self.dir = dirname
        self.segment_bytes = max(FRAME_SZ, int(segment_mb * (1 << 20)))
        self.retain_bytes = max(self.segment_bytes,
                                int(retain_mb * (1 << 20)))
        self.node_id = int(node_id)
        self.frames = 0
        self.rotations = 0
        self.aged_out = 0
        self.bytes_written = 0
        os.makedirs(dirname, exist_ok=True)
        self._f = None
        self._size = 0

    def _open_segment(self, ts_ns: int):
        # the open timestamp names the segment; a pid tiebreak keeps a
        # same-ns reopen (restart storms) from clobbering history
        name = f"{SEG_PREFIX}{ts_ns:020d}-{os.getpid()}{SEG_SUFFIX}"
        self._f = open(os.path.join(self.dir, name), "ab")
        self._size = self._f.tell()

    def append(self, kind: int, ts_ns: int, source: str, name: str,
               value: int, aux: int = 0) -> bytes:
        """Append one frame; returns its encoded bytes (the recorder's
        in-memory incident tail reuses them)."""
        frame = encode_frame(kind, ts_ns, self.node_id, source, name,
                             value, aux)
        if self._f is None or self._size + FRAME_SZ > self.segment_bytes:
            self._rotate(ts_ns)
        self._f.write(frame)
        self._size += FRAME_SZ
        self.frames += 1
        self.bytes_written += FRAME_SZ
        return frame

    def _rotate(self, ts_ns: int):
        if self._f is not None:
            self._f.close()
            self.rotations += 1
        self._open_segment(ts_ns)
        self._enforce_retention()

    def _enforce_retention(self):
        segs = _segments(self.dir)
        cur = os.path.abspath(self._f.name) if self._f else None
        sizes = {}
        for p in segs:
            try:
                sizes[p] = os.path.getsize(p)
            except OSError:
                sizes[p] = 0
        total = sum(sizes.values())
        for p in segs:
            if total <= self.retain_bytes:
                break
            if os.path.abspath(p) == cur:
                break           # never delete the active segment
            try:
                os.unlink(p)
            except OSError:
                continue
            total -= sizes[p]
            self.aged_out += 1

    def flush(self):
        if self._f is not None:
            self._f.flush()

    def close(self):
        if self._f is not None:
            self._f.flush()
            self._f.close()
            self._f = None


def write_atomic_json(path: str, doc: dict):
    """tmp + rename in the archive directory: the incident-bundle seal
    (and anything else durable next to the segments) either fully
    exists or does not — the utils/checkpt snapshot discipline."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# read side
# ---------------------------------------------------------------------------

def read_frames(dirname: str, since_ns: int | None = None,
                until_ns: int | None = None,
                kinds=None) -> tuple[list[dict], int]:
    """All archive frames in [since_ns, until_ns], oldest-first, plus
    the total torn/dropped slot count across segments. `kinds` filters
    by codec kind id set."""
    out: list[dict] = []
    dropped = 0
    for path in _segments(dirname):
        try:
            with open(path, "rb") as f:
                frames, d = decode_frames(f.read())
        except OSError:
            continue
        dropped += d
        for fr in frames:
            if since_ns is not None and fr["ts"] < since_ns:
                continue
            if until_ns is not None and fr["ts"] > until_ns:
                continue
            if kinds is not None and fr["kind"] not in kinds:
                continue
            out.append(fr)
    out.sort(key=lambda fr: fr["ts"])
    return out, dropped


def series(frames: list[dict], source: str,
           name: str) -> list[tuple[int, int]]:
    """[(ts_ns, value)] for one (source, name) series, oldest-first.
    Counter frames carry deltas; `cumulative` below re-integrates."""
    return [(fr["ts"], fr["value"]) for fr in frames
            if fr["source"] == source and fr["name"] == name]


def cumulative(points: list[tuple[int, int]]) -> list[tuple[int, int]]:
    out, total = [], 0
    for ts, v in points:
        total += v
        out.append((ts, total))
    return out


def sources_index(frames: list[dict]) -> dict[str, set]:
    """{kind_name: {(source, name)}} — what the archive holds."""
    out: dict[str, set] = {}
    for fr in frames:
        out.setdefault(fr["kind_name"], set()).add(
            (fr["source"], fr["name"]))
    return out


def window_summary(frames: list[dict]) -> dict:
    """One window's roll-up: per-tile metric totals + rates, per-link
    counter totals — the operand of `fdflight diff` (the fdbench
    diff shape pointed at runtime history instead of BENCH jsons)."""
    from .codec import KIND_HIST, KIND_LINK, KIND_METRIC
    t0 = frames[0]["ts"] if frames else 0
    t1 = frames[-1]["ts"] if frames else 0
    wall_s = max(1e-9, (t1 - t0) / 1e9)
    metrics: dict[str, dict] = {}
    links: dict[str, dict] = {}
    for fr in frames:
        if fr["kind"] == KIND_METRIC:
            key = f"{fr['source']}.{fr['name']}"
            rec = metrics.setdefault(key, {"total": 0, "gauge": None})
            if fr["aux"] & 1:
                rec["gauge"] = fr["value"]     # level: last sample wins
            else:
                rec["total"] += fr["value"]
        elif fr["kind"] == KIND_LINK:
            rec = links.setdefault(fr["source"], {})
            if fr["aux"] & 1:
                rec[fr["name"]] = fr["value"]
            else:
                rec[fr["name"]] = rec.get(fr["name"], 0) + fr["value"]
        elif fr["kind"] == KIND_HIST and (fr["aux"] & 1):
            key = f"{fr['source']}.{fr['name']}"
            metrics.setdefault(key, {"total": 0, "gauge": None})[
                "gauge"] = fr["value"]
    for rec in metrics.values():
        rec["rate"] = round(rec["total"] / wall_s, 3)
    return {"t0_ns": t0, "t1_ns": t1, "wall_s": round(wall_s, 3),
            "metrics": metrics, "links": links}


def saturating_hop(frames: list[dict]) -> str | None:
    """The link taking the most backpressure ticks inside a window —
    the fdgui graph's saturating-hop attribution, recomputed from the
    archive (incident bundles pin it at seal time)."""
    from .codec import KIND_LINK
    bp: dict[str, int] = {}
    for fr in frames:
        if fr["kind"] == KIND_LINK and fr["name"] == "backpressure":
            bp[fr["source"]] = bp.get(fr["source"], 0) + fr["value"]
    live = {ln: v for ln, v in bp.items() if v > 0}
    return max(live, key=live.get) if live else None
