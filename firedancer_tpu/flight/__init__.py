"""fdflight: the durable flight-data archive (r19).

Every observability surface this repo grew — fdmetrics counters,
fdtrace rings, fdprof samples, the SLO engine's breach deque — is
shared-memory-resident with overwrite-oldest semantics, exactly like
the reference validator's, and therefore answers "what is happening"
but never "what happened 30 seconds ago" once the rings wrap or the
workspace is unlinked. This package is the missing primitive under
ROADMAP items 3 (cluster judge needs node-tagged telemetry) and 5
(offline autotuning needs per-host history): a bounded, append-only,
crash-tolerant on-disk archive of the shm observability plane, drained
by a reader-side recorder tile (disco/tiles.py FlightAdapter — the
fdmetrics contract: zero writer-side cost) and queried post-mortem by
`tools/fdflight`, `monitor --json --archive`, and the fdgui history
panel.

Config — the `[flight]` topology section, validated by the standard
triple (config load here, topo.build, fdlint's bad-flight rule with
the registry mirror in lint/registry.py FLIGHT_SECTION_KEYS):

    [flight]
    dir       = "/tmp/fdtpu-flight/fdtpu"   # archive directory
    segment_mb = 8.0       # rotate the active segment at this size
    retain_mb  = 64.0      # age out oldest segments beyond this total
    hz         = 4.0       # recorder drain cadence
    sources    = ["metrics", "links", "slo", "trace", "prof"]
    incident_window_s = 5.0   # +/- bundle window around an SLO breach
    node_id    = 0         # stamped into every frame (cluster merge)

On-disk format: fixed-width 64-byte binary frames (flight/codec.py —
monotonic_ns | node_id | kind | source | name | value), segments named
`seg-*.fdf` under `dir` (flight/archive.py), incident bundles sealed
atomically next to them (flight/recorder.py). Torn tail frames from a
SIGKILL mid-write are detected by per-frame magic+CRC and dropped on
read, never propagated.
"""
from __future__ import annotations

FLIGHT_DEFAULTS = {
    "dir": "/tmp/fdtpu-flight/default",
    "segment_mb": 8.0,
    "retain_mb": 64.0,
    "hz": 4.0,
    "sources": None,        # None = every source family
    "incident_window_s": 5.0,
    "node_id": 0,
}

# the frame-source families the recorder can drain (codec kinds map
# onto these; `sources` selects a subset)
FLIGHT_SOURCES = ("metrics", "links", "slo", "trace", "prof")


def _suggest(key: str, candidates) -> str:
    # the ONE did-you-mean helper (lint/registry.py); lazy so the
    # recorder hot path never pays the lint import
    from ..lint.registry import suggest
    return suggest(key, candidates)


def normalize_flight(spec) -> dict:
    """Validate + default-fill a `[flight]` table. Returns a plain
    JSON-able dict; raises ValueError with a did-you-mean on typos —
    the same fail-before-launch stance as normalize_trace."""
    out = dict(FLIGHT_DEFAULTS)
    if spec is None:
        return out
    if not isinstance(spec, dict):
        raise ValueError(f"flight spec must be a table, got {spec!r}")
    unknown = set(spec) - set(FLIGHT_DEFAULTS)
    if unknown:
        key = sorted(unknown)[0]
        raise ValueError(f"unknown flight key(s) {sorted(unknown)}"
                         + _suggest(key, FLIGHT_DEFAULTS))
    out.update(spec)
    d = out["dir"]
    if not isinstance(d, str) or not d:
        raise ValueError(f"flight.dir must be a non-empty path, got {d!r}")
    seg = out["segment_mb"] = float(out["segment_mb"])
    if seg <= 0:
        raise ValueError(f"flight.segment_mb must be > 0, got {seg}")
    ret = out["retain_mb"] = float(out["retain_mb"])
    if ret < seg:
        raise ValueError(f"flight.retain_mb ({ret}) must be >= "
                         f"segment_mb ({seg}) — retention below one "
                         f"segment keeps no history at all")
    hz = out["hz"] = float(out["hz"])
    if not 0 < hz <= 1000:
        raise ValueError(f"flight.hz must be in (0, 1000], got {hz}")
    win = out["incident_window_s"] = float(out["incident_window_s"])
    if win < 0:
        raise ValueError(
            f"flight.incident_window_s must be >= 0, got {win}")
    node = out["node_id"] = int(out["node_id"])
    if not 0 <= node <= 0xFFFF:
        raise ValueError(
            f"flight.node_id must fit u16 (0..65535), got {node}")
    srcs = out.get("sources")
    if srcs is not None:
        if not isinstance(srcs, (list, tuple)) or \
                not all(isinstance(s, str) for s in srcs):
            raise ValueError("flight.sources must be a list of source "
                             f"names from {list(FLIGHT_SOURCES)}")
        bad = sorted(set(srcs) - set(FLIGHT_SOURCES))
        if bad:
            raise ValueError(
                f"unknown flight source(s) {bad}"
                + _suggest(bad[0], FLIGHT_SOURCES))
        out["sources"] = list(srcs)
    return out


def effective_sources(cfg: dict) -> set:
    """The drained source families of a normalized [flight] table."""
    srcs = cfg.get("sources")
    return set(FLIGHT_SOURCES if srcs is None else srcs)
