"""fdflight frame codec: the fixed-width binary record vocabulary.

One frame = 64 little-endian bytes, the same overwrite-safety stance
as runtime/tango.py::TraceRing but for an append-only FILE instead of
a shm ring — the payload words land first and the trailing CRC seals
them, so a reader can always tell a whole frame from the torn tail a
SIGKILL mid-write leaves behind (drop, count, never propagate):

    off  sz  field
      0   4  magic      0x31464446 ("FDF1")
      4   1  kind       KIND_* below
      5   1  ver        codec version (1)
      6   2  node_id    cluster node tag (u16, [flight].node_id)
      8   8  ts_ns      utils/tempo.monotonic_ns — the ONE clock the
                        trace/prof/gui surfaces already share
     16  16  source     tile / link / SLO-target name (NUL-padded)
     32  16  name       metric / counter / series name (NUL-padded)
     48   8  value      u64 payload (delta for counters, level for
                        gauges — see the kind table)
     56   4  aux        u32 sidecar (kind-specific, below)
     60   4  crc        zlib.crc32 of bytes [0:60)

Fixed width is the point: a segment is an mmap-friendly frame array —
frame i lives at i*64 with no index, a time-range slice is a binary
search away, and the torn tail after a crash is at most one partial
frame plus whatever the filesystem zero-fills (both fail the CRC).

Kinds (the `sources` families of the [flight] section select which
get written):

    KIND_METRIC  per-tile metric slot delta (aux=1: gauge, value is
                 the level not the delta)        source family "metrics"
    KIND_HIST    per-tile stem-histogram series (wait/work/tpu sum_ns
                 deltas + work p99 level, aux=1 for levels)  "metrics"
    KIND_LINK    per-link counter delta (pub/consumed/backpressure/..
                 aggregated over consumers) + consume-latency quantile
                 levels (aux=1)                              "links"
    KIND_SLO     SLO breach/clear transition (name = "breach"|"clear",
                 value = measured value clamped to u64, aux = total
                 breaches of the target)                     "slo"
    KIND_TRACE   sampled EV_* trace event (name = event name, value =
                 record.arg, aux = etype | min(count,0xFFFF)<<16)
                                                             "trace"
    KIND_PROF    prof folded-stack digest (name = leaf frame truncated
                 to the field, value = sample-count delta)   "prof"
    KIND_MARK    run lifecycle (name = "boot"|"halt", source = the
                 topology name) — the cross-run seam markers
"""
from __future__ import annotations

import struct
import zlib

FRAME_SZ = 64
MAGIC = 0x31464446          # "FDF1" little-endian
VERSION = 1

KIND_METRIC = 1
KIND_HIST = 2
KIND_LINK = 3
KIND_SLO = 4
KIND_TRACE = 5
KIND_PROF = 6
KIND_MARK = 7

KIND_NAMES = {
    KIND_METRIC: "metric", KIND_HIST: "hist", KIND_LINK: "link",
    KIND_SLO: "slo", KIND_TRACE: "trace", KIND_PROF: "prof",
    KIND_MARK: "mark",
}

# frame body (everything but the trailing crc)
_BODY = struct.Struct("<IBBHQ16s16sQI")
assert _BODY.size == FRAME_SZ - 4
_CRC = struct.Struct("<I")
_U64_MAX = (1 << 64) - 1


def _pad16(s: str) -> bytes:
    """Name fields are fixed 16 bytes: encode, truncate at a utf-8
    boundary, NUL-pad. Truncation is lossy by design — the archive
    stores series identity, not prose."""
    b = s.encode("utf-8", "replace")[:16]
    while b:
        try:
            b.decode("utf-8")
            break
        except UnicodeDecodeError:
            b = b[:-1]
    return b.ljust(16, b"\0")


def encode_frame(kind: int, ts_ns: int, node_id: int, source: str,
                 name: str, value: int, aux: int = 0) -> bytes:
    body = _BODY.pack(MAGIC, kind & 0xFF, VERSION, node_id & 0xFFFF,
                      int(ts_ns) & _U64_MAX, _pad16(source),
                      _pad16(name), int(value) & _U64_MAX,
                      int(aux) & 0xFFFFFFFF)
    return body + _CRC.pack(zlib.crc32(body))


def decode_frame(buf: bytes) -> dict | None:
    """One 64-byte slot -> frame dict, or None when the slot is torn
    (bad magic, bad CRC, short read) — the caller counts and drops."""
    if len(buf) < FRAME_SZ:
        return None
    body, (crc,) = buf[:_BODY.size], _CRC.unpack_from(buf, _BODY.size)
    if zlib.crc32(body) != crc:
        return None
    magic, kind, ver, node, ts, source, name, value, aux = \
        _BODY.unpack(body)
    if magic != MAGIC:
        return None
    # value rides as u64 two's complement: deltas go NEGATIVE when a
    # restarted tile's counters reset, and they must re-integrate as
    # such (a huge unsigned spike would corrupt every cumulative read)
    if value >= 1 << 63:
        value -= 1 << 64
    return {
        "ts": ts, "node": node, "kind": kind,
        "kind_name": KIND_NAMES.get(kind, f"?{kind}"), "ver": ver,
        "source": source.rstrip(b"\0").decode("utf-8", "replace"),
        "name": name.rstrip(b"\0").decode("utf-8", "replace"),
        "value": value, "aux": aux,
    }


def decode_frames(buf: bytes) -> tuple[list[dict], int]:
    """A segment's raw bytes -> (frames oldest-first, dropped count).
    Dropped counts every 64-byte slot that failed validation plus a
    trailing partial slot — the torn-tail contract: detected, counted,
    never propagated."""
    out: list[dict] = []
    dropped = 0
    n = len(buf) // FRAME_SZ
    for i in range(n):
        f = decode_frame(buf[i * FRAME_SZ:(i + 1) * FRAME_SZ])
        if f is None:
            dropped += 1
        else:
            out.append(f)
    if len(buf) % FRAME_SZ:
        dropped += 1
    return out, dropped
