/* fdtpu native runtime — see fdtpu.h for the design contract. */
#include "fdtpu.h"

#include <atomic>
#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <vector>

#include <fcntl.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/vfs.h>
#include <unistd.h>

namespace {

constexpr uint64_t kAlign = 64;  /* cacheline */

inline uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

inline uint8_t *at(void *base, uint64_t off) {
  return static_cast<uint8_t *>(base) + off;
}

/* Ring header: one cacheline of producer state, then depth slots. */
struct RingHdr {
  uint64_t magic;
  uint64_t depth;          /* power of two */
  std::atomic<uint64_t> seq;  /* next seq to publish (producer-owned) */
  uint64_t pad[5];
};
static_assert(sizeof(RingHdr) == 64, "ring header is one cacheline");

constexpr uint64_t kRingMagic = 0xfd79a9f07a960001ULL;

struct Slot {
  std::atomic<uint64_t> seq;
  uint64_t sig;
  uint32_t off;
  uint32_t sz;
  uint16_t ctl;
  uint16_t orig;
  uint32_t tspub;
};
static_assert(sizeof(Slot) == 32, "slot is 32 bytes");

inline RingHdr *ring_hdr(void *base, uint64_t off) {
  return reinterpret_cast<RingHdr *>(at(base, off));
}
inline Slot *ring_slots(void *base, uint64_t off) {
  return reinterpret_cast<Slot *>(at(base, off + sizeof(RingHdr)));
}

struct Fseq {
  std::atomic<uint64_t> seq;
  uint64_t pad[7];
};

struct Cnc {
  std::atomic<uint32_t> state;
  uint32_t pad0;
  std::atomic<uint64_t> heartbeat;
  uint64_t pad[6];
};

/* tcache: ring of most-recent tags + open-address presence map sized 2x
 * depth (power of two). Same dedup contract as the reference's tcache
 * (src/tango/fd_tcache.h:4-21) with a simpler eviction map. */
struct TcacheHdr {
  uint64_t depth;
  uint64_t map_cnt;        /* power of two, >= 2*depth */
  uint64_t next;           /* ring cursor */
  uint64_t pad[5];
  /* followed by: uint64_t ring[depth]; uint64_t map[map_cnt] */
};

inline uint64_t tmix(uint64_t x) {
  /* 64-bit finalizer-style mixer for map indexing */
  x ^= x >> 33; x *= 0xff51afd7ed558ccdULL;
  x ^= x >> 33; x *= 0xc4ceb9fe1a85ec53ULL;
  x ^= x >> 33; return x;
}

}  // namespace

extern "C" {

/* ---- workspace ------------------------------------------------------- */

static uint64_t wksp_map_len(uint64_t sz) {
  /* hugetlbfs requires hugepage-multiple lengths for ftruncate AND
   * munmap; statfs f_bsize on the mount reports its hugepage size.
   * Normal shm keeps the exact size. */
  const char *hugedir = getenv("FDTPU_HUGETLBFS");
  if (!hugedir || !hugedir[0]) return sz;
  struct statfs sf;
  if (statfs(hugedir, &sf) != 0 || sf.f_bsize <= 0) return sz;
  uint64_t ps = (uint64_t)sf.f_bsize;
  return (sz + ps - 1) / ps * ps;
}

static int wksp_open_fd(const char *name, int create) {
  /* Backing store selection (the reference's hugepage workspaces,
   * ref: src/util/shmem/fd_shmem.h — hugetlbfs-backed named regions):
   * when FDTPU_HUGETLBFS names a hugetlbfs mount, workspaces are
   * FILES there (real 2M/1G pages, kernel-enforced); otherwise
   * POSIX shm (/dev/shm) as before. Every process resolves the env
   * identically, so creators and joiners agree on the backing. */
  const char *hugedir = getenv("FDTPU_HUGETLBFS");
  char path[512];
  int fd;
  if (hugedir && hugedir[0]) {
    int n = snprintf(path, sizeof path, "%s/%s", hugedir, name);
    if (n < 0 || (size_t)n >= sizeof path) {
      errno = ENAMETOOLONG;        /* refuse truncated paths: a
                                    * truncated name could alias (and
                                    * replace-mode unlink) the WRONG
                                    * file */
      return -1;
    }
    if (create) {
      fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
      if (fd < 0 && errno == EEXIST && create == 2) {
        unlink(path);
        fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
      }
    } else {
      fd = open(path, O_RDWR);
    }
    return fd;
  }
  if (create) {
    fd = shm_open(name, O_RDWR | O_CREAT | O_EXCL, 0600);
    if (fd < 0 && errno == EEXIST && create == 2) {
      shm_unlink(name);
      fd = shm_open(name, O_RDWR | O_CREAT | O_EXCL, 0600);
    }
    return fd;
  }
  return shm_open(name, O_RDWR, 0600);
}

void *fdtpu_wksp_join(const char *name, uint64_t sz, int create) {
  /* create=0: join existing; create=1: exclusive create (fails on
   * EEXIST — safe under racing creators); create=2: replace — unlink any
   * stale segment from a crashed run and create fresh (zero-filled).
   * Replace mode is single-creator-discipline only: the caller asserts
   * no live process is using the name (the topology builder is the one
   * creator; every tile joins with create=0). */
  int fd = wksp_open_fd(name, create);
  if (fd < 0) return nullptr;
  uint64_t len = wksp_map_len(sz);
  if (create) {
    if (ftruncate(fd, (off_t)len) != 0) { close(fd); return nullptr; }
  } else {
    /* joining: segment must already be at least the requested size */
    struct stat st;
    if (fstat(fd, &st) != 0 || (uint64_t)st.st_size < len) {
      close(fd);
      return nullptr;
    }
  }
  void *p = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (p == MAP_FAILED) return nullptr;
#ifdef MADV_HUGEPAGE
  /* best-effort THP for shmem-backed regions (kernels with
   * shmem_enabled=advise honor this; harmless everywhere else) */
  madvise(p, sz, MADV_HUGEPAGE);
#endif
  return p;
}

int fdtpu_wksp_leave(void *base, uint64_t sz) {
  return munmap(base, wksp_map_len(sz));
}

int fdtpu_wksp_unlink(const char *name) {
  const char *hugedir = getenv("FDTPU_HUGETLBFS");
  if (hugedir && hugedir[0]) {
    char path[512];
    int n = snprintf(path, sizeof path, "%s/%s", hugedir, name);
    if (n < 0 || (size_t)n >= sizeof path) {
      errno = ENAMETOOLONG;
      return -1;
    }
    return unlink(path);
  }
  return shm_unlink(name);
}

/* ---- ring ------------------------------------------------------------- */

uint64_t fdtpu_ring_footprint(uint64_t depth) {
  return align_up(sizeof(RingHdr) + depth * sizeof(Slot));
}

int fdtpu_ring_init(void *base, uint64_t off, uint64_t depth) {
  if (!depth || (depth & (depth - 1))) return -1;
  RingHdr *h = ring_hdr(base, off);
  h->magic = kRingMagic;
  h->depth = depth;
  h->seq.store(0, std::memory_order_relaxed);
  Slot *s = ring_slots(base, off);
  for (uint64_t i = 0; i < depth; i++) {
    /* sentinel: "this slot last held seq i - depth", never a valid seq */
    s[i].seq.store(i - depth, std::memory_order_relaxed);
  }
  std::atomic_thread_fence(std::memory_order_release);
  return 0;
}

uint64_t fdtpu_ring_depth(void *base, uint64_t off) {
  return ring_hdr(base, off)->depth;
}

uint64_t fdtpu_ring_seq(void *base, uint64_t off) {
  return ring_hdr(base, off)->seq.load(std::memory_order_acquire);
}

/* bit 63 marks a slot as write-in-progress; real seqs stay below 2^63 */
constexpr uint64_t kWip = 1ULL << 63;

uint64_t fdtpu_ring_prepare(void *base, uint64_t ring_off) {
  RingHdr *h = ring_hdr(base, ring_off);
  uint64_t seq = h->seq.load(std::memory_order_relaxed);
  Slot *s = ring_slots(base, ring_off) + (seq & (h->depth - 1));
  /* Invalidate BEFORE the payload chunk is overwritten: a speculative
   * reader of the old frag re-checks the slot seq after its copy and now
   * sees the wip marker instead of the old seq -> rejects torn data. */
  s->seq.store(seq | kWip, std::memory_order_release);
  return seq;
}

uint64_t fdtpu_ring_publish(void *base, uint64_t ring_off, uint64_t sig,
                            uint64_t payload_off, uint32_t sz, uint16_t ctl,
                            uint16_t orig) {
  RingHdr *h = ring_hdr(base, ring_off);
  uint64_t seq = h->seq.load(std::memory_order_relaxed);
  Slot *s = ring_slots(base, ring_off) + (seq & (h->depth - 1));
  s->sig = sig;
  s->off = (uint32_t)(payload_off >> 6);  /* 64B chunk index */
  s->sz = sz;
  s->ctl = ctl;
  s->orig = orig;
  s->tspub = (uint32_t)fdtpu_ticks();
  s->seq.store(seq, std::memory_order_release);
  h->seq.store(seq + 1, std::memory_order_release);
  return seq;
}

uint64_t fdtpu_ring_publish_buf(void *base, uint64_t ring_off, uint64_t sig,
                                const uint8_t *data, uint32_t sz,
                                uint64_t arena_off, uint64_t mtu,
                                uint16_t ctl, uint16_t orig) {
  RingHdr *h = ring_hdr(base, ring_off);
  uint64_t seq = fdtpu_ring_prepare(base, ring_off);
  uint64_t chunk = arena_off + (seq & (h->depth - 1)) * mtu;
  std::memcpy(at(base, chunk), data, sz);
  return fdtpu_ring_publish(base, ring_off, sig, chunk, sz, ctl, orig);
}

int64_t fdtpu_ring_publish_batch(void *base, uint64_t ring_off,
                                 const uint8_t *buf, uint64_t stride,
                                 const uint32_t *sizes,
                                 const uint64_t *sigs,
                                 const uint8_t *mask, int64_t start,
                                 int64_t n, uint64_t arena_off,
                                 uint64_t mtu, const uint64_t *fseq_offs,
                                 int n_fseq, int64_t *published) {
  /* Publish masked rows [start, n) of a gathered buffer in one native
   * call, honoring reliable-consumer credits. Returns the row index it
   * stopped at (== n when done; < n when credits ran out — the caller
   * heartbeats and resumes). Credits are re-queried in blocks so the
   * fseq loads stay off the per-row path. */
  RingHdr *h = ring_hdr(base, ring_off);
  int64_t credits = n_fseq ? fdtpu_fctl_credits(base, ring_off, fseq_offs,
                                                n_fseq)
                           : (int64_t)h->depth;
  int64_t i = start;
  for (; i < n; i++) {
    if (!mask[i]) continue;
    if (n_fseq && credits <= 0) {
      credits = fdtpu_fctl_credits(base, ring_off, fseq_offs, n_fseq);
      if (credits <= 0) break;
    }
    uint64_t seq = fdtpu_ring_prepare(base, ring_off);
    uint64_t chunk = arena_off + (seq & (h->depth - 1)) * mtu;
    /* clamp to BOTH the slot capacity and the source row width — a
     * size past the stride would read the next row's payload */
    uint64_t cap = mtu < stride ? mtu : stride;
    uint32_t sz = sizes[i] <= cap ? sizes[i] : (uint32_t)cap;
    std::memcpy(at(base, chunk), buf + (uint64_t)i * stride, sz);
    fdtpu_ring_publish(base, ring_off, sigs ? sigs[i] : 0, chunk, sz,
                       /*ctl=*/3, /*orig=*/0);
    credits--;
    if (published) (*published)++;
  }
  return i;
}

int fdtpu_ring_consume(void *base, uint64_t ring_off, uint64_t seq,
                       fdtpu_frag_t *out) {
  RingHdr *h = ring_hdr(base, ring_off);
  Slot *s = ring_slots(base, ring_off) + (seq & (h->depth - 1));
  uint64_t found = s->seq.load(std::memory_order_acquire);
  if (found != seq) {
    /* signed distance: slot behind us -> unpublished; ahead -> overrun */
    return ((int64_t)(found - seq) < 0) ? 1 : -1;
  }
  out->sig = s->sig;
  out->off = (uint64_t)s->off << 6;  /* chunk index -> byte offset */
  out->sz = s->sz;
  out->ctl = s->ctl;
  out->orig = s->orig;
  out->tspub = s->tspub;
  std::atomic_thread_fence(std::memory_order_acquire);
  uint64_t check = s->seq.load(std::memory_order_relaxed);
  if (check != seq) return -1; /* torn: producer lapped mid-copy */
  out->seq = seq;
  return 0;
}

/* ---- fseq ------------------------------------------------------------- */

uint64_t fdtpu_fseq_footprint(void) { return sizeof(Fseq); }

int fdtpu_fseq_init(void *base, uint64_t off, uint64_t seq0) {
  reinterpret_cast<Fseq *>(at(base, off))
      ->seq.store(seq0, std::memory_order_release);
  return 0;
}

uint64_t fdtpu_fseq_query(void *base, uint64_t off) {
  return reinterpret_cast<Fseq *>(at(base, off))
      ->seq.load(std::memory_order_acquire);
}

void fdtpu_fseq_update(void *base, uint64_t off, uint64_t seq) {
  reinterpret_cast<Fseq *>(at(base, off))
      ->seq.store(seq, std::memory_order_release);
}

/* ---- fctl ------------------------------------------------------------- */

int64_t fdtpu_fctl_credits(void *base, uint64_t ring_off,
                           const uint64_t *fseq_offs, int n_fseq) {
  RingHdr *h = ring_hdr(base, ring_off);
  uint64_t seq = h->seq.load(std::memory_order_relaxed);
  int64_t credits = (int64_t)h->depth;
  for (int i = 0; i < n_fseq; i++) {
    uint64_t cseq = fdtpu_fseq_query(base, fseq_offs[i]);
    /* UINT64_MAX is the STALE sentinel: a dead/restarting consumer's
     * fseq (marked by the supervisor) is excluded from credit flow so
     * a crashed reliable consumer cannot wedge its producer; the
     * restarted tile re-includes itself by publishing a real seq. */
    if (cseq == UINT64_MAX) continue;
    int64_t c = (int64_t)h->depth - (int64_t)(seq - cseq);
    if (c < credits) credits = c;
  }
  return credits < 0 ? 0 : credits;
}

/* ---- cnc -------------------------------------------------------------- */

uint64_t fdtpu_cnc_footprint(void) { return sizeof(Cnc); }

int fdtpu_cnc_init(void *base, uint64_t off) {
  Cnc *c = reinterpret_cast<Cnc *>(at(base, off));
  c->state.store(FDTPU_CNC_BOOT, std::memory_order_relaxed);
  c->heartbeat.store(0, std::memory_order_release);
  return 0;
}

uint32_t fdtpu_cnc_state(void *base, uint64_t off) {
  return reinterpret_cast<Cnc *>(at(base, off))
      ->state.load(std::memory_order_acquire);
}

void fdtpu_cnc_set_state(void *base, uint64_t off, uint32_t st) {
  reinterpret_cast<Cnc *>(at(base, off))
      ->state.store(st, std::memory_order_release);
}

void fdtpu_cnc_heartbeat(void *base, uint64_t off, uint64_t now) {
  reinterpret_cast<Cnc *>(at(base, off))
      ->heartbeat.store(now, std::memory_order_release);
}

uint64_t fdtpu_cnc_last_heartbeat(void *base, uint64_t off) {
  return reinterpret_cast<Cnc *>(at(base, off))
      ->heartbeat.load(std::memory_order_acquire);
}

/* ---- tcache ----------------------------------------------------------- */

uint64_t fdtpu_tcache_footprint(uint64_t depth) {
  uint64_t map_cnt = 1;
  while (map_cnt < 4 * depth) map_cnt <<= 1;
  return align_up(sizeof(TcacheHdr) + (depth + map_cnt) * sizeof(uint64_t));
}

int fdtpu_tcache_init(void *base, uint64_t off, uint64_t depth) {
  if (!depth) return -1;
  TcacheHdr *h = reinterpret_cast<TcacheHdr *>(at(base, off));
  uint64_t map_cnt = 1;
  while (map_cnt < 4 * depth) map_cnt <<= 1;
  h->depth = depth;
  h->map_cnt = map_cnt;
  h->next = 0;
  uint64_t *ring = reinterpret_cast<uint64_t *>(h + 1);
  uint64_t *map = ring + depth;
  std::memset(ring, 0, depth * sizeof(uint64_t));
  std::memset(map, 0, map_cnt * sizeof(uint64_t));
  return 0;
}

int fdtpu_tcache_query(void *base, uint64_t off, uint64_t tag) {
  /* presence check only — no mutation. The verify path queries before
   * spending device lanes and inserts only tags that PASSED verification
   * (reference ordering: src/disco/verify/fd_verify_tile.h:84-101), so a
   * failed signature can never poison the dedup window. */
  if (!tag) tag = 1;
  TcacheHdr *h = reinterpret_cast<TcacheHdr *>(at(base, off));
  uint64_t *ring = reinterpret_cast<uint64_t *>(h + 1);
  uint64_t *map = ring + h->depth;
  uint64_t mask = h->map_cnt - 1;
  uint64_t idx = tmix(tag) & mask;
  while (map[idx]) {
    if (map[idx] == tag) return 1;
    idx = (idx + 1) & mask;
  }
  return 0;
}

int fdtpu_tcache_insert(void *base, uint64_t off, uint64_t tag) {
  /* tag 0 is reserved as the map's empty marker; remap (rare, and fine
   * for dedup purposes: 0 and 1 alias) */
  if (!tag) tag = 1;
  TcacheHdr *h = reinterpret_cast<TcacheHdr *>(at(base, off));
  uint64_t *ring = reinterpret_cast<uint64_t *>(h + 1);
  uint64_t *map = ring + h->depth;
  uint64_t mask = h->map_cnt - 1;

  uint64_t idx = tmix(tag) & mask;
  while (map[idx]) {
    if (map[idx] == tag) return 1; /* duplicate */
    idx = (idx + 1) & mask;
  }
  /* insert; evict oldest if ring full */
  uint64_t victim = ring[h->next % h->depth];
  ring[h->next % h->depth] = tag;
  h->next++;
  map[idx] = tag;
  if (victim && h->next > h->depth) {
    /* delete victim from map with backward-shift deletion */
    uint64_t vi = tmix(victim) & mask;
    while (map[vi] != victim) {
      if (!map[vi]) return 0; /* already gone (aliased remap) */
      vi = (vi + 1) & mask;
    }
    map[vi] = 0;
    uint64_t hole = vi, scan = (vi + 1) & mask;
    while (map[scan]) {
      uint64_t home = tmix(map[scan]) & mask;
      /* can map[scan] legally move into the hole? */
      bool movable = ((scan - home) & mask) >= ((scan - hole) & mask);
      if (movable) {
        map[hole] = map[scan];
        map[scan] = 0;
        hole = scan;
      }
      scan = (scan + 1) & mask;
    }
  }
  return 0;
}

/* ---- batch gather ------------------------------------------------------ */

int64_t fdtpu_ring_gather(void *base, uint64_t ring_off, uint64_t *seq_io,
                          int64_t max_n, uint8_t *out_buf,
                          uint64_t out_stride, uint32_t *out_sz,
                          uint64_t *out_sig, uint64_t *overrun_cnt,
                          uint64_t *out_seq) {
  int64_t n = 0;
  uint64_t seq = *seq_io;
  fdtpu_frag_t frag;
  while (n < max_n) {
    int rc = fdtpu_ring_consume(base, ring_off, seq, &frag);
    if (rc == 1) break; /* caught up */
    if (rc == -1) {
      /* lapped: resync to oldest plausibly-live seq */
      uint64_t prod = fdtpu_ring_seq(base, ring_off);
      uint64_t depth = fdtpu_ring_depth(base, ring_off);
      uint64_t resync = prod > depth ? prod - depth : 0;
      if (overrun_cnt) *overrun_cnt += resync - seq;
      seq = resync;
      continue;
    }
    uint8_t *dst = out_buf + (uint64_t)n * out_stride;
    uint32_t sz = frag.sz <= out_stride ? frag.sz : (uint32_t)out_stride;
    std::memcpy(dst, at(base, frag.off), sz);
    /* re-validate after payload copy: payload bytes are only stable while
     * the slot seq is unchanged (speculative read contract) */
    fdtpu_frag_t check;
    if (fdtpu_ring_consume(base, ring_off, seq, &check) != 0) {
      uint64_t prod = fdtpu_ring_seq(base, ring_off);
      uint64_t depth = fdtpu_ring_depth(base, ring_off);
      uint64_t resync = prod > depth ? prod - depth : 0;
      if (resync <= seq) resync = seq + 1;  /* always make progress */
      if (overrun_cnt) *overrun_cnt += resync - seq;
      seq = resync;
      continue;
    }
    if (sz < out_stride) std::memset(dst + sz, 0, out_stride - sz);
    if (out_sz) out_sz[n] = sz;
    if (out_sig) out_sig[n] = frag.sig;
    if (out_seq) out_seq[n] = seq;  /* per-frag seq: round-robin sharding
                                       key (ref: fd_verify_tile.c:49-53) */
    n++;
    seq++;
  }
  *seq_io = seq;
  return n;
}

uint64_t fdtpu_ticks(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

/* ---- batched txn parse + verify lane assembly --------------------------
 *
 * The verify tile's host hot path: at target ingest rates a per-txn
 * Python loop is the bottleneck (SURVEY language rule: no Python
 * stand-ins on the native hot path), so parsing, dedup-tag hashing and
 * device-lane assembly run here over the whole gathered batch.
 * Semantics mirror protocol/txn.py::parse_txn exactly (which itself
 * mirrors the reference zero-copy parser, ref:
 * src/ballet/txn/fd_txn.h:181-227, fd_txn_parse.c) — the Python parser
 * remains the spec; tests/test_txn.py fuzzes the two against each other.
 */

namespace {

constexpr int kMtu = 1232;
constexpr int kSigMax = 12;
constexpr int kAcctMax = 128;
constexpr int kInstrMax = 64;

/* compact-u16: 1-3 byte varint, minimal encoding enforced */
inline bool cu16(const uint8_t *p, int len, int *off, uint32_t *out) {
  uint32_t v = 0;
  for (int i = 0; i < 3; i++) {
    if (*off >= len) return false;
    uint8_t b = p[(*off)++];
    v |= (uint32_t)(b & 0x7F) << (7 * i);
    if (!(b & 0x80)) {
      if (i == 2 && b > 0x03) return false;
      if (i > 0 && b == 0) return false;   /* non-minimal */
      *out = v;
      return true;
    }
  }
  return false;
}

/* SipHash-1-3 (public domain algorithm; short-input keyed hash).
 * Plays the role of the reference's seeded fd_hash dedup tag
 * (ref: src/disco/verify/fd_verify_tile.h:82). */
inline uint64_t rotl64(uint64_t x, int b) { return (x << b) | (x >> (64 - b)); }

uint64_t siphash13(uint64_t k0, uint64_t k1, const uint8_t *data, size_t len) {
  uint64_t v0 = 0x736f6d6570736575ull ^ k0;
  uint64_t v1 = 0x646f72616e646f6dull ^ k1;
  uint64_t v2 = 0x6c7967656e657261ull ^ k0;
  uint64_t v3 = 0x7465646279746573ull ^ k1;
  auto round = [&]() {
    v0 += v1; v1 = rotl64(v1, 13); v1 ^= v0; v0 = rotl64(v0, 32);
    v2 += v3; v3 = rotl64(v3, 16); v3 ^= v2;
    v0 += v3; v3 = rotl64(v3, 21); v3 ^= v0;
    v2 += v1; v1 = rotl64(v1, 17); v1 ^= v2; v2 = rotl64(v2, 32);
  };
  size_t n = len & ~7ull;
  for (size_t i = 0; i < n; i += 8) {
    uint64_t m;
    std::memcpy(&m, data + i, 8);
    v3 ^= m;
    round();
    v0 ^= m;
  }
  uint64_t b = (uint64_t)len << 56;
  for (size_t i = n; i < len; i++) b |= (uint64_t)data[i] << (8 * (i - n));
  v3 ^= b; round(); v0 ^= b;
  v2 ^= 0xff; round(); round(); round();
  return v0 ^ v1 ^ v2 ^ v3;
}

struct TxnMeta {
  int32_t ok;        /* 1 = parsed */
  int32_t sig_cnt;
  int32_t sig_off;
  int32_t msg_off;
  int32_t acct_off;
  int32_t acct_cnt;
  int32_t version;   /* -1 legacy, 0 = v0 */
  int32_t hdr;       /* n_signed | n_ro_signed<<8 | n_ro_unsigned<<16 */
};
static_assert(sizeof(TxnMeta) == 32, "meta ABI");

bool parse_one(const uint8_t *p, int len, TxnMeta *m) {
  if (len > kMtu) return false;
  int off = 0;
  uint32_t sig_cnt;
  if (!cu16(p, len, &off, &sig_cnt)) return false;
  if (sig_cnt < 1 || sig_cnt > kSigMax) return false;
  int sig_off = off;
  off += 64 * (int)sig_cnt;
  if (off > len) return false;
  int msg_off = off;
  if (off >= len) return false;
  int version = -1;
  if (p[off] & 0x80) {
    version = p[off] & 0x7F;
    if (version != 0) return false;
    off++;
  }
  if (off + 3 > len) return false;
  uint32_t n_signed = p[off], n_ro_signed = p[off + 1],
           n_ro_unsigned = p[off + 2];
  off += 3;
  if (n_signed != sig_cnt) return false;
  if (n_ro_signed >= n_signed) return false;
  uint32_t acct_cnt;
  if (!cu16(p, len, &off, &acct_cnt)) return false;
  if (acct_cnt < n_signed || acct_cnt > kAcctMax) return false;
  if (n_ro_unsigned > acct_cnt - n_signed) return false;
  int acct_off = off;
  off += 32 * (int)acct_cnt;
  if (off > len) return false;
  off += 32;                              /* blockhash */
  if (off > len) return false;
  uint32_t instr_cnt;
  if (!cu16(p, len, &off, &instr_cnt)) return false;
  if (instr_cnt > kInstrMax) return false;
  for (uint32_t i = 0; i < instr_cnt; i++) {
    if (off >= len) return false;
    uint8_t prog_idx = p[off++];
    if (prog_idx >= acct_cnt) return false;
    uint32_t n_acct;
    if (!cu16(p, len, &off, &n_acct)) return false;
    if (off + (int)n_acct > len) return false;
    for (uint32_t a = 0; a < n_acct; a++)
      if (p[off + (int)a] >= acct_cnt) return false;
    off += (int)n_acct;
    uint32_t n_data;
    if (!cu16(p, len, &off, &n_data)) return false;
    off += (int)n_data;
    if (off > len) return false;
  }
  if (version == 0) {
    uint32_t alut_cnt;
    if (!cu16(p, len, &off, &alut_cnt)) return false;
    for (uint32_t i = 0; i < alut_cnt; i++) {
      off += 32;
      if (off > len) return false;
      uint32_t n_w;
      if (!cu16(p, len, &off, &n_w)) return false;
      off += (int)n_w;
      uint32_t n_ro;
      if (!cu16(p, len, &off, &n_ro)) return false;
      off += (int)n_ro;
      if (off > len) return false;
    }
  }
  if (off != len) return false;           /* trailing bytes */
  m->ok = 1;
  m->sig_cnt = (int32_t)sig_cnt;
  m->sig_off = sig_off;
  m->msg_off = msg_off;
  m->acct_off = acct_off;
  m->acct_cnt = (int32_t)acct_cnt;
  m->version = version;
  m->hdr = (int32_t)(n_signed | (n_ro_signed << 8) | (n_ro_unsigned << 16));
  return true;
}

}  // namespace

/* Parse a gathered batch; fill meta (n x 8 int32) and dedup tags (n u64,
 * SipHash-1-3 of the full 64-byte first signature, per-boot seeded).
 * Returns count of successfully parsed txns. */
int64_t fdtpu_txn_parse_batch(const uint8_t *buf, const uint32_t *sizes,
                              int64_t n, uint64_t stride,
                              uint64_t seed0, uint64_t seed1,
                              int32_t *meta_out, uint64_t *tags_out) {
  int64_t ok_cnt = 0;
  for (int64_t i = 0; i < n; i++) {
    const uint8_t *p = buf + (uint64_t)i * stride;
    TxnMeta *m = reinterpret_cast<TxnMeta *>(meta_out + 8 * i);
    std::memset(m, 0, sizeof(*m));
    if (parse_one(p, (int)sizes[i], m)) {
      tags_out[i] = siphash13(seed0, seed1, p + m->sig_off, 64);
      ok_cnt++;
    } else {
      tags_out[i] = 0;
    }
  }
  return ok_cnt;
}

/* Fill device verify lanes from parsed batch. One lane per signature of
 * every parsed, non-skipped txn, starting at txn *cursor_io. Stops when
 * lanes are full or txns exhausted; advances *cursor_io past consumed
 * txns (a txn's sigs never split across chunks). Unused lanes are zeroed
 * (dead lanes, masked by the caller). Returns lanes filled.
 * lane_txn[j] = source txn index. */
int64_t fdtpu_verify_assemble(const uint8_t *buf, const uint32_t *sizes,
                              const int32_t *meta, const uint8_t *skip,
                              int64_t n, uint64_t stride,
                              int64_t *cursor_io, int64_t cap,
                              uint64_t max_len,
                              uint8_t *lane_sig, uint8_t *lane_pub,
                              uint8_t *lane_msg, int32_t *lane_len,
                              int32_t *lane_txn) {
  int64_t lanes = 0;
  int64_t i = *cursor_io;
  for (; i < n; i++) {
    const TxnMeta *m = reinterpret_cast<const TxnMeta *>(meta + 8 * i);
    if (!m->ok || (skip && skip[i])) continue;
    if (lanes + m->sig_cnt > cap) break;
    const uint8_t *p = buf + (uint64_t)i * stride;
    uint32_t msg_len = sizes[i] - (uint32_t)m->msg_off;
    if (msg_len > max_len) continue;      /* cannot fit: drop (over-MTU) */
    for (int s = 0; s < m->sig_cnt; s++) {
      std::memcpy(lane_sig + 64 * lanes, p + m->sig_off + 64 * s, 64);
      std::memcpy(lane_pub + 32 * lanes, p + m->acct_off + 32 * s, 32);
      std::memcpy(lane_msg + max_len * lanes, p + m->msg_off, msg_len);
      std::memset(lane_msg + max_len * lanes + msg_len, 0, max_len - msg_len);
      lane_len[lanes] = (int32_t)msg_len;
      lane_txn[lanes] = (int32_t)i;
      lanes++;
    }
  }
  /* zero dead lanes' lengths + map */
  for (int64_t j = lanes; j < cap; j++) {
    lane_len[j] = 0;
    lane_txn[j] = -1;
  }
  *cursor_io = i;
  return lanes;
}

int fdtpu_tcache_query_batch(void *base, uint64_t off, const uint64_t *tags,
                             const uint8_t *mask, int64_t n, uint8_t *hit) {
  for (int64_t i = 0; i < n; i++)
    hit[i] = (mask && !mask[i]) ? 0
             : (uint8_t)fdtpu_tcache_query(base, off, tags[i]);
  return 0;
}

int fdtpu_tcache_insert_batch(void *base, uint64_t off, const uint64_t *tags,
                              const uint8_t *mask, int64_t n, uint8_t *dup) {
  for (int64_t i = 0; i < n; i++)
    dup[i] = (mask && !mask[i]) ? 0
             : (uint8_t)fdtpu_tcache_insert(base, off, tags[i]);
  return 0;
}

/* ---- funk store -------------------------------------------------------- */

/* See fdtpu.h for the design contract. Layout at `off`:
 *   StoreHdr | StoreTxn[txn_max] | StoreRec[rec_max]
 *   | uint32_t map[map_cnt] | heap[heap_sz]
 * The map holds rec idx+1 entries probed from store_hash(xid, key);
 * deletion is backward-shift (the tcache idiom above), so probes never
 * cross stale tombstones. The heap is power-of-two size classes
 * (64 B .. 2 MiB, never split or coalesced — <= 2x waste, O(1) ops). */

#define FDTPU_STORE_CLASSES 16
#define FDTPU_STORE_DEPTH_MAX 128

struct StoreHdr {
  uint64_t magic;
  uint64_t rec_max, txn_max, map_cnt, heap_sz;
  uint64_t txn_off, rec_off, map_off, heap_off;  /* relative to store off */
  std::atomic<uint64_t> lock;                    /* 0 free, else holder pid */
  uint32_t root_head;                            /* root rec list, idx+1 */
  uint32_t rec_free;                             /* rec freelist head, idx+1 */
  uint64_t heap_used;                            /* bump cursor (bytes) */
  uint64_t free_cls[FDTPU_STORE_CLASSES];        /* class freelists, off+1 */
  uint64_t rec_cnt;
  uint64_t pad[11];
};
static_assert(sizeof(StoreHdr) == 320, "store header ABI");

struct StoreTxn {
  uint64_t xid;       /* 0 = free slot (xid 0 is the root, never a slot) */
  uint64_t parent;    /* 0 = child of root */
  uint32_t rec_head;  /* idx+1 */
  uint32_t pad0;
  uint64_t pad[5];
};
static_assert(sizeof(StoreTxn) == 64, "store txn ABI");

struct StoreRec {
  uint8_t key[32];
  uint64_t xid;
  uint64_t val_off;   /* heap byte offset +1; 0 = empty value */
  uint32_t val_sz;
  uint32_t flags;     /* bit0 live, bit1 tombstone */
  uint32_t next;      /* idx+1 in the owning layer's list */
  uint32_t prev;      /* idx+1 (doubly linked: O(1) unlink on publish) */
};
static_assert(sizeof(StoreRec) == 64, "store rec ABI");

static const uint64_t kStoreMagic = 0xfd79a9f07a960005ULL;

static inline StoreHdr *store_hdr(void *base, uint64_t off) {
  return reinterpret_cast<StoreHdr *>(at(base, off));
}
static inline StoreTxn *store_txns(void *base, uint64_t off, StoreHdr *h) {
  return reinterpret_cast<StoreTxn *>(at(base, off + h->txn_off));
}
static inline StoreRec *store_recs(void *base, uint64_t off, StoreHdr *h) {
  return reinterpret_cast<StoreRec *>(at(base, off + h->rec_off));
}
static inline uint32_t *store_map(void *base, uint64_t off, StoreHdr *h) {
  return reinterpret_cast<uint32_t *>(at(base, off + h->map_off));
}
static inline uint8_t *store_heap(void *base, uint64_t off, StoreHdr *h) {
  return at(base, off + h->heap_off);
}

static uint64_t store_hash(uint64_t xid, const uint8_t *key) {
  uint64_t h = tmix(xid + 0x9e3779b97f4a7c15ULL), w;
  for (int i = 0; i < 4; i++) {
    std::memcpy(&w, key + 8 * i, 8);
    h = tmix(h ^ w);
  }
  return h;
}

/* pid-owned spinlock: a holder that died mid-operation is detected via
 * kill(pid, 0) == ESRCH and stolen, so a crashed exec tile can never
 * wedge every other store user (the supervision-v2 restart contract).
 * Mutations order their map/list updates so a stolen half-applied op is
 * at worst a leaked rec slot, never a corrupt probe chain. */
struct StoreLock {
  std::atomic<uint64_t> *l;
  explicit StoreLock(StoreHdr *h) : l(&h->lock) {
    uint64_t me = (uint64_t)getpid();
    for (uint64_t spin = 0;; spin++) {
      uint64_t cur = 0;
      if (l->compare_exchange_weak(cur, me, std::memory_order_acquire))
        return;
      if (cur && (spin & 1023) == 1023 &&
          kill((pid_t)cur, 0) != 0 && errno == ESRCH)
        l->compare_exchange_strong(cur, 0, std::memory_order_relaxed);
    }
  }
  ~StoreLock() { l->store(0, std::memory_order_release); }
};

static int store_cls_of(uint64_t sz) {
  for (int c = 0; c < FDTPU_STORE_CLASSES; c++)
    if ((64ULL << c) >= sz) return c;
  return -1;
}

/* returns heap byte offset +1, or 0 on exhaustion */
static uint64_t store_heap_alloc(void *base, uint64_t off, StoreHdr *h,
                                 uint64_t sz) {
  int c = store_cls_of(sz);
  if (c < 0) return 0;
  if (h->free_cls[c]) {
    uint64_t blk = h->free_cls[c] - 1;
    uint64_t nxt;
    std::memcpy(&nxt, store_heap(base, off, h) + blk, 8);
    h->free_cls[c] = nxt;
    return blk + 1;
  }
  uint64_t need = 64ULL << c;
  if (h->heap_used + need > h->heap_sz) return 0;
  uint64_t blk = h->heap_used;
  h->heap_used += need;
  return blk + 1;
}

static void store_heap_free(void *base, uint64_t off, StoreHdr *h,
                            uint64_t val_off, uint64_t sz) {
  if (!val_off) return;
  int c = store_cls_of(sz);
  uint64_t nxt = h->free_cls[c];
  std::memcpy(store_heap(base, off, h) + (val_off - 1), &nxt, 8);
  h->free_cls[c] = val_off;
}

/* map slot holding (xid, key), or -1 */
static int64_t store_map_find(void *base, uint64_t off, StoreHdr *h,
                              uint64_t xid, const uint8_t *key) {
  uint32_t *map = store_map(base, off, h);
  StoreRec *recs = store_recs(base, off, h);
  uint64_t mask = h->map_cnt - 1;
  uint64_t idx = store_hash(xid, key) & mask;
  while (map[idx]) {
    StoreRec *r = &recs[map[idx] - 1];
    if (r->xid == xid && !std::memcmp(r->key, key, 32)) return (int64_t)idx;
    idx = (idx + 1) & mask;
  }
  return -1;
}

static int store_map_insert(void *base, uint64_t off, StoreHdr *h,
                            uint32_t rec_idx1) {
  uint32_t *map = store_map(base, off, h);
  StoreRec *recs = store_recs(base, off, h);
  StoreRec *r = &recs[rec_idx1 - 1];
  uint64_t mask = h->map_cnt - 1;
  uint64_t idx = store_hash(r->xid, r->key) & mask;
  for (uint64_t probes = 0; probes <= mask; probes++) {
    if (!map[idx]) { map[idx] = rec_idx1; return 0; }
    idx = (idx + 1) & mask;
  }
  return -6;
}

static void store_map_erase(void *base, uint64_t off, StoreHdr *h,
                            uint64_t slot) {
  uint32_t *map = store_map(base, off, h);
  StoreRec *recs = store_recs(base, off, h);
  uint64_t mask = h->map_cnt - 1;
  map[slot] = 0;
  uint64_t hole = slot, scan = (slot + 1) & mask;
  while (map[scan]) {
    StoreRec *r = &recs[map[scan] - 1];
    uint64_t home = store_hash(r->xid, r->key) & mask;
    if (((scan - home) & mask) >= ((scan - hole) & mask)) {
      map[hole] = map[scan];
      map[scan] = 0;
      hole = scan;
    }
    scan = (scan + 1) & mask;
  }
}

static StoreTxn *store_txn_find(void *base, uint64_t off, StoreHdr *h,
                                uint64_t xid) {
  if (!xid) return nullptr;
  StoreTxn *t = store_txns(base, off, h);
  for (uint64_t i = 0; i < h->txn_max; i++)
    if (t[i].xid == xid) return &t[i];
  return nullptr;
}

/* unlink rec idx+1 from its layer list (head passed by pointer) */
static void store_list_unlink(StoreRec *recs, uint32_t *head,
                              uint32_t idx1) {
  StoreRec *r = &recs[idx1 - 1];
  if (r->prev) recs[r->prev - 1].next = r->next;
  else *head = r->next;
  if (r->next) recs[r->next - 1].prev = r->prev;
  r->next = r->prev = 0;
}

static void store_list_push(StoreRec *recs, uint32_t *head, uint32_t idx1) {
  StoreRec *r = &recs[idx1 - 1];
  r->next = *head;
  r->prev = 0;
  if (*head) recs[*head - 1].prev = idx1;
  *head = idx1;
}

/* free one rec slot: erase from map, free heap, push on freelist */
static void store_rec_free(void *base, uint64_t off, StoreHdr *h,
                           uint32_t idx1) {
  StoreRec *recs = store_recs(base, off, h);
  StoreRec *r = &recs[idx1 - 1];
  int64_t ms = store_map_find(base, off, h, r->xid, r->key);
  if (ms >= 0) store_map_erase(base, off, h, (uint64_t)ms);
  store_heap_free(base, off, h, r->val_off, r->val_sz);
  r->flags = 0;
  r->val_off = 0;
  r->next = h->rec_free;
  r->prev = 0;
  h->rec_free = idx1;
  h->rec_cnt--;
}

/* drop every record of one layer (cancel path) */
static void store_drop_layer(void *base, uint64_t off, StoreHdr *h,
                             uint32_t *head) {
  StoreRec *recs = store_recs(base, off, h);
  while (*head) {
    uint32_t idx1 = *head;
    store_list_unlink(recs, head, idx1);
    store_rec_free(base, off, h, idx1);
  }
}

uint64_t fdtpu_store_footprint(uint64_t rec_max, uint64_t txn_max,
                               uint64_t heap_sz) {
  uint64_t map_cnt = 1;
  while (map_cnt < 4 * rec_max) map_cnt <<= 1;
  return align_up(sizeof(StoreHdr)) + align_up(txn_max * sizeof(StoreTxn))
       + align_up(rec_max * sizeof(StoreRec))
       + align_up(map_cnt * sizeof(uint32_t)) + align_up(heap_sz);
}

int fdtpu_store_init(void *base, uint64_t off, uint64_t rec_max,
                     uint64_t txn_max, uint64_t heap_sz) {
  if (!rec_max || !txn_max || rec_max >= 0xffffffffULL) return -1;
  StoreHdr *h = store_hdr(base, off);
  std::memset(static_cast<void *>(h), 0, sizeof(StoreHdr));
  uint64_t map_cnt = 1;
  while (map_cnt < 4 * rec_max) map_cnt <<= 1;
  h->rec_max = rec_max;
  h->txn_max = txn_max;
  h->map_cnt = map_cnt;
  h->heap_sz = heap_sz;
  h->txn_off = align_up(sizeof(StoreHdr));
  h->rec_off = h->txn_off + align_up(txn_max * sizeof(StoreTxn));
  h->map_off = h->rec_off + align_up(rec_max * sizeof(StoreRec));
  h->heap_off = h->map_off + align_up(map_cnt * sizeof(uint32_t));
  std::memset(at(base, off + h->txn_off), 0, txn_max * sizeof(StoreTxn));
  std::memset(at(base, off + h->map_off), 0, map_cnt * sizeof(uint32_t));
  StoreRec *recs = store_recs(base, off, h);
  std::memset(recs, 0, rec_max * sizeof(StoreRec));
  for (uint64_t i = 0; i < rec_max; i++)
    recs[i].next = (i + 1 < rec_max) ? (uint32_t)(i + 2) : 0;
  h->rec_free = 1;
  h->magic = kStoreMagic;
  return 0;
}

int fdtpu_store_txn_prepare(void *base, uint64_t off, uint64_t parent_xid,
                            uint64_t xid) {
  StoreHdr *h = store_hdr(base, off);
  StoreLock lk(h);
  if (!xid || store_txn_find(base, off, h, xid)) return -1;
  if (parent_xid) {
    StoreTxn *p = store_txn_find(base, off, h, parent_xid);
    if (!p) return -2;
    uint64_t depth = 1, cur = parent_xid;
    while (cur) {
      if (++depth > FDTPU_STORE_DEPTH_MAX) return -3;
      StoreTxn *pp = store_txn_find(base, off, h, cur);
      if (!pp) break;
      cur = pp->parent;
    }
  }
  StoreTxn *t = store_txns(base, off, h);
  for (uint64_t i = 0; i < h->txn_max; i++)
    if (!t[i].xid) {
      t[i].xid = xid;
      t[i].parent = parent_xid;
      t[i].rec_head = 0;
      return 0;
    }
  return -4;
}

static void store_cancel_subtree(void *base, uint64_t off, StoreHdr *h,
                                 uint64_t xid) {
  StoreTxn *t = store_txns(base, off, h);
  for (uint64_t i = 0; i < h->txn_max; i++)
    if (t[i].xid && t[i].parent == xid)
      store_cancel_subtree(base, off, h, t[i].xid);
  StoreTxn *s = store_txn_find(base, off, h, xid);
  if (s) {
    store_drop_layer(base, off, h, &s->rec_head);
    s->xid = 0;
  }
}

int fdtpu_store_txn_cancel(void *base, uint64_t off, uint64_t xid) {
  StoreHdr *h = store_hdr(base, off);
  StoreLock lk(h);
  if (!store_txn_find(base, off, h, xid)) return -2;
  store_cancel_subtree(base, off, h, xid);
  return 0;
}

int fdtpu_store_txn_publish(void *base, uint64_t off, uint64_t xid) {
  StoreHdr *h = store_hdr(base, off);
  StoreLock lk(h);
  StoreTxn *t = store_txn_find(base, off, h, xid);
  if (!t) return -2;
  StoreTxn *txns = store_txns(base, off, h);
  StoreRec *recs = store_recs(base, off, h);
  /* ancestor chain, oldest first */
  uint64_t chain[FDTPU_STORE_DEPTH_MAX];
  int n_chain = 0;
  for (uint64_t cur = xid; cur && n_chain < FDTPU_STORE_DEPTH_MAX;) {
    chain[n_chain++] = cur;
    StoreTxn *c = store_txn_find(base, off, h, cur);
    cur = c ? c->parent : 0;
  }
  /* survivor marks BEFORE any slot is freed (walk-up needs parents) */
  std::vector<uint8_t> keep(h->txn_max, 0);
  for (uint64_t i = 0; i < h->txn_max; i++) {
    if (!txns[i].xid) continue;
    uint64_t cur = txns[i].xid;
    for (int d = 0; cur && d <= FDTPU_STORE_DEPTH_MAX; d++) {
      if (cur == xid) {
        keep[i] = txns[i].xid != xid;  /* subtree below xid survives */
        break;
      }
      StoreTxn *c = store_txn_find(base, off, h, cur);
      cur = c ? c->parent : 0;
    }
  }
  /* fold the chain into root, oldest ancestor first */
  for (int ci = n_chain - 1; ci >= 0; ci--) {
    StoreTxn *layer = store_txn_find(base, off, h, chain[ci]);
    while (layer->rec_head) {
      uint32_t idx1 = layer->rec_head;
      StoreRec *r = &recs[idx1 - 1];
      store_list_unlink(recs, &layer->rec_head, idx1);
      int64_t ms = store_map_find(base, off, h, r->xid, r->key);
      if (ms >= 0) store_map_erase(base, off, h, (uint64_t)ms);
      int64_t root_ms = store_map_find(base, off, h, 0, r->key);
      if (r->flags & 2) {                     /* tombstone: delete root rec */
        if (root_ms >= 0) {
          uint32_t ridx1 = store_map(base, off, h)[root_ms];
          store_list_unlink(recs, &h->root_head, ridx1);
          store_rec_free(base, off, h, ridx1);
        }
        store_heap_free(base, off, h, r->val_off, r->val_sz);
        r->flags = 0;
        r->val_off = 0;
        r->next = h->rec_free;
        h->rec_free = idx1;
        h->rec_cnt--;
      } else if (root_ms >= 0) {              /* move value into root rec */
        StoreRec *rr = &recs[store_map(base, off, h)[root_ms] - 1];
        store_heap_free(base, off, h, rr->val_off, rr->val_sz);
        rr->val_off = r->val_off;
        rr->val_sz = r->val_sz;
        r->val_off = 0;                        /* value moved, not freed */
        r->flags = 0;
        r->next = h->rec_free;
        h->rec_free = idx1;
        h->rec_cnt--;
      } else {                                 /* re-home rec under root */
        r->xid = 0;
        store_list_push(recs, &h->root_head, idx1);
        store_map_insert(base, off, h, idx1);
      }
    }
    layer->xid = 0;                            /* chain slot retires */
  }
  /* survivors re-parent to root; competitors die */
  for (uint64_t i = 0; i < h->txn_max; i++) {
    if (!txns[i].xid) continue;
    if (txns[i].parent == xid) txns[i].parent = 0;
    if (!keep[i]) {
      store_drop_layer(base, off, h, &txns[i].rec_head);
      txns[i].xid = 0;
    }
  }
  return 0;
}

int fdtpu_store_txn_exists(void *base, uint64_t off, uint64_t xid) {
  StoreHdr *h = store_hdr(base, off);
  StoreLock lk(h);
  return store_txn_find(base, off, h, xid) != nullptr;
}

int64_t fdtpu_store_txn_parent(void *base, uint64_t off, uint64_t xid) {
  StoreHdr *h = store_hdr(base, off);
  StoreLock lk(h);
  StoreTxn *t = store_txn_find(base, off, h, xid);
  return t ? (int64_t)t->parent : -2;
}

int64_t fdtpu_store_txn_children(void *base, uint64_t off, uint64_t xid,
                                 uint64_t *out, int64_t cap) {
  StoreHdr *h = store_hdr(base, off);
  StoreLock lk(h);
  if (xid && !store_txn_find(base, off, h, xid)) return -2;
  StoreTxn *t = store_txns(base, off, h);
  int64_t n = 0;
  for (uint64_t i = 0; i < h->txn_max; i++)
    if (t[i].xid && t[i].parent == xid) {
      if (n < cap) out[n] = t[i].xid;
      n++;
    }
  return n;
}

int fdtpu_store_put(void *base, uint64_t off, uint64_t xid,
                    const uint8_t *key, const uint8_t *val, uint64_t sz,
                    int tombstone) {
  StoreHdr *h = store_hdr(base, off);
  StoreLock lk(h);
  StoreRec *recs = store_recs(base, off, h);
  uint32_t *head = &h->root_head;
  if (xid) {
    StoreTxn *t = store_txn_find(base, off, h, xid);
    if (!t) return -2;
    head = &t->rec_head;
  }
  int64_t ms = store_map_find(base, off, h, xid, key);
  if (!xid && tombstone) {                    /* root delete (rec_remove) */
    if (ms >= 0) {
      uint32_t idx1 = store_map(base, off, h)[ms];
      store_list_unlink(recs, head, idx1);
      store_rec_free(base, off, h, idx1);
    }
    return 0;
  }
  uint64_t new_off = 0;
  if (!tombstone && sz) {                     /* alloc BEFORE freeing old */
    new_off = store_heap_alloc(base, off, h, sz);
    if (!new_off) return -5;
    std::memcpy(store_heap(base, off, h) + (new_off - 1), val, sz);
  }
  if (ms >= 0) {                              /* overwrite in place */
    StoreRec *r = &recs[store_map(base, off, h)[ms] - 1];
    store_heap_free(base, off, h, r->val_off, r->val_sz);
    r->val_off = new_off;
    r->val_sz = (uint32_t)sz;
    r->flags = tombstone ? 3u : 1u;
    return 0;
  }
  if (!h->rec_free) {
    store_heap_free(base, off, h, new_off, sz);
    return -4;
  }
  uint32_t idx1 = h->rec_free;
  StoreRec *r = &recs[idx1 - 1];
  h->rec_free = r->next;
  std::memcpy(r->key, key, 32);
  r->xid = xid;
  r->val_off = new_off;
  r->val_sz = (uint32_t)sz;
  r->flags = tombstone ? 3u : 1u;
  r->next = r->prev = 0;
  int rc = store_map_insert(base, off, h, idx1);
  if (rc) {
    store_heap_free(base, off, h, new_off, sz);
    r->flags = 0;
    r->next = h->rec_free;
    h->rec_free = idx1;
    return rc;
  }
  store_list_push(recs, head, idx1);
  h->rec_cnt++;
  return 0;
}

int64_t fdtpu_store_get(void *base, uint64_t off, uint64_t xid,
                        const uint8_t *key, uint8_t *out, uint64_t cap) {
  StoreHdr *h = store_hdr(base, off);
  StoreLock lk(h);
  StoreRec *recs = store_recs(base, off, h);
  uint64_t cur = xid;
  for (int d = 0; d <= FDTPU_STORE_DEPTH_MAX; d++) {
    if (cur && !store_txn_find(base, off, h, cur))
      return d == 0 ? -2 : -1;                /* chain broke mid-walk */
    int64_t ms = store_map_find(base, off, h, cur, key);
    if (ms >= 0) {
      StoreRec *r = &recs[store_map(base, off, h)[ms] - 1];
      if (r->flags & 2) return -1;            /* tombstone shadows */
      if (r->val_sz && out)
        std::memcpy(out, store_heap(base, off, h) + (r->val_off - 1),
                    r->val_sz < cap ? r->val_sz : cap);
      return r->val_sz;
    }
    if (!cur) return -1;                      /* probed root; absent */
    StoreTxn *t = store_txn_find(base, off, h, cur);
    cur = t ? t->parent : 0;
  }
  return -1;
}

int64_t fdtpu_store_iter(void *base, uint64_t off, uint64_t xid,
                         uint64_t *cursor, uint8_t *key_out,
                         uint8_t *val_out, uint64_t cap,
                         int32_t *tomb_out) {
  StoreHdr *h = store_hdr(base, off);
  StoreLock lk(h);
  StoreRec *recs = store_recs(base, off, h);
  uint32_t idx1;
  if (*cursor == 0) {
    if (xid) {
      StoreTxn *t = store_txn_find(base, off, h, xid);
      if (!t) return -2;
      idx1 = t->rec_head;
    } else {
      idx1 = h->root_head;
    }
  } else if (*cursor == UINT64_MAX) {
    return -1;
  } else {
    idx1 = (uint32_t)*cursor;
  }
  if (!idx1) {
    *cursor = UINT64_MAX;
    return -1;
  }
  StoreRec *r = &recs[idx1 - 1];
  std::memcpy(key_out, r->key, 32);
  *tomb_out = (r->flags & 2) ? 1 : 0;
  if (r->val_sz && val_out)
    std::memcpy(val_out, store_heap(base, off, h) + (r->val_off - 1),
                r->val_sz < cap ? r->val_sz : cap);
  *cursor = r->next ? (uint64_t)r->next : UINT64_MAX;
  return r->val_sz;
}

uint64_t fdtpu_store_rec_cnt(void *base, uint64_t off) {
  StoreHdr *h = store_hdr(base, off);
  StoreLock lk(h);
  return h->rec_cnt;
}

}  /* extern "C" */
