/* fdtpu native runtime — see fdtpu.h for the design contract. */
#include "fdtpu.h"

#include <atomic>
#include <cerrno>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kAlign = 64;  /* cacheline */

inline uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

inline uint8_t *at(void *base, uint64_t off) {
  return static_cast<uint8_t *>(base) + off;
}

/* Ring header: one cacheline of producer state, then depth slots. */
struct RingHdr {
  uint64_t magic;
  uint64_t depth;          /* power of two */
  std::atomic<uint64_t> seq;  /* next seq to publish (producer-owned) */
  uint64_t pad[5];
};
static_assert(sizeof(RingHdr) == 64, "ring header is one cacheline");

constexpr uint64_t kRingMagic = 0xfd79a9f07a960001ULL;

struct Slot {
  std::atomic<uint64_t> seq;
  uint64_t sig;
  uint32_t off;
  uint32_t sz;
  uint16_t ctl;
  uint16_t orig;
  uint32_t tspub;
};
static_assert(sizeof(Slot) == 32, "slot is 32 bytes");

inline RingHdr *ring_hdr(void *base, uint64_t off) {
  return reinterpret_cast<RingHdr *>(at(base, off));
}
inline Slot *ring_slots(void *base, uint64_t off) {
  return reinterpret_cast<Slot *>(at(base, off + sizeof(RingHdr)));
}

struct Fseq {
  std::atomic<uint64_t> seq;
  uint64_t pad[7];
};

struct Cnc {
  std::atomic<uint32_t> state;
  uint32_t pad0;
  std::atomic<uint64_t> heartbeat;
  uint64_t pad[6];
};

/* tcache: ring of most-recent tags + open-address presence map sized 2x
 * depth (power of two). Same dedup contract as the reference's tcache
 * (src/tango/fd_tcache.h:4-21) with a simpler eviction map. */
struct TcacheHdr {
  uint64_t depth;
  uint64_t map_cnt;        /* power of two, >= 2*depth */
  uint64_t next;           /* ring cursor */
  uint64_t pad[5];
  /* followed by: uint64_t ring[depth]; uint64_t map[map_cnt] */
};

inline uint64_t tmix(uint64_t x) {
  /* 64-bit finalizer-style mixer for map indexing */
  x ^= x >> 33; x *= 0xff51afd7ed558ccdULL;
  x ^= x >> 33; x *= 0xc4ceb9fe1a85ec53ULL;
  x ^= x >> 33; return x;
}

}  // namespace

extern "C" {

/* ---- workspace ------------------------------------------------------- */

void *fdtpu_wksp_join(const char *name, uint64_t sz, int create) {
  /* create=0: join existing; create=1: exclusive create (fails on
   * EEXIST — safe under racing creators); create=2: replace — unlink any
   * stale segment from a crashed run and create fresh (zero-filled).
   * Replace mode is single-creator-discipline only: the caller asserts
   * no live process is using the name (the topology builder is the one
   * creator; every tile joins with create=0). */
  int fd;
  if (create) {
    fd = shm_open(name, O_RDWR | O_CREAT | O_EXCL, 0600);
    if (fd < 0 && errno == EEXIST && create == 2) {
      shm_unlink(name);
      fd = shm_open(name, O_RDWR | O_CREAT | O_EXCL, 0600);
    }
    if (fd < 0) return nullptr;
    if (ftruncate(fd, (off_t)sz) != 0) { close(fd); return nullptr; }
  } else {
    fd = shm_open(name, O_RDWR, 0600);
    if (fd < 0) return nullptr;
    /* joining: segment must already be at least the requested size */
    struct stat st;
    if (fstat(fd, &st) != 0 || (uint64_t)st.st_size < sz) {
      close(fd);
      return nullptr;
    }
  }
  void *p = mmap(nullptr, sz, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  return p == MAP_FAILED ? nullptr : p;
}

int fdtpu_wksp_leave(void *base, uint64_t sz) { return munmap(base, sz); }

int fdtpu_wksp_unlink(const char *name) { return shm_unlink(name); }

/* ---- ring ------------------------------------------------------------- */

uint64_t fdtpu_ring_footprint(uint64_t depth) {
  return align_up(sizeof(RingHdr) + depth * sizeof(Slot));
}

int fdtpu_ring_init(void *base, uint64_t off, uint64_t depth) {
  if (!depth || (depth & (depth - 1))) return -1;
  RingHdr *h = ring_hdr(base, off);
  h->magic = kRingMagic;
  h->depth = depth;
  h->seq.store(0, std::memory_order_relaxed);
  Slot *s = ring_slots(base, off);
  for (uint64_t i = 0; i < depth; i++) {
    /* sentinel: "this slot last held seq i - depth", never a valid seq */
    s[i].seq.store(i - depth, std::memory_order_relaxed);
  }
  std::atomic_thread_fence(std::memory_order_release);
  return 0;
}

uint64_t fdtpu_ring_depth(void *base, uint64_t off) {
  return ring_hdr(base, off)->depth;
}

uint64_t fdtpu_ring_seq(void *base, uint64_t off) {
  return ring_hdr(base, off)->seq.load(std::memory_order_acquire);
}

/* bit 63 marks a slot as write-in-progress; real seqs stay below 2^63 */
constexpr uint64_t kWip = 1ULL << 63;

uint64_t fdtpu_ring_prepare(void *base, uint64_t ring_off) {
  RingHdr *h = ring_hdr(base, ring_off);
  uint64_t seq = h->seq.load(std::memory_order_relaxed);
  Slot *s = ring_slots(base, ring_off) + (seq & (h->depth - 1));
  /* Invalidate BEFORE the payload chunk is overwritten: a speculative
   * reader of the old frag re-checks the slot seq after its copy and now
   * sees the wip marker instead of the old seq -> rejects torn data. */
  s->seq.store(seq | kWip, std::memory_order_release);
  return seq;
}

uint64_t fdtpu_ring_publish(void *base, uint64_t ring_off, uint64_t sig,
                            uint64_t payload_off, uint32_t sz, uint16_t ctl,
                            uint16_t orig) {
  RingHdr *h = ring_hdr(base, ring_off);
  uint64_t seq = h->seq.load(std::memory_order_relaxed);
  Slot *s = ring_slots(base, ring_off) + (seq & (h->depth - 1));
  s->sig = sig;
  s->off = (uint32_t)(payload_off >> 6);  /* 64B chunk index */
  s->sz = sz;
  s->ctl = ctl;
  s->orig = orig;
  s->tspub = (uint32_t)fdtpu_ticks();
  s->seq.store(seq, std::memory_order_release);
  h->seq.store(seq + 1, std::memory_order_release);
  return seq;
}

uint64_t fdtpu_ring_publish_buf(void *base, uint64_t ring_off, uint64_t sig,
                                const uint8_t *data, uint32_t sz,
                                uint64_t arena_off, uint64_t mtu,
                                uint16_t ctl, uint16_t orig) {
  RingHdr *h = ring_hdr(base, ring_off);
  uint64_t seq = fdtpu_ring_prepare(base, ring_off);
  uint64_t chunk = arena_off + (seq & (h->depth - 1)) * mtu;
  std::memcpy(at(base, chunk), data, sz);
  return fdtpu_ring_publish(base, ring_off, sig, chunk, sz, ctl, orig);
}

int fdtpu_ring_consume(void *base, uint64_t ring_off, uint64_t seq,
                       fdtpu_frag_t *out) {
  RingHdr *h = ring_hdr(base, ring_off);
  Slot *s = ring_slots(base, ring_off) + (seq & (h->depth - 1));
  uint64_t found = s->seq.load(std::memory_order_acquire);
  if (found != seq) {
    /* signed distance: slot behind us -> unpublished; ahead -> overrun */
    return ((int64_t)(found - seq) < 0) ? 1 : -1;
  }
  out->sig = s->sig;
  out->off = (uint64_t)s->off << 6;  /* chunk index -> byte offset */
  out->sz = s->sz;
  out->ctl = s->ctl;
  out->orig = s->orig;
  out->tspub = s->tspub;
  std::atomic_thread_fence(std::memory_order_acquire);
  uint64_t check = s->seq.load(std::memory_order_relaxed);
  if (check != seq) return -1; /* torn: producer lapped mid-copy */
  out->seq = seq;
  return 0;
}

/* ---- fseq ------------------------------------------------------------- */

uint64_t fdtpu_fseq_footprint(void) { return sizeof(Fseq); }

int fdtpu_fseq_init(void *base, uint64_t off, uint64_t seq0) {
  reinterpret_cast<Fseq *>(at(base, off))
      ->seq.store(seq0, std::memory_order_release);
  return 0;
}

uint64_t fdtpu_fseq_query(void *base, uint64_t off) {
  return reinterpret_cast<Fseq *>(at(base, off))
      ->seq.load(std::memory_order_acquire);
}

void fdtpu_fseq_update(void *base, uint64_t off, uint64_t seq) {
  reinterpret_cast<Fseq *>(at(base, off))
      ->seq.store(seq, std::memory_order_release);
}

/* ---- fctl ------------------------------------------------------------- */

int64_t fdtpu_fctl_credits(void *base, uint64_t ring_off,
                           const uint64_t *fseq_offs, int n_fseq) {
  RingHdr *h = ring_hdr(base, ring_off);
  uint64_t seq = h->seq.load(std::memory_order_relaxed);
  int64_t credits = (int64_t)h->depth;
  for (int i = 0; i < n_fseq; i++) {
    uint64_t cseq = fdtpu_fseq_query(base, fseq_offs[i]);
    int64_t c = (int64_t)h->depth - (int64_t)(seq - cseq);
    if (c < credits) credits = c;
  }
  return credits < 0 ? 0 : credits;
}

/* ---- cnc -------------------------------------------------------------- */

uint64_t fdtpu_cnc_footprint(void) { return sizeof(Cnc); }

int fdtpu_cnc_init(void *base, uint64_t off) {
  Cnc *c = reinterpret_cast<Cnc *>(at(base, off));
  c->state.store(FDTPU_CNC_BOOT, std::memory_order_relaxed);
  c->heartbeat.store(0, std::memory_order_release);
  return 0;
}

uint32_t fdtpu_cnc_state(void *base, uint64_t off) {
  return reinterpret_cast<Cnc *>(at(base, off))
      ->state.load(std::memory_order_acquire);
}

void fdtpu_cnc_set_state(void *base, uint64_t off, uint32_t st) {
  reinterpret_cast<Cnc *>(at(base, off))
      ->state.store(st, std::memory_order_release);
}

void fdtpu_cnc_heartbeat(void *base, uint64_t off, uint64_t now) {
  reinterpret_cast<Cnc *>(at(base, off))
      ->heartbeat.store(now, std::memory_order_release);
}

uint64_t fdtpu_cnc_last_heartbeat(void *base, uint64_t off) {
  return reinterpret_cast<Cnc *>(at(base, off))
      ->heartbeat.load(std::memory_order_acquire);
}

/* ---- tcache ----------------------------------------------------------- */

uint64_t fdtpu_tcache_footprint(uint64_t depth) {
  uint64_t map_cnt = 1;
  while (map_cnt < 4 * depth) map_cnt <<= 1;
  return align_up(sizeof(TcacheHdr) + (depth + map_cnt) * sizeof(uint64_t));
}

int fdtpu_tcache_init(void *base, uint64_t off, uint64_t depth) {
  if (!depth) return -1;
  TcacheHdr *h = reinterpret_cast<TcacheHdr *>(at(base, off));
  uint64_t map_cnt = 1;
  while (map_cnt < 4 * depth) map_cnt <<= 1;
  h->depth = depth;
  h->map_cnt = map_cnt;
  h->next = 0;
  uint64_t *ring = reinterpret_cast<uint64_t *>(h + 1);
  uint64_t *map = ring + depth;
  std::memset(ring, 0, depth * sizeof(uint64_t));
  std::memset(map, 0, map_cnt * sizeof(uint64_t));
  return 0;
}

int fdtpu_tcache_query(void *base, uint64_t off, uint64_t tag) {
  /* presence check only — no mutation. The verify path queries before
   * spending device lanes and inserts only tags that PASSED verification
   * (reference ordering: src/disco/verify/fd_verify_tile.h:84-101), so a
   * failed signature can never poison the dedup window. */
  if (!tag) tag = 1;
  TcacheHdr *h = reinterpret_cast<TcacheHdr *>(at(base, off));
  uint64_t *ring = reinterpret_cast<uint64_t *>(h + 1);
  uint64_t *map = ring + h->depth;
  uint64_t mask = h->map_cnt - 1;
  uint64_t idx = tmix(tag) & mask;
  while (map[idx]) {
    if (map[idx] == tag) return 1;
    idx = (idx + 1) & mask;
  }
  return 0;
}

int fdtpu_tcache_insert(void *base, uint64_t off, uint64_t tag) {
  /* tag 0 is reserved as the map's empty marker; remap (rare, and fine
   * for dedup purposes: 0 and 1 alias) */
  if (!tag) tag = 1;
  TcacheHdr *h = reinterpret_cast<TcacheHdr *>(at(base, off));
  uint64_t *ring = reinterpret_cast<uint64_t *>(h + 1);
  uint64_t *map = ring + h->depth;
  uint64_t mask = h->map_cnt - 1;

  uint64_t idx = tmix(tag) & mask;
  while (map[idx]) {
    if (map[idx] == tag) return 1; /* duplicate */
    idx = (idx + 1) & mask;
  }
  /* insert; evict oldest if ring full */
  uint64_t victim = ring[h->next % h->depth];
  ring[h->next % h->depth] = tag;
  h->next++;
  map[idx] = tag;
  if (victim && h->next > h->depth) {
    /* delete victim from map with backward-shift deletion */
    uint64_t vi = tmix(victim) & mask;
    while (map[vi] != victim) {
      if (!map[vi]) return 0; /* already gone (aliased remap) */
      vi = (vi + 1) & mask;
    }
    map[vi] = 0;
    uint64_t hole = vi, scan = (vi + 1) & mask;
    while (map[scan]) {
      uint64_t home = tmix(map[scan]) & mask;
      /* can map[scan] legally move into the hole? */
      bool movable = ((scan - home) & mask) >= ((scan - hole) & mask);
      if (movable) {
        map[hole] = map[scan];
        map[scan] = 0;
        hole = scan;
      }
      scan = (scan + 1) & mask;
    }
  }
  return 0;
}

/* ---- batch gather ------------------------------------------------------ */

int64_t fdtpu_ring_gather(void *base, uint64_t ring_off, uint64_t *seq_io,
                          int64_t max_n, uint8_t *out_buf,
                          uint64_t out_stride, uint32_t *out_sz,
                          uint64_t *out_sig, uint64_t *overrun_cnt) {
  int64_t n = 0;
  uint64_t seq = *seq_io;
  fdtpu_frag_t frag;
  while (n < max_n) {
    int rc = fdtpu_ring_consume(base, ring_off, seq, &frag);
    if (rc == 1) break; /* caught up */
    if (rc == -1) {
      /* lapped: resync to oldest plausibly-live seq */
      uint64_t prod = fdtpu_ring_seq(base, ring_off);
      uint64_t depth = fdtpu_ring_depth(base, ring_off);
      uint64_t resync = prod > depth ? prod - depth : 0;
      if (overrun_cnt) *overrun_cnt += resync - seq;
      seq = resync;
      continue;
    }
    uint8_t *dst = out_buf + (uint64_t)n * out_stride;
    uint32_t sz = frag.sz <= out_stride ? frag.sz : (uint32_t)out_stride;
    std::memcpy(dst, at(base, frag.off), sz);
    /* re-validate after payload copy: payload bytes are only stable while
     * the slot seq is unchanged (speculative read contract) */
    fdtpu_frag_t check;
    if (fdtpu_ring_consume(base, ring_off, seq, &check) != 0) {
      uint64_t prod = fdtpu_ring_seq(base, ring_off);
      uint64_t depth = fdtpu_ring_depth(base, ring_off);
      uint64_t resync = prod > depth ? prod - depth : 0;
      if (resync <= seq) resync = seq + 1;  /* always make progress */
      if (overrun_cnt) *overrun_cnt += resync - seq;
      seq = resync;
      continue;
    }
    if (sz < out_stride) std::memset(dst + sz, 0, out_stride - sz);
    if (out_sz) out_sz[n] = sz;
    if (out_sig) out_sig[n] = frag.sig;
    n++;
    seq++;
  }
  *seq_io = seq;
  return n;
}

uint64_t fdtpu_ticks(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

}  /* extern "C" */
