/* fdtpu — native host runtime for the TPU-native Firedancer rebuild.
 *
 * Re-expression (NOT a port) of the reference's intra-host messaging layer
 * semantics (reference: src/tango/ — mcache/dcache/fseq/fctl/cnc/tcache;
 * design contract in src/tango/fd_tango_base.h:24-112):
 *
 *   - single-producer descriptor rings with 64-bit monotone sequence
 *     numbers; consumers NEVER block the producer — an overrun consumer
 *     detects the seq gap and resynchronizes (lossy, "unreliable" mode);
 *   - reliable consumers exert credit-based backpressure by publishing
 *     their progress sequence (fseq) which the producer folds into its
 *     credit budget (fctl);
 *   - payloads live in a separate arena ("chunk" offsets valid in any
 *     address space, so multiple processes can map the workspace at
 *     different base addresses);
 *   - per-slot seqlock publish: payload + fields first, release-store of
 *     the slot's seq last; a speculative reader re-checks the slot seq
 *     after copying to detect tearing.
 *
 * Everything lives inside a named shared-memory "workspace" (reference:
 * src/util/wksp/fd_wksp.h:27-47) addressed by byte offsets.
 *
 * This layer is the bridge ABI between host tiles (C++ or Python) and the
 * TPU dispatch loop — exactly the role the tango ABI plays for the
 * reference's FPGA sigverify offload (src/wiredancer/README.md:12,106-121).
 */
#ifndef FDTPU_H
#define FDTPU_H

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- frag descriptor ------------------------------------------------- */

/* Consumer-side copy of a ring slot (the in-ring slot itself is 32 bytes
 * and stores the payload offset as a 64-byte chunk index in 32 bits).
 * `seq` doubles as the seqlock version: slot valid iff slot.seq ==
 * expected ring seq for that slot. */
typedef struct {
  uint64_t seq;    /* published sequence number (release-stored last)   */
  uint64_t sig;    /* producer-defined signature for rx-side filtering  */
  uint64_t off;    /* payload BYTE offset within the workspace          */
  uint32_t sz;     /* payload size in bytes                             */
  uint16_t ctl;    /* bit0 SOM, bit1 EOM, bit2 ERR                      */
  uint16_t orig;   /* origin lane / tile id                             */
  uint32_t tspub;  /* publish timestamp (ticks, truncated)              */
} fdtpu_frag_t;

#define FDTPU_CTL_SOM 1u
#define FDTPU_CTL_EOM 2u
#define FDTPU_CTL_ERR 4u

/* ---- workspace ------------------------------------------------------- */

/* Create-or-join a named shared memory workspace of `sz` bytes.
 * Returns the local mapping address, or NULL on failure.
 * All intra-workspace references are byte offsets from this base. */
void   *fdtpu_wksp_join(const char *name, uint64_t sz, int create);
int     fdtpu_wksp_leave(void *base, uint64_t sz);
int     fdtpu_wksp_unlink(const char *name);

/* ---- ring (descriptor ring + optional payload arena) ------------------ */

/* Ring header lives in the workspace; depth must be a power of two.
 * Footprint = header + depth * sizeof(fdtpu_frag_t). */
uint64_t fdtpu_ring_footprint(uint64_t depth);
/* Initialize a ring at workspace offset `off`. Returns 0 on success. */
int      fdtpu_ring_init(void *base, uint64_t off, uint64_t depth);
uint64_t fdtpu_ring_depth(void *base, uint64_t off);
/* Producer-side cached sequence (next to publish). */
uint64_t fdtpu_ring_seq(void *base, uint64_t off);

/* Publish protocol (single producer):
 *   1. fdtpu_ring_prepare(): invalidates the next slot (release-stores a
 *      wip-marked seq so speculative readers of the OLD payload fail their
 *      re-check) and returns the seq about to be published.
 *   2. producer writes the payload bytes into the arena chunk.
 *   3. fdtpu_ring_publish(): fills descriptor fields, release-stores seq.
 * Payload offsets are stored as 64-byte chunk indices in 32 bits
 * (addressing up to 256 GiB of workspace); `payload_off` must be 64-byte
 * aligned. */
uint64_t fdtpu_ring_prepare(void *base, uint64_t ring_off);
uint64_t fdtpu_ring_publish(void *base, uint64_t ring_off,
                            uint64_t sig, uint64_t payload_off,
                            uint32_t sz, uint16_t ctl, uint16_t orig);
/* One-shot prepare+copy+publish for C-side producers. */
uint64_t fdtpu_ring_publish_buf(void *base, uint64_t ring_off, uint64_t sig,
                                const uint8_t *data, uint32_t sz,
                                uint64_t arena_off, uint64_t mtu,
                                uint16_t ctl, uint16_t orig);
/* Credit-gated batch publish of masked rows [start, n) of a gathered
 * (n, stride) buffer; returns the stop row (== n when complete, < n
 * when out of credits — heartbeat and resume). *published counts rows
 * actually sent. */
int64_t fdtpu_ring_publish_batch(void *base, uint64_t ring_off,
                                 const uint8_t *buf, uint64_t stride,
                                 const uint32_t *sizes,
                                 const uint64_t *sigs,
                                 const uint8_t *mask, int64_t start,
                                 int64_t n, uint64_t arena_off,
                                 uint64_t mtu, const uint64_t *fseq_offs,
                                 int n_fseq, int64_t *published);

/* Speculative consume at `seq`:
 *   returns  0: frag copied into *out (stable — seq re-check passed)
 *   returns  1: not yet published (caller spins / does housekeeping)
 *   returns -1: overrun — producer lapped the consumer; caller must
 *               resynchronize (e.g. jump to fdtpu_ring_seq - depth).   */
int fdtpu_ring_consume(void *base, uint64_t ring_off, uint64_t seq,
                       fdtpu_frag_t *out);

/* ---- fseq: published consumer progress -------------------------------- */

uint64_t fdtpu_fseq_footprint(void);
int      fdtpu_fseq_init(void *base, uint64_t off, uint64_t seq0);
uint64_t fdtpu_fseq_query(void *base, uint64_t off);
void     fdtpu_fseq_update(void *base, uint64_t off, uint64_t seq);

/* ---- fctl: producer credit computation --------------------------------
 * Credits = min over reliable consumers of
 *   depth - (producer_seq - consumer_fseq)
 * i.e. how many more frags can be published before overwriting a slot a
 * reliable consumer has not yet processed (reference semantics:
 * src/tango/fctl/fd_fctl.h:4-10 — "backpressure ... use sparingly"). */
int64_t fdtpu_fctl_credits(void *base, uint64_t ring_off,
                           const uint64_t *fseq_offs, int n_fseq);

/* ---- cnc: command & control + heartbeat ------------------------------- */

enum {
  FDTPU_CNC_BOOT = 0,
  FDTPU_CNC_RUN  = 1,
  FDTPU_CNC_HALT = 2,
  FDTPU_CNC_FAIL = 3,
};
uint64_t fdtpu_cnc_footprint(void);
int      fdtpu_cnc_init(void *base, uint64_t off);
uint32_t fdtpu_cnc_state(void *base, uint64_t off);
void     fdtpu_cnc_set_state(void *base, uint64_t off, uint32_t st);
void     fdtpu_cnc_heartbeat(void *base, uint64_t off, uint64_t now);
uint64_t fdtpu_cnc_last_heartbeat(void *base, uint64_t off);

/* ---- tcache: 64-bit tag dedup (ring + open-address map) --------------- */


uint64_t fdtpu_tcache_footprint(uint64_t depth);
int      fdtpu_tcache_init(void *base, uint64_t off, uint64_t depth);
/* Query-only presence check; returns 1 if tag present, 0 otherwise. */
int      fdtpu_tcache_query(void *base, uint64_t off, uint64_t tag);
/* Insert tag; returns 1 if tag was already present (duplicate), 0 if new.
 * Oldest tag is evicted once more than `depth` distinct tags inserted. */
int      fdtpu_tcache_insert(void *base, uint64_t off, uint64_t tag);

/* ---- batch gather: ring -> contiguous staging buffer ------------------ *
 * Drains up to max_n frags starting at *seq_io from the ring, copying
 * payloads into out_buf (stride out_stride, zero-padded) and metadata into
 * out_sz / out_sig. Stops early on an unpublished slot. On overrun,
 * resynchronizes to the producer's oldest still-valid seq and counts the
 * skip in *overrun_cnt. Returns number of frags gathered; *seq_io advances.
 * This is the microbatch assembly step of the TPU bridge tile
 * (the analog of the reference verify tile's during_frag copy,
 * src/disco/verify/fd_verify_tile.h:60-111, feeding a device batch). */
int64_t fdtpu_ring_gather(void *base, uint64_t ring_off, uint64_t *seq_io,
                          int64_t max_n, uint8_t *out_buf,
                          uint64_t out_stride, uint32_t *out_sz,
                          uint64_t *out_sig, uint64_t *overrun_cnt,
                          uint64_t *out_seq);

/* Tick counter (ns). */
uint64_t fdtpu_ticks(void);

/* Batched txn parse over a gathered buffer (full wire validation, same
 * contract as protocol/txn.py::parse_txn). meta_out: n x 8 int32 records
 * {ok, sig_cnt, sig_off, msg_off, acct_off, acct_cnt, version, hdr};
 * tags_out: n u64 seeded SipHash-1-3 dedup tags over the first 64-byte
 * signature. Returns number parsed ok. */
int64_t fdtpu_txn_parse_batch(const uint8_t *buf, const uint32_t *sizes,
                              int64_t n, uint64_t stride,
                              uint64_t seed0, uint64_t seed1,
                              int32_t *meta_out, uint64_t *tags_out);

/* Fill fixed-shape device verify lanes (one lane per signature) from the
 * parsed batch, skipping txns with skip[i] != 0. Chunk-able via
 * *cursor_io; a txn's sigs never split across chunks. Returns lanes
 * filled; dead lanes zeroed, lane_txn[j] = -1. */
int64_t fdtpu_verify_assemble(const uint8_t *buf, const uint32_t *sizes,
                              const int32_t *meta, const uint8_t *skip,
                              int64_t n, uint64_t stride,
                              int64_t *cursor_io, int64_t cap,
                              uint64_t max_len,
                              uint8_t *lane_sig, uint8_t *lane_pub,
                              uint8_t *lane_msg, int32_t *lane_len,
                              int32_t *lane_txn);

/* Batch tcache presence/insert (mask: optional per-txn enable). */
int fdtpu_tcache_query_batch(void *base, uint64_t off, const uint64_t *tags,
                             const uint8_t *mask, int64_t n, uint8_t *hit);
int fdtpu_tcache_insert_batch(void *base, uint64_t off, const uint64_t *tags,
                              const uint8_t *mask, int64_t n, uint8_t *dup);

/* ---- funk store: fork-aware shm record tree ---------------------------
 * Re-expression of funk's prepare/cancel/publish transaction tree over
 * the wksp offset ABI (ref: src/funk/fd_funk.h:28-90 — the reference
 * backs the same semantics with relocatable shared-memory maps). The
 * store is one carved region: a txn slot table, a fixed record-slot
 * array, an open-address (xid, key) -> record map with backward-shift
 * deletion (the tcache idiom), and a size-class heap for values. All
 * mutations and queries serialize on a pid-owned spinlock whose dead
 * holders are stolen (a killed exec tile must never wedge the store).
 *
 * xid 0 is the published root; keys are 32 bytes; error codes:
 *   -1 not found / bad xid      -2 unknown txn
 *   -3 fork depth limit         -4 slot table full
 *   -5 heap exhausted           -6 map full                            */

uint64_t fdtpu_store_footprint(uint64_t rec_max, uint64_t txn_max,
                               uint64_t heap_sz);
int      fdtpu_store_init(void *base, uint64_t off, uint64_t rec_max,
                          uint64_t txn_max, uint64_t heap_sz);
int      fdtpu_store_txn_prepare(void *base, uint64_t off,
                                 uint64_t parent_xid, uint64_t xid);
int      fdtpu_store_txn_cancel(void *base, uint64_t off, uint64_t xid);
int      fdtpu_store_txn_publish(void *base, uint64_t off, uint64_t xid);
int      fdtpu_store_txn_exists(void *base, uint64_t off, uint64_t xid);
/* parent xid (0 = child of root), or -2 when xid is not in preparation */
int64_t  fdtpu_store_txn_parent(void *base, uint64_t off, uint64_t xid);
int64_t  fdtpu_store_txn_children(void *base, uint64_t off, uint64_t xid,
                                  uint64_t *out, int64_t cap);
/* Write (or tombstone) a record in layer `xid`. xid 0 writes the root
 * directly; a root tombstone deletes the record (rec_remove(None)). */
int      fdtpu_store_put(void *base, uint64_t off, uint64_t xid,
                         const uint8_t *key, const uint8_t *val,
                         uint64_t sz, int tombstone);
/* Fork-visibility query: own layer, else nearest ancestor, else root.
 * Returns value size (copying min(sz, cap) bytes into out), -1 when
 * absent or tombstoned, -2 on unknown xid. */
int64_t  fdtpu_store_get(void *base, uint64_t off, uint64_t xid,
                         const uint8_t *key, uint8_t *out, uint64_t cap);
/* Enumerate ONE layer's own records (no ancestor fold). *cursor must be
 * 0 on the first call; returns value size per record (tombstones report
 * size 0 with *tomb_out = 1), -1 at end, -2 on unknown xid. */
int64_t  fdtpu_store_iter(void *base, uint64_t off, uint64_t xid,
                          uint64_t *cursor, uint8_t *key_out,
                          uint8_t *val_out, uint64_t cap,
                          int32_t *tomb_out);
/* Live record count (root + every in-preparation layer) — metrics. */
uint64_t fdtpu_store_rec_cnt(void *base, uint64_t off);

#ifdef __cplusplus
}
#endif
#endif /* FDTPU_H */
