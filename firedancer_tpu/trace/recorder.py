"""fdtrace recorder: config schema, the per-tile writer, plan helpers.

The topology builder (disco/topo.py) carves one TraceRing per traced
tile next to its metric slots; TileCtx materializes a `TraceWriter`
over it (ctx.trace) — or leaves ctx.trace = None when the tile is
untraced, which is the WHOLE disabled path: every hook in the stem and
the adapters is `if tr is not None:` on a cached attribute, no
allocation, no call, no syscall.

Config — the `[trace]` topology section plus an optional per-tile
`trace` table override:

    [trace]
    enable = true          # master switch (default false)
    depth  = 2048          # records per tile ring (power of two)
    sample = 1             # record every Nth frag-scoped event
    tiles  = ["verify"]    # optional allowlist (default: every tile)

    [tile.trace]           # per-tile override, highest precedence
    enable = false         # opt this tile out (or in) individually
    depth  = 8192
    sample = 16
"""
from __future__ import annotations

from ..runtime.tango import TRACE_LINK_NONE, TraceRing
from ..utils.tempo import monotonic_ns
from . import events as ev

TRACE_DEFAULTS = {
    "enable": False,
    "depth": 2048,
    "sample": 1,
    "tiles": None,          # None = all tiles (when enabled)
}
TILE_TRACE_KEYS = ("enable", "depth", "sample")   # per-tile override


def _suggest(key: str, candidates) -> str:
    # the ONE did-you-mean helper (lint/registry.py); lazy so the hot
    # write path never pays the lint import
    from ..lint.registry import suggest
    return suggest(key, candidates)


def normalize_trace(spec, per_tile: bool = False) -> dict:
    """Validate + default-fill a trace config table ([trace] section,
    or a tile's `trace` override with per_tile=True). Returns a plain
    JSON-able dict; raises ValueError with a did-you-mean on typos —
    the same fail-before-launch stance as supervise.normalize_policy."""
    allowed = set(TILE_TRACE_KEYS) if per_tile else set(TRACE_DEFAULTS)
    out = {} if per_tile else dict(TRACE_DEFAULTS)
    if spec is None:
        return out
    if not isinstance(spec, dict):
        raise ValueError(f"trace spec must be a table, got {spec!r}")
    unknown = set(spec) - allowed
    if unknown:
        key = sorted(unknown)[0]
        raise ValueError(f"unknown trace key(s) {sorted(unknown)}"
                         + _suggest(key, allowed))
    out.update(spec)
    if "enable" in out and out["enable"] is not None:
        out["enable"] = bool(out["enable"])
    if "depth" in out:
        d = out["depth"] = int(out["depth"])
        if d <= 0 or d & (d - 1):
            raise ValueError(
                f"trace.depth must be a positive power of two, got {d}")
    if "sample" in out:
        s = out["sample"] = int(out["sample"])
        if s < 1:
            raise ValueError(f"trace.sample must be >= 1, got {s}")
    tiles = out.get("tiles")
    if tiles is not None:
        if not isinstance(tiles, (list, tuple)) or \
                not all(isinstance(t, str) for t in tiles):
            raise ValueError("trace.tiles must be a list of tile names")
        out["tiles"] = list(tiles)
    return out


def effective_trace(topo_cfg: dict, tile_name: str,
                    tile_override: dict) -> dict | None:
    """Resolve one tile's trace settings from the normalized topology
    section + the tile's own (normalized, per_tile) override. Returns
    {depth, sample} when the tile is traced, None when it is not."""
    enabled = topo_cfg["enable"] and (
        topo_cfg["tiles"] is None or tile_name in topo_cfg["tiles"])
    if "enable" in tile_override:
        enabled = bool(tile_override["enable"])
    if not enabled:
        return None
    return {"depth": int(tile_override.get("depth", topo_cfg["depth"])),
            "sample": int(tile_override.get("sample",
                                            topo_cfg["sample"]))}


def link_ids(plan: dict) -> dict[str, int]:
    """Link name -> trace link id. The id space is the SORTED link-name
    order of the plan — deterministic on both the write side (TileCtx)
    and the read side (export), with no extra plan state."""
    return {ln: i for i, ln in enumerate(sorted(plan["links"]))}


def link_names(plan: dict) -> list[str]:
    return sorted(plan["links"])


class TraceWriter:
    """The per-tile write handle: a TraceRing + the frag-event sampler.

    Lifecycle events (`event`) always record; frag-scoped events
    (`frag`) record every `sample`-th call so a high-rate pipeline can
    trade lineage completeness for ring history span. `span` stamps
    END-relative records (ts = now, arg = now - t0)."""

    __slots__ = ("ring", "sample", "_nfrag", "_links")

    def __init__(self, ring: TraceRing, sample: int = 1,
                 links: dict[str, int] | None = None):
        self.ring = ring
        self.sample = max(1, int(sample))
        self._nfrag = 0
        self._links = links or {}

    def link_id(self, link_name: str) -> int:
        return self._links.get(link_name, TRACE_LINK_NONE)

    def event(self, etype: int, sig: int = 0, arg: int = 0,
              link: int = TRACE_LINK_NONE, count: int = 0):
        self.ring.append(monotonic_ns(), etype, sig=sig, arg=arg,
                         link=link, count=count)

    def frag(self, etype: int, sig: int = 0, arg: int = 0,
             link: int = TRACE_LINK_NONE, count: int = 0):
        """Sampled frag-scoped record (every Nth; N=1 records all)."""
        self._nfrag += 1
        if self._nfrag % self.sample == 0:
            self.ring.append(monotonic_ns(), etype, sig=sig, arg=arg,
                             link=link, count=count)

    def frag_batch(self, etype: int, sigs,
                   link: int = TRACE_LINK_NONE):
        """Batched frag(): same sampling stream as n sequential frag()
        calls (every `sample`-th of the running frag count records),
        but the selected records land via ONE vectorized ring append —
        no per-frag Python on tile hot paths (the zero-Python-hot-loop
        contract the new fdlint per-frag-loop rule enforces). Records
        in one batch share a single timestamp: the batch IS the event."""
        import numpy as np
        n = len(sigs)
        if not n:
            return
        s = self.sample
        if s == 1:
            keep = np.asarray(sigs, np.uint64)
        else:
            # indices i with (nfrag + i + 1) % s == 0
            i0 = (s - 1 - self._nfrag) % s
            keep = np.asarray(sigs[i0::s], np.uint64)
        self._nfrag += n
        if len(keep):
            self.ring.append_batch(monotonic_ns(), etype, keep,
                                   link=link)

    def span(self, etype: int, t0_ns: int, sig: int = 0,
             link: int = TRACE_LINK_NONE, count: int = 0):
        now = monotonic_ns()
        self.ring.append(now, etype, sig=sig, arg=max(0, now - t0_ns),
                         link=link, count=count)


def writer_for(ctx_or_plan, wksp, tile_name: str) -> TraceWriter | None:
    """TraceWriter over an EXISTING tile ring (reader/supervisor side:
    plan + joined workspace), or None if the tile is untraced."""
    plan = ctx_or_plan
    spec = plan["tiles"][tile_name]
    off = spec.get("trace_off")
    if off is None:
        return None
    ring = TraceRing(wksp, off, int(spec["trace_depth"]))
    return TraceWriter(ring, sample=int(spec.get("trace_sample", 1)),
                       links=link_ids(plan))


def chaos_event(tr: TraceWriter | None, action: str, at: int = 0):
    """Record a chaos-harness fault injection (stem calls this right
    BEFORE acting, so even a `crash` leaves its own footprint in the
    flight recorder — the black-box dump then shows fault -> trip)."""
    if tr is not None:
        tr.event(ev.EV_CHAOS, arg=at,
                 count=ev.CHAOS_ACTION_IDS.get(action, 0))
