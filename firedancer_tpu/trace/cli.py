"""fdtrace CLI: drain/export a topology's flight-recorder rings.

    python -m firedancer_tpu.trace <topology-name | plan.json | blackbox.json>
        [--out trace.json]        write Perfetto/Chrome JSON here
        [--format summary|chrome|both]   (default: summary to stdout)
        [--tile NAME ...]         restrict to these tiles

Attaches exactly like the monitor: via the plan JSON the runner drops
in /dev/shm, so it works live (tiles still writing — snapshot
semantics) or POST-MORTEM (the workspace is shm and survives tile
death; drain the rings any time before the runner unlinks). A
black-box dump file written by the supervisor can be re-exported by
passing its path.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _attach(target: str):
    """topology name | plan.json path -> (plan, wksp)."""
    from ..disco.launch import plan_path
    from ..runtime import Workspace
    path = target if target.endswith(".json") and os.path.exists(target) \
        else plan_path(target)
    with open(path) as f:
        plan = json.load(f)
    wksp = Workspace(plan["wksp"]["name"], plan["wksp"]["size"],
                     create=False)
    return plan, wksp


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fdtrace",
        description="drain/export fdtrace flight-recorder rings")
    ap.add_argument("target",
                    help="topology name, plan.json path, or a "
                         "supervisor blackbox .json dump")
    ap.add_argument("--out", default=None,
                    help="write Chrome-trace/Perfetto JSON to this file")
    ap.add_argument("--format", choices=("summary", "chrome", "both"),
                    default="summary")
    ap.add_argument("--tile", action="append", default=None,
                    help="only these tiles (repeatable)")
    args = ap.parse_args(argv)

    from . import export

    # a blackbox dump re-exports without any live topology
    if args.target.endswith(".json") and os.path.exists(args.target):
        with open(args.target) as f:
            doc = json.load(f)
        if "events" in doc and "tile" in doc:
            evs = {doc["tile"]: doc["events"]}
            if args.format in ("summary", "both"):
                sys.stdout.write(
                    f"blackbox: tile {doc['tile']!r} "
                    f"({doc.get('reason', '?')})\n")
                sys.stdout.write(export.summary(evs))
            if args.out:
                with open(args.out, "w") as f:
                    json.dump(export.to_chrome(
                        evs, doc.get("topology", "?")), f)
                print(f"wrote {args.out}")
            elif args.format in ("chrome", "both"):
                json.dump(doc.get("chrome")
                          or export.to_chrome(evs,
                                              doc.get("topology", "?")),
                          sys.stdout)
            return 0

    plan, wksp = _attach(args.target)
    try:
        evs = export.read_rings(plan, wksp, tiles=args.tile)
        if not evs:
            print("no traced tiles (is [trace] enabled in the "
                  "topology config?)", file=sys.stderr)
            return 1
        if args.format in ("summary", "both"):
            sys.stdout.write(export.summary(evs))
        chrome = export.to_chrome(evs, plan.get("topology", "?"))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(chrome, f)
            print(f"wrote {args.out} "
                  f"({len(chrome['traceEvents'])} events) — open at "
                  f"ui.perfetto.dev")
        elif args.format in ("chrome", "both"):
            json.dump(chrome, sys.stdout)
        return 0
    finally:
        wksp.close()
