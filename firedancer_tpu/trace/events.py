"""fdtrace event schema: the binary record vocabulary.

One flat u16 event-type space shared by every writer (stem, verify
tile, adapters, supervisor) — the moral equivalent of Chrome's
trace-event categories/phases (the Perfetto timeline model): SPAN
events carry a duration (record.arg = ns, record.ts = END of the
span), INSTANT events mark a point. Frag-scoped events additionally
carry the frag's `sig` (the dedup tag for verify-pipeline traffic), so
one transaction microbatch can be followed across rings by matching
sigs — the cross-tile lineage the exporter turns into Perfetto flow
arrows.

Record wire layout lives in runtime/tango.py::TraceRing; this module
owns only the meaning of the words.
"""
from __future__ import annotations

# -- event types (u16) ------------------------------------------------------

EV_BOOT = 1          # instant: stem entered RUN
EV_HALT = 2          # instant: clean halt path taken
EV_FAIL = 3          # instant: tile raised / external CNC_FAIL observed
EV_WAIT = 4          # span: idle streak waiting on upstream frags
EV_WORK = 5          # span: productive poll_once time (count = frags;
                     #   with sample>1 one record SUMS the last
                     #   `sample` productive polls — attribution stays
                     #   exact, only the record rate is thinned)
EV_HOUSEKEEP = 6     # span: one housekeeping pass
EV_CONSUME = 7       # instant, frag-scoped: frag consumed (sig, link)
EV_PUBLISH = 8       # instant, frag-scoped: frag published (sig, link)
EV_BACKPRESSURE = 9  # span: blocked on downstream credits (link)
EV_TPU_DISPATCH = 10  # span: device dispatch call (count = lanes)
EV_TPU_READBACK = 11  # span: verdict readback wait (count = chunks)
EV_CPU_FALLBACK = 12  # instant: verify degraded to the CPU path
EV_CHAOS = 13        # instant: chaos fault fired (count = action id)
EV_WATCHDOG = 14     # instant: supervisor wedge-watchdog trip (sup-written)
EV_RESTART = 15      # instant: supervisor respawned the tile (sup-written)
EV_DOWN = 16         # instant: supervisor observed abnormal death
EV_SLO = 17          # instant: SLO breach (metric-tile-written;
                     #   arg = measured value, count = target index
                     #   into the plan's [slo] target list)
EV_COMPILE = 18      # instant: jit cache grew — a compile the padding
                     #   discipline should have prevented (fdprof
                     #   CompileWatch; arg = device mem bytes,
                     #   count = total compiled variants)
EV_PROF_CAPTURE = 19  # span: bounded device-trace window (fdprof
                     #   DeviceCapture; count = doorbell req id)
EV_TUNE = 20         # instant: controller knob decision (fdtune;
                     #   arg = new knob value, count = knob index into
                     #   the plan's tune_knobs list, link = the
                     #   saturating hop that justified the move)

NAMES = {
    EV_BOOT: "boot", EV_HALT: "halt", EV_FAIL: "fail",
    EV_WAIT: "wait", EV_WORK: "work", EV_HOUSEKEEP: "housekeep",
    EV_CONSUME: "consume", EV_PUBLISH: "publish",
    EV_BACKPRESSURE: "backpressure",
    EV_TPU_DISPATCH: "tpu_dispatch", EV_TPU_READBACK: "tpu_readback",
    EV_CPU_FALLBACK: "cpu_fallback", EV_CHAOS: "chaos",
    EV_WATCHDOG: "watchdog", EV_RESTART: "restart", EV_DOWN: "down",
    EV_SLO: "slo", EV_COMPILE: "compile",
    EV_PROF_CAPTURE: "prof_capture", EV_TUNE: "tune",
}

# span events: record.ts is the END, record.arg the duration in ns
SPANS = {EV_WAIT, EV_WORK, EV_HOUSEKEEP, EV_BACKPRESSURE,
         EV_TPU_DISPATCH, EV_TPU_READBACK, EV_PROF_CAPTURE}

# frag-scoped events (sig is a lineage key, not 0-means-nothing)
FRAG_EVENTS = {EV_CONSUME, EV_PUBLISH}

# chaos action ids (record.count of an EV_CHAOS event); kept in lockstep
# with utils/chaos.py ACTIONS so a dumped trace names the exact fault
CHAOS_ACTION_IDS = {
    "crash": 1, "freeze_hb": 2, "wedge": 3, "stall_fseq": 4,
    "fail_dispatch": 5,
    # adversarial traffic plans (r14): injected hostile TRAFFIC, not
    # infrastructure faults — recorded before the frames flow so a
    # black-box dump names the attack even when the tile died mid-flood
    "flood_forged": 6, "flood_torsion": 7, "flood_dup": 8,
    "flood_malformed_quic": 9, "flood_crds_spam": 10,
    # snapshot/replay robustness drills (r17): the catch-up surface's
    # seeded faults — adapter-routed, recorded before the fault fires
    "crash_mid_snapshot": 11, "corrupt_checkpt_frame": 12,
    "stale_snapshot_offer": 13, "diverge_block": 14,
}
CHAOS_ACTION_NAMES = {v: k for k, v in CHAOS_ACTION_IDS.items()}


def decode(rec, link_names: list[str] | None = None) -> dict:
    """One raw (4,) u64 record -> a plain dict (the export/JSON shape).
    link_names is the plan's sorted link-name list; an out-of-range id
    (TRACE_LINK_NONE, or a torn record) decodes to link=None."""
    from ..runtime.tango import TRACE_LINK_NONE
    ts, sig, arg, meta = (int(rec[0]), int(rec[1]), int(rec[2]),
                          int(rec[3]))
    etype = meta & 0xFFFF
    link_id = (meta >> 16) & 0xFFFF
    count = meta >> 32
    link = None
    if link_names is not None and link_id != TRACE_LINK_NONE \
            and link_id < len(link_names):
        link = link_names[link_id]
    return {"ts": ts, "ev": NAMES.get(etype, f"?{etype}"),
            "etype": etype, "sig": sig, "arg": arg, "link": link,
            "count": count}
