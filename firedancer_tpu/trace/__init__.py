"""fdtrace: the shared-memory flight recorder.

Counters (disco/metrics.py) say THAT a link stalled; the flight
recorder says WHICH frag stalled it and where the microseconds went
between verify dispatch and bank commit. Each traced tile owns a
fixed-depth binary event ring in the workspace (runtime/tango.py
TraceRing, carved by disco/topo.py next to the metric slots), written
by cheap hooks in the stem run loop and the verify tile, with frag
lineage carried through the existing sig/seq discipline — one
transaction microbatch is followable verify -> dedup -> pack -> bank
-> poh across rings.

Layout of the package:

    events.py     the event-type vocabulary + record decode
    recorder.py   [trace] config schema, TraceWriter, plan helpers
    export.py     rings -> Perfetto/Chrome JSON, text summary,
                  supervisor black-box dumps
    cli.py        `python -m firedancer_tpu.trace` / tools/fdtrace

Disabled-path contract: an untraced tile's TileCtx.trace is None and
every hook is a single cached-attribute None check — untraced
topologies pay nothing per frag.
"""
from . import events  # noqa: F401
from .export import (  # noqa: F401
    blackbox_path, dump_blackbox, lineage, read_rings, summary,
    to_chrome,
)
from .recorder import (  # noqa: F401
    TILE_TRACE_KEYS, TRACE_DEFAULTS, TraceWriter, chaos_event,
    effective_trace, link_ids, link_names, normalize_trace, writer_for,
)
