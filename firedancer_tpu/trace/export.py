"""fdtrace export: shm rings -> Perfetto/Chrome JSON, text summary,
black-box dumps.

The snapshot side of the flight recorder. Everything here is
reader-only over a joined workspace (live tiles keep writing — the
ring's documented tear window applies) or over a dead topology's shm
segment (the workspace outlives tile processes, so a post-mortem drain
sees exactly the final events).

Chrome-trace mapping (the Perfetto-ingestible JSON array format):

    tile            -> one thread (tid = tile index, named via M events)
    span events     -> "X" complete events (ts = end - dur)
    instant events  -> "i" instants
    frag lineage    -> "s"/"f" flow arrows keyed by the frag sig: the
                       publish on the producing tile starts the flow,
                       every later consume/publish of the same sig on
                       ANOTHER tile binds to it — one transaction
                       microbatch reads as an arrow chain
                       verify -> dedup -> pack -> bank -> poh
"""
from __future__ import annotations

import json

from ..runtime.tango import TraceRing
from . import events as ev
from .recorder import link_names


def read_rings(plan: dict, wksp, tiles=None) -> dict[str, list[dict]]:
    """{tile: [decoded event dicts, oldest-first]} for every traced
    tile (or the `tiles` subset)."""
    names = link_names(plan)
    out: dict[str, list[dict]] = {}
    for tn, spec in plan["tiles"].items():
        if tiles is not None and tn not in tiles:
            continue
        off = spec.get("trace_off")
        if off is None:
            continue
        ring = TraceRing(wksp, off, int(spec["trace_depth"]))
        cursor, recs = ring.snapshot()
        evs = [ev.decode(r, names) for r in recs]
        # drop never-written slots a torn cursor read could expose
        out[tn] = [e for e in evs if e["etype"] in ev.NAMES]
        if out[tn]:
            out[tn][0].setdefault("_cursor", cursor)
    return out


def to_chrome(events_by_tile: dict[str, list[dict]],
              topology: str = "fdtpu") -> dict:
    """Decoded events -> a Chrome-trace JSON object (Perfetto opens it
    directly: ui.perfetto.dev 'Open trace file')."""
    pid = 1
    trace_events: list[dict] = [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": f"fdtpu:{topology}"}},
    ]
    tids = {tn: i + 1 for i, tn in enumerate(sorted(events_by_tile))}
    for tn, tid in tids.items():
        trace_events.append({"ph": "M", "pid": pid, "tid": tid,
                             "name": "thread_name",
                             "args": {"name": tn}})
    # frag lineage: the FIRST publisher of a sig starts the flow, every
    # later frag event with the same sig on a different tile binds it
    first_pub: dict[int, tuple] = {}
    for tn, evs in events_by_tile.items():
        for e in evs:
            if e["etype"] == ev.EV_PUBLISH:
                if e["sig"] not in first_pub or \
                        e["ts"] < first_pub[e["sig"]][0]:
                    first_pub[e["sig"]] = (e["ts"], tn)
    for tn, evs in sorted(events_by_tile.items()):
        tid = tids[tn]
        for e in evs:
            ts_us = e["ts"] / 1e3
            frag = e["etype"] in ev.FRAG_EVENTS
            args = {k: v for k, v in (
                ("sig", e["sig"] if frag or e["sig"] else None),
                ("link", e["link"]),
                ("count", e["count"] or None)) if v is not None}
            if e["etype"] == ev.EV_CHAOS:
                args["action"] = ev.CHAOS_ACTION_NAMES.get(
                    e["count"], "?")
            if e["etype"] in ev.SPANS:
                trace_events.append(
                    {"ph": "X", "pid": pid, "tid": tid, "cat": "fdtpu",
                     "name": e["ev"], "ts": (e["ts"] - e["arg"]) / 1e3,
                     "dur": e["arg"] / 1e3, "args": args})
            else:
                trace_events.append(
                    {"ph": "i", "pid": pid, "tid": tid, "cat": "fdtpu",
                     "name": e["ev"], "ts": ts_us, "s": "t",
                     "args": args})
            if e["etype"] in ev.FRAG_EVENTS:
                fp = first_pub.get(e["sig"])
                fid = f"{e['sig']:#x}"
                if fp and fp[1] == tn and e["etype"] == ev.EV_PUBLISH \
                        and e["ts"] == fp[0]:
                    trace_events.append(
                        {"ph": "s", "pid": pid, "tid": tid,
                         "cat": "frag", "name": "frag", "id": fid,
                         "ts": ts_us})
                elif fp and fp[1] != tn:
                    trace_events.append(
                        {"ph": "f", "bp": "e", "pid": pid, "tid": tid,
                         "cat": "frag", "name": "frag", "id": fid,
                         "ts": ts_us})
    return {"traceEvents": trace_events, "displayTimeUnit": "ms",
            "otherData": {"topology": topology,
                          "source": "fdtrace"}}


def lineage(events_by_tile: dict[str, list[dict]]) -> dict[int, list]:
    """sig -> [(ts, tile, ev-name, link), ...] time-ordered: one
    frag's journey across every ring it crossed."""
    chains: dict[int, list] = {}
    for tn, evs in events_by_tile.items():
        for e in evs:
            # frag events ALWAYS carry a meaningful sig — 0 is a real
            # value (synth sigs start at 0), not an absence marker
            if e["etype"] in ev.FRAG_EVENTS:
                chains.setdefault(e["sig"], []).append(
                    (e["ts"], tn, e["ev"], e["link"]))
    for c in chains.values():
        c.sort()
    return chains


def summary(events_by_tile: dict[str, list[dict]]) -> str:
    """Text report: per-link publish->consume latency (frag lineage
    deltas) + per-tile wait/backpressure attribution — the 'where did
    the microseconds go' answer counters cannot give."""
    lines = ["fdtrace summary", "==============="]
    # per-link latency: each consume is measured against the MOST
    # RECENT publish in the sig's chain (per-hop delta, not cumulative
    # from the chain's origin — a slow hop must blame itself)
    per_link: dict[str, list[int]] = {}
    for chain in lineage(events_by_tile).values():
        pub_ts = None
        for ts, _tn, name, link in chain:
            if name == "publish":
                pub_ts = ts
            elif name == "consume" and pub_ts is not None and link:
                per_link.setdefault(link, []).append(ts - pub_ts)
    if per_link:
        lines.append("")
        lines.append(f"{'link':<20}{'frags':>8}{'p50_us':>10}"
                     f"{'p99_us':>10}{'max_us':>10}")
        for link, dts in sorted(per_link.items()):
            dts.sort()
            p = lambda q: dts[min(len(dts) - 1,
                                  int(q * len(dts)))] / 1e3
            lines.append(f"{link:<20}{len(dts):>8}{p(0.50):>10.1f}"
                         f"{p(0.99):>10.1f}{dts[-1] / 1e3:>10.1f}")
    # per-tile attribution
    lines.append("")
    lines.append(f"{'tile':<14}{'events':>8}{'wait_ms':>10}"
                 f"{'bp_ms':>8}{'work_ms':>9}{'tpu_ms':>8}  notes")
    for tn, evs in sorted(events_by_tile.items()):
        acc = {k: 0 for k in ("wait", "backpressure", "work", "tpu")}
        notes = []
        for e in evs:
            if e["etype"] == ev.EV_WAIT:
                acc["wait"] += e["arg"]
            elif e["etype"] == ev.EV_BACKPRESSURE:
                acc["backpressure"] += e["arg"]
            elif e["etype"] == ev.EV_WORK:
                acc["work"] += e["arg"]
            elif e["etype"] in (ev.EV_TPU_DISPATCH, ev.EV_TPU_READBACK):
                acc["tpu"] += e["arg"]
            elif e["etype"] == ev.EV_CPU_FALLBACK:
                notes.append("CPU-FALLBACK")
            elif e["etype"] == ev.EV_CHAOS:
                notes.append("chaos:" + ev.CHAOS_ACTION_NAMES.get(
                    e["count"], "?"))
            elif e["etype"] in (ev.EV_WATCHDOG, ev.EV_RESTART,
                                ev.EV_DOWN):
                notes.append(e["ev"])
            elif e["etype"] == ev.EV_SLO:
                notes.append(f"SLO-BREACH#{e['count']}")
        lines.append(
            f"{tn:<14}{len(evs):>8}{acc['wait'] / 1e6:>10.2f}"
            f"{acc['backpressure'] / 1e6:>8.2f}"
            f"{acc['work'] / 1e6:>9.2f}{acc['tpu'] / 1e6:>8.2f}  "
            + " ".join(notes[:6]))
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# black-box dumps (supervisor integration)
# ---------------------------------------------------------------------------

def blackbox_path(topology: str, tile: str) -> str:
    return f"/dev/shm/fdtpu_{topology}.blackbox.{tile}.json"


def dump_blackbox(plan: dict, wksp, tile: str, reason: str) -> str | None:
    """Snapshot a (dying) tile's ring to a JSON file — the flight
    recorder's raison d'etre: called by the supervisor on a watchdog
    trip or abnormal death, BEFORE the restart wipes the live state.
    The dump carries both the decoded event list and a ready-to-open
    Chrome-trace object. Returns the path (None if the tile is
    untraced)."""
    from ..utils.tempo import monotonic_ns
    evs = read_rings(plan, wksp, tiles=[tile]).get(tile)
    if evs is None:
        return None
    path = blackbox_path(plan.get("topology", "?"), tile)
    doc = {
        "topology": plan.get("topology", "?"),
        "tile": tile,
        "reason": reason,
        "dumped_at_ns": monotonic_ns(),
        "events": [{k: v for k, v in e.items()
                    if not k.startswith("_")} for e in evs],
        "chrome": to_chrome({tile: evs}, plan.get("topology", "?")),
    }
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
