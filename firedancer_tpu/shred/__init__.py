"""Shred path: wire format, FEC-set merkle commitment, shredder.

The turbine block-propagation data plane (ref: src/ballet/shred/,
src/disco/shred/): entry batches are split into 1203-byte merkle data
shreds, Reed-Solomon-extended with 1228-byte parity shreds (the RS
encode runs as a GF(2^8) bit-matrix matmul on the MXU, ops/reedsol.py),
committed to with a 20-byte-node SHA-256 merkle tree whose root the
leader signs, and (optionally) chained root-to-root across FEC sets.
"""
from .fec_resolver import CompletedFec, FecResolver
from .format import (DataShred, CodeShred, parse_shred, SHRED_MAX_SZ,
                     SHRED_MIN_SZ)
from .merkle import MerkleTree20, shred_merkle_leaf
from .shred_dest import ClusterNode, ShredDest
from .store import FecStore, Reassembler, Slice
from .shredder import Shredder, FecSet, count_fec_sets, count_data_shreds, \
    count_parity_shreds

__all__ = ["DataShred", "CodeShred", "parse_shred", "SHRED_MAX_SZ",
           "SHRED_MIN_SZ", "MerkleTree20", "shred_merkle_leaf",
           "Shredder", "FecSet", "count_fec_sets", "count_data_shreds",
           "count_parity_shreds", "FecResolver", "CompletedFec",
           "ClusterNode", "ShredDest", "FecStore", "Reassembler",
           "Slice"]
