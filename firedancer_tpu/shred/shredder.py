"""Shredder: entry batch -> signed merkle FEC sets.

Pipeline per FEC set (ref: src/disco/shred/fd_shredder.c:130-320):
  1. split the entry-batch chunk into data-shred payloads (Agave's
     sizing policy: 31840-byte normal sets of 32x995B, one odd-sized
     tail set — count_* below reproduce the reference's closed-form
     tables, fd_shredder.h:171-234)
  2. Reed-Solomon-extend the data shreds' post-signature bytes into
     parity shreds — on device this is the GF(2^8) bit-matrix matmul
     (ops/reedsol.py) stretched over all byte positions at once
  3. hash every shred's merkle region into a leaf, build the
     20-byte-node tree, write each shred's inclusion proof
  4. sign the root (sign_fn is the keyguard seam — the identity key
     holder is elsewhere, ref src/disco/keyguard/fd_keyguard.h), stamp
     the signature into every shred
  5. chained variants thread root_{i} into set_{i+1}'s payload region

The RS + leaf-sha256 stages are the batch-shaped hot path; both have
device kernels. The framing/bookkeeping here is host-side by design
(tiny, branchy, per-set).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..utils import gf256
from . import format as fmt
from .merkle import MerkleTree20, bmtree_depth, shred_merkle_leaf

# parity shreds for a given data shred count in the "normal" regime
# (Agave's table, fd_shredder.h:45-49); beyond 32 data shreds parity
# count equals data count (fd_shredder.h:218-219)
DATA_TO_PARITY = (
    0, 17, 18, 19, 19, 20, 21, 21,
    22, 23, 23, 24, 24, 25, 25, 26,
    26, 26, 27, 27, 28, 28, 29, 29,
    29, 30, 30, 31, 31, 31, 32, 32, 32)

NORMAL_FEC_PAYLOAD = 31840      # 32 data shreds x 995 B
CHAINED_FEC_PAYLOAD = 30816     # 32 x 963
RESIGNED_FEC_PAYLOAD = 28768    # 32 x 899

# odd-set payload-per-shred tiers: (max remaining bytes, payload/shred)
# for each (chained, resigned) regime (fd_shredder.h:186-234)
_TIERS = {
    (False, False): ((9135, 1015), (31840, 995), (62400, 975), (None, 955)),
    (True, False): ((8847, 983), (30816, 963), (60352, 943), (None, 923)),
    (True, True): ((8271, 919), (28768, 899), (56256, 879), (None, 859)),
}


def _fec_payload(chained: bool, resigned: bool) -> int:
    if resigned:
        return RESIGNED_FEC_PAYLOAD
    return CHAINED_FEC_PAYLOAD if chained else NORMAL_FEC_PAYLOAD


def count_fec_sets(sz: int, chained: bool, resigned: bool = False) -> int:
    pl = _fec_payload(chained, resigned)
    return max(sz, 2 * pl - 1) // pl


def _odd_set_data_cnt(rem: int, chained: bool, resigned: bool) -> int:
    for bound, payload in _TIERS[(chained, resigned)]:
        if bound is None or rem <= bound:
            return max(1, (rem + payload - 1) // payload)
    raise AssertionError


def count_data_shreds(sz: int, chained: bool, resigned: bool = False) -> int:
    normal = count_fec_sets(sz, chained, resigned) - 1
    rem = sz - normal * _fec_payload(chained, resigned)
    return normal * 32 + _odd_set_data_cnt(rem, chained, resigned)


def count_parity_shreds(sz: int, chained: bool,
                        resigned: bool = False) -> int:
    normal = count_fec_sets(sz, chained, resigned) - 1
    rem = sz - normal * _fec_payload(chained, resigned)
    d = _odd_set_data_cnt(rem, chained, resigned)
    return normal * 32 + (DATA_TO_PARITY[d] if d < len(DATA_TO_PARITY)
                          else d)


@dataclass
class FecSet:
    """One produced FEC set: wire-ready shreds + the signed root."""
    data_shreds: list
    parity_shreds: list
    merkle_root: bytes
    fec_set_idx: int


class Shredder:
    """Stateful per-slot shredder (idx bookkeeping across batches,
    fd_shredder.h:249-266)."""

    def __init__(self, sign_fn, shred_version: int = 0,
                 rs_backend: str = "host", tpool=None):
        """tpool: optional utils.tpool.TPool — parallelizes the
        per-shred merkle leaf hashing (sha256 releases the GIL, the
        fd_tpool_exec_all pattern; P5 on the host side)."""
        self.sign_fn = sign_fn
        self.shred_version = shred_version
        self.rs_backend = rs_backend
        self.tpool = tpool
        self.slot = None
        self.data_idx = 0
        self.parity_idx = 0

    def _set_slot(self, slot: int):
        if slot != self.slot:
            self.slot = slot
            self.data_idx = 0
            self.parity_idx = 0

    def _rs_encode(self, data_mat: np.ndarray, p: int) -> np.ndarray:
        if self.rs_backend == "jax":
            from ..ops import reedsol
            return np.asarray(reedsol.encode(data_mat, p))
        return gf256.encode(data_mat, p)

    def shred_batch(self, entry_batch: bytes, slot: int, parent_off: int,
                    ref_tick: int, block_complete: bool,
                    chained_root: bytes | None = None) -> list:
        """Shred one entry batch; returns its FEC sets in order.

        chained_root: 32-byte root of the previous FEC set to chain
        from (enables the chained variants; resigned is chained +
        block_complete, fd_shredder.c:154-155). The retransmitter
        signature slot of resigned shreds is left zeroed for the
        turbine retransmitter to fill.
        """
        assert entry_batch, "empty batch"
        self._set_slot(slot)
        chained = chained_root is not None
        sets = []
        offset = 0
        sz = len(entry_batch)
        while offset < sz:
            remaining = sz - offset
            resigned = chained and block_complete
            fec_pl = _fec_payload(chained, resigned)
            chunk = fec_pl if remaining >= 2 * fec_pl else remaining
            last_in_batch = offset + chunk == sz
            fs = self._one_fec_set(
                entry_batch[offset:offset + chunk], slot, parent_off,
                ref_tick, block_complete, last_in_batch, chained_root)
            offset += chunk
            if chained:
                chained_root = fs.merkle_root
            sets.append(fs)
        return sets

    def _one_fec_set(self, chunk: bytes, slot: int, parent_off: int,
                     ref_tick: int, block_complete: bool,
                     last_in_batch: bool,
                     chained_root: bytes | None) -> FecSet:
        chained = chained_root is not None
        # resigned is chained + block_complete (fd_shredder.c:155)
        resigned = chained and block_complete
        d_cnt = count_data_shreds(len(chunk), chained, resigned)
        p_cnt = count_parity_shreds(len(chunk), chained, resigned)
        tree_depth = bmtree_depth(d_cnt + p_cnt) - 1
        if chained:
            d_type = (fmt.TYPE_MERKLE_DATA_CHAINED_RESIGNED if resigned
                      else fmt.TYPE_MERKLE_DATA_CHAINED)
            c_type = (fmt.TYPE_MERKLE_CODE_CHAINED_RESIGNED if resigned
                      else fmt.TYPE_MERKLE_CODE_CHAINED)
        else:
            d_type, c_type = fmt.TYPE_MERKLE_DATA, fmt.TYPE_MERKLE_CODE
        d_variant = d_type | tree_depth
        c_variant = c_type | tree_depth
        payload_cap = fmt.payload_capacity(d_variant)
        rs_region = payload_cap + fmt.DATA_HEADER_SZ - fmt.SIGNATURE_SZ

        flags_last = ((0x80 if block_complete else 0) |
                      0x40) if last_in_batch else 0
        fec_set_idx = self.data_idx

        # -- data shreds (headers + payload; sig/proof patched below) --
        data_wires = []
        off = 0
        for i in range(d_cnt):
            pl = chunk[off:off + payload_cap]
            off += len(pl)
            flags = (ref_tick & fmt.REF_TICK_MASK) | \
                (flags_last if i == d_cnt - 1 else 0)
            s = fmt.DataShred(
                signature=bytes(64), variant=d_variant, slot=slot,
                idx=self.data_idx + i, version=self.shred_version,
                fec_set_idx=fec_set_idx, parent_off=parent_off,
                flags=flags, size=fmt.DATA_HEADER_SZ + len(pl),
                payload=pl, chained_root=chained_root,
                proof=tuple([bytes(20)] * tree_depth),
                retransmit_sig=bytes(64) if resigned else None)
            data_wires.append(bytearray(fmt.pack_data_shred(s)))
        assert off == len(chunk), (off, len(chunk))

        # -- RS parity over the post-signature region (MXU-shaped) --
        data_mat = np.stack([
            np.frombuffer(bytes(w[64:64 + rs_region]), np.uint8)
            for w in data_wires])
        parity_mat = self._rs_encode(data_mat, p_cnt)

        code_wires = []
        for j in range(p_cnt):
            s = fmt.CodeShred(
                signature=bytes(64), variant=c_variant, slot=slot,
                idx=self.parity_idx + j, version=self.shred_version,
                fec_set_idx=fec_set_idx, data_cnt=d_cnt, code_cnt=p_cnt,
                code_idx=j, payload=parity_mat[j].tobytes(),
                chained_root=chained_root,
                proof=tuple([bytes(20)] * tree_depth),
                retransmit_sig=bytes(64) if resigned else None)
            code_wires.append(bytearray(fmt.pack_code_shred(s)))

        # -- merkle tree over all shreds' leaf regions --
        d_region = fmt.data_merkle_region_sz(d_variant)
        c_region = fmt.code_merkle_region_sz(c_variant)
        regions = [bytes(w[64:64 + d_region]) for w in data_wires] \
            + [bytes(w[64:64 + c_region]) for w in code_wires]
        if self.tpool is not None:
            leaves = self.tpool.map_chunks(
                lambda chunk: [shred_merkle_leaf(r) for r in chunk],
                regions)
        else:
            leaves = [shred_merkle_leaf(r) for r in regions]
        tree = MerkleTree20(leaves)
        root = tree.root
        sig = self.sign_fn(root)
        assert len(sig) == 64

        for i, w in enumerate(data_wires):
            w[:64] = sig
            m_off = fmt.merkle_off(d_variant)
            for k, node in enumerate(tree.proof(i)):
                w[m_off + 20 * k:m_off + 20 * (k + 1)] = node
        for j, w in enumerate(code_wires):
            w[:64] = sig
            m_off = fmt.merkle_off(c_variant)
            for k, node in enumerate(tree.proof(d_cnt + j)):
                w[m_off + 20 * k:m_off + 20 * (k + 1)] = node

        self.data_idx += d_cnt
        self.parity_idx += p_cnt
        return FecSet([bytes(w) for w in data_wires],
                      [bytes(w) for w in code_wires], root, fec_set_idx)
