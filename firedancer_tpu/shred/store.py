"""FEC payload store + slice reassembly.

Store (ref: src/disco/store/fd_store.h:1-40): the shared map from FEC
set merkle root -> payload that decouples shred receipt from replay;
insert/query/remove plus rooting-driven publish pruning. The reference
backs it with a lock-striped wksp map; here it is the single-writer
host-side equivalent with bounded capacity and FIFO eviction.

Reasm (ref: src/discof/reasm/ — FEC sets -> ordered slices): per slot,
completed FEC sets arrive keyed by fec_set_idx (= first data shred idx)
with data_complete markers; a slice is the contiguous run of payload
from the last emitted boundary through a batch-complete set. Slices
feed the replay tile in order; the final slice of the slot carries
slot_complete.
"""
from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass


class FecStore:
    def __init__(self, max_sets: int = 4096):
        self.max_sets = max_sets
        # merkle_root -> (slot, fec_set_idx, payload bytes)
        self._map: OrderedDict[bytes, tuple] = OrderedDict()
        self.metrics = {"inserts": 0, "dup": 0, "evicted": 0,
                        "pruned": 0}

    def insert(self, merkle_root: bytes, slot: int, fec_set_idx: int,
               payload: bytes) -> bool:
        if merkle_root in self._map:
            self.metrics["dup"] += 1
            return False
        while len(self._map) >= self.max_sets:
            self._map.popitem(last=False)
            self.metrics["evicted"] += 1
        self._map[merkle_root] = (slot, fec_set_idx, payload)
        self.metrics["inserts"] += 1
        return True

    def query(self, merkle_root: bytes):
        v = self._map.get(merkle_root)
        return None if v is None else v[2]

    def publish(self, root_slot: int):
        """Drop sets below the consensus root (rooting-driven prune)."""
        dead = [k for k, (s, _, _) in self._map.items() if s < root_slot]
        for k in dead:
            del self._map[k]
        self.metrics["pruned"] += len(dead)

    def __len__(self):
        return len(self._map)


@dataclass
class Slice:
    slot: int
    first_fec_idx: int
    payload: bytes            # concatenated entry-batch bytes
    slot_complete: bool


class Reassembler:
    """CompletedFec stream -> ordered slices per slot."""

    def __init__(self):
        # slot -> {state}
        self._slots: dict[int, dict] = {}
        # tombstones: slots already fully emitted — a late duplicate
        # FEC set (turbine retransmit / repair race) must not rebuild
        # empty state and re-emit the same slice to replay
        self._done: set[int] = set()
        self._root = 0               # slots below never re-emit
        self.metrics = {"fecs": 0, "slices": 0, "done_slots": 0,
                        "late_dup": 0}

    def _st(self, slot: int) -> dict:
        st = self._slots.get(slot)
        if st is None:
            st = self._slots[slot] = {
                "sets": {},          # fec_set_idx -> (payload, n_shreds,
                                     #   data_complete, slot_complete)
                "next_idx": 0,       # next expected fec_set_idx
                "run_start": 0,      # first fec idx of the open slice
                "buf": [],           # payloads of the open slice
            }
        return st

    def add_fec(self, fec) -> list[Slice]:
        """fec: shred.fec_resolver.CompletedFec. Returns newly completed
        slices (possibly several when a gap fills)."""
        self.metrics["fecs"] += 1
        if fec.slot in self._done or fec.slot < self._root:
            # tombstoned, or below the published root: either way this
            # slot's slices are history and must never re-emit
            self.metrics["late_dup"] += 1
            return []
        st = self._st(fec.slot)
        payload = b"".join(fec.data_payloads)
        st["sets"][fec.fec_set_idx] = (
            payload, len(fec.data_payloads), fec.data_complete,
            fec.slot_complete)
        out = []
        # advance the contiguous frontier
        while st["next_idx"] in st["sets"]:
            pl, n, data_done, slot_done = st["sets"][st["next_idx"]]
            st["buf"].append(pl)
            st["next_idx"] += n
            if data_done or slot_done:
                out.append(Slice(fec.slot, st["run_start"],
                                 b"".join(st["buf"]), slot_done))
                self.metrics["slices"] += 1
                st["buf"] = []
                st["run_start"] = st["next_idx"]
                if slot_done:
                    self.metrics["done_slots"] += 1
                    del self._slots[fec.slot]
                    self._done.add(fec.slot)
                    return out
        return out

    def publish(self, root_slot: int):
        """Prune state below the root. Tombstones below the root can be
        dropped because the root itself now guards re-emission (the
        `slot < _root` reject in add_fec)."""
        self._root = max(self._root, root_slot)
        self._slots = {s: st for s, st in self._slots.items()
                       if s >= root_slot}
        self._done = {s for s in self._done if s >= root_slot}
