"""Solana merkle shred wire format — pack / parse / offset algebra.

Layout contract (ref: src/ballet/shred/fd_shred.h:9-35, 183-260):

    [0x00] signature            64B   ed25519 over the FEC-set merkle root
    [0x40] variant               1B   type nibble | proof-node count
    [0x41] slot                  8B   le
    [0x49] idx                   4B   le   shred index within slot
    [0x4d] version               2B   le   shred version (chain id hash)
    [0x4f] fec_set_idx           4B   le
    data:  parent_off 2B | flags 1B | size 2B           (header = 0x58)
    code:  data_cnt   2B | code_cnt 2B | idx 2B         (header = 0x59)
    payload ...
    [chained merkle root 32B]                 (chained variants)
    [proof: cnt x 20B nodes]                  (merkle variants)
    [retransmitter signature 64B]             (resigned variants)

Merkle data shreds are always SHRED_MIN_SZ=1203 bytes on the wire; code
shreds are always SHRED_MAX_SZ=1228 (fd_shred.h:292-299). The variant's
low nibble is the number of non-root proof nodes (fd_shred.h:315-324);
chain/merkle offsets are computed back from the end of the shred
(fd_shred.h:385-394, 434-443).

This is the host-side format layer (wire bytes in numpy/python); the
batched device kernels (leaf hashing, RS parity) consume the payload
regions it defines.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass

SHRED_MAX_SZ = 1228
SHRED_MIN_SZ = 1203
DATA_HEADER_SZ = 0x58
CODE_HEADER_SZ = 0x59
SIGNATURE_SZ = 64
MERKLE_ROOT_SZ = 32
MERKLE_NODE_SZ = 20
VARIANT_OFF = 0x40

# type nibbles (high 4 bits of the variant byte, fd_shred.h:105-121)
TYPE_LEGACY_DATA = 0xA0
TYPE_LEGACY_CODE = 0x50
TYPE_MERKLE_DATA = 0x80
TYPE_MERKLE_CODE = 0x40
TYPE_MERKLE_DATA_CHAINED = 0x90
TYPE_MERKLE_CODE_CHAINED = 0x60
TYPE_MERKLE_DATA_CHAINED_RESIGNED = 0xB0
TYPE_MERKLE_CODE_CHAINED_RESIGNED = 0x70

TYPEMASK_DATA = TYPE_MERKLE_DATA
TYPEMASK_CODE = TYPE_MERKLE_CODE

# data flags byte (fd_shred.h:142-150)
FLAG_SLOT_COMPLETE = 0x80
FLAG_DATA_COMPLETE = 0x40
REF_TICK_MASK = 0x3F

MAX_SHREDS_PER_SLOT = 1 << 15          # FD_SHRED_BLK_MAX


def shred_type(variant: int) -> int:
    """High nibble, normalized to the FD_SHRED_TYPE_* values."""
    return variant & 0xF0


def is_data(variant: int) -> bool:
    t = shred_type(variant)
    return bool(t & TYPEMASK_DATA) and t != TYPE_LEGACY_CODE


def is_code(variant: int) -> bool:
    return not is_data(variant)


def is_chained(variant: int) -> bool:
    return shred_type(variant) in (
        TYPE_MERKLE_DATA_CHAINED, TYPE_MERKLE_CODE_CHAINED,
        TYPE_MERKLE_DATA_CHAINED_RESIGNED, TYPE_MERKLE_CODE_CHAINED_RESIGNED)


def is_resigned(variant: int) -> bool:
    return shred_type(variant) in (
        TYPE_MERKLE_DATA_CHAINED_RESIGNED, TYPE_MERKLE_CODE_CHAINED_RESIGNED)


def merkle_cnt(variant: int) -> int:
    """Non-root proof node count (low nibble of merkle variants)."""
    if shred_type(variant) in (TYPE_LEGACY_DATA, TYPE_LEGACY_CODE):
        return 0
    return variant & 0x0F


def shred_sz(variant: int) -> int:
    """Wire size (merkle variants only here; legacy unsupported)."""
    return SHRED_MAX_SZ if is_code(variant) else SHRED_MIN_SZ


def merkle_off(variant: int) -> int:
    """Byte offset of the proof node vector (fd_shred.h:385-394)."""
    return (shred_sz(variant) - MERKLE_NODE_SZ * merkle_cnt(variant)
            - (SIGNATURE_SZ if is_resigned(variant) else 0))


def chain_off(variant: int) -> int:
    """Byte offset of the chained merkle root (fd_shred.h:434-443)."""
    return (shred_sz(variant) - MERKLE_ROOT_SZ
            - MERKLE_NODE_SZ * merkle_cnt(variant)
            - (SIGNATURE_SZ if is_resigned(variant) else 0))


def payload_capacity(variant: int) -> int:
    """Max payload bytes a data shred of this variant can carry
    (1115 - 20*proof_cnt - 32*chained - 64*resigned,
    fd_shredder.c:188)."""
    return (1115 - MERKLE_NODE_SZ * merkle_cnt(variant)
            - (MERKLE_ROOT_SZ if is_chained(variant) else 0)
            - (SIGNATURE_SZ if is_resigned(variant) else 0))


def data_merkle_region_sz(variant: int) -> int:
    """Bytes after the signature covered by this data shred's merkle
    leaf: headers-past-sig + payload capacity + chained root
    (fd_shredder.c:189-190)."""
    return (DATA_HEADER_SZ - SIGNATURE_SZ + payload_capacity(variant)
            + (MERKLE_ROOT_SZ if is_chained(variant) else 0))


def code_merkle_region_sz(variant: int) -> int:
    """Same for code shreds (fd_shredder.c:191)."""
    return data_merkle_region_sz(variant) + CODE_HEADER_SZ - SIGNATURE_SZ


class ShredParseError(ValueError):
    pass


@dataclass(frozen=True)
class DataShred:
    signature: bytes
    variant: int
    slot: int
    idx: int
    version: int
    fec_set_idx: int
    parent_off: int
    flags: int
    size: int                 # header + actual (unpadded) payload bytes
    payload: bytes            # unpadded payload (size - DATA_HEADER_SZ)
    chained_root: bytes | None
    proof: tuple              # proof-node bytes, leaf->root order
    retransmit_sig: bytes | None

    @property
    def ref_tick(self) -> int:
        return self.flags & REF_TICK_MASK

    @property
    def slot_complete(self) -> bool:
        return bool(self.flags & FLAG_SLOT_COMPLETE)

    @property
    def data_complete(self) -> bool:
        return bool(self.flags & FLAG_DATA_COMPLETE)


@dataclass(frozen=True)
class CodeShred:
    signature: bytes
    variant: int
    slot: int
    idx: int
    version: int
    fec_set_idx: int
    data_cnt: int
    code_cnt: int
    code_idx: int
    payload: bytes            # RS parity bytes (full capacity)
    chained_root: bytes | None
    proof: tuple
    retransmit_sig: bytes | None


def _common_hdr(signature: bytes, variant: int, slot: int, idx: int,
                version: int, fec_set_idx: int) -> bytes:
    assert len(signature) == SIGNATURE_SZ
    return signature + struct.pack("<BQIHI", variant, slot, idx, version,
                                   fec_set_idx)


def _tail(buf: bytearray, variant: int, chained_root, proof,
          retransmit_sig):
    if is_chained(variant):
        assert chained_root is not None and len(chained_root) == 32
        off = chain_off(variant)
        buf[off:off + 32] = chained_root
    cnt = merkle_cnt(variant)
    assert len(proof) == cnt, (len(proof), cnt)
    off = merkle_off(variant)
    for i, node in enumerate(proof):
        assert len(node) == MERKLE_NODE_SZ
        buf[off + i * 20:off + (i + 1) * 20] = node
    if is_resigned(variant):
        assert retransmit_sig is not None and len(retransmit_sig) == 64
        buf[-64:] = retransmit_sig


def pack_data_shred(s: DataShred) -> bytes:
    buf = bytearray(SHRED_MIN_SZ)
    buf[:0x53] = _common_hdr(s.signature, s.variant, s.slot, s.idx,
                             s.version, s.fec_set_idx)
    buf[0x53:0x58] = struct.pack("<HBH", s.parent_off, s.flags, s.size)
    cap = payload_capacity(s.variant)
    assert len(s.payload) <= cap
    assert s.size == DATA_HEADER_SZ + len(s.payload)
    buf[0x58:0x58 + len(s.payload)] = s.payload
    _tail(buf, s.variant, s.chained_root, s.proof, s.retransmit_sig)
    return bytes(buf)


def pack_code_shred(s: CodeShred) -> bytes:
    buf = bytearray(SHRED_MAX_SZ)
    buf[:0x53] = _common_hdr(s.signature, s.variant, s.slot, s.idx,
                             s.version, s.fec_set_idx)
    buf[0x53:0x59] = struct.pack("<HHH", s.data_cnt, s.code_cnt, s.code_idx)
    cap = payload_capacity(s.variant) + DATA_HEADER_SZ - SIGNATURE_SZ
    assert len(s.payload) == cap, (len(s.payload), cap)
    buf[0x59:0x59 + cap] = s.payload
    _tail(buf, s.variant, s.chained_root, s.proof, s.retransmit_sig)
    return bytes(buf)


def parse_shred(b: bytes):
    """Wire bytes -> DataShred | CodeShred, with the same validation
    gates as the reference parser (fd_shred.c fd_shred_parse): exact
    wire size for the variant, size-field bounds, proof fit."""
    if len(b) < VARIANT_OFF + 1:
        raise ShredParseError("short")
    variant = b[VARIANT_OFF]
    t = shred_type(variant)
    if t in (TYPE_LEGACY_DATA, TYPE_LEGACY_CODE):
        raise ShredParseError("legacy shreds unsupported")
    if t not in (TYPE_MERKLE_DATA, TYPE_MERKLE_CODE,
                 TYPE_MERKLE_DATA_CHAINED, TYPE_MERKLE_CODE_CHAINED,
                 TYPE_MERKLE_DATA_CHAINED_RESIGNED,
                 TYPE_MERKLE_CODE_CHAINED_RESIGNED):
        raise ShredParseError(f"bad type nibble {t:#x}")
    if len(b) != shred_sz(variant):
        raise ShredParseError("wire size mismatch")
    cnt = merkle_cnt(variant)
    m_off = merkle_off(variant)
    pay_end = chain_off(variant) if is_chained(variant) else m_off
    if pay_end < (DATA_HEADER_SZ if is_data(variant) else CODE_HEADER_SZ):
        raise ShredParseError("proof overruns header")
    signature = b[:64]
    slot, idx, version, fec_set_idx = struct.unpack_from("<QIHI", b, 0x41)
    chained_root = (bytes(b[chain_off(variant):chain_off(variant) + 32])
                    if is_chained(variant) else None)
    proof = tuple(bytes(b[m_off + 20 * i:m_off + 20 * (i + 1)])
                  for i in range(cnt))
    rsig = bytes(b[-64:]) if is_resigned(variant) else None
    if is_data(variant):
        parent_off, flags, size = struct.unpack_from("<HBH", b, 0x53)
        if size < DATA_HEADER_SZ or size > pay_end:
            raise ShredParseError("bad size field")
        return DataShred(signature, variant, slot, idx, version,
                         fec_set_idx, parent_off, flags, size,
                         bytes(b[0x58:size]), chained_root, proof, rsig)
    data_cnt, code_cnt, code_idx = struct.unpack_from("<HHH", b, 0x53)
    if code_idx >= code_cnt or code_cnt == 0 or data_cnt == 0:
        raise ShredParseError("bad code header")
    return CodeShred(signature, variant, slot, idx, version, fec_set_idx,
                     data_cnt, code_cnt, code_idx,
                     bytes(b[0x59:0x59 + payload_capacity(variant)
                             + DATA_HEADER_SZ - SIGNATURE_SZ]),
                     chained_root, proof, rsig)
