"""FEC resolver: shreds off the wire -> completed, validated FEC sets.

The non-leader half of the shred tile (ref: src/disco/shred/
fd_fec_resolver.c): group incoming shreds by (slot, fec_set_idx),
validate each against the set's signed merkle root via its inclusion
proof, verify the leader's signature over the root once per set, and on
reaching data_cnt total shreds Reed-Solomon-recover any missing data
shreds. A completed set re-derives the FULL merkle tree (recovered data
+ re-encoded parity) and requires the recomputed root to equal the
signed root — recovery can never launder corrupted bytes into the block
(the reference's recovered-shred re-validation).

Conflicting roots for one set key are surfaced as equivocation
(ref: src/choreo/eqvoc/fd_eqvoc.h — same key, different merkle root),
not silently dropped.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..utils import gf256
from . import format as fmt
from .merkle import MerkleTree20, root_from_proof, shred_merkle_leaf


@dataclass
class CompletedFec:
    slot: int
    fec_set_idx: int
    merkle_root: bytes
    data_payloads: list        # per data shred, size-trimmed payload bytes
    data_complete: bool        # last shred carries DATA_COMPLETE
    slot_complete: bool
    recovered_cnt: int


@dataclass
class _SetState:
    root: bytes | None = None
    signature: bytes | None = None
    sig_ok: bool = False
    data: dict = field(default_factory=dict)     # tree pos -> wire bytes
    code: dict = field(default_factory=dict)     # code_idx -> wire bytes
    data_cnt: int | None = None                  # from any code shred
    code_cnt: int | None = None
    variant_data: int | None = None
    variant_code: int | None = None
    done: bool = False


class FecError(ValueError):
    pass


class FecResolver:
    """verify_sig(sig, root, slot) -> bool is the keyguard-side seam
    (leader schedule lookup + ed25519 verify; batched on device in the
    gossvf-style pipeline)."""

    def __init__(self, verify_sig, max_pending: int = 1024):
        self.verify_sig = verify_sig
        self.max_pending = max_pending
        self.sets: dict[tuple[int, int], _SetState] = {}
        self.metrics = {"shreds": 0, "bad_proof": 0, "bad_sig": 0,
                        "eqvoc": 0, "completed": 0, "recovered": 0,
                        "dup": 0, "root_mismatch": 0}

    # -- per-shred ingest ---------------------------------------------------

    def add_shred(self, wire: bytes):
        """Returns (CompletedFec | None, EquivocationKey | None)."""
        self.metrics["shreds"] += 1
        s = fmt.parse_shred(wire)
        variant = wire[fmt.VARIANT_OFF]
        is_data = fmt.is_data(variant)
        key = (s.slot, s.fec_set_idx)
        st = self.sets.get(key)
        if st is None:
            if len(self.sets) >= self.max_pending:
                # evict the oldest pending set (reference uses a fixed
                # pool with FIFO reuse)
                self.sets.pop(next(iter(self.sets)))
            st = self.sets[key] = _SetState()
        if st.done:
            self.metrics["dup"] += 1
            return None, None

        # tree position + merkle region
        if is_data:
            pos = s.idx - s.fec_set_idx
            region = fmt.data_merkle_region_sz(variant)
        else:
            if st.data_cnt is None:
                st.data_cnt, st.code_cnt = s.data_cnt, s.code_cnt
            elif (st.data_cnt, st.code_cnt) != (s.data_cnt, s.code_cnt):
                self.metrics["eqvoc"] += 1
                return None, key
            pos = s.data_cnt + s.code_idx
            region = fmt.code_merkle_region_sz(variant)
        if pos < 0 or region > len(wire):
            self.metrics["bad_proof"] += 1
            return None, None

        # inclusion proof -> root; first shred pins (root, signature)
        leaf = shred_merkle_leaf(wire[64:64 + region])
        root = _root_from_proof(leaf, pos, wire, variant)
        if root is None:
            self.metrics["bad_proof"] += 1
            return None, None
        if st.root is None:
            if not self.verify_sig(wire[:64], root, s.slot):
                self.metrics["bad_sig"] += 1
                return None, None
            st.root, st.signature, st.sig_ok = root, wire[:64], True
        elif st.root != root:
            # same FEC set key, different signed root: equivocation
            self.metrics["eqvoc"] += 1
            return None, key

        if is_data:
            st.variant_data = variant
            if pos in st.data:
                self.metrics["dup"] += 1
                return None, None
            st.data[pos] = wire
        else:
            st.variant_code = variant
            if s.code_idx in st.code:
                self.metrics["dup"] += 1
                return None, None
            st.code[s.code_idx] = wire

        return self._try_complete(key, st), None

    # -- completion / recovery ----------------------------------------------

    def _try_complete(self, key, st: _SetState):
        d = st.data_cnt
        if d is None:
            # no code shred yet: complete only if the data shreds alone
            # cover the set (DATA_COMPLETE seen and all present)
            if not st.data:
                return None
            last = max(st.data)
            ds = fmt.parse_shred(st.data[last])
            if not (ds.data_complete or ds.slot_complete):
                return None
            d = last + 1
            if len(st.data) < d:
                return None
        if len(st.data) + len(st.code) < d:
            return None

        recovered = 0
        if len(st.data) < d:
            if st.variant_code is None:
                return None
            recovered = d - len(st.data)
            if not self._recover(st, d):
                self.metrics["root_mismatch"] += 1
                self.sets.pop(key, None)
                return None
        st.done = True
        self.metrics["completed"] += 1
        self.metrics["recovered"] += recovered

        payloads = []
        slot_complete = data_complete = False
        for i in range(d):
            ds = fmt.parse_shred(st.data[i])
            payloads.append(ds.payload[:ds.size - fmt.DATA_HEADER_SZ])
            slot_complete |= ds.slot_complete
            data_complete |= ds.data_complete
        st.data.clear()
        st.code.clear()
        return CompletedFec(key[0], key[1], st.root, payloads,
                            data_complete, slot_complete, recovered)

    def _recover(self, st: _SetState, d: int) -> bool:
        """RS-recover missing data shreds; True iff the re-derived full
        tree reproduces the signed root."""
        p = st.code_cnt
        vd, vc = st.variant_data, st.variant_code
        if vd is None:
            # all data missing is unrecoverable without knowing the data
            # variant; derive it from the code variant's type pairing
            vd = {fmt.TYPE_MERKLE_CODE: fmt.TYPE_MERKLE_DATA,
                  fmt.TYPE_MERKLE_CODE_CHAINED: fmt.TYPE_MERKLE_DATA_CHAINED,
                  fmt.TYPE_MERKLE_CODE_CHAINED_RESIGNED:
                      fmt.TYPE_MERKLE_DATA_CHAINED_RESIGNED}[
                fmt.shred_type(vc)] | (vc & 0x0F)
        rs_region = fmt.payload_capacity(vd) + fmt.DATA_HEADER_SZ \
            - fmt.SIGNATURE_SZ
        shreds = {}
        for pos, w in st.data.items():
            shreds[pos] = np.frombuffer(
                w[64:64 + rs_region], np.uint8)
        for ci, w in st.code.items():
            pl_off = fmt.CODE_HEADER_SZ
            shreds[d + ci] = np.frombuffer(
                w[pl_off:pl_off + rs_region], np.uint8)
        try:
            data_mat = gf256.recover(shreds, d, p)
        except ValueError:
            return False
        # the chained root rides OUTSIDE the RS region but INSIDE the
        # merkle leaf; it is identical across the set, so recovered
        # shreds take it from any originally-present one
        chain = b""
        if fmt.is_chained(vd):
            if st.data:
                src = next(iter(st.data.values()))
                co = fmt.chain_off(vd)
            else:
                src = next(iter(st.code.values()))
                co = fmt.chain_off(st.variant_code)
            chain = bytes(src[co:co + fmt.MERKLE_ROOT_SZ])
        # rebuild missing data wires (signature + recovered region +
        # chain root; the proof tail is stamped after tree rebuild)
        sz_wire = fmt.shred_sz(vd)
        present_data = set(st.data)
        for i in range(d):
            if i in present_data:
                continue
            w = bytearray(sz_wire)
            w[:64] = st.signature
            w[64:64 + rs_region] = data_mat[i].tobytes()
            if chain:
                co = fmt.chain_off(vd)
                w[co:co + fmt.MERKLE_ROOT_SZ] = chain
            st.data[i] = bytes(w)
        # integrity: recompute the FULL tree (data + re-encoded parity)
        full_parity = gf256.encode(data_mat, p)
        d_region = fmt.data_merkle_region_sz(vd)
        c_region = fmt.code_merkle_region_sz(st.variant_code)
        leaves = [shred_merkle_leaf(st.data[i][64:64 + d_region])
                  for i in range(d)]
        for j in range(p):
            if j in st.code:
                leaves.append(shred_merkle_leaf(
                    st.code[j][64:64 + c_region]))
            else:
                # reconstruct the code shred's merkle region from the
                # common header fields + recomputed parity + chain root
                hdr = _synth_code_header(st, d, p, j)
                leaf_bytes = hdr + full_parity[j].tobytes() + chain
                assert len(leaf_bytes) == c_region, (len(leaf_bytes),
                                                    c_region)
                leaves.append(shred_merkle_leaf(leaf_bytes))
        tree = MerkleTree20(leaves)
        if tree.root != st.root:
            return False
        # stamp proofs into recovered data shreds so downstream
        # re-validation (store, repair served shreds) passes
        m_off = fmt.merkle_off(vd)
        for i in range(d):
            w = bytearray(st.data[i])
            for kk, node in enumerate(tree.proof(i)):
                w[m_off + 20 * kk:m_off + 20 * (kk + 1)] = node
            st.data[i] = bytes(w)
        return True


def _synth_code_header(st: _SetState, d: int, p: int, j: int) -> bytes:
    """Post-signature header of a missing code shred (for leaf
    recomputation): variant..code_idx fields, per fmt.pack_code_shred."""
    import struct
    any_code = next(iter(st.code.values())) if st.code else None
    if any_code is not None:
        slot, = struct.unpack_from("<Q", any_code, 0x41)
        version, = struct.unpack_from("<H", any_code, 0x4D)
        fec_set_idx, = struct.unpack_from("<I", any_code, 0x4F)
        base_idx, = struct.unpack_from("<I", any_code, 0x49)
        base_code_idx, = struct.unpack_from("<H", any_code, 0x57)
        idx = base_idx - base_code_idx + j
    else:
        any_data = st.data[next(iter(st.data))]
        slot, = struct.unpack_from("<Q", any_data, 0x41)
        version, = struct.unpack_from("<H", any_data, 0x4D)
        fec_set_idx, = struct.unpack_from("<I", any_data, 0x4F)
        idx = j        # unknowable without a code shred; see caller
    return (bytes([st.variant_code]) + struct.pack("<Q", slot)
            + struct.pack("<I", idx) + struct.pack("<H", version)
            + struct.pack("<I", fec_set_idx)
            + struct.pack("<HHH", d, p, j))


def _root_from_proof(leaf: bytes, pos: int, wire: bytes,
                     variant: int) -> bytes | None:
    depth = fmt.merkle_cnt(variant)
    m_off = fmt.merkle_off(variant)
    if m_off + 20 * depth > len(wire):
        return None
    proof = [wire[m_off + 20 * k: m_off + 20 * (k + 1)]
             for k in range(depth)]
    return root_from_proof(leaf, pos, proof)
