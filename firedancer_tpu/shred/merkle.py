"""FEC-set merkle commitment: 20-byte-node SHA-256 bmtree + proofs.

Tree semantics (ref: src/ballet/bmtree/fd_bmtree.c:81-137, 327-345):
  * leaf node   = sha256("\\x00SOLANA_MERKLE_SHREDS_LEAF" ‖ leaf bytes)
  * merge       = sha256("\\x01SOLANA_MERKLE_SHREDS_NODE" ‖ L[:20] ‖ R[:20])
    — children are TRUNCATED to hash_sz=20 bytes at concat time; the
    stored node (and the root) keep the full 32-byte sha256 output
  * odd layer: last node pairs with itself
  * proof = the 20-byte sibling at each merge layer, leaf->root order
    (fd_bmtree_get_proof); the signed root is the full 32 bytes

The host tree here does FEC-set bookkeeping (proof extraction needs the
whole tree resident — ~128 nodes, trivially host-sized); the *leaf*
hashes — the wide, batch-shaped work — can come from the device batched
sha256 (ops/sha2.py) via `MerkleTree20.from_leaf_hashes`.
"""
from __future__ import annotations

import hashlib

LEAF_PREFIX = b"\x00SOLANA_MERKLE_SHREDS_LEAF"
NODE_PREFIX = b"\x01SOLANA_MERKLE_SHREDS_NODE"
NODE_SZ = 20


def shred_merkle_leaf(shred_bytes_past_sig: bytes) -> bytes:
    """Leaf hash over a shred's merkle region (the bytes from the
    variant byte through the chained root, fd_shredder.c:267-269)."""
    return hashlib.sha256(LEAF_PREFIX + shred_bytes_past_sig).digest()


def _merge(left: bytes, right: bytes) -> bytes:
    return hashlib.sha256(
        NODE_PREFIX + left[:NODE_SZ] + right[:NODE_SZ]).digest()


def bmtree_depth(leaf_cnt: int) -> int:
    """Layer count INCLUDING the root layer (fd_bmtree_depth): 1 for a
    single leaf, else 1 + ceil(log2(n))."""
    if leaf_cnt <= 1:
        return 1
    d = 0
    while (1 << d) < leaf_cnt:
        d += 1
    return d + 1


class MerkleTree20:
    """Full tree over 32-byte leaf hashes; root + per-leaf proofs."""

    def __init__(self, leaf_hashes: list):
        assert leaf_hashes
        self.layers = [list(leaf_hashes)]
        while len(self.layers[-1]) > 1:
            cur = self.layers[-1]
            nxt = [_merge(cur[i],
                          cur[i + 1] if i + 1 < len(cur) else cur[i])
                   for i in range(0, len(cur), 2)]
            self.layers.append(nxt)

    @classmethod
    def from_leaves(cls, leaf_blobs: list) -> "MerkleTree20":
        return cls([shred_merkle_leaf(b) for b in leaf_blobs])

    @classmethod
    def from_leaf_hashes(cls, hashes) -> "MerkleTree20":
        """hashes: (n, 32) uint8 array (e.g. device batched sha256)."""
        return cls([bytes(h) for h in hashes])

    @property
    def root(self) -> bytes:
        return self.layers[-1][0]

    @property
    def proof_len(self) -> int:
        return len(self.layers) - 1

    def proof(self, leaf_idx: int) -> list:
        """20-byte sibling nodes, leaf->root order
        (fd_bmtree_get_proof, fd_bmtree.c:327-345)."""
        out = []
        idx = leaf_idx
        for layer in self.layers[:-1]:
            sib = idx ^ 1
            if sib >= len(layer):
                sib = idx                  # odd layer: self-pair
            out.append(layer[sib][:NODE_SZ])
            idx >>= 1
        return out


def root_from_proof(leaf_hash: bytes, leaf_idx: int, proof: list) -> bytes:
    """Root implied by one leaf + inclusion proof
    (fd_bmtree_from_proof semantics, fd_bmtree.c:356-380)."""
    node = leaf_hash
    idx = leaf_idx
    for sib in proof:
        if idx & 1:
            node = _merge(sib, node)
        else:
            node = _merge(node, sib)
        idx >>= 1
    return node


def verify_proof(leaf_hash: bytes, leaf_idx: int, proof: list,
                 root: bytes) -> bool:
    return root_from_proof(leaf_hash, leaf_idx, proof) == root
