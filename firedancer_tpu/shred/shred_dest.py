"""Turbine retransmit tree: stake-weighted destination selection.

The reference computes, per shred, a deterministic stake-weighted
shuffle of the cluster and a fanout tree over it: the leader sends to
the tree root, every node retransmits to its children
(ref: src/disco/shred/fd_shred_dest.c — fd_shred_dest_compute_first /
_compute_children; weighted sampling via src/ballet/wsample).

Shuffle: deterministic weighted sampling WITHOUT replacement, seeded by
(slot, shred idx, shred type, leader pubkey). Each node draws a key from
a seeded keyed-hash stream and the order is descending stake-scaled
priority (Efraimidis-Karypis: key = u^(1/stake) ranks a weighted shuffle;
we use the equivalent -log(u)/stake form with exact integer-safe
comparisons via floats on log space — propagation topology only, never
consensus state, so float determinism across our own build is
sufficient; DIVERGENCE from the reference's wsample bit-stream is
intentional and documented).

Tree: positions laid out in the shuffled order; node at position i has
children at positions [i*fanout+1+k*? ...] — we use the classic
contiguous layout: children(i) = positions i*fanout+1 .. i*fanout+fanout
(ref: Agave's turbine layout; fd_shred_dest mirrors it). The leader is
NOT part of the tree; it transmits to the root (position 0).
"""
from __future__ import annotations

import hashlib
import math
from dataclasses import dataclass

DATA_PLANE_FANOUT = 200


@dataclass(frozen=True)
class ClusterNode:
    pubkey: bytes
    stake: int
    addr: tuple = ("", 0)          # (ip, port) the net tile sends to


class ShredDest:
    def __init__(self, nodes: list[ClusterNode], self_pubkey: bytes,
                 fanout: int = DATA_PLANE_FANOUT):
        if fanout < 1:
            raise ValueError("fanout >= 1")
        self.nodes = {n.pubkey: n for n in nodes}
        self.self_pubkey = self_pubkey
        self.fanout = fanout

    # -- deterministic weighted shuffle -------------------------------------

    def _shuffle(self, slot: int, idx: int, shred_type: int,
                 leader: bytes) -> list[ClusterNode]:
        seed = hashlib.sha256(
            b"fdtpu-turbine" + slot.to_bytes(8, "little")
            + idx.to_bytes(4, "little") + bytes([shred_type & 0xFF])
            + leader).digest()
        keyed = []
        for n in self.nodes.values():
            if n.pubkey == leader:
                continue           # the leader never retransmits to itself
            if n.stake <= 0:
                # unstaked nodes sort after all staked ones,
                # deterministically shuffled among themselves
                h = hashlib.sha256(seed + b"u" + n.pubkey).digest()
                keyed.append((1, int.from_bytes(h[:8], "little"), n))
                continue
            h = hashlib.sha256(seed + n.pubkey).digest()
            u = (int.from_bytes(h[:8], "little") + 1) / float(1 << 64)
            # Efraimidis-Karypis: ascending -log(u)/w == descending
            # stake-weighted priority
            keyed.append((0, -math.log(u) / n.stake, n))
        keyed.sort(key=lambda t: (t[0], t[1]))
        return [n for _, _, n in keyed]

    # -- tree queries -------------------------------------------------------

    def first_hop(self, slot: int, idx: int, shred_type: int,
                  leader: bytes) -> ClusterNode | None:
        """Where the LEADER sends this shred (the tree root,
        fd_shred_dest_compute_first)."""
        order = self._shuffle(slot, idx, shred_type, leader)
        return order[0] if order else None

    def children(self, slot: int, idx: int, shred_type: int,
                 leader: bytes) -> list[ClusterNode]:
        """Who WE retransmit this shred to (empty if we are a leaf or
        not in the tree; fd_shred_dest_compute_children)."""
        order = self._shuffle(slot, idx, shred_type, leader)
        pos = next((i for i, n in enumerate(order)
                    if n.pubkey == self.self_pubkey), None)
        if pos is None:
            return []
        lo = pos * self.fanout + 1
        return order[lo:lo + self.fanout]

    def tree_positions(self, slot: int, idx: int, shred_type: int,
                       leader: bytes) -> list[bytes]:
        """Full shuffled order (tests / debugging)."""
        return [n.pubkey
                for n in self._shuffle(slot, idx, shred_type, leader)]
