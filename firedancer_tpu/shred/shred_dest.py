"""Turbine retransmit tree: stake-weighted destination selection,
**draw-for-draw compatible with Agave** (pinned against the
reference's fixtures in tests/test_shred_dest_agave.py).

The reference computes, per shred, a deterministic stake-weighted
shuffle of the cluster and a fanout tree over it: the leader sends to
the tree root, every node retransmits to its children (ref:
src/disco/shred/fd_shred_dest.c fd_shred_dest_compute_first /
_compute_children; weighted sampling via src/ballet/wsample).

Exact protocol (all citations into /root/reference):

- Per-shred RNG seed: sha256 of the packed 45-byte struct
  {slot u64 LE, type u8 (0xA5 data / 0x5A code), idx u32 LE,
  leader_pubkey 32B} (fd_shred_dest.c:24-31, compute_seeds).
- RNG: rand_chacha ChaCha20Rng, rolls in MODE_SHIFT — the power-of-two
  rejection zone of rand 0.8's gen_range (fd_chacha_rng.h).
- Staked nodes: weighted sampling WITHOUT replacement by cumulative-
  stake inversion over the un-removed weights in original index order
  (fd_wsample.h:8-15); the source (compute_first) or the slot leader
  (compute_children) is weight-removed BEFORE drawing.
- Unstaked nodes: uniform index draws with swap-remove
  (fd_shred_dest.c:150-190), appended after all staked positions.
- Tree addressing (fd_shred_dest.c:415-425): position 0's children
  are 1..F; position j in [1,F] sends to j+l*F for l in 1..F;
  positions > F are leaves.

The node list must order staked (stake>0) before unstaked, staked in
the consensus (stake desc, pubkey desc) order; the constructor sorts
canonically if the given order violates staked-before-unstaked.
"""
from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass

from ..utils.chacha import ChaChaRng

DATA_PLANE_FANOUT = 200

_EMPTY = -1              # FD_WSAMPLE_EMPTY
_INDET = -2              # FD_WSAMPLE_INDETERMINATE


@dataclass(frozen=True)
class ClusterNode:
    pubkey: bytes
    stake: int
    addr: tuple = ("", 0)          # (ip, port) the net tile sends to


class _Fenwick:
    """Prefix sums + first-index-with-cum>x search in O(log n)."""

    def __init__(self, weights):
        n = len(weights)
        self.n = n
        self.tree = [0] * (n + 1)
        for i, w in enumerate(weights):
            self._add(i, w)

    def _add(self, i, delta):
        i += 1
        while i <= self.n:
            self.tree[i] += delta
            i += i & (-i)

    def search(self, x):
        """First index whose cumulative sum exceeds x."""
        idx = 0
        bit = 1 << self.n.bit_length()
        while bit:
            nxt = idx + bit
            if nxt <= self.n and self.tree[nxt] <= x:
                x -= self.tree[nxt]
                idx = nxt
            bit >>= 1
        return idx


class _WSample:
    """fd_wsample semantics: without-replacement cumulative inversion
    with a poisoned (excluded-stake) tail, rolls via the shared
    MODE_SHIFT rng (ref src/ballet/wsample/fd_wsample.c:720-790)."""

    def __init__(self, weights: list[int], poisoned: int = 0):
        self.weights = list(weights)
        self.live = list(weights)
        self.fen = _Fenwick(weights)
        self.unremoved = sum(weights)
        self.poisoned = poisoned
        self.poisoned_mode = False
        self.rng: ChaChaRng | None = None

    def seed(self, seed32: bytes):
        self.rng = ChaChaRng(seed32)

    def remove_idx(self, idx: int):
        w = self.live[idx]
        if w:
            self.live[idx] = 0
            self.fen._add(idx, -w)
            self.unremoved -= w

    def sample(self) -> int:
        """With-replacement draw (compute_first's per-shred root)."""
        if not self.unremoved:
            return _EMPTY
        if self.poisoned_mode:
            return _INDET
        unif = self.rng.roll_shift(self.unremoved + self.poisoned)
        if unif >= self.unremoved:
            return _INDET
        return self.fen.search(unif)

    def sample_and_remove(self) -> int:
        if not self.unremoved:
            return _EMPTY
        if self.poisoned_mode:
            return _INDET
        unif = self.rng.roll_shift(self.unremoved + self.poisoned)
        if unif >= self.unremoved:
            self.poisoned_mode = True
            return _INDET
        idx = self.fen.search(unif)
        self.remove_idx(idx)
        return idx

    def sample_and_remove_many(self, n: int) -> list[int]:
        return [self.sample_and_remove() for _ in range(n)]

    def restore_all(self):
        for i, w in enumerate(self.weights):
            if self.live[i] != w:
                self.fen._add(i, w - self.live[i])
                self.live[i] = w
        self.unremoved = sum(self.weights)
        self.poisoned_mode = False


def _seed_for(slot: int, idx: int, is_data: bool, leader: bytes) -> bytes:
    return hashlib.sha256(struct.pack(
        "<QBI", slot, 0xA5 if is_data else 0x5A, idx) + leader).digest()


def _is_data_type(shred_type: int) -> bool:
    # accepts 1/0 (tile convention), 0x80/0x40 (merkle variant types),
    # 0xA5/0x5A (legacy + seed bytes)
    return bool(shred_type & 0x80) or shred_type in (1, 0xA5)


class ShredDest:
    def __init__(self, nodes: list[ClusterNode], self_pubkey: bytes,
                 fanout: int = DATA_PLANE_FANOUT,
                 excluded_stake: int = 0):
        if fanout < 1:
            raise ValueError("fanout >= 1")
        # canonical cluster order, unconditionally: staked by
        # (stake desc, pubkey desc), then unstaked by pubkey desc —
        # every node must derive the identical tree from the same
        # cluster set regardless of list order (the reference requires
        # pre-sorted info[], fd_shred_dest.c:80-86)
        staked = sorted((n for n in nodes if n.stake > 0),
                        key=lambda n: (n.stake, n.pubkey), reverse=True)
        unstaked = sorted((n for n in nodes if n.stake <= 0),
                          key=lambda n: n.pubkey, reverse=True)
        if excluded_stake > 0 and unstaked:
            # poisoned tail implies the list holds only staked nodes
            # (fd_shred_dest.c:92-96)
            raise ValueError("excluded_stake with unstaked validators")
        self.all = staked + unstaked
        self.staked_cnt = len(staked)
        self.unstaked_cnt = len(unstaked)
        self.idx_of = {n.pubkey: i for i, n in enumerate(self.all)}
        self.self_pubkey = self_pubkey
        self.fanout = fanout
        self.excluded_stake = excluded_stake
        self.wsample = _WSample([n.stake for n in staked],
                                poisoned=excluded_stake)
        self.src_idx = self.idx_of.get(self_pubkey)
        self._unstaked_pool: list[int] = []

    # -- unstaked sampling (fd_shred_dest.c:150-226) -------------------------

    def _sample_unstaked_noprepare(self, remove_idx: int) -> int:
        lo, hi = self.staked_cnt, self.staked_cnt + self.unstaked_cnt
        removed = lo <= remove_idx < hi
        cnt = self.unstaked_cnt - (1 if removed else 0)
        if cnt == 0:
            return _EMPTY
        sample = lo + self.wsample.rng.roll_shift(cnt)
        return sample if (not removed or sample < remove_idx) \
            else sample + 1

    def _prepare_unstaked(self, remove_idx: int):
        lo, hi = self.staked_cnt, self.staked_cnt + self.unstaked_cnt
        self._unstaked_pool = [i for i in range(lo, hi)
                               if i != remove_idx]

    def _sample_unstaked(self) -> int:
        pool = self._unstaked_pool
        if not pool:
            return _EMPTY
        k = self.wsample.rng.roll_shift(len(pool))
        out = pool[k]
        pool[k] = pool[-1]
        pool.pop()
        return out

    # -- leader-side root (fd_shred_dest_compute_first) ----------------------

    def first_hop(self, slot: int, idx: int, shred_type: int,
                  leader: bytes) -> ClusterNode | None:
        """Where the LEADER (== self) sends this shred: one
        stake-weighted draw with the source removed."""
        # the reference's info[] always contains the source; ours may
        # not — count CANDIDATES (everyone but self), not list length
        if len(self.all) - (1 if self.src_idx is not None else 0) < 1:
            return None
        is_data = _is_data_type(shred_type)
        src_staked = self.src_idx is not None \
            and self.src_idx < self.staked_cnt
        if src_staked:
            self.wsample.remove_idx(self.src_idx)
        try:
            any_staked = self.staked_cnt > (1 if src_staked else 0)
            self.wsample.seed(_seed_for(slot, idx, is_data, leader))
            if any_staked:
                got = self.wsample.sample()
            else:
                got = self._sample_unstaked_noprepare(
                    self.src_idx if self.src_idx is not None else -1)
        finally:
            self.wsample.restore_all()
        return self.all[got] if got >= 0 else None

    # -- retransmitter children (fd_shred_dest_compute_children) -------------

    def children(self, slot: int, idx: int, shred_type: int,
                 leader: bytes) -> list[ClusterNode]:
        """Who WE retransmit this shred to (empty if we are a leaf,
        the leader, or unknown)."""
        out = self._children_idx(slot, idx, shred_type, leader)
        return [self.all[i] for i in out]

    def _children_idx(self, slot: int, idx: int, shred_type: int,
                      leader: bytes) -> list[int]:
        my_orig = self.src_idx
        if my_orig is None or len(self.all) - 1 < 1:
            return []
        i_am_staked = my_orig < self.staked_cnt
        lq = self.idx_of.get(leader)
        leader_is_staked = lq is not None and lq < self.staked_cnt
        leader_idx = lq if lq is not None else (1 << 63)
        if leader_idx == my_orig:
            return []          # leader uses first_hop
        if (not i_am_staked) and \
                self.staked_cnt - (1 if leader_is_staked else 0) \
                > self.fanout:
            return []          # always at the bottom of the tree
        is_data = _is_data_type(shred_type)
        fanout = self.fanout
        ws = self.wsample
        try:
            if leader_is_staked:
                ws.remove_idx(leader_idx)
            ws.seed(_seed_for(slot, idx, is_data, leader))
            my_idx = 0
            if not i_am_staked:
                if self.excluded_stake > 0:
                    return []
                shuffle = ws.sample_and_remove_many(self.staked_cnt + 1)
                my_idx = self.staked_cnt \
                    - (1 if leader_is_staked else 0)
                self._prepare_unstaked(leader_idx)
                while my_idx <= fanout:
                    s = self._sample_unstaked()
                    if s == my_orig:
                        break
                    if s == _EMPTY:
                        return []
                    my_idx += 1
            else:
                n0 = min(fanout + 1, self.staked_cnt + 1)
                shuffle = ws.sample_and_remove_many(n0)
                while my_idx <= fanout:
                    s = shuffle[my_idx]
                    if s == my_orig:
                        break
                    if s == _EMPTY:
                        return []
                    if s == _INDET:
                        my_idx = (1 << 63)
                        break
                    my_idx += 1
            if my_idx > fanout:
                return []      # leaf
            # tree addressing (fd_shred_dest.c:415-425)
            last = fanout if my_idx == 0 else my_idx + fanout * fanout
            stride = 1 if my_idx == 0 else fanout
            cursor = my_idx + 1
            stored: list[int] = []
            if last >= len(shuffle) and \
                    len(shuffle) < self.staked_cnt + 1:
                adtl = min(last + 1, self.staked_cnt + 1) - len(shuffle)
                shuffle += ws.sample_and_remove_many(adtl)
            while cursor <= min(last, self.staked_cnt):
                s = shuffle[cursor]
                if s in (_EMPTY, _INDET):
                    break
                if cursor == my_idx + stride * (len(stored) + 1):
                    stored.append(s)
                cursor += 1
            if cursor <= last and i_am_staked:
                self._prepare_unstaked(leader_idx)
            while cursor <= last:
                s = self._sample_unstaked()
                if s == _EMPTY:
                    break
                if cursor == my_idx + stride * (len(stored) + 1):
                    stored.append(s)
                cursor += 1
            return stored
        finally:
            ws.restore_all()

    # -- debugging / tests ---------------------------------------------------

    def tree_positions(self, slot: int, idx: int, shred_type: int,
                       leader: bytes) -> list[bytes]:
        """Full shuffled order with the leader removed (debug aid)."""
        is_data = _is_data_type(shred_type)
        ws = self.wsample
        lq = self.idx_of.get(leader)
        try:
            if lq is not None and lq < self.staked_cnt:
                ws.remove_idx(lq)
            ws.seed(_seed_for(slot, idx, is_data, leader))
            order = []
            while True:
                s = ws.sample_and_remove()
                if s < 0:
                    break
                order.append(s)
            self._prepare_unstaked(lq if lq is not None else -1)
            while True:
                s = self._sample_unstaked()
                if s < 0:
                    break
                order.append(s)
            return [self.all[i].pubkey for i in order]
        finally:
            ws.restore_all()
