"""Transaction cost model + compute-budget instruction parsing.

The consensus cost model the leader schedules against
(ref: src/disco/pack/fd_pack_cost.h:10-28): total cost =

    per-signature cost  (720/txn sig; precompile instrs add 2400 per
                         ed25519 sig, 6690 per secp256k1, 4800 per
                         secp256r1 — counted from the instr's first
                         data byte)
  + per-write-lock cost (300 per writable account)
  + instr data cost     (total instruction data bytes / 4)
  + execution cost      (compute-budget requested CU limit, else
                         200k per non-builtin + 3k per builtin instr,
                         clamped to 1.4M)
  + loaded-accounts-data cost (8 CU per 32 KiB page of the requested
                         — default 64 MiB — loaded data size)

Simple votes short-circuit to a fixed 3428 CU
(FD_PACK_SIMPLE_VOTE_COST) regardless of contents.

The compute-budget program parser is the reference's state machine
(src/disco/pack/fd_compute_budget_program.h:91-146): four instruction
kinds keyed by the first data byte, each settable at most once, any
malformed/duplicate instruction fails the whole transaction. The
priority fee is ceil(cu_limit * micro_lamports_per_cu / 1e6) lamports
(python ints: no saturation ladder needed — the reference's careful
split arithmetic exists only to dodge u64 overflow).
"""
from __future__ import annotations

from dataclasses import dataclass

from ..protocol.txn import ParsedTxn
from ..utils.base58 import b58_decode_32

# consensus-critical constants (fd_pack_cost.h:92-99)
COST_PER_SIGNATURE = 720
COST_PER_ED25519_SIGNATURE = 2400
COST_PER_SECP256K1_SIGNATURE = 6690
COST_PER_SECP256R1_SIGNATURE = 4800
COST_PER_WRITABLE_ACCT = 300
INV_COST_PER_INSTR_DATA_BYTE = 4
MAX_TXN_COST = 1_573_166                     # fd_pack_cost.h:148
MIN_TXN_COST = COST_PER_SIGNATURE + COST_PER_WRITABLE_ACCT

# compute budget program (fd_compute_budget_program.h:40-52)
MAX_BUILTIN_CU_LIMIT = 3_000
DEFAULT_INSTR_CU_LIMIT = 200_000
MAX_CU_LIMIT = 1_400_000
HEAP_FRAME_GRANULARITY = 1024
MICRO_LAMPORTS_PER_LAMPORT = 1_000_000
HEAP_COST = 8
ACCOUNT_DATA_COST_PAGE_SIZE = 32 * 1024
MAX_LOADED_DATA_SZ = 64 * 1024 * 1024

# vote (fd_pack_cost.h:196-207)
VOTE_DEFAULT_COMPUTE_UNITS = 2_100
SIMPLE_VOTE_COST = (COST_PER_SIGNATURE + 2 * COST_PER_WRITABLE_ACCT
                    + VOTE_DEFAULT_COMPUTE_UNITS + 8)

# well-known program ids (public Solana constants; the reference keys a
# perfect hash table on the same set, fd_pack_cost.h:68-79)
VOTE_PROGRAM_ID = b58_decode_32(
    "Vote111111111111111111111111111111111111111")
SYSTEM_PROGRAM_ID = b58_decode_32("11111111111111111111111111111111")
COMPUTE_BUDGET_PROGRAM_ID = b58_decode_32(
    "ComputeBudget111111111111111111111111111111")
BPF_UPGRADEABLE_LOADER_ID = b58_decode_32(
    "BPFLoaderUpgradeab1e11111111111111111111111")
BPF_LOADER_1_ID = b58_decode_32(
    "BPFLoader1111111111111111111111111111111111")
BPF_LOADER_2_ID = b58_decode_32(
    "BPFLoader2111111111111111111111111111111111")
LOADER_V4_ID = b58_decode_32(
    "LoaderV411111111111111111111111111111111111")
KECCAK_SECP_PROGRAM_ID = b58_decode_32(
    "KeccakSecp256k11111111111111111111111111111")
ED25519_SV_PROGRAM_ID = b58_decode_32(
    "Ed25519SigVerify111111111111111111111111111")
SECP256R1_PROGRAM_ID = b58_decode_32(
    "Secp256r1SigVerify1111111111111111111111111")

BUILTIN_PROGRAMS = frozenset({
    VOTE_PROGRAM_ID, SYSTEM_PROGRAM_ID, COMPUTE_BUDGET_PROGRAM_ID,
    BPF_UPGRADEABLE_LOADER_ID, BPF_LOADER_1_ID, BPF_LOADER_2_ID,
    LOADER_V4_ID, KECCAK_SECP_PROGRAM_ID, ED25519_SV_PROGRAM_ID,
    SECP256R1_PROGRAM_ID})

_PRECOMPILE_SIG_COST = {
    ED25519_SV_PROGRAM_ID: COST_PER_ED25519_SIGNATURE,
    KECCAK_SECP_PROGRAM_ID: COST_PER_SECP256K1_SIGNATURE,
    SECP256R1_PROGRAM_ID: COST_PER_SECP256R1_SIGNATURE,
}


class CostError(ValueError):
    """Transaction fails the cost model (malformed compute budget)."""


@dataclass
class ComputeBudgetState:
    """Accumulated compute-budget requests
    (fd_compute_budget_program.h:57-80)."""
    set_cu: bool = False
    set_fee: bool = False
    set_heap: bool = False
    set_loaded: bool = False
    compute_units: int = 0
    micro_lamports_per_cu: int = 0
    heap_size: int = 0
    loaded_acct_data_sz: int = 0

    def parse_instr(self, data: bytes):
        """One ComputeBudgetProgram instruction; raises CostError on any
        malformed or duplicate request (the whole txn then fails)."""
        if len(data) < 5:
            raise CostError("compute budget instr too short")
        kind = data[0]
        if kind == 1:                                # RequestHeapFrame
            if self.set_heap:
                raise CostError("duplicate RequestHeapFrame")
            self.heap_size = int.from_bytes(data[1:5], "little")
            if self.heap_size % HEAP_FRAME_GRANULARITY:
                raise CostError("heap size granularity")
            self.set_heap = True
        elif kind == 2:                              # SetComputeUnitLimit
            if self.set_cu:
                raise CostError("duplicate SetComputeUnitLimit")
            self.compute_units = min(int.from_bytes(data[1:5], "little"),
                                     MAX_CU_LIMIT)
            self.set_cu = True
        elif kind == 3:                              # SetComputeUnitPrice
            if len(data) < 9:
                raise CostError("SetComputeUnitPrice too short")
            if self.set_fee:
                raise CostError("duplicate SetComputeUnitPrice")
            self.micro_lamports_per_cu = int.from_bytes(data[1:9], "little")
            self.set_fee = True
        elif kind == 4:                              # SetLoadedAcctDataSz
            if self.set_loaded:
                raise CostError("duplicate SetLoadedAccountsDataSize")
            sz = int.from_bytes(data[1:5], "little")
            if sz == 0:
                raise CostError("zero loaded data size")
            self.loaded_acct_data_sz = min(sz, MAX_LOADED_DATA_SZ)
            self.set_loaded = True
        else:                                        # 0 deprecated, 5+ bad
            raise CostError(f"bad compute budget discriminant {kind}")

    def finalize(self, instr_cnt: int, builtin_instr_cnt: int):
        """-> (cu_limit, priority_fee_lamports, loaded_data_cost)
        (fd_compute_budget_program.h finalize)."""
        if self.set_cu:
            cu_limit = self.compute_units
        else:
            cu_limit = ((instr_cnt - builtin_instr_cnt)
                        * DEFAULT_INSTR_CU_LIMIT
                        + builtin_instr_cnt * MAX_BUILTIN_CU_LIMIT)
        cu_limit = min(cu_limit, MAX_CU_LIMIT)
        loaded_sz = (self.loaded_acct_data_sz if self.set_loaded
                     else MAX_LOADED_DATA_SZ)
        loaded_cost = HEAP_COST * (
            (loaded_sz + ACCOUNT_DATA_COST_PAGE_SIZE - 1)
            // ACCOUNT_DATA_COST_PAGE_SIZE)
        fee = -(-(cu_limit * self.micro_lamports_per_cu)
                // MICRO_LAMPORTS_PER_LAMPORT)
        return cu_limit, fee, loaded_cost


def is_simple_vote(t: ParsedTxn, payload: bytes) -> bool:
    """fd_txn_is_simple_vote_transaction (fd_txn.h:457-471): legacy,
    one instruction, <= 2 signatures, vote program."""
    if len(t.instrs) != 1 or t.version != -1 or t.sig_cnt > 2:
        return False
    keys = t.account_keys(payload)
    return keys[t.instrs[0].prog_idx] == VOTE_PROGRAM_ID


@dataclass(frozen=True)
class TxnCost:
    total: int                 # cost units charged against block limits
    execution: int             # CU limit handed to the VM
    priority_fee: int          # lamports beyond the per-signature fee
    precompile_sig_cnt: int
    loaded_data_cost: int
    is_simple_vote: bool


def compute_cost(t: ParsedTxn, payload: bytes) -> TxnCost:
    """fd_pack_compute_cost (fd_pack_cost.h:230-320). Raises CostError
    where the reference returns 0 (txn must be dropped)."""
    if is_simple_vote(t, payload):
        return TxnCost(SIMPLE_VOTE_COST, VOTE_DEFAULT_COMPUTE_UNITS, 0,
                       0, 0, True)

    keys = t.account_keys(payload)
    sig_cost = COST_PER_SIGNATURE * t.sig_cnt
    writable_cnt = sum(t.is_writable(i) for i in range(t.acct_cnt))
    writable_cost = COST_PER_WRITABLE_ACCT * writable_cnt

    cbp = ComputeBudgetState()
    instr_data_sz = 0
    non_builtin_cnt = 0
    precompile_sig_cnt = 0
    for ins in t.instrs:
        instr_data_sz += ins.data_sz
        prog = keys[ins.prog_idx]
        data = payload[ins.data_off:ins.data_off + ins.data_sz]
        if prog not in BUILTIN_PROGRAMS:
            non_builtin_cnt += 1
        elif prog == COMPUTE_BUDGET_PROGRAM_ID:
            cbp.parse_instr(data)
        elif prog in _PRECOMPILE_SIG_COST:
            n = data[0] if ins.data_sz > 0 else 0
            precompile_sig_cnt += n
            sig_cost += n * _PRECOMPILE_SIG_COST[prog]

    instr_data_cost = instr_data_sz // INV_COST_PER_INSTR_DATA_BYTE
    cu_limit, fee, loaded_cost = cbp.finalize(
        len(t.instrs), len(t.instrs) - non_builtin_cnt)
    total = (sig_cost + writable_cost + cu_limit + instr_data_cost
             + loaded_cost)
    return TxnCost(total, cu_limit, fee, precompile_sig_cnt, loaded_cost,
                   False)
