"""pack: the leader-side transaction scheduler (hot loop #2).

Re-expression of the reference's fd_pack (ref: src/disco/pack/fd_pack.h,
fd_pack.c:1760 fd_pack_schedule_impl, :2477 schedule_next_microblock;
conflict sets src/disco/pack/fd_pack_bitset.h:1-60): maintain a
priority-ordered pool of pending transactions and emit microblocks of
mutually non-conflicting transactions to parallel bank tiles under
consensus cost limits.
"""
from .scheduler import PackScheduler, PackLimits, TxnMeta  # noqa: F401
