"""Account-conflict-aware microblock scheduler.

Semantics follow the reference's fd_pack:

* Pending transactions are priority-ordered by reward per cost unit
  (ref: src/disco/pack/fd_pack.c — treap ordered by compare_worker;
  here a lazy-deletion binary heap, which preserves the schedule order
  contract without the treap's delete-by-key machinery).
* A microblock for bank b contains only transactions that do not
  conflict with any transaction currently outstanding on OTHER banks:
  write-write and read-write overlaps are conflicts
  (ref: fd_pack.c:1760 fd_pack_schedule_impl bitset checks).
* Conflict tests use per-transaction account bitsets. The reference
  compresses into a fixed 256-bit set with reserve-on-second-reference
  (fd_pack_bitset.h:1-60) because it needs AVX-width compares; Python
  arbitrary-precision ints give exact unlimited-width bitsets for free,
  so every account gets a bit (freed when its refcount drops to zero) —
  same contract, no false negatives.
* Consensus cost limits enforced per block: total cost, per-writable-
  account write cost, vote cost, microblock count/size
  (ref: src/disco/pack/fd_pack.h:56-101 fd_pack_limits_t).

Cost/reward model (ref: src/disco/pack/fd_pack_cost.h): cost units =
per-signature + per-writable-lock + execution CUs (compute-budget
requested, else the default); reward = base fee per signature +
priority fee. Exact fee math can be swapped in without touching the
scheduler.
"""
from __future__ import annotations

import heapq
import struct
from collections import deque
from dataclasses import dataclass, field

from ..protocol.txn import ParsedTxn, parse_txn
from .cost import CostError, compute_cost

# consensus-critical defaults (cluster-agreed values; ref:
# src/disco/pack/fd_pack.h:30-36 — 48M lower bound, 12M per acct)
MAX_COST_PER_BLOCK = 48_000_000
MAX_VOTE_COST_PER_BLOCK = 36_000_000
MAX_WRITE_COST_PER_ACCT = 12_000_000

FEE_PER_SIGNATURE = 5000          # ref: fd_pack.h:20
TXN_FEE_BURN_PCT = 50             # ref: fd_pack.h:52


@dataclass
class PackLimits:
    max_cost_per_block: int = MAX_COST_PER_BLOCK
    max_vote_cost_per_block: int = MAX_VOTE_COST_PER_BLOCK
    max_write_cost_per_acct: int = MAX_WRITE_COST_PER_ACCT
    max_txn_per_microblock: int = 31
    max_microblocks_per_block: int = 16384
    # cap on serialized microblock bytes (keeps one microblock within a
    # ring frag MTU; the reference bounds block data bytes for the same
    # reason at block scale, fd_pack.h max_data_bytes_per_block)
    max_data_bytes_per_microblock: int = 1 << 20
    probe_depth: int = 64         # candidates examined per microblock


@dataclass
class TxnMeta:
    payload: bytes
    txn: ParsedTxn
    reward: int                   # lamports to the leader
    cost: int                     # cost units
    writes: tuple[bytes, ...]     # writable account keys
    reads: tuple[bytes, ...]      # readonly account keys
    is_vote: bool = False
    seq: int = 0                  # insertion order (priority tiebreak)
    w_mask: int = 0
    r_mask: int = 0


def txn_cost_and_reward(t: ParsedTxn, payload: bytes) -> tuple[int, int, bool]:
    """Full fd_pack cost/reward model -> (cost units, leader lamports,
    is_simple_vote). Raises CostError for txns the cost model rejects
    (malformed compute-budget instructions — the reference returns
    cost 0 and pack drops them, fd_pack.c:898-922)."""
    tc = compute_cost(t, payload)
    sig_rewards = FEE_PER_SIGNATURE * (t.sig_cnt + tc.precompile_sig_cnt)
    # the leader keeps the UNburned share of the signature fee
    # (fd_pack.c:914 applies the burn; burn pct fd_pack.h:52)
    reward = sig_rewards * (100 - TXN_FEE_BURN_PCT) // 100 \
        + tc.priority_fee
    return tc.total, reward, tc.is_simple_vote


def meta_from_payload(payload: bytes, seq: int = 0,
                      reward: int | None = None,
                      cost: int | None = None) -> TxnMeta:
    t = parse_txn(payload)
    if t.version == 0 and t.aluts:
        # pack's conflict bitsets require RESOLVED account sets; the
        # reference resolves v0 table loads upstream of pack (the
        # resolv tile, src/discof/resolv/). Until that tile lands in
        # the leader topology, unresolved v0 txns are refused here —
        # mis-scheduling them would break the serial-fiction invariant
        from .cost import CostError
        raise CostError("unresolved v0 address table lookups")
    keys = t.account_keys(payload)
    writes = tuple(k for i, k in enumerate(keys) if t.is_writable(i))
    reads = tuple(k for i, k in enumerate(keys) if not t.is_writable(i))
    c, r, vote = txn_cost_and_reward(t, payload)
    return TxnMeta(payload, t, reward if reward is not None else r,
                   cost if cost is not None else c, writes, reads,
                   is_vote=vote, seq=seq)


# RESOLVED frame (the resolv->pack wire, ref: src/discof/resolv/ —
# account sets, cost and reward travel WITH the payload so pack never
# re-parses and never needs account-db access for v0 txns):
#   u16 n_writes | u16 n_reads | u32 cost | u64 reward | u8 flags
#   | u16 payload_len | n_writes*32 writes | n_reads*32 reads | payload
RESOLVED_HDR = struct.Struct("<HHIQBH")
RESOLVED_FLAG_VOTE = 1


def serialize_resolved(meta: TxnMeta) -> bytes:
    """TxnMeta -> RESOLVED frame (the resolv tile's egress)."""
    flags = RESOLVED_FLAG_VOTE if meta.is_vote else 0
    return (RESOLVED_HDR.pack(len(meta.writes), len(meta.reads),
                              meta.cost, meta.reward, flags,
                              len(meta.payload))
            + b"".join(meta.writes) + b"".join(meta.reads)
            + meta.payload)


def meta_from_resolved(frame: bytes, seq: int = 0) -> TxnMeta:
    """RESOLVED frame -> TxnMeta. Account sets, cost and reward come
    off the wire verbatim — including ALUT-loaded keys a re-parse of
    the payload could NOT reproduce without db access, which is the
    whole point of the resolv tile. txn stays None: nothing downstream
    of insert reads it (microblock serialization uses the payload)."""
    nw, nr, cost, reward, flags, plen = RESOLVED_HDR.unpack_from(
        frame, 0)
    off = RESOLVED_HDR.size
    need = off + 32 * (nw + nr) + plen
    if len(frame) < need:
        raise CostError(f"short RESOLVED frame ({len(frame)} < {need})")
    writes = tuple(bytes(frame[off + 32 * i:off + 32 * (i + 1)])
                   for i in range(nw))
    off += 32 * nw
    reads = tuple(bytes(frame[off + 32 * i:off + 32 * (i + 1)])
                  for i in range(nr))
    off += 32 * nr
    payload = bytes(frame[off:off + plen])
    return TxnMeta(payload, None, reward, cost, writes, reads,
                   is_vote=bool(flags & RESOLVED_FLAG_VOTE), seq=seq)


class _AcctBits:
    """account key -> bit index, refcounted; bits freed at refcount 0
    (the reference frees at 0 too — fd_pack_bitset.h 'defer freeing the
    bit until the reference count drops to 0')."""

    def __init__(self):
        self.bits: dict[bytes, int] = {}
        self.refs: dict[bytes, int] = {}
        self.free: list[int] = []
        self.next_bit = 0

    def acquire(self, key: bytes) -> int:
        if key in self.bits:
            self.refs[key] += 1
            return self.bits[key]
        b = self.free.pop() if self.free else self.next_bit
        if b == self.next_bit:
            self.next_bit += 1
        self.bits[key] = b
        self.refs[key] = 1
        return b

    def release(self, key: bytes):
        self.refs[key] -= 1
        if self.refs[key] == 0:
            self.free.append(self.bits.pop(key))
            del self.refs[key]


class PackScheduler:
    def __init__(self, bank_cnt: int = 4, limits: PackLimits | None = None):
        self.limits = limits or PackLimits()
        self.bank_cnt = bank_cnt
        self._bits = _AcctBits()
        self._heap: list[tuple[float, int, int]] = []   # (-prio, seq, id)
        self._pending: dict[int, TxnMeta] = {}
        self._next_id = 0
        self._seq = 0
        # outstanding (in-flight) microblocks per bank: a FIFO of
        # (w_mask, r_mask, metas). The wave discipline keeps up to the
        # caller's wave depth of microblocks in flight per bank;
        # same-bank microblocks execute serially IN ORDER (the bank
        # consumes its link FIFO), so only OTHER banks' outstanding
        # masks are conflict windows — the reference's one-busy-flag
        # per bank is the wave=1 special case of this queue.
        self._out: list[deque] = [deque() for _ in range(bank_cnt)]
        # bundles: FIFO of ordered txn groups awaiting atomic placement
        # (ref: fd_pack bundle support — a bundle is never reordered,
        # never split, and outranks the regular pending pool)
        self._bundles: list[list[TxnMeta]] = []
        # block accounting
        self.block_cost = 0
        self.block_vote_cost = 0
        self.block_microblocks = 0
        self._acct_write_cost: dict[bytes, int] = {}
        self.metrics = {"inserted": 0, "scheduled": 0, "microblocks": 0,
                        "conflict_skip": 0, "limit_skip": 0,
                        "bundles": 0, "bundle_skip": 0}

    # -- insert -----------------------------------------------------------

    def insert(self, meta: TxnMeta) -> int:
        """Queue a txn; returns its pack id."""
        meta.seq = self._seq
        self._seq += 1
        meta.w_mask = 0
        meta.r_mask = 0
        for k in meta.writes:
            meta.w_mask |= 1 << self._bits.acquire(k)
        for k in meta.reads:
            meta.r_mask |= 1 << self._bits.acquire(k)
        tid = self._next_id
        self._next_id += 1
        self._pending[tid] = meta
        # reward-per-cost priority, FIFO tiebreak (deterministic)
        heapq.heappush(self._heap, (-meta.reward / max(1, meta.cost),
                                    meta.seq, tid))
        self.metrics["inserted"] += 1
        return tid

    def insert_payload(self, payload: bytes) -> int:
        return self.insert(meta_from_payload(payload))

    MAX_BUNDLE_TXNS = 5            # the reference's bundle size cap

    def insert_bundle(self, metas: list[TxnMeta]) -> int:
        """Queue an ordered atomic group (ref: fd_pack bundles — the
        Jito contract: executes in exactly this order, in one
        microblock, whole or not at all; intra-bundle account
        conflicts are expected and legal because the bank executes a
        bundle serially). Returns the bundle's queue position."""
        if not 1 <= len(metas) <= self.MAX_BUNDLE_TXNS:
            raise ValueError(f"bundle size {len(metas)}")
        # reject bundles that could NEVER schedule (limits end_block()
        # cannot relax) — otherwise the FIFO head wedges forever and
        # head-of-line-blocks every later bundle (r4 review)
        g_cost = sum(m.cost for m in metas)
        g_vote = sum(m.cost for m in metas if m.is_vote)
        g_bytes = sum(2 + len(m.payload) for m in metas)
        if g_cost > self.limits.max_cost_per_block:
            raise ValueError(f"bundle cost {g_cost} can never fit a block")
        if g_vote > self.limits.max_vote_cost_per_block:
            raise ValueError(
                f"bundle vote cost {g_vote} can never fit a block")
        if g_bytes > self.limits.max_data_bytes_per_microblock:
            raise ValueError(f"bundle bytes {g_bytes} exceed a microblock")
        g_acct: dict[bytes, int] = {}
        for m in metas:
            for k in m.writes:
                g_acct[k] = g_acct.get(k, 0) + m.cost
        for k, c in g_acct.items():
            if c > self.limits.max_write_cost_per_acct:
                raise ValueError("bundle exceeds per-account write cost")
        for meta in metas:
            meta.seq = self._seq
            self._seq += 1
            meta.w_mask = 0
            meta.r_mask = 0
            for k in meta.writes:
                meta.w_mask |= 1 << self._bits.acquire(k)
            for k in meta.reads:
                meta.r_mask |= 1 << self._bits.acquire(k)
        self._bundles.append(list(metas))
        self.metrics["inserted"] += len(metas)
        return len(self._bundles) - 1

    def _try_bundle(self, bank: int, out_w: int,
                    out_rw: int) -> list[TxnMeta] | None:
        """Oldest bundle -> its own microblock when it fits, whole or
        not at all. Conflicts are judged against OTHER banks only;
        intra-bundle overlap is the point of a bundle."""
        if not self._bundles:
            return None
        mb = self._bundles[0]
        g_w = g_r = 0
        g_cost = g_vote = 0
        g_bytes = 0
        g_acct: dict[bytes, int] = {}
        for meta in mb:
            g_w |= meta.w_mask
            g_r |= meta.r_mask
            g_cost += meta.cost
            if meta.is_vote:
                g_vote += meta.cost
            for k in meta.writes:
                g_acct[k] = g_acct.get(k, 0) + meta.cost
            g_bytes += 2 + len(meta.payload)
        if (g_w & out_rw) or (g_r & out_w):
            self.metrics["bundle_skip"] += 1
            return None
        if self.block_cost + g_cost > self.limits.max_cost_per_block \
                or self.block_vote_cost + g_vote \
                > self.limits.max_vote_cost_per_block \
                or g_bytes > self.limits.max_data_bytes_per_microblock:
            self.metrics["bundle_skip"] += 1
            return None
        for k, c in g_acct.items():
            if self._acct_write_cost.get(k, 0) + c \
                    > self.limits.max_write_cost_per_acct:
                self.metrics["bundle_skip"] += 1
                return None
        self._bundles.pop(0)
        self._out[bank].append((g_w, g_r, mb))
        self.block_cost += g_cost
        self.block_vote_cost += g_vote
        self.block_microblocks += 1
        for k, c in g_acct.items():
            self._acct_write_cost[k] = \
                self._acct_write_cost.get(k, 0) + c
        self.metrics["scheduled"] += len(mb)
        self.metrics["microblocks"] += 1
        self.metrics["bundles"] += 1
        return mb

    @property
    def pending_cnt(self) -> int:
        return len(self._pending)

    # -- schedule ---------------------------------------------------------

    def _conflicts(self, meta: TxnMeta, out_w: int, out_rw: int) -> bool:
        return bool(meta.w_mask & out_rw) or bool(meta.r_mask & out_w)

    def _block_allows(self, meta: TxnMeta, mb_cost: int,
                      mb_vote_cost: int, mb_acct_cost: dict) -> bool:
        if self.block_cost + mb_cost + meta.cost \
                > self.limits.max_cost_per_block:
            return False
        if meta.is_vote and self.block_vote_cost + mb_vote_cost \
                + meta.cost > self.limits.max_vote_cost_per_block:
            return False
        for k in meta.writes:
            if self._acct_write_cost.get(k, 0) + mb_acct_cost.get(k, 0) \
                    + meta.cost > self.limits.max_write_cost_per_acct:
                return False
        return True

    def outstanding_cnt(self, bank: int) -> int:
        """In-flight microblocks queued on `bank` (the caller's wave
        budget gate — microblock_done retires them FIFO)."""
        return len(self._out[bank])

    def schedule_microblock(self, bank: int) -> list[TxnMeta]:
        """Emit the next microblock for `bank` (empty when nothing
        schedulable). Multiple microblocks may be outstanding on one
        bank (the wave discipline); the caller signals
        microblock_done(bank) once per microblock, in FIFO order.
        (ref contract: fd_pack.c:2477 schedule_next_microblock)."""
        if self.block_microblocks >= self.limits.max_microblocks_per_block:
            return []
        out_w = 0
        out_rw = 0
        for b in range(self.bank_cnt):
            if b == bank:
                continue
            for bw, br, _ in self._out[b]:
                out_w |= bw
                out_rw |= bw | br

        # bundles outrank the pending pool and occupy a microblock
        # exclusively (never mixed, never reordered, never split)
        bundle = self._try_bundle(bank, out_w, out_rw)
        if bundle is not None:
            return bundle

        chosen: list[tuple[float, int, int]] = []
        skipped: list[tuple[float, int, int]] = []
        mb: list[TxnMeta] = []
        mb_cost = 0
        mb_vote_cost = 0
        mb_acct_cost: dict[bytes, int] = {}
        mb_bytes = 0
        mb_w = 0
        mb_r = 0
        probes = 0
        while self._heap and len(mb) < self.limits.max_txn_per_microblock \
                and probes < self.limits.probe_depth:
            entry = heapq.heappop(self._heap)
            tid = entry[2]
            meta = self._pending.get(tid)
            if meta is None:
                continue            # lazily-deleted entry
            probes += 1
            # conflicts vs other banks' outstanding AND this microblock
            if self._conflicts(meta, out_w | mb_w, out_rw | mb_w | mb_r):
                self.metrics["conflict_skip"] += 1
                skipped.append(entry)
                continue
            if not self._block_allows(meta, mb_cost, mb_vote_cost,
                                      mb_acct_cost) \
                    or mb_bytes + 2 + len(meta.payload) \
                    > self.limits.max_data_bytes_per_microblock:
                self.metrics["limit_skip"] += 1
                skipped.append(entry)
                continue
            del self._pending[tid]
            chosen.append(entry)
            mb.append(meta)
            mb_cost += meta.cost
            if meta.is_vote:
                mb_vote_cost += meta.cost
            for k in meta.writes:
                mb_acct_cost[k] = mb_acct_cost.get(k, 0) + meta.cost
            mb_bytes += 2 + len(meta.payload)
            mb_w |= meta.w_mask
            mb_r |= meta.r_mask
        for entry in skipped:       # retry later
            heapq.heappush(self._heap, entry)

        if not mb:
            return []
        self._out[bank].append((mb_w, mb_r, mb))
        self.block_cost += mb_cost
        self.block_microblocks += 1
        for m in mb:
            if m.is_vote:
                self.block_vote_cost += m.cost
            for k in m.writes:
                self._acct_write_cost[k] = \
                    self._acct_write_cost.get(k, 0) + m.cost
        self.metrics["scheduled"] += len(mb)
        self.metrics["microblocks"] += 1
        return mb

    def microblock_done(self, bank: int):
        """Bank finished executing its OLDEST outstanding microblock:
        release that microblock's account locks (banks consume their
        link FIFO, so completions arrive in schedule order; block-level
        cost accounting is permanent until end_block)."""
        if not self._out[bank]:
            return                    # idle bank: done is a no-op
        _, _, metas = self._out[bank].popleft()
        for m in metas:
            for k in m.writes:
                self._bits.release(k)
            for k in m.reads:
                self._bits.release(k)

    def end_block(self):
        """Reset per-block accounting (ref: fd_pack_end_block)."""
        self.block_cost = 0
        self.block_vote_cost = 0
        self.block_microblocks = 0
        self._acct_write_cost.clear()

    def outstanding(self, bank: int) -> list[TxnMeta]:
        """Every txn in flight on `bank`, oldest microblock first."""
        return [m for _, _, metas in self._out[bank] for m in metas]
