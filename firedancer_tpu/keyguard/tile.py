"""Sign tile: the only holder of the identity key.

Every client tile gets a DEDICATED request/response ring pair with a
fixed role, so authorization policy is attached to the wire, not the
payload (ref: src/disco/sign/fd_sign_tile.c — one in/out link pair per
client tile, role fixed at topology build; src/disco/keyguard/
fd_keyguard_client.h — the client side).

Wire format:
  request   u8 sign_type | payload          (frag sig = request id)
  response  u8 ok | 64B signature if ok=1   (frag sig echoes request id)

A refused request gets an explicit ok=0 response (the reference logs and
drops; an explicit NAK keeps the client from blocking forever and is
observable in tests).
"""
from __future__ import annotations

import hashlib
import time

from ..utils.ed25519_ref import keypair, sign
from .keyguard import SIGN_TYPE_ED25519, SIGN_TYPE_SHA256_ED25519, authorize


class SignTile:
    """Core loop logic over (role, in_ring, out_ring, out_fseqs) client
    legs; adapter-agnostic so tests can drive it in-process."""

    def __init__(self, seed: bytes, clients: list[dict]):
        """clients: {role: int, in_ring, out_ring, out_fseqs}."""
        self.seed = seed
        _, _, self.pubkey = keypair(seed)
        self.clients = clients
        self.seqs = [0] * len(clients)
        self.metrics = {"signed": 0, "refused": 0, "overruns": 0,
                        "backpressure": 0, "keyswitches": 0}

    def rekey(self, seed: bytes):
        """Hot-swap the identity (fd_keyswitch): requests polled after
        this sign with the new key."""
        self.seed = seed
        _, _, self.pubkey = keypair(seed)
        self.metrics["keyswitches"] += 1

    def _sign(self, sign_type: int, payload: bytes) -> bytes:
        if sign_type == SIGN_TYPE_SHA256_ED25519:
            payload = hashlib.sha256(payload).digest()
        return sign(self.seed, payload)

    def poll_once(self) -> int:
        total = 0
        for ci, c in enumerate(self.clients):
            ring, out = c["in_ring"], c["out_ring"]
            n, self.seqs[ci], buf, sizes, sigs, ovr = ring.gather(
                self.seqs[ci], 16, ring.mtu)
            self.metrics["overruns"] += ovr
            for i in range(n):
                frame = bytes(buf[i, :sizes[i]])
                if not frame:
                    continue
                sign_type, payload = frame[0], frame[1:]
                if sign_type in (SIGN_TYPE_ED25519,
                                 SIGN_TYPE_SHA256_ED25519) and authorize(
                        self.pubkey, payload, c["role"], sign_type):
                    resp = b"\x01" + self._sign(sign_type, payload)
                    self.metrics["signed"] += 1
                else:
                    resp = b"\x00"
                    self.metrics["refused"] += 1
                while c["out_fseqs"] and out.credits(c["out_fseqs"]) <= 0:
                    self.metrics["backpressure"] += 1
                    time.sleep(20e-6)
                out.publish(resp, sig=int(sigs[i]))
            total += n
        return total

    def in_seqs(self):
        return {i: s for i, s in enumerate(self.seqs)}


class KeyguardClient:
    """Blocking request/response signing client (the fd_keyguard_client
    pattern): publish a request, spin on the response ring until the
    echoed request id appears."""

    def __init__(self, req_ring, resp_ring, req_fseqs=None):
        self.req = req_ring
        self.resp = resp_ring
        self.req_fseqs = req_fseqs or []
        self.resp_seq = 0
        self.next_id = 0

    def sign(self, payload: bytes,
             sign_type: int = SIGN_TYPE_ED25519,
             timeout_s: float = 30.0) -> bytes | None:
        """Returns the 64-byte signature, or None if refused."""
        rid = self.next_id
        self.next_id += 1
        while self.req_fseqs and self.req.credits(self.req_fseqs) <= 0:
            time.sleep(20e-6)
        self.req.publish(bytes([sign_type]) + payload, sig=rid)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            n, self.resp_seq, buf, sizes, sigs, _ = self.resp.gather(
                self.resp_seq, 8, self.resp.mtu)
            for i in range(n):
                if int(sigs[i]) == rid:
                    frame = bytes(buf[i, :sizes[i]])
                    return frame[1:65] if frame[:1] == b"\x01" else None
            if not n:
                time.sleep(50e-6)
        raise TimeoutError("sign request timed out")
