"""Keyswitch: live identity hot-swap through shared memory
(ref: src/disco/keyguard/fd_keyswitch.h + the set_identity action,
src/app/shared/commands/set_identity.c).

A 64-byte shm region per sign tile: [state u64 | seed 32B | pad].
The operator writes the new seed then flips state to SWITCH_PENDING;
the sign tile observes it at housekeeping, swaps its key material, and
acknowledges with COMPLETED — no restart, no dropped signing requests
(requests in flight sign with whichever key was live when polled)."""
from __future__ import annotations

import hashlib

import numpy as np

STATE_UNLOCKED = 0
STATE_PENDING = 1
STATE_COMPLETED = 2

FOOTPRINT = 64


def _checksum(seed: bytes, gen: int) -> bytes:
    return hashlib.sha256(b"fdtpu-keyswitch" + seed
                          + gen.to_bytes(8, "little")).digest()[:8]


def _view(wksp, off):
    return wksp.view(off, FOOTPRINT)


def read_state(wksp, off) -> int:
    return int(_view(wksp, off)[:8].view(np.uint64)[0])


def request_switch(wksp, off, seed: bytes) -> int:
    """Operator side: bump the request GENERATION, stage seed +
    checksum(seed, gen), then flip PENDING. The checksum makes a torn
    read (a racing second request) DETECTABLE — the tile skips and
    retries; the generation makes every request distinct, so
    re-requesting even the SAME seed can never interleave with an ack
    into a wedged PENDING-with-scrubbed-seed state. Returns the
    generation to pass to wait_completed."""
    assert len(seed) == 32
    v = _view(wksp, off)
    gen = int(v[48:56].view(np.uint64)[0]) + 1
    v[:8].view(np.uint64)[0] = STATE_UNLOCKED     # close the window
    v[8:40] = np.frombuffer(seed, np.uint8)
    v[40:48] = np.frombuffer(_checksum(seed, gen), np.uint8)
    v[48:56].view(np.uint64)[0] = gen
    v[:8].view(np.uint64)[0] = STATE_PENDING
    return gen


def poll_switch(wksp, off) -> tuple[bytes, int] | None:
    """Tile side: (seed, gen) if a switch is pending AND intact."""
    v = _view(wksp, off)
    if int(v[:8].view(np.uint64)[0]) != STATE_PENDING:
        return None
    seed = bytes(v[8:40])
    gen = int(v[48:56].view(np.uint64)[0])
    if bytes(v[40:48]) != _checksum(seed, gen):
        return None                  # torn write in progress: retry
    return seed, gen


def ack_switch(wksp, off, applied_gen: int) -> bool:
    """Tile side: complete the switch ONLY if the staged generation is
    still the one we applied — a racing newer request (same seed or
    not) is left pending for the next housekeeping (compare-and-ack on
    the generation, immune to same-seed interleavings)."""
    v = _view(wksp, off)
    if int(v[48:56].view(np.uint64)[0]) != applied_gen:
        return False                 # a newer request landed: leave it
    v[8:40] = 0                      # scrub the staged seed
    v[40:48] = 0
    v[:8].view(np.uint64)[0] = STATE_COMPLETED
    return True


def wait_completed(wksp, off, gen: int | None = None,
                   timeout_s: float = 30.0) -> bool:
    """Operator side: wait for OUR generation (or any, if None) to
    complete. A newer generation completing also counts — the key has
    moved past ours."""
    import time
    v = _view(wksp, off)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        st = read_state(wksp, off)
        cur = int(v[48:56].view(np.uint64)[0])
        if st == STATE_COMPLETED and (gen is None or cur >= gen):
            return True
        time.sleep(0.01)
    return False
