"""Keyswitch: live identity hot-swap through shared memory
(ref: src/disco/keyguard/fd_keyswitch.h + the set_identity action,
src/app/shared/commands/set_identity.c).

A 64-byte shm region per sign tile: [state u64 | seed 32B | pad].
The operator writes the new seed then flips state to SWITCH_PENDING;
the sign tile observes it at housekeeping, swaps its key material, and
acknowledges with COMPLETED — no restart, no dropped signing requests
(requests in flight sign with whichever key was live when polled)."""
from __future__ import annotations

import hashlib

import numpy as np

STATE_UNLOCKED = 0
STATE_PENDING = 1
STATE_COMPLETED = 2

FOOTPRINT = 64


def _checksum(seed: bytes) -> bytes:
    return hashlib.sha256(b"fdtpu-keyswitch" + seed).digest()[:8]


def _view(wksp, off):
    return wksp.view(off, FOOTPRINT)


def read_state(wksp, off) -> int:
    return int(_view(wksp, off)[:8].view(np.uint64)[0])


def request_switch(wksp, off, seed: bytes):
    """Operator side: stage the new 32-byte seed + its checksum, then
    flip PENDING. The checksum makes a torn read (a second request
    racing the tile's poll) DETECTABLE: the tile skips a seed whose
    checksum doesn't match and retries next housekeeping, so it can
    never rekey onto part-B/part-C garbage bytes."""
    assert len(seed) == 32
    v = _view(wksp, off)
    v[:8].view(np.uint64)[0] = STATE_UNLOCKED     # close the window
    v[8:40] = np.frombuffer(seed, np.uint8)
    v[40:48] = np.frombuffer(_checksum(seed), np.uint8)
    v[:8].view(np.uint64)[0] = STATE_PENDING


def poll_switch(wksp, off) -> bytes | None:
    """Tile side: new seed if a switch is pending AND intact."""
    v = _view(wksp, off)
    if int(v[:8].view(np.uint64)[0]) != STATE_PENDING:
        return None
    seed = bytes(v[8:40])
    if bytes(v[40:48]) != _checksum(seed):
        return None                  # torn write in progress: retry
    return seed


def ack_switch(wksp, off, applied_seed: bytes) -> bool:
    """Tile side: complete the switch ONLY if the region still stages
    the seed we applied — a second request racing the swap must not be
    scrubbed and falsely reported COMPLETED (compare-and-ack)."""
    v = _view(wksp, off)
    if bytes(v[8:40]) != applied_seed:
        return False                 # a newer request landed: leave it
    v[8:40] = 0                      # scrub the staged seed
    v[40:48] = 0
    v[:8].view(np.uint64)[0] = STATE_COMPLETED
    return True


def wait_completed(wksp, off, timeout_s: float = 30.0) -> bool:
    import time
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if read_state(wksp, off) == STATE_COMPLETED:
            return True
        time.sleep(0.01)
    return False
