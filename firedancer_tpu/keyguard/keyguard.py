"""Keyguard: role-based authorization of signing requests.

The sign tile is the ONLY process holding the identity key; every other
tile requests signatures over dedicated rings, and the keyguard decides
per (role, payload shape) whether the request may be signed — so a
compromised networking tile cannot exfiltrate signatures over payloads
outside its role (ref: src/disco/keyguard/fd_keyguard.h:1-30 roles;
src/disco/keyguard/fd_keyguard_authorize.c — the role switch and the
per-payload-type shape checks mirrored here).

Payload identification is structural (shape heuristics over the bytes),
then each role admits only its own payload types:

  LEADER  32-byte merkle roots (shred signing; the reference notes the
          shred/ping ambiguity and allows it, authorize.c is_shred_ping)
  GOSSIP  ping/pong tokens, prune messages (must start with our own
          pubkey, authorize.c:90), CRDS values
  REPAIR  ping/pong + repair requests (u32 discriminant 8..11 followed
          by OUR pubkey, authorize.c:94-113)
  SEND    vote transaction messages (structurally a txn message)
"""
from __future__ import annotations

from ..protocol.txn import parse_message_shape

SIGN_TYPE_ED25519 = 0
SIGN_TYPE_SHA256_ED25519 = 1          # sign(sha256(payload)); pong path

ROLE_SEND = 0
ROLE_GOSSIP = 1
ROLE_LEADER = 2
ROLE_REPAIR = 3
ROLE_NAMES = {ROLE_SEND: "send", ROLE_GOSSIP: "gossip",
              ROLE_LEADER: "leader", ROLE_REPAIR: "repair"}

PAYLOAD_TXN = 1 << 0
PAYLOAD_SHRED = 1 << 1
PAYLOAD_GOSSIP = 1 << 2
PAYLOAD_PRUNE = 1 << 3
PAYLOAD_REPAIR = 1 << 4
PAYLOAD_PING = 1 << 5
PAYLOAD_PONG = 1 << 6

SIGN_REQ_MTU = 1280
PING_TOKEN_PREFIX = b"SOLANA_PING_PONG"

# repair protocol discriminants (window_index..ancestor_hashes span)
_REPAIR_DISC_MIN, _REPAIR_DISC_MAX = 8, 11


def payload_match(identity_pubkey: bytes, data: bytes,
                  sign_type: int) -> int:
    """Structural identification mask (ref: fd_keyguard_match.c role —
    re-derived shapes, not a port)."""
    mask = 0
    sz = len(data)
    if sz == 32:
        if sign_type == SIGN_TYPE_ED25519:
            mask |= PAYLOAD_SHRED               # a bare merkle root
            if data[:16] == PING_TOKEN_PREFIX:
                mask |= PAYLOAD_PING
    if sz == 48 and sign_type == SIGN_TYPE_SHA256_ED25519 \
            and data[:16] == PING_TOKEN_PREFIX:
        mask |= PAYLOAD_PONG
    if sign_type == SIGN_TYPE_ED25519:
        if sz >= 40 and data[:32] == identity_pubkey:
            mask |= PAYLOAD_PRUNE               # prune leads with our key
        if sz >= 80 and _REPAIR_DISC_MIN <= int.from_bytes(
                data[:4], "little") <= _REPAIR_DISC_MAX \
                and data[4:36] == identity_pubkey:
            mask |= PAYLOAD_REPAIR
        if parse_message_shape(data):
            mask |= PAYLOAD_TXN
        if sz >= 64 and not (mask & (PAYLOAD_TXN | PAYLOAD_REPAIR
                                     | PAYLOAD_PRUNE)):
            mask |= PAYLOAD_GOSSIP              # CRDS value fallback
    return mask


def authorize(identity_pubkey: bytes, data: bytes, role: int,
              sign_type: int) -> bool:
    """May `role` sign `data`? (ref: fd_keyguard_payload_authorize)"""
    if len(data) > SIGN_REQ_MTU:
        return False
    mask = payload_match(identity_pubkey, data, sign_type)
    if mask == 0:
        return False
    if role == ROLE_LEADER:
        # shreds only (ping ambiguity tolerated, ref authorize.c
        # is_shred_ping — both are 32-byte ed25519 payloads)
        return bool(mask & PAYLOAD_SHRED)
    if role == ROLE_GOSSIP:
        return bool(mask & (PAYLOAD_PING | PAYLOAD_PONG | PAYLOAD_PRUNE
                            | PAYLOAD_GOSSIP))
    if role == ROLE_REPAIR:
        return bool(mask & (PAYLOAD_PING | PAYLOAD_PONG | PAYLOAD_REPAIR))
    if role == ROLE_SEND:
        return bool(mask & PAYLOAD_TXN)
    return False
