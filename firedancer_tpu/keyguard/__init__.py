"""keyguard: identity-key custody + role-authorized signing
(ref: src/disco/keyguard/, src/disco/sign/fd_sign_tile.c)."""
from .keyguard import (  # noqa: F401
    ROLE_GOSSIP, ROLE_LEADER, ROLE_REPAIR, ROLE_SEND,
    SIGN_TYPE_ED25519, SIGN_TYPE_SHA256_ED25519, authorize, payload_match,
)
from .tile import KeyguardClient, SignTile  # noqa: F401
