"""Host runtime: Python bindings to the native tango-semantics layer.

The native library (firedancer_tpu/native/) provides the shared-memory
workspace, frag rings, flow control, cnc and tcache (reference semantics:
src/tango/). This package wraps it with ctypes for tile orchestration and
the TPU bridge; hot paths (publish, gather) stay in C++.
"""
from .tango import (  # noqa: F401
    Workspace, Ring, Fseq, Cnc, Store, Tcache, TraceRing, KnobMailbox,
    lib, CNC_BOOT, CNC_RUN, CNC_HALT, CNC_FAIL, FSEQ_STALE,
    TRACE_LINK_NONE,
)
