"""ctypes bindings for the native fdtpu runtime (see native/fdtpu.h).

Layout convention: a Workspace is a named shm segment; objects (rings,
fseqs, cncs, tcaches, payload arenas) are carved out of it at 64-byte
aligned offsets by the topology builder. Offsets — not pointers — are the
inter-process currency, mirroring the reference's gaddr/chunk discipline
(ref: src/util/wksp/fd_wksp.h:27-47, src/tango/fd_tango_base.h:105-112).
"""
from __future__ import annotations

import ctypes as ct
import os
import subprocess

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), os.pardir, "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libfdtpu.so"))

CNC_BOOT, CNC_RUN, CNC_HALT, CNC_FAIL = 0, 1, 2, 3


def _build():
    # always invoke make: its dependency check is a no-op when fresh, and
    # this prevents a stale .so from shadowing edited C++ source
    subprocess.run(["make", "-s", "-C", os.path.abspath(_NATIVE_DIR)],
                   check=True)


def _load():
    _build()
    lib = ct.CDLL(_LIB_PATH)
    u64, i64, u32, u16, vp, cp = (ct.c_uint64, ct.c_int64, ct.c_uint32,
                                  ct.c_uint16, ct.c_void_p, ct.c_char_p)
    sigs = {
        "fdtpu_wksp_join": (vp, [cp, u64, ct.c_int]),
        "fdtpu_wksp_leave": (ct.c_int, [vp, u64]),
        "fdtpu_wksp_unlink": (ct.c_int, [cp]),
        "fdtpu_ring_footprint": (u64, [u64]),
        "fdtpu_ring_init": (ct.c_int, [vp, u64, u64]),
        "fdtpu_ring_depth": (u64, [vp, u64]),
        "fdtpu_ring_seq": (u64, [vp, u64]),
        "fdtpu_ring_prepare": (u64, [vp, u64]),
        "fdtpu_ring_publish": (u64, [vp, u64, u64, u64, u32, u16, u16]),
        "fdtpu_ring_consume": (ct.c_int, [vp, u64, u64, vp]),
        "fdtpu_ring_publish_batch": (
            i64, [vp, u64, ct.POINTER(ct.c_uint8), u64,
                  ct.POINTER(u32), ct.POINTER(u64),
                  ct.POINTER(ct.c_uint8), i64, i64, u64, u64,
                  ct.POINTER(u64), ct.c_int, ct.POINTER(i64)]),
        "fdtpu_fseq_footprint": (u64, []),
        "fdtpu_fseq_init": (ct.c_int, [vp, u64, u64]),
        "fdtpu_fseq_query": (u64, [vp, u64]),
        "fdtpu_fseq_update": (None, [vp, u64, u64]),
        "fdtpu_fctl_credits": (i64, [vp, u64, ct.POINTER(u64), ct.c_int]),
        "fdtpu_cnc_footprint": (u64, []),
        "fdtpu_cnc_init": (ct.c_int, [vp, u64]),
        "fdtpu_cnc_state": (u32, [vp, u64]),
        "fdtpu_cnc_set_state": (None, [vp, u64, u32]),
        "fdtpu_cnc_heartbeat": (None, [vp, u64, u64]),
        "fdtpu_cnc_last_heartbeat": (u64, [vp, u64]),
        "fdtpu_tcache_footprint": (u64, [u64]),
        "fdtpu_tcache_init": (ct.c_int, [vp, u64, u64]),
        "fdtpu_tcache_query": (ct.c_int, [vp, u64, u64]),
        "fdtpu_tcache_insert": (ct.c_int, [vp, u64, u64]),
        "fdtpu_ring_gather": (i64, [vp, u64, ct.POINTER(u64), i64,
                                    ct.POINTER(ct.c_uint8), u64,
                                    ct.POINTER(u32), ct.POINTER(u64),
                                    ct.POINTER(u64), ct.POINTER(u64)]),
        "fdtpu_ticks": (u64, []),
        "fdtpu_txn_parse_batch": (i64, [ct.POINTER(ct.c_uint8),
                                        ct.POINTER(u32), i64, u64, u64, u64,
                                        ct.POINTER(ct.c_int32),
                                        ct.POINTER(u64)]),
        "fdtpu_verify_assemble": (i64, [ct.POINTER(ct.c_uint8),
                                        ct.POINTER(u32),
                                        ct.POINTER(ct.c_int32),
                                        ct.POINTER(ct.c_uint8), i64, u64,
                                        ct.POINTER(i64), i64, u64,
                                        ct.POINTER(ct.c_uint8),
                                        ct.POINTER(ct.c_uint8),
                                        ct.POINTER(ct.c_uint8),
                                        ct.POINTER(ct.c_int32),
                                        ct.POINTER(ct.c_int32)]),
        "fdtpu_tcache_query_batch": (ct.c_int, [vp, u64, ct.POINTER(u64),
                                                ct.POINTER(ct.c_uint8), i64,
                                                ct.POINTER(ct.c_uint8)]),
        "fdtpu_tcache_insert_batch": (ct.c_int, [vp, u64, ct.POINTER(u64),
                                                 ct.POINTER(ct.c_uint8), i64,
                                                 ct.POINTER(ct.c_uint8)]),
        "fdtpu_store_footprint": (u64, [u64, u64, u64]),
        "fdtpu_store_init": (ct.c_int, [vp, u64, u64, u64, u64]),
        "fdtpu_store_txn_prepare": (ct.c_int, [vp, u64, u64, u64]),
        "fdtpu_store_txn_cancel": (ct.c_int, [vp, u64, u64]),
        "fdtpu_store_txn_publish": (ct.c_int, [vp, u64, u64]),
        "fdtpu_store_txn_exists": (ct.c_int, [vp, u64, u64]),
        "fdtpu_store_txn_parent": (i64, [vp, u64, u64]),
        "fdtpu_store_txn_children": (i64, [vp, u64, u64,
                                           ct.POINTER(u64), i64]),
        "fdtpu_store_put": (ct.c_int, [vp, u64, u64, cp, cp, u64,
                                       ct.c_int]),
        "fdtpu_store_get": (i64, [vp, u64, u64, cp,
                                  ct.POINTER(ct.c_uint8), u64]),
        "fdtpu_store_iter": (i64, [vp, u64, u64, ct.POINTER(u64),
                                   ct.POINTER(ct.c_uint8),
                                   ct.POINTER(ct.c_uint8), u64,
                                   ct.POINTER(ct.c_int32)]),
        "fdtpu_store_rec_cnt": (u64, [vp, u64]),
    }
    for name, (res, args) in sigs.items():
        fn = getattr(lib, name)
        fn.restype = res
        fn.argtypes = args
    return lib


lib = _load()


class Frag(ct.Structure):
    _fields_ = [("seq", ct.c_uint64), ("sig", ct.c_uint64),
                ("off", ct.c_uint64), ("sz", ct.c_uint32),
                ("ctl", ct.c_uint16), ("orig", ct.c_uint16),
                ("tspub", ct.c_uint32)]


class Workspace:
    """Named shared-memory workspace with a bump allocator for layout.

    The bump cursor is Python-side state used only at topology-build time;
    joiners reconstruct offsets from the topology description, never from
    the cursor (offsets are the ABI).
    """

    def __init__(self, name: str, size: int, create: bool = True,
                 replace: bool = True):
        """create=True makes a fresh segment. replace=True (the default)
        additionally unlinks a stale leftover from a crashed run — callers
        must follow single-creator discipline (one topology builder
        creates; every other process joins with create=False), because
        replacing a name a LIVE process has mapped splits the two onto
        different memory. Use replace=False for strict exclusive create."""
        self.name, self.size = name, size
        mode = (2 if replace else 1) if create else 0
        self.base = lib.fdtpu_wksp_join(name.encode(), size, mode)
        if not self.base:
            raise OSError(f"wksp join failed: {name}")
        self._cursor = 64

    def alloc(self, footprint: int, align: int = 64) -> int:
        off = (self._cursor + align - 1) & ~(align - 1)
        if off + footprint > self.size:
            raise MemoryError("workspace exhausted")
        self._cursor = off + footprint
        return off

    def view(self, off: int, sz: int) -> np.ndarray:
        """uint8 numpy view of [off, off+sz) — zero-copy payload access."""
        buf = (ct.c_uint8 * sz).from_address(self.base + off)
        return np.ctypeslib.as_array(buf)

    def close(self):
        if self.base:
            lib.fdtpu_wksp_leave(self.base, self.size)
            self.base = None

    def unlink(self):
        lib.fdtpu_wksp_unlink(self.name.encode())

    @staticmethod
    def unlink_name(name: str):
        lib.fdtpu_wksp_unlink(name.encode())


class Ring:
    """Single-producer frag ring + payload arena inside a workspace.

    Every join additionally carries INSTANCE-LOCAL telemetry counters
    (m_pub/m_pub_bytes/m_backpressure on the publish side,
    m_consumed/m_bytes/m_overruns on the gather side) bumped by the
    hot-path methods below. They are plain Python ints — no shared
    memory, no cross-process cost — and because each tile process joins
    its own Ring per link, they ARE that tile's per-link counters: the
    stem flushes them into the per-link shm telemetry blocks at the
    housekeeping cadence (disco/metrics.py link ABI, the reference's
    per-link-pair regime counters, src/disco/metrics/fd_metrics.h)."""

    def __init__(self, wksp: Workspace, off: int, depth: int,
                 arena_off: int = 0, mtu: int = 0, init: bool = False):
        self.wksp, self.off, self.depth = wksp, off, depth
        self.arena_off, self.mtu = arena_off, mtu
        # producer-side link telemetry (publish/publish_batch/credits)
        self.m_pub = 0
        self.m_pub_bytes = 0
        self.m_backpressure = 0
        # consumer-side link telemetry (gather/consume)
        self.m_consumed = 0
        self.m_bytes = 0
        self.m_overruns = 0
        if init:
            rc = lib.fdtpu_ring_init(wksp.base, off, depth)
            if rc:
                raise ValueError("ring init failed (depth power of 2?)")

    @classmethod
    def create(cls, wksp: Workspace, depth: int, mtu: int = 0) -> "Ring":
        mtu = (mtu + 63) & ~63  # chunk-index addressing needs 64B alignment
        off = wksp.alloc(lib.fdtpu_ring_footprint(depth))
        arena_off = wksp.alloc(depth * mtu) if mtu else 0
        return cls(wksp, off, depth, arena_off, mtu, init=True)

    @property
    def seq(self) -> int:
        return lib.fdtpu_ring_seq(self.wksp.base, self.off)

    def publish(self, payload: bytes | np.ndarray, sig: int = 0,
                ctl: int = 3, orig: int = 0) -> int:
        """Prepare (invalidate slot), copy payload into the slot's arena
        chunk, publish. ctl=3 is SOM|EOM (single-frag message)."""
        assert self.mtu, "ring has no payload arena"
        seq = lib.fdtpu_ring_prepare(self.wksp.base, self.off)
        slot_off = self.arena_off + (seq % self.depth) * self.mtu
        assert slot_off % 64 == 0 and slot_off < (1 << 38), \
            "arena offset outside 32-bit chunk-index range"
        data = np.frombuffer(payload, np.uint8) if isinstance(
            payload, (bytes, bytearray)) else payload
        assert data.nbytes <= self.mtu
        self.wksp.view(slot_off, data.nbytes)[:] = data
        self.m_pub += 1
        self.m_pub_bytes += data.nbytes
        return lib.fdtpu_ring_publish(self.wksp.base, self.off, sig,
                                      slot_off, data.nbytes, ctl, orig)

    def publish_batch(self, buf: np.ndarray, sizes: np.ndarray,
                      sigs: np.ndarray, mask: np.ndarray,
                      fseqs: list["Fseq"] | None = None,
                      start: int = 0) -> tuple[int, int]:
        """Credit-gated native publish of masked rows of a gathered
        (n, stride) buffer — the verify tile's egress hot loop in ONE
        C call. Returns (stop_row, published): stop_row < len(buf)
        means credits ran out; heartbeat and resume from stop_row."""
        assert self.mtu, "ring has no payload arena"
        n, stride = buf.shape
        buf = np.ascontiguousarray(buf, np.uint8)
        sizes = np.ascontiguousarray(sizes, np.uint32)
        assert not len(sizes) or int(sizes.max()) <= self.mtu, \
            "payload larger than ring mtu"
        sigs = np.ascontiguousarray(sigs, np.uint64)
        mask = np.ascontiguousarray(mask, np.uint8)
        offs = (ct.c_uint64 * len(fseqs))(*[f.off for f in fseqs]) \
            if fseqs else None
        pub = ct.c_int64(0)
        stop = lib.fdtpu_ring_publish_batch(
            self.wksp.base, self.off,
            buf.ctypes.data_as(ct.POINTER(ct.c_uint8)), stride,
            sizes.ctypes.data_as(ct.POINTER(ct.c_uint32)),
            sigs.ctypes.data_as(ct.POINTER(ct.c_uint64)),
            mask.ctypes.data_as(ct.POINTER(ct.c_uint8)),
            start, n, self.arena_off, self.mtu,
            offs, len(fseqs) if fseqs else 0, ct.byref(pub))
        stop, pub = int(stop), int(pub.value)
        if pub:
            self.m_pub += pub
            live = mask[start:stop] != 0
            self.m_pub_bytes += int(sizes[start:stop][live].sum())
        if stop < n:
            self.m_backpressure += 1     # credits ran out mid-batch
        return stop, pub

    def consume(self, seq: int):
        """-> (rc, Frag). rc 0=ok, 1=not yet, -1=overrun."""
        frag = Frag()
        rc = lib.fdtpu_ring_consume(self.wksp.base, self.off, seq,
                                    ct.byref(frag))
        if rc == 0:
            self.m_consumed += 1
            self.m_bytes += frag.sz
        elif rc == -1:
            self.m_overruns += 1
        return rc, frag

    def payload(self, frag: Frag) -> np.ndarray:
        return self.wksp.view(frag.off, frag.sz)

    def gather(self, seq: int, max_n: int, stride: int,
               want_seqs: bool = False):
        """Drain up to max_n frags into a fresh (max_n, stride) buffer.

        Returns (n, new_seq, buf, sizes, sigs, overruns) — the microbatch
        assembly step of the TPU bridge tile. With want_seqs, appends the
        per-frag seq array (the round-robin sharding key,
        ref: src/disco/verify/fd_verify_tile.c:49-53)."""
        buf = np.zeros((max_n, stride), np.uint8)
        sizes = np.zeros(max_n, np.uint32)
        sigs = np.zeros(max_n, np.uint64)
        seqs = np.zeros(max_n, np.uint64) if want_seqs else None
        seq_io = ct.c_uint64(seq)
        ovr = ct.c_uint64(0)
        n = lib.fdtpu_ring_gather(
            self.wksp.base, self.off, ct.byref(seq_io), max_n,
            buf.ctypes.data_as(ct.POINTER(ct.c_uint8)), stride,
            sizes.ctypes.data_as(ct.POINTER(ct.c_uint32)),
            sigs.ctypes.data_as(ct.POINTER(ct.c_uint64)), ct.byref(ovr),
            seqs.ctypes.data_as(ct.POINTER(ct.c_uint64))
            if want_seqs else None)
        if n:
            self.m_consumed += int(n)
            self.m_bytes += int(sizes[:n].sum())
        self.m_overruns += int(ovr.value)
        if want_seqs:
            return n, seq_io.value, buf, sizes, sigs, ovr.value, seqs
        return n, seq_io.value, buf, sizes, sigs, ovr.value

    def credits(self, fseqs: list["Fseq"]) -> int:
        offs = (ct.c_uint64 * len(fseqs))(*[f.off for f in fseqs])
        c = lib.fdtpu_fctl_credits(self.wksp.base, self.off, offs,
                                   len(fseqs))
        if c <= 0:
            self.m_backpressure += 1     # a blocked publish attempt
        return c


TRACE_REC_U64 = 4             # ts_ns | sig | arg | meta(etype/link/count)
TRACE_REC_SZ = TRACE_REC_U64 * 8
TRACE_HDR_U64 = 8             # [0] cursor, [1] depth, rest reserved
TRACE_LINK_NONE = 0xFFFF


class TraceRing:
    """Per-tile flight-recorder event ring in the workspace — the same
    design as the frag mcache (fixed depth, overwrite-oldest, cursor is
    the total-records-written count) but for 32-byte trace records, and
    pure Python/numpy: a single writer (the owning tile) appends, any
    process attached to the workspace snapshots. The region survives
    the tile's death — the supervisor reads a dead tile's last events
    out of shm for the black-box dump (trace/export.py).

    Record layout (4 little-endian u64 words):

        [0] ts_ns   end timestamp (utils/tempo.monotonic_ns — the cnc
                    heartbeat clock, so traces and watchdog decisions
                    share one timeline)
        [1] sig     frag lineage key (the frag's sig / dedup tag; 0 if
                    the event is not frag-scoped)
        [2] arg     span duration in ns (0 for instant events)
        [3] meta    etype | link_id << 16 | count << 32
                    (etype: trace/events.py; link_id indexes the
                    plan's sorted link names, TRACE_LINK_NONE if none)
    """

    def __init__(self, wksp: Workspace, off: int, depth: int,
                 init: bool = False):
        if depth <= 0 or depth & (depth - 1):
            raise ValueError(f"trace depth {depth} not a power of two")
        self.wksp, self.off, self.depth = wksp, off, depth
        self._v = wksp.view(off, self.footprint(depth)).view(np.uint64)
        if init:
            self._v[:] = 0
            self._v[1] = depth

    @staticmethod
    def footprint(depth: int) -> int:
        return (TRACE_HDR_U64 + depth * TRACE_REC_U64) * 8

    @classmethod
    def create(cls, wksp: Workspace, depth: int) -> "TraceRing":
        off = wksp.alloc(cls.footprint(depth))
        return cls(wksp, off, depth, init=True)

    @property
    def cursor(self) -> int:
        return int(self._v[0])

    def append(self, ts_ns: int, etype: int, sig: int = 0, arg: int = 0,
               link: int = TRACE_LINK_NONE, count: int = 0):
        """Lock-free single-writer append (overwrites the oldest record
        once full; the cursor keeps counting so readers know how much
        history was lost). Record words land before the cursor bump, so
        a racing reader never sees a half-written CURRENT record — it
        can still see a torn overwritten slot, the documented snapshot
        caveat."""
        v = self._v
        cur = int(v[0])
        base = TRACE_HDR_U64 + (cur & (self.depth - 1)) * TRACE_REC_U64
        m64 = (1 << 64) - 1
        v[base] = ts_ns & m64
        v[base + 1] = int(sig) & m64
        v[base + 2] = int(arg) & m64
        v[base + 3] = (etype & 0xFFFF) | ((link & 0xFFFF) << 16) \
            | ((int(count) & 0xFFFFFFFF) << 32)
        v[0] = cur + 1

    def append_batch(self, ts_ns: int, etype: int, sigs,
                     arg: int = 0, link: int = TRACE_LINK_NONE,
                     count: int = 0):
        """Vectorized single-writer append of one record per sig: the
        whole batch lands with ONE cursor bump (numpy scatter into the
        ring view — no per-record Python). All records share the batch
        timestamp/arg/link/meta; `sig` is the per-record lineage key.
        When the batch exceeds the ring depth only the newest `depth`
        records are materialized, but the cursor still counts every
        one, so readers see the correct history-loss accounting."""
        sigs = np.asarray(sigs, np.uint64)
        n = len(sigs)
        if not n:
            return
        v = self._v
        cur = int(v[0])
        keep = sigs[-self.depth:] if n > self.depth else sigs
        m = len(keep)
        slot = (cur + (n - m) + np.arange(m, dtype=np.int64)) \
            & (self.depth - 1)
        base = TRACE_HDR_U64 + slot * TRACE_REC_U64
        m64 = (1 << 64) - 1
        v[base] = ts_ns & m64
        v[base + 1] = keep
        v[base + 2] = int(arg) & m64
        v[base + 3] = (etype & 0xFFFF) | ((link & 0xFFFF) << 16) \
            | ((int(count) & 0xFFFFFFFF) << 32)
        v[0] = cur + n

    def snapshot(self) -> tuple[int, np.ndarray]:
        """-> (cursor, records (n, 4) u64 oldest-first, n <= depth).
        A copy — safe to decode while the writer keeps appending; a
        record being overwritten concurrently may read torn (one
        record out of `depth`, oldest-first, and only on a LIVE tile —
        post-mortem reads are exact)."""
        raw = np.array(self._v, copy=True)
        cur = int(raw[0])
        recs = raw[TRACE_HDR_U64:TRACE_HDR_U64
                   + self.depth * TRACE_REC_U64].reshape(
                       self.depth, TRACE_REC_U64)
        n = min(cur, self.depth)
        if not n:
            return cur, recs[:0]
        idx = [(cur - n + i) & (self.depth - 1) for i in range(n)]
        return cur, recs[idx]

    def snapshot_since(self, since: int) -> tuple[int, np.ndarray, int]:
        """Incremental snapshot for periodic drainers (the fdflight
        recorder): only records appended after a prior cursor value.
        -> (cursor, records (n, 4) u64 oldest-first, lost) where `lost`
        counts records overwritten before this pass could read them —
        the drain cadence was slower than the write rate. Same torn-
        record caveat as snapshot()."""
        cur, recs = self.snapshot()
        new = cur - since
        if new <= 0:
            return cur, recs[:0], 0
        lost = max(0, new - len(recs))
        return cur, recs[max(0, len(recs) - new):], lost


TUNE_HDR_U64 = 8              # [0] generation, [1] knob count, reserved
TUNE_SLOT_U64 = 4             # value | seq | ts_ns | reserved


class KnobMailbox:
    """Bounded shm knob mailbox (fdtune): the controller tile's ONLY
    write surface onto the running topology. One fixed slot per knob
    in the plan's `tune_knobs` order (the inter-process ABI, like
    metric slot names), single writer per region — the controller tile
    alone posts, every adapter polls its effective knobs read-side at
    housekeeping cadence (fdlint ownership catalogs the region as
    "knob-mailbox").

    Slot layout (4 little-endian u64 words):

        [0] value   current knob value (unsigned integer domain —
                    us for windows, counts for waves/depths/levels)
        [1] seq     posts to THIS knob (0 = never steered: readers
                    keep their configured value — the disabled/idle
                    fast path never overrides config)
        [2] ts_ns   utils/tempo.monotonic_ns of the last post (the
                    trace/heartbeat clock, so decisions line up with
                    EV_TUNE records on one timeline)
        [3]         reserved

    Write ordering mirrors TraceRing: the slot words land before the
    slot seq bump, and the header generation bumps last, so a reader
    that snapshots on a generation change never sees a half-posted
    knob. Readers poll ~100/s, the writer posts a few times a minute —
    torn reads are the same one-slot-in-flight caveat as TraceRing."""

    def __init__(self, wksp: Workspace, off: int, n_knobs: int,
                 init: bool = False):
        if n_knobs <= 0:
            raise ValueError(f"knob mailbox needs >= 1 knob, got "
                             f"{n_knobs}")
        self.wksp, self.off, self.n_knobs = wksp, off, n_knobs
        self._v = wksp.view(off, self.footprint(n_knobs)).view(np.uint64)
        if init:
            self._v[:] = 0
            self._v[1] = n_knobs

    @staticmethod
    def footprint(n_knobs: int) -> int:
        return (TUNE_HDR_U64 + n_knobs * TUNE_SLOT_U64) * 8

    @classmethod
    def create(cls, wksp: Workspace, n_knobs: int) -> "KnobMailbox":
        off = wksp.alloc(cls.footprint(n_knobs))
        return cls(wksp, off, n_knobs, init=True)

    @property
    def generation(self) -> int:
        """Total posts across every knob (readers cheap-check this
        before rescanning slots)."""
        return int(self._v[0])

    def post(self, idx: int, value: int, ts_ns: int = 0):
        """Single-writer post (the controller tile alone): land the
        slot words, then the slot seq, then the generation."""
        if not 0 <= idx < self.n_knobs:
            raise IndexError(f"knob index {idx} out of range "
                             f"[0, {self.n_knobs})")
        v = self._v
        base = TUNE_HDR_U64 + idx * TUNE_SLOT_U64
        m64 = (1 << 64) - 1
        v[base] = int(value) & m64
        v[base + 2] = int(ts_ns) & m64
        v[base + 1] = int(v[base + 1]) + 1
        v[0] = int(v[0]) + 1

    def read(self, idx: int) -> tuple[int, int]:
        """-> (value, seq). seq == 0 means never posted — the reader
        keeps its configured value."""
        base = TUNE_HDR_U64 + idx * TUNE_SLOT_U64
        seq = int(self._v[base + 1])
        return (int(self._v[base]), seq)

    def snapshot(self):
        """One-pass copy -> (generation, (n_knobs, 4) u64 slots) —
        the coherent read for monitors/gui (u64_snapshot contract)."""
        raw = np.array(self._v, copy=True)
        return int(raw[0]), raw[TUNE_HDR_U64:].reshape(
            self.n_knobs, TUNE_SLOT_U64)


FSEQ_STALE = (1 << 64) - 1    # sentinel: consumer excluded from fctl


def u64_snapshot(view_u64):
    """One-shot copy of a live shm u64 view. Field reads against the
    copy are mutually coherent-enough: a writer landing between two
    subscript loads of the LIVE view hands the reader fields from two
    different states (the torn-read lint rule), while the copy is
    taken in a single pass."""
    import numpy as np
    return np.array(view_u64, copy=True)


class Fseq:
    def __init__(self, wksp: Workspace, off: int | None = None,
                 seq0: int = 0):
        self.wksp = wksp
        if off is None:
            off = wksp.alloc(lib.fdtpu_fseq_footprint())
            lib.fdtpu_fseq_init(wksp.base, off, seq0)
        self.off = off

    def query(self) -> int:
        return lib.fdtpu_fseq_query(self.wksp.base, self.off)

    def update(self, seq: int):
        lib.fdtpu_fseq_update(self.wksp.base, self.off, seq)

    def mark_stale(self):
        """Exclude this consumer from upstream credit flow (dead or
        restarting tile — the native fctl skips the sentinel, so the
        producer never wedges on a consumer that stopped advancing).
        Cleared by the next real update()."""
        lib.fdtpu_fseq_update(self.wksp.base, self.off, FSEQ_STALE)

    def is_stale(self) -> bool:
        return self.query() == FSEQ_STALE


class Cnc:
    def __init__(self, wksp: Workspace, off: int | None = None):
        self.wksp = wksp
        if off is None:
            off = wksp.alloc(lib.fdtpu_cnc_footprint())
            lib.fdtpu_cnc_init(wksp.base, off)
        self.off = off

    @property
    def state(self) -> int:
        return lib.fdtpu_cnc_state(self.wksp.base, self.off)

    @state.setter
    def state(self, st: int):
        lib.fdtpu_cnc_set_state(self.wksp.base, self.off, st)

    def heartbeat(self):
        lib.fdtpu_cnc_heartbeat(self.wksp.base, self.off, lib.fdtpu_ticks())

    @property
    def last_heartbeat(self) -> int:
        return lib.fdtpu_cnc_last_heartbeat(self.wksp.base, self.off)


class Tcache:
    def __init__(self, wksp: Workspace, depth: int, off: int | None = None):
        self.wksp, self.depth = wksp, depth
        if off is None:
            off = wksp.alloc(lib.fdtpu_tcache_footprint(depth))
            lib.fdtpu_tcache_init(wksp.base, off, depth)
        self.off = off

    def query(self, tag: int) -> bool:
        """True iff tag is present (no mutation)."""
        return bool(lib.fdtpu_tcache_query(self.wksp.base, self.off, tag))

    def insert(self, tag: int) -> bool:
        """True iff tag was already present (duplicate)."""
        return bool(lib.fdtpu_tcache_insert(self.wksp.base, self.off, tag))

    def query_batch(self, tags, mask=None):
        """tags (n,) uint64 -> (n,) uint8 hit flags (native loop)."""
        import numpy as np
        tags = np.ascontiguousarray(tags, np.uint64)
        hit = np.zeros(len(tags), np.uint8)
        mp = (mask.ctypes.data_as(ct.POINTER(ct.c_uint8))
              if mask is not None else None)
        lib.fdtpu_tcache_query_batch(
            self.wksp.base, self.off,
            tags.ctypes.data_as(ct.POINTER(ct.c_uint64)), mp, len(tags),
            hit.ctypes.data_as(ct.POINTER(ct.c_uint8)))
        return hit

    def insert_batch(self, tags, mask=None):
        """tags (n,) uint64 -> (n,) uint8 was-duplicate flags. mask: only
        insert where mask[i] != 0."""
        import numpy as np
        tags = np.ascontiguousarray(tags, np.uint64)
        dup = np.zeros(len(tags), np.uint8)
        mp = (mask.ctypes.data_as(ct.POINTER(ct.c_uint8))
              if mask is not None else None)
        lib.fdtpu_tcache_insert_batch(
            self.wksp.base, self.off,
            tags.ctypes.data_as(ct.POINTER(ct.c_uint64)), mp, len(tags),
            dup.ctypes.data_as(ct.POINTER(ct.c_uint8)))
        return dup


class Store:
    """Raw view of a carved funk store region (native/fdtpu.cc store —
    the fork-aware shm record tree). This layer speaks the native ABI
    verbatim: u64 xids (0 = published root), 32-byte keys, bytes values,
    negative error codes. The Python funk semantics (hashable xids,
    typed values, FunkTxnError) live in funk/shmfunk.py; tiles attaching
    cross-process use this class directly with wire-interned xids."""

    def __init__(self, wksp: Workspace, off: int | None = None,
                 rec_max: int = 4096, txn_max: int = 256,
                 heap_sz: int = 1 << 24):
        self.wksp = wksp
        if off is None:
            off = wksp.alloc(lib.fdtpu_store_footprint(
                rec_max, txn_max, heap_sz))
            rc = lib.fdtpu_store_init(wksp.base, off, rec_max, txn_max,
                                      heap_sz)
            if rc != 0:
                raise ValueError(f"store init failed: {rc}")
        self.off = off
        # reusable value buffer, grown on demand (get() reports the true
        # size so a too-small read retries once)
        self._buf = (ct.c_uint8 * 4096)()

    @staticmethod
    def footprint(rec_max: int, txn_max: int, heap_sz: int) -> int:
        return int(lib.fdtpu_store_footprint(rec_max, txn_max, heap_sz))

    def _grow(self, n: int):
        cap = len(self._buf)
        while cap < n:
            cap *= 2
        self._buf = (ct.c_uint8 * cap)()

    # -- txn tree (raw u64 xids) -------------------------------------------

    def txn_prepare(self, parent_xid: int, xid: int) -> int:
        return lib.fdtpu_store_txn_prepare(self.wksp.base, self.off,
                                           parent_xid, xid)

    def txn_cancel(self, xid: int) -> int:
        return lib.fdtpu_store_txn_cancel(self.wksp.base, self.off, xid)

    def txn_publish(self, xid: int) -> int:
        return lib.fdtpu_store_txn_publish(self.wksp.base, self.off, xid)

    def txn_exists(self, xid: int) -> bool:
        return bool(lib.fdtpu_store_txn_exists(self.wksp.base, self.off,
                                               xid))

    def txn_parent(self, xid: int) -> int:
        """Parent xid (0 = root child), -2 when xid is unknown."""
        return int(lib.fdtpu_store_txn_parent(self.wksp.base, self.off,
                                              xid))

    def txn_children(self, xid: int) -> list[int]:
        cap = 64
        while True:
            out = (ct.c_uint64 * cap)()
            n = lib.fdtpu_store_txn_children(self.wksp.base, self.off,
                                             xid, out, cap)
            if n == -2:
                raise KeyError(f"unknown txn {xid}")
            if n <= cap:
                return [int(out[i]) for i in range(n)]
            cap = n

    # -- records ------------------------------------------------------------

    @staticmethod
    def _key32(key: bytes) -> bytes:
        """The native ABI reads EXACTLY 32 key bytes — a shorter python
        buffer would make C hash whatever trails it in memory, which
        differs per process (a record written by one tile becomes
        unfindable from another)."""
        if len(key) != 32:
            raise ValueError(f"store keys are 32 bytes, got {len(key)}")
        return key

    def put(self, xid: int, key: bytes, val: bytes | None) -> int:
        """val=None writes a tombstone (root: deletes the record)."""
        key = self._key32(key)
        if val is None:
            return lib.fdtpu_store_put(self.wksp.base, self.off, xid,
                                       key, None, 0, 1)
        return lib.fdtpu_store_put(self.wksp.base, self.off, xid, key,
                                   val, len(val), 0)

    def get(self, xid: int, key: bytes) -> bytes | None:
        """Fork-visibility query; None when absent/tombstoned. Raises on
        unknown xid (matches funk's contract)."""
        key = self._key32(key)
        n = lib.fdtpu_store_get(self.wksp.base, self.off, xid, key,
                                self._buf, len(self._buf))
        if n == -1:
            return None
        if n == -2:
            raise KeyError(f"unknown txn {xid}")
        if n > len(self._buf):
            self._grow(n)
            n = lib.fdtpu_store_get(self.wksp.base, self.off, xid, key,
                                    self._buf, len(self._buf))
        return bytes(self._buf[:n])

    def iter_layer(self, xid: int):
        """Yield (key, val_bytes | None) for ONE layer's own records
        (None = tombstone). xid 0 iterates the published root."""
        cursor = ct.c_uint64(0)
        key = (ct.c_uint8 * 32)()
        tomb = ct.c_int32(0)
        while True:
            n = lib.fdtpu_store_iter(self.wksp.base, self.off, xid,
                                     ct.byref(cursor), key, self._buf,
                                     len(self._buf), ct.byref(tomb))
            if n == -1:
                return
            if n == -2:
                raise KeyError(f"unknown txn {xid}")
            if n > len(self._buf):
                # re-read this record with a grown buffer: back the
                # cursor up by restarting is wrong (list may be long), so
                # grow and re-fetch via get() on the captured key
                self._grow(n)
                k = bytes(key)
                v = None if tomb.value else self.get(xid, k)
                yield k, v
                continue
            yield bytes(key), (None if tomb.value else bytes(self._buf[:n]))

    def rec_cnt(self) -> int:
        return int(lib.fdtpu_store_rec_cnt(self.wksp.base, self.off))
