"""Forest: ancestry tree of blocks under repair + BFS frontier
(ref: src/discof/forest/fd_forest.h:1-70 — "constructs the ancestry
tree backwards, then repairs the tree forwards (using BFS)"; per-block
shred progress via consumed/buffered/complete idx watermarks).

Shreds (turbine) and votes (gossip) announce that a slot exists; the
forest tracks, per block, which data shred indices have been buffered
and the last index (from the SLOT_COMPLETE flag), links blocks into a
parent tree (parents may be unknown for a while — orphan roots), and
answers "what's missing" in BFS order from the root so repair requests
always favor the oldest incomplete ancestry.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field


@dataclass
class ForestBlk:
    slot: int
    parent_slot: int | None = None
    idxs: set = field(default_factory=set)   # buffered data shred idxs
    complete_idx: int | None = None          # last shred idx in slot
    children: list[int] = field(default_factory=list)

    @property
    def buffered_idx(self) -> int:
        """Highest contiguous buffered idx (-1 if none)."""
        i = -1
        while i + 1 in self.idxs:
            i += 1
        return i

    @property
    def is_complete(self) -> bool:
        return (self.complete_idx is not None
                and self.buffered_idx == self.complete_idx)

    def missing(self, max_req: int = 64) -> list[int]:
        """Missing idxs up to the known end (or a probe window past the
        highest buffered when the end is unknown)."""
        end = self.complete_idx if self.complete_idx is not None \
            else (max(self.idxs) if self.idxs else 0) + 1
        out = [i for i in range(end + 1) if i not in self.idxs]
        return out[:max_req]


class Forest:
    def __init__(self, root_slot: int):
        self.root = root_slot
        self.blks: dict[int, ForestBlk] = {
            root_slot: ForestBlk(root_slot, None,
                                 complete_idx=-1)}
        self.blks[root_slot].complete_idx = -1   # root needs no repair
        self.blks[root_slot].idxs = set()

    # -- discovery ----------------------------------------------------------

    def _ensure(self, slot: int) -> ForestBlk:
        b = self.blks.get(slot)
        if b is None:
            b = self.blks[slot] = ForestBlk(slot)
        return b

    def link(self, slot: int, parent_slot: int):
        """Record ancestry (from a data shred's parent_off or a vote)."""
        if slot <= self.root:
            return
        b = self._ensure(slot)
        if b.parent_slot is None and parent_slot >= self.root:
            b.parent_slot = parent_slot
            p = self._ensure(parent_slot)
            if slot not in p.children:
                p.children.append(slot)

    def shred(self, slot: int, idx: int, parent_off: int | None = None,
              slot_complete: bool = False):
        """Register one received data shred."""
        if slot <= self.root:
            return
        b = self._ensure(slot)
        b.idxs.add(idx)
        if slot_complete:
            b.complete_idx = idx if b.complete_idx is None \
                else min(b.complete_idx, idx)
        if parent_off is not None and parent_off > 0:
            self.link(slot, slot - parent_off)

    def vote(self, slot: int):
        """A gossip vote proves the block exists (no shreds yet)."""
        if slot > self.root:
            self._ensure(slot)

    # -- repair frontier ----------------------------------------------------

    def frontier(self) -> list[int]:
        """Incomplete blocks in BFS order from the root — oldest
        ancestry first (the repair-forward order, fd_forest.h)."""
        out = []
        q = deque([self.root])
        seen = set()
        while q:
            s = q.popleft()
            if s in seen:
                continue
            seen.add(s)
            b = self.blks[s]
            if s != self.root and not b.is_complete:
                out.append(s)
            q.extend(sorted(b.children))
        # orphans (unknown parentage) repair after connected blocks
        orphans = [s for s, b in self.blks.items()
                   if s not in seen and not b.is_complete]
        return out + sorted(orphans)

    def requests(self, max_per_blk: int = 8) -> list[tuple[int, int]]:
        """(slot, shred_idx) repair requests, frontier-ordered."""
        out = []
        for s in self.frontier():
            for i in self.blks[s].missing(max_per_blk):
                out.append((s, i))
        return out

    # -- rooting ------------------------------------------------------------

    def publish(self, new_root: int):
        """Advance the root; prune everything not descending from it
        (same rooting discipline as ghost.publish)."""
        if new_root not in self.blks:
            self.blks[new_root] = ForestBlk(new_root, None,
                                            complete_idx=-1)
        keep = set()
        q = deque([new_root])
        while q:
            s = q.popleft()
            if s in keep:
                continue
            keep.add(s)
            q.extend(self.blks[s].children)
        self.blks = {s: b for s, b in self.blks.items()
                     if s in keep or (s > new_root
                                      and self.blks[s].parent_slot is None)}
        self.root = new_root
        rb = self.blks[new_root]
        rb.parent_slot = None
        rb.complete_idx = rb.complete_idx if rb.complete_idx is not None \
            else -1
        rb.idxs = set(range(rb.complete_idx + 1)) if rb.complete_idx >= 0 \
            else set()
