"""Repair policy: which requests to send, to whom, with time-window
dedup (ref: src/discof/repair/fd_policy.h:1-45 — round-robin over
peers, DFS/BFS over the forest, LRU dedup of identical requests within
a configurable window).

Request wire (signed under the keyguard's REPAIR role, which requires
the u32 discriminant in [8, 11] followed by OUR pubkey and a body of
at least 80 bytes — src/disco/keyguard/fd_keyguard_authorize.c:94-113;
the real protocol's header carries sender, recipient, timestamp and
nonce the same way):

  u32 disc | sender 32 | recipient 32 | u64 ts_ms | u64 nonce |
  u64 slot | u32 shred_idx
"""
from __future__ import annotations

import struct

DISC_WINDOW_INDEX = 8          # request one shred by (slot, idx)
DISC_HIGHEST_WINDOW = 9        # request the highest shred of a slot
DISC_ORPHAN = 10               # request ancestry of an orphan slot
DISC_ANCESTOR_HASHES = 11

REQ_LEN = 4 + 32 + 32 + 8 + 8 + 8 + 4     # 96 >= keyguard's 80-byte floor


def pack_request(disc: int, sender: bytes, recipient: bytes, ts_ms: int,
                 nonce: int, slot: int, shred_idx: int = 0) -> bytes:
    return (struct.pack("<I", disc) + sender + recipient
            + struct.pack("<QQQI", ts_ms, nonce, slot, shred_idx))


def parse_request(b: bytes):
    disc, = struct.unpack_from("<I", b, 0)
    sender = b[4:36]
    recipient = b[36:68]
    ts_ms, nonce, slot, idx = struct.unpack_from("<QQQI", b, 68)
    return disc, sender, recipient, ts_ms, nonce, slot, idx


class RepairPolicy:
    def __init__(self, identity: bytes, dedup_window_ns: int = 100_000_000,
                 max_inflight: int = 512):
        self.identity = identity
        self.window_ns = dedup_window_ns
        self.max_inflight = max_inflight
        self.peers: list[bytes] = []
        self._rr = 0
        self._nonce = 0
        # (kind, slot, idx) -> last sent ns (LRU-ish, pruned on use)
        self._sent: dict[tuple, int] = {}

    def set_peers(self, peers: list[bytes]):
        self.peers = list(peers)

    def _dedup(self, key: tuple, now_ns: int) -> bool:
        """True = suppressed (sent within the window)."""
        last = self._sent.get(key)
        if last is not None and now_ns - last < self.window_ns:
            return True
        self._sent[key] = now_ns
        if len(self._sent) > 4 * self.max_inflight:
            cutoff = now_ns - self.window_ns
            self._sent = {k: t for k, t in self._sent.items()
                          if t >= cutoff}
        return False

    def next_peer(self) -> bytes | None:
        if not self.peers:
            return None
        p = self.peers[self._rr % len(self.peers)]
        self._rr += 1
        return p

    def plan(self, forest, now_ns: int,
             max_requests: int = 64) -> list[tuple[bytes, bytes]]:
        """-> [(peer, request_payload_to_sign)] for the current forest
        state: window-index requests for known holes, highest-window
        probes for open-ended blocks, orphan requests for parentless
        slots (ref fd_policy round-robin DFS)."""
        out = []
        for slot, idx in forest.requests():
            if len(out) >= max_requests:
                break
            blk = forest.blks[slot]
            if blk.parent_slot is None and not blk.idxs:
                disc, key = DISC_ORPHAN, ("orphan", slot, 0)
            elif blk.complete_idx is None and idx > blk.buffered_idx:
                disc, key = DISC_HIGHEST_WINDOW, ("high", slot, 0)
            else:
                disc, key = DISC_WINDOW_INDEX, ("idx", slot, idx)
            if self._dedup(key, now_ns):
                continue
            peer = self.next_peer()
            if peer is None:
                break
            self._nonce += 1
            out.append((peer, pack_request(
                disc, self.identity, peer, now_ns // 1_000_000,
                self._nonce, slot, idx)))
        return out
