"""repair: block recovery — forest ancestry tracking + request policy
(ref: src/discof/forest/, src/discof/repair/)."""
from .forest import Forest, ForestBlk  # noqa: F401
from .policy import (  # noqa: F401
    DISC_ANCESTOR_HASHES, DISC_HIGHEST_WINDOW, DISC_ORPHAN,
    DISC_WINDOW_INDEX, RepairPolicy, pack_request, parse_request,
)
