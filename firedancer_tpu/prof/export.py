"""fdprof export: shm profile regions + fdtrace rings -> one merged
Perfetto bundle, folded-stack text, and top-k summaries.

The merge is the point: fdtrace spans (wait/work/tpu_dispatch/...),
host flamegraph slices (the sampler's timestamped ring), and the
verify tile's device/compile events all carry `utils/tempo.monotonic_ns`
timestamps — ONE clock domain — so the bundle interleaves them on a
single Perfetto timeline. Each tile renders as two threads: the
fdtrace thread (tid from trace/export.py) and a `<tile>/host` sampler
thread (tid offset by HOST_TID_BASE, so ids never collide).

Folded text is the flamegraph.pl / speedscope interchange format:

    <tile>;<state>;frame;frame;... <count>

one line per (tile, state, stack) — two captures diff with nothing
more than `diff` or flamegraph.pl --negate.
"""
from __future__ import annotations

import json

from .recorder import STATE_NAMES, region_for

HOST_TID_BASE = 1000


def read_folded(plan: dict, wksp, tiles=None) -> dict[str, dict]:
    """{tile: {folded_stack: {state: count}}} for every profiled tile
    (or the `tiles` subset) — live or post-mortem."""
    out: dict[str, dict] = {}
    for tn in plan["tiles"]:
        if tiles is not None and tn not in tiles:
            continue
        region = region_for(plan, wksp, tn)
        if region is None:
            continue
        out[tn] = region.folded()
    return out


def folded_text(folded_by_tile: dict[str, dict]) -> str:
    """Folded-stack interchange text, stable-sorted for diffing."""
    lines = []
    for tn in sorted(folded_by_tile):
        for stack, states in sorted(folded_by_tile[tn].items()):
            for st, cnt in sorted(states.items()):
                lines.append(f"{tn};{st};{stack} {cnt}")
    return "\n".join(lines) + ("\n" if lines else "")


def read_samples(plan: dict, wksp,
                 tiles=None) -> dict[str, list[dict]]:
    """{tile: [{ts, state, stack}]} — the timestamped sample streams
    (ring snapshots, oldest-first)."""
    out: dict[str, list[dict]] = {}
    for tn in plan["tiles"]:
        if tiles is not None and tn not in tiles:
            continue
        region = region_for(plan, wksp, tn)
        if region is None:
            continue
        recs = []
        for ts, idx, st in region.snapshot_ring():
            stack = region.stack_at(idx)
            if stack is None:
                continue           # torn/overwritten slot: drop
            recs.append({"ts": ts,
                         "state": STATE_NAMES[st % len(STATE_NAMES)],
                         "stack": stack})
        out[tn] = recs
    return out


def merged_chrome(plan: dict, wksp, tiles=None) -> dict:
    """The merged bundle: fdtrace spans + host sampler slices on one
    timeline (open at ui.perfetto.dev). Works with either surface
    alone — an untraced-but-profiled topology still gets host slices,
    and vice versa."""
    from ..trace import export as trace_export
    evs = trace_export.read_rings(plan, wksp, tiles=tiles)
    doc = trace_export.to_chrome(evs, plan.get("topology", "fdtpu"))
    te = doc["traceEvents"]
    pid = 1
    samples = read_samples(plan, wksp, tiles=tiles)
    hz_by_tile = {}
    for tn in samples:
        region = region_for(plan, wksp, tn)
        hz_by_tile[tn] = max(1.0, int(region.hdr[5]) / 1000.0)
    for i, tn in enumerate(sorted(samples)):
        if not samples[tn]:
            continue
        tid = HOST_TID_BASE + i
        te.append({"ph": "M", "pid": pid, "tid": tid,
                   "name": "thread_name",
                   "args": {"name": f"{tn}/host"}})
        dur_us = 1e6 / hz_by_tile[tn]
        for s in samples[tn]:
            leaf = s["stack"].rsplit(";", 1)[-1]
            te.append({"ph": "X", "pid": pid, "tid": tid,
                       "cat": "fdprof", "name": leaf,
                       "ts": s["ts"] / 1e3, "dur": dur_us,
                       "args": {"stack": s["stack"],
                                "state": s["state"]}})
    doc["otherData"]["prof"] = "fdprof"
    return doc


def profile_summary(plan: dict, wksp, top_k: int = 5,
                    tiles=None) -> dict:
    """Per-tile profile digest for the bench observatory: sample
    counts, top-k folded stacks (by total count, with state
    breakdown), and the sampler's drop accounting. Cheap, JSON-able —
    this is what lands in the BENCH json as e2e_profile."""
    out: dict = {}
    for tn, folded in read_folded(plan, wksp, tiles=tiles).items():
        region = region_for(plan, wksp, tn)
        ranked = sorted(folded.items(),
                        key=lambda kv: -sum(kv[1].values()))
        out[tn] = {
            "samples": region.samples,
            "dropped": region.dropped,
            "hz": int(region.hdr[5]) / 1000.0,
            "by_state": {
                st: sum(states.get(st, 0) for states in
                        folded.values())
                for st in STATE_NAMES
                if any(st in states for states in folded.values())},
            "top": [{"stack": stack,
                     "count": sum(states.values()),
                     "states": states}
                    for stack, states in ranked[:top_k]],
        }
    return out


def summary_text(plan: dict, wksp, top_k: int = 5) -> str:
    """Human top-k report (the fdprof CLI default)."""
    lines = ["fdprof summary", "=============="]
    prof = profile_summary(plan, wksp, top_k=top_k)
    if not prof:
        return "no profiled tiles (is [prof] enabled?)\n"
    for tn in sorted(prof):
        p = prof[tn]
        states = " ".join(f"{k}={v}" for k, v in p["by_state"].items())
        lines.append("")
        lines.append(f"{tn}: {p['samples']} samples @ {p['hz']:g} Hz"
                     f" ({states})"
                     + (f" dropped={p['dropped']}" if p["dropped"]
                        else ""))
        for t in p["top"]:
            lines.append(f"  {t['count']:>6}  {t['stack']}")
    # device/compile artifacts, if any tile produced them
    from .device import capture_manifest_path, compile_manifest_path
    topo = plan.get("topology", "?")
    for tn in sorted(plan["tiles"]):
        for label, path in (
                ("capture", capture_manifest_path(topo, tn)),
                ("compile", compile_manifest_path(topo, tn))):
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            detail = f"ok={doc.get('ok')}" if label == "capture" \
                else f"compiles={doc.get('compiles')}"
            lines.append(f"{tn}: {label} artifact {path} ({detail})")
    return "\n".join(lines) + "\n"
