"""fdprof recorder: config schema, the shm profile region, the host
sampling profiler.

fdmetrics says WHICH hop saturates and fdtrace says WHEN; fdprof says
WHY — which Python frames eat the budget while a tile waits, works, or
housekeeps. Each profiled tile gets one `ProfRegion` in the workspace
(carved by disco/topo.py next to the metric slots and the flight
recorder), written by a per-tile `Sampler` daemon thread that walks the
stem thread's Python stack at a configurable rate and aggregates the
folded stacks in place. Any process attached to the workspace reads
the folded stacks live or POST-MORTEM (the shm outlives the tile), the
same snapshot discipline as fdtrace.

Config — the `[prof]` topology section plus an optional per-tile
`prof` table override (the exact [trace] pattern):

    [prof]
    enable = true            # master switch (default false)
    hz = 97                  # sampling rate (prime: avoids phase lock
                             #   with the ~100 Hz housekeeping cadence)
    slots = 256              # folded-stack table entries (power of two)
    ring = 2048              # timestamped sample ring (power of two)
    stack_depth = 16         # frames walked per sample
    tiles = ["verify"]       # optional allowlist (default: every tile)
    capture_ms = 200.0       # device-trace window length (verify tile)
    breach_capture = ["verify"]  # SLO breach -> request a device
                             #   capture on these tiles (metric tile)

    [tile.prof]              # per-tile override, highest precedence
    enable = false
    hz = 29

Shm region ABI (all little-endian, one writer per word class):

    header (8 u64): [0] samples  [1] dropped (table full)
                    [2] slots    [3] ring depth
                    [4] ring cursor (total samples ever ringed)
                    [5] hz (x1000, fixed point)
                    [6] capture_req   [7] capture_ack
    slot table: `slots` entries of SLOT_BYTES each —
                    u64 hash | 4 x u64 state counts (wait/work/
                    housekeep/other) | STACK_BYTES utf-8 folded stack
                    (null padded; hash == 0 means empty)
    sample ring: `ring` records of 16 B —
                    u64 ts_ns (utils/tempo.monotonic_ns — THE shared
                    clock, so host samples interleave exactly with
                    fdtrace spans) | u64 slot_idx | state << 32

capture_req / capture_ack are a cross-process doorbell for on-demand or
SLO-breach-triggered device-trace windows: a requester (metric tile on
breach, or tools/fdprof --capture) raises req to ack+1, the owning
tile's housekeeping sees req > ack, runs a bounded `jax.profiler`
window (prof/device.py), and acks. ack has exactly one writer (the
owner); req is written idempotently so racing requesters coalesce
into one window instead of losing an increment.
"""
from __future__ import annotations

import hashlib
import sys
import threading
import time

import numpy as np

from ..utils.tempo import monotonic_ns

PROF_DEFAULTS = {
    "enable": False,
    "hz": 97.0,
    "slots": 256,
    "ring": 2048,
    "stack_depth": 16,
    "tiles": None,          # None = all tiles (when enabled)
    "capture_ms": 200.0,
    "breach_capture": [],   # tiles to device-capture on an SLO breach
}
TILE_PROF_KEYS = ("enable", "hz", "slots", "ring", "stack_depth")

PROF_HDR_U64 = 8
N_STATES = 4
STACK_BYTES = 232
SLOT_BYTES = 8 + N_STATES * 8 + STACK_BYTES      # 272, 8-aligned
RING_REC_U64 = 2

# stem-state ids (the attribution axis; export names them)
ST_WAIT, ST_WORK, ST_HOUSEKEEP, ST_OTHER = 0, 1, 2, 3
STATE_NAMES = ("wait", "work", "housekeep", "other")


def _suggest(key: str, candidates) -> str:
    from ..lint.registry import suggest
    return suggest(key, candidates)


def normalize_prof(spec, per_tile: bool = False) -> dict:
    """Validate + default-fill a prof config table ([prof] section, or
    a tile's `prof` override with per_tile=True). Returns a plain
    JSON-able dict; raises ValueError with a did-you-mean on typos —
    the same fail-before-launch stance as normalize_trace."""
    allowed = set(TILE_PROF_KEYS) if per_tile else set(PROF_DEFAULTS)
    out = {} if per_tile else dict(PROF_DEFAULTS)
    if spec is None:
        return out
    if not isinstance(spec, dict):
        raise ValueError(f"prof spec must be a table, got {spec!r}")
    unknown = set(spec) - allowed
    if unknown:
        key = sorted(unknown)[0]
        raise ValueError(f"unknown prof key(s) {sorted(unknown)}"
                         + _suggest(key, allowed))
    out.update(spec)
    if "enable" in out and out["enable"] is not None:
        out["enable"] = bool(out["enable"])
    if "hz" in out:
        hz = out["hz"] = float(out["hz"])
        if not 0 < hz <= 10_000:
            raise ValueError(f"prof.hz must be in (0, 10000], got {hz}")
    for k in ("slots", "ring"):
        if k in out:
            d = out[k] = int(out[k])
            if d <= 0 or d & (d - 1):
                raise ValueError(
                    f"prof.{k} must be a positive power of two, got {d}")
    if "stack_depth" in out:
        d = out["stack_depth"] = int(out["stack_depth"])
        if d < 1:
            raise ValueError(f"prof.stack_depth must be >= 1, got {d}")
    if "capture_ms" in out:
        c = out["capture_ms"] = float(out["capture_ms"])
        if c <= 0:
            raise ValueError(f"prof.capture_ms must be > 0, got {c}")
    for k in ("tiles", "breach_capture"):
        v = out.get(k)
        if v is not None:
            if not isinstance(v, (list, tuple)) or \
                    not all(isinstance(t, str) for t in v):
                raise ValueError(f"prof.{k} must be a list of tile "
                                 f"names")
            out[k] = list(v)
    return out


def effective_prof(topo_cfg: dict, tile_name: str,
                   tile_override: dict) -> dict | None:
    """Resolve one tile's prof settings from the normalized topology
    section + the tile's own (normalized, per_tile) override. Returns
    {hz, slots, ring, stack_depth} when profiled, None when not."""
    enabled = topo_cfg["enable"] and (
        topo_cfg["tiles"] is None or tile_name in topo_cfg["tiles"])
    if "enable" in tile_override:
        enabled = bool(tile_override["enable"])
    if not enabled:
        return None
    return {k: tile_override.get(k, topo_cfg[k])
            for k in ("hz", "slots", "ring", "stack_depth")}


def stack_hash(stack: str) -> int:
    """Stable nonzero 64-bit content hash of a folded stack (stable
    across processes so a supervised respawn keeps accumulating into
    the same slots; 0 is the empty-slot sentinel)."""
    h = int.from_bytes(
        hashlib.blake2b(stack.encode(), digest_size=8).digest(),
        "little")
    return h or 1


class ProfRegion:
    """The per-tile profile region: header + folded-stack slot table +
    timestamped sample ring. Writer side is the tile's Sampler (plus
    the capture doorbell words, each single-writer); readers snapshot
    from any attached process."""

    PROBE = 16                 # linear-probe budget before `dropped`

    def __init__(self, wksp, off: int, slots: int, ring: int,
                 init: bool = False):
        for nm, d in (("slots", slots), ("ring", ring)):
            if d <= 0 or d & (d - 1):
                raise ValueError(f"prof {nm} {d} not a power of two")
        self.wksp, self.off = wksp, off
        self.slots, self.ring = slots, ring
        raw = wksp.view(off, self.footprint(slots, ring))
        self.hdr = raw[:PROF_HDR_U64 * 8].view(np.uint64)
        self._table = raw[PROF_HDR_U64 * 8:
                          PROF_HDR_U64 * 8 + slots * SLOT_BYTES]
        self._ringv = raw[PROF_HDR_U64 * 8 + slots * SLOT_BYTES:] \
            .view(np.uint64)
        if init:
            raw[:] = 0
            self.hdr[2] = slots
            self.hdr[3] = ring

    @staticmethod
    def footprint(slots: int, ring: int) -> int:
        return PROF_HDR_U64 * 8 + slots * SLOT_BYTES \
            + ring * RING_REC_U64 * 8

    @classmethod
    def create(cls, wksp, slots: int, ring: int) -> "ProfRegion":
        off = wksp.alloc(cls.footprint(slots, ring))
        return cls(wksp, off, slots, ring, init=True)

    # -- writer side --------------------------------------------------------

    def _slot_views(self, idx: int):
        base = idx * SLOT_BYTES
        s = self._table[base:base + SLOT_BYTES]
        return (s[:8].view(np.uint64), s[8:8 + N_STATES * 8]
                .view(np.uint64), s[8 + N_STATES * 8:])

    def slot_for(self, stack: str) -> int:
        """Claim-or-find the slot for a folded stack; -1 when the probe
        budget is exhausted (counted in `dropped` by record())."""
        h = stack_hash(stack)
        for i in range(self.PROBE):
            idx = (h + i) & (self.slots - 1)
            hv, _, sv = self._slot_views(idx)
            cur = int(hv[0])
            if cur == h:
                return idx
            if cur == 0:
                data = stack.encode()[:STACK_BYTES]
                sv[:len(data)] = np.frombuffer(data, np.uint8)
                hv[0] = h            # hash lands LAST: claims the slot
                return idx
        return -1

    def record(self, stack: str, state: int, ts_ns: int,
               slot_idx: int | None = None) -> int:
        """One sample: bump the stack's per-state count and append to
        the sample ring. Returns the slot index (cache it — repeat
        stacks skip the hash + probe)."""
        idx = self.slot_for(stack) if slot_idx is None else slot_idx
        hdr = self.hdr
        if idx < 0:
            # table full past the probe budget: the sample still rings
            # (cursor accounting stays exact) under the no-slot
            # sentinel, and `dropped` counts the lost attribution
            hdr[1] += 1
            ring_idx = 0xFFFFFFFF
        else:
            _, counts, _ = self._slot_views(idx)
            counts[state & (N_STATES - 1)] += 1
            ring_idx = idx
        cur = int(hdr[4])
        base = (cur & (self.ring - 1)) * RING_REC_U64
        self._ringv[base] = ts_ns & ((1 << 64) - 1)
        self._ringv[base + 1] = ring_idx | ((state & 0xFF) << 32)
        hdr[4] = cur + 1
        hdr[0] += 1
        return idx

    # -- capture doorbell ----------------------------------------------------

    @property
    def capture_req(self) -> int:
        return int(self.hdr[6])

    @property
    def capture_ack(self) -> int:
        return int(self.hdr[7])

    def request_capture(self):
        # requesters (metric tile on breach, fdprof CLI) may race each
        # other, so the request is written as an IDEMPOTENT level —
        # "one capture outstanding past ack" — not an increment whose
        # read-modify-write could lose a racing bump. Concurrent
        # requests coalesce into the one window, which is exactly what
        # a profiler wants.
        self.hdr[6] = int(self.hdr[7]) + 1

    def ack_capture(self, req: int):
        self.hdr[7] = req

    # -- reader side ---------------------------------------------------------

    @property
    def samples(self) -> int:
        return int(self.hdr[0])

    @property
    def dropped(self) -> int:
        return int(self.hdr[1])

    @property
    def ring_cursor(self) -> int:
        return int(self.hdr[4])

    def stack_at(self, idx: int) -> str | None:
        if not 0 <= idx < self.slots:      # dropped-sample sentinel
            return None
        hv, _, sv = self._slot_views(idx)
        if not int(hv[0]):
            return None
        b = bytes(sv)
        return b[:b.index(0)].decode("utf-8", "replace") if 0 in b \
            else b.decode("utf-8", "replace")

    def folded(self) -> dict[str, dict[str, int]]:
        """{folded_stack: {state_name: count}} — the aggregate table,
        live or post-mortem."""
        out: dict[str, dict[str, int]] = {}
        for idx in range(self.slots):
            hv, counts, _ = self._slot_views(idx)
            if not int(hv[0]):
                continue
            stack = self.stack_at(idx)
            out[stack] = {nm: int(counts[i])
                          for i, nm in enumerate(STATE_NAMES)
                          if int(counts[i])}
        return out

    def snapshot_ring(self) -> list[tuple[int, int, int]]:
        """[(ts_ns, slot_idx, state)] oldest-first — the timestamped
        sample stream the merged Perfetto export turns into host
        slices. Same overwrite-oldest/cursor accounting as TraceRing."""
        cur = self.ring_cursor
        n = min(cur, self.ring)
        out = []
        for k in range(cur - n, cur):
            base = (k & (self.ring - 1)) * RING_REC_U64
            meta = int(self._ringv[base + 1])
            out.append((int(self._ringv[base]),
                        meta & 0xFFFFFFFF, (meta >> 32) & 0xFF))
        return out


class ProfState:
    """The stem -> sampler attribution channel: two plain attributes
    the run loop stores into (GIL-atomic) and the sampler thread reads.
    Kept deliberately tiny — when profiling is off the stem never
    touches it (the None-check contract fdtrace set)."""

    __slots__ = ("state", "link")

    def __init__(self):
        self.state = ST_OTHER
        self.link: str | None = None


class Sampler:
    """Daemon-thread statistical profiler over ONE target thread (the
    stem loop). Each tick reads the target's current Python frame via
    sys._current_frames, folds it root-first (`file:func;...`), tags it
    with the stem state + active in-link from `ProfState`, and records
    into the shm region. A per-process stack->slot cache keeps the
    steady-state tick to one dict hit + three shm stores."""

    def __init__(self, region: ProfRegion, hz: float,
                 target_ident: int, state: ProfState,
                 stack_depth: int = 16):
        self.region = region
        self.hz = float(hz)
        self.ident = target_ident
        self.state = state
        self.stack_depth = int(stack_depth)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._cache: dict[str, int] = {}
        region.hdr[5] = int(self.hz * 1000)

    def sample_once(self, frame=None) -> str | None:
        """One sample (the thread loop body; separable for tests).
        Returns the folded stack recorded, or None if the target
        thread had no frame."""
        if frame is None:
            frame = sys._current_frames().get(self.ident)
            if frame is None:
                return None
        parts = []
        f, d = frame, 0
        while f is not None and d < self.stack_depth:
            code = f.f_code
            fn = code.co_filename.rsplit("/", 1)[-1]
            if fn.endswith(".py"):
                fn = fn[:-3]
            parts.append(f"{fn}:{code.co_name}")
            f = f.f_back
            d += 1
        parts.reverse()
        st = self.state.state
        link = self.state.link
        if link and st == ST_WORK:
            # active in-link as the flamegraph root under the work
            # state: "which link's traffic was I serving"
            parts.insert(0, f"[{link}]")
        stack = ";".join(parts)
        idx = self._cache.get(stack)
        idx2 = self.region.record(stack, st, monotonic_ns(),
                                  slot_idx=idx)
        if idx is None and idx2 >= 0:
            self._cache[stack] = idx2
        return stack

    def _loop(self):
        period = 1.0 / self.hz
        while not self._stop.is_set():
            t0 = time.perf_counter()
            try:
                self.sample_once()
            except Exception:      # noqa: BLE001 — the profiler must
                pass               # never take the tile down with it
            dt = time.perf_counter() - t0
            self._stop.wait(max(1e-4, period - dt))

    def start(self):
        self._thread = threading.Thread(target=self._loop,
                                        name="fdprof-sampler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)
            self._thread = None


def region_for(plan: dict, wksp, tile_name: str) -> ProfRegion | None:
    """ProfRegion over an EXISTING tile region (tile/reader side:
    plan + joined workspace), or None if the tile is unprofiled."""
    spec = plan["tiles"][tile_name]
    off = spec.get("prof_off")
    if off is None:
        return None
    return ProfRegion(wksp, off, int(spec["prof_slots"]),
                      int(spec["prof_ring"]))
