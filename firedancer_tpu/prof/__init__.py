"""fdprof: the whole-topology continuous profiler.

fdmetrics says WHICH hop saturates, fdtrace says WHEN — fdprof says
WHY: which Python frames, which XLA compiles, which device windows eat
the budget. Three surfaces over one shm + clock discipline:

    recorder.py   [prof] config schema, the per-tile ProfRegion
                  (folded-stack table + timestamped sample ring +
                  capture doorbell), the host Sampler thread
    device.py     jax.profiler capture windows + compile-event watch
                  (verify tile housekeeping), driven by the doorbell
    export.py     merged Perfetto bundle (fdtrace spans + host slices
                  on the shared utils/tempo clock), folded text,
                  top-k summaries (the BENCH json's e2e_profile)
    bench_diff.py tools/fdbench — diff two BENCH_r*.json files with a
                  regression-threshold exit code
    cli.py        `python -m firedancer_tpu.prof` / tools/fdprof

Disabled-path contract (same as fdtrace): an unprofiled tile's
TileCtx.prof is None, the stem starts no sampler thread and writes no
attribution state — unprofiled topologies pay one attribute check.
"""
from .export import (  # noqa: F401
    folded_text, merged_chrome, profile_summary, read_folded,
    read_samples, summary_text,
)
from .recorder import (  # noqa: F401
    PROF_DEFAULTS, STATE_NAMES, TILE_PROF_KEYS, ProfRegion, ProfState,
    Sampler, effective_prof, normalize_prof, region_for,
)
