"""fdprof device side: bounded `jax.profiler` trace windows + compile
artifacts, driven by the shm capture doorbell.

The host sampler (recorder.py) explains Python time; the questions it
cannot answer — which XLA ops, which dispatch stalls, which compiles —
belong to the device profiler. A capture is a bounded window: the
owning tile's housekeeping sees `capture_req > capture_ack` on its
ProfRegion (bumped by the metric tile on an SLO breach, or by
`tools/fdprof --capture`), starts `jax.profiler.start_trace` into a
per-tile directory, lets the normal poll loop run the window out, then
stops the trace, writes a JSON manifest next to the supervisor black
boxes, stamps an EV_PROF_CAPTURE span into the flight recorder, and
acks. A backend without a working profiler still produces the manifest
(ok=false + the error) — a breach-triggered drill must leave an
artifact either way.

Compile events ride the same housekeeping pass: a jit cache-size
increase since the last pass is a compile the steady-state padding
discipline should have prevented; it leaves an EV_COMPILE trace event
and refreshes the compile manifest (count, device memory, timestamps).
"""
from __future__ import annotations

import json
import os

from ..utils.tempo import monotonic_ns


def capture_manifest_path(topology: str, tile: str) -> str:
    return f"/dev/shm/fdtpu_{topology}.prof.{tile}.capture.json"


def compile_manifest_path(topology: str, tile: str) -> str:
    return f"/dev/shm/fdtpu_{topology}.prof.{tile}.compile.json"


def trace_dir(topology: str, tile: str) -> str:
    # the heavyweight profiler output (TensorBoard/XPlane) goes to
    # /tmp, not /dev/shm — only the small manifest lives with the
    # black boxes
    return f"/tmp/fdtpu_prof_{topology}_{tile}"


def request_capture(plan: dict, wksp, tile: str) -> bool:
    """Bump the capture doorbell on a profiled tile (requester side:
    metric tile on breach, or the fdprof CLI). False if unprofiled."""
    from .recorder import region_for
    region = region_for(plan, wksp, tile)
    if region is None:
        return False
    region.request_capture()
    return True


class DeviceCapture:
    """The owning tile's capture state machine (one per device tile,
    polled from its housekeeping — never from the hot loop):

        poll() -> started | stopped-manifest-path | None

    Window length comes from the plan's [prof] capture_ms; the window
    runs out across housekeeping passes so the poll loop keeps driving
    the device while the profiler records it."""

    def __init__(self, plan: dict, tile: str, region, trace=None):
        self.plan, self.tile, self.region = plan, tile, region
        self.trace = trace
        self.topology = plan.get("topology", "?")
        self.window_ms = float(
            (plan.get("prof") or {}).get("capture_ms", 200.0))
        self._active: dict | None = None
        self.captures = 0

    def _start(self, req: int):
        t0 = monotonic_ns()
        d = trace_dir(self.topology, self.tile)
        err = None
        try:
            os.makedirs(d, exist_ok=True)
            import jax
            jax.profiler.start_trace(d)
        except Exception as e:     # noqa: BLE001 — manifest either way
            err = f"{e!r}"[:200]
        self._active = {"req": req, "t0": t0, "dir": d, "err": err,
                        "deadline": t0 + int(self.window_ms * 1e6)}

    def _stop(self) -> str | None:
        act, self._active = self._active, None
        t1 = monotonic_ns()
        if act["err"] is None:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001
                act["err"] = f"{e!r}"[:200]
        doc = {
            "topology": self.topology,
            "tile": self.tile,
            "req": act["req"],
            "t0_ns": act["t0"],
            "t1_ns": t1,
            "window_ms": self.window_ms,
            "ok": act["err"] is None,
            "trace_dir": act["dir"] if act["err"] is None else None,
            "error": act["err"],
        }
        path = capture_manifest_path(self.topology, self.tile)
        try:
            with open(path, "w") as f:
                json.dump(doc, f)
        except OSError:
            path = None
        if self.trace is not None:
            from ..trace.events import EV_PROF_CAPTURE
            self.trace.span(EV_PROF_CAPTURE, act["t0"],
                            count=act["req"])
        self.region.ack_capture(act["req"])
        self.captures += 1
        return path

    def poll(self) -> str | None:
        """One housekeeping-cadence pass; returns the manifest path
        when a window just closed."""
        if self._active is not None:
            if monotonic_ns() >= self._active["deadline"]:
                return self._stop()
            return None
        req = self.region.capture_req
        if req > self.region.capture_ack:
            self._start(req)
        return None

    def flush(self):
        """Halt path: close an open window so the ack never dangles."""
        if self._active is not None:
            self._stop()


class CompileWatch:
    """Compile-event capture: detects jit cache growth between
    housekeeping passes, stamps EV_COMPILE into the flight recorder,
    and keeps the compile manifest fresh. `compiles_fn` returns the
    current compiled-variant count (adapter-provided: jax version
    differences stay in one place)."""

    def __init__(self, plan: dict, tile: str, compiles_fn, trace=None,
                 mem_fn=None, manifest: bool = True):
        self.topology = plan.get("topology", "?")
        self.tile = tile
        self._compiles = compiles_fn
        self._mem = mem_fn or (lambda: 0)
        self.trace = trace
        self.manifest = manifest   # manifest files only when profiled
        self.last = 0             # warmup's compile registers on the
        self.events = 0           # first pass: boot compile is event 1

    def poll(self) -> int | None:
        """Returns the new compile count when one was detected."""
        cur = self._compiles()
        if cur <= self.last:
            return None
        self.last = cur
        self.events += 1
        if self.trace is not None:
            from ..trace.events import EV_COMPILE
            self.trace.event(EV_COMPILE, arg=self._mem(), count=cur)
        if not self.manifest:
            return cur
        doc = {
            "topology": self.topology,
            "tile": self.tile,
            "compiles": cur,
            "cache_miss": max(0, cur - 1),
            "device_mem_bytes": self._mem(),
            "ts_ns": monotonic_ns(),
        }
        try:
            with open(compile_manifest_path(self.topology, self.tile),
                      "w") as f:
                json.dump(doc, f)
        except OSError:
            pass
        return cur
