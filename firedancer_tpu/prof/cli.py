"""fdprof CLI: drain/export a topology's profile + trace surfaces.

    python -m firedancer_tpu.prof <topology-name | plan.json>
        [--out bundle.json]       merged Perfetto bundle (fdtrace
                                  spans + host flamegraph slices, one
                                  clock domain — open at ui.perfetto.dev)
        [--folded out.folded]     folded-stack text (flamegraph.pl /
                                  speedscope; diff two runs directly)
        [--format summary|chrome|folded]   (default: summary)
        [--tile NAME ...]         restrict to these tiles
        [--top K]                 summary depth (default 5)
        [--capture TILE]          ring the device-capture doorbell on a
                                  profiled tile and return (the tile
                                  acks within a housekeeping pass;
                                  manifest lands in /dev/shm)

Attaches exactly like the monitor/fdtrace CLIs: via the plan JSON the
runner drops in /dev/shm — live or POST-MORTEM (the shm regions
outlive the tile processes)."""
from __future__ import annotations

import argparse
import json
import os
import sys


def _attach(target: str):
    from ..disco.launch import plan_path
    from ..runtime import Workspace
    path = target if target.endswith(".json") and os.path.exists(target) \
        else plan_path(target)
    with open(path) as f:
        plan = json.load(f)
    wksp = Workspace(plan["wksp"]["name"], plan["wksp"]["size"],
                     create=False)
    return plan, wksp


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fdprof",
        description="merged profiler export (host stacks + fdtrace + "
                    "device events, one clock)")
    ap.add_argument("target", help="topology name or plan.json path")
    ap.add_argument("--out", default=None,
                    help="write the merged Perfetto bundle here")
    ap.add_argument("--folded", default=None,
                    help="write folded-stack text here")
    ap.add_argument("--format", choices=("summary", "chrome", "folded"),
                    default="summary")
    ap.add_argument("--tile", action="append", default=None)
    ap.add_argument("--top", type=int, default=5)
    ap.add_argument("--capture", default=None, metavar="TILE",
                    help="request an on-demand device-trace window")
    args = ap.parse_args(argv)

    from . import export
    from .device import request_capture
    from .export import read_folded

    plan, wksp = _attach(args.target)
    try:
        if args.capture:
            if args.capture not in plan["tiles"]:
                print(f"unknown tile {args.capture!r}", file=sys.stderr)
                return 1
            if not request_capture(plan, wksp, args.capture):
                print(f"tile {args.capture!r} is not profiled "
                      f"(no [prof] region)", file=sys.stderr)
                return 1
            from .device import capture_manifest_path
            print(f"capture requested on {args.capture!r}; manifest: "
                  + capture_manifest_path(plan.get("topology", "?"),
                                          args.capture))
            return 0
        folded = read_folded(plan, wksp, tiles=args.tile)
        if not folded:
            print("no profiled tiles (is [prof] enabled in the "
                  "topology config?)", file=sys.stderr)
            return 1
        if args.folded:
            with open(args.folded, "w") as f:
                f.write(export.folded_text(folded))
            print(f"wrote {args.folded}")
        if args.out:
            doc = export.merged_chrome(plan, wksp, tiles=args.tile)
            with open(args.out, "w") as f:
                json.dump(doc, f)
            print(f"wrote {args.out} ({len(doc['traceEvents'])} "
                  f"events) — open at ui.perfetto.dev")
        if args.format == "summary":
            sys.stdout.write(export.summary_text(plan, wksp,
                                                 top_k=args.top))
        elif args.format == "chrome" and not args.out:
            json.dump(export.merged_chrome(plan, wksp,
                                           tiles=args.tile),
                      sys.stdout)
        elif args.format == "folded" and not args.folded:
            sys.stdout.write(export.folded_text(folded))
        return 0
    finally:
        wksp.close()


if __name__ == "__main__":
    raise SystemExit(main())
