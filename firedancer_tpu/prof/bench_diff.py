"""fdbench: the bench-trend observatory — diff two BENCH_r*.json
files with a regression-threshold exit code.

Every bench round prints one JSON document (bench.py): kernel
verifies/s, e2e tps + knee, per-hop link budget, and (since fdprof)
the per-stage profile summary. This tool turns two of those documents
into the answer a perf PR must ship with: WHAT moved, by HOW much, and
WHERE the time went — instead of a bare before/after number.

    tools/fdbench OLD.json NEW.json             # human diff
    tools/fdbench OLD.json NEW.json --gate      # exit 1 on regression
        [--threshold 0.05]                      # allowed fractional drop

Gated metrics (higher is better): the kernel vps (`value`), `e2e_tps`,
`e2e_knee_tps`, the leader knee, and the r14 front-door set
(`rlc_bulk_vps`, `rlc_prefilter_vps`, `flood_goodput_tps`). A metric
absent from either side is reported but
never gated (a CPU-fallback round must not fail the gate for skipping
e2e — the witnessed_tpu record stands in when present, the same
fallback bench.py's own FDTPU_BENCH_GATE_E2E uses). The profile top-k
and link-budget deltas are attribution, not gates.
"""
from __future__ import annotations

import argparse
import json
import sys

# (json key, label); all higher-is-better, gate-eligible
GATE_METRICS = (
    ("value", "kernel vps"),
    ("e2e_tps", "e2e tps"),
    ("e2e_knee_tps", "e2e knee tps"),
    ("e2e_leader_knee_tps", "leader knee tps"),
    # front-door survival (r14): RLC bulk kernel + prefilter rate and
    # staked goodput under the seeded forged-sig flood
    ("rlc_bulk_vps", "rlc bulk vps"),
    ("rlc_prefilter_vps", "rlc prefilter vps"),
    ("flood_goodput_tps", "flood goodput tps"),
)

# the knee subset: what bench.py's implicit previous-round gate
# (FDTPU_BENCH_PREV unset -> latest BENCH_r*.json) compares — knee
# regressions are the r13 contract; kernel/raw-tps noise across
# heterogeneous rounds stays report-only there
KNEE_METRICS = ("e2e_knee_tps", "e2e_leader_knee_tps")


def load_bench(path: str) -> dict:
    """A BENCH json in either shape: the bare record bench.py prints
    (BENCH_r*_witnessed.json) or the driver wrapper whose `tail`
    string holds that record as its last JSON-object line
    (BENCH_r*.json round artifacts)."""
    with open(path) as f:
        doc = json.load(f)
    tail = doc.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.strip().splitlines()):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                return rec
    return doc


def _metric(doc: dict, key: str):
    """A gated metric, honoring the witnessed-record fallback bench.py
    uses when the e2e stage was skipped (tunnel down)."""
    v = doc.get(key)
    if v is None and key.startswith("e2e"):
        v = doc.get("witnessed_tpu", {}).get(key)
    try:
        return float(v) if v is not None else None
    except (TypeError, ValueError):
        return None


def _top_stacks(doc: dict) -> dict[str, dict[str, int]]:
    """{tile: {stack: count}} from a BENCH json's e2e_profile."""
    out: dict[str, dict[str, int]] = {}
    for tn, p in (doc.get("e2e_profile") or {}).items():
        out[tn] = {t["stack"]: int(t["count"])
                   for t in p.get("top", [])}
    return out


def diff_bench(old: dict, new: dict) -> dict:
    """Structured delta document (JSON-able): gated metric moves,
    per-hop link-budget deltas, and profile top-k churn."""
    metrics = {}
    for key, label in GATE_METRICS:
        ov, nv = _metric(old, key), _metric(new, key)
        rec = {"label": label, "old": ov, "new": nv}
        if ov is not None and nv is not None and ov > 0:
            rec["frac"] = (nv - ov) / ov
        metrics[key] = rec
    links = {}
    ol = old.get("e2e_link_budget") or {}
    nl = new.get("e2e_link_budget") or {}
    for ln in sorted(set(ol) | set(nl)):
        o, n = ol.get(ln, {}), nl.get(ln, {})
        links[ln] = {k: {"old": o.get(k), "new": n.get(k)}
                     for k in ("pub", "lost", "backpressure",
                               "consume_p99_us")
                     if k in o or k in n}
    ot, nt = _top_stacks(old), _top_stacks(new)
    profile = {}
    for tn in sorted(set(ot) | set(nt)):
        o, n = ot.get(tn, {}), nt.get(tn, {})
        rows = {}
        for stack in sorted(set(o) | set(n)):
            if o.get(stack) != n.get(stack):
                rows[stack] = {"old": o.get(stack, 0),
                               "new": n.get(stack, 0)}
        if rows:
            profile[tn] = rows
    return {"metrics": metrics, "links": links, "profile": profile}


def gate_regressions(diff: dict, threshold: float = 0.05,
                     keys=None) -> list[dict]:
    """Gated metrics whose fractional drop exceeds the threshold —
    non-empty means the gate fails (exit 1). `keys` restricts the
    gate to a metric subset (KNEE_METRICS for the implicit
    previous-round gate); None gates everything."""
    out = []
    for key, rec in diff["metrics"].items():
        if keys is not None and key not in keys:
            continue
        frac = rec.get("frac")
        if frac is not None and frac < -threshold:
            out.append({"metric": key, "label": rec["label"],
                        "old": rec["old"], "new": rec["new"],
                        "frac": frac})
    return out


def report_path_for(bench_path: str,
                    dir_fallback: bool = True) -> str | None:
    """The fdgui report artifact that belongs to a BENCH json, if one
    exists: `<base>.report.html` next to it, else (when dir_fallback)
    the directory's `report.html` — what bench.py writes under
    FDTPU_BENCH_REPORT. Callers comparing two rounds in the SAME
    directory must disable the fallback: one shared report.html holds
    only the latest run and would be misattributed to both rounds."""
    import os
    cands = [os.path.splitext(bench_path)[0] + ".report.html"]
    if dir_fallback:
        cands.append(os.path.join(
            os.path.dirname(bench_path) or ".", "report.html"))
    for cand in cands:
        if os.path.exists(cand):
            return cand
    return None


def render_text(diff: dict, regressions: list[dict],
                threshold: float, reports=None) -> str:
    lines = ["fdbench diff", "============"]
    for label, path in (reports or ()):
        if path:
            lines.append(f"report ({label}): {path}")
    for key, rec in diff["metrics"].items():
        ov, nv = rec["old"], rec["new"]
        if ov is None and nv is None:
            continue
        arrow = ""
        if rec.get("frac") is not None:
            arrow = f"  ({rec['frac']:+.1%})"
        lines.append(f"{rec['label']:<16} "
                     f"{ov if ov is not None else '-':>12} -> "
                     f"{nv if nv is not None else '-':>12}{arrow}")
    if diff["links"]:
        lines.append("")
        lines.append(f"{'link':<18}{'pub':>16}{'lost':>12}"
                     f"{'bp':>12}{'p99us':>14}")
        for ln, rec in diff["links"].items():
            def cell(k):
                c = rec.get(k)
                if not c:
                    return "-"
                return f"{c['old'] if c['old'] is not None else '-'}" \
                       f"->{c['new'] if c['new'] is not None else '-'}"
            lines.append(f"{ln:<18}{cell('pub'):>16}{cell('lost'):>12}"
                         f"{cell('backpressure'):>12}"
                         f"{cell('consume_p99_us'):>14}")
    for tn, rows in diff["profile"].items():
        lines.append("")
        lines.append(f"profile {tn} (top-k sample-count deltas):")
        for stack, c in rows.items():
            lines.append(f"  {c['old']:>6} -> {c['new']:>6}  {stack}")
    lines.append("")
    if regressions:
        for r in regressions:
            lines.append(f"REGRESSION: {r['label']} {r['old']} -> "
                         f"{r['new']} ({r['frac']:+.1%}, threshold "
                         f"-{threshold:.0%})")
    else:
        lines.append(f"gate: clean (threshold -{threshold:.0%})")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fdbench",
        description="diff two BENCH json files; --gate exits nonzero "
                    "on a regression beyond --threshold")
    ap.add_argument("old")
    ap.add_argument("new")
    ap.add_argument("--gate", action="store_true")
    ap.add_argument("--threshold", type=float, default=0.05)
    ap.add_argument("--json", action="store_true",
                    help="emit the structured diff document instead")
    args = ap.parse_args(argv)
    old = load_bench(args.old)
    new = load_bench(args.new)
    d = diff_bench(old, new)
    regs = gate_regressions(d, threshold=args.threshold)
    # per-directory report.html is only attributable when the two
    # rounds live in different directories (per-round CI archives);
    # same-dir rounds share one file that holds only the latest run
    import os as _os
    fb = _os.path.dirname(_os.path.abspath(args.old)) \
        != _os.path.dirname(_os.path.abspath(args.new))
    reports = (("old", report_path_for(args.old, dir_fallback=fb)),
               ("new", report_path_for(args.new, dir_fallback=fb)))
    if args.json:
        json.dump({"diff": d, "regressions": regs,
                   "reports": dict(reports)},
                  sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        # link each round's fdgui report artifact when one exists —
        # the diff names what moved, the reports show where
        sys.stdout.write(render_text(d, regs, args.threshold,
                                     reports=reports))
    return 1 if (args.gate and regs) else 0


if __name__ == "__main__":
    raise SystemExit(main())
