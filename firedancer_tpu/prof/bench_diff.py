"""fdbench: the bench-trend observatory — diff two BENCH_r*.json
files with a regression-threshold exit code.

Every bench round prints one JSON document (bench.py): kernel
verifies/s, e2e tps + knee, per-hop link budget, and (since fdprof)
the per-stage profile summary. This tool turns two of those documents
into the answer a perf PR must ship with: WHAT moved, by HOW much, and
WHERE the time went — instead of a bare before/after number.

    tools/fdbench OLD.json NEW.json             # human diff
    tools/fdbench OLD.json NEW.json --gate      # exit 1 on regression
        [--threshold 0.05]                      # allowed fractional drop
    tools/fdbench --verify BENCH_r05_witnessed.json
                                                # fdwitness chain check

Provenance is explicit per metric: the diff badges every number
[wit] (fdwitness chain-stamped on a real device), [cpu] (measured on
the CPU backend) or [fb] (the prior witnessed record standing in), and
--verify recomputes a witnessed artifact's provenance hash chain +
record seal, exiting 1 on tamper.

Gated metrics (higher is better): the kernel vps (`value`), `e2e_tps`,
`e2e_knee_tps`, the leader knee, and the r14 front-door set
(`rlc_bulk_vps`, `rlc_prefilter_vps`, `flood_goodput_tps`). A metric
absent from either side is reported but
never gated (a CPU-fallback round must not fail the gate for skipping
e2e — the witnessed_tpu record stands in when present, the same
fallback bench.py's own FDTPU_BENCH_GATE_E2E uses). The profile top-k
and link-budget deltas are attribution, not gates.
"""
from __future__ import annotations

import argparse
import json
import sys

# (json key, label); all higher-is-better, gate-eligible
GATE_METRICS = (
    ("value", "kernel vps"),
    ("e2e_tps", "e2e tps"),
    ("e2e_knee_tps", "e2e knee tps"),
    ("e2e_leader_knee_tps", "leader knee tps"),
    # front-door survival (r14): RLC bulk kernel + prefilter rate and
    # staked goodput under the seeded forged-sig flood
    ("rlc_bulk_vps", "rlc bulk vps"),
    ("rlc_prefilter_vps", "rlc prefilter vps"),
    ("flood_goodput_tps", "flood goodput tps"),
    # execution scale-out (r16, widened r19): the exec-family leader
    # loop's capacity at 1/2/4 exec tiles — the full scaling curve,
    # so a regression that only shows at one shard count still gates
    ("exec_scale_tps_1", "exec scale tps (1 tile)"),
    ("exec_scale_tps_2", "exec scale tps (2 tiles)"),
    ("exec_scale_tps_4", "exec scale tps (4 tiles)"),
    # follower catch-up (r17): snapshot-restore + tail replay over the
    # exec family — the "become a follower" throughput contract
    ("replay_tps", "catch-up replay tps"),
    # fdtune (r20): the offline sweep's knee ratio — >= 1.0 by
    # construction (the default point is always in the argmax set), so
    # ANY regression here means the sweep machinery broke, not noise
    ("tuned_vs_default_tps", "tuned vs default tps"),
)

# report-only metrics: lower-is-better (or too noisy to gate), so a
# "drop" is an improvement — diffed and rendered, never gated
REPORT_METRICS = (
    ("catchup_s", "catch-up wall s (lower is better)"),
)
_REPORT_ONLY = frozenset(k for k, _ in REPORT_METRICS)

# the knee subset: what bench.py's implicit previous-round gate
# (FDTPU_BENCH_PREV unset -> latest BENCH_r*.json) compares — knee
# regressions are the r13 contract; kernel/raw-tps noise across
# heterogeneous rounds stays report-only there
KNEE_METRICS = ("e2e_knee_tps", "e2e_leader_knee_tps")


def load_bench(path: str) -> dict:
    """A BENCH json in either shape: the bare record bench.py prints
    (BENCH_r*_witnessed.json) or the driver wrapper whose `tail`
    string holds that record as its last JSON-object line
    (BENCH_r*.json round artifacts)."""
    with open(path) as f:
        doc = json.load(f)
    tail = doc.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.strip().splitlines()):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                return rec
    return doc


def load_multichip(path: str) -> dict | None:
    """The machine-readable `multichip_layout` stanza of a driver
    MULTICHIP_r*.json (its `tail` string carries the dryrun's one JSON
    line) or of a BENCH json that persists it as a field — so layout
    records diff round over round without scraping printed text."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc.get("multichip_layout"), dict):
        return doc["multichip_layout"]
    tail = doc.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.strip().splitlines()):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and "multichip_layout" in rec:
                return rec["multichip_layout"]
    return None


def _metric(doc: dict, key: str):
    """A gated metric, honoring the witnessed-record fallback bench.py
    uses when the e2e stage was skipped (tunnel down)."""
    return _metric_src(doc, key)[0]


def _metric_src(doc: dict, key: str):
    """(value, source) — the source says EXPLICITLY where the number
    came from: 'witnessed' (an fdwitness chain stamped it on a real
    device), 'cpu' (measured, but on the CPU backend — a smoke number,
    not a chip claim), 'measured' (no provenance info, taken at face
    value), or 'fallback' (this round skipped the stage and carries
    the prior witnessed record)."""
    v = doc.get(key)
    src = None
    if v is not None:
        wit = doc.get("witnessed")
        if isinstance(wit, dict) and key in wit:
            src = "witnessed" if wit[key].get("witnessed") else "cpu"
        elif str(doc.get("platform", "")).startswith("cpu"):
            src = "cpu"
        else:
            src = "measured"
    elif key.startswith("e2e"):
        v = doc.get("witnessed_tpu", {}).get(key)
        if v is not None:
            src = "fallback"
    try:
        return (float(v), src) if v is not None else (None, None)
    except (TypeError, ValueError):
        return None, None


def _top_stacks(doc: dict) -> dict[str, dict[str, int]]:
    """{tile: {stack: count}} from a BENCH json's e2e_profile."""
    out: dict[str, dict[str, int]] = {}
    for tn, p in (doc.get("e2e_profile") or {}).items():
        out[tn] = {t["stack"]: int(t["count"])
                   for t in p.get("top", [])}
    return out


def diff_bench(old: dict, new: dict) -> dict:
    """Structured delta document (JSON-able): gated metric moves,
    per-hop link-budget deltas, and profile top-k churn."""
    metrics = {}
    for key, label in (*GATE_METRICS, *REPORT_METRICS):
        (ov, osrc), (nv, nsrc) = (_metric_src(old, key),
                                  _metric_src(new, key))
        rec = {"label": label, "old": ov, "new": nv,
               "old_src": osrc, "new_src": nsrc}
        if ov is not None and nv is not None and ov > 0:
            rec["frac"] = (nv - ov) / ov
        metrics[key] = rec
    # multichip layout choice (fdwitness multichip stage): a layout
    # flip between rounds is exactly the kind of silent change the
    # diff must surface
    multichip = None
    oc, nc = old.get("multichip_choice"), new.get("multichip_choice")
    if oc is not None or nc is not None:
        multichip = {"old": oc, "new": nc, "changed": oc != nc}
    links = {}
    ol = old.get("e2e_link_budget") or {}
    nl = new.get("e2e_link_budget") or {}
    for ln in sorted(set(ol) | set(nl)):
        o, n = ol.get(ln, {}), nl.get(ln, {})
        links[ln] = {k: {"old": o.get(k), "new": n.get(k)}
                     for k in ("pub", "lost", "backpressure",
                               "consume_p99_us")
                     if k in o or k in n}
    ot, nt = _top_stacks(old), _top_stacks(new)
    profile = {}
    for tn in sorted(set(ot) | set(nt)):
        o, n = ot.get(tn, {}), nt.get(tn, {})
        rows = {}
        for stack in sorted(set(o) | set(n)):
            if o.get(stack) != n.get(stack):
                rows[stack] = {"old": o.get(stack, 0),
                               "new": n.get(stack, 0)}
        if rows:
            profile[tn] = rows
    return {"metrics": metrics, "links": links, "profile": profile,
            "multichip": multichip}


def gate_regressions(diff: dict, threshold: float = 0.05,
                     keys=None) -> list[dict]:
    """Gated metrics whose fractional drop exceeds the threshold —
    non-empty means the gate fails (exit 1). `keys` restricts the
    gate to a metric subset (KNEE_METRICS for the implicit
    previous-round gate); None gates everything."""
    out = []
    for key, rec in diff["metrics"].items():
        if keys is not None and key not in keys:
            continue
        if key in _REPORT_ONLY:
            continue
        frac = rec.get("frac")
        if frac is not None and frac < -threshold:
            out.append({"metric": key, "label": rec["label"],
                        "old": rec["old"], "new": rec["new"],
                        "frac": frac})
    return out


def report_path_for(bench_path: str,
                    dir_fallback: bool = True) -> str | None:
    """The fdgui report artifact that belongs to a BENCH json, if one
    exists: `<base>.report.html` next to it, else (when dir_fallback)
    the directory's `report.html` — what bench.py writes under
    FDTPU_BENCH_REPORT. Callers comparing two rounds in the SAME
    directory must disable the fallback: one shared report.html holds
    only the latest run and would be misattributed to both rounds."""
    import os
    cands = [os.path.splitext(bench_path)[0] + ".report.html"]
    if dir_fallback:
        cands.append(os.path.join(
            os.path.dirname(bench_path) or ".", "report.html"))
    for cand in cands:
        if os.path.exists(cand):
            return cand
    return None


def render_text(diff: dict, regressions: list[dict],
                threshold: float, reports=None) -> str:
    lines = ["fdbench diff", "============"]
    for label, path in (reports or ()):
        if path:
            lines.append(f"report ({label}): {path}")
    # provenance badges (fdwitness): [wit] chain-stamped on a device,
    # [cpu] measured on the CPU backend, [fb] prior witnessed record
    # standing in — so a diff can never pass off a fallback or a smoke
    # number as a fresh chip measurement
    _BADGE = {"witnessed": "[wit]", "cpu": "[cpu]", "fallback": "[fb]",
              "measured": "", None: ""}
    for key, rec in diff["metrics"].items():
        ov, nv = rec["old"], rec["new"]
        if ov is None and nv is None:
            continue
        arrow = ""
        if rec.get("frac") is not None:
            arrow = f"  ({rec['frac']:+.1%})"
        ob = _BADGE.get(rec.get("old_src"), "")
        nb = _BADGE.get(rec.get("new_src"), "")
        lines.append(f"{rec['label']:<16} "
                     f"{ov if ov is not None else '-':>12}{ob:<5} -> "
                     f"{nv if nv is not None else '-':>12}{nb:<5}"
                     f"{arrow}")
    mc = diff.get("multichip")
    if mc:
        lines.append(f"multichip layout  "
                     f"{mc['old'] or '-'} -> {mc['new'] or '-'}"
                     + ("  (CHANGED)" if mc["changed"] else ""))
    if diff["links"]:
        lines.append("")
        lines.append(f"{'link':<18}{'pub':>16}{'lost':>12}"
                     f"{'bp':>12}{'p99us':>14}")
        for ln, rec in diff["links"].items():
            def cell(k):
                c = rec.get(k)
                if not c:
                    return "-"
                return f"{c['old'] if c['old'] is not None else '-'}" \
                       f"->{c['new'] if c['new'] is not None else '-'}"
            lines.append(f"{ln:<18}{cell('pub'):>16}{cell('lost'):>12}"
                         f"{cell('backpressure'):>12}"
                         f"{cell('consume_p99_us'):>14}")
    for tn, rows in diff["profile"].items():
        lines.append("")
        lines.append(f"profile {tn} (top-k sample-count deltas):")
        for stack, c in rows.items():
            lines.append(f"  {c['old']:>6} -> {c['new']:>6}  {stack}")
    lines.append("")
    if regressions:
        for r in regressions:
            lines.append(f"REGRESSION: {r['label']} {r['old']} -> "
                         f"{r['new']} ({r['frac']:+.1%}, threshold "
                         f"-{threshold:.0%})")
    else:
        lines.append(f"gate: clean (threshold -{threshold:.0%})")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fdbench",
        description="diff two BENCH json files; --gate exits nonzero "
                    "on a regression beyond --threshold; --verify "
                    "checks a witnessed artifact's provenance chain")
    ap.add_argument("old")
    ap.add_argument("new", nargs="?", default=None)
    ap.add_argument("--gate", action="store_true")
    ap.add_argument("--threshold", type=float, default=0.05)
    ap.add_argument("--json", action="store_true",
                    help="emit the structured diff document instead")
    ap.add_argument("--verify", action="store_true",
                    help="single-file mode: verify the fdwitness "
                         "provenance hash chain of a "
                         "BENCH_r*_witnessed.json (exit 1 on tamper)")
    args = ap.parse_args(argv)
    if args.verify:
        # one definition of chain verification, shared with
        # `tools/fdwitness verify`
        from ..witness.cli import verify_artifact
        return verify_artifact(args.old)
    if args.new is None:
        ap.error("new is required unless --verify is given")
    old = load_bench(args.old)
    new = load_bench(args.new)
    d = diff_bench(old, new)
    regs = gate_regressions(d, threshold=args.threshold)
    # per-directory report.html is only attributable when the two
    # rounds live in different directories (per-round CI archives);
    # same-dir rounds share one file that holds only the latest run
    import os as _os
    fb = _os.path.dirname(_os.path.abspath(args.old)) \
        != _os.path.dirname(_os.path.abspath(args.new))
    reports = (("old", report_path_for(args.old, dir_fallback=fb)),
               ("new", report_path_for(args.new, dir_fallback=fb)))
    if args.json:
        json.dump({"diff": d, "regressions": regs,
                   "reports": dict(reports)},
                  sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        # link each round's fdgui report artifact when one exists —
        # the diff names what moved, the reports show where
        sys.stdout.write(render_text(d, regs, args.threshold,
                                     reports=reports))
    return 1 if (args.gate and regs) else 0


if __name__ == "__main__":
    raise SystemExit(main())
