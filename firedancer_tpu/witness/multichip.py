"""Multichip layout shootout: measure, don't print.

`dryrun_multichip` (driver entry) compiles the sharded pipeline every
round and prints the two candidate multi-chip verify layouts — but the
choice ROADMAP 1b demands (per-chip rr-sharded verify tiles vs ONE
verify tile owning the whole mesh) was still being made by reading a
stanza. This stage runs BOTH layouts side by side on the same mesh and
records what each actually delivers, plus per-device memory stats and
the per-dispatch wall series, so the witnessed artifact carries the
measured decision:

    one_mesh_tile   one jitted shard_map program over the batch axis
                    (the verify tile's `devices` arg): one dispatch
                    feeds the whole mesh, psum fan-in over ICI
    rr_tiles        the r13 topology concept: one verify program per
                    device, batch round-robined across them host-side
                    (async dispatch all, block at the end — the
                    in-flight discipline the tile uses)

Self-provisions a virtual CPU mesh when no accelerator can provide the
requested device count (same posture as `dryrun_multichip`: the
sharding program is identical either way; on CPU the NUMBERS only rank
the layouts' overhead shapes, the chip run ranks their throughput).
Prints one JSON line — the fdwitness stage contract.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _measure(dispatch, block, iters: int, batch: int) -> dict:
    """Pipelined-throughput methodology (bench.py): async dispatch all
    rounds, block at the end; per-round blocking walls give the
    series."""
    series = []
    for _ in range(max(2, iters // 2)):
        t0 = time.perf_counter()
        block([dispatch()])
        series.append(round((time.perf_counter() - t0) * 1e3, 2))
    t0 = time.perf_counter()
    outs = [dispatch() for _ in range(iters)]
    block(outs)
    dt = time.perf_counter() - t0
    return {"vps": round(batch * iters / dt, 1),
            "iters": iters,
            "wall_series_ms": series}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fdwitness-multichip")
    ap.add_argument("--devices", type=int, default=0,
                    help="mesh size (0 = auto: every real accelerator "
                         "device, else an 8-way virtual CPU mesh)")
    ap.add_argument("--batch", type=int, default=0,
                    help="total lanes across the mesh (0 = sized per "
                         "platform)")
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--msg-len", type=int, default=96)
    args = ap.parse_args(argv)

    here = os.getcwd()
    sys.path.insert(0, here)
    import __graft_entry__ as g

    import jax
    import jax.numpy as jnp
    import numpy as np

    devs = jax.devices()
    accel = devs[0].platform != "cpu"
    n = args.devices
    if n <= 0:
        n = len(devs) if accel else 8
    if accel:
        # the layout decision must be measured on the chips that
        # EXIST — asking for more than the mesh has must shrink to
        # the real mesh, never silently fall back to virtual CPU
        # devices while real chips sit idle (the 2-chip witnessed
        # run is exactly the len(devs) < 8 case)
        n = min(n, len(devs))
    on_tpu = accel and len(devs) >= n
    if not on_tpu and not g._force_cpu_mesh(n):
        # jax already latched a backend that cannot provide n devices:
        # re-exec in a fresh interpreter with the platform forced
        # before jax loads (the dryrun_multichip pattern)
        if os.environ.get("_FDTPU_WITNESS_MULTI_INPROC") == "1":
            print(json.dumps({"error": f"no {n}-device mesh available"}))
            return 1
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        env["XLA_FLAGS"] = " ".join(
            flags + [f"--xla_force_host_platform_device_count={n}"])
        env["_FDTPU_WITNESS_MULTI_INPROC"] = "1"
        r = subprocess_run_self(env)
        return r
    g._enable_compile_cache()
    devs = jax.devices()[:n]

    from firedancer_tpu.ops import ed25519 as ed
    if on_tpu:
        from firedancer_tpu.ops import pallas_ed as ped
        verify = ped.verify_batch
        kernel = "pallas"
    else:
        verify = ed.verify_batch
        kernel = "jnp"
    batch = args.batch or (8192 if on_tpu else 4 * n)
    batch = max(n, batch - batch % n)      # equal per-device shards
    sig, pub, msg, ln = g._example_batch(batch, max_len=args.msg_len)

    out = {"multichip_devices": n,
           "platform": devs[0].platform,
           "kernel": kernel,
           "batch": batch,
           "msg_len": args.msg_len,
           "layouts": {}}

    # --- layout 1: one mesh tile (shard_map over the batch axis) ----------
    try:
        from jax import shard_map
    except ImportError:          # jax < 0.5 keeps it experimental
        from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(devs), ("shard",))
    skw = dict(mesh=mesh, in_specs=(P("shard"),) * 4,
               out_specs=P("shard"))
    # kernel scan carries start as constants and become axis-varying
    # in the loop body — disable the replication check (renamed
    # check_rep -> check_vma across jax versions; tiles/verify.py
    # precedent)
    try:
        step = shard_map(lambda s, p, m, l: verify(s, p, m, l),
                         **skw, check_vma=False)
    except TypeError:
        step = shard_map(lambda s, p, m, l: verify(s, p, m, l),
                         **skw, check_rep=False)
    fn = jax.jit(step)
    sharded = [jax.device_put(jnp.asarray(a),
                              NamedSharding(mesh, P("shard")))
               for a in (sig, pub, msg, ln)]
    t0 = time.perf_counter()
    ok = fn(*sharded)
    ok.block_until_ready()
    compile_s = time.perf_counter() - t0
    assert bool(np.asarray(ok).all()), "mesh verify failed"
    rec = _measure(lambda: fn(*sharded), jax.block_until_ready,
                   args.iters, batch)
    rec["compile_s"] = round(compile_s, 2)
    out["layouts"]["one_mesh_tile"] = rec

    # --- layout 2: rr-sharded tiles (one program per device) --------------
    per = batch // n
    fn1 = jax.jit(lambda s, p, m, l: verify(s, p, m, l))
    shards = []
    t0 = time.perf_counter()
    for i, d in enumerate(devs):
        sl = slice(i * per, (i + 1) * per)
        shards.append(tuple(
            jax.device_put(jnp.asarray(a[sl]), d)
            for a in (sig, pub, msg, ln)))
    outs = [fn1(*s) for s in shards]
    jax.block_until_ready(outs)
    compile_s = time.perf_counter() - t0
    assert all(bool(np.asarray(o).all()) for o in outs), \
        "rr verify failed"
    rec = _measure(lambda: [fn1(*s) for s in shards],
                   jax.block_until_ready, args.iters, batch)
    rec["compile_s"] = round(compile_s, 2)
    out["layouts"]["rr_tiles"] = rec

    # --- per-device evidence ----------------------------------------------
    per_dev = []
    for d in devs:
        mem = {}
        try:
            mem = d.memory_stats() or {}
        except Exception:  # noqa: BLE001 — CPU backends have none
            pass
        per_dev.append({"id": int(getattr(d, "id", 0)),
                        "kind": getattr(d, "device_kind", ""),
                        "memory_stats":
                        {k: int(v) for k, v in mem.items()}})
    out["per_device"] = per_dev

    lay = out["layouts"]
    choice = max(lay, key=lambda k: lay[k]["vps"])
    other = min(lay, key=lambda k: lay[k]["vps"])
    out["multichip_choice"] = choice
    out["multichip_choice_ratio"] = round(
        lay[choice]["vps"] / lay[other]["vps"], 3) \
        if lay[other]["vps"] else 0.0
    print(json.dumps(out))
    return 0


def subprocess_run_self(env: dict) -> int:
    import subprocess
    r = subprocess.run([sys.executable, "-m",
                        "firedancer_tpu.witness.multichip"]
                       + sys.argv[1:],
                       cwd=os.getcwd(), env=env)
    return r.returncode


if __name__ == "__main__":
    raise SystemExit(main())
