"""fdwitness stage plan: the [witness] schema + the ordered sweep.

The witnessed-run process used to be an oral tradition — ad-hoc
`/tmp/tpu_watch.sh` scripts, hand-run `bench.py` invocations, a
hardcoded fallback filename. This module is the committed replacement's
contract: ONE ordered catalog of gated stanzas (every number ROADMAP
item 1 queues behind the tunnel window), a validated `[witness]` config
section (the standard load/build/lint triple: app/config.py rejects a
typo at load with a did-you-mean, `build_plan` is the build gate, and
fdlint's `bad-witness` rule catches it at review), and the per-stage
subprocess specs the runner executes.

Stage catalog (plan order — the hash chain follows it):

    device_probe   hang-proof backend fingerprint (platform, device
                   kind, memory stats, device count) — the provenance
                   anchor every later stage is stamped with
    kernel_vps     bench.py kernel stage: strict Pallas `value` vps +
                   the `rlc_bulk_vps` Pallas-MSM bulk stanza
    mxu_fmul       tools/exp_mxu_fmul.py go/no-go (>2x over the VPU
                   control pays for radix-2^7)
    e2e_feed       bench.py e2e stage: feed-path tps + offered sweep +
                   knee (the r10 >=4x target)
    leader_knee    bench.py leader stage: full pack->bank->poh->shred
                   knee + saturating hop (r13)
    exec_scale     bench.py exec_scale stage: leader loop over the shm
                   funk store with resolv + an exec tile family —
                   measured tps per exec_tile_cnt and the hop snapshot
                   proving the knee moved off the bank (r16)
    flood_soak     bench.py flood stage: front-door survival goodput +
                   `rlc_prefilter_vps` at chip rate (r14)
    catchup        bench.py catchup stage: follower cold-start from a
                   ShmFunk snapshot racing live tail ingest — replay
                   over the exec family against the oracle's pinned
                   bank hashes, measured as replay_tps/catchup_s (r17)
    autotune       bench.py autotune stage: the fdtune offline knob
                   sweep through _e2e_run — one topology boot per
                   config point, resumable checkpoint — persisting a
                   provenance-stamped tuned profile and
                   tuned_vs_default_tps (>= 1.0 by construction) (r20)
    multichip      witness/multichip.py: the shard_map layout shootout
                   — per-chip rr tiles vs one mesh tile, measured side
                   by side with per-device memory/occupancy series
                   (the ROADMAP 1b layout decision, by measurement)

Every stage command prints its result as the LAST JSON-object line of
stdout (the bench.py child convention); the runner records it in a
checkpoint stamped with the provenance block and chained to the
previous stage's hash.
"""
from __future__ import annotations

import os
import sys

# ordered: the sweep runs (and the hash chain links) in this order
STAGES = ("device_probe", "kernel_vps", "mxu_fmul", "e2e_feed",
          "leader_knee", "exec_scale", "flood_soak", "catchup",
          "autotune", "multichip")

# [witness] section keys (lint/registry.py WITNESS_SECTION_KEYS is the
# static mirror — tests/test_witness.py keeps it honest)
WITNESS_DEFAULTS = {
    "stages": None,            # ordered subset of STAGES (None = all)
    "out_dir": ".fdwitness",   # run/checkpoint dir (repo-root relative)
    "round": 0,                # artifact round (0 = latest BENCH_r*)
    "stage_timeout_s": 1800.0,  # default per-stage subprocess deadline
    "probe_timeout_s": 60.0,   # hang-proof backend-probe deadline
    "park_s": 30.0,            # watch-mode backoff floor
    "park_max_s": 360.0,       # watch-mode backoff ceiling
    "keep_going": False,       # continue the sweep past a failed stage
    "report": True,            # merged fdgui report next to the artifact
    "stage": None,             # per-stage override table (stage.<name>)
}

# [witness.stage.<name>] keys: per-stage enable/deadline and the
# command/env override (also the seam tests script failures through)
WITNESS_STAGE_KEYS = ("enable", "timeout_s", "cmd", "env")


def normalize_witness(spec: dict | None) -> dict:
    """Validate a [witness] table against the schema; returns the
    normalized dict (defaults applied). Raises ValueError with a
    did-you-mean on unknown keys/stage names — the same gate at config
    load (app/config.py), plan build (build_plan), and review
    (fdlint bad-witness)."""
    from ..lint.registry import suggest
    out = dict(WITNESS_DEFAULTS)
    spec = spec or {}
    bad = set(spec) - set(WITNESS_DEFAULTS)
    if bad:
        key = sorted(bad)[0]
        raise ValueError(f"unknown witness key(s) {sorted(bad)}"
                         + suggest(key, WITNESS_DEFAULTS))
    out.update(spec)
    if out["stages"] is not None:
        if not isinstance(out["stages"], (list, tuple)) or \
                not all(isinstance(s, str) for s in out["stages"]):
            raise ValueError("witness stages must be a list of stage "
                             f"names (subset of {list(STAGES)})")
        for s in out["stages"]:
            if s not in STAGES:
                raise ValueError(f"unknown witness stage {s!r}"
                                 + suggest(s, STAGES))
        # the sweep (and the hash chain) runs in catalog order
        out["stages"] = [s for s in STAGES if s in out["stages"]]
    for key in ("stage_timeout_s", "probe_timeout_s", "park_s",
                "park_max_s"):
        out[key] = float(out[key])
        if out[key] <= 0:
            raise ValueError(f"witness {key} must be > 0")
    if out["park_max_s"] < out["park_s"]:
        raise ValueError("witness park_max_s must be >= park_s")
    out["round"] = int(out["round"])
    if out["round"] < 0:
        raise ValueError("witness round must be >= 0")
    if not isinstance(out["out_dir"], str) or not out["out_dir"]:
        raise ValueError("witness out_dir must be a non-empty string")
    out["keep_going"] = bool(out["keep_going"])
    out["report"] = bool(out["report"])
    if out["stage"] is not None:
        if not isinstance(out["stage"], dict):
            raise ValueError("witness stage must be a table of "
                             "per-stage overrides")
        for sn, ov in out["stage"].items():
            if sn not in STAGES:
                raise ValueError(f"unknown witness stage {sn!r}"
                                 + suggest(sn, STAGES))
            if not isinstance(ov, dict):
                raise ValueError(f"witness stage {sn!r} override must "
                                 f"be a table")
            bad = set(ov) - set(WITNESS_STAGE_KEYS)
            if bad:
                key = sorted(bad)[0]
                raise ValueError(
                    f"witness stage {sn!r}: unknown key(s) "
                    f"{sorted(bad)}" + suggest(key, WITNESS_STAGE_KEYS))
            if "cmd" in ov and (
                    not isinstance(ov["cmd"], (list, tuple))
                    or not all(isinstance(c, str) for c in ov["cmd"])):
                raise ValueError(f"witness stage {sn!r}: cmd must be "
                                 f"an argv list of strings")
            if "env" in ov and (
                    not isinstance(ov["env"], dict)
                    or not all(isinstance(k, str) and isinstance(v, str)
                               for k, v in ov["env"].items())):
                raise ValueError(f"witness stage {sn!r}: env must be a "
                                 f"string -> string table")
            if "timeout_s" in ov and float(ov["timeout_s"]) <= 0:
                raise ValueError(f"witness stage {sn!r}: timeout_s "
                                 f"must be > 0")
    return out


# hang-proof backend fingerprint: the RUNNER bounds this subprocess
# with probe_timeout_s and kills it on hang (the tunnel's documented
# failure mode is jax.devices() blocking forever) — the snippet itself
# just reports what it sees
PROBE_SNIPPET = """\
import json, os, sys
import jax
devs = jax.devices()
d0 = devs[0]
mem = {}
try:
    mem = d0.memory_stats() or {}
except Exception:
    pass
print(json.dumps({
    "platform": d0.platform,
    "device_kind": getattr(d0, "device_kind", ""),
    "device_count": len(devs),
    "local_device_count": jax.local_device_count(),
    "memory_stats": {k: int(v) for k, v in mem.items()},
    "jax_version": jax.__version__,
}))
"""

# cpu-smoke knob sets: the SAME stages, CPU-sized so a box with no
# accelerator can drill the whole orchestrator end to end (checkpoints,
# chain, artifact, report) in minutes. RLC is skipped by default — the
# jnp MSM graph costs minutes of compile on CPU (PERF.md); the chip
# sweep runs it for real.
_CPU_SMOKE_ENV = {
    "JAX_PLATFORMS": "cpu", "FDTPU_BENCH_FORCE_CPU": "1",
    "FDTPU_JAX_PLATFORM": "cpu",
}
_CPU_SMOKE_STAGE_ENV = {
    "kernel_vps": {"FDTPU_BENCH_BATCH": "16", "FDTPU_BENCH_ITERS": "2",
                   "FDTPU_BENCH_MSG_LEN": "256",
                   "FDTPU_BENCH_SKIP_RLC": "1"},
    "e2e_feed": {"FDTPU_BENCH_E2E_COUNT": "8192",
                 "FDTPU_BENCH_E2E_UNIQUE": "128",
                 "FDTPU_BENCH_E2E_BATCH": "64",
                 "FDTPU_BENCH_E2E_SWEEP": "0.8"},
    "leader_knee": {"FDTPU_BENCH_LEADER_COUNT": "1024",
                    "FDTPU_BENCH_LEADER_UNIQUE": "256",
                    "FDTPU_BENCH_LEADER_BATCH": "16",
                    "FDTPU_BENCH_LEADER_TILES": "1",
                    "FDTPU_BENCH_LEADER_SWEEP": "0.8",
                    "FDTPU_BENCH_LEADER_STANZA_S": "2.0"},
    "exec_scale": {"FDTPU_BENCH_EXEC_COUNT": "1024",
                   "FDTPU_BENCH_EXEC_UNIQUE": "256",
                   "FDTPU_BENCH_EXEC_BATCH": "16",
                   "FDTPU_BENCH_EXEC_VERIFY_TILES": "1",
                   "FDTPU_BENCH_EXEC_SCALE_CNTS": "1,2"},
    "flood_soak": {"FDTPU_BENCH_FLOOD_S": "4",
                   "FDTPU_BENCH_FLOOD_PROBE_PPS": "40",
                   "FDTPU_BENCH_FLOOD_SYBILS": "8",
                   "FDTPU_BENCH_FLOOD_MULT": "3"},
    "catchup": {"FDTPU_BENCH_CATCHUP_COUNT": "96",
                "FDTPU_BENCH_CATCHUP_SLOTS": "8",
                "FDTPU_BENCH_CATCHUP_SNAP_SLOT": "3",
                "FDTPU_BENCH_CATCHUP_EXEC_TILES": "2"},
    "autotune": {"FDTPU_BENCH_AUTOTUNE_COUNT": "2048",
                 "FDTPU_BENCH_AUTOTUNE_UNIQUE": "128",
                 "FDTPU_BENCH_AUTOTUNE_POINTS": "2"},
}


def default_stage_cmds(repo_root: str,
                       cpu_smoke: bool = False) -> dict[str, list[str]]:
    """stage name -> argv (cwd = repo_root for every stage)."""
    py = sys.executable
    bench = os.path.join(repo_root, "bench.py")
    mxu = [py, os.path.join(repo_root, "tools", "exp_mxu_fmul.py")]
    multi = [py, "-m", "firedancer_tpu.witness.multichip"]
    if cpu_smoke:
        mxu += ["--batch", "64", "--reps", "2"]
        multi += ["--devices", "2", "--batch", "16", "--iters", "2",
                  "--msg-len", "96"]
    return {
        "device_probe": [py, "-c", PROBE_SNIPPET],
        "kernel_vps": [py, bench],
        "mxu_fmul": mxu,
        "e2e_feed": [py, bench],
        "leader_knee": [py, bench],
        "exec_scale": [py, bench],
        "flood_soak": [py, bench],
        "catchup": [py, bench],
        "autotune": [py, bench],
        "multichip": multi,
    }


# the bench.py stage-mux envs (main() dispatches on these)
_STAGE_CHILD_ENV = {
    "kernel_vps": {"FDTPU_BENCH_CHILD": "1"},
    "e2e_feed": {"FDTPU_BENCH_E2E_CHILD": "1"},
    "leader_knee": {"FDTPU_BENCH_LEADER_CHILD": "1"},
    "exec_scale": {"FDTPU_BENCH_EXEC_SCALE_CHILD": "1"},
    "flood_soak": {"FDTPU_BENCH_FLOOD_CHILD": "1"},
    "catchup": {"FDTPU_BENCH_CATCHUP_CHILD": "1"},
    "autotune": {"FDTPU_BENCH_AUTOTUNE_CHILD": "1"},
}


def build_plan(cfg: dict | None, repo_root: str,
               cpu_smoke: bool = False,
               stages: list[str] | None = None) -> list[dict]:
    """[witness] config (or None) -> the ordered, fully-resolved stage
    plan: [{name, cmd, env, timeout_s}]. This is the build-time gate of
    the load/build/lint triple — a bad table fails here before any
    stage runs. `stages` (CLI --stages) narrows further; order is
    always catalog order."""
    norm = normalize_witness(cfg)
    names = norm["stages"] or list(STAGES)
    if stages is not None:
        for s in stages:
            if s not in STAGES:
                from ..lint.registry import suggest
                raise ValueError(f"unknown witness stage {s!r}"
                                 + suggest(s, STAGES))
        names = [s for s in names if s in stages]
    cmds = default_stage_cmds(repo_root, cpu_smoke=cpu_smoke)
    overrides = norm["stage"] or {}
    plan = []
    for name in names:
        ov = overrides.get(name, {})
        if not ov.get("enable", True):
            continue
        env = {}
        if cpu_smoke:
            env.update(_CPU_SMOKE_ENV)
            env.update(_CPU_SMOKE_STAGE_ENV.get(name, {}))
        env.update(_STAGE_CHILD_ENV.get(name, {}))
        env.update(ov.get("env", {}))
        timeout = float(ov.get("timeout_s",
                               norm["probe_timeout_s"]
                               if name == "device_probe"
                               else norm["stage_timeout_s"]))
        plan.append({"name": name,
                     "cmd": list(ov.get("cmd", cmds[name])),
                     "env": env, "timeout_s": timeout})
    if not plan:
        raise ValueError("witness plan is empty (every stage disabled "
                         "or filtered out)")
    return plan
