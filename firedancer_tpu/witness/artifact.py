"""Witnessed-artifact discovery + assembly.

One definition of "the latest witnessed round" shared by bench.py's
tunnel-down fallback, fdwitness (next round number, previous-round
diffing) and fdbench (witnessed-vs-fallback reporting) — replacing the
hardcoded `BENCH_r05_witnessed.json` filename that silently went stale
every round.
"""
from __future__ import annotations

import glob
import json
import os
import re

WITNESSED_RE = re.compile(r"BENCH_r(\d+)_witnessed\.json$")
ROUND_RE = re.compile(r"BENCH_r(\d+)(?:_witnessed)?\.json$")


def witnessed_rounds(root: str) -> list[tuple[int, str]]:
    """[(round, path)] of every BENCH_r*_witnessed.json under root,
    NUMERICALLY ordered (r10 beats r9 — lexicographic sort does not)."""
    out = []
    for p in glob.glob(os.path.join(root, "BENCH_r*_witnessed.json")):
        m = WITNESSED_RE.search(os.path.basename(p))
        if m:
            out.append((int(m.group(1)), p))
    return sorted(out)


def latest_witnessed(root: str, require_platform: str | None = "tpu"
                     ) -> tuple[str, dict] | None:
    """(path, record) of the newest readable witnessed artifact —
    newest round first, skipping unreadable files and (when
    require_platform is set) records measured on another backend (a
    cpu-smoke artifact must never stand in for the chip number)."""
    for _, path in reversed(witnessed_rounds(root)):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(doc, dict):
            continue
        if require_platform is not None and \
                doc.get("platform") != require_platform:
            continue
        return path, doc
    return None


def next_round(root: str) -> int:
    """The round number a fresh witnessed artifact belongs to: the
    latest BENCH_r*.json round (witnessed or not), so the artifact
    lands NEXT TO the driver round it witnesses; 1 when none exist."""
    rounds = [int(m.group(1))
              for p in glob.glob(os.path.join(root, "BENCH_r*.json"))
              if (m := ROUND_RE.search(os.path.basename(p)))]
    return max(rounds) if rounds else 1


# stage -> (exact result keys, key prefixes) merged into the artifact
# top level. The artifact keeps bench.py's bare-record shape so every
# existing reader (bench.py fallback, fdbench, fdgui trends) consumes
# it unchanged; the `witness` block rides alongside.
_MERGE_RULES = {
    "kernel_vps": (("metric", "value", "unit", "vs_baseline",
                    "platform", "kernel", "batch", "iters", "msg_len",
                    "p99_batch_ms", "compile_s", "rlc_bulk_vps",
                    "rlc_bulk_batch", "rlc_compile_s", "rlc_error"),
                   ()),
    "e2e_feed": ((), ("e2e_",)),
    "leader_knee": ((), ("e2e_leader",)),
    "exec_scale": ((), ("exec_scale",)),
    "flood_soak": (("rlc_prefilter_vps",), ("flood_",)),
    "catchup": (("replay_tps",), ("catchup_",)),
    "autotune": (("tuned_vs_default_tps",), ("autotune_",)),
}


def stage_platform(ckpt: dict, result: dict) -> str:
    """What backend a stage's numbers were really measured on. A stage
    that names its platform is authoritative ('cpu (fallback)' is a
    cpu number wherever it ran); bench children that DON'T emit one
    (leader/flood — host-side loops driving device verify tiles) or
    that report the 'device' placeholder (the e2e parent must not
    init jax) inherit the probe stage's device fingerprint, which the
    runner stamps into every later checkpoint's provenance."""
    plat = str(result.get("platform") or "")
    if plat in ("", "device"):
        plat = str(((ckpt.get("provenance") or {}).get("device")
                    or {}).get("platform") or "")
    return plat


def merge_stages(stages: list[dict]) -> dict:
    """Stage checkpoints -> the flat witnessed record (bare bench.py
    shape) + the per-metric witnessed map."""
    rec: dict = {}
    witnessed: dict = {}
    for ckpt in stages:
        name, result = ckpt.get("stage"), ckpt.get("result")
        if not isinstance(result, dict):
            continue
        if ckpt.get("status") != "ok":
            # a failed/timed-out stage's parsed output stays in the
            # chain for diagnosis but must never surface as a headline
            # metric — a --keep-going artifact may carry gaps, not
            # clean-looking numbers from a failed run
            continue
        plat = stage_platform(ckpt, result)
        rule = _MERGE_RULES.get(name)
        if rule is not None:
            keys, prefixes = rule
            for k, v in result.items():
                if k in keys or k.startswith(prefixes):
                    rec[k] = v
                    witnessed[k] = {
                        "stage": name,
                        "witnessed": bool(plat)
                        and not plat.startswith("cpu"),
                    }
        elif name == "device_probe":
            rec.setdefault("platform", result.get("platform"))
        elif name == "mxu_fmul":
            rec["mxu_fmul"] = result
        elif name == "multichip":
            rec["multichip"] = result
            if "multichip_choice" in result:
                rec["multichip_choice"] = result["multichip_choice"]
    return {"record": rec, "witnessed": witnessed}


def assemble(run_doc: dict, stages: list[dict]) -> dict:
    """Run header + chained checkpoints -> the final self-describing
    artifact: flat record + `witnessed` per-metric map + full `witness`
    chain block."""
    merged = merge_stages(stages)
    art = dict(merged["record"])
    art["witnessed"] = merged["witnessed"]
    art["witness"] = {
        "v": 1,
        "run_id": run_doc.get("run_id"),
        "cpu_smoke": bool(run_doc.get("cpu_smoke")),
        "header": run_doc.get("header"),
        "genesis": run_doc.get("genesis"),
        "stages": stages,
        "head": stages[-1]["hash"] if stages else run_doc.get("genesis"),
        # the flat record (everything outside this block) is sealed
        # too: editing a headline number without re-deriving it from
        # the chained stage results is detectable
        "record_sha256": record_sha256(art),
    }
    return art


def record_sha256(doc: dict) -> str:
    """Recompute the flat-record seal of an artifact (everything
    outside the witness block) — compared against
    witness.record_sha256 by the verifiers."""
    import hashlib

    from .provenance import canonical
    return hashlib.sha256(
        canonical({k: v for k, v in doc.items()
                   if k != "witness"})).hexdigest()
