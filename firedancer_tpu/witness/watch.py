"""Watch mode: park on a dead tunnel, resume the sweep the moment
devices return.

The committed, kill-hardened replacement for the `/tmp/tpu_watch.sh`
oral tradition (PERF.md r3–r5 history): probe the backend in a BOUNDED
subprocess (the tunnel's documented failure mode is `jax.devices()`
hanging forever — the probe child gets killed at the deadline, the
watcher never blocks), park with exponential backoff while the tunnel
is down, and run/resume the SAME run-id the moment a device answers.
Kill-hardening is structural, not careful coding: the run's state is
its checkpoint files, so killing the watcher (or the box rebooting)
loses at most the stage in flight — rerunning the same command
continues where it stopped.
"""
from __future__ import annotations

import os
import shlex
import subprocess
import sys
import time

from .plan import PROBE_SNIPPET

# test/operator override: a shell command standing in for the real
# backend probe (e.g. a hanging `sleep` to drill the park path)
PROBE_CMD_ENV = "FDTPU_WITNESS_PROBE_CMD"


def probe_backend(repo_root: str, timeout_s: float,
                  cmd: list[str] | None = None,
                  env: dict | None = None) -> dict | None:
    """One bounded backend probe; returns the device fingerprint dict
    or None (probe hung, crashed, or printed no JSON)."""
    from .runner import _last_json_line
    if cmd is None:
        ov = os.environ.get(PROBE_CMD_ENV)
        cmd = shlex.split(ov) if ov \
            else [sys.executable, "-c", PROBE_SNIPPET]
    penv = dict(os.environ)
    penv.update(env or {})
    try:
        r = subprocess.run(cmd, cwd=repo_root, env=penv,
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except (subprocess.TimeoutExpired, OSError):
        return None
    if r.returncode != 0:
        return None
    return _last_json_line(r.stdout or "")


def watch(run, probe_timeout_s: float = 60.0, park_s: float = 30.0,
          park_max_s: float = 360.0, require_accel: bool = True,
          max_probes: int | None = None, probe_cmd: list[str] | None = None,
          log=print, sleep=time.sleep) -> int:
    """Probe-park-resume loop around a WitnessRun. Returns the run's
    exit code once the sweep finalizes, or 3 when max_probes expires
    still parked (the bounded form tests and cron wrappers use;
    max_probes=None parks forever like the old watcher)."""
    backoff = park_s
    probes = 0
    while True:
        probes += 1
        fp = probe_backend(run.repo_root, probe_timeout_s,
                           cmd=probe_cmd)
        up = fp is not None and (not require_accel
                                 or not str(fp.get("platform", "cpu")
                                            ).startswith("cpu"))
        if up:
            log(f"fdwitness: backend up ({fp.get('platform')}"
                f"/{fp.get('device_kind', '?')}) — running sweep")
            rc = run.run()
            if rc == 0 or rc == 2:
                # finalized, or chain broken (retrying won't fix a
                # tampered run — surface it)
                return rc
            log("fdwitness: sweep parked mid-run (stage failure — "
                "likely the tunnel flapped); backing off "
                f"{backoff:.0f}s")
        else:
            log(f"fdwitness: backend down (probe "
                f"{'timed out/failed' if fp is None else 'cpu-only'}) "
                f"— parked, retry in {backoff:.0f}s")
        if max_probes is not None and probes >= max_probes:
            return 3
        sleep(backoff)
        backoff = min(backoff * 2, park_max_s)
