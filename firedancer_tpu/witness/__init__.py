"""fdwitness: the one-command, resumable, provenance-stamped
witnessed-sweep orchestrator.

ROADMAP item 1's bottleneck is not code, it is PROCESS: rounds of
performance work queue behind one flaky TPU tunnel window, and the
witnessed-run procedure lived in /tmp scripts and PERF.md prose. This
package makes the run a committed artifact of the repo:

    plan.py        the ordered stage catalog (every gated stanza) +
                   the [witness] config schema (load/build/lint triple)
    provenance.py  git/stack/device/knob/clock stamps + the per-stage
                   hash chain (tamper-evident artifacts)
    runner.py      bounded-subprocess stage execution, atomic per-stage
                   checkpoints, resume-by-run-id, artifact + merged
                   fdgui report assembly
    watch.py       hang-proof backend probe + park/backoff/resume loop
                   (the committed replacement for /tmp/tpu_watch.sh)
    multichip.py   the measured shard_map layout shootout (per-chip rr
                   tiles vs one mesh tile) — ROADMAP 1b's decision
    artifact.py    glob-latest BENCH_r*_witnessed.json discovery shared
                   with bench.py and fdbench, artifact assembly
    cli.py         `python -m firedancer_tpu.witness` / tools/fdwitness

No module here imports jax at module level, and the orchestrator
process never initializes a backend — the device tunnel belongs to the
stage subprocesses (whose documented failure mode, hanging, is why
every stage and probe runs under a hard deadline).
"""
from .artifact import (  # noqa: F401
    assemble, latest_witnessed, merge_stages, next_round,
    record_sha256, witnessed_rounds,
)
from .plan import (  # noqa: F401
    STAGES, WITNESS_DEFAULTS, WITNESS_STAGE_KEYS, build_plan,
    normalize_witness,
)
from .provenance import (  # noqa: F401
    chain_hash, checkpoint_payload, provenance_block, seal,
    verify_chain,
)
from .runner import WitnessRun, dry_run  # noqa: F401
from .watch import probe_backend, watch  # noqa: F401
