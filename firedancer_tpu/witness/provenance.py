"""Provenance blocks + the per-stage hash chain.

The north-star gate (>= 1M ed25519 verifies/s, BASELINE.json) is only
credible if the witnessed artifact carries its own provenance: WHAT
code ran (git sha + dirty flag), on WHAT stack (jax/jaxlib/libtpu
versions — read via importlib.metadata, never by importing jax: the
orchestrator process must not touch the exclusive device tunnel), on
WHAT hardware (the device fingerprint from the probe stage), with WHAT
knobs (the full FDTPU_BENCH_* env snapshot), and WHEN (wall + monotonic
clock anchors, so stage records order even across host clock steps).

Stages are hash-chained in plan order: each checkpoint's `hash` is
sha256 over the canonical JSON of the checkpoint payload plus the
previous stage's hash (genesis = the run header). Editing any stage
result, provenance field, or the header after the fact breaks every
downstream link — `verify_chain` (used by `fdwitness verify` and
`tools/fdbench --verify`) names the first tampered stage.
"""
from __future__ import annotations

import hashlib
import json
import os
import subprocess
import time

# env prefixes snapshotted into every provenance block: the bench knob
# space plus the backend selectors that change what a stage measures
KNOB_PREFIXES = ("FDTPU_BENCH_", "FDTPU_VERIFY_", "FDTPU_WITNESS_")
KNOB_KEYS = ("JAX_PLATFORMS", "XLA_FLAGS")


def canonical(obj) -> bytes:
    """Deterministic JSON encoding — the only form the chain hashes."""
    return json.dumps(obj, sort_keys=True,
                      separators=(",", ":")).encode()


def chain_hash(prev_hash: str, payload: dict) -> str:
    h = hashlib.sha256()
    h.update(prev_hash.encode())
    h.update(canonical(payload))
    return h.hexdigest()


def git_state(repo_root: str) -> dict:
    """{"sha", "dirty"} — best-effort (a non-repo checkout still gets
    a self-describing artifact, just an unknown sha)."""
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=repo_root,
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or "unknown"
        dirty = bool(subprocess.run(
            ["git", "status", "--porcelain"], cwd=repo_root,
            capture_output=True, text=True, timeout=10,
        ).stdout.strip())
    except (OSError, subprocess.SubprocessError):
        sha, dirty = "unknown", True
    return {"sha": sha, "dirty": dirty}


def pkg_versions() -> dict:
    """jax/jaxlib/libtpu versions WITHOUT importing jax (the parent
    must never initialize the backend — it belongs to the stage
    children)."""
    from importlib import metadata
    out = {}
    for pkg in ("jax", "jaxlib", "libtpu", "libtpu-nightly", "numpy"):
        try:
            out[pkg] = metadata.version(pkg)
        except metadata.PackageNotFoundError:
            continue
    return out


def knob_snapshot(env: dict | None = None) -> dict:
    env = os.environ if env is None else env
    out = {k: v for k, v in env.items()
           if k.startswith(KNOB_PREFIXES) or k in KNOB_KEYS}
    return dict(sorted(out.items()))


def lint_state(repo_root: str) -> dict:
    """fdlint's verdict on the tree that produced the artifact:
    {"clean", "errors", "warnings"}. A witnessed number from a tree
    with non-baseline findings is still a number — but the reader
    deserves to know the static gates did not pass. Cached per
    process: the orchestrator stamps many stages from one tree."""
    global _LINT_STATE
    if _LINT_STATE is None:
        try:
            from ..lint.cli import run as lint_run
            from ..lint.core import filter_baselined, load_baseline
            findings = lint_run([os.path.join(repo_root, "cfg"),
                                 os.path.join(repo_root,
                                              "firedancer_tpu")])
            findings = filter_baselined(
                findings,
                load_baseline(os.path.join(repo_root,
                                           "lint-baseline.toml")))
            errors = sum(1 for f in findings if f.severity == "error")
            warnings = len(findings) - errors
            _LINT_STATE = {"clean": errors == 0, "errors": errors,
                           "warnings": warnings}
        except Exception as e:   # lint must never block a witness run
            _LINT_STATE = {"clean": False, "errors": -1,
                           "warnings": -1,
                           "reason": f"lint failed to run: {e}"}
    return dict(_LINT_STATE)


_LINT_STATE: dict | None = None


def provenance_block(repo_root: str,
                     extra_env: dict | None = None) -> dict:
    """The stamp every stage checkpoint (and the run header) carries.
    `extra_env` folds the stage's own env overrides into the knob
    snapshot — the knobs recorded are the knobs the stage SAW."""
    import platform
    env = dict(os.environ)
    env.update(extra_env or {})
    return {
        "git": git_state(repo_root),
        "host": {
            "hostname": platform.node(),
            "platform": platform.platform(),
            "machine": platform.machine(),
            "cpu_count": os.cpu_count() or 1,
            "python": platform.python_version(),
        },
        "versions": pkg_versions(),
        "lint": lint_state(repo_root),
        "knobs": knob_snapshot(env),
        "clock": {
            "wall_iso": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                      time.gmtime()),
            "wall_s": round(time.time(), 3),
            "monotonic_ns": time.monotonic_ns(),
        },
    }


def checkpoint_payload(ckpt: dict) -> dict:
    """The hashed portion of a checkpoint: everything except the hash
    itself (prev_hash IS included — that is the chain link)."""
    return {k: v for k, v in ckpt.items() if k != "hash"}


def seal(ckpt: dict, prev_hash: str) -> dict:
    """Stamp prev_hash + hash onto a checkpoint dict (in place)."""
    ckpt["prev_hash"] = prev_hash
    ckpt["hash"] = chain_hash(prev_hash, checkpoint_payload(ckpt))
    return ckpt


def verify_chain(witness: dict) -> list[str]:
    """Verify a witness block ({header, genesis, stages, head}) —
    returns human-readable errors, [] when the chain is intact."""
    errors = []
    if not isinstance(witness, dict):
        return ["witness block is not a dict"]
    header = witness.get("header")
    genesis = witness.get("genesis")
    if header is None or genesis is None:
        return ["witness block missing header/genesis"]
    want_genesis = chain_hash("", header)
    if genesis != want_genesis:
        errors.append("genesis hash does not match the run header "
                      "(header tampered)")
    prev = genesis
    for i, ckpt in enumerate(witness.get("stages", [])):
        name = ckpt.get("stage", f"#{i}")
        if ckpt.get("prev_hash") != prev:
            errors.append(f"stage {name!r}: prev_hash broke the chain "
                          f"(expected {prev[:12]}..., got "
                          f"{str(ckpt.get('prev_hash'))[:12]}...)")
        want = chain_hash(ckpt.get("prev_hash", ""),
                          checkpoint_payload(ckpt))
        if ckpt.get("hash") != want:
            errors.append(f"stage {name!r}: content hash mismatch "
                          f"(checkpoint tampered)")
        prev = ckpt.get("hash", want)
    head = witness.get("head")
    if head is not None and witness.get("stages") and head != prev:
        errors.append("head hash does not match the last stage")
    return errors
