"""fdwitness CLI: one command from tunnel window to witnessed artifact.

    tools/fdwitness run [--cpu-smoke] [--run-id ID] [--stages a,b]
        [--config cfg.toml] [--out-dir DIR] [--artifact PATH]
                                 run (or RESUME) the checkpointed sweep
    tools/fdwitness run --dry-run
                                 validate the plan + provenance capture
                                 (prints the resolved plan, runs nothing)
    tools/fdwitness watch [...]  park on a dead tunnel with backoff,
        [--park-s S] [--max-probes N] [--allow-cpu]
                                 run/resume the moment devices return
    tools/fdwitness verify ARTIFACT.json
                                 verify the provenance hash chain
    tools/fdwitness status [--out-dir DIR]
                                 list runs + per-stage checkpoints

`--watch` / `--dry-run` as the first token are accepted as aliases for
the subcommands (the ISSUE's spelling).
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _load_cfg(path: str | None) -> dict | None:
    if not path:
        return None
    from ..app.config import load_config
    return load_config(path).get("witness")


def _add_run_args(ap):
    ap.add_argument("--run-id", default=None,
                    help="resume (or name) this run; default: latest "
                         "unfinalized run, else a fresh timestamped id")
    ap.add_argument("--cpu-smoke", action="store_true",
                    help="CPU-sized knobs for every stage (full "
                         "orchestrator drill on a box with no device)")
    ap.add_argument("--stages", default=None,
                    help="comma list (subset of the catalog, runs in "
                         "catalog order)")
    ap.add_argument("--config", default=None,
                    help="TOML with a [witness] section")
    ap.add_argument("--out-dir", default=None,
                    help="run/checkpoint directory (default: "
                         "<repo>/.fdwitness)")
    ap.add_argument("--artifact", default=None,
                    help="artifact path override (default: "
                         "<repo>/BENCH_r<NN>_witnessed.json)")
    ap.add_argument("--keep-going", action="store_true",
                    help="continue the sweep past a failed stage")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # ISSUE spelling: `fdwitness --watch` / `fdwitness --dry-run`
    if argv[:1] == ["--watch"]:
        argv[0] = "watch"
    elif "--dry-run" in argv and (not argv or argv[0].startswith("-")):
        argv.insert(0, "run")

    ap = argparse.ArgumentParser(
        prog="fdwitness",
        description="resumable, provenance-stamped witnessed-sweep "
                    "orchestrator")
    sub = ap.add_subparsers(dest="cmd", required=True)

    run_p = sub.add_parser("run", help="run/resume the sweep")
    _add_run_args(run_p)
    run_p.add_argument("--dry-run", action="store_true",
                       help="validate plan + provenance, run nothing")

    watch_p = sub.add_parser("watch", help="park until devices return, "
                                           "then run/resume")
    _add_run_args(watch_p)
    watch_p.add_argument("--park-s", type=float, default=None,
                         help="backoff floor (default from [witness])")
    watch_p.add_argument("--probe-timeout-s", type=float, default=None,
                         help="hang-proof probe deadline (default "
                              "from [witness])")
    watch_p.add_argument("--max-probes", type=int, default=None,
                         help="give up (exit 3) after N parked probes "
                              "(default: park forever)")
    watch_p.add_argument("--allow-cpu", action="store_true",
                         help="a cpu-only backend counts as up "
                              "(cpu-smoke watch drills)")

    ver_p = sub.add_parser("verify", help="verify an artifact's chain")
    ver_p.add_argument("artifact")

    st_p = sub.add_parser("status", help="list runs + checkpoints")
    st_p.add_argument("--out-dir", default=None)

    args = ap.parse_args(argv)
    root = _repo_root()

    if args.cmd == "verify":
        return verify_artifact(args.artifact)

    if args.cmd == "status":
        return status(root, args.out_dir)

    cfg = _load_cfg(args.config)
    if args.keep_going:
        cfg = dict(cfg or {})
        cfg["keep_going"] = True
    if getattr(args, "probe_timeout_s", None):
        # one deadline, both probes: the watch-loop probe AND the
        # sweep's own device_probe stage (a tunnel slow enough to need
        # the raised deadline must not pass the first and fail the
        # second forever)
        cfg = dict(cfg or {})
        cfg["probe_timeout_s"] = float(args.probe_timeout_s)
    stages = [s for s in (args.stages or "").split(",") if s] or None

    if args.cmd == "run" and args.dry_run:
        from .runner import dry_run
        try:
            return dry_run(root, cfg, args.cpu_smoke, stages)
        except ValueError as e:
            print(f"fdwitness: {e}", file=sys.stderr)
            return 2

    from .runner import WitnessRun
    try:
        run = WitnessRun.create(root, run_id=args.run_id, cfg=cfg,
                                cpu_smoke=args.cpu_smoke, stages=stages,
                                out_dir=args.out_dir,
                                artifact_path=args.artifact)
    except ValueError as e:
        print(f"fdwitness: {e}", file=sys.stderr)
        return 2

    if args.cmd == "watch":
        from .plan import normalize_witness
        from .watch import watch
        norm = normalize_witness(cfg)
        return watch(run,
                     probe_timeout_s=args.probe_timeout_s
                     or norm["probe_timeout_s"],
                     park_s=args.park_s or norm["park_s"],
                     park_max_s=max(norm["park_max_s"],
                                    args.park_s or 0),
                     require_accel=not args.allow_cpu
                     and not args.cpu_smoke,
                     max_probes=args.max_probes)
    return run.run()


def verify_artifact(path: str) -> int:
    from .provenance import verify_chain
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"fdwitness: cannot read {path}: {e}", file=sys.stderr)
        return 2
    wit = doc.get("witness")
    if not wit:
        print(f"fdwitness: {path} carries no witness block "
              f"(pre-fdwitness artifact)", file=sys.stderr)
        return 2
    errors = verify_chain(wit)
    want = wit.get("record_sha256")
    if want is not None:
        from .artifact import record_sha256
        if record_sha256(doc) != want:
            errors.append("flat record does not match its seal "
                          "(headline fields tampered)")
    # lint stamp: the run header (a provenance block) records fdlint's
    # verdict on the tree that ran; verify surfaces a dirty stamp
    # loudly (older artifacts predate the stamp — absence is reported,
    # not an error)
    lint = (wit.get("header") or {}).get("lint")
    if lint is None:
        print("  lint_clean     (no stamp — pre-abi-lint artifact)")
    elif lint.get("clean"):
        print("  lint_clean     yes")
    else:
        print(f"  lint_clean     NO ({lint.get('errors')} error(s))")
        errors.append(
            f"tree had {lint.get('errors')} non-baseline lint "
            f"error(s) when this artifact was produced "
            f"(lint_clean stamp)")
    from .artifact import stage_platform
    for ckpt in wit.get("stages", []):
        # same platform resolution as the artifact's witnessed map
        plat = stage_platform(ckpt, ckpt.get("result") or {})
        badge = "witnessed" if plat and not plat.startswith("cpu") \
            else "cpu"
        print(f"  {ckpt.get('stage'):<14} {ckpt.get('status'):<8} "
              f"[{badge}] {str(ckpt.get('hash'))[:12]}...")
    if errors:
        for e in errors:
            print(f"fdwitness: TAMPERED: {e}", file=sys.stderr)
        return 1
    print(f"fdwitness: chain intact "
          f"(head {str(wit.get('head'))[:12]}..., "
          f"{len(wit.get('stages', []))} stages, run "
          f"{wit.get('run_id')})")
    return 0


def status(root: str, out_dir: str | None) -> int:
    from .plan import WITNESS_DEFAULTS
    base = out_dir or os.path.join(root, WITNESS_DEFAULTS["out_dir"])
    try:
        runs = sorted(d for d in os.listdir(base)
                      if os.path.exists(os.path.join(base, d,
                                                     "run.json")))
    except OSError:
        runs = []
    if not runs:
        print(f"no runs under {base}")
        return 0
    from .runner import WitnessRun
    for rid in runs:
        run = WitnessRun.load(root, os.path.join(base, rid),
                              log=lambda *_: None)
        ckpts = {c["stage"]: c["status"] for c in run.checkpoints()}
        states = " ".join(
            f"{s['name']}={ckpts.get(s['name'], '-')}"
            for s in run.doc["plan"])
        tag = "final" if run.finalized() else "in-flight"
        print(f"{rid}  [{tag}]  {states}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
