"""The checkpointed sweep runner: one tunnel window -> one artifact.

Every stage runs as a bounded subprocess (the tunnel's failure mode is
a HANG, so nothing in this process ever calls into jax) and writes one
checkpoint JSON — atomically, tmp + rename — the moment it finishes.
A killed run therefore loses at most the stage that was in flight:
`fdwitness run` with the same run-id reloads the checkpoints, verifies
the chain is intact, skips every completed stage, and resumes at the
first missing/failed one. Because stages execute strictly in plan
order and failures rerun only from the TAIL, the hash chain stays
append-only by construction.

Layout of a run directory (<out_dir>/<run_id>/):

    run.json          the immutable run header: plan + provenance +
                      genesis hash (resume uses THIS plan, not the
                      CLI's — the plan that finishes is provably the
                      plan that started)
    NN_<stage>.json   one chained checkpoint per stage, plan order
    NN_<stage>.log    the stage's captured stdout+stderr (full)

Finalize merges the checkpoints into `BENCH_r*_witnessed.json` (bare
bench.py record shape + the `witness` chain block) and renders the
merged fdgui report (`<artifact>.report.html`) with the provenance
header panel and every stanza's numbers on the bench-trend page.
"""
from __future__ import annotations

import json
import os
import resource
import subprocess
import sys
import time

from . import artifact as art
from . import plan as planmod
from . import provenance as prov

# stage status taxonomy: ok/skipped are terminal ("completed"),
# failed/timeout rerun on resume
DONE_STATUSES = ("ok", "skipped")


def _atomic_write(path: str, doc: dict):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _last_json_line(text: str) -> dict | None:
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(doc, dict):
            return doc
    return None


class WitnessRun:
    """One named, resumable witnessed sweep."""

    def __init__(self, repo_root: str, run_dir: str, run_doc: dict,
                 log=print):
        self.repo_root = repo_root
        self.run_dir = run_dir
        self.doc = run_doc
        self.log = log

    # -- construction ------------------------------------------------------

    @classmethod
    def create(cls, repo_root: str, run_id: str | None = None,
               cfg: dict | None = None, cpu_smoke: bool = False,
               stages: list[str] | None = None,
               out_dir: str | None = None, artifact_path: str | None = None,
               log=print) -> "WitnessRun":
        norm = planmod.normalize_witness(cfg)
        base = out_dir or os.path.join(repo_root, norm["out_dir"])
        stage_plan = planmod.build_plan(cfg, repo_root,
                                        cpu_smoke=cpu_smoke,
                                        stages=stages)

        def _resume(rid: str) -> "WitnessRun":
            run = cls.load(repo_root, os.path.join(base, rid), log=log)
            # the PLAN is the immutable run.json record (what resumes
            # is provably what started) — but mutable EXECUTION knobs
            # follow this invocation, so `run --keep-going` on a
            # parked run actually keeps going
            if "keep_going" in (cfg or {}):
                run.doc["keep_going"] = norm["keep_going"]
            if artifact_path:
                run.doc["artifact"] = artifact_path
            return run

        if run_id is not None and \
                os.path.exists(os.path.join(base, run_id, "run.json")):
            return _resume(run_id)
        if run_id is None:
            # resume-friendly default: the newest unfinalized run
            # whose stored plan MATCHES this invocation continues (a
            # leftover full-size run must not hijack a --cpu-smoke
            # drill, or vice versa); none compatible -> a fresh
            # wall-clock-stamped run starts
            for cand in cls._unfinished(base):
                stored = cls.load(repo_root, os.path.join(base, cand),
                                  log=lambda *_: None).doc
                if bool(stored.get("cpu_smoke")) == bool(cpu_smoke) \
                        and [s["name"] for s in stored["plan"]] \
                        == [s["name"] for s in stage_plan]:
                    return _resume(cand)
                log(f"fdwitness: unfinished run {cand!r} has a "
                    f"different plan — skipping it")
            run_id = time.strftime("run-%Y%m%d-%H%M%S", time.gmtime())
        rnd = norm["round"] or art.next_round(repo_root)
        header = prov.provenance_block(repo_root)
        run_dir = os.path.join(base, run_id)
        if artifact_path is None:
            # a cpu-smoke drill must never claim (or clobber) the
            # repo-root witnessed slot a real chip run owns — its
            # artifact stays inside the run directory unless the
            # operator points elsewhere explicitly
            art_dir = run_dir if cpu_smoke else repo_root
            artifact_path = os.path.join(
                art_dir, f"BENCH_r{rnd:02d}_witnessed.json")
        run_doc = {
            "v": 1,
            "run_id": run_id,
            "cpu_smoke": bool(cpu_smoke),
            "round": rnd,
            "keep_going": norm["keep_going"],
            "report": norm["report"],
            "artifact": artifact_path,
            "plan": stage_plan,
            "header": header,
            "genesis": prov.chain_hash("", header),
        }
        os.makedirs(run_dir, exist_ok=True)
        _atomic_write(os.path.join(run_dir, "run.json"), run_doc)
        return cls(repo_root, run_dir, run_doc, log=log)

    @classmethod
    def load(cls, repo_root: str, run_dir: str, log=print) -> "WitnessRun":
        with open(os.path.join(run_dir, "run.json")) as f:
            return cls(repo_root, run_dir, json.load(f), log=log)

    @staticmethod
    def _unfinished(base: str) -> list[str]:
        """Unfinalized run ids under base, newest first."""
        try:
            runs = sorted(d for d in os.listdir(base)
                          if os.path.exists(
                              os.path.join(base, d, "run.json")))
        except OSError:
            return []
        return [rid for rid in reversed(runs)
                if not os.path.exists(os.path.join(base, rid,
                                                   "final.json"))]

    # -- checkpoints -------------------------------------------------------

    def _ckpt_path(self, idx: int, name: str) -> str:
        return os.path.join(self.run_dir, f"{idx:02d}_{name}.json")

    def checkpoints(self) -> list[dict]:
        """Stage checkpoints in plan order, stopping at the first gap
        (stages run strictly in order — a gap means nothing after it
        ever ran)."""
        out = []
        for i, spec in enumerate(self.doc["plan"]):
            path = self._ckpt_path(i, spec["name"])
            if not os.path.exists(path):
                break
            try:
                with open(path) as f:
                    out.append(json.load(f))
            except (OSError, json.JSONDecodeError):
                break
        return out

    def chain_ok(self, ckpts: list[dict]) -> list[str]:
        return prov.verify_chain({"header": self.doc["header"],
                                  "genesis": self.doc["genesis"],
                                  "stages": ckpts})

    def finalized(self) -> bool:
        return os.path.exists(os.path.join(self.run_dir, "final.json"))

    # -- execution ---------------------------------------------------------

    def run_stage(self, idx: int, spec: dict, prev_hash: str,
                  device: dict | None) -> dict:
        env = dict(os.environ)
        env.update(spec["env"])
        stamp = prov.provenance_block(self.repo_root,
                                      extra_env=spec["env"])
        if device:
            stamp["device"] = device
        self.log(f"fdwitness: stage {spec['name']} "
                 f"(timeout {spec['timeout_s']:.0f}s)")
        t0 = time.monotonic()
        ru0 = resource.getrusage(resource.RUSAGE_CHILDREN)
        status, rc, out_text = "ok", 0, ""
        try:
            r = subprocess.run(spec["cmd"], env=env, cwd=self.repo_root,
                               capture_output=True, text=True,
                               timeout=spec["timeout_s"])
            rc = r.returncode
            out_text = (r.stdout or "") + "\n--- stderr ---\n" \
                + (r.stderr or "")
            result = _last_json_line(r.stdout or "")
            if result is None:
                status = "failed"
                result = {"error": "no JSON result line on stdout"}
            elif rc != 0:
                # the stage children (bench.py child modes, the mxu
                # experiment, the multichip shootout) all exit 0 on
                # success — a nonzero exit is a failure even when a
                # JSON line made it out (e.g. multichip's structured
                # no-mesh error); the parsed result is kept in the
                # checkpoint for diagnosis, and resume reruns it
                status = "failed"
                result.setdefault("stage_rc", rc)
        except subprocess.TimeoutExpired as e:
            status, rc = "timeout", -1
            out_text = ((e.stdout or b"").decode("utf-8", "replace")
                        if isinstance(e.stdout, bytes)
                        else (e.stdout or ""))
            result = {"error":
                      f"stage deadline {spec['timeout_s']:.0f}s "
                      f"expired (subprocess killed)"}
        except OSError as e:
            status, rc = "failed", -1
            result = {"error": f"spawn failed: {e!r}"}
        ru1 = resource.getrusage(resource.RUSAGE_CHILDREN)
        dur = time.monotonic() - t0
        log_path = self._ckpt_path(idx, spec["name"])[:-5] + ".log"
        try:
            with open(log_path, "w") as f:
                f.write(out_text)
        except OSError:
            pass
        ckpt = {
            "stage": spec["name"],
            "idx": idx,
            "status": status,
            "rc": rc,
            "duration_s": round(dur, 3),
            "rusage": {
                "utime_s": round(ru1.ru_utime - ru0.ru_utime, 3),
                "stime_s": round(ru1.ru_stime - ru0.ru_stime, 3),
                "maxrss_kb": ru1.ru_maxrss,
            },
            "cmd": spec["cmd"],
            "env": spec["env"],
            "result": result,
            "provenance": stamp,
        }
        prov.seal(ckpt, prev_hash)
        _atomic_write(self._ckpt_path(idx, spec["name"]), ckpt)
        self.log(f"fdwitness: stage {spec['name']} -> {status} "
                 f"({dur:.1f}s)")
        return ckpt

    def run(self) -> int:
        """Resume/run the sweep. Returns 0 when every stage completed
        and the artifact was finalized; 1 when a stage failed (and
        keep_going is off); 2 when the existing checkpoint chain is
        broken (refuse to extend a tampered run)."""
        ckpts = self.checkpoints()
        # completed prefix: ok/skipped stages are skipped on resume;
        # the first failed/timeout checkpoint (and everything after)
        # reruns — failures are exactly what a tunnel flap leaves
        done = []
        for c in ckpts:
            if c.get("status") in DONE_STATUSES:
                done.append(c)
            else:
                break
        errors = self.chain_ok(done)
        if errors:
            for e in errors:
                self.log(f"fdwitness: CHAIN BROKEN: {e}")
            return 2
        if done:
            self.log(f"fdwitness: resuming {self.doc['run_id']} — "
                     f"{len(done)}/{len(self.doc['plan'])} stages "
                     f"already witnessed")
        device = None
        for c in done:
            if c["stage"] == "device_probe" and \
                    isinstance(c.get("result"), dict):
                device = c["result"]
        prev_hash = done[-1]["hash"] if done else self.doc["genesis"]
        for idx in range(len(done), len(self.doc["plan"])):
            spec = self.doc["plan"][idx]
            ckpt = self.run_stage(idx, spec, prev_hash, device)
            prev_hash = ckpt["hash"]
            done.append(ckpt)
            if ckpt["stage"] == "device_probe" and \
                    ckpt["status"] == "ok":
                device = ckpt["result"]
            if ckpt["status"] not in DONE_STATUSES and \
                    not self.doc.get("keep_going"):
                self.log(f"fdwitness: stage {spec['name']} "
                         f"{ckpt['status']} — parking the sweep "
                         f"(resume with the same run-id)")
                return 1
        self.finalize(done)
        return 0

    # -- artifact ----------------------------------------------------------

    def finalize(self, ckpts: list[dict] | None = None) -> str:
        ckpts = ckpts if ckpts is not None else self.checkpoints()
        doc = art.assemble(self.doc, ckpts)
        out_path = self.doc["artifact"]
        # last-line defense (the cpu-smoke default path already avoids
        # this): a cpu-measured record must never overwrite an
        # existing chip-witnessed artifact — the chip number is the
        # irreplaceable one. Divert into the run dir and say so.
        if str(doc.get("platform", "")).startswith("cpu") and \
                os.path.exists(out_path):
            try:
                with open(out_path) as f:
                    old_plat = str(json.load(f).get("platform", ""))
            except (OSError, json.JSONDecodeError):
                old_plat = ""
            if old_plat and not old_plat.startswith("cpu"):
                diverted = os.path.join(self.run_dir,
                                        os.path.basename(out_path))
                self.log(f"fdwitness: {out_path} holds a "
                         f"{old_plat!r}-witnessed record — NOT "
                         f"overwriting with a cpu run; artifact "
                         f"diverted to {diverted}")
                out_path = self.doc["artifact"] = diverted
        _atomic_write(out_path, doc)
        _atomic_write(os.path.join(self.run_dir, "final.json"),
                      {"artifact": out_path,
                       "head": doc["witness"]["head"]})
        self.log(f"fdwitness: artifact {out_path} "
                 f"(head {doc['witness']['head'][:12]}...)")
        if self.doc.get("report", True):
            try:
                rep = self._report(out_path, doc)
                self.log(f"fdwitness: report {rep}")
            except Exception as e:  # noqa: BLE001 — the artifact stands
                self.log(f"fdwitness: report failed: {e!r}")
        return out_path

    def _report(self, artifact_path: str, doc: dict) -> str:
        """ONE merged fdgui report next to the artifact: every BENCH
        round's trend plus this run, the per-stage profile digests as
        flamegraph data, and the provenance/witness header panel."""
        import glob as _glob
        from ..gui.report import report_from_bench
        rounds = sorted(_glob.glob(
            os.path.join(self.repo_root, "BENCH_r*.json")))
        rounds = [r for r in rounds
                  if "witnessed" not in os.path.basename(r)]
        flame = {}
        prof = (doc.get("e2e_profile") or {})
        for tn, p in prof.items():
            if isinstance(p, dict) and p.get("top"):
                flame[tn] = {t["stack"]: {"work": int(t["count"])}
                             for t in p["top"]}
        rep_path = os.path.splitext(artifact_path)[0] + ".report.html"
        return report_from_bench(rounds + [artifact_path], rep_path,
                                 witness=doc.get("witness"),
                                 witnessed=doc.get("witnessed"),
                                 flame=flame)


def dry_run(repo_root: str, cfg: dict | None, cpu_smoke: bool,
            stages: list[str] | None, out=sys.stdout) -> int:
    """`fdwitness --dry-run`: validate the plan + provenance capture
    without running any stage or creating a run dir — the CI hook that
    keeps the sweep one WORKING command while the tunnel is down."""
    stage_plan = planmod.build_plan(cfg, repo_root, cpu_smoke=cpu_smoke,
                                    stages=stages)
    header = prov.provenance_block(repo_root)
    doc = {
        "dry_run": True,
        "round": (planmod.normalize_witness(cfg)["round"]
                  or art.next_round(repo_root)),
        "plan": [{"name": s["name"], "cmd": s["cmd"],
                  "env": s["env"], "timeout_s": s["timeout_s"]}
                 for s in stage_plan],
        "header": header,
        "genesis": prov.chain_hash("", header),
    }
    json.dump(doc, out, indent=1, sort_keys=True)
    out.write("\n")
    return 0
