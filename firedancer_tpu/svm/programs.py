"""Host-side transaction executor + native system program + CPI.

The reference's per-txn execution (load accounts, charge fees, dispatch
instructions sequentially through native program handlers, commit or
roll back atomically) lives in fd_executor/fd_system_program
(ref: src/flamenco/runtime/fd_executor.c, fd_runtime.h:254-266,
program/fd_system_program.c:59-330). The wave executor (executor.py)
covers the batched pure-transfer fast path on device; THIS module is
the general host path the exec tiles run for everything else — the
split SURVEY §7 hard-part 6 prescribes (sBPF and general dispatch stay
on host cores).

Instructions execute through an InstrCtx: a local-index account view
carrying THIS invocation's privileges. The top-level view derives
signer/writable from the transaction message; a CPI view derives them
from the caller-validated account metas (ref: the instruction context
stack of fd_executor.c / fd_exec_instr_ctx.h — privileges never
escalate down the stack except through verified PDA seed signing,
fd_vm_syscall_cpi.c).

Semantics mirrored from the reference per instruction:
  Transfer        from must SIGN, be writable, system-owned, no data;
                  insufficient lamports aborts the txn
                  (fd_system_program.c:59-137)
  CreateAccount   to must SIGN, be empty (0 lamports, no data, system
                  owner); allocate+assign+fund (:254-330)
  Assign          account must SIGN, be writable, system-owned (:202-230)
  Allocate        account must SIGN, be writable, system-owned, data
                  empty; space <= MAX_PERMITTED_DATA_LENGTH (:143-200)

A failing instruction rolls the whole transaction back; the fee is
charged to the payer regardless (the reference commits fees before
execution). Every touched account goes through accdb rw handles, so
rollback is just dropping them (accdb.close_rw(discard=True)).
"""
from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass

from ..funk.funk import key32
from ..protocol.txn import ParsedTxn, parse_txn
from .accdb import AccDb, Account, SYSTEM_PROGRAM_ID

# the REAL base58 program id (shared with the pack cost model)
from ..pack.cost import COMPUTE_BUDGET_PROGRAM_ID  # noqa: E402
BPF_LOADER_ID = b"BPFLoader" + bytes(23)
MAX_PERMITTED_DATA_LENGTH = 10 * 1024 * 1024
MAX_CPI_DEPTH = 4                  # instruction stack height limit
MAX_SEEDS = 16                     # PDA seed count limit (Solana)
MAX_SEED_LEN = 32

# system instruction discriminants (u32 LE bincode, the Agave enum)
SYS_CREATE_ACCOUNT = 0
SYS_ASSIGN = 1
SYS_TRANSFER = 2
SYS_CREATE_WITH_SEED = 3
SYS_ADVANCE_NONCE = 4
SYS_WITHDRAW_NONCE = 5
SYS_INIT_NONCE = 6
SYS_AUTHORIZE_NONCE = 7
SYS_ALLOCATE = 8
SYS_ALLOCATE_WITH_SEED = 9
SYS_ASSIGN_WITH_SEED = 10
SYS_TRANSFER_WITH_SEED = 11

NONCE_STATE_SZ = 80           # u32 version | u32 state | authority 32
                              # | durable nonce 32 | fee/sig u64


def create_with_seed(base: bytes, seed: bytes, owner: bytes) -> bytes:
    """Pubkey::create_with_seed — sha256(base || seed || owner); seed-
    derived addresses are NOT PDAs (no off-curve requirement),
    ref fd_system_program.c:389."""
    return hashlib.sha256(base + seed + owner).digest()


def _read_seed_str(data: bytes, off: int):
    """bincode String: u64 length + utf8 bytes; -> (seed, next_off)."""
    if off + 8 > len(data):
        raise ValueError("truncated seed")
    n, = struct.unpack_from("<Q", data, off)
    if n > 32 or off + 8 + n > len(data):     # MAX_SEED_LEN
        raise ValueError("seed too long")
    return data[off + 8:off + 8 + n], off + 8 + n


def _nonce_state(authority: bytes, durable: bytes,
                 fee_per_sig: int = 5000) -> bytes:
    return struct.pack("<II", 1, 1) + authority + durable         + struct.pack("<Q", fee_per_sig)


def _parse_nonce(data: bytes):
    if len(data) < NONCE_STATE_SZ:
        raise ValueError("short nonce state")
    ver, state = struct.unpack_from("<II", data, 0)
    if ver != 1 or state != 1:
        raise ValueError("nonce not initialized")
    return data[8:40], data[40:72]            # authority, durable

# status codes (fd_executor error flavor)
OK = "ok"
ERR_FEE = "fee_payer_insufficient"
ERR_PARSE = "parse_failed"
ERR_MISSING_SIG = "missing_required_signature"
ERR_NOT_WRITABLE = "account_not_writable"
ERR_INSUFFICIENT = "insufficient_funds"
ERR_ALREADY_IN_USE = "account_already_in_use"
ERR_INVALID_OWNER = "invalid_account_owner"
ERR_HAS_DATA = "account_has_data"
ERR_SPACE = "invalid_space"
ERR_UNKNOWN_IX = "unknown_instruction"
ERR_UNKNOWN_PROGRAM = "unknown_program"
ERR_BAD_IX_DATA = "bad_instruction_data"
ERR_VM = "program_failed"
ERR_BALANCE_VIOLATION = "sum_of_lamports_changed"
ERR_RENT = "insufficient_funds_for_rent"
ERR_CPI = "cpi_violation"
ERR_ALUT = "alut_resolution_failed"


class LogCollector(list):
    """Bounded program-log buffer (the reference's fd_log_collector:
    10KB budget, a single truncation marker once exceeded)."""

    MAX_BYTES = 10_000

    def __init__(self):
        super().__init__()
        self._bytes = 0
        self._truncated = False

    def append(self, line):
        if self._truncated:
            return
        n = len(line.encode()) if isinstance(line, str) else len(line)
        if self._bytes + n > self.MAX_BYTES:
            self._truncated = True
            super().append("Log truncated")
            return
        self._bytes += n
        super().append(line)

    def extend(self, lines):
        for ln in lines:
            self.append(ln)


@dataclass
class TxnResult:
    status: str
    fee: int
    logs: list
    return_data: bytes = b""


class TxnContext:
    """Per-txn view: copy-on-write accounts over one accdb fork."""

    def __init__(self, db: AccDb, xid, txn: ParsedTxn, payload: bytes,
                 epoch: int = 0, slot: int = 0, loaded_keys=(),
                 loaded_writable=()):
        self.db = db
        self.xid = xid
        self.txn = txn
        self.payload = payload
        self.epoch = epoch            # Clock-sysvar stand-in
        self.slot = slot
        # v0: table-loaded addresses extend the static list (writables
        # first — the resolv contract, svm/alut.py)
        self.keys = txn.account_keys(payload) + list(loaded_keys)
        self._loaded_writable = list(loaded_writable)
        self._work: dict[bytes, Account] = {}
        self._pre: dict[bytes, tuple] = {}   # (lamports, data_len) at load
        self.logs = LogCollector()
        self.last_exec_cu = 0        # CU used by the last BPF frame
        self.cu_limit = 200_000      # SetComputeUnitLimit applies here
        self.cu_used = 0             # shared meter across instructions
        self.heap_sz = 32 * 1024     # RequestHeapFrame applies here
        self.return_data = b""       # sol_set_return_data (txn-wide)
        self.return_data_program = bytes(32)

    def is_signer(self, idx: int) -> bool:
        return idx < self.txn.sig_cnt

    def is_writable(self, idx: int) -> bool:
        if idx >= self.txn.acct_cnt:
            return self._loaded_writable[idx - self.txn.acct_cnt]
        return self.txn.is_writable(idx)

    def account(self, idx: int) -> Account:
        k = self.keys[idx]
        if k not in self._work:
            a = self.db.peek(self.xid, k)
            self._work[k] = Account() if a is None else \
                Account(a.lamports, a.data, a.owner, a.executable,
                        a.rent_epoch)
            self._pre[k] = (0, 0) if a is None else \
                (a.lamports, len(a.data))
        return self._work[k]

    def rent_violation(self) -> bytes | None:
        """Post-execution rent-state check (modern consensus: rent is
        never collected, but every touched account must LEAVE the txn
        rent-exempt — ref src/flamenco/runtime/sysvar/fd_sysvar_rent.c
        minimum-balance discipline + Agave check_rent_state):
        an account passes when it is empty (0 lamports), meets the
        rent-exempt minimum for its data size, or was ALREADY
        rent-paying and did not grow (Agave's RentPaying->RentPaying
        transition: same data size, lamports non-increasing; an
        exempt account may never become rent-paying). Returns the
        first offending key, else None."""
        from .sysvars import rent_exempt_minimum
        for k, a in self._work.items():
            if a.lamports == 0:
                continue
            need = rent_exempt_minimum(len(a.data))
            if a.lamports >= need:
                continue
            pre_l, pre_len = self._pre.get(k, (0, 0))
            pre_paying = 0 < pre_l < rent_exempt_minimum(pre_len)
            if pre_paying and a.lamports <= pre_l \
                    and len(a.data) == pre_len:
                continue               # rent-paying shrank/held: legal
            return k
        return None

    def commit(self):
        for k, a in self._work.items():
            self.db.funk.rec_write(self.xid, key32(k), a)


class InstrCtx:
    """One instruction invocation: local account indices + privileges.

    privileges=None -> top-level (txn-message signer/writable bits);
    privileges=[(signer, writable)] -> a CPI frame with the flags the
    caller requested AND the runtime validated."""

    def __init__(self, ctx: TxnContext, program_id: bytes,
                 acct_idxs, data: bytes, privileges=None):
        self.ctx = ctx
        self.program_id = program_id
        self.acct_idxs = list(acct_idxs)
        self.data = data
        self.priv = privileges

    @property
    def n(self) -> int:
        return len(self.acct_idxs)

    def key(self, i: int) -> bytes:
        return self.ctx.keys[self.acct_idxs[i]]

    def account(self, i: int) -> Account:
        return self.ctx.account(self.acct_idxs[i])

    def is_signer(self, i: int) -> bool:
        if self.priv is not None:
            return self.priv[i][0]
        return self.ctx.is_signer(self.acct_idxs[i])

    def is_writable(self, i: int) -> bool:
        if self.priv is not None:
            return self.priv[i][1]
        return self.ctx.is_writable(self.acct_idxs[i])

    def signer_keys(self) -> set:
        if self.priv is not None:
            return {self.key(i) for i in range(self.n)
                    if self.priv[i][0]}
        return {self.ctx.keys[i] for i in range(self.ctx.txn.sig_cnt)}

    @property
    def logs(self):
        return self.ctx.logs


def _u64(data: bytes, off: int) -> int:
    return struct.unpack_from("<Q", data, off)[0]


def _exec_system(ic: InstrCtx) -> str:
    data = ic.data
    if len(data) < 4:
        return ERR_BAD_IX_DATA
    disc = struct.unpack_from("<I", data, 0)[0]

    if disc == SYS_TRANSFER:
        if len(data) < 12 or ic.n < 2:
            return ERR_BAD_IX_DATA
        amount = _u64(data, 4)
        if not ic.is_signer(0):
            return ERR_MISSING_SIG
        if not ic.is_writable(0) or not ic.is_writable(1):
            return ERR_NOT_WRITABLE
        src = ic.account(0)
        if src.owner != SYSTEM_PROGRAM_ID:
            # the system program may only debit accounts it owns — a
            # signer must not drain an account previously Assigned to
            # another program (ref fd_system_program_transfer_verified,
            # Agave ExternalAccountLamportSpend)
            return ERR_INVALID_OWNER
        if src.data:
            return ERR_HAS_DATA          # transfer-from must hold no data
        if amount > src.lamports:
            ic.logs.append(
                f"Transfer: insufficient lamports {src.lamports}, "
                f"need {amount}")
            return ERR_INSUFFICIENT
        src.lamports -= amount
        ic.account(1).lamports += amount
        return OK

    if disc == SYS_CREATE_ACCOUNT:
        if len(data) < 4 + 8 + 8 + 32 or ic.n < 2:
            return ERR_BAD_IX_DATA
        lamports = _u64(data, 4)
        space = _u64(data, 12)
        owner = data[20:52]
        if not ic.is_signer(0) or not ic.is_signer(1):
            return ERR_MISSING_SIG
        if not ic.is_writable(0) or not ic.is_writable(1):
            return ERR_NOT_WRITABLE
        to = ic.account(1)
        if to.lamports or to.data or to.owner != SYSTEM_PROGRAM_ID:
            ic.logs.append("Create Account: account already in use")
            return ERR_ALREADY_IN_USE
        if space > MAX_PERMITTED_DATA_LENGTH:
            return ERR_SPACE
        src = ic.account(0)
        if lamports > src.lamports:
            return ERR_INSUFFICIENT
        to.data = bytes(space)
        to.owner = owner
        src.lamports -= lamports
        to.lamports += lamports
        return OK

    if disc == SYS_ASSIGN:
        if len(data) < 36 or ic.n < 1:
            return ERR_BAD_IX_DATA
        if not ic.is_signer(0):
            return ERR_MISSING_SIG
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        acct = ic.account(0)
        if acct.owner != SYSTEM_PROGRAM_ID:
            return ERR_INVALID_OWNER
        acct.owner = data[4:36]
        return OK

    if disc == SYS_CREATE_WITH_SEED:
        # disc | base 32 | seed str | lamports u64 | space u64 | owner
        try:
            base = data[4:36]
            seed, off = _read_seed_str(data, 36)
            lamports, space = struct.unpack_from("<QQ", data, off)
            owner = data[off + 16:off + 48]
        except (ValueError, struct.error):
            return ERR_BAD_IX_DATA
        if ic.n < 2 or len(owner) != 32:
            return ERR_BAD_IX_DATA
        if ic.key(1) != create_with_seed(base, seed, owner):
            return ERR_INVALID_OWNER          # address mismatch
        # base must sign (it authorizes the derived address)
        if not ic.is_signer(0) or base not in ic.signer_keys():
            return ERR_MISSING_SIG
        if not ic.is_writable(0) or not ic.is_writable(1):
            return ERR_NOT_WRITABLE
        to = ic.account(1)
        if to.lamports or to.data or to.owner != SYSTEM_PROGRAM_ID:
            return ERR_ALREADY_IN_USE
        if space > MAX_PERMITTED_DATA_LENGTH:
            return ERR_SPACE
        src = ic.account(0)
        if src.owner != SYSTEM_PROGRAM_ID or src.data:
            return ERR_INVALID_OWNER
        if lamports > src.lamports:
            return ERR_INSUFFICIENT
        to.data = bytes(space)
        to.owner = owner
        src.lamports -= lamports
        to.lamports += lamports
        return OK

    if disc in (SYS_ALLOCATE_WITH_SEED, SYS_ASSIGN_WITH_SEED):
        try:
            base = data[4:36]
            seed, off = _read_seed_str(data, 36)
            if disc == SYS_ALLOCATE_WITH_SEED:
                space, = struct.unpack_from("<Q", data, off)
                owner = data[off + 8:off + 40]
            else:
                space = None
                owner = data[off:off + 32]
        except (ValueError, struct.error):
            return ERR_BAD_IX_DATA
        if ic.n < 1 or len(owner) != 32:
            return ERR_BAD_IX_DATA
        if ic.key(0) != create_with_seed(base, seed, owner):
            return ERR_INVALID_OWNER
        if base not in ic.signer_keys():
            return ERR_MISSING_SIG
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        acct = ic.account(0)
        if acct.owner != SYSTEM_PROGRAM_ID:
            return ERR_INVALID_OWNER
        if disc == SYS_ALLOCATE_WITH_SEED:
            if acct.data:
                return ERR_HAS_DATA
            if space > MAX_PERMITTED_DATA_LENGTH:
                return ERR_SPACE
            acct.data = bytes(space)
        acct.owner = owner
        return OK

    if disc == SYS_TRANSFER_WITH_SEED:
        # disc | lamports u64 | from_seed str | from_owner 32;
        # accounts [from(derived), base(signer), to]
        try:
            amount, = struct.unpack_from("<Q", data, 4)
            seed, off = _read_seed_str(data, 12)
            from_owner = data[off:off + 32]
        except (ValueError, struct.error):
            return ERR_BAD_IX_DATA
        if ic.n < 3 or len(from_owner) != 32:
            return ERR_BAD_IX_DATA
        if ic.key(0) != create_with_seed(ic.key(1), seed, from_owner):
            return ERR_INVALID_OWNER
        if not ic.is_signer(1):
            return ERR_MISSING_SIG
        if not ic.is_writable(0) or not ic.is_writable(2):
            return ERR_NOT_WRITABLE
        src = ic.account(0)
        if src.owner != SYSTEM_PROGRAM_ID or src.data:
            return ERR_INVALID_OWNER
        if amount > src.lamports:
            return ERR_INSUFFICIENT
        src.lamports -= amount
        ic.account(2).lamports += amount
        return OK

    if disc == SYS_INIT_NONCE:
        if len(data) < 36 or ic.n < 1:
            return ERR_BAD_IX_DATA
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        acct = ic.account(0)
        # the account must be PRE-ALLOCATED to exactly the nonce size
        # (Agave's guard: allocation required the account's signature
        # at CreateAccount time — without it, init+withdraw would
        # drain any writable wallet that never signed)
        if acct.owner != SYSTEM_PROGRAM_ID \
                or len(acct.data) != NONCE_STATE_SZ \
                or any(acct.data[:8]):
            return ERR_INVALID_OWNER
        durable = hashlib.sha256(
            b"DURABLE_NONCE" + ic.key(0)
            + ic.ctx.slot.to_bytes(8, "little")).digest()
        acct.data = _nonce_state(data[4:36], durable)
        return OK

    if disc in (SYS_ADVANCE_NONCE, SYS_AUTHORIZE_NONCE):
        if ic.n < 1:
            return ERR_BAD_IX_DATA
        acct = ic.account(0)
        if acct.owner != SYSTEM_PROGRAM_ID:
            return ERR_INVALID_OWNER
        try:
            authority, durable = _parse_nonce(acct.data)
        except ValueError:
            return ERR_INVALID_OWNER
        if authority not in ic.signer_keys():
            return ERR_MISSING_SIG
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        if disc == SYS_ADVANCE_NONCE:
            # derived from (key, slot) — the SAME formula as init, so
            # advancing twice in one slot yields an unchanged value
            # and FAILS (Agave: advance on an unmoved blockhash fails)
            new = hashlib.sha256(
                b"DURABLE_NONCE" + ic.key(0)
                + ic.ctx.slot.to_bytes(8, "little")).digest()
            if new == durable:
                return ERR_BAD_IX_DATA        # nonce must move
            acct.data = _nonce_state(authority, new)
        else:
            if len(data) < 36:
                return ERR_BAD_IX_DATA
            acct.data = _nonce_state(data[4:36], durable)
        return OK

    if disc == SYS_WITHDRAW_NONCE:
        if len(data) < 12 or ic.n < 2:
            return ERR_BAD_IX_DATA
        lamports = _u64(data, 4)
        acct = ic.account(0)
        if acct.owner != SYSTEM_PROGRAM_ID:
            return ERR_INVALID_OWNER
        try:
            authority, _durable = _parse_nonce(acct.data)
        except ValueError:
            # UNINITIALIZED nonce-sized account: recoverable by the
            # account's own signature (Agave's uninitialized-withdraw
            # path — otherwise allocated-but-never-initialized funds
            # would be stuck: Transfer refuses data-bearing sources)
            if len(acct.data) == NONCE_STATE_SZ \
                    and not any(acct.data) \
                    and ic.key(0) in ic.signer_keys():
                authority = ic.key(0)
            else:
                return ERR_INVALID_OWNER
        if authority not in ic.signer_keys():
            return ERR_MISSING_SIG
        if not ic.is_writable(0) or not ic.is_writable(1):
            return ERR_NOT_WRITABLE
        if lamports > acct.lamports:
            return ERR_INSUFFICIENT
        if lamports != acct.lamports:
            # partial withdraw must leave the rent-exempt reserve
            # (Agave nonce withdraw: lamports + min_balance must fit;
            # a FULL withdraw closes the account instead)
            from .sysvars import rent_exempt_minimum
            if lamports + rent_exempt_minimum(NONCE_STATE_SZ) \
                    > acct.lamports:
                return ERR_INSUFFICIENT
        acct.lamports -= lamports
        ic.account(1).lamports += lamports
        if acct.lamports == 0:
            acct.data = b""               # full withdraw closes
        return OK

    if disc == SYS_ALLOCATE:
        if len(data) < 12 or ic.n < 1:
            return ERR_BAD_IX_DATA
        space = _u64(data, 4)
        if not ic.is_signer(0):
            return ERR_MISSING_SIG
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        acct = ic.account(0)
        if acct.owner != SYSTEM_PROGRAM_ID:
            return ERR_INVALID_OWNER
        if acct.data:
            return ERR_HAS_DATA
        if space > MAX_PERMITTED_DATA_LENGTH:
            return ERR_SPACE
        acct.data = bytes(space)
        return OK

    return ERR_UNKNOWN_IX


# ---------------------------------------------------------------------------
# program-derived addresses (ref: fd_vm_syscall_pda.c / Agave
# Pubkey::create_program_address)
# ---------------------------------------------------------------------------

PDA_MARKER = b"ProgramDerivedAddress"


def create_program_address(seeds: list[bytes],
                           program_id: bytes) -> bytes | None:
    """sha256(seeds .. program_id .. marker); None if the result lies
    ON the ed25519 curve (a PDA must have no private key)."""
    from ..utils.ed25519_ref import pt_decompress
    if len(seeds) > MAX_SEEDS or any(len(s) > MAX_SEED_LEN
                                     for s in seeds):
        return None
    h = hashlib.sha256()
    for s in seeds:
        h.update(s)
    h.update(program_id)
    h.update(PDA_MARKER)
    addr = h.digest()
    if pt_decompress(addr) is not None:
        return None                   # on-curve: invalid PDA
    return addr


def find_program_address(seeds: list[bytes],
                         program_id: bytes) -> tuple[bytes, int]:
    for bump in range(255, -1, -1):
        addr = create_program_address(seeds + [bytes([bump])],
                                      program_id)
        if addr is not None:
            return addr, bump
    raise ValueError("no viable bump seed")


# ---------------------------------------------------------------------------
# sBPF execution + CPI
# ---------------------------------------------------------------------------

def _build_input(ic: InstrCtx) -> tuple[bytes, list[int]]:
    """Compact input layout (raw-text fixture programs): u16 n_accounts
    | n x (pubkey 32 | lamports u64 | is_signer u8 | is_writable u8) |
    u16 data_len | instruction data (the input-region discipline of
    src/flamenco/vm/fd_vm.h, compact layout documented in
    vm/interp.py). Returns (blob, per-account lamports offsets)."""
    blob = struct.pack("<H", ic.n)
    offs = []
    for i in range(ic.n):
        offs.append(len(blob) + 32)
        blob += (ic.key(i)
                 + struct.pack("<Q", ic.account(i).lamports)
                 + bytes([1 if ic.is_signer(i) else 0,
                          1 if ic.is_writable(i) else 0]))
    blob += struct.pack("<H", len(ic.data)) + ic.data
    return blob, offs


MAX_PERMITTED_DATA_INCREASE = 10 * 1024


def _build_input_solana(ic: InstrCtx) -> tuple[bytes, list[int]]:
    """The real Solana aligned input serialization, for ELF programs
    built with the SDK entrypoint (ref: the reference's account
    serialization into the VM input region, src/flamenco/runtime/
    fd_runtime serialize + Agave serialize_parameters_aligned):

      u64 n | per account: u8 dup(0xff) | u8 signer | u8 writable |
      u8 executable | 4B pad | pubkey 32 | owner 32 | u64 lamports |
      u64 data_len | data | 10KiB spare | pad to 8 | u64 rent_epoch
      | u64 instr data len | instr data | program_id 32

    Duplicate account entries serialize as u8 index + 7B pad."""
    blob = bytearray(struct.pack("<Q", ic.n))
    offs: list[int] = []
    seen: dict[bytes, int] = {}
    for i in range(ic.n):
        key = ic.key(i)
        if key in seen:
            offs.append(offs[seen[key]])
            blob += bytes([seen[key]]) + bytes(7)
            continue
        seen[key] = i
        a = ic.account(i)
        blob += bytes([0xFF, 1 if ic.is_signer(i) else 0,
                       1 if ic.is_writable(i) else 0,
                       1 if a.executable else 0]) + bytes(4)
        blob += key + a.owner
        offs.append(len(blob))
        blob += struct.pack("<QQ", a.lamports, len(a.data))
        blob += a.data
        blob += bytes(MAX_PERMITTED_DATA_INCREASE)
        pad = (-len(blob)) % 8
        blob += bytes(pad)
        blob += struct.pack("<Q", a.rent_epoch)
    blob += struct.pack("<Q", len(ic.data)) + ic.data
    blob += ic.program_id
    return bytes(blob), offs


def _refresh_input_lamports(vm, ic: InstrCtx):
    """Rewrite the VM input region's lamport slots from the current
    account state (after a CPI mutated them). NOTE a documented
    divergence from the reference: direct lamport stores made by the
    caller BEFORE a CPI are overwritten by this refresh — combine
    direct writes with CPI by re-applying them after the call."""
    for i, off in enumerate(vm._lam_offsets):
        vm.mem_write(0x4_0000_0000 + off,
                     struct.pack("<Q", ic.account(i).lamports))


def _parse_cpi_instruction(vm, vaddr):
    """Our compact CPI ABI (documented; the reference marshals the
    Rust/C AccountInfo layouts, fd_vm_syscall_cpi.c — same contract,
    different wire): program_id 32 | u16 n | n x (pubkey 32 |
    u8 signer | u8 writable) | u16 dlen | data."""
    program_id = vm.mem_read(vaddr, 32)
    n, = struct.unpack("<H", vm.mem_read(vaddr + 32, 2))
    if n > 64:
        raise ValueError("too many CPI accounts")
    metas = []
    off = vaddr + 34
    for _ in range(n):
        pk = vm.mem_read(off, 32)
        flags = vm.mem_read(off + 32, 2)
        metas.append((pk, bool(flags[0]), bool(flags[1])))
        off += 34
    dlen, = struct.unpack("<H", vm.mem_read(off, 2))
    data = vm.mem_read(off + 2, dlen)
    return program_id, metas, data


def _parse_signer_seeds(vm, vaddr):
    """u8 n_signers | per signer: u8 n_seeds | n x (u8 len | bytes)."""
    if not vaddr:
        return []
    n_signers = vm.mem_read(vaddr, 1)[0]
    if n_signers > MAX_SEEDS:
        raise ValueError("too many CPI signers")
    out = []
    off = vaddr + 1
    for _ in range(n_signers):
        n_seeds = vm.mem_read(off, 1)[0]
        off += 1
        if n_seeds > MAX_SEEDS:
            raise ValueError("too many seeds")
        seeds = []
        for _ in range(n_seeds):
            ln = vm.mem_read(off, 1)[0]
            seeds.append(vm.mem_read(off + 1, ln))
            off += 1 + ln
        out.append(seeds)
    return out


def _make_cpi_syscalls(ctx: TxnContext, ic: InstrCtx, depth: int):
    """Bind invoke_signed + PDA syscalls to this instruction frame
    (ref: src/flamenco/vm/syscall/fd_vm_syscall_cpi.c:1-40,
    fd_vm_syscall_pda.c)."""
    from ..vm.interp import ERR_ABORT, VmFault
    from ..vm.syscalls import CU_SYSCALL_BASE

    def sys_invoke_signed(vm, r1, r2, r3, r4, r5):
        vm.charge(CU_SYSCALL_BASE * 10)
        if depth + 1 >= MAX_CPI_DEPTH:
            raise VmFault(ERR_ABORT, "max CPI depth")
        # the invocation stack shares ONE budget: the child runs on the
        # caller's remaining CU and its usage is charged back (the
        # reference's shared compute meter)
        remaining = vm.compute_budget - vm._cu
        try:
            program_id, metas, data = _parse_cpi_instruction(vm, r1)
            signer_seeds = _parse_signer_seeds(vm, r2)
        except Exception as e:
            raise VmFault(ERR_ABORT, f"bad CPI instruction: {e}")
        pda_signers = set()
        for seeds in signer_seeds:
            addr = create_program_address(list(seeds), ic.program_id)
            if addr is None:
                raise VmFault(ERR_ABORT, "invalid PDA seeds")
            pda_signers.add(addr)
        # accounts must already be in the txn, and privileges must not
        # escalate beyond the caller's view (PDA seeds excepted)
        outer = {ic.key(i): i for i in range(ic.n)}
        idxs, privs = [], []
        for pk, want_sign, want_write in metas:
            oi = outer.get(pk)
            if oi is None:
                raise VmFault(ERR_ABORT,
                              "CPI account not in caller accounts")
            if want_sign and not ic.is_signer(oi) \
                    and pk not in pda_signers:
                raise VmFault(ERR_ABORT, "CPI signer escalation")
            if want_write and not ic.is_writable(oi):
                raise VmFault(ERR_ABORT, "CPI writable escalation")
            idxs.append(ic.acct_idxs[oi])
            privs.append((want_sign, want_write))
        child = InstrCtx(ctx, bytes(program_id), idxs, bytes(data),
                         privileges=privs)
        ctx.last_exec_cu = 0
        st = dispatch_instr(ctx, child, depth + 1, budget=remaining)
        if st != OK:
            raise VmFault(ERR_ABORT, f"CPI failed: {st}")
        vm.charge(ctx.last_exec_cu)
        # the callee's return data becomes visible to the caller's
        # sol_get_return_data (the CPI-result ABI)
        vm.return_data = ctx.return_data
        vm.return_data_program = ctx.return_data_program
        _refresh_input_lamports(vm, ic)
        return 0

    def sys_create_pda(vm, r1, r2, r3, r4, r5):
        vm.charge(CU_SYSCALL_BASE * 15)
        if r2 > MAX_SEEDS:
            return 1                  # MaxSeedLengthExceeded, not trunc
        seeds = [vm.mem_read(vm.read_u(r1 + 16 * i, 8),
                             vm.read_u(r1 + 16 * i + 8, 8))
                 for i in range(r2)]
        program_id = vm.mem_read(r3, 32)
        addr = create_program_address(seeds, program_id)
        if addr is None:
            return 1
        vm.mem_write(r4, addr)
        return 0

    def sys_find_pda(vm, r1, r2, r3, r4, r5):
        vm.charge(CU_SYSCALL_BASE * 15)
        if r2 > MAX_SEEDS:
            return 1
        seeds = [vm.mem_read(vm.read_u(r1 + 16 * i, 8),
                             vm.read_u(r1 + 16 * i + 8, 8))
                 for i in range(r2)]
        program_id = vm.mem_read(r3, 32)
        try:
            addr, bump = find_program_address(seeds, program_id)
        except ValueError:
            return 1
        vm.mem_write(r4, addr)
        vm.mem_write(r5, bytes([bump]))
        return 0

    from ..vm.syscalls import syscall_id
    return {
        syscall_id(b"sol_invoke_signed_c"): sys_invoke_signed,
        syscall_id(b"sol_invoke_signed_rust"): sys_invoke_signed,
        syscall_id(b"sol_create_program_address"): sys_create_pda,
        syscall_id(b"sol_try_find_program_address"): sys_find_pda,
    }


_PROG_CACHE: dict[bytes, "object"] = {}     # sha256(elf) -> SbpfProgram
_PROG_CACHE_MAX = 64


def _load_elf_cached(data: bytes):
    """Loaded-program cache (the reference's progcache role): keyed by
    content hash so redeployments miss cleanly; bounded FIFO."""
    from ..vm import elf
    key = hashlib.sha256(data).digest()
    prog = _PROG_CACHE.get(key)
    if prog is None:
        prog = elf.load(data)
        while len(_PROG_CACHE) >= _PROG_CACHE_MAX:
            _PROG_CACHE.pop(next(iter(_PROG_CACHE)))
        _PROG_CACHE[key] = prog
    return prog


def _exec_bpf(ctx: TxnContext, ic: InstrCtx, program: Account,
              depth: int = 0, budget: int | None = None) -> str:
    """Run a deployed sBPF program (executable account owned by the
    loader) in the VM. ELF-packaged programs (magic 0x7f 'ELF') go
    through the loader (vm/elf.py — parse, relocate, call registry,
    ref src/ballet/sbpf/fd_sbpf_loader.h:1-12); raw text sections
    execute directly (the pre-ELF deployment path, kept for fixtures).

    After a successful run, lamports of WRITABLE accounts are read back
    under two runtime rules: sum-of-lamports conservation (never mint
    or burn), and the OWNERSHIP rule — only the executing program may
    DEBIT an account, and only if that account is owned by it
    (credits are unrestricted), mirroring the reference runtime's
    account-modification checks."""
    from ..vm import DEFAULT_SYSCALLS, ERR_NONE as VM_OK, Vm
    syscalls = dict(DEFAULT_SYSCALLS)
    syscalls.update(_make_cpi_syscalls(ctx, ic, depth))
    if budget is None:
        # top-level frame: the txn's shared meter (requested limit
        # minus CU already burned by earlier instructions)
        budget = max(0, ctx.cu_limit - ctx.cu_used)
    kw = {"compute_budget": budget, "heap_sz": ctx.heap_sz}
    # sysvars the VM exposes via get_*_sysvar syscalls (the reference's
    # fd_sysvar_cache): account bytes when the bank materialized them
    # (svm/sysvars.py), synthesized from slot/epoch otherwise — the
    # account view and the syscall view must agree byte-for-byte
    from .sysvars import read_sysvar_cache
    sysvars = read_sysvar_cache(ctx.db, ctx.xid, ctx.slot, ctx.epoch)
    if program.data[:4] == b"\x7fELF":
        from ..vm import elf
        try:
            prog = _load_elf_cached(program.data)
        except elf.ElfError as e:
            ctx.logs.append(f"ELF load failed: {e}")
            return ERR_VM
        # SDK-built programs deserialize the REAL Solana input ABI
        blob, lam_offs = _build_input_solana(ic)
        vm = Vm(prog.text, input_data=blob, syscalls=syscalls,
                image=prog.image, text_off=prog.text_off,
                calls=prog.calls, **kw)
        vm._lam_offsets = lam_offs
        vm.sysvars = sysvars
        vm.program_id = ic.program_id
        vm.return_data = ctx.return_data
        vm.return_data_program = ctx.return_data_program
        res = vm.run(entry_pc=prog.entry_pc)
    else:
        blob, lam_offs = _build_input(ic)
        vm = Vm(program.data, input_data=blob, syscalls=syscalls, **kw)
        vm._lam_offsets = lam_offs
        vm.sysvars = sysvars
        vm.program_id = ic.program_id
        vm.return_data = ctx.return_data
        vm.return_data_program = ctx.return_data_program
        res = vm.run()
    ctx.logs.extend(res.log)
    ctx.last_exec_cu = res.compute_used
    if depth == 0:
        ctx.cu_used += res.compute_used
    ctx.return_data = getattr(vm, "return_data", b"")
    ctx.return_data_program = getattr(vm, "return_data_program",
                                      bytes(32))
    if res.error != VM_OK or res.r0 != 0:
        return ERR_VM
    # lamports write-back with conservation over UNIQUE accounts: an
    # instruction may list the same account at several indices (the
    # runtime maps them to ONE account), so both the before-sum and the
    # applied value dedup by key with last-slot-wins — otherwise a
    # duplicated index could double-count `before` and mint the
    # difference
    final: dict[bytes, tuple[int, int]] = {}     # key -> (local_i, lam)
    for i, off in enumerate(vm._lam_offsets):
        lam = int.from_bytes(vm.mem_read(
            0x4_0000_0000 + off, 8), "little")
        final[ic.key(i)] = (i, lam)
    uniq = {ic.key(i): ic.account(i) for i in range(ic.n)}
    before = sum(a.lamports for a in uniq.values())
    if sum(lam for _, lam in final.values()) != before:
        return ERR_BALANCE_VIOLATION
    for key, (i, lam) in final.items():
        a = uniq[key]
        if lam != a.lamports:
            if not ic.is_writable(i):
                return ERR_NOT_WRITABLE
            if lam < a.lamports and a.owner != ic.program_id:
                # a program may only DEBIT accounts it owns — txn-level
                # writability alone must not let an arbitrary deployed
                # program drain a victim's account
                return ERR_INVALID_OWNER
            a.lamports = lam
    return OK


def dispatch_instr(ctx: TxnContext, ic: InstrCtx, depth: int = 0,
                   budget: int | None = None) -> str:
    """Route one instruction frame to its program (the fd_executor
    native-program dispatch switch + BPF fallback)."""
    from ..pack.cost import BPF_UPGRADEABLE_LOADER_ID
    from .alut import ALUT_PROGRAM_ID, exec_alut
    from .loader import exec_upgradeable_loader, resolve_program_elf
    from .precompiles import (
        ED25519_PROGRAM_ID, SECP256K1_PROGRAM_ID, SECP256R1_PROGRAM_ID,
        exec_ed25519_precompile, exec_secp256k1_precompile,
        exec_secp256r1_precompile,
    )
    from .stake import STAKE_PROGRAM_ID, exec_stake
    from .vote import VOTE_PROGRAM_ID, exec_vote
    pid = ic.program_id
    if pid == SYSTEM_PROGRAM_ID:
        return _exec_system(ic)
    if pid == VOTE_PROGRAM_ID:
        return exec_vote(ic)
    if pid == STAKE_PROGRAM_ID:
        return exec_stake(ic)
    if pid == ALUT_PROGRAM_ID:
        return exec_alut(ic)
    if pid == ED25519_PROGRAM_ID:
        return exec_ed25519_precompile(ic)
    if pid == SECP256K1_PROGRAM_ID:
        return exec_secp256k1_precompile(ic)
    if pid == SECP256R1_PROGRAM_ID:
        return exec_secp256r1_precompile(ic)
    if pid == BPF_UPGRADEABLE_LOADER_ID:
        return exec_upgradeable_loader(ic)
    if pid == COMPUTE_BUDGET_PROGRAM_ID:
        return OK                    # requests pre-scanned by execute()
    pa = ctx.db.peek(ctx.xid, pid)
    if pa is not None and pa.executable:
        if pa.owner == BPF_LOADER_ID:
            return _exec_bpf(ctx, ic, pa, depth, budget=budget)
        if pa.owner == BPF_UPGRADEABLE_LOADER_ID:
            # loader-v3 indirection: program -> programdata -> ELF
            elf_bytes = resolve_program_elf(ctx.db, ctx.xid, pa)
            if elf_bytes is None:
                return ERR_UNKNOWN_PROGRAM
            shim = Account(pa.lamports, bytes(elf_bytes), pa.owner,
                           True, pa.rent_epoch)
            return _exec_bpf(ctx, ic, shim, depth, budget=budget)
    return ERR_UNKNOWN_PROGRAM


class TxnExecutor:
    """fd_runtime_prepare_and_execute_txn analog for the host path."""

    def __init__(self, db: AccDb, fee_per_signature: int = 5000,
                 enforce_rent: bool = True):
        self.db = db
        self.fee_per_signature = fee_per_signature
        self.enforce_rent = enforce_rent
        self.epoch = 0               # advanced by the bank at boundaries
        self.slot = 0

    def begin_slot(self, xid, slot: int, epoch: int | None = None,
                   slots_per_epoch: int = 432_000,
                   blockhash: bytes | None = None):
        """Slot-boundary duty (ref: fd_runtime block-prepare calling
        the fd_sysvar_*_update family): advance the executor's clock
        view and materialize the sysvar ACCOUNTS in this fork so
        programs reading them as instruction accounts and via syscalls
        see identical bytes."""
        from .sysvars import update_sysvars
        self.slot = slot
        self.epoch = slot // slots_per_epoch if epoch is None else epoch
        update_sysvars(self.db, xid, slot, self.epoch,
                       slots_per_epoch=slots_per_epoch,
                       blockhash=blockhash,
                       lamports_per_sig=self.fee_per_signature)

    def execute(self, xid, payload: bytes) -> TxnResult:
        try:
            txn = parse_txn(payload)
        except Exception:
            return TxnResult(ERR_PARSE, 0, [])
        keys = txn.account_keys(payload)
        fee = self.fee_per_signature * txn.sig_cnt

        # fee payer: signer 0, charged even when execution fails
        # (the reference commits fees before dispatch)
        payer = self.db.open_rw(xid, keys[0], do_create=True)
        if payer.account.lamports < fee:
            self.db.close_rw(payer, discard=True)
            return TxnResult(ERR_FEE, 0, [])
        # rent-state baseline is the PRE-FEE payer; the fee itself may
        # not push an exempt payer into rent-paying (Agave
        # validate_fee_payer rejects at LOAD: no fee charged, no state
        # committed)
        payer_pre = (payer.account.lamports, len(payer.account.data))
        if self.enforce_rent:
            from .sysvars import rent_exempt_minimum
            post = payer_pre[0] - fee
            need = rent_exempt_minimum(payer_pre[1])
            pre_paying = payer_pre[0] < need
            if post != 0 and post < need and not pre_paying:
                self.db.close_rw(payer, discard=True)
                return TxnResult(ERR_RENT, 0, [])
        payer.account.lamports -= fee
        self.db.close_rw(payer)

        loaded_keys, loaded_writable = (), ()
        if txn.version == 0 and txn.aluts:
            from .alut import AlutResolveError, resolve_loaded_keys
            try:
                loaded_keys, loaded_writable = resolve_loaded_keys(
                    self.db, xid, txn, slot=self.slot)
            except AlutResolveError:
                return TxnResult(ERR_ALUT, fee, [])
        ctx = TxnContext(self.db, xid, txn, payload, epoch=self.epoch,
                         slot=self.slot, loaded_keys=loaded_keys,
                         loaded_writable=loaded_writable)
        if self.enforce_rent:
            # force the payer into the working set under its pre-fee
            # baseline so the rent-state check always covers it
            ctx.account(0)
            ctx._pre[keys[0]] = payer_pre
        keys = ctx.keys                # static + table-loaded
        total = len(keys)
        # pre-scan ComputeBudget requests (the reference resolves the
        # whole budget before dispatch, fd_compute_budget_program.h)
        from ..pack.cost import ComputeBudgetState, CostError
        cb = ComputeBudgetState()
        for instr in txn.instrs:
            if instr.prog_idx < len(keys) \
                    and keys[instr.prog_idx] == COMPUTE_BUDGET_PROGRAM_ID:
                data = payload[instr.data_off:
                               instr.data_off + instr.data_sz]
                try:
                    cb.parse_instr(data)
                except CostError:
                    return TxnResult(ERR_BAD_IX_DATA, fee, [])
        if cb.set_cu:
            ctx.cu_limit = cb.compute_units
        if cb.set_heap:
            ctx.heap_sz = cb.heap_size
        for instr in txn.instrs:
            # v0 defers the index bound to post-resolution
            if instr.prog_idx >= total or \
                    any(i >= total for i in instr.acct_idxs):
                return TxnResult(ERR_PARSE, fee, ctx.logs)
            data = payload[instr.data_off:instr.data_off + instr.data_sz]
            ic = InstrCtx(ctx, keys[instr.prog_idx],
                          list(instr.acct_idxs), data)
            st = dispatch_instr(ctx, ic)
            if st != OK:
                # atomic rollback: drop the working set (fee stays)
                return TxnResult(st, fee, ctx.logs)
        if self.enforce_rent and ctx.rent_violation() is not None:
            return TxnResult(ERR_RENT, fee, ctx.logs)
        ctx.commit()
        return TxnResult(OK, fee, ctx.logs, ctx.return_data)
