"""Host-side transaction executor + native system program.

The reference's per-txn execution (load accounts, charge fees, dispatch
instructions sequentially through native program handlers, commit or
roll back atomically) lives in fd_executor/fd_system_program
(ref: src/flamenco/runtime/fd_executor.c, fd_runtime.h:254-266,
program/fd_system_program.c:59-330). The wave executor (executor.py)
covers the batched pure-transfer fast path on device; THIS module is
the general host path the exec tiles run for everything else — the
split SURVEY §7 hard-part 6 prescribes (sBPF and general dispatch stay
on host cores).

Semantics mirrored from the reference per instruction:
  Transfer        from must SIGN and be system-owned with no data;
                  insufficient lamports aborts the txn
                  (fd_system_program.c:59-137)
  CreateAccount   to must SIGN, be empty (0 lamports, no data, system
                  owner); allocate+assign+fund (:254-330)
  Assign          account must SIGN, be system-owned (:202-230)
  Allocate        account must SIGN, be system-owned, data empty;
                  space <= MAX_PERMITTED_DATA_LENGTH (:143-200)

A failing instruction rolls the whole transaction back; the fee is
charged to the payer regardless (the reference commits fees before
execution). Every touched account goes through accdb rw handles, so
rollback is just dropping them (accdb.close_rw(discard=True)).
"""
from __future__ import annotations

import struct
from dataclasses import dataclass

from ..protocol.txn import ParsedTxn, parse_txn
from .accdb import AccDb, Account, SYSTEM_PROGRAM_ID

COMPUTE_BUDGET_PROGRAM_ID = b"ComputeBudget" + bytes(19)
BPF_LOADER_ID = b"BPFLoader" + bytes(23)
MAX_PERMITTED_DATA_LENGTH = 10 * 1024 * 1024

# system instruction discriminants (u32 LE bincode)
SYS_CREATE_ACCOUNT = 0
SYS_ASSIGN = 1
SYS_TRANSFER = 2
SYS_ALLOCATE = 8

# status codes (fd_executor error flavor)
OK = "ok"
ERR_FEE = "fee_payer_insufficient"
ERR_PARSE = "parse_failed"
ERR_MISSING_SIG = "missing_required_signature"
ERR_NOT_WRITABLE = "account_not_writable"
ERR_INSUFFICIENT = "insufficient_funds"
ERR_ALREADY_IN_USE = "account_already_in_use"
ERR_INVALID_OWNER = "invalid_account_owner"
ERR_HAS_DATA = "account_has_data"
ERR_SPACE = "invalid_space"
ERR_UNKNOWN_IX = "unknown_instruction"
ERR_UNKNOWN_PROGRAM = "unknown_program"
ERR_BAD_IX_DATA = "bad_instruction_data"
ERR_VM = "program_failed"
ERR_BALANCE_VIOLATION = "sum_of_lamports_changed"


@dataclass
class TxnResult:
    status: str
    fee: int
    logs: list


class TxnContext:
    """Per-txn view: copy-on-write accounts over one accdb fork."""

    def __init__(self, db: AccDb, xid, txn: ParsedTxn, payload: bytes):
        self.db = db
        self.xid = xid
        self.txn = txn
        self.payload = payload
        self.keys = txn.account_keys(payload)
        self._work: dict[bytes, Account] = {}
        self.logs: list[str] = []

    def is_signer(self, idx: int) -> bool:
        return idx < self.txn.sig_cnt

    def is_writable(self, idx: int) -> bool:
        return self.txn.is_writable(idx)

    def account(self, idx: int) -> Account:
        k = self.keys[idx]
        if k not in self._work:
            a = self.db.peek(self.xid, k)
            self._work[k] = Account() if a is None else \
                Account(a.lamports, a.data, a.owner, a.executable,
                        a.rent_epoch)
        return self._work[k]

    def commit(self):
        for k, a in self._work.items():
            self.db.funk.rec_write(self.xid, k, a)


def _u64(data: bytes, off: int) -> int:
    return struct.unpack_from("<Q", data, off)[0]


def _exec_system(ctx: TxnContext, instr) -> str:
    data = ctx.payload[instr.data_off:instr.data_off + instr.data_sz]
    if len(data) < 4:
        return ERR_BAD_IX_DATA
    disc = struct.unpack_from("<I", data, 0)[0]
    ai = instr.acct_idxs

    if disc == SYS_TRANSFER:
        if len(data) < 12 or len(ai) < 2:
            return ERR_BAD_IX_DATA
        amount = _u64(data, 4)
        f, t = ai[0], ai[1]
        if not ctx.is_signer(f):
            return ERR_MISSING_SIG
        if not ctx.is_writable(f) or not ctx.is_writable(t):
            return ERR_NOT_WRITABLE
        src = ctx.account(f)
        if src.owner != SYSTEM_PROGRAM_ID:
            # the system program may only debit accounts it owns — a
            # signer must not drain an account previously Assigned to
            # another program (ref fd_system_program_transfer_verified,
            # Agave ExternalAccountLamportSpend)
            return ERR_INVALID_OWNER
        if src.data:
            return ERR_HAS_DATA          # transfer-from must hold no data
        if amount > src.lamports:
            ctx.logs.append(
                f"Transfer: insufficient lamports {src.lamports}, "
                f"need {amount}")
            return ERR_INSUFFICIENT
        src.lamports -= amount
        ctx.account(t).lamports += amount
        return OK

    if disc == SYS_CREATE_ACCOUNT:
        if len(data) < 4 + 8 + 8 + 32 or len(ai) < 2:
            return ERR_BAD_IX_DATA
        lamports = _u64(data, 4)
        space = _u64(data, 12)
        owner = data[20:52]
        f, t = ai[0], ai[1]
        if not ctx.is_signer(f) or not ctx.is_signer(t):
            return ERR_MISSING_SIG
        if not ctx.is_writable(f) or not ctx.is_writable(t):
            return ERR_NOT_WRITABLE
        to = ctx.account(t)
        if to.lamports or to.data or to.owner != SYSTEM_PROGRAM_ID:
            ctx.logs.append("Create Account: account already in use")
            return ERR_ALREADY_IN_USE
        if space > MAX_PERMITTED_DATA_LENGTH:
            return ERR_SPACE
        src = ctx.account(f)
        if lamports > src.lamports:
            return ERR_INSUFFICIENT
        to.data = bytes(space)
        to.owner = owner
        src.lamports -= lamports
        to.lamports += lamports
        return OK

    if disc == SYS_ASSIGN:
        if len(data) < 36 or len(ai) < 1:
            return ERR_BAD_IX_DATA
        a = ai[0]
        if not ctx.is_signer(a):
            return ERR_MISSING_SIG
        if not ctx.is_writable(a):
            return ERR_NOT_WRITABLE
        acct = ctx.account(a)
        if acct.owner != SYSTEM_PROGRAM_ID:
            return ERR_INVALID_OWNER
        acct.owner = data[4:36]
        return OK

    if disc == SYS_ALLOCATE:
        if len(data) < 12 or len(ai) < 1:
            return ERR_BAD_IX_DATA
        space = _u64(data, 4)
        a = ai[0]
        if not ctx.is_signer(a):
            return ERR_MISSING_SIG
        if not ctx.is_writable(a):
            return ERR_NOT_WRITABLE
        acct = ctx.account(a)
        if acct.owner != SYSTEM_PROGRAM_ID:
            return ERR_INVALID_OWNER
        if acct.data:
            return ERR_HAS_DATA
        if space > MAX_PERMITTED_DATA_LENGTH:
            return ERR_SPACE
        acct.data = bytes(space)
        return OK

    return ERR_UNKNOWN_IX


def _exec_bpf(ctx: TxnContext, instr, program: Account) -> str:
    """Run a deployed sBPF program (executable account owned by the
    loader) in the VM (ref: fd_executor -> fd_vm_exec; serialization
    per the input-region discipline of src/flamenco/vm/fd_vm.h input
    regions, compact layout documented in vm/interp.py).

    Input layout: u16 n_accounts | n × (pubkey 32 | lamports u64 |
    is_signer u8 | is_writable u8) | u16 data_len | instruction data.
    After a successful run, lamports of WRITABLE accounts are read back
    under two runtime rules: sum-of-lamports conservation (never mint
    or burn), and the OWNERSHIP rule — only the executing program may
    DEBIT an account, and only if that account is owned by it
    (credits are unrestricted), mirroring the reference runtime's
    account-modification checks."""
    from ..vm import DEFAULT_SYSCALLS, ERR_NONE as VM_OK, Vm
    accts = [ctx.account(i) for i in instr.acct_idxs]
    program_id = ctx.keys[instr.prog_idx]
    data = ctx.payload[instr.data_off:instr.data_off + instr.data_sz]
    blob = struct.pack("<H", len(accts))
    for ix, a in zip(instr.acct_idxs, accts):
        blob += (ctx.keys[ix] + struct.pack("<Q", a.lamports)
                 + bytes([1 if ctx.is_signer(ix) else 0,
                          1 if ctx.is_writable(ix) else 0]))
    blob += struct.pack("<H", len(data)) + data
    vm = Vm(program.data, input_data=blob, syscalls=DEFAULT_SYSCALLS)
    res = vm.run()
    ctx.logs.extend(res.log)
    if res.error != VM_OK or res.r0 != 0:
        return ERR_VM
    # lamports write-back with conservation over UNIQUE accounts: an
    # instruction may list the same account at several indices (the
    # runtime maps them to ONE account), so both the before-sum and the
    # applied value dedup by key with last-slot-wins — otherwise a
    # duplicated index could double-count `before` and mint the
    # difference
    off = 2
    final: dict[bytes, tuple[int, int]] = {}     # key -> (idx, lamports)
    for ix in instr.acct_idxs:
        lam = int.from_bytes(vm.mem_read(
            0x4_0000_0000 + off + 32, 8), "little")
        final[ctx.keys[ix]] = (ix, lam)
        off += 42
    uniq = {ctx.keys[ix]: ctx.account(ix) for ix in instr.acct_idxs}
    before = sum(a.lamports for a in uniq.values())
    if sum(lam for _, lam in final.values()) != before:
        return ERR_BALANCE_VIOLATION
    for key, (ix, lam) in final.items():
        a = uniq[key]
        if lam != a.lamports:
            if not ctx.is_writable(ix):
                return ERR_NOT_WRITABLE
            if lam < a.lamports and a.owner != program_id:
                # a program may only DEBIT accounts it owns — txn-level
                # writability alone must not let an arbitrary deployed
                # program drain a victim's account
                return ERR_INVALID_OWNER
            a.lamports = lam
    return OK


class TxnExecutor:
    """fd_runtime_prepare_and_execute_txn analog for the host path."""

    def __init__(self, db: AccDb, fee_per_signature: int = 5000):
        self.db = db
        self.fee_per_signature = fee_per_signature

    def execute(self, xid, payload: bytes) -> TxnResult:
        try:
            txn = parse_txn(payload)
        except Exception:
            return TxnResult(ERR_PARSE, 0, [])
        keys = txn.account_keys(payload)
        fee = self.fee_per_signature * txn.sig_cnt

        # fee payer: signer 0, charged even when execution fails
        # (the reference commits fees before dispatch)
        payer = self.db.open_rw(xid, keys[0], do_create=True)
        if payer.account.lamports < fee:
            self.db.close_rw(payer, discard=True)
            return TxnResult(ERR_FEE, 0, [])
        payer.account.lamports -= fee
        self.db.close_rw(payer)

        ctx = TxnContext(self.db, xid, txn, payload)
        from .vote import VOTE_PROGRAM_ID, exec_vote
        for instr in txn.instrs:
            prog = keys[instr.prog_idx]
            if prog == SYSTEM_PROGRAM_ID:
                st = _exec_system(ctx, instr)
            elif prog == VOTE_PROGRAM_ID:
                st = exec_vote(ctx, instr)
            elif prog == COMPUTE_BUDGET_PROGRAM_ID:
                st = OK                  # limits handled by pack/cost
            else:
                pa = self.db.peek(xid, prog)
                if pa is not None and pa.executable \
                        and pa.owner == BPF_LOADER_ID:
                    st = _exec_bpf(ctx, instr, pa)
                else:
                    st = ERR_UNKNOWN_PROGRAM
            if st != OK:
                # atomic rollback: drop the working set (fee stays)
                return TxnResult(st, fee, ctx.logs)
        ctx.commit()
        return TxnResult(OK, fee, ctx.logs)
