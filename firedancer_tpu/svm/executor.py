"""Wave-scheduled block executor for system-program transfers (SVM core).

North-star P3: the reference replays a block by feeding a conflict DAG
to N exec tiles (ref: src/discof/replay/fd_rdisp.h:6-80,
src/discof/exec/fd_exec_tile.c:14-21, runtime entry
src/flamenco/runtime/fd_runtime.h:254-266, system program semantics
src/flamenco/runtime/program/fd_system_program.c). On TPU the same DAG
becomes *topological waves*: every wave is pairwise conflict-free, so
one `lax.scan` step executes the whole wave vmapped over lanes, and the
scan over waves replays the block — bit-identical to serial execution
(the serial fiction), which `execute_block_serial` pins down as the
oracle.

Scope: system-program transfers (the first native program; sBPF stays on
host exec tiles by design — SURVEY §7 hard-part 6). Lamports are u64 as
(hi, lo) uint32 pairs — no 64-bit integer lanes on TPU, same move as the
SHA-512 kernel. Consensus math is integer-only throughout.

Failure semantics (mirrors the runtime's fee model, simplified):
  * balance < fee                -> STATUS_FEE_FAIL, no state change
    (the reference would never include such a txn; we report it)
  * fee <= balance < fee+amount  -> STATUS_INSUFFICIENT, fee charged
  * otherwise                    -> STATUS_OK, fee + amount moved
Transfers to unknown accounts create them (system-owned model).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..replay.rdisp import ConflictDag

STATUS_PAD = -1
STATUS_OK = 0
STATUS_INSUFFICIENT = 1
STATUS_FEE_FAIL = 2

_MASK32 = (1 << 32) - 1


@dataclass(frozen=True)
class SystemTxn:
    """One system-program transfer: src pays fee + sends amount to dst."""
    src: bytes           # 32B pubkey
    dst: bytes           # 32B pubkey
    amount: int          # u64 lamports
    fee: int             # u64 lamports (burned in this model)


def execute_block_serial(balances: dict, txns) -> list[int]:
    """Serial oracle: execute in insertion order, mutating `balances`
    (pubkey -> int lamports). Returns per-txn status codes."""
    out = []
    for t in txns:
        bal = balances.get(t.src, 0)
        if bal < t.fee:
            out.append(STATUS_FEE_FAIL)
            continue
        if bal < t.fee + t.amount:
            balances[t.src] = bal - t.fee
            out.append(STATUS_INSUFFICIENT)
            continue
        balances[t.src] = bal - t.fee - t.amount
        balances[t.dst] = balances.get(t.dst, 0) + t.amount
        out.append(STATUS_OK)
    return out


def _build_waves(txns, key_idx):
    """Conflict DAG -> padded wave tables (numpy). Dead (padding) lanes
    point at a dummy account slot (index len(key_idx)) so their no-op
    scatter writes can never collide with a live lane's write — XLA
    scatter with duplicate indices is last-wins, so a dead lane aimed at
    a real account could clobber it."""
    dag = ConflictDag()
    for t in txns:
        dag.add_txn(writes=(t.src, t.dst), reads=())
    waves = dag.waves() if len(dag) else []
    n_waves = len(waves)
    cap = max((len(w) for w in waves), default=1)
    dummy = len(key_idx)
    src = np.full((n_waves, cap), dummy, np.int32)
    dst = np.full((n_waves, cap), dummy, np.int32)
    amt = np.zeros((n_waves, cap, 2), np.uint32)    # (hi, lo)
    fee = np.zeros((n_waves, cap, 2), np.uint32)
    tix = np.full((n_waves, cap), -1, np.int32)
    act = np.zeros((n_waves, cap), bool)
    for wi, wave in enumerate(waves):
        for li, t_idx in enumerate(wave):
            t = txns[t_idx]
            src[wi, li] = key_idx[t.src]
            dst[wi, li] = key_idx[t.dst]
            amt[wi, li] = (t.amount >> 32, t.amount & _MASK32)
            fee[wi, li] = (t.fee >> 32, t.fee & _MASK32)
            tix[wi, li] = t_idx
            act[wi, li] = True
    return waves, (src, dst, amt, fee, tix, act)


def _bucket(n: int, lo: int = 4) -> int:
    """Next power of two >= max(n, lo): the padded-shape discipline
    that keeps the jitted wave scan at a bounded set of compiled
    variants (the verify tile's fixed-batch rule, applied per axis)."""
    b = lo
    while b < n:
        b <<= 1
    return b


def _scan_packed(packed, bal_hi, bal_lo):
    """The jitted kernel: ONE packed (W, C, 7) uint32 table — src, dst,
    amt_hi, amt_lo, fee_hi, fee_lo, act per lane — split on-device
    (the _StageBuf discipline: host->device is a single transfer per
    wave, lanes unpack inside the program)."""
    import jax
    import jax.numpy as jnp

    src = packed[..., 0].astype(jnp.int32)
    dst = packed[..., 1].astype(jnp.int32)
    amt = packed[..., 2:4]
    fee = packed[..., 4:6]
    act = packed[..., 6] != 0

    def u64_ge(ah, al, bh, bl):
        return (ah > bh) | ((ah == bh) & (al >= bl))

    def u64_add(ah, al, bh, bl):
        lo = al + bl
        return ah + bh + (lo < al).astype(jnp.uint32), lo

    def u64_sub(ah, al, bh, bl):
        lo = al - bl
        return ah - bh - (al < bl).astype(jnp.uint32), lo

    def wave_step(carry, wave):
        bh, bl = carry
        w_src, w_dst, w_amt, w_fee, w_act = wave
        s_hi = bh[w_src]
        s_lo = bl[w_src]
        need_hi, need_lo = u64_add(w_amt[:, 0], w_amt[:, 1],
                                   w_fee[:, 0], w_fee[:, 1])
        fee_ok = u64_ge(s_hi, s_lo, w_fee[:, 0], w_fee[:, 1]) & w_act
        ok = u64_ge(s_hi, s_lo, need_hi, need_lo) & w_act
        # charge fee where payable, amount where fully funded
        sub_hi = jnp.where(ok, need_hi, jnp.where(fee_ok, w_fee[:, 0], 0))
        sub_lo = jnp.where(ok, need_lo, jnp.where(fee_ok, w_fee[:, 1], 0))
        n_hi, n_lo = u64_sub(s_hi, s_lo, sub_hi, sub_lo)
        # within a wave all written accounts are disjoint across txns
        # (conflict rule), so scatter-set is race-free; self-transfer is
        # handled by writing src first, then read-modify-write dst
        bh = bh.at[w_src].set(jnp.where(w_act, n_hi, s_hi))
        bl = bl.at[w_src].set(jnp.where(w_act, n_lo, s_lo))
        d_hi = bh[w_dst]
        d_lo = bl[w_dst]
        add_hi = jnp.where(ok, w_amt[:, 0], 0)
        add_lo = jnp.where(ok, w_amt[:, 1], 0)
        r_hi, r_lo = u64_add(d_hi, d_lo, add_hi, add_lo)
        bh = bh.at[w_dst].set(jnp.where(w_act, r_hi, d_hi))
        bl = bl.at[w_dst].set(jnp.where(w_act, r_lo, d_lo))
        status = jnp.where(~w_act, STATUS_PAD,
                           jnp.where(ok, STATUS_OK,
                                     jnp.where(fee_ok, STATUS_INSUFFICIENT,
                                               STATUS_FEE_FAIL)))
        return (bh, bl), status

    (bh, bl), statuses = jax.lax.scan(
        wave_step, (bal_hi, bal_lo), (src, dst, amt, fee, act))
    return bh, bl, statuses


@dataclass
class StagedWave:
    """One staged device wave: the packed conflict tables (already in
    flight to the device — the transfer is balance-independent, so it
    overlaps whatever the device was computing) plus the host-side
    decode maps. Built by WaveExecutor.stage, consumed by dispatch."""
    txns: list
    key_idx: dict
    packed_dev: object          # device array (or None when empty)
    tix: np.ndarray
    act: np.ndarray


@dataclass
class DispatchedWave:
    """An in-flight wave: the funk fork is prepared, balances are on
    the wire, the scan's result futures are pending. finalize() forces
    them and commits."""
    staged: StagedWave
    xid: object
    prior: dict
    fut: tuple                  # (bal_hi, bal_lo, statuses) futures


class WaveExecutor:
    """Device-wave block execution split into stage -> dispatch ->
    finalize, so a pipelining caller (the bank tile) can overlap wave
    k+1's staging transfer with wave k's compute:

      stage(txns)      build conflict waves, pack ALL lane tables into
                       ONE (W, C, 7) uint32 buffer, async device_put —
                       balance-INdependent, safe before the previous
                       wave committed
      dispatch(...)    prepare the funk fork, read balances (after the
                       previous wave's commit), launch the jitted scan
                       — returns futures, never blocks
      finalize(...)    force the futures, commit lamports into the
                       fork, return per-txn statuses in insertion order

    Shapes are padded to power-of-two buckets per axis so the jit
    compiles a bounded set of variants (verify's fixed-shape rule)."""

    def __init__(self):
        self._fn = None

    def _jit(self):
        if self._fn is None:
            import jax
            self._fn = jax.jit(_scan_packed)
        return self._fn

    def stage(self, txns) -> StagedWave:
        txns = list(txns)
        key_idx: dict = {}
        for t in txns:
            for k in (t.src, t.dst):
                if k not in key_idx:
                    key_idx[k] = len(key_idx)
        if not txns:
            return StagedWave(txns, key_idx, None,
                              np.zeros((0, 0), np.int32),
                              np.zeros((0, 0), bool))
        _, (src, dst, amt, fee, tix, act) = _build_waves(txns, key_idx)
        w, c = tix.shape
        wp, cp = _bucket(w), _bucket(c)
        dummy = len(key_idx)
        packed = np.zeros((wp, cp, 7), np.uint32)
        # padding lanes aim at the dummy slot with act=0: their
        # write-back is a same-value no-op by construction
        packed[..., 0] = dummy
        packed[..., 1] = dummy
        packed[:w, :c, 0] = src
        packed[:w, :c, 1] = dst
        packed[:w, :c, 2:4] = amt
        packed[:w, :c, 4:6] = fee
        packed[:w, :c, 6] = act
        import jax
        return StagedWave(txns, key_idx, jax.device_put(packed),
                          tix, act)

    def dispatch(self, funk, parent_xid, xid, staged: StagedWave
                 ) -> DispatchedWave:
        funk.txn_prepare(parent_xid, xid)
        if not staged.txns:
            return DispatchedWave(staged, xid, {}, None)
        from .accdb import Account
        n = len(staged.key_idx)
        np_acct = _bucket(n + 1)
        bal_hi = np.zeros((np_acct,), np.uint32)
        bal_lo = np.zeros((np_acct,), np.uint32)
        prior: dict = {}
        for k, i in staged.key_idx.items():
            rec = funk.rec_query(parent_xid, k)
            prior[k] = rec
            # funk values are either typed accdb Accounts or bare
            # lamports ints (legacy genesis path); both carry u64
            v = rec.lamports if isinstance(rec, Account) \
                else (0 if rec is None else int(rec))
            bal_hi[i] = v >> 32
            bal_lo[i] = v & _MASK32
        fut = self._jit()(staged.packed_dev, bal_hi, bal_lo)
        return DispatchedWave(staged, xid, prior, fut)

    def finalize(self, funk, disp: DispatchedWave) -> list[int]:
        staged = disp.staged
        if disp.fut is None:
            return []
        bh, bl, st = (np.asarray(x) for x in disp.fut)
        statuses = [STATUS_PAD] * len(staged.txns)
        tix, act = staged.tix, staged.act
        for wi in range(tix.shape[0]):
            for li in range(tix.shape[1]):
                if act[wi, li]:
                    statuses[int(tix[wi, li])] = int(st[wi, li])
        from .accdb import Account, commit_lamports
        typed = any(isinstance(v, Account) for v in disp.prior.values())
        for k, i in staged.key_idx.items():
            commit_lamports(funk, disp.xid, k,
                            (int(bh[i]) << 32) | int(bl[i]), typed,
                            disp.prior[k])
        return statuses


_DEFAULT_WX: WaveExecutor | None = None


def execute_block(funk, parent_xid, xid, txns) -> list[int]:
    """Replay a block of system transfers on the device and commit the
    result as funk fork `xid` (prepared from `parent_xid`). Returns
    per-txn statuses in insertion order. One synchronous
    stage -> dispatch -> finalize round on the shared WaveExecutor —
    the bank tile pipelines the same three calls itself.

    funk record format: key = pubkey bytes, val = int lamports.
    """
    global _DEFAULT_WX
    if _DEFAULT_WX is None:
        _DEFAULT_WX = WaveExecutor()
    wx = _DEFAULT_WX
    staged = wx.stage(txns)
    disp = wx.dispatch(funk, parent_xid, xid, staged)
    return wx.finalize(funk, disp)
