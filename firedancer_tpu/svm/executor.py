"""Wave-scheduled block executor for system-program transfers (SVM core).

North-star P3: the reference replays a block by feeding a conflict DAG
to N exec tiles (ref: src/discof/replay/fd_rdisp.h:6-80,
src/discof/exec/fd_exec_tile.c:14-21, runtime entry
src/flamenco/runtime/fd_runtime.h:254-266, system program semantics
src/flamenco/runtime/program/fd_system_program.c). On TPU the same DAG
becomes *topological waves*: every wave is pairwise conflict-free, so
one `lax.scan` step executes the whole wave vmapped over lanes, and the
scan over waves replays the block — bit-identical to serial execution
(the serial fiction), which `execute_block_serial` pins down as the
oracle.

Scope: system-program transfers (the first native program; sBPF stays on
host exec tiles by design — SURVEY §7 hard-part 6). Lamports are u64 as
(hi, lo) uint32 pairs — no 64-bit integer lanes on TPU, same move as the
SHA-512 kernel. Consensus math is integer-only throughout.

Failure semantics (mirrors the runtime's fee model, simplified):
  * balance < fee                -> STATUS_FEE_FAIL, no state change
    (the reference would never include such a txn; we report it)
  * fee <= balance < fee+amount  -> STATUS_INSUFFICIENT, fee charged
  * otherwise                    -> STATUS_OK, fee + amount moved
Transfers to unknown accounts create them (system-owned model).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..replay.rdisp import ConflictDag

STATUS_PAD = -1
STATUS_OK = 0
STATUS_INSUFFICIENT = 1
STATUS_FEE_FAIL = 2

_MASK32 = (1 << 32) - 1


@dataclass(frozen=True)
class SystemTxn:
    """One system-program transfer: src pays fee + sends amount to dst."""
    src: bytes           # 32B pubkey
    dst: bytes           # 32B pubkey
    amount: int          # u64 lamports
    fee: int             # u64 lamports (burned in this model)


def execute_block_serial(balances: dict, txns) -> list[int]:
    """Serial oracle: execute in insertion order, mutating `balances`
    (pubkey -> int lamports). Returns per-txn status codes."""
    out = []
    for t in txns:
        bal = balances.get(t.src, 0)
        if bal < t.fee:
            out.append(STATUS_FEE_FAIL)
            continue
        if bal < t.fee + t.amount:
            balances[t.src] = bal - t.fee
            out.append(STATUS_INSUFFICIENT)
            continue
        balances[t.src] = bal - t.fee - t.amount
        balances[t.dst] = balances.get(t.dst, 0) + t.amount
        out.append(STATUS_OK)
    return out


def _build_waves(txns, key_idx):
    """Conflict DAG -> padded wave tables (numpy). Dead (padding) lanes
    point at a dummy account slot (index len(key_idx)) so their no-op
    scatter writes can never collide with a live lane's write — XLA
    scatter with duplicate indices is last-wins, so a dead lane aimed at
    a real account could clobber it."""
    dag = ConflictDag()
    for t in txns:
        dag.add_txn(writes=(t.src, t.dst), reads=())
    waves = dag.waves() if len(dag) else []
    n_waves = len(waves)
    cap = max((len(w) for w in waves), default=1)
    dummy = len(key_idx)
    src = np.full((n_waves, cap), dummy, np.int32)
    dst = np.full((n_waves, cap), dummy, np.int32)
    amt = np.zeros((n_waves, cap, 2), np.uint32)    # (hi, lo)
    fee = np.zeros((n_waves, cap, 2), np.uint32)
    tix = np.full((n_waves, cap), -1, np.int32)
    act = np.zeros((n_waves, cap), bool)
    for wi, wave in enumerate(waves):
        for li, t_idx in enumerate(wave):
            t = txns[t_idx]
            src[wi, li] = key_idx[t.src]
            dst[wi, li] = key_idx[t.dst]
            amt[wi, li] = (t.amount >> 32, t.amount & _MASK32)
            fee[wi, li] = (t.fee >> 32, t.fee & _MASK32)
            tix[wi, li] = t_idx
            act[wi, li] = True
    return waves, (src, dst, amt, fee, tix, act)


def _jax_wave_scan(bal_hi, bal_lo, tables):
    import jax
    import jax.numpy as jnp

    src, dst, amt, fee, tix, act = (jnp.asarray(x) for x in tables)

    def u64_ge(ah, al, bh, bl):
        return (ah > bh) | ((ah == bh) & (al >= bl))

    def u64_add(ah, al, bh, bl):
        lo = al + bl
        return ah + bh + (lo < al).astype(jnp.uint32), lo

    def u64_sub(ah, al, bh, bl):
        lo = al - bl
        return ah - bh - (al < bl).astype(jnp.uint32), lo

    def wave_step(carry, wave):
        bh, bl = carry
        w_src, w_dst, w_amt, w_fee, w_act = wave
        s_hi = bh[w_src]
        s_lo = bl[w_src]
        need_hi, need_lo = u64_add(w_amt[:, 0], w_amt[:, 1],
                                   w_fee[:, 0], w_fee[:, 1])
        fee_ok = u64_ge(s_hi, s_lo, w_fee[:, 0], w_fee[:, 1]) & w_act
        ok = u64_ge(s_hi, s_lo, need_hi, need_lo) & w_act
        # charge fee where payable, amount where fully funded
        sub_hi = jnp.where(ok, need_hi, jnp.where(fee_ok, w_fee[:, 0], 0))
        sub_lo = jnp.where(ok, need_lo, jnp.where(fee_ok, w_fee[:, 1], 0))
        n_hi, n_lo = u64_sub(s_hi, s_lo, sub_hi, sub_lo)
        # within a wave all written accounts are disjoint across txns
        # (conflict rule), so scatter-set is race-free; self-transfer is
        # handled by writing src first, then read-modify-write dst
        bh = bh.at[w_src].set(jnp.where(w_act, n_hi, s_hi))
        bl = bl.at[w_src].set(jnp.where(w_act, n_lo, s_lo))
        d_hi = bh[w_dst]
        d_lo = bl[w_dst]
        add_hi = jnp.where(ok, w_amt[:, 0], 0)
        add_lo = jnp.where(ok, w_amt[:, 1], 0)
        r_hi, r_lo = u64_add(d_hi, d_lo, add_hi, add_lo)
        bh = bh.at[w_dst].set(jnp.where(w_act, r_hi, d_hi))
        bl = bl.at[w_dst].set(jnp.where(w_act, r_lo, d_lo))
        status = jnp.where(~w_act, STATUS_PAD,
                           jnp.where(ok, STATUS_OK,
                                     jnp.where(fee_ok, STATUS_INSUFFICIENT,
                                               STATUS_FEE_FAIL)))
        return (bh, bl), status

    (bh, bl), statuses = jax.lax.scan(
        wave_step, (jnp.asarray(bal_hi), jnp.asarray(bal_lo)),
        (src, dst, amt, fee, act))
    return np.asarray(bh), np.asarray(bl), np.asarray(statuses)


def execute_block(funk, parent_xid, xid, txns) -> list[int]:
    """Replay a block of system transfers on the device and commit the
    result as funk fork `xid` (prepared from `parent_xid`). Returns
    per-txn statuses in insertion order.

    funk record format: key = pubkey bytes, val = int lamports.
    """
    txns = list(txns)
    funk.txn_prepare(parent_xid, xid)
    if not txns:
        return []

    # dense account table for this block
    key_idx: dict = {}
    for t in txns:
        for k in (t.src, t.dst):
            if k not in key_idx:
                key_idx[k] = len(key_idx)
    keys = list(key_idx)
    n = len(keys)
    # slot n is the dummy account targeted by padding lanes
    bal_hi = np.zeros((n + 1,), np.uint32)
    bal_lo = np.zeros((n + 1,), np.uint32)
    from .accdb import Account
    prior: dict = {}
    for k, i in key_idx.items():
        rec = funk.rec_query(parent_xid, k)
        prior[k] = rec
        # funk values are either typed accdb Accounts or bare lamports
        # ints (legacy genesis path); both carry u64 lamports
        v = rec.lamports if isinstance(rec, Account) \
            else (0 if rec is None else int(rec))
        bal_hi[i] = v >> 32
        bal_lo[i] = v & _MASK32

    waves, tables = _build_waves(txns, key_idx)
    bh, bl, st = _jax_wave_scan(bal_hi, bal_lo, tables)

    statuses = [STATUS_PAD] * len(txns)
    tix, act = tables[4], tables[5]
    for wi in range(tix.shape[0]):
        for li in range(tix.shape[1]):
            if act[wi, li]:
                statuses[int(tix[wi, li])] = int(st[wi, li])

    from .accdb import commit_lamports
    typed = any(isinstance(v, Account) for v in prior.values())
    for k, i in key_idx.items():
        commit_lamports(funk, xid, k,
                        (int(bh[i]) << 32) | int(bl[i]), typed, prior[k])
    return statuses
