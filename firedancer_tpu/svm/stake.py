"""Native stake program: delegation lifecycle feeding consensus stake.

Subset of the reference's stake program re-expressed for this runtime
(ref: src/flamenco/runtime/program/fd_stake_program.c — Initialize /
DelegateStake / Deactivate / Withdraw with the authorized-staker/
withdrawer split; epoch-boundary activation semantics per the stake
history discipline, simplified to step activation: a delegation made
in epoch E is ACTIVE for epochs > E, a deactivation in epoch E stops
counting for epochs > E — the reference's warmup/cooldown RATE limits
are not modeled, documented divergence).

The current epoch reaches the program through TxnContext.epoch — this
framework's stand-in for the Clock sysvar (the reference reads
fd_sysvar_clock).

State layout (compact struct, this framework's own; semantics follow
the reference):
  u8 state (0 uninitialized | 1 initialized | 2 delegated)
  staker 32 | withdrawer 32 | rent_reserve u64
  voter 32 | amount u64 | activation_epoch u64 | deactivation_epoch u64
"""
from __future__ import annotations

import struct

STAKE_PROGRAM_ID = b"Stake" + bytes(27)
EPOCH_NONE = (1 << 64) - 1

STAKE_IX_INITIALIZE = 0
STAKE_IX_DELEGATE = 1
STAKE_IX_DEACTIVATE = 2
STAKE_IX_WITHDRAW = 3

_FMT = "<B32s32sQ32sQQQ"
STATE_SZ = struct.calcsize(_FMT)

ST_UNINIT, ST_INIT, ST_DELEGATED = 0, 1, 2


class StakeState:
    def __init__(self, state=ST_UNINIT, staker=bytes(32),
                 withdrawer=bytes(32), rent_reserve=0, voter=bytes(32),
                 amount=0, activation_epoch=EPOCH_NONE,
                 deactivation_epoch=EPOCH_NONE):
        self.state = state
        self.staker = staker
        self.withdrawer = withdrawer
        self.rent_reserve = rent_reserve
        self.voter = voter
        self.amount = amount
        self.activation_epoch = activation_epoch
        self.deactivation_epoch = deactivation_epoch

    def to_bytes(self) -> bytes:
        return struct.pack(_FMT, self.state, self.staker,
                           self.withdrawer, self.rent_reserve,
                           self.voter, self.amount,
                           self.activation_epoch,
                           self.deactivation_epoch)

    @classmethod
    def from_bytes(cls, b: bytes) -> "StakeState":
        return cls(*struct.unpack_from(_FMT, b, 0))

    # -- epoch semantics ----------------------------------------------------

    def active_at(self, epoch: int) -> int:
        """Stake counted for `epoch` (step activation: active strictly
        after the activation epoch, through the deactivation epoch)."""
        if self.state != ST_DELEGATED:
            return 0
        if self.activation_epoch == EPOCH_NONE \
                or epoch <= self.activation_epoch:
            return 0
        if self.deactivation_epoch != EPOCH_NONE \
                and epoch > self.deactivation_epoch:
            return 0
        return self.amount

    def fully_inactive(self, epoch: int) -> bool:
        if self.state != ST_DELEGATED:
            return True
        if self.activation_epoch == EPOCH_NONE:
            return True
        return (self.deactivation_epoch != EPOCH_NONE
                and epoch > self.deactivation_epoch)


def ix_initialize(staker: bytes, withdrawer: bytes) -> bytes:
    return struct.pack("<I", STAKE_IX_INITIALIZE) + staker + withdrawer


def ix_delegate() -> bytes:
    return struct.pack("<I", STAKE_IX_DELEGATE)


def ix_deactivate() -> bytes:
    return struct.pack("<I", STAKE_IX_DEACTIVATE)


def ix_withdraw(lamports: int) -> bytes:
    return struct.pack("<IQ", STAKE_IX_WITHDRAW, lamports)


def exec_stake(ic) -> str:
    """ic: programs.InstrCtx. Dispatched from the executor's native
    program switch."""
    from .programs import (
        ERR_BAD_IX_DATA, ERR_INSUFFICIENT, ERR_INVALID_OWNER,
        ERR_MISSING_SIG, ERR_NOT_WRITABLE, ERR_UNKNOWN_IX, OK,
    )
    from .vote import VOTE_PROGRAM_ID
    data = ic.data
    if len(data) < 4 or ic.n < 1:
        return ERR_BAD_IX_DATA
    disc = struct.unpack_from("<I", data, 0)[0]
    acct = ic.account(0)
    if acct.owner != STAKE_PROGRAM_ID:
        return ERR_INVALID_OWNER
    epoch = ic.ctx.epoch

    if disc == STAKE_IX_INITIALIZE:
        if len(data) < 4 + 64:
            return ERR_BAD_IX_DATA
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        if acct.data and any(acct.data[:1]):
            return ERR_INVALID_OWNER         # already initialized
        st = StakeState(ST_INIT, staker=data[4:36],
                        withdrawer=data[36:68])
        acct.data = st.to_bytes()
        return OK

    if len(acct.data) < STATE_SZ:
        return ERR_INVALID_OWNER
    st = StakeState.from_bytes(acct.data)

    if disc == STAKE_IX_DELEGATE:
        if ic.n < 2:
            return ERR_BAD_IX_DATA
        if st.state == ST_UNINIT:
            return ERR_INVALID_OWNER
        if st.staker not in ic.signer_keys():
            return ERR_MISSING_SIG
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        vote_acct = ic.account(1)
        if vote_acct.owner != VOTE_PROGRAM_ID:
            return ERR_INVALID_OWNER
        if st.state == ST_DELEGATED and not st.fully_inactive(epoch):
            # re-delegation of live stake is refused (the reference
            # allows it only through the deactivate-then-delegate path)
            return ERR_INVALID_OWNER
        amount = acct.lamports - st.rent_reserve
        if amount <= 0:
            return ERR_INSUFFICIENT
        st.state = ST_DELEGATED
        st.voter = ic.key(1)
        st.amount = amount
        st.activation_epoch = epoch
        st.deactivation_epoch = EPOCH_NONE
        acct.data = st.to_bytes()
        return OK

    if disc == STAKE_IX_DEACTIVATE:
        if st.state != ST_DELEGATED or st.deactivation_epoch != EPOCH_NONE:
            return ERR_INVALID_OWNER
        if st.staker not in ic.signer_keys():
            return ERR_MISSING_SIG
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        st.deactivation_epoch = epoch
        acct.data = st.to_bytes()
        return OK

    if disc == STAKE_IX_WITHDRAW:
        if len(data) < 12 or ic.n < 2:
            return ERR_BAD_IX_DATA
        lamports = struct.unpack_from("<Q", data, 4)[0]
        if st.withdrawer not in ic.signer_keys():
            return ERR_MISSING_SIG
        if not ic.is_writable(0) or not ic.is_writable(1):
            return ERR_NOT_WRITABLE
        if st.fully_inactive(epoch):
            locked = 0                        # may drain + close
        else:
            locked = st.amount + st.rent_reserve
        if lamports > acct.lamports - locked:
            return ERR_INSUFFICIENT
        acct.lamports -= lamports
        ic.account(1).lamports += lamports
        return OK

    return ERR_UNKNOWN_IX
