"""Native stake program: delegation lifecycle feeding consensus stake.

Subset of the reference's stake program re-expressed for this runtime
(ref: src/flamenco/runtime/program/fd_stake_program.c — Initialize /
DelegateStake / Deactivate / Withdraw with the authorized-staker/
withdrawer split; epoch-boundary activation semantics per the stake
history discipline). Activation runs in TWO modes: with a
StakeHistory sysvar present, the reference's RATE-LIMITED
warmup/cooldown (at most WARMUP_COOLDOWN_RATE x the prior epoch's
cluster-effective stake moves per epoch, pro-rata —
stake_activating_and_deactivating below, r5); without one, step
activation (a delegation made in epoch E is ACTIVE for epochs > E, a
deactivation in epoch E stops counting for epochs > E) for
self-contained clusters and unit tests.

The current epoch reaches the program through TxnContext.epoch — this
framework's stand-in for the Clock sysvar (the reference reads
fd_sysvar_clock).

State layout (compact struct, this framework's own; semantics follow
the reference):
  u8 state (0 uninitialized | 1 initialized | 2 delegated)
  staker 32 | withdrawer 32 | rent_reserve u64
  voter 32 | amount u64 | activation_epoch u64 | deactivation_epoch u64
"""
from __future__ import annotations

import struct

STAKE_PROGRAM_ID = b"Stake" + bytes(27)
EPOCH_NONE = (1 << 64) - 1

STAKE_IX_INITIALIZE = 0
STAKE_IX_DELEGATE = 1
STAKE_IX_DEACTIVATE = 2
STAKE_IX_WITHDRAW = 3

_FMT = "<B32s32sQ32sQQQ"
STATE_SZ = struct.calcsize(_FMT)

ST_UNINIT, ST_INIT, ST_DELEGATED = 0, 1, 2


class StakeState:
    def __init__(self, state=ST_UNINIT, staker=bytes(32),
                 withdrawer=bytes(32), rent_reserve=0, voter=bytes(32),
                 amount=0, activation_epoch=EPOCH_NONE,
                 deactivation_epoch=EPOCH_NONE):
        self.state = state
        self.staker = staker
        self.withdrawer = withdrawer
        self.rent_reserve = rent_reserve
        self.voter = voter
        self.amount = amount
        self.activation_epoch = activation_epoch
        self.deactivation_epoch = deactivation_epoch

    def to_bytes(self) -> bytes:
        return struct.pack(_FMT, self.state, self.staker,
                           self.withdrawer, self.rent_reserve,
                           self.voter, self.amount,
                           self.activation_epoch,
                           self.deactivation_epoch)

    @classmethod
    def from_bytes(cls, b: bytes) -> "StakeState":
        return cls(*struct.unpack_from(_FMT, b, 0))

    # -- epoch semantics ----------------------------------------------------

    def active_at(self, epoch: int, history: dict | None = None,
                  rate: float | None = None) -> int:
        """Stake counted for `epoch`.

        Without `history`: step activation (active strictly after the
        activation epoch, through the deactivation epoch) — the
        self-contained-cluster mode.

        With `history` (epoch -> (effective, activating, deactivating)
        cluster totals, the StakeHistory sysvar): the reference's
        RATE-LIMITED warmup/cooldown — at most rate x the prior
        epoch's cluster-effective stake (de)activates per epoch,
        apportioned pro-rata across waiting delegations (ref:
        src/flamenco/runtime/program/fd_stake_program.c stake history
        discipline; Agave stake_activating_and_deactivating)."""
        if history is not None:
            eff, _act, _deact = stake_activating_and_deactivating(
                self, epoch, history,
                rate if rate is not None else WARMUP_COOLDOWN_RATE)
            return eff
        if self.state != ST_DELEGATED:
            return 0
        if self.activation_epoch == EPOCH_NONE \
                or epoch <= self.activation_epoch:
            return 0
        if self.deactivation_epoch != EPOCH_NONE \
                and epoch > self.deactivation_epoch:
            return 0
        return self.amount

    def fully_inactive(self, epoch: int) -> bool:
        if self.state != ST_DELEGATED:
            return True
        if self.activation_epoch == EPOCH_NONE:
            return True
        return (self.deactivation_epoch != EPOCH_NONE
                and epoch > self.deactivation_epoch)


# post reduce_stake_warmup_cooldown rate (9%/epoch of cluster
# effective stake; 25% before the feature)
WARMUP_COOLDOWN_RATE = 0.09


def _stake_and_activating(amount: int, activation_epoch: int,
                          target_epoch: int, history: dict,
                          rate: float) -> tuple[int, int]:
    """(effective, activating) at target_epoch. Float weights mirror
    Agave's f64 arithmetic exactly (consensus-visible there too)."""
    if activation_epoch == EPOCH_NONE:
        return amount, 0               # bootstrap: effective at genesis
    if target_epoch < activation_epoch:
        return 0, 0
    if target_epoch == activation_epoch:
        return 0, amount
    prev = history.get(activation_epoch)
    if prev is None:
        return amount, 0               # no history entry: fully active
    prev_epoch = activation_epoch
    current = 0
    while True:
        current_epoch = prev_epoch + 1
        remaining = amount - current
        prev_eff, prev_act, _ = prev
        if prev_act == 0:
            break
        weight = remaining / prev_act
        newly_cluster = prev_eff * rate
        newly = max(1, int(weight * newly_cluster))
        current += newly
        if current >= amount:
            return amount, 0
        if current_epoch >= target_epoch:
            break
        prev = history.get(current_epoch)
        if prev is None:
            break
        prev_epoch = current_epoch
    return current, amount - current


def stake_activating_and_deactivating(st: "StakeState",
                                      target_epoch: int,
                                      history: dict,
                                      rate: float = WARMUP_COOLDOWN_RATE
                                      ) -> tuple[int, int, int]:
    """(effective, activating, deactivating) for one delegation under
    the cluster stake history — Agave
    Delegation::stake_activating_and_deactivating, draw-compatible
    including the max(1,...) per-epoch floor and the f64 weights."""
    if st.state != ST_DELEGATED:
        return 0, 0, 0
    eff, act = _stake_and_activating(st.amount, st.activation_epoch,
                                     target_epoch, history, rate)
    de = st.deactivation_epoch
    if target_epoch < de or de == EPOCH_NONE:
        return eff, act, 0
    if target_epoch == de:
        return eff, 0, eff             # all effective stake cooling
    # cooldown from the deactivation epoch's effective amount
    eff_at_de, _ = _stake_and_activating(st.amount, st.activation_epoch,
                                         de, history, rate)
    prev = history.get(de)
    if prev is None:
        return 0, 0, 0                 # no history: instant cooldown
    prev_epoch = de
    current = eff_at_de
    while True:
        current_epoch = prev_epoch + 1
        _, _, prev_deact = prev
        prev_eff = prev[0]
        if prev_deact == 0:
            break
        weight = current / prev_deact
        newly_not = max(1, int(weight * (prev_eff * rate)))
        current -= newly_not
        if current <= 0:
            return 0, 0, 0
        if current_epoch >= target_epoch:
            break
        prev = history.get(current_epoch)
        if prev is None:
            break
        prev_epoch = current_epoch
    return current, 0, current


def _read_history(ic) -> dict | None:
    """StakeHistory sysvar via the instruction's txn context (None
    when the account doesn't exist — step-activation mode)."""
    from .sysvars import STAKE_HISTORY_ID, stake_history_from_account
    return stake_history_from_account(
        ic.ctx.db.peek(ic.ctx.xid, STAKE_HISTORY_ID))


def ix_initialize(staker: bytes, withdrawer: bytes) -> bytes:
    return struct.pack("<I", STAKE_IX_INITIALIZE) + staker + withdrawer


def ix_delegate() -> bytes:
    return struct.pack("<I", STAKE_IX_DELEGATE)


def ix_deactivate() -> bytes:
    return struct.pack("<I", STAKE_IX_DEACTIVATE)


def ix_withdraw(lamports: int) -> bytes:
    return struct.pack("<IQ", STAKE_IX_WITHDRAW, lamports)


def exec_stake(ic) -> str:
    """ic: programs.InstrCtx. Dispatched from the executor's native
    program switch."""
    from .programs import (
        ERR_BAD_IX_DATA, ERR_INSUFFICIENT, ERR_INVALID_OWNER,
        ERR_MISSING_SIG, ERR_NOT_WRITABLE, ERR_UNKNOWN_IX, OK,
    )
    from .vote import VOTE_PROGRAM_ID
    data = ic.data
    if len(data) < 4 or ic.n < 1:
        return ERR_BAD_IX_DATA
    disc = struct.unpack_from("<I", data, 0)[0]
    acct = ic.account(0)
    if acct.owner != STAKE_PROGRAM_ID:
        return ERR_INVALID_OWNER
    epoch = ic.ctx.epoch

    if disc == STAKE_IX_INITIALIZE:
        if len(data) < 4 + 64:
            return ERR_BAD_IX_DATA
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        if acct.data and any(acct.data[:1]):
            return ERR_INVALID_OWNER         # already initialized
        # the rent-exempt reserve is locked at initialize and never
        # delegated or withdrawable while the account lives (ref
        # fd_stake_program.c initialize: requires the rent minimum)
        from .sysvars import rent_exempt_minimum
        reserve = rent_exempt_minimum(STATE_SZ)
        if acct.lamports < reserve:
            return ERR_INSUFFICIENT
        st = StakeState(ST_INIT, staker=data[4:36],
                        withdrawer=data[36:68], rent_reserve=reserve)
        acct.data = st.to_bytes()
        return OK

    if len(acct.data) < STATE_SZ:
        return ERR_INVALID_OWNER
    st = StakeState.from_bytes(acct.data)

    if disc == STAKE_IX_DELEGATE:
        if ic.n < 2:
            return ERR_BAD_IX_DATA
        if st.state == ST_UNINIT:
            return ERR_INVALID_OWNER
        if st.staker not in ic.signer_keys():
            return ERR_MISSING_SIG
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        vote_acct = ic.account(1)
        if vote_acct.owner != VOTE_PROGRAM_ID:
            return ERR_INVALID_OWNER
        if st.state == ST_DELEGATED and not st.fully_inactive(epoch):
            # re-delegation of live stake is refused (the reference
            # allows it only through the deactivate-then-delegate path)
            return ERR_INVALID_OWNER
        amount = acct.lamports - st.rent_reserve
        if amount <= 0:
            return ERR_INSUFFICIENT
        st.state = ST_DELEGATED
        st.voter = ic.key(1)
        st.amount = amount
        st.activation_epoch = epoch
        st.deactivation_epoch = EPOCH_NONE
        acct.data = st.to_bytes()
        return OK

    if disc == STAKE_IX_DEACTIVATE:
        if st.state != ST_DELEGATED or st.deactivation_epoch != EPOCH_NONE:
            return ERR_INVALID_OWNER
        if st.staker not in ic.signer_keys():
            return ERR_MISSING_SIG
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        st.deactivation_epoch = epoch
        acct.data = st.to_bytes()
        return OK

    if disc == STAKE_IX_WITHDRAW:
        if len(data) < 12 or ic.n < 2:
            return ERR_BAD_IX_DATA
        lamports = struct.unpack_from("<Q", data, 4)[0]
        if st.withdrawer not in ic.signer_keys():
            return ERR_MISSING_SIG
        if not ic.is_writable(0) or not ic.is_writable(1):
            return ERR_NOT_WRITABLE
        hist = _read_history(ic)
        if hist:
            # rate-limited cooldown: lamports stay locked while the
            # stake history still counts them as effective (ref
            # fd_stake_program.c withdraw: staked = delegation stake
            # at the clock epoch under the history)
            eff, act, _ = stake_activating_and_deactivating(
                st, epoch, hist)
            staked = eff + act
        else:
            staked = 0 if st.fully_inactive(epoch) else st.amount
        if staked:
            locked = staked + st.rent_reserve
        elif lamports == acct.lamports:
            locked = 0            # full drain closes the account
        else:
            # Agave withdraw: a NON-closing withdraw must keep the
            # rent-exempt reserve funded even with nothing staked
            # (lamports + reserve <= balance)
            locked = st.rent_reserve
        if lamports > acct.lamports - locked:
            return ERR_INSUFFICIENT
        acct.lamports -= lamports
        ic.account(1).lamports += lamports
        return OK

    return ERR_UNKNOWN_IX
