"""Sysvar ACCOUNTS — the on-chain view of runtime state.

The reference maintains a sysvar cache and materializes each sysvar as
a real account under its well-known address at every slot boundary
(ref: src/flamenco/runtime/sysvar/fd_sysvar.c, fd_sysvar_clock.c,
fd_sysvar_slot_hashes.c; the cache in fd_sysvar_cache.h). Programs
read them two ways — as instruction accounts (stake/vote pass Clock
and Rent explicitly) and via sol_get_*_sysvar syscalls — and both
views must agree byte-for-byte.

This module owns the account layouts (Agave bincode encodings, pinned
by tests) and `update_sysvars`, which the bank/replay stage calls at
each slot start. The VM's syscall cache (svm/programs.py `_exec_bpf`)
reads the same encodings from accdb when the accounts exist, so the
two views cannot drift.
"""
from __future__ import annotations

import struct

from ..funk.funk import key32
from ..utils.base58 import b58_decode_32
from .accdb import Account

SYSVAR_OWNER = b58_decode_32("Sysvar1111111111111111111111111111111111111")
CLOCK_ID = b58_decode_32("SysvarC1ock11111111111111111111111111111111")
RENT_ID = b58_decode_32("SysvarRent111111111111111111111111111111111")
EPOCH_SCHEDULE_ID = b58_decode_32(
    "SysvarEpochSchedu1e111111111111111111111111")
SLOT_HASHES_ID = b58_decode_32(
    "SysvarS1otHashes111111111111111111111111111")
RECENT_BLOCKHASHES_ID = b58_decode_32(
    "SysvarRecentB1ockHashes11111111111111111111")
STAKE_HISTORY_ID = b58_decode_32(
    "SysvarStakeHistory1111111111111111111111111")

SLOT_HASHES_MAX = 512
RECENT_MAX = 150

# rent parameters (Solana mainnet defaults)
LAMPORTS_PER_BYTE_YEAR = 3480
EXEMPTION_THRESHOLD = 2.0
BURN_PERCENT = 50


def enc_clock(slot: int, epoch: int, epoch_start_ts: int = 0,
              leader_schedule_epoch: int | None = None,
              unix_ts: int = 0) -> bytes:
    """40-byte Clock (ref: fd_sysvar_clock.h layout)."""
    lse = epoch + 1 if leader_schedule_epoch is None \
        else leader_schedule_epoch
    return struct.pack("<QqQQq", slot, epoch_start_ts, epoch, lse,
                       unix_ts)


def dec_clock(b: bytes) -> dict:
    slot, ets, epoch, lse, ts = struct.unpack("<QqQQq", b[:40])
    return {"slot": slot, "epoch_start_timestamp": ets, "epoch": epoch,
            "leader_schedule_epoch": lse, "unix_timestamp": ts}


def enc_rent(lamports_per_byte_year: int = LAMPORTS_PER_BYTE_YEAR,
             exemption_threshold: float = EXEMPTION_THRESHOLD,
             burn_percent: int = BURN_PERCENT) -> bytes:
    """17-byte Rent."""
    return struct.pack("<Qd B", lamports_per_byte_year,
                       exemption_threshold, burn_percent)


def rent_exempt_minimum(data_len: int,
                        lamports_per_byte_year: int =
                        LAMPORTS_PER_BYTE_YEAR,
                        exemption_threshold: float =
                        EXEMPTION_THRESHOLD) -> int:
    """Minimum balance for rent exemption (Agave Rent::minimum_balance:
    (ACCOUNT_STORAGE_OVERHEAD=128 + data_len) * lpby * threshold)."""
    return int((128 + data_len) * lamports_per_byte_year
               * exemption_threshold)


def enc_epoch_schedule(slots_per_epoch: int,
                       leader_schedule_slot_offset: int | None = None,
                       warmup: bool = False,
                       first_normal_epoch: int = 0,
                       first_normal_slot: int = 0) -> bytes:
    """33-byte EpochSchedule."""
    off = slots_per_epoch if leader_schedule_slot_offset is None \
        else leader_schedule_slot_offset
    return struct.pack("<QQBQQ", slots_per_epoch, off,
                       1 if warmup else 0, first_normal_epoch,
                       first_normal_slot)


def enc_stake_history(entries: list[tuple[int, tuple]]) -> bytes:
    """StakeHistory: Vec<(Epoch, {effective, activating, deactivating}
    u64 x3)>, newest first, capped at 512 entries (Agave layout; ref
    src/flamenco/runtime/sysvar/fd_sysvar_stake_history.c)."""
    entries = entries[:512]
    out = struct.pack("<Q", len(entries))
    for epoch, (eff, act, deact) in entries:
        out += struct.pack("<QQQQ", epoch, eff, act, deact)
    return out


def dec_stake_history(b: bytes) -> dict[int, tuple]:
    (n,) = struct.unpack_from("<Q", b, 0)
    out = {}
    off = 8
    for _ in range(n):
        epoch, eff, act, deact = struct.unpack_from("<QQQQ", b, off)
        out[epoch] = (eff, act, deact)
        off += 32
    return out


def stake_history_from_account(acct) -> dict | None:
    """Decode the StakeHistory sysvar account (or None when absent /
    malformed) — the ONE read-and-decode policy shared by the stake
    program's withdraw gate and the epoch-stakes aggregation."""
    if acct is None or len(getattr(acct, "data", b"")) < 8:
        return None
    try:
        return dec_stake_history(bytes(acct.data))
    except Exception:
        return None


def enc_slot_hashes(entries: list[tuple[int, bytes]]) -> bytes:
    """bincode Vec<(Slot, Hash)>, newest first, capped at 512."""
    entries = entries[:SLOT_HASHES_MAX]
    out = struct.pack("<Q", len(entries))
    for slot, h in entries:
        out += struct.pack("<Q", slot) + h
    return out


def dec_slot_hashes(b: bytes) -> list[tuple[int, bytes]]:
    n, = struct.unpack_from("<Q", b, 0)
    out = []
    off = 8
    for _ in range(n):
        slot, = struct.unpack_from("<Q", b, off)
        out.append((slot, b[off + 8:off + 40]))
        off += 40
    return out


def enc_recent_blockhashes(entries: list[tuple[bytes, int]]) -> bytes:
    """bincode Vec<Entry{blockhash, fee_calculator{u64}}>, newest
    first, capped at 150."""
    entries = entries[:RECENT_MAX]
    out = struct.pack("<Q", len(entries))
    for h, lps in entries:
        out += h + struct.pack("<Q", lps)
    return out


def _write(db, xid, key: bytes, data: bytes):
    """Materialize a sysvar account; accepts an AccDb or a bare Funk
    (the one shape for every sysvar writer)."""
    funk = db.funk if hasattr(db, "funk") else db
    funk.rec_write(xid, key32(key), Account(
        lamports=rent_exempt_minimum(len(data)), data=bytearray(data),
        owner=SYSVAR_OWNER, executable=False))


def update_sysvars(db, xid, slot: int, epoch: int,
                   slots_per_epoch: int = 432_000,
                   blockhash: bytes | None = None,
                   lamports_per_sig: int = 5000,
                   unix_ts: int = 0):
    """Materialize/refresh the sysvar accounts for `slot` — the slot-
    boundary duty of the bank (ref: fd_runtime block prepare calling
    the fd_sysvar_*_update family). `blockhash` (the PARENT bank hash)
    prepends to SlotHashes and RecentBlockhashes."""
    _write(db, xid, CLOCK_ID,
           enc_clock(slot, epoch,
                     epoch_start_ts=unix_ts, unix_ts=unix_ts))
    _write(db, xid, RENT_ID, enc_rent())
    _write(db, xid, EPOCH_SCHEDULE_ID,
           enc_epoch_schedule(slots_per_epoch))
    if blockhash is not None:
        prev = db.peek(xid, SLOT_HASHES_ID)
        hashes = dec_slot_hashes(bytes(prev.data)) if prev else []
        if slot > 0:
            hashes = [(slot - 1, blockhash)] + hashes
        _write(db, xid, SLOT_HASHES_ID, enc_slot_hashes(hashes))
        prevr = db.peek(xid, RECENT_BLOCKHASHES_ID)
        rb = []
        if prevr:
            raw = bytes(prevr.data)
            n, = struct.unpack_from("<Q", raw, 0)
            off = 8
            for _ in range(n):
                rb.append((raw[off:off + 32],
                           struct.unpack_from("<Q", raw, off + 32)[0]))
                off += 40
        rb = [(blockhash, lamports_per_sig)] + rb
        _write(db, xid, RECENT_BLOCKHASHES_ID,
               enc_recent_blockhashes(rb))


def read_sysvar_cache(db, xid, fallback_slot: int,
                      fallback_epoch: int) -> dict[str, bytes]:
    """The VM syscall view: account bytes when materialized, else
    synthesized from the executor's slot/epoch (keeps pre-sysvar
    topologies working)."""
    cache = {}
    clock = db.peek(xid, CLOCK_ID)
    cache["clock"] = bytes(clock.data[:40]) if clock \
        and len(clock.data) >= 40 else enc_clock(fallback_slot,
                                                 fallback_epoch)
    rent = db.peek(xid, RENT_ID)
    cache["rent"] = bytes(rent.data[:17]) if rent \
        and len(rent.data) >= 17 else enc_rent()
    es = db.peek(xid, EPOCH_SCHEDULE_ID)
    if es and len(es.data) >= 33:
        cache["epoch_schedule"] = bytes(es.data[:33])
    return cache
