"""accdb v2 — hot funk + cold groove, one account-DB facade.

The reference's accdb v2 layers funk (hot, fork-aware) over a disk
store reached through a vtable (ref: src/flamenco/accdb/
fd_accdb_impl_v2.c over funk+vinyl; fd_accdb_user.h keeps the caller
API identical between v1 and v2). Same shape here: `AccDbCold`
IS an `AccDb` (every handle/fork semantic inherited), with a groove
cold store underneath:

  * peek/open_* fall through to groove on a hot miss; a cold hit is
    PROMOTED into the funk ROOT (cold records only ever hold rooted
    state, so root promotion preserves fork visibility rules).
  * evict(pubkey) moves a ROOTED account to disk and drops it from
    the hot map — the working-set valve. Eviction refuses accounts
    with unpublished fork state (fork overlays must never be silently
    flattened into the cold store).
  * restart: a fresh AccDbCold over an empty funk serves everything
    the previous generation evicted (groove's scan recovery).

Cold record encoding: lamports u64 | executable u8 | rent_epoch u64 |
owner 32 | data (length-implicit) — little-endian, versioned by the
groove volume magic.
"""
from __future__ import annotations

import struct

from ..funk.funk import key32
from ..groove import GrooveStore
from .accdb import AccDb, Account

_META = "<QBQ32s"
_META_SZ = struct.calcsize(_META)


def account_to_bytes(a: Account) -> bytes:
    return struct.pack(_META, a.lamports, 1 if a.executable else 0,
                       a.rent_epoch, bytes(a.owner)) + bytes(a.data)


def account_from_bytes(b: bytes) -> Account:
    lam, ex, rent, owner = struct.unpack_from(_META, b, 0)
    return Account(lamports=lam, data=bytes(b[_META_SZ:]),
                   owner=owner, executable=bool(ex), rent_epoch=rent)


class ColdEvictError(RuntimeError):
    pass


class AccDbCold(AccDb):
    def __init__(self, funk, cold_dir: str):
        super().__init__(funk)
        self.cold = GrooveStore(cold_dir)
        self.cold_stats = {"hits": 0, "promoted": 0, "evicted": 0}

    # -- read path: hot, then cold ------------------------------------------

    def peek(self, xid, pubkey: bytes) -> Account | None:
        a = super().peek(xid, pubkey)
        if a is not None:
            return a
        raw = self.cold.get(pubkey)
        if raw is None:
            return None
        acct = account_from_bytes(bytes(raw))
        # promote into the ROOT: cold state is rooted state, and root
        # records are visible through every fork overlay. The cold
        # copy is DELETED at promotion — an account lives hot XOR
        # cold, so later hot updates/deletions can never be shadowed
        # by a stale cold record after a restart (r4 review)
        self.funk.rec_write(None, key32(pubkey), acct)
        self.cold.delete(pubkey)
        self.cold_stats["hits"] += 1
        self.cold_stats["promoted"] += 1
        return super().peek(xid, pubkey)

    # -- the working-set valve ----------------------------------------------

    def _has_fork_state(self, pubkey: bytes) -> bool:
        for xid in list(getattr(self.funk, "_txns", {})):
            if pubkey in self.funk.txn_recs(xid):
                return True
        return False

    def evict(self, pubkey: bytes, flush: bool = True):
        """Move a ROOTED account to the cold store. Refuses when any
        in-preparation fork carries state for the key (eviction must
        not change what any fork can observe once it publishes)."""
        a = self.funk.rec_query(None, pubkey)
        if a is None:
            raise ColdEvictError("no rooted record to evict")
        if self._has_fork_state(pubkey):
            raise ColdEvictError("key has unpublished fork state")
        acct = a if isinstance(a, Account) else Account(lamports=a)
        self.cold.put(pubkey, account_to_bytes(acct))
        if flush:
            self.cold.flush()
        self.funk.rec_remove(None, key32(pubkey))
        self.cold_stats["evicted"] += 1

    def evict_larger_than(self, data_len: int) -> int:
        """Bulk valve: push every rooted account with data above the
        threshold to disk (skipping keys with live fork state).
        Returns the count evicted. One durability flush for the whole
        sweep."""
        n = 0
        for key, val in list(self.funk.root_items().items()):
            data = val.data if isinstance(val, Account) else b""
            if len(data) <= data_len:
                continue
            try:
                self.evict(key, flush=False)
            except ColdEvictError:
                continue              # fork-dirty key: skip
            n += 1
        if n:
            self.cold.flush()
        return n

    def remove(self, xid, pubkey: bytes):
        """Delete an account through the facade — BOTH layers. Direct
        funk.rec_remove on a key that was evicted (and never promoted)
        would leave a cold copy to resurrect; all deletions of
        possibly-cold keys must come through here."""
        self.cold.delete(pubkey)
        self.funk.rec_remove(xid, key32(pubkey))

    def close(self):
        self.cold.close()
