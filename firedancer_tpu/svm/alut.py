"""Address lookup table program + v0 address resolution.

The reference implements the ALUT program in
src/flamenco/runtime/program/fd_address_lookup_table_program.c and
resolves v0 transactions' table-loaded addresses in the resolv tile
(src/discof/resolv/). This module provides both halves for this
runtime: the native program (Create/Extend/Deactivate/Close with the
PDA-derived table address and authority discipline) and
`resolve_loaded_keys`, which the executor calls to extend a v0 txn's
key list past its static accounts — writables first, then readonlys,
exactly the privilege layout the wire encodes.

State layout (Agave's, via the bincode codec): u32 discriminant
(0 uninitialized, 1 lookup table) | deactivation_slot u64 |
last_extended_slot u64 | last_extended_start_index u8 |
Option<authority Pubkey> | u16 padding, then raw 32-byte addresses
from byte 56 (LOOKUP_TABLE_META_SIZE)."""
from __future__ import annotations

import struct

from .accdb import Account

ALUT_PROGRAM_ID = b"AddressLookupTab" + bytes(16)
LOOKUP_TABLE_META_SIZE = 56
MAX_ADDRESSES = 256
SLOT_MAX = (1 << 64) - 1

IX_CREATE = 0
IX_FREEZE = 1
IX_EXTEND = 2
IX_DEACTIVATE = 3
IX_CLOSE = 4


class AlutState:
    def __init__(self, authority: bytes | None,
                 deactivation_slot: int = SLOT_MAX,
                 last_extended_slot: int = 0,
                 last_extended_start: int = 0,
                 addresses: list[bytes] = ()):
        self.authority = authority
        self.deactivation_slot = deactivation_slot
        self.last_extended_slot = last_extended_slot
        self.last_extended_start = last_extended_start
        self.addresses = list(addresses)

    def to_bytes(self) -> bytes:
        out = struct.pack("<IQQB", 1, self.deactivation_slot,
                          self.last_extended_slot,
                          self.last_extended_start)
        if self.authority is None:
            out += b"\x00" + bytes(32)
        else:
            out += b"\x01" + self.authority
        out += bytes(2)                       # padding to 56
        assert len(out) == LOOKUP_TABLE_META_SIZE
        return out + b"".join(self.addresses)

    @classmethod
    def from_bytes(cls, b: bytes) -> "AlutState":
        if len(b) < LOOKUP_TABLE_META_SIZE:
            raise ValueError("short ALUT state")
        disc, deact, last_slot, last_start = struct.unpack_from(
            "<IQQB", b, 0)
        if disc != 1:
            raise ValueError(f"not a lookup table (disc {disc})")
        has_auth = b[21]
        auth = bytes(b[22:54]) if has_auth else None
        body = b[LOOKUP_TABLE_META_SIZE:]
        addrs = [bytes(body[i:i + 32])
                 for i in range(0, len(body) - len(body) % 32, 32)]
        return cls(auth, deact, last_slot, last_start, addrs)

    def is_active(self, slot: int) -> bool:
        return slot <= self.deactivation_slot


def derive_table_address(authority: bytes, recent_slot: int):
    """(table_pda, bump) — Agave derives the table account as a PDA of
    [authority, recent_slot_le] under the ALUT program."""
    from .programs import find_program_address
    return find_program_address(
        [authority, recent_slot.to_bytes(8, "little")], ALUT_PROGRAM_ID)


def ix_create(recent_slot: int, bump: int) -> bytes:
    return struct.pack("<IQB", IX_CREATE, recent_slot, bump)


def ix_extend(addresses: list[bytes]) -> bytes:
    out = struct.pack("<IQ", IX_EXTEND, len(addresses))
    for a in addresses:
        assert len(a) == 32
        out += a
    return out


def ix_deactivate() -> bytes:
    return struct.pack("<I", IX_DEACTIVATE)


def ix_close() -> bytes:
    return struct.pack("<I", IX_CLOSE)


def exec_alut(ic) -> str:
    """Accounts: [table, authority, (payer for create / recipient for
    close)]. The authority must SIGN everything past creation."""
    from .programs import (
        ERR_BAD_IX_DATA, ERR_INVALID_OWNER, ERR_MISSING_SIG,
        ERR_NOT_WRITABLE, ERR_UNKNOWN_IX, OK,
    )
    data = ic.data
    if len(data) < 4 or ic.n < 2:
        return ERR_BAD_IX_DATA
    disc = struct.unpack_from("<I", data, 0)[0]
    table = ic.account(0)
    authority_key = ic.key(1)
    slot = ic.ctx.slot

    if disc == IX_CREATE:
        if len(data) < 13:
            return ERR_BAD_IX_DATA
        recent_slot, bump = struct.unpack_from("<QB", data, 4)
        want, want_bump = derive_table_address(authority_key,
                                               recent_slot)
        if ic.key(0) != want or bump != want_bump:
            return ERR_INVALID_OWNER          # wrong PDA
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        if table.owner == ALUT_PROGRAM_ID and table.data:
            return ERR_INVALID_OWNER          # already created
        table.owner = ALUT_PROGRAM_ID
        table.data = AlutState(authority_key).to_bytes()
        return OK

    if table.owner != ALUT_PROGRAM_ID or not table.data:
        return ERR_INVALID_OWNER
    try:
        st = AlutState.from_bytes(table.data)
    except ValueError:
        return ERR_INVALID_OWNER
    if st.authority is None or st.authority != authority_key:
        return ERR_INVALID_OWNER              # frozen or wrong authority
    if not ic.is_signer(1):
        return ERR_MISSING_SIG
    if not ic.is_writable(0):
        return ERR_NOT_WRITABLE

    if disc == IX_FREEZE:
        st.authority = None
        table.data = st.to_bytes()
        return OK

    if disc == IX_EXTEND:
        if len(data) < 12:
            return ERR_BAD_IX_DATA
        (cnt,) = struct.unpack_from("<Q", data, 4)
        if len(data) < 12 + 32 * cnt or cnt == 0:
            return ERR_BAD_IX_DATA
        addrs = [data[12 + 32 * i:12 + 32 * (i + 1)]
                 for i in range(cnt)]
        if len(st.addresses) + cnt > MAX_ADDRESSES:
            return ERR_BAD_IX_DATA
        if st.deactivation_slot != SLOT_MAX:
            return ERR_INVALID_OWNER          # deactivated: frozen set
        st.last_extended_slot = slot
        st.last_extended_start = len(st.addresses)
        st.addresses.extend(addrs)
        table.data = st.to_bytes()
        return OK

    if disc == IX_DEACTIVATE:
        if st.deactivation_slot != SLOT_MAX:
            return ERR_INVALID_OWNER
        st.deactivation_slot = slot
        table.data = st.to_bytes()
        return OK

    if disc == IX_CLOSE:
        if ic.n < 3 or not ic.is_writable(2):
            return ERR_BAD_IX_DATA
        if st.deactivation_slot == SLOT_MAX \
                or slot <= st.deactivation_slot:
            return ERR_INVALID_OWNER          # must be deactivated+cooled
        ic.account(2).lamports += table.lamports
        table.lamports = 0
        table.data = b""
        table.owner = bytes(32)
        return OK

    return ERR_UNKNOWN_IX


# ---------------------------------------------------------------------------
# v0 resolution (the resolv tile's job, executor-side)
# ---------------------------------------------------------------------------

class AlutResolveError(ValueError):
    pass


def resolve_loaded_keys(db, xid, txn, slot: int = 0):
    """v0 txn -> (extra_keys, extra_writable_flags): table-loaded
    addresses in wire order (each table's writables, then each table's
    readonlys — Agave's LoadedAddresses layout). Raises on a missing/
    foreign/deactivated table or an out-of-range index."""
    w_keys: list[bytes] = []
    ro_keys: list[bytes] = []
    for tkey, w_idxs, ro_idxs in txn.aluts:
        acct = db.peek(xid, tkey)
        if acct is None or acct.owner != ALUT_PROGRAM_ID:
            raise AlutResolveError("missing lookup table")
        try:
            st = AlutState.from_bytes(acct.data)
        except ValueError as e:
            raise AlutResolveError(f"malformed lookup table: {e}")
        if not st.is_active(slot):
            raise AlutResolveError("deactivated lookup table")
        for i in w_idxs:
            if i >= len(st.addresses):
                raise AlutResolveError("lookup index out of range")
            w_keys.append(st.addresses[i])
        for i in ro_idxs:
            if i >= len(st.addresses):
                raise AlutResolveError("lookup index out of range")
            ro_keys.append(st.addresses[i])
    keys = w_keys + ro_keys
    flags = [True] * len(w_keys) + [False] * len(ro_keys)
    return keys, flags
