"""BPF upgradeable loader: the program deploy path.

Subset of the reference's loader-v3 program
(ref: src/flamenco/runtime/program/fd_bpf_loader_program.c —
InitializeBuffer/Write/Deploy/Upgrade/SetAuthority/Close with the
UpgradeableLoaderState account indirection): a BUFFER account collects
the ELF via Write instructions, Deploy moves it into a PROGRAMDATA
account and marks the PROGRAM account executable; execution then
dereferences program -> programdata (svm/programs.py dispatch).

State layouts (bincode enum, Agave's):
  Buffer      u32 1 | Option<authority Pubkey>
  Program     u32 2 | programdata_address 32
  ProgramData u32 3 | slot u64 | Option<upgrade_authority Pubkey>
ProgramData's ELF starts at byte 45 (4 + 8 + 1 + 32)."""
from __future__ import annotations

import struct

from ..pack.cost import BPF_UPGRADEABLE_LOADER_ID

BUFFER_META_SZ = 37           # 4 disc + 1 opt + 32 authority
PROGRAMDATA_META_SZ = 45      # 4 disc + 8 slot + 1 opt + 32 authority

IX_INIT_BUFFER = 0
IX_WRITE = 1
IX_DEPLOY = 2
IX_UPGRADE = 3
IX_SET_AUTHORITY = 4
IX_CLOSE = 5

ST_UNINIT, ST_BUFFER, ST_PROGRAM, ST_PROGRAMDATA = 0, 1, 2, 3


def buffer_state(authority: bytes | None) -> bytes:
    return struct.pack("<I", ST_BUFFER) + (
        b"\x01" + authority if authority else b"\x00" + bytes(32))


def program_state(programdata: bytes) -> bytes:
    return struct.pack("<I", ST_PROGRAM) + programdata


def programdata_state(slot: int, authority: bytes | None) -> bytes:
    return struct.pack("<IQ", ST_PROGRAMDATA, slot) + (
        b"\x01" + authority if authority else b"\x00" + bytes(32))


def parse_state(data: bytes) -> tuple[int, dict]:
    if len(data) < 4:
        raise ValueError("short loader state")
    disc, = struct.unpack_from("<I", data, 0)
    if disc == ST_BUFFER:
        if len(data) < BUFFER_META_SZ:
            raise ValueError("short buffer state")
        auth = data[5:37] if data[4] else None
        return disc, {"authority": auth, "elf": data[BUFFER_META_SZ:]}
    if disc == ST_PROGRAM:
        if len(data) < 36:
            raise ValueError("short program state")
        return disc, {"programdata": data[4:36]}
    if disc == ST_PROGRAMDATA:
        if len(data) < PROGRAMDATA_META_SZ:
            raise ValueError("short programdata state")
        slot, = struct.unpack_from("<Q", data, 4)
        auth = data[13:45] if data[12] else None
        return disc, {"slot": slot, "authority": auth,
                      "elf": data[PROGRAMDATA_META_SZ:]}
    return disc, {}


def ix_init_buffer() -> bytes:
    return struct.pack("<I", IX_INIT_BUFFER)


def ix_write(offset: int, chunk: bytes) -> bytes:
    return struct.pack("<II", IX_WRITE, offset) \
        + struct.pack("<Q", len(chunk)) + chunk


def ix_deploy(max_data_len: int) -> bytes:
    return struct.pack("<IQ", IX_DEPLOY, max_data_len)


def ix_upgrade() -> bytes:
    return struct.pack("<I", IX_UPGRADE)


def exec_upgradeable_loader(ic) -> str:
    """Accounts per instruction:
      InitializeBuffer [buffer, authority]
      Write            [buffer, authority(signer)]
      Deploy           [program, programdata, buffer, authority(signer)]
      Upgrade          [programdata, program, buffer, authority(signer)]
    """
    from .programs import (
        ERR_BAD_IX_DATA, ERR_INVALID_OWNER, ERR_MISSING_SIG,
        ERR_NOT_WRITABLE, ERR_UNKNOWN_IX, OK,
    )
    data = ic.data
    if len(data) < 4 or ic.n < 2:
        return ERR_BAD_IX_DATA
    disc, = struct.unpack_from("<I", data, 0)

    if disc == IX_INIT_BUFFER:
        buf = ic.account(0)
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        if buf.owner != BPF_UPGRADEABLE_LOADER_ID or (
                buf.data and any(buf.data[:4])):
            return ERR_INVALID_OWNER
        buf.data = buffer_state(ic.key(1))
        return OK

    if disc == IX_WRITE:
        if len(data) < 16:
            return ERR_BAD_IX_DATA
        offset, = struct.unpack_from("<I", data, 4)
        ln, = struct.unpack_from("<Q", data, 8)
        chunk = data[16:16 + ln]
        if len(chunk) != ln:
            return ERR_BAD_IX_DATA
        from .programs import MAX_PERMITTED_DATA_LENGTH
        if offset + ln > MAX_PERMITTED_DATA_LENGTH:
            # a u32 offset must not drive a multi-GiB allocation
            return ERR_BAD_IX_DATA
        buf = ic.account(0)
        if buf.owner != BPF_UPGRADEABLE_LOADER_ID:
            return ERR_INVALID_OWNER
        st, info = parse_state(buf.data)
        if st != ST_BUFFER or info["authority"] is None:
            return ERR_INVALID_OWNER
        if info["authority"] != ic.key(1) or not ic.is_signer(1):
            return ERR_MISSING_SIG
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        body = bytearray(buf.data)
        end = BUFFER_META_SZ + offset + ln
        if end > len(body):
            body += bytes(end - len(body))
        body[BUFFER_META_SZ + offset:end] = chunk
        buf.data = bytes(body)
        return OK

    if disc in (IX_DEPLOY, IX_UPGRADE):
        if ic.n < 4:
            return ERR_BAD_IX_DATA
        if disc == IX_DEPLOY:
            prog_i, pdata_i, buf_i, auth_i = 0, 1, 2, 3
        else:
            pdata_i, prog_i, buf_i, auth_i = 0, 1, 2, 3
        prog = ic.account(prog_i)
        pdata = ic.account(pdata_i)
        buf = ic.account(buf_i)
        if not ic.is_signer(auth_i):
            return ERR_MISSING_SIG
        if not (ic.is_writable(prog_i) and ic.is_writable(pdata_i)
                and ic.is_writable(buf_i)):
            return ERR_NOT_WRITABLE
        if buf.owner != BPF_UPGRADEABLE_LOADER_ID:
            return ERR_INVALID_OWNER
        bst, binfo = parse_state(buf.data)
        if bst != ST_BUFFER or binfo["authority"] != ic.key(auth_i):
            return ERR_INVALID_OWNER
        elf = binfo["elf"]
        if not elf:
            return ERR_BAD_IX_DATA
        if disc == IX_DEPLOY:
            if len(data) < 12:
                return ERR_BAD_IX_DATA
            max_data_len, = struct.unpack_from("<Q", data, 4)
            if prog.owner != BPF_UPGRADEABLE_LOADER_ID \
                    or pdata.owner != BPF_UPGRADEABLE_LOADER_ID:
                return ERR_INVALID_OWNER
            if prog.data and any(prog.data[:4]):
                return ERR_INVALID_OWNER      # already deployed
            # programdata must be UNINITIALIZED: deploying into a live
            # programdata would hijack whatever program dereferences it
            if pdata.data and any(pdata.data[:4]):
                return ERR_INVALID_OWNER
            if len(elf) > max_data_len \
                    or max_data_len > 10 * 1024 * 1024:
                return ERR_BAD_IX_DATA
        else:
            # upgrade: the PROGRAM must be loader-owned, its Program
            # state must point at THIS programdata (no repointing an
            # arbitrary writable account), and the programdata's
            # upgrade authority must be the signer
            if prog.owner != BPF_UPGRADEABLE_LOADER_ID:
                return ERR_INVALID_OWNER
            try:
                prst, prinfo = parse_state(prog.data)
            except ValueError:
                return ERR_INVALID_OWNER
            if prst != ST_PROGRAM \
                    or prinfo["programdata"] != ic.key(pdata_i):
                return ERR_INVALID_OWNER
            pst, pinfo = parse_state(pdata.data)
            if pst != ST_PROGRAMDATA \
                    or pinfo["authority"] != ic.key(auth_i):
                return ERR_INVALID_OWNER
            # the new ELF must fit the deploy-time allocation
            if len(elf) > len(pdata.data) - PROGRAMDATA_META_SZ:
                return ERR_BAD_IX_DATA
        # pre-validate the ELF so a broken deploy fails the TXN, not
        # later executions (the reference verifies at deploy too)
        from ..vm import elf as elf_mod
        try:
            elf_mod.load(bytes(elf))
        except elf_mod.ElfError:
            return ERR_BAD_IX_DATA
        if disc == IX_DEPLOY:
            # allocate to max_data_len (the sizing contract Upgrade
            # bounds against)
            body = bytes(elf) + bytes(max_data_len - len(elf))
        else:
            alloc = len(pdata.data) - PROGRAMDATA_META_SZ
            body = bytes(elf) + bytes(alloc - len(elf))
        pdata.data = programdata_state(ic.ctx.slot,
                                       ic.key(auth_i)) + body
        if disc == IX_DEPLOY:
            prog.data = program_state(ic.key(pdata_i))
            prog.executable = True
        buf.data = struct.pack("<I", ST_UNINIT)   # buffer consumed
        return OK

    return ERR_UNKNOWN_IX


def resolve_program_elf(db, xid, program_acct) -> bytes | None:
    """program account -> its ELF bytes through the programdata
    indirection (the execution-path dereference)."""
    try:
        st, info = parse_state(program_acct.data)
    except ValueError:
        return None
    if st != ST_PROGRAM:
        return None
    pd = db.peek(xid, info["programdata"])
    if pd is None or pd.owner != BPF_UPGRADEABLE_LOADER_ID:
        return None
    try:
        pst, pinfo = parse_state(pd.data)
    except ValueError:
        return None
    if pst != ST_PROGRAMDATA:
        return None
    return pinfo["elf"]
