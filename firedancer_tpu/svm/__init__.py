from .executor import (SystemTxn, execute_block, execute_block_serial,  # noqa: F401
                       STATUS_OK, STATUS_INSUFFICIENT, STATUS_FEE_FAIL)
