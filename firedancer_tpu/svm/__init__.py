from .accdb import AccDb, Account, RwHandle  # noqa: F401
from .executor import (SystemTxn, execute_block, execute_block_serial,  # noqa: F401
                       STATUS_OK, STATUS_INSUFFICIENT, STATUS_FEE_FAIL)
from .programs import TxnExecutor, TxnResult  # noqa: F401
from .txncache import MAX_CACHE_AGE_SLOTS, TxnCache  # noqa: F401
