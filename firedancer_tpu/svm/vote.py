"""Native vote program (ref: src/flamenco/runtime/program/
fd_vote_program.c — subset: InitializeAccount, Vote, Withdraw; vote
state per src/flamenco/types vote state layout, re-shaped).

The on-chain tower state IS choreo's TowerBFT tower (choreo/tower.py):
a Vote instruction pushes slots through the same expiry/doubling/
rooting rules the consensus layer uses, credits accrue per rooted slot,
and the serialized account state round-trips through a compact struct
layout (not Solana bincode — the layout is this framework's own; the
SEMANTICS follow the reference).

State layout (little-endian):
  node_pubkey 32 | authorized_voter 32 | authorized_withdrawer 32 |
  commission u8 | root_slot u64 (2^64-1 = none) | credits u64 |
  last_ts u64 | vote_cnt u16 | votes: (slot u64 | conf u32)* |
  [optional trailer, r4:] ec_cnt u16 | (epoch u64 | credits u64 |
  prev_credits u64)*  — the epoch-credits history Agave keeps on the
  vote state (ref: fd_vote_program epoch_credits), consumed by the
  epoch-rewards points calculation (flamenco/rewards.py). Absent on
  pre-r4 blobs; from_bytes treats a missing trailer as empty history.
"""
from __future__ import annotations

import struct

from ..choreo.tower import Tower, TowerVote

VOTE_PROGRAM_ID = b"Vote" + bytes(28)
NO_ROOT = (1 << 64) - 1

# Agave VoteInstruction enum discriminants (r5 wire parity; ref
# src/flamenco/runtime/program/fd_vote_program.c instruction decode —
# the subset this program implements)
VOTE_IX_INITIALIZE = 0         # VoteInit {node, voter, withdrawer, u8}
VOTE_IX_AUTHORIZE = 1          # Pubkey + u32 VoteAuthorize kind
VOTE_IX_VOTE = 2               # Vote {slots: Vec<u64>, hash, Opt<i64>}
VOTE_IX_WITHDRAW = 3           # u64 lamports
VOTE_IX_UPDATE_COMMISSION = 5  # u8 commission
VOTE_IX_TOWER_SYNC = 14        # TowerSync {lockouts, root, hash, ts,
                               #            block_id}
AUTH_KIND_VOTER = 0
AUTH_KIND_WITHDRAWER = 1

_HDR = "<32s32s32sBQQQH"
_HDR_SZ = struct.calcsize(_HDR)


class VoteState:
    def __init__(self, node_pubkey: bytes, authorized_voter: bytes,
                 authorized_withdrawer: bytes, commission: int = 0):
        self.node_pubkey = node_pubkey
        self.authorized_voter = authorized_voter
        self.authorized_withdrawer = authorized_withdrawer
        self.commission = commission
        self.tower = Tower()
        self.root_slot: int | None = None
        self.credits = 0
        self.last_ts = 0
        # (epoch, cumulative credits at epoch end, cumulative at the
        # previous epoch's end) — newest LAST, capped at 64 entries
        self.epoch_credits: list[tuple[int, int, int]] = []

    # -- serialization ------------------------------------------------------

    def to_bytes(self) -> bytes:
        out = struct.pack(
            _HDR, self.node_pubkey, self.authorized_voter,
            self.authorized_withdrawer, self.commission,
            NO_ROOT if self.root_slot is None else self.root_slot,
            self.credits, self.last_ts, len(self.tower.votes))
        for v in self.tower.votes:
            out += struct.pack("<QI", v.slot, v.conf)
        out += struct.pack("<H", len(self.epoch_credits))
        for ep, cr, prev in self.epoch_credits:
            out += struct.pack("<QQQ", ep, cr, prev)
        return out

    @classmethod
    def from_bytes(cls, b: bytes) -> "VoteState":
        (node, voter, wd, comm, root, credits, ts, cnt) = \
            struct.unpack_from(_HDR, b, 0)
        st = cls(node, voter, wd, comm)
        st.root_slot = None if root == NO_ROOT else root
        st.credits = credits
        st.last_ts = ts
        off = _HDR_SZ
        for _ in range(cnt):
            slot, conf = struct.unpack_from("<QI", b, off)
            st.tower.votes.append(TowerVote(slot, conf))
            off += 12
        st.tower.root = st.root_slot
        if off + 2 <= len(b):            # r4 epoch-credits trailer
            (ec_cnt,) = struct.unpack_from("<H", b, off)
            off += 2
            for _ in range(ec_cnt):
                ep, cr, prev = struct.unpack_from("<QQQ", b, off)
                st.epoch_credits.append((ep, cr, prev))
                off += 24
        return st

    # -- semantics ----------------------------------------------------------

    def _increment_credits(self, epoch: int):
        """Agave vote_state::increment_credits: per-epoch history with
        a 64-entry cap, cumulative + previous-cumulative per entry."""
        if not self.epoch_credits:
            # Agave vote_state::increment_credits seeds the empty
            # history with (epoch, 0, 0) — pre-existing credits must
            # not inflate the first rewarded epoch's earned delta.
            self.epoch_credits.append((epoch, 0, 0))
        elif self.epoch_credits[-1][0] != epoch:
            _, cr, prev = self.epoch_credits[-1]
            if cr != prev:
                self.epoch_credits.append((epoch, cr, cr))
                if len(self.epoch_credits) > 64:
                    self.epoch_credits.pop(0)
            else:
                # the open entry earned nothing: move it to the new
                # epoch in place instead of appending, so empty epochs
                # never consume 64-entry window slots (Agave
                # vote_state::increment_credits "else just move the
                # current epoch" branch — an appending impl diverges
                # from Agave's history for vote accounts with quiet
                # epochs, and the rewards calc reads this window)
                self.epoch_credits[-1] = (epoch, cr, prev)
        self.credits += 1
        ep, cr, prev = self.epoch_credits[-1]
        self.epoch_credits[-1] = (ep, cr + 1, prev)

    def apply_vote(self, slots: list[int], timestamp: int = 0,
                   epoch: int = 0) -> int:
        """Push new vote slots (ascending, > last voted); returns the
        number of newly-rooted slots (credits accrue per root —
        ref: vote credits on root advance; epoch feeds the
        epoch-credits history the rewards calculation reads)."""
        rooted = 0
        last = self.tower.votes[-1].slot if self.tower.votes else -1
        for s in slots:
            if s <= last:
                continue            # stale/duplicate slots are skipped
            r = self.tower.vote(s)
            if r is not None:
                self.root_slot = r
                self._increment_credits(epoch)
                rooted += 1
            last = s
        if timestamp > self.last_ts:
            self.last_ts = timestamp
        return rooted


# -- instruction encoding ----------------------------------------------------

def ix_initialize(node_pubkey: bytes, authorized_voter: bytes,
                  authorized_withdrawer: bytes,
                  commission: int = 0) -> bytes:
    return (struct.pack("<I", VOTE_IX_INITIALIZE) + node_pubkey
            + authorized_voter + authorized_withdrawer
            + bytes([commission]))


def _opt_i64(v: int | None) -> bytes:
    return b"\x00" if v is None else b"\x01" + struct.pack("<q", v)


def ix_vote(slots: list[int], block_hash: bytes = bytes(32),
            timestamp: int | None = None) -> bytes:
    """VoteInstruction::Vote — bincode: u32 disc 2, Vec<u64> slots
    (u64 length), 32-byte hash, Option<i64> timestamp."""
    out = struct.pack("<IQ", VOTE_IX_VOTE, len(slots))
    for s in slots:
        out += struct.pack("<Q", s)
    return out + block_hash + _opt_i64(timestamp)


def ix_tower_sync(lockouts: list[tuple[int, int]], root: int | None,
                  block_hash: bytes, block_id: bytes,
                  timestamp: int | None = None) -> bytes:
    """VoteInstruction::TowerSync — bincode: u32 disc 14, Vec<Lockout>
    {u64 slot, u32 confirmation_count}, Option<u64> root, hash,
    Option<i64> timestamp, block_id."""
    out = struct.pack("<IQ", VOTE_IX_TOWER_SYNC, len(lockouts))
    for slot, conf in lockouts:
        out += struct.pack("<QI", slot, conf)
    out += b"\x00" if root is None else b"\x01" + struct.pack("<Q", root)
    return out + block_hash + _opt_i64(timestamp) + block_id


def ix_withdraw(lamports: int) -> bytes:
    return struct.pack("<IQ", VOTE_IX_WITHDRAW, lamports)


# -- executor hook (called from programs.TxnExecutor) ------------------------

def exec_vote(ic) -> str:
    """ic: programs.InstrCtx — local account indices, invocation-level
    privileges (top-level txn bits, or CPI-validated metas)."""
    from .programs import (
        ERR_BAD_IX_DATA, ERR_INSUFFICIENT, ERR_INVALID_OWNER,
        ERR_MISSING_SIG, ERR_NOT_WRITABLE, OK,
    )
    data = ic.data
    if len(data) < 4:
        return ERR_BAD_IX_DATA
    disc = struct.unpack_from("<I", data, 0)[0]
    if ic.n < 1:
        return ERR_BAD_IX_DATA
    acct = ic.account(0)

    if disc == VOTE_IX_INITIALIZE:
        if len(data) < 4 + 96 + 1:
            return ERR_BAD_IX_DATA
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        if acct.owner != VOTE_PROGRAM_ID or acct.data.strip(b"\x00"):
            return ERR_INVALID_OWNER      # must be fresh + vote-owned
        # the NODE identity must sign initialization, or anyone could
        # hijack a freshly-created vote account by installing their own
        # authorities (ref: vote program InitializeAccount requires the
        # node pubkey signature)
        node = data[4:36]
        if node not in ic.signer_keys():
            return ERR_MISSING_SIG
        st = VoteState(node, data[36:68], data[68:100], data[100])
        acct.data = st.to_bytes()
        return OK

    if acct.owner != VOTE_PROGRAM_ID or len(acct.data) < _HDR_SZ:
        return ERR_INVALID_OWNER
    st = VoteState.from_bytes(acct.data)

    if disc in (VOTE_IX_VOTE, VOTE_IX_TOWER_SYNC):
        # bincode layouts (Agave VoteInstruction::Vote / ::TowerSync)
        try:
            off = 4
            (cnt,) = struct.unpack_from("<Q", data, off)
            off += 8
            if cnt == 0 or cnt > 64:
                return ERR_BAD_IX_DATA
            slots = []
            for _ in range(cnt):
                (s,) = struct.unpack_from("<Q", data, off)
                slots.append(s)
                off += 8 if disc == VOTE_IX_VOTE else 12  # + u32 conf
            if disc == VOTE_IX_TOWER_SYNC:
                if data[off]:                 # Option<u64> root
                    off += 9
                else:
                    off += 1
            off += 32                         # bank hash
            ts = None
            if data[off]:                     # Option<i64> timestamp
                (ts,) = struct.unpack_from("<q", data, off + 1)
                off += 9
            else:
                off += 1
            if disc == VOTE_IX_TOWER_SYNC:
                off += 32                     # block_id
            if off > len(data):
                return ERR_BAD_IX_DATA
        except (struct.error, IndexError):
            return ERR_BAD_IX_DATA
        # the AUTHORIZED VOTER must sign (ref: vote program authority
        # checks), not merely the vote account
        if st.authorized_voter not in ic.signer_keys():
            return ERR_MISSING_SIG
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        st.apply_vote(sorted(slots), ts or 0, epoch=ic.ctx.epoch)
        acct.data = st.to_bytes()
        return OK

    if disc == VOTE_IX_AUTHORIZE:
        if len(data) < 40:
            return ERR_BAD_IX_DATA
        new_auth = data[4:36]
        kind = struct.unpack_from("<I", data, 36)[0]
        # the CURRENT authority of that kind must sign (ref: vote
        # program authorize — voter changes need the voter OR the
        # withdrawer; withdrawer changes need the withdrawer)
        signers = ic.signer_keys()
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        if kind == AUTH_KIND_VOTER:
            if st.authorized_voter not in signers \
                    and st.authorized_withdrawer not in signers:
                return ERR_MISSING_SIG
            st.authorized_voter = new_auth
        elif kind == AUTH_KIND_WITHDRAWER:
            if st.authorized_withdrawer not in signers:
                return ERR_MISSING_SIG
            st.authorized_withdrawer = new_auth
        else:
            return ERR_BAD_IX_DATA
        acct.data = st.to_bytes()
        return OK

    if disc == VOTE_IX_UPDATE_COMMISSION:
        if len(data) < 5:
            return ERR_BAD_IX_DATA
        if st.authorized_withdrawer not in ic.signer_keys():
            return ERR_MISSING_SIG
        if not ic.is_writable(0):
            return ERR_NOT_WRITABLE
        st.commission = data[4]
        acct.data = st.to_bytes()
        return OK

    if disc == VOTE_IX_WITHDRAW:
        if len(data) < 12 or ic.n < 2:
            return ERR_BAD_IX_DATA
        lamports = struct.unpack_from("<Q", data, 4)[0]
        if st.authorized_withdrawer not in ic.signer_keys():
            return ERR_MISSING_SIG
        if not ic.is_writable(0) or not ic.is_writable(1):
            return ERR_NOT_WRITABLE
        if lamports > acct.lamports:
            return ERR_INSUFFICIENT
        acct.lamports -= lamports
        ic.account(1).lamports += lamports
        return OK

    return ERR_BAD_IX_DATA
