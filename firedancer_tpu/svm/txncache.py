"""Transaction status cache (ref: src/flamenco/runtime/fd_txncache.c).

Consensus requires that a transaction executes at most once while its
recent-blockhash is still valid (~150 slots / MAX_RECENT_BLOCKHASHES).
The status cache records every executed signature keyed by
(blockhash, signature) with the slot it executed in; a query is a hit
only if that slot is on the querying fork (ancestor set), so competing
forks each see exactly their own history — same fork discipline as funk.

root-slot registration prunes blockhashes whose newest insertion is
older than the age window, bounding memory like the reference's
fixed-footprint pools.
"""
from __future__ import annotations

MAX_CACHE_AGE_SLOTS = 150   # blockhash validity window (consensus)


class TxnCache:
    def __init__(self, max_age_slots: int = MAX_CACHE_AGE_SLOTS):
        self.max_age = max_age_slots
        # blockhash -> {signature -> [(slot, status), ...]}
        self._by_hash: dict[bytes, dict[bytes, list[tuple[int, int]]]] = {}
        # blockhash -> newest slot inserted (prune index)
        self._newest: dict[bytes, int] = {}
        self.root_slot = 0

    def insert(self, slot: int, blockhash: bytes, sig: bytes,
               status: int = 0):
        sigs = self._by_hash.setdefault(blockhash, {})
        sigs.setdefault(sig, []).append((slot, status))
        if slot > self._newest.get(blockhash, -1):
            self._newest[blockhash] = slot

    def query(self, blockhash: bytes, sig: bytes,
              ancestors) -> int | None:
        """Status if `sig` executed under `blockhash` on this fork.
        `ancestors`: container (or callable) deciding slot-on-fork;
        slots <= the root are always on every fork (published
        history)."""
        entries = self._by_hash.get(blockhash, {}).get(sig)
        if not entries:
            return None
        on_fork = ancestors if callable(ancestors) \
            else (lambda s: s in ancestors)
        for slot, status in entries:
            if slot <= self.root_slot or on_fork(slot):
                return status
        return None

    def register_root(self, root_slot: int, rooted_slots=None):
        """Advance the root. `rooted_slots`: the slots that became
        rooted history with this advance (the rooted fork's chain);
        entries recorded in (old_root, new_root] on OTHER (abandoned)
        forks are purged, so they can never shadow the rooted fork's
        view once `slot <= root` makes history globally visible
        (ref: fd_txncache root registration / purge). Passing None
        keeps every entry (single-fork callers). Blockhashes whose
        newest slot fell out of the age window are pruned wholesale."""
        old_root = self.root_slot
        self.root_slot = max(self.root_slot, root_slot)
        if rooted_slots is not None:
            on_chain = rooted_slots if callable(rooted_slots) \
                else (lambda s: s in rooted_slots)
            for sigs in self._by_hash.values():
                for sig, entries in list(sigs.items()):
                    kept = [(s, st) for s, st in entries
                            if not (old_root < s <= self.root_slot
                                    and not on_chain(s))]
                    if kept:
                        sigs[sig] = kept
                    else:
                        del sigs[sig]
        dead = [bh for bh, newest in self._newest.items()
                if newest + self.max_age < self.root_slot]
        for bh in dead:
            del self._by_hash[bh]
            del self._newest[bh]

    def __len__(self):
        return sum(len(v) for v in self._by_hash.values())
