"""Account DB facade over funk (ref: src/flamenco/accdb/fd_accdb_user.h
— the peek/open_ro/open_rw/close vtable, fork depth <= 128).

Accounts are typed records (lamports, data, owner, executable,
rent_epoch — the Solana account shape, ref: src/flamenco/types account
meta) stored as funk record values, so every fork/publish/cancel
semantic is inherited from the funk transaction tree.

Handle discipline mirrors the vtable: peek is a borrow (no copy —
callers must not mutate), open_ro a defensive copy, open_rw a
copy-on-write handle that only lands in the fork on close_rw (so a
failed transaction simply drops its handles — the runtime's rollback
unit). Active-handle counts are tracked like the reference's
rw_active/ro_active for leak detection in tests.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace

from ..funk.funk import key32

DEPTH_MAX = 128                      # ref: FD_ACCDB_DEPTH_MAX
SYSTEM_PROGRAM_ID = bytes(32)


@dataclass
class Account:
    lamports: int = 0
    data: bytes = b""
    owner: bytes = SYSTEM_PROGRAM_ID
    executable: bool = False
    rent_epoch: int = 0


@dataclass
class RwHandle:
    pubkey: bytes
    xid: object
    account: Account
    created: bool = False
    _closed: bool = field(default=False, repr=False)


class AccDb:
    def __init__(self, funk):
        self.funk = funk
        self.ro_active = 0
        self.rw_active = 0

    # -- reads --------------------------------------------------------------

    def peek(self, xid, pubkey: bytes) -> Account | None:
        """Borrow: the caller MUST NOT mutate or hold across a write
        (ref: fd_accdb_peek_t semantics). Legacy bare-int records (the
        genesis lamports path) read as balance-only system Accounts, so
        a funded key is never mistaken for absent — an open_rw over one
        upgrades it to a typed record on close."""
        v = self.funk.rec_query(xid, pubkey)
        if isinstance(v, Account):
            return v
        if isinstance(v, int):
            return Account(lamports=v)
        return None

    def open_ro(self, xid, pubkey: bytes) -> Account | None:
        acct = self.peek(xid, pubkey)
        if acct is None:
            return None
        self.ro_active += 1
        return replace(acct)

    def close_ro(self, acct: Account):
        self.ro_active -= 1

    # -- writes -------------------------------------------------------------

    def open_rw(self, xid, pubkey: bytes,
                do_create: bool = False) -> RwHandle | None:
        """Copy-on-write handle; mutations land in fork `xid` only on
        close_rw. do_create materializes a fresh system account
        (ref: open_rw's do_create flag)."""
        acct = self.peek(xid, pubkey)
        created = False
        if acct is None:
            if not do_create:
                return None
            acct = Account()
            created = True
        self.rw_active += 1
        return RwHandle(pubkey, xid, replace(acct), created)

    def close_rw(self, h: RwHandle, discard: bool = False):
        if h._closed:
            raise RuntimeError("double close of rw handle")
        h._closed = True
        self.rw_active -= 1
        if not discard:
            self.funk.rec_write(h.xid, key32(h.pubkey), h.account)

    # -- convenience (the hot SVM path) -------------------------------------

    def lamports(self, xid, pubkey: bytes) -> int:
        a = self.peek(xid, pubkey)
        return 0 if a is None else a.lamports

    def set_lamports(self, xid, pubkey: bytes, lamports: int):
        """Fast-path balance commit used by the wave executor: preserves
        the rest of the account record, creating system accounts on
        first credit."""
        a = self.peek(xid, pubkey)
        a = Account() if a is None else replace(a)
        a.lamports = lamports
        self.funk.rec_write(xid, key32(pubkey), a)


def commit_lamports(funk, xid, pubkey: bytes, lamports: int,
                    typed: bool, prior):
    """THE one place deciding the funk value convention for balance
    commits (the wave executor's write-back). typed mode (any account in
    the block is accdb-typed) always lands Account records — including
    creations and upgrades of legacy int records, which carry only a
    balance; legacy mode (pure-int block) keeps bare lamport ints."""
    if typed:
        rec = replace(prior, lamports=lamports) \
            if isinstance(prior, Account) else Account(lamports=lamports)
    else:
        rec = lamports
    funk.rec_write(xid, key32(pubkey), rec)
