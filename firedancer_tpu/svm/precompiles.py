"""Precompile programs: ed25519 + secp256k1 signature verification.

The reference verifies precompile instructions before execution
(ref: src/flamenco/runtime/fd_precompiles.c — fd_precompile_ed25519_
verify / fd_precompile_secp256k1_verify, instruction layouts per the
Agave wire structs). Both programs carry OFFSETS into (possibly other)
instructions' data, so verification reads through the whole message.

ed25519 instruction data:
  u8 count | u8 pad | count x { sig_off u16 | sig_ix u16 |
  pub_off u16 | pub_ix u16 | msg_off u16 | msg_sz u16 | msg_ix u16 }
  (ix 0xFFFF = "this instruction")

secp256k1 instruction data:
  u8 count | count x { sig_off u16 | sig_ix u8 | addr_off u16 |
  addr_ix u8 | msg_off u16 | msg_sz u16 | msg_ix u8 }
  signature = 64 bytes r||s + 1 recovery byte; the 20-byte eth address
  must equal keccak256(recovered_pubkey)[12:].
"""
from __future__ import annotations

import struct

# the REAL base58 ids — shared with the pack cost model so costing
# and dispatch always agree on what is a precompile
from ..pack.cost import (
    ED25519_SV_PROGRAM_ID as ED25519_PROGRAM_ID,
    KECCAK_SECP_PROGRAM_ID as SECP256K1_PROGRAM_ID,
    SECP256R1_PROGRAM_ID,
)

THIS_IX = 0xFFFF          # u16 marker (ed25519 layout)
THIS_IX_U8 = 0xFF         # u8 marker (secp256k1 layout)


def _instr_data(ctx, idx: int, this_data: bytes,
                marker: int) -> bytes | None:
    """marker is LAYOUT-SPECIFIC: 0xFFFF for the u16 ed25519 indexes,
    0xFF for the u8 secp256k1 indexes — 0x00FF is a REAL index in the
    u16 layout and must bounds-check like any other."""
    if idx == marker:
        return this_data
    if idx >= len(ctx.txn.instrs):
        return None
    ins = ctx.txn.instrs[idx]
    return ctx.payload[ins.data_off:ins.data_off + ins.data_sz]


def _slice(data: bytes | None, off: int, sz: int) -> bytes | None:
    if data is None or off + sz > len(data):
        return None
    return data[off:off + sz]


def exec_ed25519_precompile(ic) -> str:
    from ..utils.ed25519_ref import verify
    from .programs import ERR_BAD_IX_DATA, ERR_VM, OK
    data = ic.data
    if len(data) < 2:
        return ERR_BAD_IX_DATA
    count = data[0]
    need = 2 + 14 * count
    if len(data) < need:
        return ERR_BAD_IX_DATA
    for i in range(count):
        (sig_off, sig_ix, pub_off, pub_ix, msg_off, msg_sz,
         msg_ix) = struct.unpack_from("<HHHHHHH", data, 2 + 14 * i)
        sig = _slice(_instr_data(ic.ctx, sig_ix, data, THIS_IX),
                     sig_off, 64)
        pub = _slice(_instr_data(ic.ctx, pub_ix, data, THIS_IX),
                     pub_off, 32)
        msg = _slice(_instr_data(ic.ctx, msg_ix, data, THIS_IX),
                     msg_off, msg_sz)
        if sig is None or pub is None or msg is None:
            return ERR_BAD_IX_DATA
        if not verify(sig, pub, msg):
            ic.logs.append(f"ed25519 precompile: sig {i} invalid")
            return ERR_VM
    return OK


def exec_secp256k1_precompile(ic) -> str:
    from ..utils.keccak import keccak256
    from ..utils.secp256k1 import eth_address, recover
    from .programs import ERR_BAD_IX_DATA, ERR_VM, OK
    data = ic.data
    if len(data) < 1:
        return ERR_BAD_IX_DATA
    count = data[0]
    need = 1 + 11 * count
    if len(data) < need:
        return ERR_BAD_IX_DATA
    for i in range(count):
        (sig_off, sig_ix, addr_off, addr_ix, msg_off, msg_sz,
         msg_ix) = struct.unpack_from("<HBHBHHB", data, 1 + 11 * i)
        sig = _slice(_instr_data(ic.ctx, sig_ix, data, THIS_IX_U8),
                     sig_off, 65)
        addr = _slice(_instr_data(ic.ctx, addr_ix, data, THIS_IX_U8),
                     addr_off, 20)
        msg = _slice(_instr_data(ic.ctx, msg_ix, data, THIS_IX_U8),
                     msg_off, msg_sz)
        if sig is None or addr is None or msg is None:
            return ERR_BAD_IX_DATA
        r = int.from_bytes(sig[0:32], "big")
        s = int.from_bytes(sig[32:64], "big")
        q = recover(keccak256(msg), r, s, sig[64])
        if q is None or eth_address(q) != addr:
            ic.logs.append(f"secp256k1 precompile: sig {i} invalid")
            return ERR_VM
    return OK


def exec_secp256r1_precompile(ic) -> str:
    """SIMD-0075 P-256 precompile: same 14-byte offsets entry as the
    ed25519 layout (u16 indexes, 0xFFFF = this instruction), 33-byte
    SEC1 compressed pubkeys, 64-byte r‖s signatures with the low-s
    rule (ref: src/ballet/secp256r1/)."""
    from ..utils.secp256r1 import verify
    from .programs import ERR_BAD_IX_DATA, ERR_VM, OK
    data = ic.data
    if len(data) < 2:
        return ERR_BAD_IX_DATA
    count = data[0]
    # SIMD-0075: num_signatures MUST be 1..=8 (the reference rejects
    # out-of-range counts; agreeing here is consensus-critical)
    if count == 0 or count > 8:
        return ERR_BAD_IX_DATA
    need = 2 + 14 * count
    if len(data) < need:
        return ERR_BAD_IX_DATA
    for i in range(count):
        (sig_off, sig_ix, pub_off, pub_ix, msg_off, msg_sz,
         msg_ix) = struct.unpack_from("<HHHHHHH", data, 2 + 14 * i)
        sig = _slice(_instr_data(ic.ctx, sig_ix, data, THIS_IX),
                     sig_off, 64)
        pub = _slice(_instr_data(ic.ctx, pub_ix, data, THIS_IX),
                     pub_off, 33)
        msg = _slice(_instr_data(ic.ctx, msg_ix, data, THIS_IX),
                     msg_off, msg_sz)
        if sig is None or pub is None or msg is None:
            return ERR_BAD_IX_DATA
        if not verify(pub, msg, sig):
            ic.logs.append(f"secp256r1 precompile: sig {i} invalid")
            return ERR_VM
    return OK
