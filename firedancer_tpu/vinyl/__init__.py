"""vinyl: log-structured disk account store (ref: src/vinyl/)."""
from .vinyl import Vinyl, VinylError  # noqa: F401
