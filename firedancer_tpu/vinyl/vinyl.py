"""Vinyl: the disk-resident account store under funk's published root.

The reference's vinyl is a log-structured store driven by a dedicated
tile over a bstream (append-only record log) with crash recovery by
replaying the stream past the last sync point, plus GC/partitioning
thresholds (ref: src/vinyl/fd_vinyl.h:13-29 — the SYNC/GET/SET/GC
control verbs; src/groove/fd_groove.h:1-13 is the cold-store data
layer). This module is that design re-expressed host-side:

  * one append-only log file; every record CRC-framed
  * in-memory index {key -> (offset, len)} rebuilt by scanning on open
    (crash recovery: a torn tail fails its CRC and truncates there —
    the bstream "resume from the current past" discipline)
  * tombstones for deletes; `compact()` rewrites live records to a
    fresh log and atomically renames it in (the GC verb)
  * `sync()` fsyncs the log (the SYNC verb)

Account values serialize through the checkpoint codec (utils/checkpt),
so a vinyl log, a snapshot stream, and the funk root all speak the
same record encoding.

Record wire: u32 magic | u8 type (1 put, 2 del) | u16 klen | u32 vlen
| key | val | u32 crc32(over all prior fields).
"""
from __future__ import annotations

import os
import struct
import zlib

_MAGIC = 0xFD71A1C5
_PUT, _DEL = 1, 2
_HDR = struct.Struct("<IBHI")


class VinylError(RuntimeError):
    pass


class Vinyl:
    def __init__(self, path: str):
        self.path = path
        self.index: dict[bytes, tuple[int, int]] = {}
        self.live_bytes = 0
        self.dead_bytes = 0
        self._fp = open(path, "a+b")
        self._recover()

    # -- recovery -----------------------------------------------------------

    def _recover(self):
        """Scan the log, rebuild the index, truncate a torn tail."""
        self._fp.seek(0)
        off = 0
        data = self._fp.read()
        n = len(data)
        while off < n:
            if off + _HDR.size > n:
                break                        # torn header
            magic, typ, klen, vlen = _HDR.unpack_from(data, off)
            end = off + _HDR.size + klen + vlen + 4
            if magic != _MAGIC or typ not in (_PUT, _DEL) or end > n:
                break                        # torn/corrupt: stop here
            body = data[off:end - 4]
            (crc,) = struct.unpack_from("<I", data, end - 4)
            if zlib.crc32(body) & 0xFFFFFFFF != crc:
                break                        # torn tail
            key = data[off + _HDR.size:off + _HDR.size + klen]
            if typ == _PUT:
                old = self.index.get(key)
                if old is not None:
                    self.dead_bytes += old[1]
                    self.live_bytes -= old[1]
                self.index[key] = (off, end - off)
                self.live_bytes += end - off
            else:
                old = self.index.pop(key, None)
                if old is not None:
                    self.dead_bytes += old[1]
                    self.live_bytes -= old[1]
                self.dead_bytes += end - off
            off = end
        if off < n:
            # torn tail: truncate to the last good record boundary
            self._fp.truncate(off)
        self._end = off

    # -- ops ----------------------------------------------------------------

    def _append(self, typ: int, key: bytes, val: bytes) -> int:
        rec = _HDR.pack(_MAGIC, typ, len(key), len(val)) + key + val
        rec += struct.pack("<I", zlib.crc32(rec) & 0xFFFFFFFF)
        self._fp.seek(0, os.SEEK_END)
        off = self._fp.tell()
        self._fp.write(rec)
        self._end = off + len(rec)
        return off

    def put(self, key: bytes, val: bytes):
        if len(key) > 0xFFFF or len(val) > 0xFFFF_FFFF:
            raise VinylError("record too large")
        off = self._append(_PUT, key, val)
        old = self.index.get(key)
        if old is not None:
            self.dead_bytes += old[1]
            self.live_bytes -= old[1]
        sz = _HDR.size + len(key) + len(val) + 4
        self.index[key] = (off, sz)
        self.live_bytes += sz

    def get(self, key: bytes) -> bytes | None:
        ent = self.index.get(key)
        if ent is None:
            return None
        off, sz = ent
        self._fp.seek(off)
        rec = self._fp.read(sz)
        magic, typ, klen, vlen = _HDR.unpack_from(rec, 0)
        return rec[_HDR.size + klen:_HDR.size + klen + vlen]

    def delete(self, key: bytes):
        if key not in self.index:
            return
        self._append(_DEL, key, b"")
        off, sz = self.index.pop(key)
        self.dead_bytes += sz + _HDR.size + len(key) + 4
        self.live_bytes -= sz

    def sync(self):
        self._fp.flush()
        os.fsync(self._fp.fileno())

    def __len__(self):
        return len(self.index)

    def keys(self):
        return self.index.keys()

    # -- GC -----------------------------------------------------------------

    def compact(self):
        """Rewrite live records into a fresh log; atomic rename-in
        (the reference's GC pass)."""
        tmp = self.path + ".compact"
        new = Vinyl.__new__(Vinyl)
        new.path = tmp
        new.index = {}
        new.live_bytes = 0
        new.dead_bytes = 0
        new._fp = open(tmp, "w+b")
        new._end = 0
        for key in list(self.index):
            val = self.get(key)
            new.put(key, val)
        new.sync()
        self._fp.close()
        os.replace(tmp, self.path)
        self._fp = new._fp
        self.index = new.index
        self.live_bytes = new.live_bytes
        self.dead_bytes = 0
        self._end = new._end

    def maybe_compact(self, gc_thresh: float = 0.5):
        """Compact when dead bytes dominate (FD_VINYL_OPT_GC_THRESH)."""
        total = self.live_bytes + self.dead_bytes
        if total and self.dead_bytes / total > gc_thresh:
            self.compact()

    def close(self):
        self._fp.close()


# ---------------------------------------------------------------------------
# funk integration: the cold store under the published root
# ---------------------------------------------------------------------------

def store_root(funk, vinyl: Vinyl):
    """Write funk's published root through to vinyl (accounts encode
    via the checkpoint codec — one record format across snapshot,
    checkpt, and the cold store)."""
    from ..funk.funk import key32
    from ..utils.checkpt import _enc_val
    for key, val in funk.root_items().items():
        vinyl.put(key32(key), _enc_val(val))
    vinyl.sync()


def load_root(funk, vinyl: Vinyl):
    """Restore vinyl's contents into funk's root (boot path)."""
    from ..funk.funk import key32
    from ..utils.checkpt import _dec_val
    for key in vinyl.keys():
        if len(key) != 32:
            raise VinylError(
                f"corrupt vinyl: {len(key)}-byte record key (funk "
                f"keys are exactly 32) — refusing to install a root "
                f"record no other process could look up")
        funk.rec_write(None, key32(key), _dec_val(vinyl.get(key)))
