"""Solana snapshot archive formats: zstd stream -> tar -> AppendVec.

The reference's restore pipeline is a tile chain — snapct/snapld
(download/read), snapdc (zstd), snapin (tar + AppendVec parse into the
account DB) with a parallel lattice-hash verification fan-out
(ref: src/discof/restore/fd_snapin_tile.c:14-17, fd_snapct_tile.c,
snapla/snapls). This module provides the FORMAT layer those tiles
speak:

  * AppendVec: Agave's account-storage file layout, byte-compatible —
    per entry StoredMeta(write_version u64, data_len u64, pubkey 32) |
    AccountMeta(lamports u64, rent_epoch u64, owner 32, executable u8,
    7B pad) | stored hash 32 | data | pad to 8 (the 136-byte
    STORE_META_OVERHEAD; the hash field is vestigial in modern Agave
    and written as zeros, accepted as-is on read)
  * TarStream: incremental ustar parser (512-byte headers, NUL-name
    terminator) usable from a tile that receives the byte stream as
    ring frags
  * archive writer/reader: `<slot>/...` tar.zst with a version file, a
    minimal manifest (slot + accounts lattice checksum + appendvec
    list — the full Agave bank manifest is the 15k-line generated
    bincode surface, NOT reproduced; documented divergence), and one
    AppendVec per accounts file
  * restore verification: the restored accounts' lattice hash must
    match the manifest checksum (the snapla/snapls fan-in, one batched
    device lthash)
"""
from __future__ import annotations

import hashlib
import io
import struct
import tarfile

from ..funk.funk import key32
from ..svm.accdb import Account

STORED_META = struct.Struct("<QQ32s")          # write_version, dlen, key
ACCOUNT_META = struct.Struct("<QQ32sB7x")      # lamports, rent, owner, exec
STORED_HASH_SZ = 32                            # vestigial, zeros


def _pad8(n: int) -> int:
    return (-n) % 8


def write_append_vec(items) -> bytes:
    """[(pubkey, Account)] -> AppendVec bytes (Agave account storage
    entry layout; write_version is a monotonic counter)."""
    out = bytearray()
    for wv, (pk, a) in enumerate(items):
        out += STORED_META.pack(wv, len(a.data), pk)
        out += ACCOUNT_META.pack(a.lamports, a.rent_epoch, a.owner,
                                 1 if a.executable else 0)
        out += bytes(STORED_HASH_SZ)
        out += a.data
        out += bytes(_pad8(len(a.data)))
    return bytes(out)


def parse_append_vec(data: bytes) -> list:
    """AppendVec bytes -> [(pubkey, Account)] with bounds checking
    (hostile snapshots must fail cleanly, fd_snapin's stance)."""
    out = []
    off = 0
    n = len(data)
    hdr = STORED_META.size + ACCOUNT_META.size + STORED_HASH_SZ
    while off + hdr <= n:
        wv, dlen, pk = STORED_META.unpack_from(data, off)
        lam, rent, owner, execu = ACCOUNT_META.unpack_from(
            data, off + STORED_META.size)
        off += hdr
        if dlen > n - off:
            raise ValueError("append-vec entry data out of bounds")
        acct_data = bytes(data[off:off + dlen])
        off += dlen + _pad8(dlen)
        out.append((bytes(pk), Account(lam, acct_data, bytes(owner),
                                       bool(execu), rent)))
    if off < n and any(data[off:]):
        raise ValueError("trailing garbage in append-vec")
    return out


# ---------------------------------------------------------------------------
# incremental tar (ustar) parsing — tile-friendly
# ---------------------------------------------------------------------------

class TarStream:
    """Feed raw tar bytes in arbitrary chunk sizes; yields complete
    (name, payload) members. Zero-block terminator ends the stream."""

    def __init__(self):
        self._buf = bytearray()
        self.done = False

    def feed(self, chunk: bytes) -> list:
        """-> complete (name, payload) members unlocked by this chunk."""
        self._buf += chunk
        out = []
        while not self.done:
            if len(self._buf) < 512:
                break
            hdr = bytes(self._buf[:512])
            if hdr == bytes(512):
                self.done = True
                break
            name = hdr[:100].split(b"\x00")[0].decode("utf-8")
            size_field = hdr[124:136].split(b"\x00")[0].strip()
            size = int(size_field or b"0", 8)
            total = 512 + size + _pad512(size)
            if len(self._buf) < total:
                break
            payload = bytes(self._buf[512:512 + size])
            del self._buf[:total]
            if hdr[156:157] in (b"0", b"\x00"):    # regular file only
                out.append((name, payload))
        return out


def _pad512(n: int) -> int:
    return (-n) % 512


# ---------------------------------------------------------------------------
# archive write / restore
# ---------------------------------------------------------------------------

MANIFEST_MAGIC = b"FDTPUSNAP1"


def _manifest_bytes(slot: int, lt_checksum: bytes,
                    vec_names: list[str]) -> bytes:
    out = bytearray(MANIFEST_MAGIC)
    out += struct.pack("<Q", slot)
    out += lt_checksum
    out += struct.pack("<H", len(vec_names))
    for nm in vec_names:
        b = nm.encode()
        out += struct.pack("<H", len(b)) + b
    return bytes(out)


def _parse_manifest(b: bytes):
    if b[:len(MANIFEST_MAGIC)] != MANIFEST_MAGIC:
        raise ValueError("bad manifest magic")
    off = len(MANIFEST_MAGIC)
    (slot,) = struct.unpack_from("<Q", b, off)
    off += 8
    checksum = bytes(b[off:off + 32])
    off += 32
    (n,) = struct.unpack_from("<H", b, off)
    off += 2
    names = []
    for _ in range(n):
        (ln,) = struct.unpack_from("<H", b, off)
        off += 2
        names.append(b[off:off + ln].decode())
        off += ln
    return slot, checksum, names


def write_snapshot_archive(path: str, slot: int, funk,
                           accounts_per_vec: int = 1024):
    """funk root -> <path> (tar.zst): version | snapshots/<slot>/<slot>
    manifest | accounts/<slot>.N AppendVecs. The manifest records the
    accounts lattice checksum the restorer must reproduce. The tar
    streams through the zstd compressor (snapshots are multi-GB in
    production; peak memory stays one AppendVec, not the archive)."""
    import zstandard

    from .bank_hash import BankHasher, lthash_of_root
    items = sorted(
        ((k, v) for k, v in funk.root_items().items()
         if isinstance(v, Account)), key=lambda kv: kv[0])
    h = BankHasher(lthash_of_root(funk))
    vec_names = [f"accounts/{slot}.{i // accounts_per_vec}"
                 for i in range(0, max(len(items), 1),
                                accounts_per_vec)]
    manifest = _manifest_bytes(slot, h.checksum(), vec_names)
    with open(path, "wb") as f, \
            zstandard.ZstdCompressor(level=3).stream_writer(f) as zw, \
            tarfile.open(fileobj=zw, mode="w|",
                         format=tarfile.USTAR_FORMAT) as tf:
        def add(name, data):
            ti = tarfile.TarInfo(name)
            ti.size = len(data)
            tf.addfile(ti, io.BytesIO(data))
        add("version", b"1.2.0")
        add(f"snapshots/{slot}/{slot}", manifest)
        for vi, nm in enumerate(vec_names):
            i = vi * accounts_per_vec
            add(nm, write_append_vec(items[i:i + accounts_per_vec]))


class SnapshotRestorer:
    """Streaming restore: feed zstd-compressed chunks (the snapdc +
    snapin stages fused at the format level). Accounts accumulate in a
    STAGING area; `finish()` verifies the lattice checksum and only
    then installs them into the funk root — a tampered snapshot never
    leaves bad state behind (fd_snapin's stance)."""

    def __init__(self, funk, compressed: bool = True):
        """compressed=False when a snapdc stage upstream already
        inflated the stream (the tile pipeline split)."""
        self.funk = funk
        self._dctx = None
        if compressed:
            import zstandard
            self._dctx = zstandard.ZstdDecompressor().decompressobj()
        self._tar = TarStream()
        self.slot = None
        self._checksum = None
        self._expected_vecs: list[str] | None = None
        self._seen_vecs: set[str] = set()
        self._staging: dict[bytes, Account] = {}
        self.accounts = 0

    def feed(self, chunk: bytes):
        raw = self._dctx.decompress(chunk) if self._dctx else chunk
        if not raw:
            return
        for name, payload in self._tar.feed(raw):
            if name.startswith("snapshots/"):
                self.slot, self._checksum, self._expected_vecs = \
                    _parse_manifest(payload)
            elif name.startswith("accounts/"):
                self._seen_vecs.add(name)
                for pk, acct in parse_append_vec(payload):
                    self._staging[pk] = acct
                    self.accounts += 1

    def finish(self) -> bool:
        """True iff every manifest-listed vec arrived AND the staged
        accounts reproduce the manifest's lattice checksum — only a
        verified snapshot installs into the funk root."""
        from .bank_hash import BankHasher, accounts_lthash
        if self._expected_vecs is None:
            raise ValueError("no manifest in stream")
        if set(self._expected_vecs) - self._seen_vecs:
            return False
        got = BankHasher(
            accounts_lthash(self._staging.items())).checksum()
        if got != self._checksum:
            return False
        for pk, acct in self._staging.items():
            # zero-lamport entries are outside the lattice commitment:
            # installing them would let a tampered snapshot smuggle
            # unverified state past the checksum
            if acct.lamports == 0:
                continue
            self.funk.rec_write(None, key32(pk), acct)
        self._staging.clear()
        return True


def restore_snapshot(path: str, funk) -> tuple[int, bool]:
    """-> (slot, checksum_ok)."""
    r = SnapshotRestorer(funk)
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 16)
            if not chunk:
                break
            r.feed(chunk)
    return r.slot, r.finish()
