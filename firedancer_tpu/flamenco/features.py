"""Feature gates — on-chain activation switches for consensus changes.

The reference keeps a generated table of ~200 feature pubkeys and
resolves each to its activation slot from the feature accounts at
epoch boundaries (ref: src/flamenco/features/fd_features.h, generated
fd_features table; runtime checks via FD_FEATURE_ACTIVE). Same model
here: a feature is an account owned by the Feature program whose data
is `u8 option-tag | u64 activated_at_slot`; a gate is active at slot S
when its account says activated_at <= S.

The named set below covers the gates this runtime actually branches
on; unknown feature accounts are still readable through the generic
API so fixtures can carry real mainnet feature pubkeys.
"""
from __future__ import annotations

import struct

from ..funk.funk import key32
from ..svm.accdb import Account
from ..utils.base58 import b58_decode_32

FEATURE_PROGRAM_ID = b58_decode_32(
    "Feature111111111111111111111111111111111111")

# named gates this runtime branches on (real mainnet feature ids)
SECP256R1_PRECOMPILE = b58_decode_32(
    "sr11RdZWgbHTHxSroPALe6zgaT5A1K9LcE4nfsZS4gi")
PARTITIONED_EPOCH_REWARDS = b58_decode_32(
    "9bn2vTJUsUcnpiZWbu2woSKtTGW3ErZC9ERv88SDqQjK")

KNOWN = {
    "secp256r1_precompile": SECP256R1_PRECOMPILE,
    "partitioned_epoch_rewards": PARTITIONED_EPOCH_REWARDS,
}


def encode_feature(activated_at: int | None) -> bytes:
    """Agave Feature bincode: Option<u64> activated_at."""
    if activated_at is None:
        return b"\x00"
    return b"\x01" + struct.pack("<Q", activated_at)


def decode_feature(data: bytes) -> int | None:
    if not data or data[0] == 0:
        return None
    if len(data) < 9:
        return None
    return struct.unpack_from("<Q", data, 1)[0]


def activate(funk, xid, feature_id: bytes, slot: int):
    """Write the feature account as activated at `slot` (genesis/test
    plumbing; on a live cluster activation lands via governance)."""
    funk.rec_write(xid, key32(feature_id), Account(
        1, bytearray(encode_feature(slot)), FEATURE_PROGRAM_ID))


def activation_slot(db, xid, feature_id: bytes) -> int | None:
    acct = db.peek(xid, feature_id)
    if acct is None or acct.owner != FEATURE_PROGRAM_ID:
        return None
    return decode_feature(bytes(acct.data))


def is_active(db, xid, feature_id: bytes, slot: int) -> bool:
    at = activation_slot(db, xid, feature_id)
    return at is not None and at <= slot


class FeatureSet:
    """Slot-resolved snapshot of every named gate (the reference's
    fd_features_t: resolved once per epoch boundary, read hot)."""

    def __init__(self, db, xid, slot: int):
        self.slot = slot
        self.active = {
            name: is_active(db, xid, fid, slot)
            for name, fid in KNOWN.items()
        }

    def __getattr__(self, name: str) -> bool:
        try:
            return self.__dict__["active"][name]
        except KeyError:
            raise AttributeError(name) from None
