"""Solana gossip WIRE codec — the real cluster formats (VERDICT r4
item 4: "interop layer 1").

Everything here is byte-compatible with Agave's bincode layouts as
specified by the reference's zero-copy parser/serializer
(ref: src/flamenco/gossip/fd_gossip_msg_parse.c, fd_gossip_msg_ser.c,
fd_gossip_private.h) — each function cites the parse routine it
mirrors. The in-memory protocol logic (gossip/protocol.py, crds.py)
speaks THESE encodings on the UDP wire; two fdtpu nodes — or an fdtpu
node and a real cluster peer — exchange identical bytes.

Message envelope (u32 LE enum, fd_gossip_private.h:29-35):
  0 PullRequest(CrdsFilter, CrdsValue)
  1 PullResponse(from: Pubkey, Vec<CrdsValue>)
  2 PushMessage(from: Pubkey, Vec<CrdsValue>)
  3 PruneMessage(from: Pubkey, PruneData)
  4 Ping { from, token[32], signature }
  5 Pong { from, hash[32], signature }

CrdsValue = signature[64] ++ u32 LE tag ++ variant payload; the
signature covers (tag ++ payload) and the value identity hash is
sha256 over the whole serialized value (Agave CrdsValue semantics, as
consumed by fd_gossip_msg_crds_vals_parse:615-621).
"""
from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass, field

MTU = 1232                      # FD_GOSSIP_MTU
MSG_PULL_REQUEST = 0
MSG_PULL_RESPONSE = 1
MSG_PUSH = 2
MSG_PRUNE = 3
MSG_PING = 4
MSG_PONG = 5

# CRDS discriminants (fd_gossip_private.h:37-51)
V_LEGACY_CONTACT_INFO = 0
V_VOTE = 1
V_LOWEST_SLOT = 2
V_LEGACY_SNAPSHOT_HASHES = 3
V_ACCOUNT_HASHES = 4
V_EPOCH_SLOTS = 5
V_LEGACY_VERSION = 6
V_VERSION = 7
V_NODE_INSTANCE = 8
V_DUPLICATE_SHRED = 9
V_INC_SNAPSHOT_HASHES = 10
V_CONTACT_INFO = 11
V_RESTART_LAST_VOTED_FORK_SLOTS = 12
V_RESTART_HEAVIEST_FORK = 13

MAX_CRDS_PER_MSG = 18           # FD_GOSSIP_MSG_MAX_CRDS
VOTE_IDX_MAX = 32               # FD_GOSSIP_VOTE_IDX_MAX
WALLCLOCK_MAX_MS = 1_000_000_000_000_000

# ContactInfo socket tags (fd_gossip_types.h:47-61)
SOCKET_GOSSIP = 0
SOCKET_SERVE_REPAIR_QUIC = 1
SOCKET_RPC = 2
SOCKET_RPC_PUBSUB = 3
SOCKET_SERVE_REPAIR = 4
SOCKET_TPU = 5
SOCKET_TPU_FORWARDS = 6
SOCKET_TPU_FORWARDS_QUIC = 7
SOCKET_TPU_QUIC = 8
SOCKET_TPU_VOTE = 9
SOCKET_TVU = 10
SOCKET_TVU_QUIC = 11
SOCKET_TPU_VOTE_QUIC = 12
SOCKET_ALPENGLOW = 13
SOCKET_CNT = 14

CLIENT_FIREDANCER = 5           # FD_CONTACT_INFO_VERSION_CLIENT_*


class WireError(ValueError):
    pass


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------

def enc_varint(v: int) -> bytes:
    """LEB128 7-bit varint (serde_varint; decode mirror:
    fd_gossip_msg_parse.c decode_u64_varint)."""
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            return bytes(out)


def dec_varint(b: bytes, off: int) -> tuple[int, int]:
    value = 0
    shift = 0
    while off < len(b):
        byte = b[off]
        off += 1
        value |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return value, off
        shift += 7
        if shift >= 64:
            raise WireError("varint overlong")
    raise WireError("varint truncated")


# compact_u16 is the same 7-bit groups scheme capped at 3 bytes
enc_cu16 = enc_varint


def dec_cu16(b: bytes, off: int) -> tuple[int, int]:
    v, end = dec_varint(b, off)
    if end - off > 3 or v > 0xFFFF:
        raise WireError("compact_u16 out of range")
    return v, end


def _ip4(addr: str | int) -> int:
    if isinstance(addr, int):
        return addr
    p = [int(x) for x in addr.split(".")]
    return p[0] | (p[1] << 8) | (p[2] << 16) | (p[3] << 24)  # LE u32 load


def _ip4_str(v: int) -> str:
    return ".".join(str((v >> (8 * i)) & 0xFF) for i in range(4))


# ---------------------------------------------------------------------------
# CRDS variant payloads
# ---------------------------------------------------------------------------

@dataclass
class ContactInfo:
    """CrdsData::ContactInfo(11) — the v2 contact info
    (fd_gossip_msg_crds_contact_info_parse). sockets: tag -> (ip4
    dotted-quad or int, port host-order)."""
    pubkey: bytes
    wallclock_ms: int
    outset_us: int = 0            # instance creation, micros
    shred_version: int = 0
    version: tuple = (0, 6, 0)    # (major, minor, patch)
    commit: int = 0
    feature_set: int = 0
    client: int = CLIENT_FIREDANCER
    sockets: dict = field(default_factory=dict)
    extensions: tuple = ()

    def encode(self) -> bytes:
        out = bytearray(self.pubkey)
        out += enc_varint(self.wallclock_ms)
        out += struct.pack("<QH", self.outset_us, self.shred_version)
        out += enc_cu16(self.version[0]) + enc_cu16(self.version[1]) \
            + enc_cu16(self.version[2])
        out += struct.pack("<II", self.commit, self.feature_set)
        out += enc_cu16(self.client)
        # dedup addresses preserving first-seen order
        addrs: list[int] = []
        entries = []                        # (tag, addr_idx, port)
        for tag in sorted(self.sockets):
            ip, port = self.sockets[tag]
            ipv = _ip4(ip)
            if ipv not in addrs:
                addrs.append(ipv)
            entries.append((tag, addrs.index(ipv), port))
        out += enc_cu16(len(addrs))
        for ipv in addrs:
            out += struct.pack("<II", 0, ipv)      # IpAddr::V4 variant
        # ports are delta-encoded in entry order (parse: cur_port+=off)
        out += enc_cu16(len(entries))
        cur = 0
        for tag, ai, port in entries:
            out += bytes([tag, ai]) + enc_cu16((port - cur) & 0xFFFF)
            cur = port
        out += enc_cu16(len(self.extensions))
        for e in self.extensions:
            out += struct.pack("<I", e)
        return bytes(out)

    @classmethod
    def decode(cls, b: bytes, off: int) -> tuple["ContactInfo", int]:
        pubkey = bytes(b[off:off + 32])
        if len(pubkey) != 32:
            raise WireError("truncated pubkey")
        off += 32
        wallclock, off = dec_varint(b, off)
        if wallclock >= WALLCLOCK_MAX_MS:
            raise WireError("wallclock out of range")
        outset, shred_version = struct.unpack_from("<QH", b, off)
        off += 10
        major, off = dec_cu16(b, off)
        minor, off = dec_cu16(b, off)
        patch, off = dec_cu16(b, off)
        commit, feature_set = struct.unpack_from("<II", b, off)
        off += 8
        client, off = dec_cu16(b, off)
        addrs_len, off = dec_cu16(b, off)
        if addrs_len > 102:                 # MAX_ADDRESSES
            raise WireError("too many addresses")
        addrs = []
        for _ in range(addrs_len):
            (is_ip6,) = struct.unpack_from("<I", b, off)
            off += 4
            if is_ip6 & 0xFF:
                off += 16
                addrs.append(None)          # ipv6 unsupported, skipped
            else:
                (ipv,) = struct.unpack_from("<I", b, off)
                off += 4
                addrs.append(ipv)
        sockets_len, off = dec_cu16(b, off)
        if sockets_len > 256:               # MAX_SOCKETS
            raise WireError("too many sockets")
        sockets = {}
        cur = 0
        seen = set()
        for _ in range(sockets_len):
            tag, ai = b[off], b[off + 1]
            off += 2
            delta, off = dec_cu16(b, off)
            cur = (cur + delta) & 0xFFFF
            if tag in seen:
                raise WireError("duplicate socket tag")
            seen.add(tag)
            if ai >= addrs_len:
                raise WireError("addr idx out of range")
            if tag < SOCKET_CNT and addrs[ai] is not None:
                sockets[tag] = (_ip4_str(addrs[ai]), cur)
        ext_len, off = dec_cu16(b, off)
        ext = struct.unpack_from("<%dI" % ext_len, b, off)
        off += 4 * ext_len
        return cls(pubkey, wallclock, outset, shred_version,
                   (major, minor, patch), commit, feature_set, client,
                   sockets, tuple(ext)), off

    def gossip_addr(self):
        s = self.sockets.get(SOCKET_GOSSIP)
        return s if s and s[1] else None


def encode_vote(index: int, pubkey: bytes, txn: bytes,
                wallclock_ms: int) -> bytes:
    """CrdsData::Vote(1): u8 index + pubkey + full vote txn + u64
    wallclock ms (fd_gossip_msg_crds_vote_parse)."""
    if not 0 <= index < VOTE_IDX_MAX:
        raise WireError("vote index out of range")
    return bytes([index]) + pubkey + txn \
        + struct.pack("<Q", wallclock_ms)


def decode_vote(b: bytes, off: int) -> tuple[dict, int]:
    from ..protocol.txn import parse_txn
    index = b[off]
    if index >= VOTE_IDX_MAX:
        raise WireError("vote index out of range")
    pubkey = bytes(b[off + 1:off + 33])
    # the txn length is discovered by parsing it (the reference calls
    # fd_txn_parse_core, fd_gossip_msg_crds_vote_parse:114)
    body = bytes(b[off + 33:])
    txn = parse_txn(body, allow_trailing=True)
    txn_sz = txn.size
    p = off + 33 + txn_sz
    (wallclock,) = struct.unpack_from("<Q", b, p)
    if wallclock >= WALLCLOCK_MAX_MS:
        raise WireError("wallclock out of range")
    return {"index": index, "pubkey": pubkey,
            "txn": body[:txn_sz], "wallclock_ms": wallclock}, p + 8


def encode_node_instance(pubkey: bytes, wallclock_ms: int,
                         timestamp: int, token: int) -> bytes:
    """CrdsData::NodeInstance(8) (fd_gossip_msg_crds_node_instance_parse)."""
    return pubkey + struct.pack("<QQQ", wallclock_ms, timestamp, token)


def decode_node_instance(b: bytes, off: int) -> tuple[dict, int]:
    pubkey = bytes(b[off:off + 32])
    wallclock, ts, token = struct.unpack_from("<QQQ", b, off + 32)
    if wallclock >= WALLCLOCK_MAX_MS:
        raise WireError("wallclock out of range")
    return {"pubkey": pubkey, "wallclock_ms": wallclock,
            "timestamp": ts, "token": token}, off + 56


def encode_lowest_slot(pubkey: bytes, lowest: int,
                       wallclock_ms: int) -> bytes:
    """CrdsData::LowestSlot(2) with the deprecated vectors empty
    (fd_gossip_msg_crds_lowest_slot_parse)."""
    return bytes([0]) + pubkey + struct.pack("<QQQQ", 0, lowest, 0, 0) \
        + struct.pack("<Q", wallclock_ms)


def decode_lowest_slot(b: bytes, off: int) -> tuple[dict, int]:
    if b[off]:
        raise WireError("lowest_slot ix != 0")
    pubkey = bytes(b[off + 1:off + 33])
    root, lowest, slots_len = struct.unpack_from("<QQQ", b, off + 33)
    if slots_len:
        raise WireError("deprecated slots set non-empty")
    (stash_len,) = struct.unpack_from("<Q", b, off + 57)
    if stash_len:
        raise WireError("deprecated stash non-empty")
    (wallclock,) = struct.unpack_from("<Q", b, off + 65)
    return {"pubkey": pubkey, "lowest": lowest, "root": root,
            "wallclock_ms": wallclock}, off + 73


# ---------------------------------------------------------------------------
# CRDS value envelope
# ---------------------------------------------------------------------------

def signable(tag: int, payload: bytes) -> bytes:
    """What the origin signs: serialize(CrdsData) = u32 tag + payload
    (verify_crds_value in fd_gossvf_tile.c:341-349 verifies exactly
    the bytes after the signature)."""
    return struct.pack("<I", tag) + payload


def encode_value(tag: int, payload: bytes, signature: bytes) -> bytes:
    return signature + struct.pack("<I", tag) + payload


def value_hash(wire: bytes) -> bytes:
    """CRDS identity hash: sha256 over the serialized value
    (signature included) — the key pull-request blooms filter on."""
    return hashlib.sha256(wire).digest()


_PUBKEY_OFF = {                  # payload offset of the origin pubkey
    V_LEGACY_CONTACT_INFO: 0, V_VOTE: 1, V_LOWEST_SLOT: 1,
    V_LEGACY_SNAPSHOT_HASHES: 0, V_ACCOUNT_HASHES: 0, V_EPOCH_SLOTS: 1,
    V_LEGACY_VERSION: 0, V_VERSION: 0, V_NODE_INSTANCE: 0,
    V_DUPLICATE_SHRED: 2, V_INC_SNAPSHOT_HASHES: 0, V_CONTACT_INFO: 0,
    V_RESTART_LAST_VOTED_FORK_SLOTS: 0, V_RESTART_HEAVIEST_FORK: 0,
}


def _payload_size(tag: int, b: bytes, off: int) -> int:
    """Byte length of a variant payload starting at off — the value
    boundary scan containers need (fd_gossip_msg_crds_data_parse)."""
    start = off
    if tag == V_CONTACT_INFO:
        _, end = ContactInfo.decode(b, off)
        return end - start
    if tag == V_VOTE:
        _, end = decode_vote(b, off)
        return end - start
    if tag == V_NODE_INSTANCE:
        return 56
    if tag == V_LOWEST_SLOT:
        _, end = decode_lowest_slot(b, off)
        return end - start
    if tag == V_LEGACY_VERSION or tag == V_VERSION:
        # pubkey + wallclock + 3 u16 + Option<u32 commit> [+ u32]
        p = off + 32 + 8 + 6
        has_commit = b[p]
        p += 1 + (4 if has_commit else 0)
        if tag == V_VERSION:
            p += 4
        return p - start
    if tag == V_LEGACY_CONTACT_INFO:
        p = off + 32
        for _ in range(10):
            (is6,) = struct.unpack_from("<I", b, p)
            p += 4 + (6 if not is6 else 26)
        return p + 10 - start          # + wallclock u64 + shred u16
    if tag in (V_LEGACY_SNAPSHOT_HASHES, V_ACCOUNT_HASHES):
        # pubkey + Vec<(u64 slot, 32B hash)> + wallclock
        (n,) = struct.unpack_from("<Q", b, off + 32)
        return 32 + 8 + 40 * n + 8
    if tag == V_INC_SNAPSHOT_HASHES:
        # pubkey + full (u64+32) + Vec<(u64+32)> incremental + wallclock
        (n,) = struct.unpack_from("<Q", b, off + 72)
        return 32 + 40 + 8 + 40 * n + 8
    if tag == V_EPOCH_SLOTS:
        # u8 index + pubkey + Vec<CompressedSlots> + wallclock
        p = off + 33
        (n,) = struct.unpack_from("<Q", b, p)
        p += 8
        for _ in range(n):
            (uncompressed,) = struct.unpack_from("<I", b, p)
            p += 4
            if uncompressed:
                p += 16                  # first_slot + num
                if b[p]:                 # BitVec<u8>: Option + len
                    (cap,) = struct.unpack_from("<Q", b, p + 1)
                    p += 1 + 8 + cap + 8
                else:
                    p += 1
            else:
                (clen,) = struct.unpack_from("<Q", b, p + 16)
                p += 24 + clen
        return p + 8 - start
    if tag == V_DUPLICATE_SHRED:
        # u16 idx + pubkey + wallclock + slot + 5B + num/idx + chunk
        (clen,) = struct.unpack_from("<Q", b, off + 57)
        return 2 + 32 + 8 + 8 + 5 + 2 + 8 + clen
    if tag == V_RESTART_LAST_VOTED_FORK_SLOTS:
        p = off + 40
        (raw,) = struct.unpack_from("<I", b, p)
        p += 4
        if not raw:
            (n,) = struct.unpack_from("<Q", b, p)
            p += 8 + 4 * n               # RunLengthEncoding<u32>
        else:
            if b[p]:
                (cap,) = struct.unpack_from("<Q", b, p + 1)
                p += 1 + 8 + cap + 8
            else:
                p += 1
        return p + 42 - start            # slot + hash + shred_version
    if tag == V_RESTART_HEAVIEST_FORK:
        return 32 + 8 + 8 + 32 + 8 + 2
    raise WireError(f"unsupported CRDS tag {tag}")


def decode_value(b: bytes, off: int) -> tuple[dict, int]:
    """One CrdsValue: returns {signature, tag, payload, origin,
    wallclock_ms, wire} and the end offset
    (fd_gossip_msg_crds_vals_parse:610-622)."""
    sig = bytes(b[off:off + 64])
    if len(sig) != 64:
        raise WireError("truncated signature")
    (tag,) = struct.unpack_from("<I", b, off + 64)
    p = off + 68
    sz = _payload_size(tag, b, p)
    payload = bytes(b[p:p + sz])
    if len(payload) != sz:
        raise WireError("truncated payload")
    pk_off = _PUBKEY_OFF[tag]
    origin = payload[pk_off:pk_off + 32]
    if tag == V_CONTACT_INFO:
        wc, _ = dec_varint(payload, 32)
    elif tag in (V_VOTE, V_LOWEST_SLOT, V_LEGACY_SNAPSHOT_HASHES,
                 V_ACCOUNT_HASHES, V_EPOCH_SLOTS,
                 V_INC_SNAPSHOT_HASHES):
        (wc,) = struct.unpack_from("<Q", payload, sz - 8)
    elif tag in (V_NODE_INSTANCE, V_LEGACY_VERSION, V_VERSION,
                 V_RESTART_LAST_VOTED_FORK_SLOTS,
                 V_RESTART_HEAVIEST_FORK):
        (wc,) = struct.unpack_from("<Q", payload, 32)
    elif tag == V_LEGACY_CONTACT_INFO:
        (wc,) = struct.unpack_from("<Q", payload, sz - 10)
    elif tag == V_DUPLICATE_SHRED:
        (wc,) = struct.unpack_from("<Q", payload, 34)
    else:
        wc = 0
    end = p + sz
    return {"signature": sig, "tag": tag, "payload": payload,
            "origin": bytes(origin), "wallclock_ms": wc,
            "wire": bytes(b[off:end])}, end


# ---------------------------------------------------------------------------
# messages
# ---------------------------------------------------------------------------

def encode_container(msg: int, from_pubkey: bytes,
                     values: list[bytes]) -> bytes:
    """Push(2) / PullResponse(1): u32 tag + from + u64 len + values
    (fd_gossip_msg_crds_container_parse)."""
    assert msg in (MSG_PUSH, MSG_PULL_RESPONSE)
    out = struct.pack("<I", msg) + from_pubkey \
        + struct.pack("<Q", len(values))
    return out + b"".join(values)


def encode_pull_request(bloom_keys: list[int], bloom_bits: bytes,
                        bloom_num_bits_set: int, mask: int,
                        mask_bits: int, ci_value: bytes,
                        bits_cnt: int | None = None) -> bytes:
    """PullRequest(0): CrdsFilter { Bloom { keys: Vec<u64>,
    bits: BitVec<u64> (Option<Vec<u64>> + u64 bit len),
    num_bits_set }, mask, mask_bits } + our ContactInfo CrdsValue
    (fd_gossip_pull_req_parse). bits_cnt is the logical bit length
    (<= words*64; defaults to the full capacity)."""
    assert len(bloom_bits) % 8 == 0
    nwords = len(bloom_bits) // 8
    if bits_cnt is None:
        bits_cnt = nwords * 64
    out = struct.pack("<I", MSG_PULL_REQUEST)
    out += struct.pack("<Q", len(bloom_keys))
    out += b"".join(struct.pack("<Q", k & 0xFFFFFFFFFFFFFFFF)
                    for k in bloom_keys)
    out += bytes([1]) + struct.pack("<Q", nwords) + bloom_bits \
        + struct.pack("<Q", bits_cnt)
    out += struct.pack("<QQI", bloom_num_bits_set, mask, mask_bits)
    return out + ci_value


def encode_prune(from_pubkey: bytes, origins: list[bytes],
                 signature: bytes, destination: bytes,
                 wallclock_ms: int) -> bytes:
    """PruneMessage(3): from + PruneData { pubkey, prunes, signature,
    destination, wallclock } (fd_gossip_msg_prune_parse; the outer
    from must equal PruneData.pubkey)."""
    return struct.pack("<I", MSG_PRUNE) + from_pubkey + from_pubkey \
        + struct.pack("<Q", len(origins)) + b"".join(origins) \
        + signature + destination + struct.pack("<Q", wallclock_ms)


def prune_signable(pubkey: bytes, origins: list[bytes],
                   destination: bytes, wallclock_ms: int,
                   prefixed: bool = True) -> bytes:
    """PruneData signable bytes; verifiers accept BOTH the prefixed
    and unprefixed form (fd_gossvf_tile.c verify_prune:321-338)."""
    body = pubkey + struct.pack("<Q", len(origins)) \
        + b"".join(origins) + destination \
        + struct.pack("<Q", wallclock_ms)
    return (b"\xffSOLANA_PRUNE_DATA" + body) if prefixed else body


def encode_ping(from_pubkey: bytes, token: bytes,
                signature: bytes) -> bytes:
    """Ping(4): from + 32B token + signature over the raw token
    (fd_gossip.c:779)."""
    return struct.pack("<I", MSG_PING) + from_pubkey + token + signature


def pong_preimage(token: bytes) -> bytes:
    """Pong hash/signature pre-image: "SOLANA_PING_PONG" + token;
    the pong carries sha256(preimage) and a signature over that hash
    (fd_gossip.c:655-663)."""
    return b"SOLANA_PING_PONG" + token


def encode_pong(from_pubkey: bytes, token: bytes,
                signature: bytes) -> bytes:
    h = hashlib.sha256(pong_preimage(token)).digest()
    return struct.pack("<I", MSG_PONG) + from_pubkey + h + signature


def parse_message(b: bytes) -> dict:
    """Datagram -> typed view (fd_gossip_msg_parse). Raises WireError
    on malformed input; trailing bytes are rejected like the
    reference's payload_sz==CUR_OFFSET check."""
    if len(b) > MTU:
        raise WireError("datagram exceeds gossip MTU")
    (tag,) = struct.unpack_from("<I", b, 0)
    off = 4
    if tag in (MSG_PUSH, MSG_PULL_RESPONSE):
        frm = bytes(b[off:off + 32])
        (n,) = struct.unpack_from("<Q", b, off + 32)
        if n > MAX_CRDS_PER_MSG:
            raise WireError("too many CRDS values")
        off += 40
        values = []
        for _ in range(n):
            v, off = decode_value(b, off)
            values.append(v)
        kind = "push" if tag == MSG_PUSH else "pull_response"
        out = {"kind": kind, "from": frm, "values": values}
    elif tag == MSG_PULL_REQUEST:
        (keys_len,) = struct.unpack_from("<Q", b, off)
        off += 8
        keys = list(struct.unpack_from("<%dQ" % keys_len, b, off))
        off += 8 * keys_len
        has_bits = b[off]
        off += 1
        bits = b""
        if has_bits:
            (nwords,) = struct.unpack_from("<Q", b, off)
            off += 8
            bits = bytes(b[off:off + 8 * nwords])
            if len(bits) != 8 * nwords:
                raise WireError("truncated bloom bits")
            off += 8 * nwords
            (bits_cnt,) = struct.unpack_from("<Q", b, off)
            off += 8
            if bits_cnt > nwords * 64:
                raise WireError("bloom bit len > capacity")
        else:
            raise WireError("bloom without bits")
        num_set, mask = struct.unpack_from("<QQ", b, off)
        (mask_bits,) = struct.unpack_from("<I", b, off + 16)
        off += 20
        ci, off = decode_value(b, off)
        out = {"kind": "pull_request", "bloom_keys": keys,
               "bloom_bits": bits, "bloom_bits_cnt": bits_cnt,
               "bloom_num_bits_set": num_set,
               "mask": mask, "mask_bits": mask_bits, "ci": ci}
    elif tag == MSG_PRUNE:
        frm = bytes(b[off:off + 32])
        pk = bytes(b[off + 32:off + 64])
        if frm != pk:
            raise WireError("prune from != PruneData.pubkey")
        off += 64
        (n,) = struct.unpack_from("<Q", b, off)
        off += 8
        origins = [bytes(b[off + 32 * i:off + 32 * (i + 1)])
                   for i in range(n)]
        off += 32 * n
        sig = bytes(b[off:off + 64])
        dest = bytes(b[off + 64:off + 96])
        (wc,) = struct.unpack_from("<Q", b, off + 96)
        off += 104
        out = {"kind": "prune", "from": frm, "origins": origins,
               "signature": sig, "destination": dest,
               "wallclock_ms": wc}
    elif tag in (MSG_PING, MSG_PONG):
        frm = bytes(b[off:off + 32])
        tok = bytes(b[off + 32:off + 64])
        sig = bytes(b[off + 64:off + 128])
        if len(sig) != 64:
            raise WireError("truncated ping/pong")
        off += 128
        out = {"kind": "ping" if tag == MSG_PING else "pong",
               "from": frm, "token": tok, "signature": sig}
    else:
        raise WireError(f"unknown message tag {tag}")
    if off != len(b):
        raise WireError("trailing bytes")
    return out
