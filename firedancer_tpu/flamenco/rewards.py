"""Partitioned epoch rewards: inflation -> stake/vote payouts.

Re-expression of the reference's rewards pipeline
(ref: src/flamenco/rewards/fd_rewards.c — calculate_inflation_rates,
calculate_stake_points_and_credits, the partitioned distribution of
SIMD-0118 mirrored in fd_rewards.c's epoch_rewards partitions):

  1. The inflation schedule (initial 8%/yr tapering 15%/yr to a 1.5%
     terminal rate) fixes the epoch's total validator issuance from
     the capitalization and the epoch's fraction of a year.
  2. Each stake delegation earns POINTS = active_stake × credits its
     vote account earned THAT epoch (the epoch_credits history on the
     vote state). Lamports pro-rate by points; the vote account's
     commission takes its cut, the remainder COMPOUNDS into the
     delegation.
  3. Distribution is partitioned: payouts are hash-assigned to
     `num_partitions` buckets credited one per slot at the start of
     the next epoch, bounding per-block write load.

All arithmetic is integer (floor division at each step — consensus
code must not float); the only float is the published inflation RATE,
converted to lamports via a fixed-point basis-points product.
"""
from __future__ import annotations

import hashlib
import struct

from ..funk.funk import key32
from ..svm.accdb import Account
from ..svm.stake import STAKE_PROGRAM_ID, StakeState
from ..svm.vote import VOTE_PROGRAM_ID, VoteState, _HDR_SZ

SLOT_SECONDS = 0.4
YEAR_SECONDS = 31_557_600       # Julian year: 365.25 * 24 * 3600

INITIAL_RATE_BPS = 800          # 8.00 %/yr
TAPER_BPS = 1500                # 15 % of itself per year
TERMINAL_RATE_BPS = 150         # 1.50 %/yr

MAX_ACCOUNTS_PER_PARTITION = 4096


def inflation_rate_bps(epoch: int, slots_per_epoch: int) -> int:
    """Validator inflation rate (basis points/yr) in effect at
    `epoch`: initial·(1−taper)^years, floored at terminal. Computed in
    integer bps with per-year taper multiplication so every validator
    lands on the identical value."""
    # exact integer ratio (slots·0.4s vs 31557600s/yr → ×4 // ×10·year)
    years = (epoch * slots_per_epoch * 4) // (10 * YEAR_SECONDS)
    rate = INITIAL_RATE_BPS
    for _ in range(years):
        rate = rate * (10_000 - TAPER_BPS) // 10_000
        if rate <= TERMINAL_RATE_BPS:
            return TERMINAL_RATE_BPS
    return max(rate, TERMINAL_RATE_BPS)


def epoch_validator_issuance(capitalization: int, epoch: int,
                             slots_per_epoch: int) -> int:
    """Lamports to mint for `epoch`: cap × rate × epoch_year_fraction.
    The year fraction is (slots·SLOT_SECONDS)/year expressed as an
    exact integer ratio (slots·4, year·10) to avoid floats."""
    rate = inflation_rate_bps(epoch, slots_per_epoch)
    num = capitalization * rate * slots_per_epoch * 4
    den = 10_000 * YEAR_SECONDS * 10
    return num // den


def calculate_stake_rewards(funk, xid, rewarded_epoch: int,
                            issuance: int, items: dict | None = None):
    """Point totals + per-account payouts for `rewarded_epoch`.

    Returns (rewards, total_points) where rewards is a list of
    (stake_pubkey, stake_delta, vote_pubkey, vote_delta) with deltas
    in lamports. Stake accounts whose voter earned no credits that
    epoch earn nothing (ref: calculate_stake_points_and_credits
    skipping zero-credit epochs)."""
    if items is None:
        # one overlay fold serves both scans (items_at re-folds the
        # whole fork per call — r4 review finding)
        items = funk.items_at(xid)
    credits_by_vote: dict[bytes, int] = {}
    commission_by_vote: dict[bytes, int] = {}
    for key, acct in items.items():
        if not isinstance(acct, Account) \
                or acct.owner != VOTE_PROGRAM_ID \
                or len(acct.data) < _HDR_SZ:
            continue
        try:
            vs = VoteState.from_bytes(acct.data)
        except Exception:
            continue
        earned = 0
        for ep, cr, prev in vs.epoch_credits:
            if ep == rewarded_epoch:
                earned = cr - prev
                break
        if earned > 0:
            credits_by_vote[key] = earned
            commission_by_vote[key] = vs.commission

    # rewards and vote_stakes/leader schedule must count the SAME
    # stake: apply the rate-limited history when the sysvar exists
    from .stakes import read_stake_history
    history = read_stake_history(funk, xid)
    entries = []                 # (stake_key, points, vote_key)
    total_points = 0
    for key, acct in items.items():
        if not isinstance(acct, Account) \
                or acct.owner != STAKE_PROGRAM_ID:
            continue
        try:
            st = StakeState.from_bytes(acct.data)
        except Exception:
            continue
        stake = st.active_at(rewarded_epoch, history=history or None)
        credits = credits_by_vote.get(st.voter, 0)
        pts = stake * credits
        if pts > 0:
            entries.append((key, pts, st.voter))
            total_points += pts

    rewards = []
    if total_points == 0:
        return rewards, 0
    for key, pts, voter in entries:
        amount = issuance * pts // total_points
        commission = commission_by_vote.get(voter, 0)
        vote_delta = amount * commission // 100
        stake_delta = amount - vote_delta
        rewards.append((key, stake_delta, voter, vote_delta))
    return rewards, total_points


def num_partitions(n_rewards: int) -> int:
    return max(1, -(-n_rewards // MAX_ACCOUNTS_PER_PARTITION))


def partition_of(stake_pubkey: bytes, parent_blockhash: bytes,
                 parts: int) -> int:
    """Deterministic hash partition (the reference seeds its
    SipHash-based partitioner with the parent blockhash; we use
    sha256(parent_blockhash ‖ pubkey) — internal determinism, same
    load-spreading role)."""
    h = hashlib.sha256(parent_blockhash + stake_pubkey).digest()
    return struct.unpack_from("<Q", h, 0)[0] % parts


def apply_rewards_partition(funk, xid, rewards, parent_blockhash: bytes,
                            parts: int, partition: int) -> int:
    """Credit one partition's payouts (the per-slot duty at the start
    of the new epoch). Stake deltas COMPOUND into the delegation
    amount; vote deltas are plain lamport credits. Returns lamports
    distributed."""
    paid = 0
    for stake_key, stake_delta, vote_key, vote_delta in rewards:
        if partition_of(stake_key, parent_blockhash, parts) != partition:
            continue
        acct = funk.rec_query(xid, stake_key)
        if isinstance(acct, Account):
            st = StakeState.from_bytes(acct.data)
            st.amount += stake_delta
            na = Account(acct.lamports + stake_delta,
                         bytearray(st.to_bytes()), acct.owner,
                         acct.executable, acct.rent_epoch)
            funk.rec_write(xid, key32(stake_key), na)
            paid += stake_delta
        if vote_delta:
            va = funk.rec_query(xid, vote_key)
            if isinstance(va, Account):
                nv = Account(va.lamports + vote_delta, va.data,
                             va.owner, va.executable, va.rent_epoch)
                funk.rec_write(xid, key32(vote_key), nv)
                paid += vote_delta
    return paid


def distribute_epoch_rewards(funk, xid, rewarded_epoch: int,
                             capitalization: int | None,
                             slots_per_epoch: int,
                             parent_blockhash: bytes) -> dict:
    """Whole-epoch convenience: compute + pay every partition (callers
    that stage per-slot call apply_rewards_partition themselves).
    capitalization=None derives it from the same single overlay fold
    the points calculation uses. Returns a summary dict."""
    items = funk.items_at(xid)
    if capitalization is None:
        capitalization = sum(a.lamports for a in items.values()
                             if isinstance(a, Account))
    issuance = epoch_validator_issuance(capitalization, rewarded_epoch,
                                        slots_per_epoch)
    rewards, points = calculate_stake_rewards(funk, xid, rewarded_epoch,
                                              issuance, items=items)
    parts = num_partitions(len(rewards))
    paid = 0
    for p in range(parts):
        paid += apply_rewards_partition(funk, xid, rewards,
                                        parent_blockhash, parts, p)
    return {"issuance": issuance, "paid": paid, "points": points,
            "accounts": len(rewards), "partitions": parts}


# -- paid-through marker ------------------------------------------------------
# Restart discipline: the highest epoch whose rewards have been paid
# lives in a marker ACCOUNT, so it rides snapshots/checkpoints and a
# rebooted bank neither re-pays (supply inflation) nor skips epochs
# (r4 review finding). Internal reserved address (not a Solana one).

REWARDS_MARKER_KEY = b"FdtpuEpochRewardsPaidThrough\x00\x00\x00\x00"


def paid_through(funk, xid) -> int:
    acct = funk.rec_query(xid, REWARDS_MARKER_KEY)
    if isinstance(acct, Account) and len(acct.data) >= 8:
        return struct.unpack_from("<Q", bytes(acct.data[:8]), 0)[0]
    return 0


def mark_paid_through(funk, xid, epoch: int):
    funk.rec_write(xid, REWARDS_MARKER_KEY,
                   Account(0, bytearray(struct.pack("<Q", epoch)),
                           b"\x00" * 32))
