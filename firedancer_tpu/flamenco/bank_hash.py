"""Bank hash: the per-slot state commitment.

The reference assembles each slot's bank hash from the parent bank
hash, the accounts delta (now the homomorphic lattice hash), the
signature count, and the last blockhash (ref: fd_runtime bank-hash
assembly; lthash accumulator per src/ballet/lthash/fd_lthash.h — the
accounts_lt_hash feature). TPU-first shape: every modified account's
lattice element is one lane of ONE batched blake3-XOF device call
(ops/blake3.lthash_batch), and the accumulator update is a pair of
wrapping u16 vector sums — the same lthash kernels the snapshot
pipeline uses.

  account_lt(pubkey, account) = lthash_2048(serialized account)
  acc' = acc - Σ account_lt(old_i) + Σ account_lt(new_i)
  bank_hash = sha256(parent || checksum(acc') || sig_cnt_le || blockhash)

Zero-lamport (deleted) accounts contribute nothing — removing an
account subtracts its old element only, mirroring the reference's
delete discipline."""
from __future__ import annotations

import hashlib
import struct

import numpy as np

LT_MSG_MAX = 2048           # lthash_batch input cap per lane


def serialize_account(pubkey: bytes, acct) -> bytes:
    """Canonical per-account hash input: lamports | rent_epoch |
    data | executable | owner | pubkey (the reference's account-hash
    field order, truncated to the lattice input cap; longer data folds
    through sha256 first so every account hashes in one lane)."""
    data = acct.data
    head = struct.pack("<QQ", acct.lamports, acct.rent_epoch)
    tail = bytes([1 if acct.executable else 0]) + acct.owner + pubkey
    if len(head) + len(data) + len(tail) > LT_MSG_MAX:
        data = hashlib.sha256(data).digest()
    return head + data + tail


def _lthash_on_host() -> bool:
    """The batched jnp kernel pays ~15k eager op dispatches per call —
    a net loss on the CPU backend (~2 s/call warm) where the host
    oracle does the same work in ~6 ms/message. On accelerators the
    batch IS the win, so keep the device path there."""
    import jax
    return jax.default_backend() == "cpu"


def accounts_lthash(items) -> np.ndarray:
    """[(pubkey, Account)] -> summed lattice element (1024 u16), all
    lanes in one batched device call (host oracle on the CPU backend).
    Zero-lamport accounts skip."""
    raws = []
    for pk, a in items:
        if a is None or a.lamports == 0:
            continue
        raws.append(serialize_account(pk, a))
    if not raws:
        return np.zeros(1024, np.uint16)
    if _lthash_on_host():
        from ..utils.blake3_ref import lthash
        acc = np.zeros(1024, np.uint32)
        for m in raws:
            acc += np.frombuffer(lthash(m), np.uint16)
        return acc.astype(np.uint16)
    from ..ops.blake3 import lthash_batch
    msgs, lens = [], []
    for m in raws:
        buf = np.zeros(LT_MSG_MAX, np.uint8)
        buf[:len(m)] = np.frombuffer(m, np.uint8)
        msgs.append(buf)
        lens.append(len(m))
    # pad the lane count to the next power of two: the kernel compiles
    # per batch shape, and per-slot deltas would otherwise trace a
    # fresh XLA graph for every distinct modified-account count (~12s
    # each on a cold cpu cache); padded lanes are sliced off before
    # the sum so the lattice value is unchanged
    n = len(msgs)
    while len(msgs) < (1 << (n - 1).bit_length()):
        msgs.append(np.zeros(LT_MSG_MAX, np.uint8))
        lens.append(0)
    lt = np.asarray(lthash_batch(np.stack(msgs),
                                 np.asarray(lens, np.int32)))[:n]
    return lt.astype(np.uint32).sum(axis=0).astype(np.uint16)


class BankHasher:
    """Running accounts lattice + the per-slot hash chain."""

    def __init__(self, acc: np.ndarray | None = None):
        self.acc = (np.zeros(1024, np.uint16) if acc is None
                    else acc.astype(np.uint16))

    def apply_txn_delta(self, funk, xid):
        """Fold one in-preparation funk txn's account changes into the
        lattice (old = parent-visible values). THE shared delta scan —
        the replay tile and the backtest recorder both use it, so two
        consumers hashing identical ledgers cannot drift."""
        from ..svm.accdb import Account
        recs = funk.txn_recs(xid)
        old_items = [(key, v) for key in recs
                     if isinstance(v := funk.rec_query(None, key),
                                   Account)]
        new_items = [(key, v) for key, v in recs.items()
                     if isinstance(v, Account)]
        self.apply_delta(old_items, new_items)

    def apply_delta(self, old_items, new_items):
        """old/new: [(pubkey, Account|None)] for every record the slot
        modified (old = parent-visible value)."""
        self.acc = (self.acc
                    - accounts_lthash(old_items)
                    + accounts_lthash(new_items))

    def checksum(self) -> bytes:
        """32-byte lattice checksum (blake3 of the 2048-byte element in
        the reference; sha256 here — internal commitment, documented)."""
        return hashlib.sha256(self.acc.tobytes()).digest()

    def bank_hash(self, parent: bytes, sig_cnt: int,
                  last_blockhash: bytes) -> bytes:
        return hashlib.sha256(
            parent + self.checksum()
            + struct.pack("<Q", sig_cnt) + last_blockhash).digest()


def lthash_of_root(funk) -> np.ndarray:
    """Full recompute over the published root (the snapshot-verify
    fan-out; the delta path must always agree with this oracle)."""
    from ..svm.accdb import Account
    items = [(k, v) for k, v in funk.root_items().items()
             if isinstance(v, Account)]
    return accounts_lthash(items)
