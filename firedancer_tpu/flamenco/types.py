"""Solana bincode wire types: the interop codec layer.

The reference generates 15k lines of (de)serializers from
fd_types.json (ref: src/flamenco/types/fd_types.c); this module is a
hand-written TPU-framework subset covering the types the consensus
path actually exchanges with a real cluster:

  * bincode primitives (fixed-int little-endian, Option<T> as u8 tag,
    Vec<T>/String with u64 length — Agave's default bincode config)
  * StakeStateV2      (stake account data, exactly 200 bytes)
  * VoteState1_14_11  (vote account data, the layout Agave still
                       serializes inside VoteStateVersions::V1_14_11)
  * VoteInstruction::Vote (the vote transaction's instruction data)

Byte-for-byte layouts follow the public Agave definitions; sizes are
pinned by the well-known constants (StakeStateV2::size_of() == 200,
vote account size 3762) in tests/test_types.py. Internal runtime
state (svm/vote.py, svm/stake.py) CONVERTS to/from these layouts at
the wire boundary — the in-memory form stays this framework's own.
"""
from __future__ import annotations

import struct


class BincodeError(ValueError):
    pass


class Reader:
    def __init__(self, data: bytes):
        self.b = data
        self.off = 0

    def take(self, n: int) -> bytes:
        if self.off + n > len(self.b):
            raise BincodeError("truncated")
        out = self.b[self.off:self.off + n]
        self.off += n
        return out

    def u8(self) -> int:
        return self.take(1)[0]

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self.take(8))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self.take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self.take(8))[0]

    def pubkey(self) -> bytes:
        return self.take(32)

    def option(self, fn):
        tag = self.u8()
        if tag == 0:
            return None
        if tag != 1:
            raise BincodeError(f"bad Option tag {tag}")
        return fn()

    def vec(self, fn) -> list:
        n = self.u64()
        if n > 1 << 24:
            raise BincodeError("vec too long")
        return [fn() for _ in range(n)]


class Writer:
    def __init__(self):
        self.out = bytearray()

    def u8(self, v):
        self.out.append(v & 0xFF)

    def u32(self, v):
        self.out += struct.pack("<I", v)

    def u64(self, v):
        self.out += struct.pack("<Q", v)

    def i64(self, v):
        self.out += struct.pack("<q", v)

    def f64(self, v):
        self.out += struct.pack("<d", v)

    def pubkey(self, v: bytes):
        assert len(v) == 32
        self.out += v

    def option(self, v, fn):
        if v is None:
            self.u8(0)
        else:
            self.u8(1)
            fn(v)

    def vec(self, items, fn):
        self.u64(len(items))
        for it in items:
            fn(it)

    def bytes(self) -> bytes:
        return bytes(self.out)


# ---------------------------------------------------------------------------
# StakeStateV2 (Agave stake account data; 200 bytes total)
# ---------------------------------------------------------------------------

STAKE_STATE_SZ = 200
DEFAULT_WARMUP_COOLDOWN_RATE = 0.25


def encode_stake_state(state: str, *, rent_exempt_reserve: int = 0,
                       staker: bytes = bytes(32),
                       withdrawer: bytes = bytes(32),
                       lockup_ts: int = 0, lockup_epoch: int = 0,
                       custodian: bytes = bytes(32),
                       voter: bytes = bytes(32), stake: int = 0,
                       activation_epoch: int = 0,
                       deactivation_epoch: int = (1 << 64) - 1,
                       warmup_cooldown_rate: float =
                       DEFAULT_WARMUP_COOLDOWN_RATE,
                       credits_observed: int = 0,
                       stake_flags: int = 0) -> bytes:
    """state: 'uninitialized' | 'initialized' | 'stake' |
    'rewards_pool'. Output is padded to exactly 200 bytes (the account
    allocation size Agave uses)."""
    w = Writer()
    if state == "uninitialized":
        w.u32(0)
    elif state in ("initialized", "stake"):
        w.u32(1 if state == "initialized" else 2)
        w.u64(rent_exempt_reserve)
        w.pubkey(staker)
        w.pubkey(withdrawer)
        w.i64(lockup_ts)
        w.u64(lockup_epoch)
        w.pubkey(custodian)
        if state == "stake":
            w.pubkey(voter)
            w.u64(stake)
            w.u64(activation_epoch)
            w.u64(deactivation_epoch)
            w.f64(warmup_cooldown_rate)
            w.u64(credits_observed)
            w.u8(stake_flags)
    elif state == "rewards_pool":
        w.u32(3)
    else:
        raise BincodeError(f"unknown stake state {state!r}")
    out = w.bytes()
    if len(out) > STAKE_STATE_SZ:
        raise BincodeError("stake state overflow")
    return out + bytes(STAKE_STATE_SZ - len(out))


def decode_stake_state(data: bytes) -> dict:
    r = Reader(data)
    disc = r.u32()
    if disc == 0:
        return {"state": "uninitialized"}
    if disc == 3:
        return {"state": "rewards_pool"}
    if disc not in (1, 2):
        raise BincodeError(f"bad StakeStateV2 discriminant {disc}")
    out = {"state": "initialized" if disc == 1 else "stake",
           "rent_exempt_reserve": r.u64(), "staker": r.pubkey(),
           "withdrawer": r.pubkey(), "lockup_ts": r.i64(),
           "lockup_epoch": r.u64(), "custodian": r.pubkey()}
    if disc == 2:
        out.update(voter=r.pubkey(), stake=r.u64(),
                   activation_epoch=r.u64(),
                   deactivation_epoch=r.u64(),
                   warmup_cooldown_rate=r.f64(),
                   credits_observed=r.u64(), stake_flags=r.u8())
    return out


# ---------------------------------------------------------------------------
# VoteState1_14_11 inside VoteStateVersions (vote account data)
# ---------------------------------------------------------------------------

VOTE_ACCOUNT_SZ = 3762          # Agave VoteStateVersions::vote_state_size_of


def encode_vote_state(node_pubkey: bytes, authorized_voter: bytes,
                      authorized_withdrawer: bytes, commission: int,
                      votes: list[tuple[int, int]],
                      root_slot: int | None,
                      epoch_credits: list[tuple[int, int, int]] = (),
                      last_ts_slot: int = 0, last_ts: int = 0,
                      voter_epoch: int = 0, pad: bool = True) -> bytes:
    """VoteStateVersions::V1_14_11 (enum variant 1):
    votes: [(slot, confirmation_count)], authorized_voters as the
    single-entry map {voter_epoch: authorized_voter}, empty
    prior_voters circular buffer."""
    w = Writer()
    w.u32(1)                                 # VoteStateVersions::V1_14_11
    w.pubkey(node_pubkey)
    w.pubkey(authorized_withdrawer)
    w.u8(commission)
    w.vec(votes, lambda v: (w.u64(v[0]), w.u32(v[1])))
    w.option(root_slot, w.u64)
    # authorized_voters: BTreeMap<u64, Pubkey> with u64 length
    w.u64(1)
    w.u64(voter_epoch)
    w.pubkey(authorized_voter)
    # prior_voters: [(Pubkey, u64, u64); 32] + idx u64 + is_empty bool
    for _ in range(32):
        w.pubkey(bytes(32))
        w.u64(0)
        w.u64(0)
    w.u64(31)
    w.u8(1)                                  # is_empty = true
    w.vec(list(epoch_credits),
          lambda e: (w.u64(e[0]), w.u64(e[1]), w.u64(e[2])))
    w.u64(last_ts_slot)
    w.i64(last_ts)
    out = w.bytes()
    if not pad:
        return out
    if len(out) > VOTE_ACCOUNT_SZ:
        raise BincodeError("vote state overflow")
    return out + bytes(VOTE_ACCOUNT_SZ - len(out))


def decode_vote_state(data: bytes) -> dict:
    r = Reader(data)
    variant = r.u32()
    if variant != 1:
        raise BincodeError(
            f"unsupported VoteStateVersions variant {variant}")
    out = {"node_pubkey": r.pubkey(),
           "authorized_withdrawer": r.pubkey(),
           "commission": r.u8(),
           "votes": r.vec(lambda: (r.u64(), r.u32()))}
    out["root_slot"] = r.option(r.u64)
    n_av = r.u64()
    if n_av > 64:
        raise BincodeError("authorized_voters too long")
    av = [(r.u64(), r.pubkey()) for _ in range(n_av)]
    out["authorized_voters"] = av
    out["authorized_voter"] = av[0][1] if av else bytes(32)
    for _ in range(32):                      # prior_voters buffer
        r.pubkey()
        r.u64()
        r.u64()
    r.u64()
    r.u8()
    out["epoch_credits"] = r.vec(lambda: (r.u64(), r.u64(), r.u64()))
    out["last_ts_slot"] = r.u64()
    out["last_ts"] = r.i64()
    return out


# ---------------------------------------------------------------------------
# VoteInstruction::Vote (vote txn instruction data)
# ---------------------------------------------------------------------------

VOTE_IX_VOTE_DISC = 2           # VoteInstruction enum variant index


def encode_vote_instruction(slots: list[int], block_hash: bytes,
                            timestamp: int | None = None) -> bytes:
    """VoteInstruction::Vote(Vote { slots, hash, timestamp }) — single
    implementation lives with the program (svm/vote.ix_vote)."""
    from ..svm.vote import ix_vote
    return ix_vote(slots, block_hash, timestamp)


def decode_vote_instruction(data: bytes) -> dict:
    r = Reader(data)
    disc = r.u32()
    if disc != VOTE_IX_VOTE_DISC:
        raise BincodeError(f"not VoteInstruction::Vote ({disc})")
    return {"slots": r.vec(r.u64), "hash": r.pubkey(),
            "timestamp": r.option(r.i64)}


# ---------------------------------------------------------------------------
# conversions: runtime state <-> wire
# ---------------------------------------------------------------------------

def stake_state_to_wire(st) -> bytes:
    """svm/stake.StakeState -> StakeStateV2 bytes."""
    from ..svm.stake import ST_DELEGATED, ST_INIT
    if st.state == ST_INIT:
        return encode_stake_state(
            "initialized", rent_exempt_reserve=st.rent_reserve,
            staker=st.staker, withdrawer=st.withdrawer)
    if st.state == ST_DELEGATED:
        return encode_stake_state(
            "stake", rent_exempt_reserve=st.rent_reserve,
            staker=st.staker, withdrawer=st.withdrawer, voter=st.voter,
            stake=st.amount, activation_epoch=st.activation_epoch,
            deactivation_epoch=st.deactivation_epoch)
    return encode_stake_state("uninitialized")


def stake_state_from_wire(data: bytes):
    from ..svm.stake import (
        EPOCH_NONE, ST_DELEGATED, ST_INIT, ST_UNINIT, StakeState,
    )
    d = decode_stake_state(data)
    if d["state"] == "initialized":
        return StakeState(ST_INIT, d["staker"], d["withdrawer"],
                          d["rent_exempt_reserve"])
    if d["state"] == "stake":
        return StakeState(ST_DELEGATED, d["staker"], d["withdrawer"],
                          d["rent_exempt_reserve"], d["voter"],
                          d["stake"], d["activation_epoch"],
                          d["deactivation_epoch"])
    return StakeState(ST_UNINIT)


def vote_state_to_wire(vs) -> bytes:
    """svm/vote.VoteState -> VoteStateVersions::V1_14_11 bytes."""
    return encode_vote_state(
        vs.node_pubkey, vs.authorized_voter, vs.authorized_withdrawer,
        vs.commission, [(v.slot, v.conf) for v in vs.tower.votes],
        vs.root_slot, last_ts=vs.last_ts)


def vote_state_from_wire(data: bytes):
    from ..choreo.tower import TowerVote
    from ..svm.vote import VoteState
    d = decode_vote_state(data)
    vs = VoteState(d["node_pubkey"], d["authorized_voter"],
                   d["authorized_withdrawer"], d["commission"])
    for slot, conf in d["votes"]:
        vs.tower.votes.append(TowerVote(slot, conf))
    vs.root_slot = d["root_slot"]
    vs.tower.root = vs.root_slot
    vs.last_ts = d["last_ts"]
    return vs
