"""flamenco: Solana runtime-layer components (ref: src/flamenco/)."""
from .leaders import EpochLeaders, WeightedSampler  # noqa: F401
