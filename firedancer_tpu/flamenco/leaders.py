"""Epoch leader schedule: stake-weighted rotation sampling
(ref: src/flamenco/leaders/fd_leaders.h:1-30 — rotations of
SLOTS_PER_ROTATION slots, deduped pubkey table; sampling via a
ChaCha20 RNG over cumulative stakes, ref fd_leaders.c:112 +
src/ballet/wsample/).

The schedule derives deterministically from (epoch seed, stake map):
stakes sort descending with pubkey tie-break (consensus requires every
validator to derive the identical table), then each rotation draws one
leader by cumulative-stake inversion of a bounded uniform draw. The
RNG stream layout follows the reference's structure; byte-for-byte
Agave equivalence is NOT claimed here (that requires replicating
rand_chacha's exact WeightedIndex consumption) — determinism and
stake-proportionality are what the tests pin.

INTEROP BLOCKER (tracked): on a real cluster this node would compute a
different leader for every slot than Agave peers. Before any
real-cluster milestone this must replicate rand_chacha's exact draw
sequence (ChaCha20 block order + WeightedIndex's f64 cumulative-weight
inversion). Self-contained clusters (all nodes this framework) are
unaffected — every node derives the identical table.
"""
from __future__ import annotations

import bisect

from ..utils.chacha import ChaChaRng

SLOTS_PER_ROTATION = 4          # FD_EPOCH_SLOTS_PER_ROTATION


class WeightedSampler:
    """Cumulative-stake inversion sampler (src/ballet/wsample/
    fd_wsample.h semantics, sampling WITH replacement)."""

    def __init__(self, weighted: list[tuple[bytes, int]]):
        """weighted: (pubkey, stake), stake > 0; order = consensus
        order (descending stake, pubkey tie-break)."""
        assert weighted, "empty stake set"
        self.keys = [k for k, _ in weighted]
        self.cum = []
        total = 0
        for _, w in weighted:
            assert w > 0
            total += w
            self.cum.append(total)
        self.total = total

    def sample(self, rng: ChaChaRng) -> bytes:
        x = rng.roll_u64(self.total)
        return self.keys[bisect.bisect_right(self.cum, x)]


class EpochLeaders:
    def __init__(self, epoch: int, seed: bytes, stakes: dict[bytes, int],
                 slots_per_epoch: int,
                 slots_per_rotation: int = SLOTS_PER_ROTATION):
        """stakes: node identity pubkey -> active stake (zero-stake
        nodes never lead)."""
        self.epoch = epoch
        self.slots_per_epoch = slots_per_epoch
        self.slots_per_rotation = slots_per_rotation
        self.slot0 = epoch * slots_per_epoch
        weighted = sorted(
            ((k, s) for k, s in stakes.items() if s > 0),
            key=lambda kv: (-kv[1], kv[0]))
        sampler = WeightedSampler(weighted)
        rng = ChaChaRng(seed)
        n_rot = -(-slots_per_epoch // slots_per_rotation)
        # deduped pubkey table + per-rotation index, the reference's
        # space layout (fd_leaders.h "dedup pubkeys into a lookup table")
        self.pub: list[bytes] = []
        idx_of: dict[bytes, int] = {}
        self.sched: list[int] = []
        for _ in range(n_rot):
            k = sampler.sample(rng)
            i = idx_of.get(k)
            if i is None:
                i = idx_of[k] = len(self.pub)
                self.pub.append(k)
            self.sched.append(i)

    def leader_for(self, slot: int) -> bytes:
        off = slot - self.slot0
        if not 0 <= off < self.slots_per_epoch:
            raise ValueError(f"slot {slot} outside epoch {self.epoch}")
        return self.pub[self.sched[off // self.slots_per_rotation]]

    def leader_slots(self, pubkey: bytes) -> list[int]:
        """All slots this identity leads in the epoch."""
        out = []
        for r, i in enumerate(self.sched):
            if self.pub[i] == pubkey:
                base = self.slot0 + r * self.slots_per_rotation
                out.extend(
                    s for s in range(base, base + self.slots_per_rotation)
                    if s < self.slot0 + self.slots_per_epoch)
        return out
