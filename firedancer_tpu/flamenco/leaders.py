"""Epoch leader schedule: stake-weighted rotation sampling
(ref: src/flamenco/leaders/fd_leaders.h:1-30 — rotations of
SLOTS_PER_ROTATION slots, deduped pubkey table; sampling via a
ChaCha20 RNG over cumulative stakes, ref fd_leaders.c:112 +
src/ballet/wsample/).

The schedule derives deterministically from (epoch, stake map) and is
**draw-for-draw identical to Agave's** (pinned against the reference's
mainnet epoch-454 fixtures in tests/test_leaders_agave.py):

- stakes aggregate by node identity, then sort by stake descending
  with pubkey DESCENDING tie-break (ref fd_leaders.c sort_vote_weights
  _by_stake_id: memcmp(a,b) > 0 orders first);
- the RNG is rand_chacha's ChaCha20Rng seeded with the epoch number as
  little-endian u64 in a zeroed 32-byte key (ref fd_leaders.c:112);
- each of ceil(slots/4) rotations draws Uniform<u64>[0, total_stake)
  with rand 0.7's widening-multiply rejection (MODE_MOD, ref
  fd_chacha_rng.h) and takes the first index whose cumulative stake
  exceeds the draw (WeightedIndex semantics, ref fd_wsample.h:12-15).

An explicit `seed` overrides the epoch-derived key for self-contained
cluster tests; wire-parity requires seed=None.
"""
from __future__ import annotations

import bisect

from ..utils.chacha import ChaChaRng

SLOTS_PER_ROTATION = 4          # FD_EPOCH_SLOTS_PER_ROTATION

# base58 "1111111111indeterminateLeader9QSxFYNqsXA" — the placeholder
# the reference returns for draws landing in the excluded-stake tail
# (ref fd_leaders.h FD_INDETERMINATE_LEADER)
INDETERMINATE_LEADER = bytes.fromhex(
    "00000000000000000000" "99f60f962cdd3821f30c161de30a"
    "0badf00d0badf00d")


class WeightedSampler:
    """Cumulative-stake inversion sampler (src/ballet/wsample/
    fd_wsample.h semantics, sampling WITH replacement; draw-compatible
    with rand's WeightedIndex via roll_mod). An `excluded` weight
    models the reference's poisoned tail: draws landing past the live
    cumulative range return index len(keys) (indeterminate)."""

    def __init__(self, weighted: list[tuple[bytes, int]],
                 excluded: int = 0):
        """weighted: (pubkey, stake), stake > 0; order = consensus
        order (descending stake, pubkey DESC tie-break)."""
        assert weighted, "empty stake set"
        self.keys = [k for k, _ in weighted]
        self.cum = []
        total = 0
        for _, w in weighted:
            assert w > 0
            total += w
            self.cum.append(total)
        self.total = total + excluded

    def sample_idx(self, rng: ChaChaRng) -> int:
        x = rng.roll_mod(self.total)
        return bisect.bisect_right(self.cum, x)

    def sample(self, rng: ChaChaRng) -> bytes:
        i = self.sample_idx(rng)
        return self.keys[i] if i < len(self.keys) else INDETERMINATE_LEADER


def sort_stakes(stakes: dict[bytes, int]) -> list[tuple[bytes, int]]:
    """Consensus stake order: stake descending, pubkey descending
    tie-break (ref fd_leaders.c sort_vote_weights_by_stake_id)."""
    return sorted(((k, s) for k, s in stakes.items() if s > 0),
                  key=lambda kv: (kv[1], kv[0]), reverse=True)


def epoch_seed(epoch: int) -> bytes:
    """Agave's leader-schedule RNG key: epoch as LE u64, zero-padded
    to 32 bytes (ref fd_leaders.c:112-115)."""
    return epoch.to_bytes(8, "little") + bytes(24)


class EpochLeaders:
    def __init__(self, epoch: int, seed: bytes | None,
                 stakes: dict[bytes, int], slots_per_epoch: int,
                 slots_per_rotation: int = SLOTS_PER_ROTATION,
                 excluded_stake: int = 0):
        """stakes: node identity pubkey -> active stake (zero-stake
        nodes never lead). seed=None derives Agave's epoch key; a
        bytes seed overrides it (self-contained clusters only)."""
        self.epoch = epoch
        self.slots_per_epoch = slots_per_epoch
        self.slots_per_rotation = slots_per_rotation
        self.slot0 = epoch * slots_per_epoch
        weighted = sort_stakes(stakes)
        sampler = WeightedSampler(weighted, excluded=excluded_stake)
        rng = ChaChaRng(epoch_seed(epoch) if seed is None else seed)
        n_rot = -(-slots_per_epoch // slots_per_rotation)
        # deduped pubkey table + per-rotation index, the reference's
        # space layout (fd_leaders.h "dedup pubkeys into a lookup table")
        self.pub: list[bytes] = []
        idx_of: dict[bytes, int] = {}
        self.sched: list[int] = []
        for _ in range(n_rot):
            k = sampler.sample(rng)
            i = idx_of.get(k)
            if i is None:
                i = idx_of[k] = len(self.pub)
                self.pub.append(k)
            self.sched.append(i)

    def leader_for(self, slot: int) -> bytes:
        off = slot - self.slot0
        if not 0 <= off < self.slots_per_epoch:
            raise ValueError(f"slot {slot} outside epoch {self.epoch}")
        return self.pub[self.sched[off // self.slots_per_rotation]]

    def leader_slots(self, pubkey: bytes) -> list[int]:
        """All slots this identity leads in the epoch."""
        out = []
        for r, i in enumerate(self.sched):
            if self.pub[i] == pubkey:
                base = self.slot0 + r * self.slots_per_rotation
                out.extend(
                    s for s in range(base, base + self.slots_per_rotation)
                    if s < self.slot0 + self.slots_per_epoch)
        return out
