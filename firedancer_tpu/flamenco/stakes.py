"""Epoch stakes: aggregate delegations -> consensus weights.

The reference computes per-epoch stake weights from the stake
delegations the runtime landed, keyed to vote accounts, then to node
identities for the leader schedule and turbine tree
(ref: src/flamenco/runtime/sysvar/fd_sysvar_stake_history.c usage in
fd_stakes.c — refresh_vote_accounts / stake delegations iteration;
leader schedule input src/flamenco/leaders/fd_leaders.c:112).

This module walks the funk fork (overlay scan: nearest-ancestor record
wins, same visibility rule as funk.rec_query), filters stake-program
accounts, applies the epoch activation window (svm/stake.py
StakeState.active_at), and returns:

  vote_stakes(...)  vote-account pubkey -> active stake
  node_stakes(...)  node identity      -> active stake (via the vote
                    account's node_pubkey)

Feed node_stakes into EpochLeaders (leader schedule), ShredDest
(turbine weights), and the tower's total_stake — one stake source for
all three, the way the reference plumbs epoch stakes everywhere.
"""
from __future__ import annotations

from ..svm.accdb import Account
from ..svm.stake import STAKE_PROGRAM_ID, StakeState
from ..svm.vote import VOTE_PROGRAM_ID, VoteState, _HDR_SZ


def vote_stakes(funk, xid, epoch: int) -> dict[bytes, int]:
    out: dict[bytes, int] = {}
    for key, acct in funk.items_at(xid).items():
        if not isinstance(acct, Account) \
                or acct.owner != STAKE_PROGRAM_ID:
            continue
        try:
            st = StakeState.from_bytes(acct.data)
        except Exception:
            continue
        amt = st.active_at(epoch)
        if amt > 0:
            out[st.voter] = out.get(st.voter, 0) + amt
    return out


def node_stakes(funk, xid, epoch: int) -> dict[bytes, int]:
    """Active stake per node identity: stake -> vote account ->
    node_pubkey (zero for vote accounts that don't resolve)."""
    per_vote = vote_stakes(funk, xid, epoch)
    out: dict[bytes, int] = {}
    for vote_key, amt in per_vote.items():
        va = funk.rec_query(xid, vote_key)
        if not isinstance(va, Account) or va.owner != VOTE_PROGRAM_ID \
                or len(va.data) < _HDR_SZ:
            continue
        node = VoteState.from_bytes(va.data).node_pubkey
        out[node] = out.get(node, 0) + amt
    return out


def total_stake(funk, xid, epoch: int) -> int:
    return sum(vote_stakes(funk, xid, epoch).values())
