"""Epoch stakes: aggregate delegations -> consensus weights.

The reference computes per-epoch stake weights from the stake
delegations the runtime landed, keyed to vote accounts, then to node
identities for the leader schedule and turbine tree
(ref: src/flamenco/runtime/sysvar/fd_sysvar_stake_history.c usage in
fd_stakes.c — refresh_vote_accounts / stake delegations iteration;
leader schedule input src/flamenco/leaders/fd_leaders.c:112).

This module walks the funk fork (overlay scan: nearest-ancestor record
wins, same visibility rule as funk.rec_query), filters stake-program
accounts, applies the epoch activation window (svm/stake.py
StakeState.active_at), and returns:

  vote_stakes(...)  vote-account pubkey -> active stake
  node_stakes(...)  node identity      -> active stake (via the vote
                    account's node_pubkey)

Feed node_stakes into EpochLeaders (leader schedule), ShredDest
(turbine weights), and the tower's total_stake — one stake source for
all three, the way the reference plumbs epoch stakes everywhere.
"""
from __future__ import annotations

from ..svm.accdb import Account
from ..svm.stake import STAKE_PROGRAM_ID, StakeState
from ..svm.vote import VOTE_PROGRAM_ID, VoteState, _HDR_SZ


def _delegations(funk, xid):
    for key, acct in funk.items_at(xid).items():
        if not isinstance(acct, Account) \
                or acct.owner != STAKE_PROGRAM_ID:
            continue
        try:
            yield key, StakeState.from_bytes(acct.data)
        except Exception:
            continue


def read_stake_history(funk, xid) -> dict | None:
    """StakeHistory sysvar -> {epoch: (effective, activating,
    deactivating)}, or None when the account doesn't exist (tests /
    self-contained clusters run step activation)."""
    from ..svm.sysvars import (STAKE_HISTORY_ID,
                               stake_history_from_account)
    acct = funk.rec_query(xid, STAKE_HISTORY_ID) \
        if hasattr(funk, "rec_query") else None
    return stake_history_from_account(
        acct if isinstance(acct, Account) else None)


def cluster_stake_totals(funk, xid, epoch: int,
                         history: dict) -> tuple[int, int, int]:
    """(effective, activating, deactivating) cluster totals at `epoch`
    given the history through epoch-1 — the entry the bank appends to
    the StakeHistory sysvar at each boundary (ref:
    src/flamenco/runtime/sysvar/fd_sysvar_stake_history.c update)."""
    from ..svm.stake import stake_activating_and_deactivating
    te = ta = td = 0
    for _, st in _delegations(funk, xid):
        e, a, d = stake_activating_and_deactivating(st, epoch, history)
        te += e
        ta += a
        td += d
    return te, ta, td


def update_stake_history(funk, xid, epoch: int):
    """Epoch-boundary duty: append `epoch`'s cluster totals to the
    StakeHistory sysvar (newest first)."""
    from ..svm.sysvars import (STAKE_HISTORY_ID, _write,
                               dec_stake_history, enc_stake_history)
    prev = funk.rec_query(xid, STAKE_HISTORY_ID)
    hist = {}
    if isinstance(prev, Account) and len(prev.data) >= 8:
        try:
            hist = dec_stake_history(bytes(prev.data))
        except Exception:
            hist = {}
    totals = cluster_stake_totals(funk, xid, epoch, hist)
    entries = [(epoch, totals)] + sorted(
        ((e, t) for e, t in hist.items() if e != epoch),
        key=lambda kv: -kv[0])
    _write(funk, xid, STAKE_HISTORY_ID, enc_stake_history(entries))
    return totals


def vote_stakes(funk, xid, epoch: int,
                history: dict | None = None) -> dict[bytes, int]:
    """history=None reads the StakeHistory sysvar if present; pass {}
    to force step activation."""
    if history is None:
        history = read_stake_history(funk, xid)
    out: dict[bytes, int] = {}
    for _, st in _delegations(funk, xid):
        amt = st.active_at(epoch, history=history or None)
        if amt > 0:
            out[st.voter] = out.get(st.voter, 0) + amt
    return out


def node_stakes(funk, xid, epoch: int) -> dict[bytes, int]:
    """Active stake per node identity: stake -> vote account ->
    node_pubkey (zero for vote accounts that don't resolve)."""
    per_vote = vote_stakes(funk, xid, epoch)
    out: dict[bytes, int] = {}
    for vote_key, amt in per_vote.items():
        va = funk.rec_query(xid, vote_key)
        if not isinstance(va, Account) or va.owner != VOTE_PROGRAM_ID \
                or len(va.data) < _HDR_SZ:
            continue
        node = VoteState.from_bytes(va.data).node_pubkey
        out[node] = out.get(node, 0) + amt
    return out


def total_stake(funk, xid, epoch: int) -> int:
    return sum(vote_stakes(funk, xid, epoch).values())
