"""solcap — execution capture for differential debugging
(ref: src/flamenco/capture/fd_solcap_writer.h, fd_solcap_diff.c).

The reference captures protobuf records of bank pre/post state and
per-account pre/post data during block execution, then a diff tool
pinpoints the first divergence between two captures (e.g. our runtime
vs Agave, or two builds of ours). This is the same design over this
repo's native artifacts: capture frames ride `utils/checkpt.py`
(CRC-framed, zlib, sha256 trailer — the archival container every other
subsystem uses), and the capture hook wraps `TxnExecutor` without
touching the executor itself: account pre/post states are snapshotted
through the accdb `peek` interface around each `execute` call.

Record kinds (one checkpt frame each, kind-tagged):
  SLOT  slot, parent bank hash
  TXN   index, payload sha256, status, fee, per-account (pubkey,
        lamports, owner, executable, data) pre/post for every static +
        ALUT-resolved key the txn names
  BANK  end-of-slot bank hash

`diff(a, b)` walks two captures in lockstep and reports the FIRST
divergence at (slot, txn, account, field) granularity — the
fd_solcap_diff workflow. CLI: `python -m firedancer_tpu.flamenco.solcap
{dump,diff} ...`.

Account data is stored in full up to DATA_CAP bytes, beyond that as
sha256 + length (diff still detects divergence, just without byte-level
context — same tradeoff the reference's account-data toggle makes).
"""
from __future__ import annotations

import hashlib
import io
import struct
import sys

from ..utils.checkpt import CheckptReader, CheckptWriter

DATA_CAP = 10 * 1024

_K_SLOT, _K_TXN, _K_BANK = 1, 2, 3


# ---------------------------------------------------------------------------
# account snapshot codec
# ---------------------------------------------------------------------------

def _enc_acct(key: bytes, acct) -> bytes:
    """(pubkey, Account|None) -> record bytes."""
    if acct is None:
        return key + b"\x00"
    data = bytes(acct.data)
    full = len(data) <= DATA_CAP
    body = key + (b"\x01" if full else b"\x02")
    body += struct.pack("<QB", acct.lamports, 1 if acct.executable else 0)
    body += bytes(acct.owner)
    if full:
        body += struct.pack("<I", len(data)) + data
    else:
        body += struct.pack("<I", len(data)) + hashlib.sha256(data).digest()
    return body


def _dec_acct(buf: io.BytesIO):
    key = buf.read(32)
    if not key:
        return None
    tag = buf.read(1)[0]
    if tag == 0:
        return key, None
    lamports, execu = struct.unpack("<QB", buf.read(9))
    owner = buf.read(32)
    (dlen,) = struct.unpack("<I", buf.read(4))
    payload = buf.read(dlen if tag == 1 else 32)
    return key, {
        "lamports": lamports, "executable": bool(execu), "owner": owner,
        "data": payload if tag == 1 else None,
        "data_sha256": hashlib.sha256(payload).digest()
        if tag == 1 else payload,
        "data_len": dlen,
    }


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------

class CapWriter:
    def __init__(self, fp, compress: bool = True):
        self._w = CheckptWriter(fp, compress=compress)

    def slot(self, slot: int, parent_hash: bytes):
        self._w.frame(struct.pack("<BQ", _K_SLOT, slot) + parent_hash)

    def txn(self, index: int, payload: bytes, status: str, fee: int,
            pre: dict, post: dict):
        """pre/post: pubkey -> ALREADY-ENCODED account record bytes
        (`_enc_acct`). Callers must encode at snapshot time — holding
        live accdb peek borrows across a write window would record
        post-state as pre-state if accdb ever mutated in place."""
        body = struct.pack("<BI", _K_TXN, index)
        body += hashlib.sha256(payload).digest()
        sb = status.encode()
        body += struct.pack("<B", len(sb)) + sb + struct.pack("<Q", fee)
        keys = sorted(set(pre) | set(post))
        body += struct.pack("<H", len(keys))
        for k in keys:
            body += pre.get(k) or _enc_acct(k, None)
            body += post.get(k) or _enc_acct(k, None)
        self._w.frame(body)

    def bank(self, bank_hash: bytes):
        self._w.frame(struct.pack("<B", _K_BANK) + bank_hash)

    def fini(self):
        self._w.fini()


# ---------------------------------------------------------------------------
# reader
# ---------------------------------------------------------------------------

def read_records(fp):
    """Yield ('slot'|'txn'|'bank', dict) records."""
    for frame in CheckptReader(fp).frames():
        buf = io.BytesIO(frame)
        kind = buf.read(1)[0]
        if kind == _K_SLOT:
            (slot,) = struct.unpack("<Q", buf.read(8))
            yield "slot", {"slot": slot, "parent": buf.read(32)}
        elif kind == _K_TXN:
            (index,) = struct.unpack("<I", buf.read(4))
            payload_sha = buf.read(32)
            slen = buf.read(1)[0]
            status = buf.read(slen).decode()
            (fee,) = struct.unpack("<Q", buf.read(8))
            (n,) = struct.unpack("<H", buf.read(2))
            pre, post = {}, {}
            for _ in range(n):
                k, a = _dec_acct(buf)
                pre[k] = a
                k2, a2 = _dec_acct(buf)
                post[k2] = a2
            yield "txn", {"index": index, "payload_sha256": payload_sha,
                          "status": status, "fee": fee,
                          "pre": pre, "post": post}
        elif kind == _K_BANK:
            yield "bank", {"bank_hash": buf.read(32)}
        else:
            raise ValueError(f"bad solcap record kind {kind}")


# ---------------------------------------------------------------------------
# capture hook around TxnExecutor
# ---------------------------------------------------------------------------

class CapturingExecutor:
    """Wraps a TxnExecutor; snapshots every named account's state via
    accdb.peek before/after each execute and writes TXN records. The
    executor is untouched — capture composes at the call boundary, the
    seam the reference gets from its runtime hooks."""

    def __init__(self, ex, writer: CapWriter):
        self.ex = ex
        self.writer = writer
        self._idx = 0

    def _keys(self, xid, payload: bytes):
        from ..protocol.txn import parse_txn
        try:
            txn = parse_txn(payload)
        except Exception:
            return []
        keys = list(txn.account_keys(payload))
        if txn.version == 0 and txn.aluts:
            from ..svm.alut import AlutResolveError, resolve_loaded_keys
            try:
                extra, _writable = resolve_loaded_keys(
                    self.ex.db, xid, txn, slot=self.ex.slot)
                keys += list(extra)
            except AlutResolveError:
                pass
        return keys

    def execute(self, xid, payload: bytes):
        keys = self._keys(xid, payload)
        # encode AT snapshot time: peek hands out borrows that must not
        # be held across the execute() write window (svm/accdb.py
        # borrow contract)
        pre = {k: _enc_acct(k, self.ex.db.peek(xid, k)) for k in keys}
        res = self.ex.execute(xid, payload)
        post = {k: _enc_acct(k, self.ex.db.peek(xid, k)) for k in keys}
        self.writer.txn(self._idx, payload, res.status, res.fee,
                        pre, post)
        self._idx += 1
        return res

    def __getattr__(self, name):
        return getattr(self.ex, name)


# ---------------------------------------------------------------------------
# diff
# ---------------------------------------------------------------------------

def _acct_fields(a):
    if a is None:
        return {"missing": True}
    return {"lamports": a["lamports"], "owner": a["owner"].hex(),
            "executable": a["executable"], "data_len": a["data_len"],
            "data_sha256": a["data_sha256"].hex()}


def diff(fp_a, fp_b) -> dict | None:
    """First divergence between two captures, or None if identical.
    Returns {"where": ..., "a": ..., "b": ...} with where one of
    slot / record_kind / record_count / txn_payload / txn_status
    (covers fee) / account (pre or post state) / bank_hash."""
    ra, rb = read_records(fp_a), read_records(fp_b)
    slot = None
    while True:
        a = next(ra, None)
        b = next(rb, None)
        if a is None and b is None:
            return None
        if a is None or b is None:
            return {"where": "record_count", "slot": slot,
                    "a": a and a[0], "b": b and b[0]}
        (ka, va), (kb, vb) = a, b
        if ka != kb:
            return {"where": "record_kind", "slot": slot, "a": ka, "b": kb}
        if ka == "slot":
            slot = va["slot"]
            if va != vb:
                return {"where": "slot", "a": va, "b": vb}
        elif ka == "bank":
            if va != vb:
                return {"where": "bank_hash", "slot": slot,
                        "a": va["bank_hash"].hex(),
                        "b": vb["bank_hash"].hex()}
        else:
            if va["payload_sha256"] != vb["payload_sha256"]:
                return {"where": "txn_payload", "slot": slot,
                        "txn": va["index"],
                        "a": va["payload_sha256"].hex(),
                        "b": vb["payload_sha256"].hex()}
            if va["status"] != vb["status"] or va["fee"] != vb["fee"]:
                return {"where": "txn_status", "slot": slot,
                        "txn": va["index"],
                        "a": (va["status"], va["fee"]),
                        "b": (vb["status"], vb["fee"])}
            # pre first: a divergence that entered outside txn execution
            # (e.g. snapshot state) must be pinned to the txn that FIRST
            # saw it, even if execution then overwrites it identically
            for phase in ("pre", "post"):
                for k in sorted(set(va[phase]) | set(vb[phase])):
                    fa = _acct_fields(va[phase].get(k))
                    fb = _acct_fields(vb[phase].get(k))
                    if fa != fb:
                        return {"where": "account", "phase": phase,
                                "slot": slot, "txn": va["index"],
                                "pubkey": k.hex(), "a": fa, "b": fb}
    # unreachable


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    usage = "usage: solcap dump CAP | solcap diff CAP_A CAP_B"
    if not argv or argv[0] not in ("dump", "diff") \
            or len(argv) != (2 if argv[0] == "dump" else 3):
        print(usage, file=sys.stderr)
        return 2
    if argv[0] == "dump":
        with open(argv[1], "rb") as fp:
            for kind, rec in read_records(fp):
                if kind == "txn":
                    rec = {**rec,
                           "payload_sha256": rec["payload_sha256"].hex(),
                           "pre": {k.hex()[:16]: _acct_fields(v)
                                   for k, v in rec["pre"].items()},
                           "post": {k.hex()[:16]: _acct_fields(v)
                                    for k, v in rec["post"].items()}}
                elif kind == "slot":
                    rec = {**rec, "parent": rec["parent"].hex()}
                else:
                    rec = {**rec, "bank_hash": rec["bank_hash"].hex()}
                print(kind, rec)
        return 0
    with open(argv[1], "rb") as fa, open(argv[2], "rb") as fb:
        d = diff(fa, fb)
    if d is None:
        print("captures identical")
        return 0
    print("FIRST DIVERGENCE:", d)
    return 1


if __name__ == "__main__":
    sys.exit(main())
