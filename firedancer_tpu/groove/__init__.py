from .groove import GrooveError, GrooveStore  # noqa: F401
