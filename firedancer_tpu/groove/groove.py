"""groove — disk-backed mmap cold store with size-class allocation.

Re-expression of the reference's groove (ref: src/groove/fd_groove.h:
1-13 — "meta map + volume pool + size-class data heap"; data layout
fd_groove_data.h). Role in the storage stack: funk is the hot
fork-aware KV, vinyl is the log-structured crash-safe stream, groove
is the COLD random-access store — big account payloads that left the
working set but must stay addressable.

Design (TPU-framework shape, not a C port):

  * volumes: fixed-size mmap'd files (`vol-NNNN.groove`) created on
    demand in the store directory — the reference's volume pool.
  * size classes: powers of two from MIN_CLASS to MAX_CLASS; an
    object lives in the smallest class that fits header+payload+crc.
    Per-class free lists make delete->put reuse O(1).
  * records are self-describing on disk: magic, state byte
    (LIVE/DEAD), class, key, payload length, crc32 trailer — so
    open() rebuilds the meta map and the free lists by scanning
    volumes (crash recovery = the scan; a torn write fails its crc
    and is reclaimed as free space).
  * reads are zero-copy memoryviews over the mmap; callers copy if
    they hold the data across a delete (documented borrow, same
    discipline as accdb.peek).

Single-writer / multi-reader per process; cross-process sharing goes
through the filesystem (a fresh open sees every durable record).
"""
from __future__ import annotations

import mmap
import os
import struct
import zlib

MAGIC = 0x67726F32          # "gro2" — v2 layout (lsn in the header)
MAGIC_V1 = 0x67726F6F       # pre-lsn layout: refused loudly, never
#                             silently misread as torn (r4 review)
ST_LIVE = 1
ST_DEAD = 2

MIN_CLASS = 7               # 128 B
MAX_CLASS = 24              # 16 MiB object ceiling
VOLUME_SZ = 1 << 26         # 64 MiB volumes

_HDR = "<IBBH32sIQ"         # magic, state, class, rsvd, key,
#                             data_len, lsn (monotone write sequence —
#                             the duplicate-LIVE tiebreak on recovery)
_HDR_SZ = struct.calcsize(_HDR)
_CRC_SZ = 4


class GrooveError(RuntimeError):
    pass


def _class_for(payload_len: int) -> int:
    need = _HDR_SZ + payload_len + _CRC_SZ
    c = MIN_CLASS
    while (1 << c) < need:
        c += 1
        if c > MAX_CLASS:
            raise GrooveError(f"object too large: {payload_len}")
    return c


class _Volume:
    def __init__(self, path: str, create: bool):
        self.path = path
        if create:
            with open(path, "wb") as f:
                f.truncate(VOLUME_SZ)
        self.f = open(path, "r+b")
        self.mm = mmap.mmap(self.f.fileno(), VOLUME_SZ)
        self.cursor = 0          # bump frontier (recovered on scan)

    def close(self):
        self.mm.flush()
        self.mm.close()
        self.f.close()


class GrooveStore:
    """put/get/delete of 32-byte-keyed blobs over mmap'd volumes."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.vols: list[_Volume] = []
        self.meta: dict[bytes, tuple[int, int]] = {}   # key -> (vol, off)
        self.free: dict[int, list[tuple[int, int]]] = {}
        self._lsn = 0
        self._live_lsn: dict[bytes, int] = {}
        self.stats = {"puts": 0, "gets": 0, "deletes": 0,
                      "reused": 0, "torn_reclaimed": 0,
                      "dup_reconciled": 0}
        for name in sorted(os.listdir(directory)):
            if name.startswith("vol-") and name.endswith(".groove"):
                self._scan(_Volume(os.path.join(directory, name),
                                   create=False))

    # -- recovery scan ------------------------------------------------------

    def _scan(self, vol: _Volume):
        vid = len(self.vols)
        self.vols.append(vol)
        off = 0
        while off + _HDR_SZ <= VOLUME_SZ:
            magic, state, cls, _, key, dlen, lsn = struct.unpack_from(
                _HDR, vol.mm, off)
            if magic == MAGIC_V1:
                raise GrooveError(
                    f"{vol.path}: v1 groove volume (pre-lsn layout) — "
                    f"refusing to misread it; migrate or remove")
            if magic != MAGIC:
                break                         # frontier reached
            if not MIN_CLASS <= cls <= MAX_CLASS:
                break                         # corrupt header: stop at
                # the frontier rather than walk garbage (records past a
                # corrupt class byte are unreachable anyway — the slot
                # stride is unknown)
            sz = 1 << cls
            # dlen bounds-check BEFORE the crc read: a corrupt length
            # must reclaim the slot, not crash open() (the recovery
            # contract)
            if state == ST_LIVE and _HDR_SZ + dlen + _CRC_SZ <= sz \
                    and off + sz <= VOLUME_SZ:
                end = off + _HDR_SZ + dlen
                crc, = struct.unpack_from("<I", vol.mm, end)
                if zlib.crc32(vol.mm[off + _HDR_SZ:end]) == crc:
                    self._lsn = max(self._lsn, lsn)
                    prev = self.meta.get(key)
                    if prev is not None:
                        # crash window duplicate (put() died between
                        # writing the new copy and killing the old):
                        # higher lsn wins, the loser is tombstoned so
                        # a later delete cannot be resurrected
                        self.stats["dup_reconciled"] += 1
                        if lsn > self._live_lsn[key]:
                            self._kill(*prev)
                            self.meta[key] = (vid, off)
                            self._live_lsn[key] = lsn
                        else:
                            self._kill(vid, off)
                    else:
                        self.meta[key] = (vid, off)
                        self._live_lsn[key] = lsn
                else:                         # torn write: reclaim
                    self.stats["torn_reclaimed"] += 1
                    self.free.setdefault(cls, []).append((vid, off))
            elif state == ST_LIVE:            # corrupt dlen: reclaim
                self.stats["torn_reclaimed"] += 1
                self.free.setdefault(cls, []).append((vid, off))
            else:
                self.free.setdefault(cls, []).append((vid, off))
            off += sz
        vol.cursor = off

    # -- allocation ---------------------------------------------------------

    def _alloc(self, cls: int) -> tuple[int, int]:
        fl = self.free.get(cls)
        if fl:
            self.stats["reused"] += 1
            return fl.pop()
        sz = 1 << cls
        for vid, vol in enumerate(self.vols):
            if vol.cursor + sz <= VOLUME_SZ:
                off = vol.cursor
                vol.cursor += sz
                return (vid, off)
        path = os.path.join(self.dir, f"vol-{len(self.vols):04d}.groove")
        vol = _Volume(path, create=True)
        self.vols.append(vol)
        vol.cursor = sz
        return (len(self.vols) - 1, 0)

    # -- operations ---------------------------------------------------------

    def put(self, key: bytes, data: bytes):
        """Insert or overwrite. Overwrite writes the new copy first,
        then tombstones the old; a crash between the two leaves BOTH
        live and the recovery scan keeps the higher-lsn copy (the new
        one when its crc completed, otherwise the old) — never a torn
        value, never a resurrectable duplicate."""
        if len(key) != 32:
            raise GrooveError("key must be 32 bytes")
        cls = _class_for(len(data))
        vid, off = self._alloc(cls)
        mm = self.vols[vid].mm
        self._lsn += 1
        struct.pack_into(_HDR, mm, off, MAGIC, ST_LIVE, cls, 0, key,
                         len(data), self._lsn)
        end = off + _HDR_SZ
        mm[end:end + len(data)] = data
        struct.pack_into("<I", mm, end + len(data),
                         zlib.crc32(data))
        old = self.meta.get(key)
        self.meta[key] = (vid, off)
        self._live_lsn[key] = self._lsn
        if old is not None:
            self._kill(*old)
        self.stats["puts"] += 1

    def get(self, key: bytes) -> memoryview | None:
        loc = self.meta.get(key)
        if loc is None:
            return None
        vid, off = loc
        mm = self.vols[vid].mm
        dlen = struct.unpack_from(_HDR, mm, off)[5]
        self.stats["gets"] += 1
        return memoryview(mm)[off + _HDR_SZ:off + _HDR_SZ + dlen]

    def delete(self, key: bytes) -> bool:
        loc = self.meta.pop(key, None)
        self._live_lsn.pop(key, None)
        if loc is None:
            return False
        self._kill(*loc)
        self.stats["deletes"] += 1
        return True

    def _kill(self, vid: int, off: int):
        mm = self.vols[vid].mm
        cls = mm[off + 5]
        mm[off + 4] = ST_DEAD
        self.free.setdefault(cls, []).append((vid, off))

    def flush(self):
        for v in self.vols:
            v.mm.flush()

    def close(self):
        for v in self.vols:
            v.close()
        self.vols.clear()
        self.meta.clear()

    def __len__(self):
        return len(self.meta)
