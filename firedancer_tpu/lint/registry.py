"""The tile-arg key registry: one table shared by fdlint's graph
analyzer (dangling-reference checks) and `app/config.py` (unknown-key
rejection with a did-you-mean hint).

Every adapter in disco/tiles.py reads its args with `args.get(...)` /
`args[...]`; this table is the static mirror of those reads. A key's
value classifies what it references so the linter can resolve it
against the topology:

    None        plain value, nothing to resolve
    IN          a link name that must be among the tile's ins
    OUT         a link name that must be among the tile's outs
    IN_LIST     list of link names, each among the tile's ins
    OUT_LIST    list of link names, each among the tile's outs
    TCACHE      a tcache name declared in the topology
    TILE        another tile's name

Keys every tile understands (consumed by the stem/launcher/builder,
not the adapter) live in COMMON_KEYS.
"""
from __future__ import annotations

IN, OUT, IN_LIST, OUT_LIST, TCACHE, TILE = (
    "in", "out", "in[]", "out[]", "tcache", "tile")

# consumed by topo.build / launch.tile_main / stem, valid on any tile
COMMON_KEYS: dict[str, str | None] = {
    "supervise": None,      # disco/supervise.py policy table
    "chaos": None,          # utils/chaos.py fault plan
    "trace": None,          # trace/recorder.py per-tile override table
    "prof": None,           # prof/recorder.py per-tile override table
    "shed": None,           # disco/shed.py per-tile policing override
    "cpu_idx": None,        # launch: sched_setaffinity pin
    "sandbox": None,        # launch: utils/sandbox hardening
    "sandbox_files": None,
    "lazy_ns": None,        # stem: pinned housekeeping cadence
    "lazy_auto": None,      # stem: depth-derived cadence
}

# [trace] topology-section keys (mirror of trace/recorder.py
# TRACE_DEFAULTS / TILE_TRACE_KEYS — tests/test_trace.py keeps the
# mirror honest). `tiles` entries are tile-name references, resolved by
# the graph analyzer's bad-trace check.
TRACE_SECTION_KEYS = ("enable", "depth", "sample", "tiles")
TILE_TRACE_KEYS = ("enable", "depth", "sample")

# [prof] topology-section keys (mirror of prof/recorder.py
# PROF_DEFAULTS / TILE_PROF_KEYS — tests/test_prof.py keeps the mirror
# honest). `tiles`/`breach_capture` entries are tile-name references,
# resolved by the graph analyzer's bad-prof check.
PROF_SECTION_KEYS = ("enable", "hz", "slots", "ring", "stack_depth",
                     "tiles", "capture_ms", "breach_capture")
TILE_PROF_KEYS = ("enable", "hz", "slots", "ring", "stack_depth")

# [slo] topology-section keys (mirror of disco/slo.py SLO_DEFAULTS /
# TARGET_KEYS — tests/test_metrics.py keeps the mirror honest).
# Target expressions reference tiles/metrics/links, resolved by the
# graph analyzer's bad-slo check.
SLO_SECTION_KEYS = ("fast_window_s", "slow_window_s", "burn_fast",
                    "burn_slow", "target")
SLO_TARGET_KEYS = ("name", "expr", "fast_window_s", "slow_window_s",
                   "burn_fast", "burn_slow")

# [shed] topology-section keys (mirror of disco/shed.py SHED_DEFAULTS /
# TILE_SHED_KEYS — tests/test_shed.py keeps the mirror honest). The
# per-tile `shed` override (COMMON_KEYS) takes the same table; both are
# validated by normalize_shed at config load, topo.build, and the graph
# analyzer's bad-shed rule.
SHED_SECTION_KEYS = ("enable", "rate_pps", "burst", "max_peers",
                     "min_stake", "overload_hold_s", "stakes")
TILE_SHED_KEYS = SHED_SECTION_KEYS

# [funk] topology-section keys (mirror of funk/shmfunk.py
# FUNK_DEFAULTS — tests/test_exec_tile.py keeps the mirror honest).
# Validated by normalize_funk at config load, topo.build (which carves
# the shm store for backend="shm"), and the graph analyzer's bad-funk
# rule.
FUNK_SECTION_KEYS = ("backend", "rec_max", "txn_max", "heap_mb")

# [replay] topology-section keys (mirror of tiles/replay.py
# REPLAY_DEFAULTS — tests/test_follower.py keeps the mirror honest).
# Validated by normalize_replay at config load, topo.build, and the
# graph analyzer's bad-replay rule.
REPLAY_SECTION_KEYS = ("exec_tile_cnt", "redispatch_s", "verify_poh",
                       "hashes_per_tick")

# [snapshot] topology-section keys (mirror of tiles/snapshot.py
# SNAPSHOT_DEFAULTS — tests/test_follower.py keeps the mirror honest).
# Validated by normalize_snapshot at config load, topo.build, and the
# graph analyzer's bad-snapshot rule.
SNAPSHOT_SECTION_KEYS = ("path", "every_slots", "min_slot", "compress",
                         "chunk")

# [flight] topology-section keys (mirror of flight/__init__.py
# FLIGHT_DEFAULTS — tests/test_flight.py keeps the mirror honest).
# Validated by normalize_flight at config load, topo.build, and the
# graph analyzer's bad-flight rule.
FLIGHT_SECTION_KEYS = ("dir", "segment_mb", "retain_mb", "hz",
                       "sources", "incident_window_s", "node_id")

# [tune] topology-section keys (mirror of tune/__init__.py
# TUNE_DEFAULTS / KNOB_KEYS — tests/test_tune.py keeps the mirror
# honest). [tune.knob.<name>] names resolve against the tune KNOBS
# catalog; validated by normalize_tune at config load, topo.build
# (mailbox carve), and the graph analyzer's bad-tune rule.
TUNE_SECTION_KEYS = ("enable", "interval_s", "cooldown_s", "recovery_s",
                     "hysteresis", "max_moves", "window_s", "bp_ref",
                     "knob")
TUNE_KNOB_KEYS = ("min", "max", "step", "default")

# [witness] topology-section keys (mirror of witness/plan.py
# WITNESS_DEFAULTS / WITNESS_STAGE_KEYS — tests/test_witness.py keeps
# the mirror honest). Stage names in `stages` / [witness.stage.<name>]
# resolve against the witness/plan.py STAGES catalog; validated by
# normalize_witness at config load, plan build (fdwitness run/dry-run),
# and the graph analyzer's bad-witness rule.
WITNESS_SECTION_KEYS = ("stages", "out_dir", "round", "stage_timeout_s",
                        "probe_timeout_s", "park_s", "park_max_s",
                        "keep_going", "report", "stage")
WITNESS_STAGE_KEYS = ("enable", "timeout_s", "cmd", "env")

TILE_ARGS: dict[str, dict[str, str | None]] = {
    "synth": {"count": None, "burst": None, "unique": None, "seed": None,
              "rate_tps": None},
    "verify": {"batch": None, "max_len": None, "tcache": TCACHE,
               "device_retries": None, "device_timeout_s": None,
               "device_fail_limit": None, "rr_cnt": None, "rr_idx": None,
               "devices": None, "coalesce_us": None,
               # rr-sharded scale-out (config-side expansion in
               # app/config.py: tile_cnt shards, one out link each,
               # optional cpu0+i core pinning; a list-valued tcache
               # distributes per shard)
               "tile_cnt": None, "cpu0": None,
               # front-door bulk pre-filter (r14): mode =
               # "bulk_prefilter" gates every strict dispatch behind
               # the RLC batch kernel — fail -> bisect, shed garbage
               # halves under ingest saturation (tiles/verify.py)
               "mode": None, "prefilter_shed": None},
    "dedup": {"tcache": TCACHE, "batch": None},
    "pack": {"txn_in": IN, "bank_links": OUT_LIST, "done_links": IN_LIST,
             "slot_in": IN, "bundle_in": IN, "slot_ms": None,
             "batch": None, "max_txn_per_microblock": None,
             "wave": None,
             # resolved_in: txn_in carries RESOLVED frames from a
             # resolv tile (account sets + cost precomputed upstream —
             # pack/scheduler.py meta_from_resolved), the reference's
             # resolv->pack seam (src/discof/resolv/)
             "resolved_in": None},
    "bank": {"exec": None, "poh_link": OUT, "forward_payloads": None,
             "slots_per_epoch": None, "genesis_ckpt": None,
             "genesis": None, "genesis_synth": None, "rpc_port": None,
             "ws_port": None, "wave": None, "redispatch_s": None,
             # exec tile fan-out (r16): one dispatch out link + one
             # completion in link per exec shard; the bank keeps wave
             # scheduling/commit ordering/poh handoff, execution runs
             # in the exec tile family over the shm funk store
             "exec_links": OUT_LIST, "exec_done": IN_LIST},
    "sock": {"port": None, "bind_addr": None, "batch": None, "mtu": None},
    "quic": {"port": None, "bind_addr": None, "batch": None, "mtu": None},
    "poh": {"hashes_per_tick": None, "ticks_per_slot": None,
            "seed": None, "slot_link": OUT},
    "shred": {"mode": None, "req": OUT, "resp": IN,
              "shreds_link": OUT, "batches_link": OUT,
              "turbine_in": IN, "identity_hex": None, "cluster": None,
              "shred_version": None, "fanout": None, "flush_bytes": None,
              "drop_slot_every": None, "leader_pubkey_hex": None},
    "sign": {"seed": None, "clients": None},   # clients resolved specially
    "tower": {"total_stake": None},
    "repair": {"req": OUT, "resp": IN, "identity_hex": None,
               "port": None, "bind_addr": None, "peers": None,
               "root_slot": None},
    "replay": {"genesis": None, "genesis_synth": None,
               "hashes_per_tick": None, "verify_poh": None,
               "slots_per_epoch": None,
               # follower fan-out (r17): same shape as the bank's exec
               # family seam, plus the catch-up surface — leader
               # bank-hash pins, snapshot-gated cold start, periodic
               # snapshot writing (defaults from [replay]/[snapshot])
               "exec_links": OUT_LIST, "exec_done": IN_LIST,
               "redispatch_s": None, "expected": None,
               "wait_restore": None, "snapshot_path": None,
               "snapshot_every": None, "snapshot_compress": None},
    "send": {"req": OUT, "resp": IN, "identity_hex": None,
             "vote_account_hex": None, "dest": None},
    "archiver": {"path": None},
    "playback": {"path": None},
    "gossip": {"seed": None, "port": None, "bind_addr": None,
               "entrypoints": None, "publish": None,
               "device_verify": None,
               # gossvf bulk pre-filter (r14): front the per-packet
               # device sigcheck with the RLC batch kernel
               # (gossip/gossvf.py mode="bulk")
               "gossvf_bulk": None},
    "snapld": {"path": None, "chunk": None, "stale_path": None},
    "snapdc": {},
    "snapin": {"format": None, "min_slot": None},
    "metric": {"port": None, "bind_addr": None, "healthz_stale_s": None},
    # flight recorder tile (r19): all configuration rides the plan's
    # [flight] section — the adapter reads no args at all
    "flight": {},
    # adaptive controller tile (r20, fdtune): all configuration rides
    # the plan's [tune] section — the adapter reads no args at all
    "controller": {},
    "bundle": {"engine": None, "path": None, "authority": None},
    "plugin": {"sock_path": None, "data_hex_max": None},
    "netlnk": {},
    "vinyl": {"path": None, "gc": None},
    "gui": {"port": None, "bind_addr": None, "tps_tile": TILE,
            "tps_metric": None,                 # validated against TILE's kind
            # fdgui v2 knobs (gui/schema.py GUI_DEFAULTS is the
            # authoritative mirror — tests/test_gui.py keeps it honest)
            "ws_max_clients": None, "ws_queue": None,
            "ws_sndbuf": None, "bench_glob": None,
            "report_on_halt": None},
    "cswtch": {},
    # exec tile family (r16, ref: src/discof/exec/fd_exec_tile.c):
    # consumes the bank's conflict-group dispatch frames, executes via
    # the WaveExecutor against the shm funk store, publishes
    # completion frags; declared via tile_cnt (sharded_tile) with a
    # per-shard ins distribution
    "exec": {"batch": None, "rr_cnt": None, "rr_idx": None,
             "tile_cnt": None, "cpu0": None},
    # resolv tile (r16, ref: src/discof/resolv/): ahead of pack —
    # parses txns, resolves v0 ALUT loads + checks the fee payer
    # against the shm store, emits RESOLVED frames
    "resolv": {"batch": None, "fee_payer_check": None},
    "ipecho": {"shred_version": None, "port": None, "bind_addr": None},
    "pcap": {"path": None, "realtime": None, "loop": None},
    "sink": {"batch": None},
}


def known_keys(kind: str) -> set[str]:
    """All valid [[tile]] keys for a kind (structural + common + args);
    empty set means the kind itself is unknown."""
    if kind not in TILE_ARGS:
        return set()
    return ({"name", "kind", "ins", "outs"} | set(COMMON_KEYS)
            | set(TILE_ARGS[kind]))


def suggest(key: str, candidates) -> str:
    """did-you-mean suffix for an unknown key/kind ('' if no close
    match)."""
    import difflib
    close = difflib.get_close_matches(key, sorted(candidates), n=1)
    return f" (did you mean {close[0]!r}?)" if close else ""


# Frame-growth contracts for the mtu-underflow rule: tiles that re-wrap
# an in-link payload into a larger out-link frame, mirrored from the
# adapters' own boot-time checks (disco/tiles.py Bank/Poh) and the
# verbatim-forwarding hot paths (verify/dedup publish the original
# payload). Checked statically so a too-small link fails review, not
# boot (or worse, mid-flight publish).
FORWARD_VERBATIM = {"verify", "dedup"}    # every out mtu >= max in mtu
BANK_POH_GROWTH = -20 + 42     # microblock hdr 20 -> poh frame hdr 42
POH_ENTRY_GROWTH = -42 + 116   # poh frame hdr 42 -> entry frame hdr 116

# Minimum out-link mtus per wire family, for the wire-mtu rule (the
# r16/r17 extension of the growth contracts above): a link too small
# for even one frame of its producer kind's wire is a review-time
# finding, not a publish assert. Mirrored from the frame layouts in
# disco/tiles.py (exec wire), tiles/shred.py (slice + shred wire) and
# tiles/tower.py (vote frame) — lint/abi.py's WIRE_CONTRACTS catalog
# pins the same formats, and tests/test_lint.py recomputes these from
# the live struct sizes.
EXEC_DISPATCH_MIN_MTU = 18 + 80   # <QQH> header + one 80B txn row
EXEC_DONE_MIN_MTU = 16            # <QII> completion frame
SLICE_MIN_MTU = 13 + 1            # <QIB> slice header + >=1 payload byte
SHRED_WIRE_MIN_MTU = 0x49 + 4     # fixed shred header through the idx u32
TOWER_WIRE_MIN_MTU = 1 + 32 + 8 + 32   # vote frame (largest fixed frame)

# TILE_ARGS keys consumed OUTSIDE the adapter class (config-side
# expansion in app/config.py, topo.build sizing, launch) — the
# registry-drift analyzer exempts these from its "registered but never
# consumed by the adapter" direction. Every entry names its consumer.
EXTERNAL_ARG_KEYS: dict[str, tuple[str, ...]] = {
    # app/config.py sharded_tile expansion: tile_cnt shards, cpu0+i
    # core pinning, per-shard out-link/tcache distribution
    "verify": ("tile_cnt", "cpu0"),
    # rr_cnt/rr_idx are stamped onto every shard by Topology's generic
    # shard expansion (disco/topo.py); exec ignores them (it shards by
    # dedicated per-shard exec_links, not round-robin seq filtering)
    "exec": ("tile_cnt", "cpu0", "rr_cnt", "rr_idx"),
    # the gui adapter hands its args dict wholesale to
    # gui/schema.py normalize_gui, which validates and consumes every
    # key at config load
    "gui": ("bench_glob", "bind_addr", "port", "report_on_halt",
            "tps_metric", "tps_tile", "ws_max_clients", "ws_queue",
            "ws_sndbuf"),
}
