"""fdlint core: findings, rule catalog, suppressions, baseline, output.

The moral equivalent of the reference's build-time discipline: the tile
graph, credit flow, and shared-memory protocol are statically knowable,
so violations should be REVIEW-time findings, not runtime wedges. Every
analyzer family (graph/contracts/jaxlint) emits the same `Finding`
shape through the same suppression/baseline filters, so one CLI and one
pytest gate cover all of them.

Suppression syntax (checked against the rule catalog):

    x = thing()        # fdlint: disable=<rule-id>[,<rule-id2>] — why
    # fdlint: disable=<rule-id> — why          (applies to next line)

Baseline (`lint-baseline.toml` at the repo root) grandfathers legacy
findings by (rule, path[, line]) so the CLI can gate NEW findings while
a burn-down is in flight; intentional keeps belong inline (with a
justification), not in the baseline.
"""
from __future__ import annotations

import json
import re
from dataclasses import dataclass

SEVERITIES = ("error", "warning")

# rule id -> (family, severity, one-line description). THE catalog:
# analyzers must emit ids from here, suppressions are validated against
# it, and the README table is generated from these descriptions.
RULES: dict[str, tuple[str, str, str]] = {
    # -- topology graph family (lint/graph.py) ---------------------------
    "dead-link": (
        "graph", "error",
        "link is produced but never consumed (dead ring: frags are "
        "dropped silently and the config lies about the dataflow)"),
    "orphan-link": (
        "graph", "error",
        "link is consumed but never produced (consumer polls a ring "
        "that never advances)"),
    "dup-producer": (
        "graph", "error",
        "link has two producers — rings are SPMC, a second producer "
        "corrupts seq ordering"),
    "depth-pow2": (
        "graph", "error",
        "link depth is not a positive power of two (ring init fails "
        "at build)"),
    "mtu-underflow": (
        "graph", "error",
        "out link mtu is smaller than the producing tile's worst-case "
        "frame (publish asserts mid-flight instead of at review)"),
    "backpressure-cycle": (
        "graph", "error",
        "reliable-consumption cycle between tiles: every member waits "
        "on the next one's credits — a static deadlock candidate"),
    "reliable-sink": (
        "graph", "error",
        "reliable input on a tile kind that never publishes consumer "
        "progress (no in_seqs): its fseq never advances and the "
        "producer wedges after `depth` frags"),
    "unread-in": (
        "graph", "warning",
        "tile declares ins but its adapter kind never reads in_rings "
        "(dead wiring: the frags are never consumed)"),
    "unknown-kind": (
        "graph", "error",
        "tile kind has no registered adapter"),
    "bad-supervise": (
        "graph", "error",
        "[tile.supervise] table rejected by the supervise.py schema "
        "(unknown key, bad policy, out-of-range value)"),
    "bad-chaos": (
        "graph", "error",
        "chaos fault plan rejected by the chaos.py schema (unknown "
        "action) or stall_fseq names a link the tile does not consume"),
    "dangling-ref": (
        "graph", "error",
        "tile arg references an unknown link/tile/tcache, or a link "
        "outside the tile's declared ins/outs"),
    "bad-trace": (
        "graph", "error",
        "[trace] section or [tile.trace] table rejected by the fdtrace "
        "schema (unknown key, non-power-of-two depth, sample < 1) or "
        "trace.tiles names an undeclared tile"),
    "bad-slo": (
        "graph", "error",
        "[slo] section rejected by the disco/slo.py schema (unknown "
        "key, bad expression grammar, out-of-range window/burn) or a "
        "target references an undeclared tile/metric/link"),
    "bad-prof": (
        "graph", "error",
        "[prof] section or [tile.prof] table rejected by the fdprof "
        "schema (unknown key, non-power-of-two slots/ring, hz out of "
        "range) or prof.tiles / prof.breach_capture names an "
        "undeclared tile"),
    "bad-gui": (
        "graph", "error",
        "[tile.gui] args rejected by the gui schema (unknown key, "
        "out-of-range ws_max_clients/ws_queue/ws_sndbuf, empty "
        "tps/bench/report strings) — the fdgui v2 knob set, "
        "gui/schema.py normalize_gui"),
    "bad-shed": (
        "graph", "error",
        "[shed] section or per-tile `shed` override rejected by the "
        "disco/shed.py schema (unknown key with did-you-mean, "
        "non-positive rate_pps/burst/overload_hold_s, max_peers < 2, "
        "malformed stakes table), or shed configured on a tile kind "
        "with no ingest door to police"),
    "bad-witness": (
        "graph", "error",
        "[witness] section rejected by the witness/plan.py schema "
        "(unknown key with did-you-mean, unknown stage name, "
        "non-positive timeout/park values, malformed per-stage "
        "cmd/env override) — the fdwitness sweep plan must validate "
        "at review, not at 3am when the tunnel finally comes up"),
    "bad-funk": (
        "graph", "error",
        "[funk] section rejected by the funk/shmfunk.py schema "
        "(unknown key with did-you-mean, unknown backend, rec_max/"
        "txn_max < 16, heap_mb < 1) — the account-store carve must "
        "validate at review, not when topo.build sizes the workspace"),
    "bad-replay": (
        "graph", "error",
        "[replay] section rejected by the tiles/replay.py schema "
        "(unknown key with did-you-mean, exec_tile_cnt < 0, "
        "redispatch_s <= 0, hashes_per_tick < 1) — the follower "
        "fan-out defaults must validate at review, not when the "
        "catch-up node boots"),
    "bad-snapshot": (
        "graph", "error",
        "[snapshot] section rejected by the tiles/snapshot.py schema "
        "(unknown key with did-you-mean, negative every_slots/"
        "min_slot, chunk < 64) — the snapshot path/cadence the "
        "snapld/snapin/replay tiles share must validate at review, "
        "not mid-restore"),
    "bad-flight": (
        "graph", "error",
        "[flight] section rejected by the flight/__init__.py schema "
        "(unknown key with did-you-mean, empty dir, segment_mb <= 0, "
        "retain_mb < segment_mb, hz out of (0,1000], negative "
        "incident_window_s, node_id not u16, unknown source family) — "
        "the telemetry-archive config must validate at review, not "
        "when the recorder tile boots"),
    "bad-tune": (
        "graph", "error",
        "[tune] section rejected by the tune/__init__.py schema "
        "(unknown key with did-you-mean, non-positive interval/"
        "cooldown/recovery/window, hysteresis outside (0,1), "
        "cooldown_s < interval_s, [tune.knob.<name>] naming an "
        "unknown knob or with min > max / default outside bounds), "
        "or a controller tile is declared without an enabled [tune] "
        "section to give it a knob mailbox"),
    # -- tile-contract family (lint/contracts.py) ------------------------
    "reserved-metric": (
        "contract", "error",
        "tile METRICS name collides with the supervisor's reserved "
        "top slots (sup_restarts/sup_watchdog_trips/sup_down)"),
    "metrics-overflow": (
        "contract", "error",
        "tile declares more metric slots than SUP_SLOT_MIN — the "
        "topology builder will reject the kind at build"),
    "undeclared-gauge": (
        "contract", "error",
        "GAUGES or DEVICE_SERIES entry is not a declared METRICS name "
        "(the prometheus renderer matches both declarations by name)"),
    "dup-metric": (
        "contract", "error",
        "duplicate name in a tile's METRICS declaration (slots are "
        "positional; the second name shadows the first)"),
    "uncredited-publish": (
        "contract", "error",
        "Ring.publish with no credit check in the same function — "
        "tango order requires publish inside a credit window "
        "(fd_fctl discipline) or it laps reliable consumers"),
    "stale-outside-supervision": (
        "contract", "error",
        "Fseq.mark_stale called from tile code — the STALE sentinel "
        "is supervision-owned (supervisor marks, rejoin clears)"),
    "per-frag-loop": (
        "contract", "error",
        "per-frag Python for loop calling a single-item hot-path API "
        "(.frag/.publish/tcache .insert/.query) inside a tile's "
        "poll_once call closure — batched equivalents exist "
        "(frag_batch/publish_batch/insert_batch/query_batch); "
        "per-txn Python is the host-pipeline bottleneck the batched "
        "tile contract forbids"),
    "silent-consumer": (
        "contract", "error",
        "registered adapter reads ctx.in_rings but defines no "
        "in_seqs(): the stem never publishes its consumer progress, "
        "so any reliable upstream producer wedges"),
    # -- JAX/Pallas purity family (lint/jaxlint.py) ----------------------
    "host-sync-item": (
        "jax", "error",
        ".item() inside jitted code forces a device->host sync per "
        "call (or a tracer error under jit)"),
    "host-cast-traced": (
        "jax", "error",
        "float()/int() on a traced value inside jitted code — host "
        "sync or ConcretizationTypeError"),
    "numpy-in-jit": (
        "jax", "error",
        "np.* call inside jitted code: applied to a traced array it "
        "forces a host sync; constants belong hoisted out of the "
        "traced region"),
    "traced-bool": (
        "jax", "error",
        "Python if/while on a jnp expression inside jitted code — "
        "traced booleans cannot drive Python control flow"),
    "x64-in-kernel": (
        "jax", "error",
        "int64/float64 dtype inside jitted/Pallas code — x64 is "
        "disabled on TPU, the dtype silently truncates or fails"),
    "prng-key-reuse": (
        "jax", "error",
        "same PRNG key passed to multiple jax.random draws without a "
        "split — correlated randomness"),
    "missing-donate": (
        "jax", "warning",
        "jax.jit entry point without donate_argnums/donate_argnames: "
        "large device inputs are copied instead of reused"),
    # -- wire/shm ABI family (lint/abi.py) -------------------------------
    "wire-mismatch": (
        "abi", "error",
        "a cataloged cross-process wire site drifted: the struct "
        "format strings extracted at the site no longer match the "
        "WIRE_CONTRACTS catalog (or the site vanished) — producer and "
        "consumer tiles would parse different bytes"),
    "wire-mtu": (
        "abi", "error",
        "link mtu below the wire family's minimum frame for its "
        "producer kind (exec dispatch header+row, exec done, shred "
        "slice/shred wire, tower vote, snapshot chunk) — publish "
        "asserts mid-flight instead of at review"),
    "short-key": (
        "abi", "error",
        "bytes key reaches a store/funk WRITE api without a provable "
        "32-byte width — the native store ABI reads EXACTLY 32 bytes, "
        "so a shorter buffer hashes per-process trailing garbage and "
        "the record becomes unfindable from other tiles (the r17 "
        "_key32 bug class)"),
    "registry-drift": (
        "abi", "error",
        "lint/registry.py mirror disagrees with the code it mirrors: "
        "an adapter consumes an args key the registry does not "
        "declare (or declares one nothing consumes), or a "
        "*_SECTION_KEYS tuple drifted from its module's *_DEFAULTS"),
    # -- shm single-writer family (lint/ownership.py) --------------------
    "dual-writer": (
        "ownership", "error",
        "write API of a single-writer shm region (trace ring, sup_* "
        "metric slots, restore marker, funk root) called from a "
        "module outside the region's cataloged writer set — two "
        "uncoordinated writers tear the region (the supervisor's "
        "post-mortem blackbox append is the annotated handoff "
        "exemplar)"),
    "torn-read": (
        "ownership", "error",
        "multiple subscript reads of a live shm u64 view in one "
        "function — a concurrent writer can update between the "
        "accesses, so the fields read belong to different states; "
        "snapshot with .copy() (tango.u64_snapshot) first"),
    # -- suppression hygiene (lint/core.py itself) -----------------------
    "bad-suppression": (
        "core", "error",
        "a '# fdlint: disable=' comment names a rule id not in the "
        "catalog — the suppression has no effect (typo?)"),
}


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int          # 1-based; 0 = file-level
    message: str

    @property
    def severity(self) -> str:
        return RULES[self.rule][1]

    @property
    def family(self) -> str:
        return RULES[self.rule][0]

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.severity}: "
                f"[{self.rule}] {self.message}")


def finding(rule: str, path: str, line: int, message: str) -> Finding:
    if rule not in RULES:
        raise KeyError(f"unknown fdlint rule {rule!r}")
    return Finding(rule, path, int(line), message)


# ---------------------------------------------------------------------------
# inline suppressions
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*fdlint:\s*disable=([A-Za-z0-9_,\- ]+)")


def suppressions(source: str) -> dict[int, set[str]]:
    """line (1-based) -> suppressed rule ids. A suppression on a line
    holding only the comment also covers the NEXT line."""
    out: dict[int, set[str]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        out.setdefault(i, set()).update(rules)
        if text.split("#", 1)[0].strip() == "":     # comment-only line
            out.setdefault(i + 1, set()).update(rules)
    return out


def filter_suppressed(findings: list[Finding],
                      source: str) -> list[Finding]:
    sup = suppressions(source)
    return [f for f in findings
            if f.rule not in sup.get(f.line, ()) and
            "all" not in sup.get(f.line, ())]


def check_suppressions(source: str, path: str) -> list[Finding]:
    """Validate disable= tokens against the catalog: a typo'd rule id
    suppresses nothing, which for a warning-severity rule can go
    unnoticed forever — so the typo itself is an error finding."""
    out = []
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        for r in m.group(1).split(","):
            r = r.strip()
            if r and r != "all" and r not in RULES:
                from .registry import suggest
                out.append(finding(
                    "bad-suppression", path, i,
                    f"disable={r!r} is not a known rule id"
                    f"{suggest(r, RULES)}"))
    return out


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> list[dict]:
    """[[finding]] entries with rule, path, optional line. Missing file
    -> empty baseline."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return []
    from ..app.config import tomllib       # shared TOML-parser fallback
    doc = tomllib.loads(data.decode())
    entries = doc.get("finding", [])
    for e in entries:
        if "rule" not in e or "path" not in e:
            raise ValueError(
                f"{path}: baseline entry needs rule + path: {e}")
    return entries


def filter_baselined(findings: list[Finding],
                     baseline: list[dict]) -> list[Finding]:
    def matches(f: Finding) -> bool:
        for e in baseline:
            if e["rule"] != f.rule:
                continue
            # path-component boundary: an entry for "demo.toml" must
            # not swallow findings in "cluster-demo.toml"
            if f.path != e["path"] and \
                    not f.path.endswith("/" + e["path"]):
                continue
            if "line" in e and int(e["line"]) != f.line:
                continue
            return True
        return False
    return [f for f in findings if not matches(f)]


# ---------------------------------------------------------------------------
# emitters
# ---------------------------------------------------------------------------

def sort_findings(findings: list[Finding]) -> list[Finding]:
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule,
                                           f.message))


def render_text(findings: list[Finding]) -> str:
    fs = sort_findings(findings)
    lines = [f.render() for f in fs]
    errs = sum(1 for f in fs if f.severity == "error")
    warns = len(fs) - errs
    lines.append(f"fdlint: {errs} error(s), {warns} warning(s)")
    return "\n".join(lines)


def render_json(findings: list[Finding]) -> str:
    """Stable machine-readable form: schema-versioned, findings sorted
    by (path, line, rule), keys fixed — safe to diff in CI."""
    fs = sort_findings(findings)
    doc = {
        "fdlint": 1,
        "counts": {
            "error": sum(1 for f in fs if f.severity == "error"),
            "warning": sum(1 for f in fs if f.severity == "warning"),
        },
        "findings": [
            {"rule": f.rule, "severity": f.severity, "path": f.path,
             "line": f.line, "message": f.message}
            for f in fs
        ],
    }
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"
