"""Shm single-writer ownership + torn-read analysis.

Every shared-memory region in this tree has ONE writer class by
design — tango mcaches/fseqs have the producer, metric slots have
their owning tile (plus the supervisor's reserved `sup_*` slots),
trace rings belong to the tile they record, the funk root namespace
is written by a small cataloged set of lifecycle owners, the restore
marker by the snapshot inserter alone. Nothing enforces that: a new
module can import `SUP_SLOTS` and poke another tile's slots, or
rec_write the restore marker from the wrong side of the catch-up
gate, and the bug only shows up as a torn counter or a wedged
follower under chaos.

This analyzer makes region ownership a reviewed artifact:

  * dual-writer: each region class below carries the cataloged set of
    writer modules. A write-API call outside that set is a finding.
    Legitimate handoffs are annotated in place —
    `# fdlint: disable=dual-writer — handoff: <why>` — the
    supervisor's post-mortem append of reap marks into a dead tile's
    trace ring is the exemplar (the tile is provably dead, ownership
    transferred to the reaper).
  * torn-read: >=2 subscript reads of one live shm u64 view inside a
    function. The writer can land between the two loads, so the
    fields read belong to different states. Snapshot first with
    `tango.u64_snapshot(view)` (one copy, then coherent reads) — the
    metrics `seed_from` resurrect path had exactly this bug.

runtime/tango.py is exempt from torn-read: it IS the atomicity
discipline (speculative double-read of seq around the payload copy is
the tango protocol, not a bug).
"""
from __future__ import annotations

import ast
import re

from .core import Finding, filter_suppressed, finding

# receiver spelling filters keep generic method names (.event, .record)
# from matching unrelated objects
_TRACE_RECV = re.compile(r"(?:^|\.)_?(?:tr|trace)$")
_PROF_RECV = re.compile(r"(?:^|\.)_?(?:prof|region)$")
_MAILBOX_RECV = re.compile(r"(?:^|\.)_?(?:mb|mailbox)$")

# region -> (doc, writer module suffixes). A suffix ending in "/"
# allows the whole subpackage.
SHM_REGIONS: dict[str, tuple[str, tuple[str, ...]]] = {
    "trace-ring": (
        "a tile's flight-recorder ring (trace/recorder.py); owned by "
        "the recording tile's process",
        ("trace/", "disco/stem.py", "disco/tiles.py", "tiles/",
         "prof/device.py", "disco/slo.py", "tune/controller.py")),
    "sup-slots": (
        "the supervisor-reserved sup_* metric slots; owned by the "
        "supervisor loop alone — tiles only read them",
        ("disco/supervise.py",)),
    "restore-marker": (
        "the funk restore marker record; written once by the snapshot "
        "inserter when catch-up completes, read by replay's gate",
        ("tiles/snapshot.py",)),
    "funk-root": (
        "funk root-namespace records (rec_write(None, ...)); owned by "
        "the cataloged lifecycle writers (genesis, snapshot restore, "
        "checkpoint/vinyl load, bank/replay commit)",
        ("funk/", "utils/checkpt.py", "vinyl/vinyl.py",
         "tiles/snapshot.py", "tiles/replay.py", "disco/tiles.py",
         "app/genesis.py", "svm/accdb_cold.py",
         "flamenco/snapshot.py")),
    "prof-region": (
        "a tile's profiler region (ring + slot state + capture "
        "req/ack); written via ProfRegion APIs from the owning "
        "tile's sampler",
        ("prof/",)),
    "knob-mailbox": (
        "the fdtune knob mailbox (runtime/tango.py KnobMailbox); "
        "single writer per topology — the controller tile's decision "
        "loop alone posts, every steered adapter only reads its "
        "slots (tune/__init__.py KnobReader)",
        ("tune/controller.py",)),
}

TORN_READ_EXEMPT = ("runtime/tango.py",)


def _rel(path: str) -> str:
    """Path relative to the package root, for writer-set matching."""
    p = path.replace("\\", "/")
    marker = "firedancer_tpu/"
    i = p.rfind(marker)
    return p[i + len(marker):] if i >= 0 else p


def _allowed(rel: str, writers: tuple[str, ...]) -> bool:
    for w in writers:
        if w.endswith("/"):
            if rel.startswith(w):
                return True
        elif rel == w or rel.endswith("/" + w):
            return True
    return False


def _recv_text(func: ast.Attribute) -> str:
    try:
        return ast.unparse(func.value)
    except Exception:               # pragma: no cover - defensive
        return ""


def _region_of_call(node: ast.Call) -> str | None:
    f = node.func
    if not isinstance(f, ast.Attribute):
        return None
    name = f.attr
    if name in ("frag", "frag_batch", "event") and \
            _TRACE_RECV.search(_recv_text(f)):
        return "trace-ring"
    if name in ("record", "request_capture", "ack_capture") and \
            _PROF_RECV.search(_recv_text(f)):
        return "prof-region"
    if name == "post" and _MAILBOX_RECV.search(_recv_text(f)):
        return "knob-mailbox"
    if name in ("rec_write", "rec_remove"):
        for a in node.args:
            if "RESTORE_MARKER" in ast.unparse(a):
                return "restore-marker"
    if name == "rec_write" and node.args and \
            isinstance(node.args[0], ast.Constant) and \
            node.args[0].value is None:
        return "funk-root"
    return None


def _region_of_store(target: ast.AST) -> str | None:
    if isinstance(target, ast.Subscript) and \
            "SUP_SLOTS" in ast.unparse(target.slice):
        return "sup-slots"
    return None


def _check_dual_writer(tree: ast.Module, path: str) -> list[Finding]:
    rel = _rel(path)
    out: list[Finding] = []

    def emit(region: str, line: int):
        doc, writers = SHM_REGIONS[region]
        if _allowed(rel, writers):
            return
        out.append(finding(
            "dual-writer", path, line,
            f"write to single-writer shm region {region!r} ({doc}) "
            f"from {rel}, outside its cataloged writer set "
            f"{list(writers)} — if this is a deliberate ownership "
            f"handoff, annotate the line with "
            f"'# fdlint: disable=dual-writer — handoff: <why>'; "
            f"otherwise route the write through the owning tile"))
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            region = _region_of_call(node)
            if region:
                emit(region, node.lineno)
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                region = _region_of_store(t)
                if region:
                    emit(region, node.lineno)
    return out


# -- torn-read --------------------------------------------------------------

_VIEW_PARAM = re.compile(r"view")


def _live_views(fn: ast.AST) -> dict[str, int]:
    """name -> def line of locals/params holding a LIVE shm view (a
    `.view(...)` product that was not `.copy()`d)."""
    from .contracts import own_nodes
    out: dict[str, int] = {}
    args = getattr(fn, "args", None)
    if args is not None:
        for a in list(args.args) + list(args.kwonlyargs):
            if _VIEW_PARAM.search(a.arg):
                out[a.arg] = fn.lineno
    for n in own_nodes(fn):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Name):
            try:
                text = ast.unparse(n.value)
            except Exception:       # pragma: no cover - defensive
                continue
            name = n.targets[0].id
            if ".view(" in text and ".copy(" not in text:
                out[name] = n.lineno
            elif name in out:
                out.pop(name)       # rebound to something harmless
    return out


def _check_torn_read(tree: ast.Module, path: str) -> list[Finding]:
    rel = _rel(path)
    if any(rel == e or rel.endswith("/" + e) for e in TORN_READ_EXEMPT):
        return []
    from .contracts import own_nodes
    out: list[Finding] = []
    for fn in [n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        live = _live_views(fn)
        if not live:
            continue
        reads: dict[str, list[int]] = {}
        for n in own_nodes(fn):
            # scalar index loads only: slicing a view builds another
            # lazy view (no bytes move), it is not a torn value read
            if isinstance(n, ast.Subscript) and \
                    isinstance(n.ctx, ast.Load) and \
                    not isinstance(n.slice, ast.Slice) and \
                    isinstance(n.value, ast.Name) and \
                    n.value.id in live:
                reads.setdefault(n.value.id, []).append(n.lineno)
        for name, lines in sorted(reads.items()):
            if len(lines) >= 2:
                out.append(finding(
                    "torn-read", path, lines[1],
                    f"{fn.name}() reads live shm view {name!r} "
                    f"{len(lines)} times (lines {lines}) — the writer "
                    f"can land between the loads, so the fields belong "
                    f"to different states; snapshot once with "
                    f"tango.u64_snapshot({name}) and read the copy"))
    return out


def lint_ownership_source(source: str, path: str) -> list[Finding]:
    """Per-file ownership analysis: dual-writer + torn-read."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    out = _check_dual_writer(tree, path)
    out.extend(_check_torn_read(tree, path))
    return filter_suppressed(out, source)
