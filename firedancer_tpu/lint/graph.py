"""Topology graph analysis: prove the dataflow graph before it runs.

The whole tile graph — links, credit flow, supervision policy — is
statically knowable from config (the reference's stance: fd_topob
validates at build; here review-time is even earlier than build-time).
This analyzer loads `cfg/*.toml` through `app/config.py` (layer
directives honored, see below) or accepts a programmatic `Topology`
via `lint_topology`, and checks:

  * every non-external link has exactly one producer and >=1 consumer
  * depths are powers of two; out-link mtus absorb the producing
    tile's worst-case frame (verify/dedup forward verbatim; bank->poh
    and poh->entry re-wrap with known header growth)
  * no backpressure cycles: an edge A->B exists when B reliably
    consumes a link A produces (B's fseq gates A's credits); a cycle
    means every member can end up waiting on the next — a static
    deadlock candidate
  * reliable consumers actually publish progress (their adapter kind
    defines in_seqs) — otherwise the producer wedges on a frozen fseq
    and only the FSEQ_STALE supervision path could ever unwedge it
  * supervise/chaos tables satisfy the disco/supervise.py and
    utils/chaos.py schemas, and stall_fseq targets a link the tile
    consumes
  * args that name links/tiles/tcaches resolve (registry.TILE_ARGS)

Overlay configs (files meant to be layered over another TOML, like
cfg/cluster-demo.toml) declare their base with a directive comment:

    # fdlint: layers=default.toml

paths are relative to the overlay file; the linter loads the stack in
order before analyzing.
"""
from __future__ import annotations

import re

from .core import Finding, filter_suppressed, finding
from . import registry as reg


# ---------------------------------------------------------------------------
# model extraction
# ---------------------------------------------------------------------------

def _norm_ins(ins) -> list[tuple[str, bool]]:
    out = []
    for i in ins or ():
        if isinstance(i, str):
            out.append((i, True))
        elif isinstance(i, dict):
            out.append((i["link"], bool(i.get("reliable", True))))
        elif isinstance(i, (list, tuple)) and i and \
                all(isinstance(e, str) for e in i):
            # per-shard distribution entry (sharded_tile / tile_cnt in
            # config): shard k consumes i[k]. The un-expanded model
            # consumes them all — folding to i[0] would orphan the
            # other shards' links into dead-link false positives.
            out.extend((e, True) for e in i)
        else:
            out.append((i[0], bool(i[1])))
    return out


def model_from_config(cfg: dict) -> dict:
    links = {ln["name"]: {"depth": int(ln.get("depth", 128)),
                          "mtu": int(ln.get("mtu", 1280)),
                          "external": bool(ln.get("external", False))}
             for ln in cfg.get("link", [])}
    tcaches = {tc["name"] for tc in cfg.get("tcache", [])}
    default_sup = cfg.get("topology", {}).get("supervise")
    tiles = {}
    for t in cfg.get("tile", []):
        args = {k: v for k, v in t.items()
                if k not in ("name", "kind", "ins", "outs")}
        if default_sup:
            merged = dict(default_sup)
            merged.update(args.get("supervise", {}) or {})
            args["supervise"] = merged
        tiles[t["name"]] = {"kind": t.get("kind"),
                            "ins": _norm_ins(t.get("ins")),
                            "outs": list(t.get("outs", ())),
                            "args": args}
    return {"links": links, "tcaches": tcaches, "tiles": tiles,
            "trace": cfg.get("trace"), "slo": cfg.get("slo"),
            "prof": cfg.get("prof"), "shed": cfg.get("shed"),
            "witness": cfg.get("witness"), "funk": cfg.get("funk"),
            "replay": cfg.get("replay"),
            "snapshot": cfg.get("snapshot"),
            "flight": cfg.get("flight"),
            "tune": cfg.get("tune")}


def model_from_topology(topo) -> dict:
    """disco.topo.Topology (unbuilt) -> the same model shape."""
    links = {ln: {"depth": s.depth, "mtu": s.mtu, "external": s.external}
             for ln, s in topo.links.items()}
    tiles = {tn: {"kind": t.kind,
                  "ins": [(i["link"], bool(i["reliable"]))
                          for i in t.ins],
                  "outs": list(t.outs), "args": dict(t.args)}
             for tn, t in topo.tiles.items()}
    return {"links": links, "tcaches": set(topo.tcaches),
            "tiles": tiles, "trace": getattr(topo, "trace", None),
            "slo": getattr(topo, "slo", None),
            "prof": getattr(topo, "prof", None),
            "shed": getattr(topo, "shed", None),
            "witness": getattr(topo, "witness", None),
            "funk": getattr(topo, "funk", None),
            "replay": getattr(topo, "replay", None),
            "snapshot": getattr(topo, "snapshot", None),
            "flight": getattr(topo, "flight", None),
            "tune": getattr(topo, "tune", None)}


# ---------------------------------------------------------------------------
# line attribution (best-effort: the `name = "..."` line in the TOML)
# ---------------------------------------------------------------------------

class _Lines:
    """Attribute an entity (link/tile name) to the layer file + line
    where its `name = "..."` appears — for an overlay config the
    finding points INTO the base layer, so one inline suppression
    covers every stack that includes it. Later layers win (an overlay
    redeclaring the entity owns the finding)."""

    def __init__(self, sources: list[tuple[str, str]], default: str):
        self.sources = sources
        self.default = default
        self._cache: dict[str, tuple[str, int]] = {}

    def of(self, entity: str) -> tuple[str, int]:
        if entity not in self._cache:
            pat = re.compile(
                r'^\s*name\s*=\s*"' + re.escape(entity) + r'"', re.M)
            hit = (self.default, 0)
            for path, source in self.sources:
                m = pat.search(source)
                if m:
                    hit = (path, source.count("\n", 0, m.start()) + 1)
            self._cache[entity] = hit
        return self._cache[entity]


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

LAYERS_RE = re.compile(r"^#\s*fdlint:\s*layers=(\S+)", re.M)


def lint_config_file(path: str) -> list[Finding]:
    """One TOML file (with its declared base layers) -> findings."""
    import os
    from ..app.config import load_config
    with open(path) as f:
        source = f.read()
    m = LAYERS_RE.search(source)
    stack = []
    if m:
        base_dir = os.path.dirname(os.path.abspath(path))
        stack = [os.path.join(base_dir, p)
                 for p in m.group(1).split(",") if p]
    try:
        cfg = load_config(*stack, path)
    except Exception as e:
        return [finding("dangling-ref", path, 0,
                        f"config failed to load: {e}")]
    sources = []
    for p in stack + [path]:
        with open(p) as f:
            sources.append((p, f.read()))
    return _lint_model(model_from_config(cfg), sources, path)


def lint_config(cfg: dict, path: str,
                source: str = "") -> list[Finding]:
    return _lint_model(model_from_config(cfg), [(path, source)], path)


def lint_topology(topo, path: str = "<topology>") -> list[Finding]:
    """Programmatic Topology builds get the same static pass the TOML
    path gets (tests call this on fixtures before .build())."""
    return _lint_model(model_from_topology(topo), [(path, "")], path)


def _lint_model(model: dict, sources: list[tuple[str, str]],
                default_path: str) -> list[Finding]:
    findings = _check_model(model, default_path,
                            _Lines(sources, default_path))
    by_path = dict(sources)
    return [f for f in findings
            if f in filter_suppressed([f], by_path.get(f.path, ""))]


# ---------------------------------------------------------------------------
# the checks
# ---------------------------------------------------------------------------

def _emit(out: list, lines: _Lines, rule: str, entity: str, msg: str):
    p, ln = lines.of(entity)
    out.append(finding(rule, p, ln, msg))


def _check_model(model: dict, path: str, lines: _Lines) -> list[Finding]:
    out: list[Finding] = []
    links, tiles = model["links"], model["tiles"]
    from .contracts import adapter_summaries
    kinds = adapter_summaries()

    producers: dict[str, str] = {}
    consumers: dict[str, list[tuple[str, bool]]] = {}
    for tn, t in tiles.items():
        for ln in t["outs"]:
            if ln in producers:
                _emit(out, lines, "dup-producer", ln,
                      f"link {ln!r} produced by both "
                      f"{producers[ln]!r} and {tn!r}")
            producers.setdefault(ln, tn)
        for ln, rel in t["ins"]:
            consumers.setdefault(ln, []).append((tn, rel))

    # dead / orphan / shape
    for ln, spec in links.items():
        if not spec["external"]:
            if ln in producers and ln not in consumers:
                _emit(out, lines, "dead-link", ln,
                      f"link {ln!r} is produced by "
                      f"{producers[ln]!r} but never consumed")
            if ln in consumers and ln not in producers:
                _emit(out, lines, "orphan-link", ln,
                      f"link {ln!r} is consumed by "
                      f"{[c for c, _ in consumers[ln]]} but never "
                      f"produced")
        d = spec["depth"]
        if d <= 0 or d & (d - 1):
            _emit(out, lines, "depth-pow2", ln,
                  f"link {ln!r} depth {d} is not a positive power "
                  f"of two")
    # unknown links referenced in ins/outs
    for tn, t in tiles.items():
        for ln in t["outs"]:
            if ln not in links:
                _emit(out, lines, "dangling-ref", tn,
                      f"tile {tn!r}: out link {ln!r} is not declared")
        for ln, _ in t["ins"]:
            if ln not in links:
                _emit(out, lines, "dangling-ref", tn,
                      f"tile {tn!r}: in link {ln!r} is not declared")

    out.extend(_check_mtus(model, lines))
    out.extend(_check_cycles(model, producers, lines))
    out.extend(_check_tiles(model, kinds, lines))
    out.extend(_check_trace(model, path, lines))
    out.extend(_check_slo(model, kinds, path, lines))
    out.extend(_check_prof(model, path, lines))
    out.extend(_check_gui(model, lines))
    out.extend(_check_shed(model, path, lines))
    out.extend(_check_witness(model, path))
    out.extend(_check_funk(model, path))
    out.extend(_check_replay(model, path))
    out.extend(_check_snapshot(model, path))
    out.extend(_check_flight(model, path))
    out.extend(_check_tune(model, path, lines))
    return out


def _check_witness(model, path) -> list[Finding]:
    """[witness] section: the witness/plan.py schema gate (one
    validator, same as config load and fdwitness plan build) — unknown
    keys, unknown stage names, malformed per-stage overrides all land
    as review-time findings."""
    from ..witness.plan import normalize_witness
    out: list[Finding] = []
    spec = model.get("witness")
    if spec is not None:
        try:
            normalize_witness(spec)
        except Exception as e:
            out.append(finding("bad-witness", path, 0,
                               f"[witness]: {e}"))
    return out


def _check_funk(model, path) -> list[Finding]:
    """[funk] section: the funk/shmfunk.py schema gate (one validator,
    same as config load and topo.build's store carve) — unknown keys,
    unknown backend, out-of-range rec_max/txn_max/heap_mb all land as
    review-time findings with a did-you-mean."""
    from ..funk.shmfunk import normalize_funk
    out: list[Finding] = []
    spec = model.get("funk")
    if spec is not None:
        try:
            normalize_funk(spec)
        except Exception as e:
            out.append(finding("bad-funk", path, 0, f"[funk]: {e}"))
    return out


def _check_replay(model, path) -> list[Finding]:
    """[replay] section: the tiles/replay.py schema gate (one
    validator, same as config load and topo.build) — unknown keys,
    negative exec_tile_cnt, non-positive redispatch_s all land as
    review-time findings with a did-you-mean."""
    from ..tiles.replay import normalize_replay
    out: list[Finding] = []
    spec = model.get("replay")
    if spec is not None:
        try:
            normalize_replay(spec)
        except Exception as e:
            out.append(finding("bad-replay", path, 0, f"[replay]: {e}"))
    return out


def _check_snapshot(model, path) -> list[Finding]:
    """[snapshot] section: the tiles/snapshot.py schema gate (one
    validator, same as config load and topo.build) — unknown keys,
    negative every_slots/min_slot, undersized chunk all land as
    review-time findings with a did-you-mean."""
    from ..tiles.snapshot import normalize_snapshot
    out: list[Finding] = []
    spec = model.get("snapshot")
    if spec is not None:
        try:
            normalize_snapshot(spec)
        except Exception as e:
            out.append(finding("bad-snapshot", path, 0,
                               f"[snapshot]: {e}"))
    return out


def _check_flight(model, path) -> list[Finding]:
    """[flight] section: the flight/__init__.py schema gate (one
    validator, same as config load and topo.build) — unknown keys,
    empty dir, retention below one segment, out-of-range hz/node_id,
    unknown source families all land as review-time findings with a
    did-you-mean."""
    from ..flight import normalize_flight
    out: list[Finding] = []
    spec = model.get("flight")
    if spec is not None:
        try:
            normalize_flight(spec)
        except Exception as e:
            out.append(finding("bad-flight", path, 0,
                               f"[flight]: {e}"))
    return out


def _check_tune(model, path, lines) -> list[Finding]:
    """[tune] section: the tune/__init__.py schema gate (one
    validator, same as config load and topo.build's mailbox carve) —
    unknown keys, out-of-range cadences/hysteresis, bad per-knob
    overrides all land as review-time findings with a did-you-mean.
    Plus the coherence check topo.build enforces at boot: a controller
    tile without an enabled [tune] section has no mailbox to steer."""
    from ..tune import normalize_tune
    out: list[Finding] = []
    spec = model.get("tune")
    cfg = None
    if spec is not None:
        try:
            cfg = normalize_tune(spec)
        except Exception as e:
            out.append(finding("bad-tune", path, 0, f"[tune]: {e}"))
    controllers = [tn for tn, t in model["tiles"].items()
                   if t["kind"] == "controller"]
    enabled = bool(cfg and cfg["enable"])
    if controllers and not enabled and not (spec is not None
                                            and cfg is None):
        # (when the section itself failed validation, the bad-tune
        # schema finding above already owns the problem)
        _emit(out, lines, "bad-tune", controllers[0],
              f"controller tile {controllers[0]!r} declared but [tune] "
              "is missing or disabled — it would have no knob mailbox "
              "to steer")
    return out


# tile kinds with an ingest door the shed gate can police (the only
# readers of an effective shed table — shed on anything else is dead
# config, flagged so a topo that THINKS it is protected actually is)
SHED_KINDS = {"sock", "quic", "gossip", "repair"}


def _check_shed(model, path, lines) -> list[Finding]:
    """[shed] section + per-tile `shed` overrides: the disco/shed.py
    schema gate (one validator, same as config load and topo.build),
    plus a dead-config check — a tile-level shed override on a kind
    that has no ingest door to police protects nothing."""
    from ..disco.shed import normalize_shed
    out: list[Finding] = []
    spec = model.get("shed")
    if spec is not None:
        try:
            normalize_shed(spec)
        except Exception as e:
            out.append(finding("bad-shed", path, 0, f"[shed]: {e}"))
    for tn, t in model["tiles"].items():
        if "shed" not in t["args"]:
            continue
        try:
            normalize_shed(t["args"]["shed"], per_tile=True)
        except Exception as e:
            _emit(out, lines, "bad-shed", tn, f"tile {tn!r}: {e}")
            continue
        if t["kind"] not in SHED_KINDS:
            _emit(out, lines, "bad-shed", tn,
                  f"tile {tn!r}: kind {t['kind']!r} has no ingest "
                  f"door to police — shed is only read by "
                  f"{sorted(SHED_KINDS)}")
    return out


def _check_gui(model, lines) -> list[Finding]:
    """[tile.gui] args: the fdgui schema gate (gui/schema.py is the
    one validator, same as topo.build) — ws queue/client bounds, knob
    types, unknown keys with a did-you-mean. The tps_tile/tps_metric
    REFERENCES stay under dangling-ref (_check_arg_refs), like every
    other registry-typed arg."""
    out: list[Finding] = []
    for tn, t in model["tiles"].items():
        if t["kind"] != "gui":
            continue
        from ..gui import normalize_gui
        try:
            normalize_gui(t["args"])
        except Exception as e:
            _emit(out, lines, "bad-gui", tn, f"tile {tn!r}: {e}")
    return out


def _check_trace(model, path, lines) -> list[Finding]:
    """[trace] section + [tile.trace] overrides: the fdtrace schema
    gate (trace/recorder.py is the one validator) plus tile-name
    resolution for the `tiles` allowlist."""
    from ..trace import normalize_trace
    out: list[Finding] = []
    spec = model.get("trace")
    if spec is not None:
        try:
            norm = normalize_trace(spec)
        except Exception as e:
            out.append(finding("bad-trace", path, 0, f"[trace]: {e}"))
        else:
            for tn in norm["tiles"] or ():
                if tn not in model["tiles"]:
                    _emit(out, lines, "bad-trace", tn,
                          f"[trace] tiles entry {tn!r} is not a "
                          f"declared tile"
                          + reg.suggest(str(tn), model["tiles"]))
    for tn, t in model["tiles"].items():
        if "trace" in t["args"]:
            try:
                normalize_trace(t["args"]["trace"], per_tile=True)
            except Exception as e:
                _emit(out, lines, "bad-trace", tn, f"tile {tn!r}: {e}")
    return out


def _check_prof(model, path, lines) -> list[Finding]:
    """[prof] section + [tile.prof] overrides: the fdprof schema gate
    (prof/recorder.py is the one validator) plus tile-name resolution
    for the `tiles` allowlist and the breach_capture list."""
    from ..prof import normalize_prof
    out: list[Finding] = []
    spec = model.get("prof")
    if spec is not None:
        try:
            norm = normalize_prof(spec)
        except Exception as e:
            out.append(finding("bad-prof", path, 0, f"[prof]: {e}"))
        else:
            for key in ("tiles", "breach_capture"):
                for tn in norm[key] or ():
                    if tn not in model["tiles"]:
                        _emit(out, lines, "bad-prof", tn,
                              f"[prof] {key} entry {tn!r} is not a "
                              f"declared tile"
                              + reg.suggest(str(tn), model["tiles"]))
    for tn, t in model["tiles"].items():
        if "prof" in t["args"]:
            try:
                normalize_prof(t["args"]["prof"], per_tile=True)
            except Exception as e:
                _emit(out, lines, "bad-prof", tn, f"tile {tn!r}: {e}")
    return out


def _check_slo(model, kinds, path, lines) -> list[Finding]:
    """[slo] section: the disco/slo.py schema gate (one validator,
    same as topo.build) plus target-source resolution against the
    DECLARED topology — tile metric slot names come from the adapter
    registry's static summaries, so a target naming a metric the tile
    kind never exports is a review-time finding with a did-you-mean."""
    from ..disco.slo import check_target, normalize_slo
    out: list[Finding] = []
    spec = model.get("slo")
    if spec is None:
        return out
    try:
        norm = normalize_slo(spec)
    except Exception as e:
        out.append(finding("bad-slo", path, 0, f"[slo]: {e}"))
        return out
    tiles_metrics = {
        tn: kinds.get(t["kind"], {}).get("metrics", [])
        for tn, t in model["tiles"].items()
    }
    for t in norm["target"]:
        err = check_target(t["parsed"], tiles_metrics, model["links"])
        if err:
            _emit(out, lines, "bad-slo", t["name"],
                  f"slo target {t['name']!r}: {err}")
    return out


def _check_mtus(model, lines) -> list[Finding]:
    """Frame-growth contracts (registry.py): a producing tile's
    worst-case frame must fit the out link."""
    out: list[Finding] = []
    links, tiles = model["links"], model["tiles"]

    def mtu(ln):
        return links[ln]["mtu"] if ln in links else None

    for tn, t in tiles.items():
        in_mtus = [mtu(ln) for ln, _ in t["ins"] if mtu(ln)]
        if not in_mtus:
            continue
        worst_in = max(in_mtus)
        kind, args = t["kind"], t["args"]
        if kind in reg.FORWARD_VERBATIM:
            for ln in t["outs"]:
                m = mtu(ln)
                if m is not None and m < worst_in:
                    _emit(out, lines, "mtu-underflow", ln,
                          f"link {ln!r} mtu {m} < {worst_in} ({kind} "
                          f"tile {tn!r} forwards in-payloads verbatim)")
        elif kind == "bank" and args.get("forward_payloads") and \
                args.get("poh_link") in links:
            need = worst_in + reg.BANK_POH_GROWTH
            m = mtu(args["poh_link"])
            if m is not None and m < need:
                _emit(out, lines, "mtu-underflow", args["poh_link"],
                      f"link {args['poh_link']!r} mtu {m} < {need} "
                      f"(bank {tn!r} re-wraps microblocks with "
                      f"forward_payloads: header 20 -> 42)")
        elif kind == "poh":
            entry = [ln for ln in t["outs"]
                     if ln != args.get("slot_link")]
            need = worst_in + reg.POH_ENTRY_GROWTH
            for ln in entry:
                m = mtu(ln)
                if m is not None and m < need:
                    _emit(out, lines, "mtu-underflow", ln,
                          f"link {ln!r} mtu {m} < {need} (poh {tn!r} "
                          f"re-wraps bank frames: header 42 -> 116)")
    out.extend(_check_wire_mtus(model, lines))
    return out


def _check_wire_mtus(model, lines) -> list[Finding]:
    """wire-mtu: fixed wire-family minimums per producer kind (the
    r16 exec wire, r17 snapshot stream, shred/tower wires) — the lint
    graph model attributes each cataloged wire to its topology links,
    so a link too small for one frame of its family fails review."""
    out: list[Finding] = []
    links, tiles = model["links"], model["tiles"]

    def mtu(ln):
        return links[ln]["mtu"] if ln in links else None

    def need(ln, floor, why):
        m = mtu(ln)
        if m is not None and m < floor:
            _emit(out, lines, "wire-mtu", ln,
                  f"link {ln!r} mtu {m} < {floor} ({why})")

    for tn, t in tiles.items():
        kind, args = t["kind"], t["args"]
        if kind in ("bank", "replay"):
            for ln in args.get("exec_links") or ():
                need(ln, reg.EXEC_DISPATCH_MIN_MTU,
                     f"{kind} {tn!r} exec dispatch: <QQH> header + "
                     f"one 80B txn row")
        elif kind == "exec":
            for ln in t["outs"]:
                need(ln, reg.EXEC_DONE_MIN_MTU,
                     f"exec {tn!r} completion frame <QII>")
        elif kind == "shred":
            if args.get("batches_link"):
                need(args["batches_link"], reg.SLICE_MIN_MTU,
                     f"shred {tn!r} slice frame <QIB> + payload")
            if args.get("shreds_link"):
                need(args["shreds_link"], reg.SHRED_WIRE_MIN_MTU,
                     f"shred {tn!r} wire: fixed header through idx")
        elif kind == "tower":
            for ln in t["outs"]:
                need(ln, reg.TOWER_WIRE_MIN_MTU,
                     f"tower {tn!r} vote frame (1+32+8+32)")
        elif kind == "snapld":
            chunk = args.get("chunk")
            if not isinstance(chunk, int):
                snap = model.get("snapshot") or {}
                chunk = snap.get("chunk") if isinstance(snap, dict) \
                    else None
            if isinstance(chunk, int):
                for ln in t["outs"]:
                    need(ln, chunk,
                         f"snapld {tn!r} publishes {chunk}B snapshot "
                         f"stream chunks ([snapshot].chunk)")
    return out


def _check_cycles(model, producers, lines) -> list[Finding]:
    """Reliable-consumption cycles. Edge A->B when B reliably consumes
    a link A produces: A's credits gate on B's fseq, so A waits on B;
    a cycle is mutual waiting — the static deadlock candidate."""
    out: list[Finding] = []
    edges: dict[str, set[str]] = {tn: set() for tn in model["tiles"]}
    for tn, t in model["tiles"].items():
        for ln, rel in t["ins"]:
            if rel and ln in producers:
                edges[producers[ln]].add(tn)

    color: dict[str, int] = {}
    stack: list[str] = []
    reported: set[frozenset] = set()

    def dfs(u: str):
        color[u] = 1
        stack.append(u)
        for v in sorted(edges[u]):
            if color.get(v) == 1:
                cyc = stack[stack.index(v):] + [v]
                key = frozenset(cyc)
                if key not in reported:
                    reported.add(key)
                    _emit(out, lines, "backpressure-cycle",
                          min(cyc),
                          "reliable-consumption cycle "
                          + " -> ".join(cyc)
                          + " — every member credit-waits on the next")
            elif color.get(v) is None:
                dfs(v)
        stack.pop()
        color[u] = 2

    for tn in sorted(edges):
        if color.get(tn) is None:
            dfs(tn)
    return out


def _check_tiles(model, kinds, lines) -> list[Finding]:
    out: list[Finding] = []
    tiles = model["tiles"]
    tcaches = model["tcaches"]

    for tn, t in tiles.items():
        kind, args = t["kind"], t["args"]
        summary = kinds.get(kind)
        if summary is None:
            _emit(out, lines, "unknown-kind", tn,
                  f"tile {tn!r}: kind {kind!r} has no registered "
                  f"adapter" + reg.suggest(str(kind), kinds))
        else:
            if t["ins"] and not summary["reads_in_rings"]:
                _emit(out, lines, "unread-in", tn,
                      f"tile {tn!r} declares ins but kind {kind!r} "
                      f"never reads in_rings — dead wiring")
            if not summary["in_seqs"]:
                for ln, rel in t["ins"]:
                    if rel:
                        _emit(out, lines, "reliable-sink", tn,
                              f"tile {tn!r} consumes {ln!r} reliably "
                              f"but kind {kind!r} never publishes "
                              f"consumer progress (no in_seqs): the "
                              f"producer wedges after depth frags; "
                              f"declare the in unreliable")

        # supervise schema (disco/supervise.py is the one validator)
        if "supervise" in args:
            from ..disco.supervise import normalize_policy
            try:
                normalize_policy(args["supervise"])
            except Exception as e:
                _emit(out, lines, "bad-supervise", tn,
                      f"tile {tn!r}: {e}")

        # chaos schema (utils/chaos.py) + stall_fseq link resolution
        if "chaos" in args:
            from ..utils.chaos import ChaosPlan
            try:
                ChaosPlan(args["chaos"])
            except Exception as e:
                _emit(out, lines, "bad-chaos", tn, f"tile {tn!r}: {e}")
            else:
                my_ins = {ln for ln, _ in t["ins"]}
                for ev in args["chaos"].get("events", []):
                    if ev.get("action") == "stall_fseq" and \
                            ev.get("link") is not None and \
                            ev["link"] not in my_ins:
                        _emit(out, lines, "bad-chaos", tn,
                              f"tile {tn!r}: stall_fseq targets "
                              f"{ev['link']!r}, not one of its ins "
                              f"{sorted(my_ins)}")

        out.extend(_check_arg_refs(tn, t, tcaches, tiles, kinds,
                                   lines))
    return out


def _check_arg_refs(tn, t, tcaches, tiles, kinds, lines) -> list[Finding]:
    out: list[Finding] = []
    kind, args = t["kind"], t["args"]
    ins = {ln for ln, _ in t["ins"]}
    outs = set(t["outs"])
    spec = reg.TILE_ARGS.get(kind, {})

    def bad(key, val, what):
        _emit(out, lines, "dangling-ref", tn,
              f"tile {tn!r}: {key} = {val!r} is not {what}")

    for key, ref in spec.items():
        if ref is None or key not in args:
            continue
        # list-valued refs: IN_LIST/OUT_LIST by schema, and TCACHE for
        # the sharded-tile expansion (a per-shard tcache list — each
        # entry must still resolve)
        vals = args[key] if isinstance(args[key], (list, tuple)) and \
            ref in (reg.IN_LIST, reg.OUT_LIST, reg.TCACHE) \
            else [args[key]]
        for v in vals:
            if ref in (reg.IN, reg.IN_LIST) and v not in ins:
                bad(key, v, f"one of the tile's ins {sorted(ins)}")
            elif ref in (reg.OUT, reg.OUT_LIST) and v not in outs:
                bad(key, v, f"one of the tile's outs {sorted(outs)}")
            elif ref == reg.TCACHE and v not in tcaches:
                bad(key, v, "a declared tcache"
                    + reg.suggest(str(v), tcaches))
            elif ref == reg.TILE and v not in tiles:
                bad(key, v, "a declared tile"
                    + reg.suggest(str(v), tiles))

    # sign.clients: role-bound ring pairs — req must be an in, resp an
    # out (the keyguard contract binds policy to the wire)
    if kind == "sign":
        clients = args.get("clients", [])
        for c in clients if isinstance(clients, list) else []:
            if not isinstance(c, dict):
                continue
            if c.get("req") not in ins:
                bad("clients.req", c.get("req"),
                    f"one of the tile's ins {sorted(ins)}")
            if c.get("resp") not in outs:
                bad("clients.resp", c.get("resp"),
                    f"one of the tile's outs {sorted(outs)}")

    # gui.tps_metric must exist on the target tile's kind
    if kind == "gui" and "tps_metric" in args:
        target = args.get("tps_tile", "sink")
        tkind = tiles.get(target, {}).get("kind")
        metrics = kinds.get(tkind, {}).get("metrics")
        if metrics is not None and args["tps_metric"] not in metrics:
            bad("tps_metric", args["tps_metric"],
                f"a metric of tile {target!r} (kind {tkind!r}: "
                f"{metrics})")
    return out
