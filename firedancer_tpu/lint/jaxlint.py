"""JAX/Pallas purity lint: host-sync and TPU-hostility hazards.

Scope: `firedancer_tpu/ops/*.py` and `firedancer_tpu/tiles/*.py` — the
device compute kernels and the tiles that drive them.

Region model (conservative — no call-graph): a function is *jitted
code* when it is jit-decorated, passed to a tracing transform
(jax.jit / shard_map / vmap / checkpoint / lax.scan / fori_loop /
while_loop / cond / pl.pallas_call), or lexically nested inside such a
function. Hazard rules fire only inside these regions, so host-side
helpers (numpy constant prep, ctypes glue) never false-positive; the
fixture tests in tests/test_lint.py prove each rule still fires.

Rules: .item() and float()/int() on traced values (device->host sync /
ConcretizationTypeError), np.* calls (sync when applied to traced
arrays; constants belong hoisted out of the trace), Python if/while on
jnp expressions (traced bools cannot branch), int64/float64 dtypes
(x64 is off on TPU), PRNG key reuse across draws, and jit entry points
taking arrays without donate_argnums (warning).
"""
from __future__ import annotations

import ast

from .core import Finding, filter_suppressed, finding

# names whose call-argument functions become jit regions
_TRACING_CALLS = {
    "jit", "pallas_call", "shard_map", "vmap", "checkpoint", "remat",
    "scan", "fori_loop", "while_loop", "cond", "switch", "custom_jvp",
    "custom_vjp", "grad", "value_and_grad",
}
_X64_ATTRS = {"int64", "float64", "uint64"}
_X64_STRS = {"int64", "float64", "uint64"}
# jax.random draws that consume a key (reusing one key across several
# of these is the bug; split/fold_in/PRNGKey derive keys and are fine)
_KEY_CONSUMERS = {
    "bits", "uniform", "normal", "randint", "bernoulli", "categorical",
    "choice", "permutation", "shuffle", "gamma", "beta", "exponential",
    "poisson", "truncated_normal", "gumbel", "laplace",
}


def _call_name(func: ast.AST) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _src_has(node: ast.AST, needle: str) -> bool:
    return needle in ast.unparse(node)


class _Regions:
    """Compute the set of function/lambda nodes that are jitted code."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.funcs = [n for n in ast.walk(tree)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef, ast.Lambda))]
        rooted_names: set[str] = set()
        rooted_nodes: set[ast.AST] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self._is_tracing_expr(dec):
                        rooted_nodes.add(node)
            if isinstance(node, ast.Call) and \
                    _call_name(node.func) in _TRACING_CALLS:
                for arg in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Lambda):
                        rooted_nodes.add(arg)
                    elif isinstance(arg, ast.Name):
                        rooted_names.add(arg.id)
        for fn in self.funcs:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and fn.name in rooted_names:
                rooted_nodes.add(fn)
        self.region_funcs: set[ast.AST] = set()
        for fn in self.funcs:
            n: ast.AST | None = fn
            while n is not None:
                if n in rooted_nodes:
                    self.region_funcs.add(fn)
                    break
                n = self.parents.get(n)

    @staticmethod
    def _is_tracing_expr(dec: ast.AST) -> bool:
        """@jax.jit / @jit / @partial(jax.jit, ...) /
        @functools.partial(jax.jit, ...)."""
        if _call_name(dec) in _TRACING_CALLS:
            return True
        if isinstance(dec, ast.Call):
            if _call_name(dec.func) in _TRACING_CALLS:
                return True
            if _call_name(dec.func) == "partial" and dec.args and \
                    _call_name(dec.args[0]) in _TRACING_CALLS:
                return True
        return False

    def enclosing_func(self, node: ast.AST) -> ast.AST | None:
        n = self.parents.get(node)
        while n is not None and not isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            n = self.parents.get(n)
        return n

    def in_region(self, node: ast.AST) -> bool:
        fn = self.enclosing_func(node)
        return fn is not None and fn in self.region_funcs


def lint_jax_source(source: str, path: str) -> list[Finding]:
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [finding("numpy-in-jit", path, e.lineno or 0,
                        f"unparseable module: {e.msg}")]
    regions = _Regions(tree)
    out: list[Finding] = []

    for node in ast.walk(tree):
        in_region = regions.in_region(node)
        if isinstance(node, ast.Call):
            name = _call_name(node.func)
            if in_region and name == "item" and \
                    isinstance(node.func, ast.Attribute):
                out.append(finding(
                    "host-sync-item", path, node.lineno,
                    f"{ast.unparse(node.func)}() inside jitted code"))
            elif in_region and name in ("float", "int", "bool") and \
                    isinstance(node.func, ast.Name) and node.args and \
                    _src_has(node.args[0], "jnp."):
                out.append(finding(
                    "host-cast-traced", path, node.lineno,
                    f"{name}({ast.unparse(node.args[0])}) inside "
                    f"jitted code forces the traced value to host"))
            elif in_region and isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id in ("np", "numpy"):
                out.append(finding(
                    "numpy-in-jit", path, node.lineno,
                    f"np.{node.func.attr}() inside jitted code — "
                    f"hoist constants out of the trace; on traced "
                    f"arrays this is a host sync"))
        elif isinstance(node, (ast.If, ast.While)) and in_region and \
                _src_has(node.test, "jnp."):
            out.append(finding(
                "traced-bool", path, node.lineno,
                f"Python {type(node).__name__.lower()} on "
                f"`{ast.unparse(node.test)}` — use jnp.where/"
                f"lax.cond, a traced bool cannot branch"))
        if in_region:
            if isinstance(node, ast.Attribute) and \
                    node.attr in _X64_ATTRS and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id in ("jnp", "np", "numpy", "jax"):
                out.append(finding(
                    "x64-in-kernel", path, node.lineno,
                    f"{ast.unparse(node)} inside jitted/Pallas code — "
                    f"x64 is disabled on TPU"))
            elif isinstance(node, ast.Constant) and \
                    node.value in _X64_STRS and \
                    _is_dtype_position(node, regions):
                out.append(finding(
                    "x64-in-kernel", path, node.lineno,
                    f"dtype {node.value!r} inside jitted/Pallas code "
                    f"— x64 is disabled on TPU"))

    out.extend(_lint_key_reuse(tree, path))
    out.extend(_lint_missing_donate(tree, path))
    return filter_suppressed(out, source)


def _is_dtype_position(node: ast.Constant, regions: _Regions) -> bool:
    """String x64 names only count as dtypes when passed as
    dtype=... or astype('int64')."""
    parent = regions.parents.get(node)
    if isinstance(parent, ast.keyword) and parent.arg == "dtype":
        return True
    return isinstance(parent, ast.Call) and \
        _call_name(parent.func) == "astype"


def _lint_key_reuse(tree: ast.Module, path: str) -> list[Finding]:
    """Within each function's OWN scope, in source order: the same
    Name passed as the key (first positional arg) to 2+ jax.random
    draws — without being rebound in between (the `key, sub =
    split(key)` idiom resets the count) — is correlated randomness."""
    from .contracts import own_nodes
    out: list[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # (position, kind, name, line): rebinding clears the tally
        events: list[tuple[tuple[int, int], str, str, int]] = []
        for node in own_nodes(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _KEY_CONSUMERS \
                    and _src_has(node.func, "random") \
                    and node.args and isinstance(node.args[0], ast.Name):
                a = node.args[0]
                events.append(((a.lineno, a.col_offset), "use",
                               a.id, node.lineno))
            elif isinstance(node, (ast.Assign, ast.AugAssign,
                                   ast.AnnAssign, ast.NamedExpr,
                                   ast.For)):
                tgts = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in tgts:
                    for nm in ast.walk(t):
                        if isinstance(nm, ast.Name):
                            events.append(
                                ((nm.lineno, nm.col_offset), "bind",
                                 nm.id, nm.lineno))
        first_use: dict[str, int] = {}
        for _, kind, name, line in sorted(events):
            if kind == "bind":
                first_use.pop(name, None)
            elif name in first_use:
                out.append(finding(
                    "prng-key-reuse", path, line,
                    f"PRNG key {name!r} consumed again (first draw "
                    f"at line {first_use[name]}) without a split"))
            else:
                first_use[name] = line
    return out


def _lint_missing_donate(tree: ast.Module, path: str) -> list[Finding]:
    """jax.jit(...) calls/decorators without donate_argnums — large
    device inputs get copied every dispatch (warning severity: only
    worth it for entry points fed big arrays)."""
    out: list[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        target = None
        if _call_name(node.func) == "jit" and \
                _src_has(node.func, "jit"):
            target = node
        elif _call_name(node.func) == "partial" and node.args and \
                _call_name(node.args[0]) == "jit":
            target = node
        if target is None:
            continue
        kwargs = {kw.arg for kw in target.keywords}
        if not kwargs & {"donate_argnums", "donate_argnames"}:
            out.append(finding(
                "missing-donate", path, node.lineno,
                "jax.jit without donate_argnums/donate_argnames — "
                "device inputs are copied, not reused"))
    return out
