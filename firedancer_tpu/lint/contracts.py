"""Tile-contract analysis: AST over tile classes.

Scope: `firedancer_tpu/tiles/*.py` and `disco/tiles.py` — every class
that runs inside a tile process. Three contract groups:

  * metric-slot ABI: METRICS/GAUGES declarations must not collide with
    the supervisor's reserved top slots (disco/metrics.py renders both
    from the same region; disco/supervise.py owns slots >= SUP_SLOT_MIN),
    must not duplicate names (slots are positional), and every GAUGES
    entry must be a declared metric.
  * tango protocol order: Ring.publish only inside a credit window
    (a `.credits(...)` / `_wait_credits(...)` check in the same
    function; `publish_batch` is credit-gated natively), and
    Fseq.mark_stale never from tile code (the STALE sentinel is
    supervision-owned).
  * consumer progress: a registered adapter that reads `ctx.in_rings`
    must define `in_seqs()` — otherwise the stem never publishes its
    fseq progress and any reliable upstream producer wedges.

The same AST pass also exports `adapter_summaries()` — the per-kind
facts (metrics, in_seqs, ring usage) the graph analyzer cross-checks
configs against.
"""
from __future__ import annotations

import ast
import os
import re
from functools import lru_cache

from .core import Finding, filter_suppressed, finding

# reserved supervisor slot names + the slot floor, mirrored from
# disco/supervise.py (imported lazily so linting never needs the native
# runtime; verified in tests/test_lint.py against the live module)
SUP_NAMES = ("sup_restarts", "sup_watchdog_trips", "sup_down")
SUP_SLOT_MIN = 61

_RING_RECEIVER = re.compile(r"ring|out|\brq\b|\bcq\b", re.I)


def own_nodes(fn: ast.AST):
    """Yield the nodes belonging to fn's OWN body — not to nested
    function/lambda scopes (those are analyzed as their own
    functions). Scope-sensitive rules must use this, or a credit
    check inside a never-called nested helper would exempt the outer
    function's publish."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _const_str_list(node: ast.AST) -> list[str] | None:
    if not isinstance(node, (ast.List, ast.Tuple)):
        return None
    out = []
    for el in node.elts:
        if not (isinstance(el, ast.Constant) and isinstance(el.value, str)):
            return None
        out.append(el.value)
    return out


def _is_registered(cls: ast.ClassDef) -> str | None:
    """The registry kind string when the class carries
    @register("kind")."""
    for dec in cls.decorator_list:
        if isinstance(dec, ast.Call) and isinstance(dec.func, ast.Name) \
                and dec.func.id == "register" and dec.args \
                and isinstance(dec.args[0], ast.Constant):
            return str(dec.args[0].value)
    return None


def _attr_used(node: ast.AST, attr: str) -> bool:
    return any(isinstance(n, ast.Attribute) and n.attr == attr
               for n in ast.walk(node))


def _class_metrics(cls: ast.ClassDef):
    """(METRICS, line, GAUGES, line, DEVICE_SERIES, line) — each list
    or None when the class doesn't declare it."""
    metrics = gauges = device = None
    mline = gline = dline = cls.lineno
    for st in cls.body:
        if isinstance(st, ast.Assign) and len(st.targets) == 1 and \
                isinstance(st.targets[0], ast.Name):
            if st.targets[0].id == "METRICS":
                metrics, mline = _const_str_list(st.value), st.lineno
            elif st.targets[0].id == "GAUGES":
                gauges, gline = _const_str_list(st.value), st.lineno
            elif st.targets[0].id == "DEVICE_SERIES":
                device, dline = _const_str_list(st.value), st.lineno
    return metrics, mline, gauges, gline, device, dline


def lint_tiles_source(source: str, path: str) -> list[Finding]:
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [finding("silent-consumer", path, e.lineno or 0,
                        f"unparseable tile module: {e.msg}")]
    out: list[Finding] = []

    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            out.extend(_lint_class(node, path))

    # tango order rules are function-granular (own scope only, see
    # own_nodes) and apply to every function/lambda in a tile module
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)):
            out.extend(_lint_function(fn, path))
    return filter_suppressed(out, source)


def _lint_class(cls: ast.ClassDef, path: str) -> list[Finding]:
    out: list[Finding] = []
    metrics, mline, gauges, gline, device, dline = _class_metrics(cls)
    if metrics is not None:
        for nm in metrics:
            if nm in SUP_NAMES:
                out.append(finding(
                    "reserved-metric", path, mline,
                    f"{cls.name}.METRICS declares {nm!r} — reserved "
                    f"for the supervisor's top slots"))
        if len(metrics) > SUP_SLOT_MIN:
            out.append(finding(
                "metrics-overflow", path, mline,
                f"{cls.name} declares {len(metrics)} metric slots "
                f"(max {SUP_SLOT_MIN} below the supervisor region)"))
        seen: set[str] = set()
        for nm in metrics:
            if nm in seen:
                out.append(finding(
                    "dup-metric", path, mline,
                    f"{cls.name}.METRICS lists {nm!r} twice"))
            seen.add(nm)
        if gauges is not None:
            for nm in gauges:
                if nm not in metrics and nm not in SUP_NAMES:
                    out.append(finding(
                        "undeclared-gauge", path, gline,
                        f"{cls.name}.GAUGES entry {nm!r} is not a "
                        f"declared metric"))
        if device is not None:
            # same declared-subset contract as GAUGES; topo.build
            # additionally rejects reserved-family shadowing at launch
            for nm in device:
                if nm not in metrics:
                    out.append(finding(
                        "undeclared-gauge", path, dline,
                        f"{cls.name}.DEVICE_SERIES entry {nm!r} is "
                        f"not a declared metric"))
    kind = _is_registered(cls)
    if kind is not None and _attr_used(cls, "in_rings"):
        has_in_seqs = any(
            isinstance(st, ast.FunctionDef) and st.name == "in_seqs"
            for st in cls.body)
        if not has_in_seqs:
            out.append(finding(
                "silent-consumer", path, cls.lineno,
                f"adapter {cls.name} (kind {kind!r}) reads "
                f"ctx.in_rings but defines no in_seqs(); reliable "
                f"upstream producers wedge on its frozen fseq"))
    return out


def _lint_function(fn: ast.FunctionDef, path: str) -> list[Finding]:
    out: list[Finding] = []
    has_credit_check = False
    publishes: list[tuple[int, str]] = []
    for node in own_nodes(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else "")
        if name in ("credits", "_wait_credits", "publish_batch"):
            has_credit_check = True
        elif name == "mark_stale":
            out.append(finding(
                "stale-outside-supervision", path, node.lineno,
                "mark_stale() from tile code — only the supervisor "
                "marks a consumer stale (rejoin clears it)"))
        elif name == "publish" and isinstance(func, ast.Attribute):
            recv = ast.unparse(func.value)
            if _RING_RECEIVER.search(recv):
                publishes.append((node.lineno, recv))
    if not has_credit_check:
        name = getattr(fn, "name", "<lambda>")
        for line, recv in publishes:
            out.append(finding(
                "uncredited-publish", path, line,
                f"{recv}.publish() with no credit check in "
                f"{name}() — gate on .credits(fseqs) (or "
                f"_wait_credits) before publishing"))
    return out


# ---------------------------------------------------------------------------
# adapter summaries for the graph analyzer
# ---------------------------------------------------------------------------

def adapters_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "disco", "tiles.py")


@lru_cache(maxsize=4)
def adapter_summaries(path: str | None = None) -> dict[str, dict]:
    """kind -> {metrics, gauges, in_seqs, reads_in_rings,
    reads_out_rings}, extracted statically from the adapter registry
    module (no tile imports, no jax, no native lib)."""
    path = path or adapters_path()
    with open(path) as f:
        tree = ast.parse(f.read())
    out: dict[str, dict] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        kind = _is_registered(node)
        if kind is None:
            continue
        metrics, _, gauges, _, _, _ = _class_metrics(node)
        out[kind] = {
            "metrics": metrics or [],
            "gauges": gauges or [],
            "in_seqs": any(isinstance(st, ast.FunctionDef)
                           and st.name == "in_seqs"
                           for st in node.body),
            "reads_in_rings": _attr_used(node, "in_rings"),
            "reads_out_rings": _attr_used(node, "out_rings"),
        }
    return out
