"""Tile-contract analysis: AST over tile classes.

Scope: `firedancer_tpu/tiles/*.py` and `disco/tiles.py` — every class
that runs inside a tile process. Three contract groups:

  * metric-slot ABI: METRICS/GAUGES declarations must not collide with
    the supervisor's reserved top slots (disco/metrics.py renders both
    from the same region; disco/supervise.py owns slots >= SUP_SLOT_MIN),
    must not duplicate names (slots are positional), and every GAUGES
    entry must be a declared metric.
  * tango protocol order: Ring.publish only inside a credit window
    (a `.credits(...)` / `_wait_credits(...)` check in the same
    function; `publish_batch` is credit-gated natively), and
    Fseq.mark_stale never from tile code (the STALE sentinel is
    supervision-owned).
  * consumer progress: a registered adapter that reads `ctx.in_rings`
    must define `in_seqs()` — otherwise the stem never publishes its
    fseq progress and any reliable upstream producer wedges.
  * per-frag loops: inside the poll_once call closure (poll_once plus
    every same-module function it transitively calls), a Python `for`
    loop may not call the single-item hot-path APIs — `.frag(` on a
    trace writer, `.publish(` on a ring, `.insert(`/`.query(` on a
    tcache — because batched equivalents exist (frag_batch,
    publish_batch, insert_batch/query_batch) and per-txn Python on the
    batched ingest/egress path is exactly the host bottleneck the r10
    pipeline work removed. Frame-granular control work (parse + state
    machine per microblock, per-socket syscalls) suppresses inline
    with a justification.

The same AST pass also exports `adapter_summaries()` — the per-kind
facts (metrics, in_seqs, ring usage) the graph analyzer cross-checks
configs against.
"""
from __future__ import annotations

import ast
import os
import re
from functools import lru_cache

from .core import Finding, filter_suppressed, finding

# reserved supervisor slot names + the slot floor, mirrored from
# disco/supervise.py (imported lazily so linting never needs the native
# runtime; verified in tests/test_lint.py against the live module)
SUP_NAMES = ("sup_restarts", "sup_watchdog_trips", "sup_down")
SUP_SLOT_MIN = 61

_RING_RECEIVER = re.compile(r"ring|out|\brq\b|\bcq\b", re.I)
_TCACHE_RECEIVER = re.compile(r"tcache|\btc\b", re.I)


def own_nodes(fn: ast.AST):
    """Yield the nodes belonging to fn's OWN body — not to nested
    function/lambda scopes (those are analyzed as their own
    functions). Scope-sensitive rules must use this, or a credit
    check inside a never-called nested helper would exempt the outer
    function's publish."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _const_str_list(node: ast.AST) -> list[str] | None:
    if not isinstance(node, (ast.List, ast.Tuple)):
        return None
    out = []
    for el in node.elts:
        if not (isinstance(el, ast.Constant) and isinstance(el.value, str)):
            return None
        out.append(el.value)
    return out


def _is_registered(cls: ast.ClassDef) -> str | None:
    """The registry kind string when the class carries
    @register("kind")."""
    for dec in cls.decorator_list:
        if isinstance(dec, ast.Call) and isinstance(dec.func, ast.Name) \
                and dec.func.id == "register" and dec.args \
                and isinstance(dec.args[0], ast.Constant):
            return str(dec.args[0].value)
    return None


def _attr_used(node: ast.AST, attr: str) -> bool:
    return any(isinstance(n, ast.Attribute) and n.attr == attr
               for n in ast.walk(node))


def _class_metrics(cls: ast.ClassDef):
    """(METRICS, line, GAUGES, line, DEVICE_SERIES, line) — each list
    or None when the class doesn't declare it."""
    metrics = gauges = device = None
    mline = gline = dline = cls.lineno
    for st in cls.body:
        if isinstance(st, ast.Assign) and len(st.targets) == 1 and \
                isinstance(st.targets[0], ast.Name):
            if st.targets[0].id == "METRICS":
                metrics, mline = _const_str_list(st.value), st.lineno
            elif st.targets[0].id == "GAUGES":
                gauges, gline = _const_str_list(st.value), st.lineno
            elif st.targets[0].id == "DEVICE_SERIES":
                device, dline = _const_str_list(st.value), st.lineno
    return metrics, mline, gauges, gline, device, dline


def lint_tiles_source(source: str, path: str) -> list[Finding]:
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [finding("silent-consumer", path, e.lineno or 0,
                        f"unparseable tile module: {e.msg}")]
    out: list[Finding] = []

    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            out.extend(_lint_class(node, path))

    # tango order rules are function-granular (own scope only, see
    # own_nodes) and apply to every function/lambda in a tile module
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)):
            out.extend(_lint_function(fn, path))
    out.extend(_lint_per_frag_loops(tree, path))
    return filter_suppressed(out, source)


def _called_names(fn: ast.AST):
    """Names this function's own body calls OR hands off as callback
    arguments: bare names, self.attr methods, and Name/Attribute
    arguments of calls — the intra-module edges of the poll_once call
    closure (a handler passed into a gather helper is just as hot as
    one called directly)."""
    for node in own_nodes(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name):
            yield f.id
        elif isinstance(f, ast.Attribute):
            yield f.attr
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(a, ast.Name):
                yield a.id
            elif isinstance(a, ast.Attribute):
                yield a.attr


def _hot_closure(tree: ast.Module):
    """Functions reachable from any poll_once by same-module calls
    (matched by bare name — class boundaries ignored on purpose: a
    helper shared by two adapters is hot if either reaches it)."""
    defs: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            defs.setdefault(node.name, []).append(node)
    hot: set[str] = set()
    work = ["poll_once"]
    while work:
        name = work.pop()
        if name in hot or name not in defs:
            continue
        hot.add(name)
        for fn in defs[name]:
            work.extend(_called_names(fn))
    return [fn for name in hot for fn in defs[name]]


def _single_item_call(node: ast.AST):
    """-> (receiver, name, batched) when `node` is a single-item
    hot-path API call with a batched equivalent, else None."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)):
        return None
    name = node.func.attr
    recv = ast.unparse(node.func.value)
    if name == "frag":
        return recv, name, "frag_batch"
    if name == "publish" and _RING_RECEIVER.search(recv):
        return recv, name, "publish_batch"
    if name in ("insert", "query") and _TCACHE_RECEIVER.search(recv):
        return recv, name, f"{name}_batch"
    return None


def _tainted_fns(tree: ast.Module) -> set[str]:
    """Same-module function names whose call closure reaches a
    single-item hot-path API — so a for loop calling such a helper
    per iteration is per-frag work even though the .publish itself
    lives a frame lower."""
    direct: set[str] = set()
    edges: dict[str, set[str]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        if any(_single_item_call(x) for x in own_nodes(node)):
            direct.add(node.name)
        edges.setdefault(node.name, set()).update(_called_names(node))
    tainted = set(direct)
    changed = True
    while changed:
        changed = False
        for name, calls in edges.items():
            if name not in tainted and calls & tainted:
                tainted.add(name)
                changed = True
    return tainted


def _lint_per_frag_loops(tree: ast.Module, path: str) -> list[Finding]:
    """Flag single-item hot-path API calls inside `for` loops of the
    poll_once call closure — each has a batched equivalent, and one
    per-frag Python iteration costs more than the whole native batch
    call it should have been. Indirect forms count too: a loop calling
    a same-module helper whose closure reaches a single-item API is
    the same defect one frame deeper (the nested-closure-handed-to-a-
    gather-helper pattern rides the closure walk in _called_names)."""
    out: list[Finding] = []
    tainted = _tainted_fns(tree)
    seen: set[tuple[int, int]] = set()   # nested fors see a call twice
    for fn in _hot_closure(tree):
        for loop in own_nodes(fn):
            if not isinstance(loop, ast.For):
                continue
            for node in own_nodes(loop):
                if (getattr(node, "lineno", 0),
                        getattr(node, "col_offset", 0)) in seen:
                    continue
                if not isinstance(node, ast.Call):
                    continue
                hit = _single_item_call(node)
                if hit:
                    recv, name, batched = hit
                    seen.add((node.lineno, node.col_offset))
                    # anchor on the LOOP line: the loop is the defect
                    # (and the suppression point), the call is the
                    # evidence
                    out.append(finding(
                        "per-frag-loop", path, loop.lineno,
                        f"{recv}.{name}() (line {node.lineno}) inside "
                        f"a for loop in {fn.name}() (poll_once hot "
                        f"path) — use the batched {recv}.{batched}() "
                        f"outside the loop"))
                    continue
                callee = None
                if isinstance(node.func, ast.Name):
                    callee = node.func.id
                elif isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "self":
                    callee = node.func.attr
                if callee in tainted:
                    seen.add((node.lineno, node.col_offset))
                    out.append(finding(
                        "per-frag-loop", path, loop.lineno,
                        f"{callee}() (line {node.lineno}) called per "
                        f"iteration in {fn.name}() (poll_once hot "
                        f"path) reaches a single-item .frag/.publish/"
                        f"tcache API — hoist to the batched form "
                        f"outside the loop"))
    return out


def _lint_class(cls: ast.ClassDef, path: str) -> list[Finding]:
    out: list[Finding] = []
    metrics, mline, gauges, gline, device, dline = _class_metrics(cls)
    if metrics is not None:
        for nm in metrics:
            if nm in SUP_NAMES:
                out.append(finding(
                    "reserved-metric", path, mline,
                    f"{cls.name}.METRICS declares {nm!r} — reserved "
                    f"for the supervisor's top slots"))
        if len(metrics) > SUP_SLOT_MIN:
            out.append(finding(
                "metrics-overflow", path, mline,
                f"{cls.name} declares {len(metrics)} metric slots "
                f"(max {SUP_SLOT_MIN} below the supervisor region)"))
        seen: set[str] = set()
        for nm in metrics:
            if nm in seen:
                out.append(finding(
                    "dup-metric", path, mline,
                    f"{cls.name}.METRICS lists {nm!r} twice"))
            seen.add(nm)
        if gauges is not None:
            for nm in gauges:
                if nm not in metrics and nm not in SUP_NAMES:
                    out.append(finding(
                        "undeclared-gauge", path, gline,
                        f"{cls.name}.GAUGES entry {nm!r} is not a "
                        f"declared metric"))
        if device is not None:
            # same declared-subset contract as GAUGES; topo.build
            # additionally rejects reserved-family shadowing at launch
            for nm in device:
                if nm not in metrics:
                    out.append(finding(
                        "undeclared-gauge", path, dline,
                        f"{cls.name}.DEVICE_SERIES entry {nm!r} is "
                        f"not a declared metric"))
    kind = _is_registered(cls)
    if kind is not None and _attr_used(cls, "in_rings"):
        has_in_seqs = any(
            isinstance(st, ast.FunctionDef) and st.name == "in_seqs"
            for st in cls.body)
        if not has_in_seqs:
            out.append(finding(
                "silent-consumer", path, cls.lineno,
                f"adapter {cls.name} (kind {kind!r}) reads "
                f"ctx.in_rings but defines no in_seqs(); reliable "
                f"upstream producers wedge on its frozen fseq"))
    return out


def _lint_function(fn: ast.FunctionDef, path: str) -> list[Finding]:
    out: list[Finding] = []
    has_credit_check = False
    publishes: list[tuple[int, str]] = []
    for node in own_nodes(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else "")
        if name in ("credits", "_wait_credits", "publish_batch"):
            has_credit_check = True
        elif name == "mark_stale":
            out.append(finding(
                "stale-outside-supervision", path, node.lineno,
                "mark_stale() from tile code — only the supervisor "
                "marks a consumer stale (rejoin clears it)"))
        elif name == "publish" and isinstance(func, ast.Attribute):
            recv = ast.unparse(func.value)
            if _RING_RECEIVER.search(recv):
                publishes.append((node.lineno, recv))
    if not has_credit_check:
        name = getattr(fn, "name", "<lambda>")
        for line, recv in publishes:
            out.append(finding(
                "uncredited-publish", path, line,
                f"{recv}.publish() with no credit check in "
                f"{name}() — gate on .credits(fseqs) (or "
                f"_wait_credits) before publishing"))
    return out


# ---------------------------------------------------------------------------
# adapter summaries for the graph analyzer
# ---------------------------------------------------------------------------

def adapters_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "disco", "tiles.py")


@lru_cache(maxsize=4)
def adapter_summaries(path: str | None = None) -> dict[str, dict]:
    """kind -> {metrics, gauges, in_seqs, reads_in_rings,
    reads_out_rings}, extracted statically from the adapter registry
    module (no tile imports, no jax, no native lib)."""
    path = path or adapters_path()
    with open(path) as f:
        tree = ast.parse(f.read())
    out: dict[str, dict] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        kind = _is_registered(node)
        if kind is None:
            continue
        metrics, _, gauges, _, _, _ = _class_metrics(node)
        out[kind] = {
            "metrics": metrics or [],
            "gauges": gauges or [],
            "in_seqs": any(isinstance(st, ast.FunctionDef)
                           and st.name == "in_seqs"
                           for st in node.body),
            "reads_in_rings": _attr_used(node, "in_rings"),
            "reads_out_rings": _attr_used(node, "out_rings"),
        }
    return out
