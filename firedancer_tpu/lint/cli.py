"""fdlint CLI: `python -m firedancer_tpu.lint [paths...]`.

File routing (mirrors the analyzer scopes):

    *.toml                         -> graph analysis (app/config.py load,
                                      `# fdlint: layers=` honored)
    **/tiles/*.py, **/disco/tiles.py -> tile-contract analysis
    **/ops/*.py,  **/tiles/*.py      -> JAX/Pallas purity analysis

Exit status: nonzero iff any non-baselined ERROR finding remains
(warnings report but never gate). `--format json` is stable for
machine consumption (schema-versioned, sorted, fixed keys).
"""
from __future__ import annotations

import argparse
import os
import sys

from .core import (Finding, RULES, filter_baselined, load_baseline,
                   render_json, render_text)

DEFAULT_BASELINE = "lint-baseline.toml"


def _collect(paths: list[str]) -> tuple[list[str], list[str], list[str]]:
    toml, contract, jaxf = [], [], []

    def route(p: str):
        q = p.replace(os.sep, "/")
        if q.endswith(".toml") and not q.endswith(DEFAULT_BASELINE):
            toml.append(p)
        elif q.endswith(".py"):
            if "/tiles/" in q or q.endswith("disco/tiles.py"):
                contract.append(p)
            if "/ops/" in q or "/tiles/" in q:
                jaxf.append(p)

    for path in paths:
        if os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                for fn in sorted(files):
                    route(os.path.join(root, fn))
        else:
            route(path)
    return toml, contract, jaxf


def run(paths: list[str]) -> list[Finding]:
    from .core import check_suppressions
    from .contracts import lint_tiles_source
    from .graph import lint_config_file
    from .jaxlint import lint_jax_source
    toml, contract, jaxf = _collect(paths)
    findings: list[Finding] = []
    sources: dict[str, str] = {}        # read each file exactly once

    def src(p: str) -> str:
        if p not in sources:
            with open(p) as f:
                sources[p] = f.read()
        return sources[p]

    for p in toml:
        src(p)
        findings.extend(lint_config_file(p))
    for p in contract:
        findings.extend(lint_tiles_source(src(p), p))
    for p in jaxf:
        findings.extend(lint_jax_source(src(p), p))
    for p in sorted(sources):           # typo'd disable= tokens
        findings.extend(check_suppressions(sources[p], p))
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fdlint",
        description="static topology / tile-contract / JAX purity lint")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories (default: cfg "
                         "firedancer_tpu, relative to the repo root)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline TOML (default: {DEFAULT_BASELINE} "
                         f"next to the package, then cwd)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline file")
    ap.add_argument("--rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.rules:
        for rule, (family, sev, desc) in sorted(
                RULES.items(), key=lambda kv: (kv[1][0], kv[0])):
            print(f"{rule:28s} {family:9s} {sev:8s} {desc}")
        return 0

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    paths = args.paths or [os.path.join(repo_root, "cfg"),
                           os.path.join(repo_root, "firedancer_tpu")]
    findings = run(paths)

    if not args.no_baseline:
        bl_path = args.baseline
        if bl_path is None:
            cand = os.path.join(repo_root, DEFAULT_BASELINE)
            bl_path = cand if os.path.exists(cand) else DEFAULT_BASELINE
        findings = filter_baselined(findings, load_baseline(bl_path))

    out = render_json(findings) if args.format == "json" \
        else render_text(findings) + "\n"
    sys.stdout.write(out)
    return 1 if any(f.severity == "error" for f in findings) else 0
