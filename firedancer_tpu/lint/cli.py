"""fdlint CLI: `python -m firedancer_tpu.lint [paths...]`.

File routing (mirrors the analyzer scopes):

    *.toml                         -> graph analysis (app/config.py load,
                                      `# fdlint: layers=` honored)
    **/tiles/*.py, **/disco/tiles.py -> tile-contract analysis
    **/ops/*.py,  **/tiles/*.py      -> JAX/Pallas purity analysis
    **/*.py                          -> abi short-key + shm ownership
                                        analysis (lint/abi.py,
                                        lint/ownership.py)

Tree-level passes (wire-contract catalog, registry-drift mirror) run
whenever the scan covers the package itself — they pin cross-module
agreements and so read their cataloged modules directly.

`--changed [BASE]` lints only files reported by
`git diff --name-only BASE` (default HEAD) — the fast pre-commit
loop; the full default run stays the tier-1 gate. Touching lint/
itself escalates to a full run, since every file is reachable from an
analyzer change.

Exit status: nonzero iff any non-baselined ERROR finding remains
(warnings report but never gate). `--format json` is stable for
machine consumption (schema-versioned, sorted, fixed keys).
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys

from .core import (Finding, RULES, filter_baselined, load_baseline,
                   render_json, render_text)

DEFAULT_BASELINE = "lint-baseline.toml"


def _collect(paths: list[str]) -> tuple[
        list[str], list[str], list[str], list[str]]:
    toml, contract, jaxf, py = [], [], [], []

    def route(p: str):
        q = p.replace(os.sep, "/")
        if q.endswith(".toml") and not q.endswith(DEFAULT_BASELINE):
            toml.append(p)
        elif q.endswith(".py"):
            py.append(p)
            if "/tiles/" in q or q.endswith("disco/tiles.py"):
                contract.append(p)
            if "/ops/" in q or "/tiles/" in q:
                jaxf.append(p)

    for path in paths:
        if os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                for fn in sorted(files):
                    route(os.path.join(root, fn))
        else:
            route(path)
    return toml, contract, jaxf, py


def run(paths: list[str], tree: bool | None = None) -> list[Finding]:
    """Lint `paths`. `tree` forces the tree-level passes on/off;
    None auto-enables them when the scan reaches into the package."""
    from .core import check_suppressions
    from .abi import (lint_abi_source, lint_registry_drift,
                      lint_wire_contracts, pkg_root)
    from .contracts import lint_tiles_source
    from .graph import lint_config_file
    from .jaxlint import lint_jax_source
    from .ownership import lint_ownership_source
    toml, contract, jaxf, py = _collect(paths)
    findings: list[Finding] = []
    sources: dict[str, str] = {}        # read each file exactly once

    def src(p: str) -> str:
        if p not in sources:
            with open(p) as f:
                sources[p] = f.read()
        return sources[p]

    for p in toml:
        src(p)
        findings.extend(lint_config_file(p))
    for p in contract:
        findings.extend(lint_tiles_source(src(p), p))
    for p in jaxf:
        findings.extend(lint_jax_source(src(p), p))
    for p in py:
        findings.extend(lint_abi_source(src(p), p))
        findings.extend(lint_ownership_source(src(p), p))
    if tree is None:
        root = os.path.abspath(pkg_root())
        tree = any(os.path.abspath(p).startswith(root + os.sep)
                   for p in py)
    if tree:
        findings.extend(lint_wire_contracts())
        findings.extend(lint_registry_drift())
    for p in sorted(sources):           # typo'd disable= tokens
        findings.extend(check_suppressions(sources[p], p))
    return findings


def changed_paths(repo_root: str, base: str) -> list[str] | None:
    """Repo files changed vs `base` (plus untracked), absolute paths;
    None when git is unavailable (caller falls back to a full run)."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", base, "--"],
            cwd=repo_root, capture_output=True, text=True, timeout=30)
        extra = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=repo_root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if diff.returncode != 0:
        return None
    names = [ln.strip() for ln in
             diff.stdout.splitlines() + extra.stdout.splitlines()
             if ln.strip()]
    out = []
    for name in names:
        p = os.path.join(repo_root, name)
        if os.path.exists(p):
            out.append(p)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fdlint",
        description="static topology / tile-contract / JAX purity / "
                    "wire-abi / shm-ownership lint")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories (default: cfg "
                         "firedancer_tpu, relative to the repo root)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline TOML (default: {DEFAULT_BASELINE} "
                         f"next to the package, then cwd)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline file")
    ap.add_argument("--rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="BASE",
                    help="lint only files changed vs BASE (default "
                         "HEAD) — fast pre-commit loop; falls back to "
                         "a full run if lint/ itself changed")
    args = ap.parse_args(argv)

    if args.rules:
        for rule, (family, sev, desc) in sorted(
                RULES.items(), key=lambda kv: (kv[1][0], kv[0])):
            print(f"{rule:28s} {family:9s} {sev:8s} {desc}")
        return 0

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    paths = args.paths or [os.path.join(repo_root, "cfg"),
                           os.path.join(repo_root, "firedancer_tpu")]
    tree: bool | None = None
    if args.changed is not None:
        changed = changed_paths(repo_root, args.changed)
        if changed is not None and not any(
                "/lint/" in p.replace(os.sep, "/") for p in changed):
            scoped = [p for p in changed
                      if any(os.path.abspath(p).startswith(
                          os.path.abspath(d) + os.sep) or
                          os.path.abspath(p) == os.path.abspath(d)
                          for d in paths)]
            # tree-level catalogs pin cross-module agreements: run
            # them only when a mirrored/cataloged module changed
            from .abi import (SECTION_MIRRORS, WIRE_CONTRACTS,
                              _ADAPTERS_SUFFIX)
            watched = {_ADAPTERS_SUFFIX}
            watched.update(m[1] for m in SECTION_MIRRORS)
            for _, _, sites in WIRE_CONTRACTS:
                watched.update(s[0] for s in sites)
            tree = any(p.replace(os.sep, "/").endswith(w)
                       for p in scoped for w in watched)
            paths = scoped
            if not paths and not tree:
                sys.stdout.write(
                    render_json([]) if args.format == "json"
                    else "clean: no lintable changes\n")
                return 0
    findings = run(paths, tree=tree)

    if not args.no_baseline:
        bl_path = args.baseline
        if bl_path is None:
            cand = os.path.join(repo_root, DEFAULT_BASELINE)
            bl_path = cand if os.path.exists(cand) else DEFAULT_BASELINE
        findings = filter_baselined(findings, load_baseline(bl_path))

    out = render_json(findings) if args.format == "json" \
        else render_text(findings) + "\n"
    sys.stdout.write(out)
    return 1 if any(f.severity == "error" for f in findings) else 0
