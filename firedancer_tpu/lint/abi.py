"""Wire/shm ABI contract analysis: the cross-process byte contracts.

Firedancer's concurrency model is processes agreeing on hand-rolled
binary contracts — ring frames, wksp offsets, metric slots — with no
compiler checking either side. This analyzer makes the two ABI bug
classes that already bit this clone statically impossible to ship:

  * wire-mismatch: every cataloged `Ring.publish`/consume site's
    struct format strings are AST-extracted and pinned against the
    WIRE_CONTRACTS catalog below. Editing one side of a wire (or the
    catalog) without the other is a review-time finding at the site
    that drifted. Formats are compared whitespace-normalized (struct
    ignores whitespace) and resolve module-level
    `_X = struct.Struct(fmt)` constants.
  * short-key: any bytes key reaching a store/funk API must provably
    be 32 bytes wide — the exact r17 `_key32` class (the native store
    ABI reads EXACTLY 32 key bytes; a 15-byte python buffer hashed
    per-process trailing garbage and wedged the follower gate).
    Accepted proofs: a 32-byte literal/slice/concatenation, a
    `*key32*(...)` call, `.digest()`, `bytes(32)`, `.ljust(32, ...)`,
    an ALL_CAPS module constant (reviewed at its definition), or —
    for a plain name — a same-scope `assert len(k) == 32` /
    `if len(k) != 32: raise` guard or `key32(k)` call.
  * registry-drift: lint/registry.py's hand-maintained mirrors are
    recomputed from the code they mirror — adapter `args.get(...)`
    keys vs TILE_ARGS, and each `[section]` key tuple vs the owning
    module's *_DEFAULTS dict.

The wire-mtu rule (frame size vs link mtu for the exec/replay/shred
wire families) lives in lint/graph.py's `_check_wire_mtus`, because
attributing a wire to its link needs the topology model; the minimums
it enforces are mirrored in registry.py next to the older growth
contracts.
"""
from __future__ import annotations

import ast
import os
import re

from .core import Finding, filter_suppressed, finding
from . import registry as reg

# ---------------------------------------------------------------------------
# struct-format extraction
# ---------------------------------------------------------------------------

_STRUCT_FNS = ("pack", "pack_into", "unpack", "unpack_from",
               "iter_unpack", "calcsize")


def _norm_fmt(fmt: str) -> str:
    return re.sub(r"\s+", "", fmt)


def _struct_consts(tree: ast.Module) -> dict[str, str]:
    """module-level `_X = struct.Struct("fmt")` name -> fmt."""
    out: dict[str, str] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Name):
            v = n.value
            if isinstance(v, ast.Call) and \
                    isinstance(v.func, ast.Attribute) and \
                    v.func.attr == "Struct" and v.args and \
                    isinstance(v.args[0], ast.Constant) and \
                    isinstance(v.args[0].value, str):
                out[n.targets[0].id] = v.args[0].value
    return out


def _formats_in(node: ast.AST, consts: dict[str, str]) -> dict[str, int]:
    """normalized format -> first line, for every struct call under
    `node` (struct.pack/unpack*, struct.Struct, and pack/unpack on a
    module-level Struct constant)."""
    out: dict[str, int] = {}

    def add(fmt: str, line: int):
        fmt = _norm_fmt(fmt)
        if fmt not in out:
            out[fmt] = line
    for n in ast.walk(node):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if not isinstance(f, ast.Attribute):
            continue
        recv = f.value
        if isinstance(recv, ast.Name) and recv.id == "struct" and \
                f.attr in _STRUCT_FNS + ("Struct",):
            if n.args and isinstance(n.args[0], ast.Constant) and \
                    isinstance(n.args[0].value, str):
                add(n.args[0].value, n.lineno)
        elif f.attr in _STRUCT_FNS:
            name = recv.id if isinstance(recv, ast.Name) else (
                recv.attr if isinstance(recv, ast.Attribute) else None)
            if name in consts:
                add(consts[name], n.lineno)
    return out


def module_format_map(source: str) -> dict[str, dict[str, int]]:
    """qualname ("Class.method" or "function") -> {fmt: first line}.
    Nested defs fold into their enclosing top-level def (the wire site
    granularity the catalog pins)."""
    tree = ast.parse(source)
    consts = _struct_consts(tree)
    out: dict[str, dict[str, int]] = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            for st in node.body:
                if isinstance(st, ast.FunctionDef):
                    fmts = _formats_in(st, consts)
                    if fmts:
                        out[f"{node.name}.{st.name}"] = fmts
        elif isinstance(node, ast.FunctionDef):
            fmts = _formats_in(node, consts)
            if fmts:
                out[node.name] = fmts
    return out


# ---------------------------------------------------------------------------
# the wire-contract catalog
# ---------------------------------------------------------------------------

# Each contract: (wire name, layout doc, sites); each site:
# (module path suffix, qualname, expected normalized formats). A site
# whose extracted formats lose one of these — or that vanishes — is a
# wire-mismatch finding; extra formats at a cataloged site (uncataloged
# ABI growth) are flagged by the exact-cover pass below. Sites list
# BOTH directions of every wire, so editing a producer's pack without
# its consumers' unpack (or vice versa) cannot pass review silently.
WIRE_CONTRACTS: tuple = (
    ("pack->bank microblock",
     "<HHQQ> 20B header (bank, txn_cnt, mb_id, slot) + <H>-framed "
     "txn payloads",
     (("disco/tiles.py", "PackAdapter._serialize", ("<H", "<HHQQ")),
      ("disco/tiles.py", "BankAdapter.poll_once", ("<HHQQ",)),
      ("disco/tiles.py", "BankAdapter._poll_exec_family", ("<HHQQ",)),
      ("disco/tiles.py", "BankAdapter._parse_payloads", ("<H",)),
      ("disco/tiles.py", "BankAdapter._parse_transfers", ("<H",)))),
    ("bank->pack done",
     "<QH> (mb_id, txn_cnt) per retired microblock; <Q> slot flush",
     (("disco/tiles.py", "BankAdapter._finalize_wave", ("<QH",)),
      ("disco/tiles.py", "BankAdapter._ef_commit", ("<QH",)),
      ("disco/tiles.py", "BankAdapter._wave_general", ("<QH",)),
      ("disco/tiles.py", "BankAdapter._flush_wave", ("<Q",)),
      ("disco/tiles.py", "PackAdapter.poll_once", ("<H", "<Q")))),
    ("bank/replay->exec dispatch + exec->done (r16 wire)",
     "<QQH> (wave_seq, xid, txn_cnt) + 80B rows (32B src + 32B dst + "
     "<QQ> amount,fee); completion <QII> (wave_seq, ok, fail)",
     (("disco/tiles.py", "ExecFanout._send", ("<QQ", "<QQH")),
      ("disco/tiles.py", "ExecFanout.poll", ("<QII",)),
      ("disco/tiles.py", "ExecAdapter.poll_once",
       ("<QQ", "<QQH", "<QII")))),
    ("bank->poh microblock handoff",
     "42B header; poh reads the txn_cnt <H> at offset 8",
     (("disco/tiles.py", "PohAdapter.poll_once", ("<H",)),)),
    ("poh->shred entry wire",
     "<QIIB> (slot, tick, num_hashes, has_mix) + 32B hash + <H> txn "
     "blob; shred re-frames into <I>-counted entry batches",
     (("disco/tiles.py", "PohAdapter._emit_entry", ("<H", "<QIIB")),
      ("tiles/shred.py", "ShredLeaderCore.on_entry",
       ("<H", "<I", "<QIIB")),
      ("tiles/shred.py", "parse_entry_batch", ("<H", "<I")))),
    ("poh slot wire",
     "<Q> completed slot",
     (("disco/tiles.py", "PohAdapter._flush_pending", ("<Q",)),)),
    ("shred->replay slice wire (r17)",
     "<QIB> (slot, first_fec_idx, done) + entry-batch payload",
     (("tiles/shred.py", "pack_slice", ("<QIB",)),
      ("tiles/shred.py", "parse_slice", ("<QIB",)))),
    ("shred wire (turbine/repair)",
     "fixed header: slot <Q> at 0x41, idx <I> at 0x49; batch flush "
     "<QB>",
     (("tiles/shred.py", "ShredLeaderCore._flush", ("<QB",)),
      ("tiles/shred.py", "ShredLeaderCore._tx", ("<I",)),
      ("tiles/shred.py", "ShredRecoverCore.on_shred", ("<I", "<Q")),
      ("tiles/shred.py", "ShredRecoverCore._retransmit",
       ("<I", "<Q")))),
    ("replay->tower block/vote wire",
     "block: tag + <QQ> (slot, parent) + 2x32B ids; vote: tag + 32B "
     "voter + <Q> stake + 32B block id",
     (("tiles/tower.py", "pack_block", ("<QQ",)),
      ("tiles/tower.py", "pack_vote", ("<Q",)),
      ("tiles/tower.py", "TowerCore.handle", ("<Q", "<QQ")))),
    ("tower->send root/votes wire",
     "<Q> slot + 32B block id + optional root <Q> + <H>-counted "
     "<QI> (slot, conf) votes",
     (("disco/tiles.py", "TowerAdapter.housekeeping",
       ("<H", "<Q", "<QI")),
      ("disco/tiles.py", "SendAdapter.poll_once", ("<H", "<Q", "<QI")))),
    ("archiver record wire",
     "<QQHI> (seq, sig, ctl, sz) + payload, one record per frag",
     (("tiles/archiver.py", "ArchiveWriter.poll_once", ("<QQHI",)),
      ("tiles/archiver.py", "ArchivePlayback.poll_once", ("<QQHI",)))),
    ("vinyl req/resp wire",
     "req: op u8 + <Q> req_id + 32B key [+ value]; resp: <QB> "
     "(req_id, status) [+ value]",
     (("disco/tiles.py", "VinylAdapter._serve", ("<Q", "<QB")),)),
    ("funk account codec (snapshot/checkpt/vinyl shared)",
     "<Q32sBQ> account header + tag-framed <Q> value frames",
     (("funk/shmfunk.py", "encode_value", ("<Q", "<Q32sBQ")),
      ("funk/shmfunk.py", "decode_value", ("<Q", "<Q32sBQ")))),
)


def pkg_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _contract_files() -> dict[str, str]:
    """module path suffix -> absolute path for every cataloged module."""
    root = pkg_root()
    out = {}
    for _, _, sites in WIRE_CONTRACTS:
        for suffix, _, _ in sites:
            out[suffix] = os.path.join(root, *suffix.split("/"))
    return out


def lint_wire_contracts(
        sources: dict[str, str] | None = None) -> list[Finding]:
    """Check every WIRE_CONTRACTS site. `sources` (path suffix ->
    module source) overrides the shipped tree — fixtures inject a
    skewed module to prove the analyzer catches a seeded mismatch."""
    if sources is None:
        sources = {}
        for suffix, path in _contract_files().items():
            try:
                with open(path) as f:
                    sources[suffix] = f.read()
            except OSError:
                sources[suffix] = ""
    maps: dict[str, dict[str, dict[str, int]]] = {}
    for suffix, src in sources.items():
        try:
            maps[suffix] = module_format_map(src)
        except SyntaxError:
            maps[suffix] = {}
    out: list[Finding] = []
    cataloged: dict[tuple[str, str], set[str]] = {}
    for wire, _doc, sites in WIRE_CONTRACTS:
        for suffix, qual, fmts in sites:
            if suffix not in sources:
                continue                # fixture runs scope to one file
            want = {_norm_fmt(f) for f in fmts}
            cataloged.setdefault((suffix, qual), set()).update(want)
            got = maps[suffix].get(qual)
            if got is None:
                out.append(finding(
                    "wire-mismatch", suffix, 0,
                    f"wire {wire!r}: cataloged site {qual}() vanished "
                    f"(renamed or dropped) — re-sync lint/abi.py "
                    f"WIRE_CONTRACTS with both sides of the wire"))
                continue
            missing = want - set(got)
            if missing:
                line = min(got.values()) if got else 0
                out.append(finding(
                    "wire-mismatch", suffix, line,
                    f"wire {wire!r}: {qual}() no longer uses "
                    f"{sorted(missing)} (found {sorted(got)}) — the "
                    f"other side of this wire still parses the "
                    f"cataloged layout"))
    # exact cover: a cataloged site growing a NEW format is silent ABI
    # drift until its counterpart sites and the catalog acknowledge it
    for (suffix, qual), want in sorted(cataloged.items()):
        got = maps.get(suffix, {}).get(qual)
        if not got:
            continue
        extra = set(got) - want
        for fmt in sorted(extra):
            out.append(finding(
                "wire-mismatch", suffix, got[fmt],
                f"{qual}() uses format {fmt!r} not in its "
                f"WIRE_CONTRACTS entry — if the wire grew, update the "
                f"catalog AND every counterpart site"))
    filtered: list[Finding] = []
    for f in out:
        src = sources.get(f.path, "")
        filtered.extend(filter_suppressed([f], src))
    return filtered


# ---------------------------------------------------------------------------
# short-key: fixed-width keys from unvalidated-length sources
# ---------------------------------------------------------------------------

# method name -> positional index of the key argument. WRITE apis
# only: a short-key write poisons shared state permanently (the record
# lands under a garbage-extended key no other process can derive); a
# short-key read just misses, loudly and locally.
_KEY_APIS = {"rec_write": 1, "rec_remove": 1}
_KV_APIS = {"put": 0, "delete": 0}
_KV_RECEIVER = re.compile(r"(?:^|\.)(?:db|store|funk|vinyl)$")

KEY_WIDTH = 32


def _const_len(node: ast.AST) -> int | None:
    """Provable byte width of an expression, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, bytes):
        return len(node.value)
    if isinstance(node, ast.Subscript) and \
            isinstance(node.slice, ast.Slice) and node.slice.step is None:
        lo, hi = node.slice.lower, node.slice.upper
        lo_v = 0 if lo is None else (
            lo.value if isinstance(lo, ast.Constant) and
            isinstance(lo.value, int) else None)
        hi_v = hi.value if isinstance(hi, ast.Constant) and \
            isinstance(hi.value, int) else None
        if lo_v is not None and hi_v is not None and 0 <= lo_v <= hi_v:
            return hi_v - lo_v
        return None
    if isinstance(node, ast.Call):
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        if "key32" in name or name == "digest":
            return KEY_WIDTH
        if name in ("ljust", "rjust", "to_bytes", "bytes") and \
                node.args and isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, int):
            return node.args[0].value
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left, right = _const_len(node.left), _const_len(node.right)
        if left is not None and right is not None:
            return left + right
    return None


def _len_checked_exprs(fn: ast.AST) -> set[str]:
    """Unparsed expressions `x` with a same-scope `len(x) == 32` /
    `len(x) != 32` guard (assert or if-raise) or a `*key32*(x)` call —
    the width-normalization proofs the short-key rule accepts."""
    from .contracts import own_nodes
    out: set[str] = set()
    for n in own_nodes(fn):
        if isinstance(n, ast.Compare) and len(n.comparators) == 1 and \
                isinstance(n.ops[0], (ast.Eq, ast.NotEq)):
            for side in (n.left, n.comparators[0]):
                if isinstance(side, ast.Call) and \
                        isinstance(side.func, ast.Name) and \
                        side.func.id == "len" and side.args:
                    other = n.comparators[0] if side is n.left else n.left
                    if isinstance(other, ast.Constant) and \
                            other.value == KEY_WIDTH:
                        out.add(ast.unparse(side.args[0]))
        elif isinstance(n, ast.Call):
            f = n.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            if "key32" in name:
                for a in n.args:
                    out.add(ast.unparse(a))
        elif isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Name) and \
                _const_len(n.value) == KEY_WIDTH:
            out.add(n.targets[0].id)
    return out


def _key_arg(node: ast.Call) -> tuple[ast.AST, str] | None:
    """(key expression, api name) when `node` calls a store/funk key
    API, else None."""
    f = node.func
    if not isinstance(f, ast.Attribute):
        return None
    name = f.attr
    if name in _KEY_APIS:
        idx = _KEY_APIS[name]
    elif name in _KV_APIS and \
            _KV_RECEIVER.search(ast.unparse(f.value)):
        idx = _KV_APIS[name]
    else:
        return None
    if len(node.args) <= idx:
        return None
    return node.args[idx], name


def lint_abi_source(source: str, path: str) -> list[Finding]:
    """Per-file short-key analysis (the wire/registry passes are
    tree-level; see lint_wire_contracts / lint_registry_drift)."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    out: list[Finding] = []
    fns = [n for n in ast.walk(tree)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    from .contracts import own_nodes
    for fn in fns:
        checked: set[str] | None = None     # computed lazily per fn
        for node in own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            hit = _key_arg(node)
            if hit is None:
                continue
            key, api = hit
            width = _const_len(key)
            if width == KEY_WIDTH:
                continue
            if width is not None:
                out.append(finding(
                    "short-key", path, node.lineno,
                    f"{api}() key is provably {width} bytes, store "
                    f"keys are {KEY_WIDTH} — the native ABI reads "
                    f"exactly {KEY_WIDTH} and hashes per-process "
                    f"trailing garbage past a short buffer"))
                continue
            if isinstance(key, ast.Name) and key.id.isupper():
                continue        # module constant, reviewed at its def
            if checked is None:
                checked = _len_checked_exprs(fn)
            if ast.unparse(key) in checked:
                continue
            out.append(finding(
                "short-key", path, node.lineno,
                f"{api}() key {ast.unparse(key)!r} has no provable "
                f"{KEY_WIDTH}-byte width in {fn.name}() — pass it "
                f"through a width-normalizing helper (key32 / "
                f".digest() / .ljust({KEY_WIDTH},...)) or guard with "
                f"len(...) == {KEY_WIDTH}"))
    return filter_suppressed(out, source)


# ---------------------------------------------------------------------------
# registry drift: the analyzer computes the mirror
# ---------------------------------------------------------------------------

# section -> (owning module suffix, defaults symbol, registry tuple
# name, keys in the registry tuple that are structural sub-tables or
# reference lists resolved by the graph analyzer, not defaults)
SECTION_MIRRORS = (
    ("trace", "trace/recorder.py", "TRACE_DEFAULTS",
     "TRACE_SECTION_KEYS", ()),
    ("prof", "prof/recorder.py", "PROF_DEFAULTS",
     "PROF_SECTION_KEYS", ()),
    ("slo", "disco/slo.py", "SLO_DEFAULTS", "SLO_SECTION_KEYS", ()),
    ("shed", "disco/shed.py", "SHED_DEFAULTS", "SHED_SECTION_KEYS", ()),
    ("funk", "funk/shmfunk.py", "FUNK_DEFAULTS",
     "FUNK_SECTION_KEYS", ()),
    ("replay", "tiles/replay.py", "REPLAY_DEFAULTS",
     "REPLAY_SECTION_KEYS", ()),
    ("snapshot", "tiles/snapshot.py", "SNAPSHOT_DEFAULTS",
     "SNAPSHOT_SECTION_KEYS", ()),
    ("witness", "witness/plan.py", "WITNESS_DEFAULTS",
     "WITNESS_SECTION_KEYS", ("stage",)),
    ("flight", "flight/__init__.py", "FLIGHT_DEFAULTS",
     "FLIGHT_SECTION_KEYS", ()),
    ("tune", "tune/__init__.py", "TUNE_DEFAULTS",
     "TUNE_SECTION_KEYS", ()),
)

_ADAPTERS_SUFFIX = "disco/tiles.py"


def _dict_literal_keys(source: str, symbol: str) -> set[str] | None:
    """Keys of a module-level `SYMBOL = {...}` dict literal, extracted
    statically (no import: the owning modules pull in jax/numpy)."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == symbol and \
                isinstance(node.value, ast.Dict):
            keys = set()
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
            return keys
    return None


def _registry_line(symbol: str) -> tuple[str, int]:
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "registry.py")
    rel = "lint/registry.py"
    try:
        with open(path) as f:
            for i, text in enumerate(f, start=1):
                if re.match(rf"\s*{symbol}\b[^=]*=", text):
                    return rel, i
    except OSError:
        pass
    return rel, 0


def check_section_mirror(section: str, module_source: str,
                         module_path: str, defaults_symbol: str,
                         tuple_name: str,
                         structural: tuple = ()) -> list[Finding]:
    registered = set(getattr(reg, tuple_name)) - set(structural)
    defaults = _dict_literal_keys(module_source, defaults_symbol)
    if defaults is not None:
        defaults = defaults - set(structural)
    out: list[Finding] = []
    if defaults is None:
        out.append(finding(
            "registry-drift", module_path, 0,
            f"[{section}]: {defaults_symbol} dict literal not found "
            f"in {module_path} — the registry mirror "
            f"{tuple_name} cannot be recomputed"))
        return out
    rel, line = _registry_line(tuple_name)
    for k in sorted(defaults - registered):
        out.append(finding(
            "registry-drift", rel, line,
            f"[{section}] key {k!r} exists in {module_path} "
            f"{defaults_symbol} but not in registry.{tuple_name} — "
            f"configs setting it would be rejected as unknown"))
    for k in sorted(registered - defaults):
        out.append(finding(
            "registry-drift", rel, line,
            f"registry.{tuple_name} declares {k!r} but {module_path} "
            f"{defaults_symbol} does not define it — the registry "
            f"mirror drifted ahead of the schema"))
    return out


def _adapter_arg_keys(source: str) -> dict[str, tuple[int, set[str]]]:
    """kind -> (class line, args keys consumed) for every @register'd
    adapter: `args.get("k")`, `args["k"]`, `args.pop("k")`."""
    from .contracts import _is_registered
    tree = ast.parse(source)
    out: dict[str, tuple[int, set[str]]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        kind = _is_registered(node)
        if kind is None:
            continue
        keys: set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in ("get", "pop") and \
                    isinstance(n.func.value, ast.Name) and \
                    n.func.value.id == "args" and n.args and \
                    isinstance(n.args[0], ast.Constant) and \
                    isinstance(n.args[0].value, str):
                keys.add(n.args[0].value)
            elif isinstance(n, ast.Subscript) and \
                    isinstance(n.value, ast.Name) and \
                    n.value.id == "args" and \
                    isinstance(n.slice, ast.Constant) and \
                    isinstance(n.slice.value, str):
                keys.add(n.slice.value)
        out[kind] = (node.lineno, keys)
    return out


def check_adapter_registry(source: str, path: str) -> list[Finding]:
    """TILE_ARGS vs the keys the adapters actually read — both
    directions, EXTERNAL_ARG_KEYS exempting config-side consumers."""
    out: list[Finding] = []
    consumed = _adapter_arg_keys(source)
    rel, tline = _registry_line("TILE_ARGS")
    for kind, (line, keys) in sorted(consumed.items()):
        registered = set(reg.TILE_ARGS.get(kind, ()))
        known = registered | set(reg.COMMON_KEYS)
        for k in sorted(keys - known):
            out.append(finding(
                "registry-drift", path, line,
                f"adapter kind {kind!r} reads args[{k!r}] but "
                f"registry.TILE_ARGS does not declare it — configs "
                f"setting it would be rejected as unknown"
                f"{reg.suggest(k, known)}"))
        external = set(reg.EXTERNAL_ARG_KEYS.get(kind, ()))
        for k in sorted(registered - keys - external):
            out.append(finding(
                "registry-drift", rel, tline,
                f"registry.TILE_ARGS[{kind!r}] declares {k!r} but the "
                f"adapter never reads it — drop it or add it to "
                f"EXTERNAL_ARG_KEYS with its config-side consumer"))
    return out


def lint_registry_drift(
        sources: dict[str, str] | None = None) -> list[Finding]:
    """Tree-level registry-drift pass: adapter args + every section
    mirror. `sources` (path suffix -> source) overrides file reads for
    fixtures."""
    root = pkg_root()

    def read(suffix: str) -> str:
        if sources is not None and suffix in sources:
            return sources[suffix]
        try:
            with open(os.path.join(root, *suffix.split("/"))) as f:
                return f.read()
        except OSError:
            return ""
    out: list[Finding] = []
    adapters = read(_ADAPTERS_SUFFIX)
    if adapters:
        out.extend(check_adapter_registry(adapters, _ADAPTERS_SUFFIX))
    for section, suffix, defaults, tuple_name, structural in \
            SECTION_MIRRORS:
        src = read(suffix)
        if src:
            out.extend(check_section_mirror(
                section, src, suffix, defaults, tuple_name, structural))
    filtered: list[Finding] = []
    for f in out:
        src = read(f.path) if f.path.endswith(".py") and \
            "/" in f.path else ""
        filtered.extend(filter_suppressed([f], src))
    return filtered
