"""fdlint: static topology, tile-contract, and JAX/Pallas purity lint.

Three analyzer families over one finding/suppression/reporting core
(lint/core.py):

  graph.py      topology graph analysis — cfg/*.toml (and programmatic
                `Topology` builds via `lint_topology`) checked for dead
                links, credit-flow hazards, backpressure cycles, and
                supervise/chaos schema errors before anything runs
  contracts.py  tile-contract analysis — AST over tile classes:
                metric-slot collisions with the supervisor's reserved
                top slots, tango protocol order (credit-gated publish,
                mark_stale only from supervision), consumer-progress
                contracts
  jaxlint.py    JAX/Pallas purity — host-sync hazards inside jitted
                code, x64 dtypes reaching kernels, PRNG key reuse,
                jit entry points without donation

CLI: `python -m firedancer_tpu.lint [paths...]` (tools/fdlint wraps it);
exits nonzero on any non-baselined error finding.
"""
from .core import Finding, RULES  # noqa: F401
