"""App layer: layered TOML config + run command (the fdctl analog,
ref: src/app/fdctl/main.c, src/app/shared/commands/run/run.c)."""
from .config import build_topology, load_config

__all__ = ["build_topology", "load_config"]
