"""Backtest: deterministic ledger replay against a state fingerprint
(ref: src/discof/backtest/fd_backtest_tile.c — replay recorded ledger
segments through the runtime and assert bank hashes; CI tier 8 of
SURVEY §4).

Ledger file = checkpoint frame stream (utils/checkpt.py):
  frame 0   genesis funk checkpoint (nested, bytes)
  frame i   one block: u64 slot | u32 txn_cnt | (u32 len | payload)*
  last      expected final state fingerprint (8 bytes) — written by
            `record`, asserted by `replay`

Replay executes every block through the host TxnExecutor in a funk
fork published per block (the bank discipline), recomputes the
fingerprint, and reports sec/slot — the reference's benchmark.yml
regression metric.

CLI:  python -m firedancer_tpu.app.backtest replay <ledger>
"""
from __future__ import annotations

import io
import struct
import sys
import time

from ..funk.funk import Funk
from ..svm import AccDb, TxnExecutor
from ..svm.programs import OK
from ..tiles.snapshot import state_fingerprint
from ..utils.checkpt import (
    CheckptReader, CheckptWriter, funk_checkpt, funk_restore,
)


def pack_block(slot: int, payloads: list[bytes]) -> bytes:
    out = struct.pack("<QI", slot, len(payloads))
    for p in payloads:
        out += struct.pack("<I", len(p)) + p
    return bytes(out)


def unpack_block(b: bytes):
    slot, cnt = struct.unpack_from("<QI", b, 0)
    off = 12
    payloads = []
    for _ in range(cnt):
        (ln,) = struct.unpack_from("<I", b, off)
        off += 4
        payloads.append(b[off:off + ln])
        off += ln
    return slot, payloads


def record(genesis: Funk, blocks: list[tuple[int, list[bytes]]],
           fp) -> int:
    """Execute blocks from genesis, writing the ledger + final
    fingerprint. Returns the fingerprint."""
    gbuf = io.BytesIO()
    funk_checkpt(genesis, gbuf)
    w = CheckptWriter(fp)
    w.frame(gbuf.getvalue())
    funk = funk_restore(Funk, io.BytesIO(gbuf.getvalue()))
    ex = TxnExecutor(AccDb(funk))
    for slot, payloads in blocks:
        w.frame(pack_block(slot, payloads))
        _exec_block(funk, ex, slot, payloads)
    fingerprint = state_fingerprint(funk)
    w.frame(fingerprint.to_bytes(8, "little"))
    w.fini()
    return fingerprint


def _exec_block(funk: Funk, ex: TxnExecutor, slot: int,
                payloads: list[bytes]) -> int:
    xid = ("block", slot)
    funk.txn_prepare(None, xid)
    ok = 0
    for p in payloads:
        ok += ex.execute(xid, p).status == OK
    funk.txn_publish(xid)
    return ok


def replay(fp, verbose: bool = False) -> dict:
    """Replay a ledger; raises on fingerprint divergence."""
    r = CheckptReader(fp)
    frames = r.frames()
    genesis_blob = next(frames)
    funk = funk_restore(Funk, io.BytesIO(genesis_blob))
    ex = TxnExecutor(AccDb(funk))
    blocks = txns = executed = 0
    t0 = time.perf_counter()
    last = None
    for frame in frames:
        if last is not None:
            slot, payloads = unpack_block(last)
            executed += _exec_block(funk, ex, slot, payloads)
            blocks += 1
            txns += len(payloads)
        last = frame
    dt = time.perf_counter() - t0
    want = int.from_bytes(last, "little") if last and len(last) == 8 \
        else None
    got = state_fingerprint(funk)
    if want is None or got != want:
        raise AssertionError(
            f"state diverged: fingerprint {got:#x} != expected "
            f"{want:#x}" if want is not None else "ledger missing "
            "fingerprint trailer")
    out = {"blocks": blocks, "txns": txns, "executed_ok": executed,
           "sec_per_slot": round(dt / max(blocks, 1), 6),
           "fingerprint": got}
    if verbose:
        print(out)
    return out


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 2 or argv[0] != "replay":
        print(__doc__)
        return 1
    with open(argv[1], "rb") as f:
        replay(f, verbose=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
