"""Backtest: deterministic ledger replay asserting BANK HASHES
(ref: src/discof/backtest/fd_backtest_tile.c:317 — replay recorded
ledger segments through the runtime and assert each slot's bank hash;
CI tier 8 of SURVEY §4).

Ledger file = checkpoint frame stream (utils/checkpt.py):
  frame 0   genesis funk checkpoint (nested, bytes)
  frame i   one block: u64 slot | u32 txn_cnt | (u32 len | payload)*
            | bank_hash 32 — the recorder's per-slot state commitment
            (flamenco/bank_hash.py lattice chain), asserted slot by
            slot on replay
  last      expected final state fingerprint (8 bytes)

Replay executes every block through the host TxnExecutor in a funk
fork published per block (the bank discipline), recomputes each
slot's bank hash AND the final fingerprint, and reports sec/slot —
the reference's benchmark.yml regression metric.

CLI:  python -m firedancer_tpu.app.backtest replay <ledger>
"""
from __future__ import annotations

import hashlib
import io
import struct
import sys
import time

from ..flamenco.bank_hash import BankHasher, lthash_of_root
from ..funk.funk import Funk
from ..svm import AccDb, TxnExecutor
from ..svm.programs import OK
from ..tiles.snapshot import state_fingerprint
from ..utils.checkpt import (
    CheckptReader, CheckptWriter, funk_checkpt, funk_restore,
)


def pack_block(slot: int, payloads: list[bytes],
               bank_hash: bytes = b"") -> bytes:
    """u64 slot | u32 cnt | (u32 len | payload)* | u8 has_hash |
    [bank_hash 32] — the marker is EXPLICIT so a corrupted length
    field fails parsing loudly instead of silently disabling the
    per-slot gate."""
    out = struct.pack("<QI", slot, len(payloads))
    for p in payloads:
        out += struct.pack("<I", len(p)) + p
    if bank_hash:
        assert len(bank_hash) == 32
        return bytes(out) + b"\x01" + bank_hash
    return bytes(out) + b"\x00"


def unpack_block(b: bytes):
    """-> (slot, payloads, bank_hash|b"") — the trailing 32 bytes, if
    present, are the recorded per-slot commitment."""
    slot, cnt = struct.unpack_from("<QI", b, 0)
    off = 12
    payloads = []
    for _ in range(cnt):
        (ln,) = struct.unpack_from("<I", b, off)
        off += 4
        payloads.append(b[off:off + ln])
        off += ln
    if off >= len(b) or b[off] not in (0, 1):
        raise ValueError("corrupt block frame (bad hash marker)")
    has = b[off]
    bank_hash = b[off + 1:off + 33] if has else b""
    if has and len(bank_hash) != 32:
        raise ValueError("corrupt block frame (short bank hash)")
    return slot, payloads, bank_hash


def record(genesis: Funk, blocks: list[tuple[int, list[bytes]]],
           fp) -> int:
    """Execute blocks from genesis, writing the ledger + final
    fingerprint. Returns the fingerprint."""
    gbuf = io.BytesIO()
    funk_checkpt(genesis, gbuf)
    w = CheckptWriter(fp)
    w.frame(gbuf.getvalue())
    funk = funk_restore(Funk, io.BytesIO(gbuf.getvalue()))
    ex = TxnExecutor(AccDb(funk))
    hasher = BankHasher(lthash_of_root(funk))
    parent = hashlib.sha256(b"genesis" + hasher.checksum()).digest()
    for slot, payloads in blocks:
        raw = pack_block(slot, payloads)     # serialized ONCE
        _, parent = _exec_block(funk, ex, slot, payloads, hasher,
                                parent, raw_block=raw)
        w.frame(raw[:-1] + b"\x01" + parent)
    fingerprint = state_fingerprint(funk)
    w.frame(fingerprint.to_bytes(8, "little"))
    w.fini()
    return fingerprint


def _exec_block(funk: Funk, ex: TxnExecutor, slot: int,
                payloads: list[bytes], hasher: BankHasher,
                parent: bytes,
                raw_block: bytes | None = None) -> tuple[int, bytes]:
    """Execute + publish one block; -> (ok_count, bank_hash). The
    DELTA scan is shared with the replay tile
    (BankHasher.apply_txn_delta); the chain INPUTS (parent seed,
    sig-count heuristic, blockhash = frame sha256) are backtest-local,
    so backtest hashes gate ledger determinism, not cross-component
    equality."""
    xid = ("block", slot)
    funk.txn_prepare(None, xid)
    ok = 0
    sigs = 0
    for p in payloads:
        ok += ex.execute(xid, p).status == OK
        sigs += max(1, p[0] if p else 1)      # compact-u16 first byte
    hasher.apply_txn_delta(funk, xid)
    funk.txn_publish(xid)
    # blockhash over the block's serialized bytes; replay passes the
    # frame it already holds instead of re-packing
    blockhash = hashlib.sha256(
        raw_block if raw_block is not None
        else pack_block(slot, payloads)).digest()
    return ok, hasher.bank_hash(parent, sigs, blockhash)


def replay(fp, verbose: bool = False) -> dict:
    """Replay a ledger; raises on fingerprint divergence."""
    r = CheckptReader(fp)
    frames = r.frames()
    genesis_blob = next(frames)
    funk = funk_restore(Funk, io.BytesIO(genesis_blob))
    ex = TxnExecutor(AccDb(funk))
    hasher = BankHasher(lthash_of_root(funk))
    parent = hashlib.sha256(b"genesis" + hasher.checksum()).digest()
    blocks = txns = executed = 0
    t0 = time.perf_counter()
    last = None
    for frame in frames:
        if last is not None:
            slot, payloads, want_hash = unpack_block(last)
            raw = (last[:-33] if want_hash else last[:-1]) + b"\x00"
            ok, got_hash = _exec_block(funk, ex, slot, payloads,
                                       hasher, parent, raw_block=raw)
            executed += ok
            parent = got_hash
            if want_hash and got_hash != want_hash:
                raise AssertionError(
                    f"bank hash diverged at slot {slot}: "
                    f"{got_hash.hex()[:16]} != {want_hash.hex()[:16]}")
            blocks += 1
            txns += len(payloads)
        last = frame
    dt = time.perf_counter() - t0
    want = int.from_bytes(last, "little") if last and len(last) == 8 \
        else None
    got = state_fingerprint(funk)
    if want is None or got != want:
        raise AssertionError(
            f"state diverged: fingerprint {got:#x} != expected "
            f"{want:#x}" if want is not None else "ledger missing "
            "fingerprint trailer")
    out = {"blocks": blocks, "txns": txns, "executed_ok": executed,
           "sec_per_slot": round(dt / max(blocks, 1), 6),
           "fingerprint": got}
    if verbose:
        print(out)
    return out


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 2 or argv[0] != "replay":
        print(__doc__)
        return 1
    with open(argv[1], "rb") as f:
        replay(f, verbose=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
