"""Layered TOML configuration -> topology.

The reference boots from a layered TOML stack: a 1,799-line default
config overridden by the user's file, parsed into a typed struct and
handed to the topology builder (ref: src/app/fdctl/config/default.toml,
src/app/shared/fd_config.h, fd_config_load). This module is that seam
re-expressed: TOML layers deep-merge (later layers win), and the merged
document declares the whole topology — links, tcaches, tiles with their
args — which `build_topology` materializes into the declarative
`Topology` builder (disco/topo.py).

Schema:

    [topology]
    name = "demo"            # shm namespace (default: file stem + pid)
    wksp_size = 16777216

    [[link]]
    name = "synth_verify"
    depth = 128              # frags (power of two)
    mtu = 1280

    [[tcache]]
    name = "dedup_tc"
    depth = 4096

    [[tile]]
    name = "verify"
    kind = "verify"          # registry kind (disco/tiles.py)
    ins = ["synth_verify"]
    outs = ["verify_dedup"]
    batch = 32               # every other key = tile arg, verbatim
    # tile_cnt = 2           # rr-sharded scale-out: expands into tiles
    #                        # verify0/verify1 (rr_cnt/rr_idx auto-set),
    # outs = ["vd0", "vd1"]  # one declared out link PER shard (SPMC);
    # cpu0 = 2               # optional: pin shard i to core cpu0+i;
    # tcache = ["tc0","tc1"] # a list-valued tcache distributes one
    #                        # per shard (other args are shared)

    [tile.supervise]         # per-tile restart policy (supervise.py)
    policy = "restart"       # "fail_fast" (default) | "restart"
    backoff_s = 0.05         # first respawn delay (doubles, capped
    backoff_max_s = 1.0      #  at backoff_max_s)
    max_restarts = 3         # within window_s -> circuit breaker
    window_s = 30.0
    wedge_timeout_s = 2.0    # heartbeat/fseq-progress staleness
                             #  deadline (omit to disable watchdog)

    [topology.supervise]     # optional topology-wide defaults,
    policy = "restart"       #  deep-merged under each tile's table

    [trace]                  # fdtrace flight recorder (trace/recorder.py)
    enable = true            # default false: untraced topologies pay
    depth = 2048             #  NOTHING per frag (hooks stay None)
    sample = 1               # record every Nth frag-scoped event
    tiles = ["verify"]       # optional allowlist (default: all tiles)

    [tile.trace]             # per-tile override (opt out/in, depth,
    sample = 16              #  sample) — highest precedence

    [prof]                   # fdprof continuous profiler (prof/recorder.py)
    enable = true            # default false: unprofiled tiles pay one
    hz = 97                  #  attribute check, no sampler thread
    slots = 256              # folded-stack table entries (power of two)
    ring = 2048              # timestamped sample ring (power of two)
    tiles = ["verify"]       # optional allowlist (default: all tiles)
    capture_ms = 200.0       # device-trace window length
    breach_capture = ["verify"]  # SLO breach -> device capture here

    [tile.prof]              # per-tile override (opt out/in, hz,
    hz = 29                  #  slots, ring, stack_depth)

    [slo]                    # service-level objectives (disco/slo.py),
    fast_window_s = 5.0      #  evaluated by the metric tile; breaches
    slow_window_s = 60.0     #  flip its slo_breach gauge, leave an
    burn_fast = 1.0          #  EV_SLO trace event, and dump next to
    burn_slow = 0.5          #  the supervisor black boxes

    [[slo.target]]           # one objective per table (merged by name
    name = "verify-latency"  #  across layers); expr grammar:
    expr = "verify.work p99 < 500us"   # <source> [agg] <op> <threshold>
                             #  sources: tile.metric, tile.wait|work|tpu,
                             #  link.<link>.<counter>

    [shed]                   # front-door policing (disco/shed.py):
    rate_pps = 1000.0        #  per-peer token buckets, bounded peer
    max_peers = 4096         #  table, stake-weighted overload shedding
    min_stake = 1            #  — read by the ingest tiles (sock/quic/
                             #  gossip); [shed.stakes] maps peer keys
                             #  ("ip:port" / origin hex) to stake

    [tile.shed]              # per-tile override (same keys; highest
    rate_pps = 50.0          #  precedence, like [tile.trace])

    [witness]                # fdwitness sweep plan (witness/plan.py):
    stages = ["kernel_vps"]  #  ordered stage subset, watch-mode
    park_s = 30.0            #  backoff, per-stage deadlines; read by
    park_max_s = 360.0       #  tools/fdwitness, not the topology

    [witness.stage.kernel_vps]   # per-stage override: enable,
    timeout_s = 900.0            #  timeout_s, cmd (argv), env

    [tune]                   # fdtune knob space + controller policy
    enable = true            #  (tune/__init__.py): topo.build carves
    cooldown_s = 2.0         #  the shm knob mailbox, the controller
    hysteresis = 0.25        #  tile steers runtime knobs through it;
                             #  [tune.knob.<name>] overrides bounds.
                             #  FDTPU_TUNED_PROFILE overlays a sweep's
                             #  tuned profile onto the declared tiles

    [[tile.chaos.events]]    # seeded fault plan (utils/chaos.py):
    action = "crash"         #  crash | freeze_hb | wedge | stall_fseq
    at_rx = 24               #  | fail_dispatch (verify tile); fire at
                             #  stem iteration (at_iter) or cumulative
                             #  frags consumed (at_rx); [lo, hi] picks
                             #  seeded-uniform from tile.chaos.seed;
                             #  traffic plans (flood_forged | flood_dup
                             #  | flood_torsion | flood_malformed_quic
                             #  | flood_crds_spam) add frames= + seed=

Unknown top-level sections are rejected (typo safety — the reference
validates its config the same way, fd_config_validate); a bad
supervise table fails topology build before launch.
"""
from __future__ import annotations

import os

try:
    import tomllib
except ModuleNotFoundError:          # py<3.11
    try:
        import tomli as tomllib
    except ModuleNotFoundError:
        try:                         # last resort: setuptools' vendored
            from setuptools._vendor import tomli as tomllib
        except ModuleNotFoundError as e:
            raise ModuleNotFoundError(
                "no TOML parser available on this Python (<3.11): "
                "install 'tomli'") from e

_TOP_SECTIONS = {"topology", "link", "tcache", "tile", "trace", "slo",
                 "prof", "shed", "witness", "funk", "replay",
                 "snapshot", "flight", "tune"}


def _deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _merge_named_lists(base: list, over: list, where: str) -> list:
    """[[link]]/[[tile]] arrays merge by `name`: same-name entries
    deep-merge (override layer wins per key), new names append."""
    out = {}
    for src, entries in (("base", base), (where, over)):
        for e in entries:
            name = e.get("name")
            if not isinstance(name, str):
                raise ValueError(
                    f"{src}: array-of-tables entry missing 'name': {e}")
            out[name] = _deep_merge(out.get(name, {}), e)
    return list(out.values())


def load_config(*paths, overrides: dict | None = None) -> dict:
    """Parse + deep-merge TOML layers left-to-right (later wins), then
    apply the `overrides` dict (the -D command-line escape hatch, same
    by-name merge semantics for link/tcache/tile arrays as TOML
    layers)."""
    cfg: dict = {}
    layers = [(p, None) for p in paths]
    if overrides:
        layers.append(("<overrides>", overrides))
    for p, preparsed in layers:
        if preparsed is None:
            with open(p, "rb") as f:
                layer = tomllib.load(f)
        else:
            layer = preparsed
        bad = set(layer) - _TOP_SECTIONS
        if bad:
            raise ValueError(f"{p}: unknown config sections {sorted(bad)}")
        for key in ("link", "tcache", "tile"):
            if key in layer:
                cfg[key] = _merge_named_lists(cfg.get(key, []),
                                              layer[key], str(p))
        for key in ("topology", "trace", "slo", "prof", "shed",
                    "witness", "funk", "replay", "snapshot",
                    "flight", "tune"):
            if key in layer:
                merged = _deep_merge(cfg.get(key, {}), layer[key])
                if key == "slo" and "target" in layer[key]:
                    # [[slo.target]] arrays merge by name like
                    # [[link]]/[[tile]]: an overlay can tighten one
                    # objective without restating the rest
                    merged["target"] = _merge_named_lists(
                        cfg.get(key, {}).get("target", []),
                        layer[key]["target"], str(p))
                cfg[key] = merged
    return cfg


def _validate_tile_keys(t: dict):
    """Reject unknown [[tile]] keys with a did-you-mean hint. The key
    registry is shared with fdlint (lint/registry.py) — the linter's
    dangling-ref checks and this schema gate stay in sync by
    construction. A typo'd arg key used to pass through silently as a
    tile arg the adapter never reads."""
    from ..lint import registry as reg
    kind = t["kind"]
    known = reg.known_keys(kind)
    if not known:
        raise ValueError(
            f"[[tile]] {t.get('name')!r}: unknown kind {kind!r}"
            + reg.suggest(str(kind), reg.TILE_ARGS))
    bad = set(t) - known
    if bad:
        key = sorted(bad)[0]
        raise ValueError(
            f"[[tile]] {t.get('name')!r} (kind {kind!r}): unknown "
            f"key(s) {sorted(bad)}" + reg.suggest(key, known))


def build_topology(cfg: dict, name: str | None = None):
    """Merged config -> Topology (unbuilt; caller runs .build())."""
    from ..disco import Topology

    top = cfg.get("topology", {})
    # [trace] flight-recorder section — validated here (fail at config
    # load with a did-you-mean, like every other schema gate) and again
    # by topo.build
    from ..trace import normalize_trace
    trace_cfg = cfg.get("trace")
    if trace_cfg is not None:
        normalize_trace(trace_cfg)
    # [slo] objectives — schema-validated here (fail at config load
    # with a did-you-mean); target references resolve at topo.build
    # once the declared tiles/links/metrics are known
    from ..disco.slo import normalize_slo
    slo_cfg = cfg.get("slo")
    if slo_cfg is not None:
        normalize_slo(slo_cfg)
    # [prof] continuous profiler — same gate (tiles/breach_capture
    # references resolve at topo.build)
    from ..prof import normalize_prof
    prof_cfg = cfg.get("prof")
    if prof_cfg is not None:
        normalize_prof(prof_cfg)
    # [shed] front-door policing — same gate (disco/shed.py is the one
    # validator; per-tile `shed` overrides validate at topo.build)
    from ..disco.shed import normalize_shed
    shed_cfg = cfg.get("shed")
    if shed_cfg is not None:
        normalize_shed(shed_cfg)
    # [witness] sweep plan — same gate (witness/plan.py is the one
    # validator; the section configures tools/fdwitness, not the
    # topology, but a typo'd stage name must still fail at load with a
    # did-you-mean, not at 3am when the tunnel finally comes up)
    from ..witness.plan import normalize_witness
    wit_cfg = cfg.get("witness")
    if wit_cfg is not None:
        normalize_witness(wit_cfg)
    # [funk] account store — same gate (funk/shmfunk.py is the one
    # validator; backend "shm" makes topo.build carve the store region)
    from ..funk.shmfunk import normalize_funk
    funk_cfg = cfg.get("funk")
    if funk_cfg is not None:
        normalize_funk(funk_cfg)
    # [replay]/[snapshot] follower surface — same gate (tiles/replay.py
    # and tiles/snapshot.py are the one validator each)
    from ..tiles.replay import normalize_replay
    replay_cfg = cfg.get("replay")
    if replay_cfg is not None:
        normalize_replay(replay_cfg)
    from ..tiles.snapshot import normalize_snapshot
    snap_cfg = cfg.get("snapshot")
    if snap_cfg is not None:
        normalize_snapshot(snap_cfg)
    # [flight] durable telemetry archive — same gate (flight/__init__
    # is the one validator; the recorder tile reads the normalized
    # section off the plan)
    from ..flight import normalize_flight
    flight_cfg = cfg.get("flight")
    if flight_cfg is not None:
        normalize_flight(flight_cfg)
    # [tune] autotuning knob space + controller policy — same gate
    # (tune/__init__ is the one validator; topo.build carves the knob
    # mailbox when enabled)
    from ..tune import normalize_tune
    tune_cfg = cfg.get("tune")
    if tune_cfg is not None:
        normalize_tune(tune_cfg)
    topo = Topology(name or top.get("name", f"cfg{os.getpid()}"),
                    wksp_size=int(top.get("wksp_size", 1 << 26)),
                    trace=trace_cfg, slo=slo_cfg, prof=prof_cfg,
                    shed=shed_cfg, funk=funk_cfg, replay=replay_cfg,
                    snapshot=snap_cfg, flight=flight_cfg,
                    tune=tune_cfg)
    for ln in cfg.get("link", []):
        topo.link(ln["name"], depth=int(ln.get("depth", 128)),
                  mtu=int(ln.get("mtu", 1280)))
    for tc in cfg.get("tcache", []):
        topo.tcache(tc["name"], depth=int(tc.get("depth", 4096)))
    default_sup = top.get("supervise")
    for t in cfg.get("tile", []):
        if "kind" not in t:
            raise ValueError(f"[[tile]] {t.get('name')!r}: missing 'kind'")
        _validate_tile_keys(t)
        args = {k: v for k, v in t.items()
                if k not in ("name", "kind", "ins", "outs")}
        if default_sup:
            # topology-wide supervision defaults; the tile's own table
            # wins per key (validated by topo.build via supervise.py)
            args["supervise"] = _deep_merge(default_sup,
                                            args.get("supervise", {}))
        tile_cnt = int(args.pop("tile_cnt", 1) or 1)
        cpu0 = args.pop("cpu0", None)
        if tile_cnt > 1:
            # rr-sharded scale-out (verify_tile_cnt as config): one
            # [[tile]] stanza expands into tile_cnt round-robin shards
            # sharing the ins, one declared out link per shard
            topo.sharded_tile(t["name"], t["kind"], tile_cnt,
                              ins=t.get("ins", ()),
                              outs=t.get("outs", ()), cpu0=cpu0,
                              **args)
        else:
            if cpu0 is not None:
                # cpu0 on an unsharded tile still pins it (shard 0)
                args["cpu_idx"] = int(cpu0)
            topo.tile(t["name"], t["kind"], ins=t.get("ins", ()),
                      outs=t.get("outs", ()), **args)
    # FDTPU_TUNED_PROFILE: overlay a sweep's tuned knob values onto the
    # declared tiles before build (tune/profile.py checks provenance;
    # config keys the profile does not carry stay authoritative)
    prof_path = os.environ.get("FDTPU_TUNED_PROFILE")
    if prof_path:
        from ..tune.profile import apply_profile, load_profile
        apply_profile(topo, load_profile(prof_path))
    return topo
