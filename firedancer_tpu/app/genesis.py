"""Genesis builder: create a cluster's slot-0 state.

The reference's genesi tile materializes genesis (funded accounts,
vote + stake accounts for the boot validator set) that every node
restores before slot 0 (ref: src/discof/genesi/ and the fd_genesis
create path). This builds the same artifact for this framework:

  * funded payer/user accounts
  * per-validator: an initialized VOTE account (node identity =
    authorized voter = the validator's pubkey) and a DELEGATED stake
    account (active from epoch 1), so flamenco/stakes.py derives a
    non-empty leader schedule, turbine weights, and tower total from
    slot 0
  * output: a funk checkpoint (utils/checkpt.py) any snapld/snapin
    chain or bank can restore, plus the derived epoch-0/1 stakes

CLI:
  python -m firedancer_tpu.app.genesis out.checkpt \\
      --validators 3 --user-accounts 16 --stake 1000000
"""
from __future__ import annotations

import argparse
import hashlib
import sys

from ..funk.funk import Funk, key32
from ..svm.accdb import Account
from ..svm.stake import STAKE_PROGRAM_ID, ST_DELEGATED, StakeState
from ..svm.vote import VOTE_PROGRAM_ID, VoteState


def validator_seed(i: int) -> bytes:
    return hashlib.sha256(b"fdtpu-validator-%d" % i).digest()


def build_genesis(n_validators: int = 3, n_user_accounts: int = 16,
                  stake: int = 1_000_000,
                  user_lamports: int = 1 << 44) -> tuple[Funk, list]:
    """-> (funk, [(identity_pub, vote_key, stake_key)])."""
    from ..disco.tiles import _synth_genesis
    from ..utils.ed25519_ref import keypair
    funk = Funk()
    validators = []
    for i in range(n_validators):
        _, _, identity = keypair(validator_seed(i))
        vote_key = hashlib.sha256(b"vote" + identity).digest()
        stake_key = hashlib.sha256(b"stake" + identity).digest()
        vs = VoteState(identity, identity, identity)
        funk.rec_write(None, vote_key, Account(
            lamports=1, data=vs.to_bytes(), owner=VOTE_PROGRAM_ID))
        st = StakeState(ST_DELEGATED, staker=identity,
                        withdrawer=identity, voter=vote_key,
                        amount=stake, activation_epoch=0)
        funk.rec_write(None, stake_key, Account(
            lamports=stake, data=st.to_bytes(),
            owner=STAKE_PROGRAM_ID))
        funk.rec_write(None, key32(identity), Account(
            lamports=user_lamports))
        validators.append((identity, vote_key, stake_key))
    # user accounts come from THE shared synth-genesis map (the same
    # one the bank/replay tiles derive); the pool is finite and wraps,
    # so an oversized request is an error, not a silent cap
    users = _synth_genesis(n_user_accounts)
    if len(users) < n_user_accounts:
        raise ValueError(
            f"user-accounts capped at {len(users)} (the deterministic "
            f"synth signer pool wraps); requested {n_user_accounts}")
    for pub in users:
        funk.rec_write(None, key32(pub), Account(lamports=user_lamports))
    return funk, validators


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="firedancer_tpu genesis")
    ap.add_argument("out", help="output checkpoint path")
    ap.add_argument("--validators", type=int, default=3)
    ap.add_argument("--user-accounts", type=int, default=16)
    ap.add_argument("--stake", type=int, default=1_000_000)
    args = ap.parse_args(argv)

    from ..flamenco.stakes import node_stakes
    from ..utils.checkpt import funk_checkpt
    funk, validators = build_genesis(args.validators,
                                     args.user_accounts, args.stake)
    with open(args.out, "wb") as f:
        funk_checkpt(funk, f)
    ns = node_stakes(funk, None, 1)
    print(f"genesis: {len(funk.root_items())} accounts, "
          f"{len(validators)} validators")
    for ident, vote, stake_key in validators:
        print(f"  identity {ident.hex()[:16]}.. vote {vote.hex()[:16]}"
              f".. stake@1 {ns.get(ident, 0)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
