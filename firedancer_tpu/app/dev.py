"""`dev` command: one-command local development cluster.

    python -m firedancer_tpu.app.dev [--duration 30] [--validators 2]

The fddev-dev analog (ref: src/app/shared_dev/commands/dev.c:40-100 —
"auto-configure, genesis creation, keygen, single-machine cluster",
README.md:47-56): runs the configure preflight, builds a genesis
checkpoint (funded users + initialized vote/stake accounts per
validator), then boots the committed default leader topology with the
genesis-derived funding layered in — ending at the same live monitor
`run` gives, with zero hand-written config.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="firedancer_tpu dev")
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--validators", type=int, default=2)
    ap.add_argument("--user-accounts", type=int, default=16)
    ap.add_argument("--name", default=None)
    ap.add_argument("--skip-configure", action="store_true")
    args = ap.parse_args(argv)

    from . import configure as cfg_mod
    if not args.skip_configure:
        print("== configure check ==")
        worst = cfg_mod.PASS
        for st in cfg_mod.fix():
            line = (f"[{st['status']:4s}] {st['stage']:<10s} "
                    f"{st['detail']}")
            print(line)
            if st["status"] == cfg_mod.FAIL:
                worst = cfg_mod.FAIL
        if worst == cfg_mod.FAIL:
            print("(continuing — dev mode tolerates FAIL stages)")

    print("== genesis ==")
    from .genesis import main as genesis_main
    tmp = tempfile.mkdtemp(prefix="fdtpu-dev-")
    ckpt = os.path.join(tmp, "genesis.ckpt")
    rc = genesis_main([ckpt, "--validators", str(args.validators),
                       "--user-accounts", str(args.user_accounts)])
    if rc:
        print("genesis failed", file=sys.stderr)
        return rc

    print("== boot ==")
    # the committed default leader loop + an overlay layering the
    # genesis checkpoint into the bank (config layers merge per key)
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    default_toml = os.path.join(repo, "cfg", "default.toml")
    overlay = os.path.join(tmp, "dev-overlay.toml")
    with open(overlay, "w") as f:
        f.write(f'[[tile]]\nname = "bank0"\n'
                f'genesis_ckpt = "{ckpt}"\n')
    from .run import main as run_main
    run_args = [default_toml, overlay,
                "--duration", str(args.duration)]
    if args.name:
        run_args += ["--name", args.name]
    return run_main(run_args)


if __name__ == "__main__":
    sys.exit(main())
