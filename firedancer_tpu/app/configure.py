"""`configure` — system preflight stages (check / fix).

The reference's configure command runs privileged stages before boot:
hugetlbfs mounts, sysctl tuning, ethtool channels, hyperthread
isolation (ref: src/app/shared/commands/configure/, listed in
src/app/fdctl/main.c:33-42). This framework's runtime needs are
narrower — /dev/shm capacity for workspaces, fd limits for rings and
sockets, scheduling headroom for pinned tiles — and the container
environments it runs in rarely grant root. So each stage follows the
reference's check/fix contract, but `fix` only applies what the
process may legally do (rlimits up to the hard cap); everything else
reports a clear PASS/WARN/FAIL with the operator command that would
fix it.

CLI:  python -m firedancer_tpu.app.configure check [--wksp-bytes N]
      python -m firedancer_tpu.app.configure fix
"""
from __future__ import annotations

import json
import os
import resource
import sys

PASS, WARN, FAIL = "PASS", "WARN", "FAIL"


def _read(path: str) -> str | None:
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return None


def stage_shm(wksp_bytes: int = 1 << 30) -> dict:
    """The workspace backing store must hold the planned topology:
    /dev/shm normally, or the FDTPU_HUGETLBFS mount when workspaces
    are redirected there (native/fdtpu.cc wksp_open_fd)."""
    backing = os.environ.get("FDTPU_HUGETLBFS") or "/dev/shm"
    st = {"stage": "shm", "status": FAIL, "detail": "", "fix": ""}
    try:
        s = os.statvfs(backing)
    except OSError as e:
        st["detail"] = f"{backing} unavailable: {e}"
        st["fix"] = "mount -t tmpfs -o size=2g tmpfs /dev/shm"             if backing == "/dev/shm" else             f"mount hugetlbfs at {backing} or unset FDTPU_HUGETLBFS"
        return st
    free = s.f_bavail * s.f_frsize
    total = s.f_blocks * s.f_frsize
    st["detail"] = (f"{backing}: free {free >> 20} MiB of "
                    f"{total >> 20} MiB, want {wksp_bytes >> 20} MiB")
    if free >= wksp_bytes:
        st["status"] = PASS
    elif total >= wksp_bytes:
        st["status"] = WARN
        st["fix"] = "remove stale /dev/shm/fdtpu_* workspaces"
    else:
        st["fix"] = (f"mount -o remount,size="
                     f"{max(total, wksp_bytes * 2) >> 20}m /dev/shm")
    return st


def _rl_ge(v: int, want: int) -> bool:
    """limit >= want with RLIM_INFINITY treated as unbounded."""
    return v == resource.RLIM_INFINITY or v >= want


def stage_nofile(want: int = 4096) -> dict:
    """fd headroom: rings, sockets, mmaps (the reference raises
    RLIMIT_NOFILE in its boot path)."""
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    st = {"stage": "nofile", "status": PASS,
          "detail": f"soft {soft}, hard {hard}, want {want}", "fix": ""}
    if not _rl_ge(soft, want):
        st["status"] = WARN if _rl_ge(hard, want) else FAIL
        st["fix"] = (f"raise soft limit (fix stage does this up to "
                     f"hard={hard})" if _rl_ge(hard, want)
                     else f"ulimit -n {want} as root / raise hard cap")
    return st


def fix_nofile(want: int = 4096) -> bool:
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if _rl_ge(soft, want):
        return True
    try:
        new_soft = want if _rl_ge(hard, want) else hard
        resource.setrlimit(resource.RLIMIT_NOFILE, (new_soft, hard))
        return _rl_ge(resource.getrlimit(resource.RLIMIT_NOFILE)[0],
                      want)
    except (ValueError, OSError):
        return False


def stage_memlock(want: int = 1 << 26) -> dict:
    """Locked-memory headroom (device staging buffers pin pages)."""
    soft, hard = resource.getrlimit(resource.RLIMIT_MEMLOCK)
    inf = resource.RLIM_INFINITY

    def fmt(v):
        return "unlimited" if v == inf else f"{v >> 20} MiB"
    st = {"stage": "memlock", "status": PASS,
          "detail": f"soft {fmt(soft)}, hard {fmt(hard)}", "fix": ""}
    if soft != inf and soft < want:
        st["status"] = WARN if (hard == inf or hard >= want) else FAIL
        st["fix"] = "raise RLIMIT_MEMLOCK (fix stage tries up to hard)"
    return st


def fix_memlock(want: int = 1 << 26) -> bool:
    """True only when the resulting soft limit actually covers `want`
    (raising toward a too-small hard cap is progress, not success —
    same contract as fix_nofile)."""
    soft, hard = resource.getrlimit(resource.RLIMIT_MEMLOCK)
    if _rl_ge(soft, want):
        return True
    try:
        new_soft = want if _rl_ge(hard, want) else hard
        resource.setrlimit(resource.RLIMIT_MEMLOCK, (new_soft, hard))
        return _rl_ge(resource.getrlimit(resource.RLIMIT_MEMLOCK)[0],
                      want)
    except (ValueError, OSError):
        return False


def stage_cpus(want: int = 4) -> dict:
    """Schedulable cores vs the topology's tile count (tile pinning
    needs distinct cores to mean anything)."""
    avail = len(os.sched_getaffinity(0))
    st = {"stage": "cpus", "status": PASS if avail >= want else WARN,
          "detail": f"{avail} schedulable cores, want {want} for "
                    f"pinned tiles", "fix": ""}
    if avail < want:
        st["fix"] = ("tiles will timeshare cores; reduce topology or "
                     "widen the cpuset")
    return st


def stage_somaxconn(want: int = 128) -> dict:
    """Listen backlog for the rpc/gui/grpc services."""
    raw = _read("/proc/sys/net/core/somaxconn")
    if raw is None:
        return {"stage": "somaxconn", "status": WARN,
                "detail": "procfs unavailable", "fix": ""}
    v = int(raw)
    return {"stage": "somaxconn",
            "status": PASS if v >= want else WARN,
            "detail": f"{v}, want {want}",
            "fix": "" if v >= want else
            f"sysctl -w net.core.somaxconn={want}"}


def stage_overcommit() -> dict:
    """Heuristic overcommit: large sparse mmaps (groove volumes) need
    mode 0 or 1."""
    raw = _read("/proc/sys/vm/overcommit_memory")
    if raw is None:
        return {"stage": "overcommit", "status": WARN,
                "detail": "procfs unavailable", "fix": ""}
    v = int(raw)
    return {"stage": "overcommit",
            "status": PASS if v in (0, 1) else WARN,
            "detail": f"vm.overcommit_memory={v}",
            "fix": "" if v in (0, 1) else
            "sysctl -w vm.overcommit_memory=0"}


def stage_hugepages() -> dict:
    """Hugepage availability (the reference mounts hugetlbfs for its
    workspaces; ours use them when FDTPU_HUGETLBFS names a mount —
    native/fdtpu.cc wksp_open_fd)."""
    total = 0
    raw = _read("/proc/meminfo") or ""
    for line in raw.splitlines():
        if line.startswith("HugePages_Total"):
            total = int(line.split()[1])
    mounts = []
    for line in (_read("/proc/mounts") or "").splitlines():
        f = line.split()
        if len(f) >= 3 and f[2] == "hugetlbfs":
            # /proc/mounts octal-escapes spaces etc. (\040)
            mp = f[1].encode().decode("unicode_escape")
            mounts.append(os.path.realpath(mp))
    env_raw = os.environ.get("FDTPU_HUGETLBFS", "")
    env = os.path.realpath(env_raw.rstrip("/")) if env_raw else ""
    st = {"stage": "hugepages", "status": PASS,
          "detail": f"HugePages_Total={total}, mounts={mounts or '-'}"
                    f", FDTPU_HUGETLBFS={env_raw or '-'}", "fix": ""}
    if env and env not in mounts:
        st["status"] = WARN
        st["fix"] = (f"FDTPU_HUGETLBFS={env_raw} is not a hugetlbfs "
                     f"mount; workspaces get normal pages there")
    elif total == 0:
        st["status"] = WARN
        st["fix"] = ("no hugepages reserved; THP madvise still "
                     "applies — for guaranteed pages: sysctl -w "
                     "vm.nr_hugepages=N and mount hugetlbfs, then set "
                     "FDTPU_HUGETLBFS")
    return st


def check(wksp_bytes: int = 1 << 30) -> list[dict]:
    return [stage_shm(wksp_bytes), stage_hugepages(), stage_nofile(),
            stage_memlock(), stage_cpus(), stage_somaxconn(),
            stage_overcommit()]


def fix(wksp_bytes: int = 1 << 30) -> list[dict]:
    """Apply the unprivileged fixes, then re-check at the same
    target."""
    fix_nofile()
    fix_memlock()
    return check(wksp_bytes)


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="firedancer_tpu.app.configure",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("action", choices=["check", "fix"])
    ap.add_argument("--wksp-bytes", type=int, default=1 << 30)
    try:
        args = ap.parse_args(argv)
    except SystemExit:
        return 1
    wksp = args.wksp_bytes
    stages = fix(wksp) if args.action == "fix" else check(wksp)
    worst = PASS
    for st in stages:
        line = f"[{st['status']:4s}] {st['stage']:<10s} {st['detail']}"
        if st["fix"]:
            line += f"  -> {st['fix']}"
        print(line)
        if st["status"] == FAIL or (st["status"] == WARN
                                    and worst == PASS):
            worst = st["status"]
    print(json.dumps({"result": worst}))
    return 0 if worst != FAIL else 2


if __name__ == "__main__":
    sys.exit(main())
