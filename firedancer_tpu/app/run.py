"""`run` command: boot a topology from TOML config layers.

    python -m firedancer_tpu.app.run cfg/default.toml [cfg/local.toml ...]
        [--duration S] [--name N]

The fdctl-run analog (ref: src/app/shared/commands/run/run.c): load the
config stack, materialize the topology, spawn every tile, run the
policy-driven supervisor (fail-fast by default; per-tile restart +
wedge watchdog via [tile.supervise], disco/supervise.py), print the
monitor table periodically, tear down on SIGINT or after --duration
seconds.
"""
from __future__ import annotations

import argparse
import sys
import time

from ..disco.launch import TopologyRunner
from ..disco.monitor import format_table, snapshot
from .config import build_topology, load_config


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="firedancer_tpu run")
    ap.add_argument("config", nargs="+", help="TOML layers, later wins")
    ap.add_argument("--duration", type=float, default=0.0,
                    help="seconds to run (0 = until SIGINT)")
    ap.add_argument("--name", default=None, help="topology name override")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="monitor refresh seconds")
    ap.add_argument("--boot-timeout", type=float, default=600.0,
                    help="seconds to wait for every tile to reach RUN "
                         "(first-compile warmup on a cold or shared "
                         "box can exceed the default)")
    args = ap.parse_args(argv)

    cfg = load_config(*args.config)
    topo = build_topology(cfg, name=args.name)
    plan = topo.build()
    runner = TopologyRunner(plan).start()
    try:
        runner.wait_running(timeout_s=args.boot_timeout)
        t0 = time.monotonic()   # duration clock starts once tiles RUN
        next_print = 0.0
        while not args.duration \
                or time.monotonic() - t0 < args.duration:
            # supervision runs at a fast cadence (restart backoffs and
            # the wedge watchdog need sub-second polls); the monitor
            # table prints at the human --interval
            runner.check_failures()
            now = time.monotonic()
            if now >= next_print:
                # the runner already holds the plan + workspace; no
                # need to re-attach through the plan JSON like an
                # external monitor
                print(format_table(snapshot(runner.plan, runner.wksp)),
                      flush=True)
                next_print = now + args.interval
            time.sleep(0.05)
    except KeyboardInterrupt:
        pass
    finally:
        runner.halt()
        runner.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
