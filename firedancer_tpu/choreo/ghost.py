"""LMD-GHOST fork choice (ref: src/choreo/ghost/fd_ghost.h:1-120).

The fork tree is keyed by block id (a 32-byte hash), not slot, so
equivocating blocks for the same slot remain distinct nodes
(ref: fd_ghost.h block_id discussion). Each node carries:

  replay_stake  stake of voters whose LATEST vote is this block (LMD:
                a re-vote moves the voter's stake off the old block)
  weight        subtree sum of replay_stake (the GHOST quantity)
  valid         equivocating blocks are marked invalid for fork choice
                until duplicate-confirmed (>= 52% of stake observed
                voting for that exact block, ref: fd_ghost.h eqvoc notes)

best() is the greedy heaviest-valid traversal from the root; ties break
to the LOWER slot, matching the reference exactly
(ref: src/choreo/ghost/fd_ghost.c:135-160 — "if the weights are equal
then tie-break by lower slot number").

publish(new_root) prunes every node not descending from the new root —
the rooting-driven state pruning the tower doc calls "publishing"
(ref: src/choreo/tower/fd_tower.h rooting discussion).
"""
from __future__ import annotations

from dataclasses import dataclass, field

DUPLICATE_CONFIRMED_PCT = 0.52     # ref: fd_ghost.h ">=52%" revalidation


@dataclass
class GhostNode:
    block_id: bytes
    slot: int
    parent: bytes | None
    children: list[bytes] = field(default_factory=list)
    replay_stake: int = 0          # latest-vote stake directly on this block
    weight: int = 0                # subtree stake (self + descendants)
    valid: bool = True             # False while an unconfirmed duplicate


class Ghost:
    def __init__(self, root_block: bytes, root_slot: int, total_stake: int):
        self.total_stake = total_stake
        self.root = root_block
        self.nodes: dict[bytes, GhostNode] = {
            root_block: GhostNode(root_block, root_slot, None)}
        # voter pubkey -> (block_id, stake): the L in LMD
        self.latest: dict[bytes, tuple[bytes, int]] = {}

    # -- tree construction --------------------------------------------------

    def insert(self, block_id: bytes, slot: int, parent_block: bytes):
        if block_id in self.nodes:
            raise ValueError(f"duplicate block {block_id.hex()[:16]}")
        if parent_block not in self.nodes:
            raise KeyError(f"unknown parent {parent_block.hex()[:16]}")
        parent = self.nodes[parent_block]
        if slot <= parent.slot:
            raise ValueError(f"child slot {slot} <= parent {parent.slot}")
        self.nodes[block_id] = GhostNode(block_id, slot, parent_block)
        parent.children.append(block_id)

    # -- votes --------------------------------------------------------------

    def _bump(self, block_id: bytes, delta: int):
        n = self.nodes[block_id]
        n.replay_stake += delta
        while block_id is not None:
            node = self.nodes[block_id]
            node.weight += delta
            block_id = node.parent

    def replay_vote(self, voter: bytes, stake: int, block_id: bytes):
        """Record voter's latest vote (LMD: the previous vote's stake is
        removed first, ref: fd_ghost.h "only a validator's latest vote
        counts"). Votes for pruned/unknown blocks are ignored, matching
        the reference's vote-older-than-root drop
        (ref: fd_ghost.c:283)."""
        if block_id not in self.nodes:
            return
        prev = self.latest.get(voter)
        if prev is not None and prev[0] in self.nodes:
            self._bump(prev[0], -prev[1])
        self.latest[voter] = (block_id, stake)
        self._bump(block_id, stake)

    # -- equivocation hooks (driven by eqvoc / gossip) ----------------------

    def mark_invalid(self, block_id: bytes):
        if block_id in self.nodes:
            self.nodes[block_id].valid = False

    def mark_duplicate_confirmed(self, block_id: bytes):
        """>=52% of stake voted for exactly this version: valid again."""
        if block_id in self.nodes:
            self.nodes[block_id].valid = True

    def check_duplicate_confirmed(self, block_id: bytes) -> bool:
        n = self.nodes.get(block_id)
        if n is None:
            return False
        if n.weight >= DUPLICATE_CONFIRMED_PCT * self.total_stake:
            n.valid = True
        return n.valid

    # -- queries ------------------------------------------------------------

    def best(self) -> bytes:
        """Greedy heaviest-valid leaf-ward traversal from the root."""
        cur = self.nodes[self.root]
        while True:
            best_child = None
            for cid in cur.children:
                c = self.nodes[cid]
                if not c.valid:
                    continue
                if best_child is None:
                    best_child = c
                elif (c.weight, -c.slot) > (best_child.weight,
                                            -best_child.slot):
                    # heavier wins; equal weight -> lower slot
                    best_child = c
            if best_child is None:
                return cur.block_id
            cur = best_child

    def is_ancestor(self, a: bytes, b: bytes) -> bool:
        """a is b or an ancestor of b."""
        cur = b
        a_slot = self.nodes[a].slot
        while cur is not None:
            if cur == a:
                return True
            node = self.nodes[cur]
            if node.slot < a_slot:
                return False
            cur = node.parent
        return False

    def gca(self, a: bytes, b: bytes) -> bytes:
        """Greatest common ancestor of two blocks."""
        anc = set()
        cur = a
        while cur is not None:
            anc.add(cur)
            cur = self.nodes[cur].parent
        cur = b
        while cur is not None:
            if cur in anc:
                return cur
            cur = self.nodes[cur].parent
        raise ValueError("no common ancestor (corrupt tree)")

    def weight(self, block_id: bytes) -> int:
        return self.nodes[block_id].weight

    def path_child(self, ancestor: bytes, descendant: bytes) -> bytes:
        """The child of `ancestor` on the path down to `descendant`."""
        cur = descendant
        while True:
            p = self.nodes[cur].parent
            if p is None:
                raise ValueError("not a descendant")
            if p == ancestor:
                return cur
            cur = p

    # -- rooting ------------------------------------------------------------

    def publish(self, new_root: bytes):
        """Prune everything not descending from new_root (the tower's
        rooting-driven publish, ref: fd_tower.h)."""
        if new_root not in self.nodes:
            raise KeyError("new root unknown")
        keep: dict[bytes, GhostNode] = {}
        stack = [new_root]
        while stack:
            bid = stack.pop()
            n = self.nodes[bid]
            keep[bid] = n
            stack.extend(n.children)
        self.nodes = keep
        self.root = new_root
        self.nodes[new_root].parent = None
        self.latest = {v: (b, s) for v, (b, s) in self.latest.items()
                       if b in keep}
