"""choreo: consensus components (ref: src/choreo/fd_choreo.h:1-12).

tower — TowerBFT vote tower + lockout/threshold/switch checks
ghost — LMD-GHOST weighted fork choice tree
eqvoc — equivocation (duplicate block/shred) detection
notar — confirmation tracking (propagated / duplicate / optimistic)
hfork — hard-fork (consensus-divergence) detection
voter — direct-offset vote-account accessors
"""
from .eqvoc import EqvocDetector, EquivocationProof, FecMeta  # noqa: F401
from .ghost import Ghost, GhostNode  # noqa: F401
from .hfork import HardFork, HforkDetector  # noqa: F401
from .notar import Confirmation, Notar  # noqa: F401
from .tower import (  # noqa: F401
    MAX_LOCKOUT_HISTORY, SWITCH_RATIO, THRESHOLD_DEPTH, THRESHOLD_RATIO,
    Tower, TowerVote,
)
